// Native data plane: one-pass CSV parse + schema-driven encode.
//
// The reference's record pipeline is the JVM: Hadoop TextInputFormat splits
// lines, every mapper re-splits and re-parses each record's fields
// (e.g. bayesian/BayesianDistribution.java:137-179). Here the equivalent
// hot path — CSV bytes -> int bin codes / float features / class labels —
// is a C++ kernel invoked via ctypes, feeding fixed-shape numpy buffers that
// go straight to TPU infeed. The Python DatasetEncoder
// (core/encoding.py) remains the portable fallback and the source of truth
// for vocab/bin semantics; this kernel implements the identical rules:
//   categorical: vocab lookup, miss -> OOV slot (n_bins-1)
//   binned numeric: clip(floor(v / bucket_width) - bin_offset, 0, n_bins-1)
//   continuous: parsed as float
//   label: vocab lookup, miss -> error
//
// Build: g++ -O3 -shared -fPIC (driven by avenir_tpu/runtime/native.py).

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// column kinds, mirroring FeatureField roles
enum Kind : int32_t {
  kCategorical = 0,   // binned via vocab
  kBinnedNumeric = 1, // binned via bucket width
  kContinuous = 2,    // raw float feature
  kLabel = 3,         // class attribute via vocab
  kId = 4,            // record id: emit (offset, length) into the buffer
};

// error codes (negative returns)
constexpr long kErrRagged = -1;
constexpr long kErrBadNumber = -2;
constexpr long kErrUnknownLabel = -3;
constexpr long kErrTooManyRows = -4;

struct ColumnSpec {
  int32_t kind;
  int32_t ordinal;
  double bucket_width;
  int64_t bin_offset;
  int32_t n_bins;
  std::unordered_map<std::string, int32_t> vocab;
};

bool parse_double(const char* s, size_t n, double* out) {
  if (n == 0) return false;
  std::string tmp(s, n);
  char* end = nullptr;
  *out = std::strtod(tmp.c_str(), &end);
  return end == tmp.c_str() + tmp.size();
}

}  // namespace

extern "C" {

// Parse + encode up to max_rows CSV records from buf[0:len].
//
// Specs arrive as parallel arrays of length nspec, ordered so that all
// categorical/binned specs fill codes_out columns 0..n_binned-1 in order,
// continuous specs fill cont_out columns 0..n_cont-1 in order, and the
// label spec (at most one) fills labels_out. vocab_blob packs the
// vocabularies of vocab-bearing specs in spec order: values separated by
// '\x1f', columns terminated by '\x1e'.
//
// Returns the number of rows encoded, or a negative error code with
// *err_row set to the offending row index.
long avenir_csv_encode(
    const char* buf, long len, char delim, int32_t ncols,
    const int32_t* kinds, const int32_t* ordinals,
    const double* bucket_widths, const int64_t* bin_offsets,
    const int32_t* n_bins, int32_t nspec,
    const char* vocab_blob,
    int32_t* codes_out, long n_binned,
    float* cont_out, long n_cont,
    int32_t* labels_out,
    int64_t* id_off_out, int32_t* id_len_out,
    long max_rows, long* err_row) {
  // build specs
  std::vector<ColumnSpec> specs(nspec);
  const char* vb = vocab_blob;
  for (int32_t i = 0; i < nspec; ++i) {
    ColumnSpec& c = specs[i];
    c.kind = kinds[i];
    c.ordinal = ordinals[i];
    c.bucket_width = bucket_widths[i];
    c.bin_offset = bin_offsets[i];
    c.n_bins = n_bins[i];
    if (c.kind == kCategorical || c.kind == kLabel) {
      int32_t code = 0;
      std::string cur;
      while (*vb != '\x1e') {
        if (*vb == '\x1f') {
          c.vocab.emplace(cur, code++);
          cur.clear();
        } else {
          cur.push_back(*vb);
        }
        ++vb;
      }
      ++vb;  // skip column terminator
    }
  }
  // spec index -> output slot
  std::vector<int32_t> slot(nspec, 0);
  {
    int32_t bi = 0, ci = 0;
    for (int32_t i = 0; i < nspec; ++i) {
      if (specs[i].kind == kCategorical || specs[i].kind == kBinnedNumeric)
        slot[i] = bi++;
      else if (specs[i].kind == kContinuous)
        slot[i] = ci++;
    }
  }

  std::vector<const char*> starts(ncols);
  std::vector<size_t> lens(ncols);
  long row = 0;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    // locate line
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    const char* line_end = nl ? nl : end;
    // strip CR
    const char* trimmed = line_end;
    if (trimmed > p && trimmed[-1] == '\r') --trimmed;
    if (trimmed == p) {  // blank line
      p = nl ? nl + 1 : end;
      continue;
    }
    if (row >= max_rows) {
      *err_row = row;
      return kErrTooManyRows;
    }
    // split fields
    int32_t f = 0;
    const char* fs = p;
    for (const char* q = p; q <= trimmed; ++q) {
      if (q == trimmed || *q == delim) {
        if (f < ncols) {
          starts[f] = fs;
          lens[f] = static_cast<size_t>(q - fs);
        }
        ++f;
        fs = q + 1;
      }
    }
    if (f != ncols) {
      *err_row = row;
      return kErrRagged;
    }
    // encode
    for (int32_t i = 0; i < nspec; ++i) {
      const ColumnSpec& c = specs[i];
      const char* s = starts[c.ordinal];
      size_t n = lens[c.ordinal];
      switch (c.kind) {
        case kCategorical: {
          auto it = c.vocab.find(std::string(s, n));
          codes_out[row * n_binned + slot[i]] =
              it == c.vocab.end() ? c.n_bins - 1 : it->second;
          break;
        }
        case kBinnedNumeric: {
          double v;
          if (!parse_double(s, n, &v)) {
            *err_row = row;
            return kErrBadNumber;
          }
          int64_t b = static_cast<int64_t>(std::floor(v / c.bucket_width)) -
                      c.bin_offset;
          if (b < 0) b = 0;
          if (b >= c.n_bins) b = c.n_bins - 1;
          codes_out[row * n_binned + slot[i]] = static_cast<int32_t>(b);
          break;
        }
        case kContinuous: {
          double v;
          if (!parse_double(s, n, &v)) {
            *err_row = row;
            return kErrBadNumber;
          }
          cont_out[row * n_cont + slot[i]] = static_cast<float>(v);
          break;
        }
        case kLabel: {
          auto it = c.vocab.find(std::string(s, n));
          if (it == c.vocab.end()) {
            *err_row = row;
            return kErrUnknownLabel;
          }
          if (labels_out) labels_out[row] = it->second;
          break;
        }
        case kId: {
          if (id_off_out) {
            id_off_out[row] = static_cast<int64_t>(s - buf);
            id_len_out[row] = static_cast<int32_t>(n);
          }
          break;
        }
      }
    }
    ++row;
    p = nl ? nl + 1 : end;
  }
  return row;
}

// Count newline-terminated records (for buffer pre-sizing).
long avenir_csv_count_rows(const char* buf, long len) {
  long rows = 0;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    const char* line_end = nl ? nl : end;
    const char* trimmed = line_end;
    if (trimmed > p && trimmed[-1] == '\r') --trimmed;
    if (trimmed > p) ++rows;
    p = nl ? nl + 1 : end;
  }
  return rows;
}

}  // extern "C"
