// Native data plane: one-pass CSV parse + schema-driven encode.
//
// The reference's record pipeline is the JVM: Hadoop TextInputFormat splits
// lines, every mapper re-splits and re-parses each record's fields
// (e.g. bayesian/BayesianDistribution.java:137-179). Here the equivalent
// hot path — CSV bytes -> int bin codes / float features / class labels —
// is a C++ kernel invoked via ctypes, feeding fixed-shape numpy buffers that
// go straight to TPU infeed. The Python DatasetEncoder
// (core/encoding.py) remains the portable fallback and the source of truth
// for vocab/bin semantics; this kernel implements the identical rules:
//   categorical: vocab lookup, miss -> OOV slot (n_bins-1)
//   binned numeric: clip(floor(v / bucket_width) - bin_offset, 0, n_bins-1)
//   continuous: parsed as float
//   label: vocab lookup, miss -> error
//
// Build: g++ -O3 -shared -fPIC (driven by avenir_tpu/runtime/native.py).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// column kinds, mirroring FeatureField roles
enum Kind : int32_t {
  kCategorical = 0,   // binned via vocab
  kBinnedNumeric = 1, // binned via bucket width
  kContinuous = 2,    // raw float feature
  kLabel = 3,         // class attribute via vocab
  kId = 4,            // record id: emit (offset, length) into the buffer
};

// error codes (negative returns)
constexpr long kErrRagged = -1;
constexpr long kErrBadNumber = -2;
constexpr long kErrUnknownLabel = -3;
constexpr long kErrTooManyRows = -4;

// Zero-copy vocabulary lookup: open-addressing flat table keyed by an
// FNV-1a hash of the raw field bytes. Small-cardinality vocabs (the schema
// contract caps them) probe once or twice; no per-field std::string
// construction or bucket-chain pointer chase as with unordered_map.
struct VocabTable {
  struct Entry {
    uint64_t hash = 0;
    const char* key = nullptr;
    uint32_t len = 0;
    int32_t code = 0;
  };
  std::vector<Entry> entries;
  uint64_t mask = 0;
  std::string storage;  // owns key bytes; pointers stable after build()

  // Word-at-a-time mixer for the short keys vocabularies hold (overlapping
  // head/tail loads, murmur-style finalizer); FNV-1a byte loop only for
  // keys longer than 16 bytes. Used by both build() and find(), so the
  // choice of hash is invisible to callers.
  static uint64_t hash_bytes(const char* s, size_t n) {
    uint64_t a = 0, b = 0;
    if (n > 16) {
      uint64_t h = 1469598103934665603ull;
      for (size_t i = 0; i < n; ++i) {
        h ^= static_cast<unsigned char>(s[i]);
        h *= 1099511628211ull;
      }
      return h;
    }
    if (n >= 8) {
      memcpy(&a, s, 8);
      memcpy(&b, s + n - 8, 8);
    } else if (n >= 4) {
      uint32_t x, y;
      memcpy(&x, s, 4);
      memcpy(&y, s + n - 4, 4);
      a = x;
      b = y;
    } else if (n > 0) {
      a = static_cast<uint8_t>(s[0]) |
          (static_cast<uint64_t>(static_cast<uint8_t>(s[n / 2])) << 8) |
          (static_cast<uint64_t>(static_cast<uint8_t>(s[n - 1])) << 16);
    }
    uint64_t h = (a ^ (b + 0x9e3779b97f4a7c15ull)) * 0xff51afd7ed558ccdull;
    h ^= h >> 33;
    h *= 0xc4ceb9fe1a85ec53ull;
    h ^= h >> 29;
    return h ^ (n * 0x9e3779b97f4a7c15ull);
  }

  void build(const std::vector<std::string>& keys) {
    size_t cap = 8;
    while (cap < keys.size() * 2) cap <<= 1;
    entries.assign(cap, Entry{});
    mask = cap - 1;
    size_t total = 0;
    for (const auto& k : keys) total += k.size();
    storage.reserve(total);
    std::vector<size_t> offs;
    offs.reserve(keys.size());
    for (const auto& k : keys) {
      offs.push_back(storage.size());
      storage += k;
    }
    for (size_t i = 0; i < keys.size(); ++i) {
      const char* k = storage.data() + offs[i];
      const size_t n = keys[i].size();
      const uint64_t h = hash_bytes(k, n);
      size_t p = h & mask;
      while (entries[p].key) p = (p + 1) & mask;
      entries[p] = Entry{h, k, static_cast<uint32_t>(n),
                         static_cast<int32_t>(i)};
    }
  }

  // code for the bytes, or -1 if absent
  int32_t find(const char* s, size_t n) const {
    const uint64_t h = hash_bytes(s, n);
    size_t p = h & mask;
    while (entries[p].key) {
      const Entry& e = entries[p];
      if (e.hash == h && e.len == n && memcmp(e.key, s, n) == 0)
        return e.code;
      p = (p + 1) & mask;
    }
    return -1;
  }
};

struct ColumnSpec {
  int32_t kind;
  int32_t ordinal;
  double bucket_width;
  int64_t bin_offset;
  int32_t n_bins;
  VocabTable vocab;
};

bool parse_double_slow(const char* s, size_t n, double* out) {
  if (n == 0) return false;
  // fields are short: stack buffer avoids a heap allocation per field
  char tmp[64];
  if (n < sizeof(tmp)) {
    memcpy(tmp, s, n);
    tmp[n] = '\0';
    char* end = nullptr;
    *out = std::strtod(tmp, &end);
    return end == tmp + n;
  }
  std::string big(s, n);
  char* end = nullptr;
  *out = std::strtod(big.c_str(), &end);
  return end == big.c_str() + big.size();
}

constexpr double kPow10[16] = {1e0, 1e1, 1e2,  1e3,  1e4,  1e5,  1e6,  1e7,
                               1e8, 1e9, 1e10, 1e11, 1e12, 1e13, 1e14, 1e15};

// Fast path for plain [-]ddd[.ddd] with <=15 total digits: numerator and
// power-of-ten denominator are both exact in double, so the single division
// is correctly rounded — bit-identical to strtod. Anything else (exponents,
// inf/nan, leading whitespace, long digit strings) falls back to strtod.
bool parse_double(const char* s, size_t n, double* out) {
  const char* p = s;
  const char* end = s + n;
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) {
    neg = (*p == '-');
    ++p;
  }
  uint64_t num = 0;
  int digits = 0;
  while (p < end && *p >= '0' && *p <= '9') {
    num = num * 10 + static_cast<uint64_t>(*p - '0');
    ++digits;
    ++p;
  }
  int frac_digits = 0;
  if (p < end && *p == '.') {
    ++p;
    while (p < end && *p >= '0' && *p <= '9') {
      num = num * 10 + static_cast<uint64_t>(*p - '0');
      ++digits;
      ++frac_digits;
      ++p;
    }
  }
  if (p != end || digits == 0 || digits > 15)
    return parse_double_slow(s, n, out);
  const double v = static_cast<double>(num) / kPow10[frac_digits];
  *out = neg ? -v : v;
  return true;
}

std::vector<ColumnSpec> build_specs(
    const int32_t* kinds, const int32_t* ordinals,
    const double* bucket_widths, const int64_t* bin_offsets,
    const int32_t* n_bins, int32_t nspec, const char* vocab_blob) {
  std::vector<ColumnSpec> specs(nspec);
  const char* vb = vocab_blob;
  for (int32_t i = 0; i < nspec; ++i) {
    ColumnSpec& c = specs[i];
    c.kind = kinds[i];
    c.ordinal = ordinals[i];
    c.bucket_width = bucket_widths[i];
    c.bin_offset = bin_offsets[i];
    c.n_bins = n_bins[i];
    if (c.kind == kCategorical || c.kind == kLabel) {
      std::vector<std::string> keys;
      std::string cur;
      while (*vb != '\x1e') {
        if (*vb == '\x1f') {
          keys.push_back(cur);
          cur.clear();
        } else {
          cur.push_back(*vb);
        }
        ++vb;
      }
      ++vb;  // skip column terminator
      c.vocab.build(keys);
    }
  }
  return specs;
}

std::vector<int32_t> build_slots(const std::vector<ColumnSpec>& specs) {
  std::vector<int32_t> slot(specs.size(), 0);
  int32_t bi = 0, ci = 0;
  for (size_t i = 0; i < specs.size(); ++i) {
    if (specs[i].kind == kCategorical || specs[i].kind == kBinnedNumeric)
      slot[i] = bi++;
    else if (specs[i].kind == kContinuous)
      slot[i] = ci++;
  }
  return slot;
}

// Encode records in buf[range_begin:range_end] (newline-aligned) writing
// rows starting at row_start. Returns rows encoded or a negative error code
// with *err_row set to the ABSOLUTE offending row index.
long encode_range(
    const char* buf, const char* range_begin, const char* range_end,
    char delim, int32_t ncols,
    const std::vector<ColumnSpec>& specs, const std::vector<int32_t>& slot,
    int32_t* codes_out, long n_binned, float* cont_out, long n_cont,
    int32_t* labels_out, int64_t* id_off_out, int32_t* id_len_out,
    long row_start, long max_rows, long* err_row) {
  const int32_t nspec = static_cast<int32_t>(specs.size());
  std::vector<const char*> starts(ncols);
  std::vector<size_t> lens(ncols);
  long row = row_start;
  const char* p = range_begin;
  const char* end = range_end;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    const char* line_end = nl ? nl : end;
    const char* trimmed = line_end;
    if (trimmed > p && trimmed[-1] == '\r') --trimmed;
    // skip blank AND whitespace-only lines: the Python ingest path filters
    // on line.strip(), so a line of spaces/tabs must not parse as a 1-field
    // row here and fail the ragged-record check
    const char* ws = p;
    while (ws < trimmed &&
           (*ws == ' ' || *ws == '\t' || *ws == '\v' || *ws == '\f' ||
            *ws == '\r')) ++ws;
    if (ws == trimmed) {
      p = nl ? nl + 1 : end;
      continue;
    }
    if (row >= max_rows) {
      *err_row = row;
      return kErrTooManyRows;
    }
    // SWAR field split: find delimiter bytes 8 at a time (exact zero-byte
    // detect on w ^ broadcast(delim)), ~8x fewer iterations than a per-byte
    // scan on the ~76-byte rows of the north-star workload.
    // NOTE the exact formula: the cheaper (x-0x01..)&~x&0x80.. trick is
    // positionally wrong — its borrow can flag a byte equal to delim^0x01
    // right after a true delimiter (e.g. '-' after ','), splitting negative
    // numbers into phantom fields.
    int32_t f = 0;
    const char* fs = p;
    const uint64_t dbroad =
        0x0101010101010101ull * static_cast<uint8_t>(delim);
    const char* q = p;
    while (q + 8 <= trimmed) {
      uint64_t w;
      memcpy(&w, q, 8);
      const uint64_t x = w ^ dbroad;
      uint64_t hit = ~(((x & 0x7f7f7f7f7f7f7f7full) + 0x7f7f7f7f7f7f7f7full) |
                       x | 0x7f7f7f7f7f7f7f7full);
      while (hit) {
        const char* d = q + (__builtin_ctzll(hit) >> 3);
        if (f < ncols) {
          starts[f] = fs;
          lens[f] = static_cast<size_t>(d - fs);
        }
        ++f;
        fs = d + 1;
        hit &= hit - 1;
      }
      q += 8;
    }
    for (; q < trimmed; ++q) {
      if (*q == delim) {
        if (f < ncols) {
          starts[f] = fs;
          lens[f] = static_cast<size_t>(q - fs);
        }
        ++f;
        fs = q + 1;
      }
    }
    if (f < ncols) {
      starts[f] = fs;
      lens[f] = static_cast<size_t>(trimmed - fs);
    }
    ++f;
    if (f != ncols) {
      *err_row = row;
      return kErrRagged;
    }
    for (int32_t i = 0; i < nspec; ++i) {
      const ColumnSpec& c = specs[i];
      const char* s = starts[c.ordinal];
      size_t n = lens[c.ordinal];
      switch (c.kind) {
        case kCategorical: {
          const int32_t code = c.vocab.find(s, n);
          codes_out[row * n_binned + slot[i]] =
              code < 0 ? c.n_bins - 1 : code;
          break;
        }
        case kBinnedNumeric: {
          double v;
          if (!parse_double(s, n, &v)) {
            *err_row = row;
            return kErrBadNumber;
          }
          int64_t b = static_cast<int64_t>(std::floor(v / c.bucket_width)) -
                      c.bin_offset;
          if (b < 0) b = 0;
          if (b >= c.n_bins) b = c.n_bins - 1;
          codes_out[row * n_binned + slot[i]] = static_cast<int32_t>(b);
          break;
        }
        case kContinuous: {
          double v;
          if (!parse_double(s, n, &v)) {
            *err_row = row;
            return kErrBadNumber;
          }
          cont_out[row * n_cont + slot[i]] = static_cast<float>(v);
          break;
        }
        case kLabel: {
          const int32_t code = c.vocab.find(s, n);
          if (code < 0) {
            *err_row = row;
            return kErrUnknownLabel;
          }
          if (labels_out) labels_out[row] = code;
          break;
        }
        case kId: {
          if (id_off_out) {
            id_off_out[row] = static_cast<int64_t>(s - buf);
            id_len_out[row] = static_cast<int32_t>(n);
          }
          break;
        }
      }
    }
    ++row;
    p = nl ? nl + 1 : end;
  }
  return row - row_start;
}

long count_rows_range(const char* p, const char* end) {
  long rows = 0;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    const char* line_end = nl ? nl : end;
    const char* trimmed = line_end;
    if (trimmed > p && trimmed[-1] == '\r') --trimmed;
    if (trimmed > p) ++rows;
    p = nl ? nl + 1 : end;
  }
  return rows;
}

}  // namespace

extern "C" {

// Parse + encode up to max_rows CSV records from buf[0:len].
//
// Specs arrive as parallel arrays of length nspec, ordered so that all
// categorical/binned specs fill codes_out columns 0..n_binned-1 in order,
// continuous specs fill cont_out columns 0..n_cont-1 in order, and the
// label spec (at most one) fills labels_out. vocab_blob packs the
// vocabularies of vocab-bearing specs in spec order: values separated by
// '\x1f', columns terminated by '\x1e'.
//
// Returns the number of rows encoded, or a negative error code with
// *err_row set to the offending row index.
long avenir_csv_encode(
    const char* buf, long len, char delim, int32_t ncols,
    const int32_t* kinds, const int32_t* ordinals,
    const double* bucket_widths, const int64_t* bin_offsets,
    const int32_t* n_bins, int32_t nspec,
    const char* vocab_blob,
    int32_t* codes_out, long n_binned,
    float* cont_out, long n_cont,
    int32_t* labels_out,
    int64_t* id_off_out, int32_t* id_len_out,
    long max_rows, long* err_row) {
  auto specs = build_specs(kinds, ordinals, bucket_widths, bin_offsets,
                           n_bins, nspec, vocab_blob);
  auto slot = build_slots(specs);
  return encode_range(buf, buf, buf + len, delim, ncols, specs, slot,
                      codes_out, n_binned, cont_out, n_cont, labels_out,
                      id_off_out, id_len_out, 0, max_rows, err_row);
}

// Multithreaded variant: splits the buffer into newline-aligned ranges,
// prefix-sums per-range row counts, then encodes ranges in parallel into
// the shared outputs — deterministic row order identical to the
// single-threaded path (the analog of the reference's per-HDFS-split mapper
// parallelism, in one process).
long avenir_csv_encode_mt(
    const char* buf, long len, char delim, int32_t ncols,
    const int32_t* kinds, const int32_t* ordinals,
    const double* bucket_widths, const int64_t* bin_offsets,
    const int32_t* n_bins, int32_t nspec,
    const char* vocab_blob,
    int32_t* codes_out, long n_binned,
    float* cont_out, long n_cont,
    int32_t* labels_out,
    int64_t* id_off_out, int32_t* id_len_out,
    long max_rows, long* err_row, int32_t nthreads) {
  if (nthreads <= 1 || len < (1 << 20)) {
    return avenir_csv_encode(buf, len, delim, ncols, kinds, ordinals,
                             bucket_widths, bin_offsets, n_bins, nspec,
                             vocab_blob, codes_out, n_binned, cont_out,
                             n_cont, labels_out, id_off_out, id_len_out,
                             max_rows, err_row);
  }
  auto specs = build_specs(kinds, ordinals, bucket_widths, bin_offsets,
                           n_bins, nspec, vocab_blob);
  auto slot = build_slots(specs);

  // newline-aligned range boundaries
  const char* end = buf + len;
  std::vector<const char*> bounds;
  bounds.push_back(buf);
  for (int32_t t = 1; t < nthreads; ++t) {
    const char* guess = buf + (len * t) / nthreads;
    if (guess <= bounds.back()) continue;
    const char* nl = static_cast<const char*>(
        memchr(guess, '\n', static_cast<size_t>(end - guess)));
    const char* b = nl ? nl + 1 : end;
    if (b > bounds.back() && b < end) bounds.push_back(b);
  }
  bounds.push_back(end);
  const int nr = static_cast<int>(bounds.size()) - 1;

  // per-range row counts -> absolute row offsets (parallel count pass)
  std::vector<long> counts(nr, 0);
  {
    std::vector<std::thread> ts;
    for (int r = 0; r < nr; ++r)
      ts.emplace_back([&, r] { counts[r] = count_rows_range(bounds[r], bounds[r + 1]); });
    for (auto& t : ts) t.join();
  }
  std::vector<long> offsets(nr + 1, 0);
  for (int r = 0; r < nr; ++r) offsets[r + 1] = offsets[r] + counts[r];
  if (offsets[nr] > max_rows) {
    *err_row = max_rows;
    return kErrTooManyRows;
  }

  // parallel encode; first (lowest-row) error wins
  std::vector<long> errs(nr, 0);
  std::vector<long> err_rows(nr, 0);
  {
    std::vector<std::thread> ts;
    for (int r = 0; r < nr; ++r) {
      ts.emplace_back([&, r] {
        long e = 0;
        long got = encode_range(buf, bounds[r], bounds[r + 1], delim, ncols,
                                specs, slot, codes_out, n_binned, cont_out,
                                n_cont, labels_out, id_off_out, id_len_out,
                                offsets[r], max_rows, &e);
        errs[r] = got < 0 ? got : 0;
        err_rows[r] = e;
      });
    }
    for (auto& t : ts) t.join();
  }
  for (int r = 0; r < nr; ++r) {
    if (errs[r] < 0) {
      *err_row = err_rows[r];
      return errs[r];
    }
  }
  return offsets[nr];
}

// Count newline-terminated records (for buffer pre-sizing).
long avenir_csv_count_rows(const char* buf, long len) {
  return count_rows_range(buf, buf + len);
}

// Gather id byte ranges, widened to UCS4, into a null-padded [n, maxlen]
// uint32 matrix — the exact memory layout of a numpy 'U<maxlen>' array, so
// the caller just views the buffer. Replaces the numpy fancy-indexing
// gather plus astype('U') pair, whose rows*maxlen temporaries and
// per-element casts dominated encode time. Byte-for-codepoint widening is
// only correct for ASCII: returns 1 if every id byte was ASCII, else 0
// (caller must re-extract with real UTF-8 decoding).
int32_t avenir_gather_ids_u32(const char* buf, const int64_t* off,
                              const int32_t* len, long n, uint32_t* out,
                              int32_t maxlen) {
  uint8_t acc = 0;
  for (long i = 0; i < n; ++i) {
    uint32_t* dst = out + static_cast<long>(i) * maxlen;
    const uint8_t* src = reinterpret_cast<const uint8_t*>(buf + off[i]);
    const int32_t m = len[i] < maxlen ? len[i] : maxlen;
    int32_t j = 0;
    for (; j < m; ++j) {
      acc |= src[j];
      dst[j] = src[j];
    }
    for (; j < maxlen; ++j) dst[j] = 0;
  }
  return (acc & 0x80) ? 0 : 1;
}

}  // extern "C"
