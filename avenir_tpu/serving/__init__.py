"""ServeGraft — the device-resident online scoring plane.

Layers (docs/architecture.md "Serving"): a :class:`ModelRegistry` loads any
trained artifact the batch jobs produce and holds its parameters device-
resident; a :class:`BucketedMicrobatcher` folds concurrent requests into
pre-compiled padded batch buckets (zero steady-state recompiles); HTTP and
RESP-list front ends expose it; ``ScoringPlane`` replays files through it
as a pipeline stage.
"""

from avenir_tpu.serving.batcher import BucketedMicrobatcher, PendingRequest
from avenir_tpu.serving.errors import (
    ReplicaDownError,
    RequestError,
    RequestTimeout,
    ServingError,
    ShedError,
    UnknownModelError,
)
from avenir_tpu.serving.frontend import (
    QueueScoreFrontend,
    ScoreHTTPServer,
    redis_score_frontend,
)
from avenir_tpu.serving.pool import PoolRequest, ReplicaPool
from avenir_tpu.serving.registry import FAMILIES, ModelRegistry, ServableModel
from avenir_tpu.serving.replay import ScoringPlane

__all__ = [
    "BucketedMicrobatcher", "PendingRequest",
    "ServingError", "UnknownModelError", "ShedError", "RequestTimeout",
    "RequestError", "ReplicaDownError",
    "QueueScoreFrontend", "ScoreHTTPServer", "redis_score_frontend",
    "FAMILIES", "ModelRegistry", "ServableModel",
    "ReplicaPool", "PoolRequest",
    "ScoringPlane",
]
