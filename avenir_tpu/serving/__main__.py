"""Serving CLI — ``python -m avenir_tpu.serving --conf serve.properties``.

Loads every family in ``serve.models`` from the properties file's artifact
paths, warms the (model, bucket) compile cache, and serves.  With
``pool.replicas`` (or ``pool.autoscale.on``) set, the plane is a
FleetServe :class:`~avenir_tpu.serving.pool.ReplicaPool` — N batcher
replicas with health-gated routing, breaker/heartbeat failure detection,
request failover and burn-rate autoscaling — behind the same transports:

- HTTP on ``serve.http.port`` (default 8390): ``POST /score``,
  ``GET /healthz``, ``GET /stats`` — see docs/deployment.md for a
  serve-then-curl walkthrough;
- optionally a RESP list pair on a Redis server when
  ``serve.request.queue`` is set (``serve.redis.host``/``serve.redis.port``,
  responses to ``serve.response.queue``) — the transport the reference's
  own Redis simulators drive.

Runs until interrupted; stats print once on shutdown.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
from typing import List

from avenir_tpu.core.config import JobConfig


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m avenir_tpu.serving",
        description="ServeGraft — device-resident online scoring plane")
    ap.add_argument("--conf", required=True,
                    help="properties file (serve.* keys + model artifacts)")
    ap.add_argument("--http-port", type=int, default=None,
                    help="override serve.http.port")
    ap.add_argument("-D", dest="overrides", action="append", default=[],
                    metavar="KEY=VALUE",
                    help="conf override (repeatable) — how the GlobalServe "
                         "launcher pins per-worker keys (trace.run.id, "
                         "split tenant contracts) over a shared conf file")
    args = ap.parse_args(argv)

    from avenir_tpu.serving.batcher import BucketedMicrobatcher
    from avenir_tpu.serving.frontend import (
        ScoreHTTPServer,
        redis_score_frontend,
    )
    from avenir_tpu.serving.pool import ReplicaPool
    from avenir_tpu.serving.registry import ModelRegistry

    conf = JobConfig.from_file(args.conf)
    for item in args.overrides:
        key, eq, value = item.partition("=")
        if not eq or not key.strip():
            ap.error(f"-D expects KEY=VALUE, got {item!r}")
        conf.set(key.strip(), value.strip())
    # wire GraftTrace/GraftProf from the same properties file the models
    # load from (trace.on / profile.on — both default off); a replica
    # pool sets trace.writer.suffix per worker, which names this
    # process's journal shard AND its /metrics `replica` label
    from avenir_tpu.telemetry import spans as tel
    from avenir_tpu.telemetry.export import fleet_identity
    from avenir_tpu.telemetry.slo import SloEvaluator

    tel.configure(conf)
    # GraftPool (round 18): arm the tenant arbiter from tenant.* contracts
    # (no-op without them) — a tenant-owned serving plane (tenant.id) then
    # draws arbitrated dispatch slots and sheds tenant-scoped 429s with
    # Retry-After drain estimates
    from avenir_tpu import tenancy

    tenancy.configure(conf)
    slo = SloEvaluator.from_conf(conf)
    # FleetServe (round 17): any pool.* arming serves a ReplicaPool — N
    # batcher replicas with health-gated routing, breaker/heartbeat
    # failure detection, failover and burn-rate autoscaling — behind the
    # SAME frontends; without it the plane stays one batcher
    if conf.get_int("pool.replicas", 0) or \
            conf.get_bool("pool.autoscale.on", False):
        # the frontend and the pool's autoscaler share ONE evaluator, so
        # its violation latch journals one slo.violation per excursion
        # (the round-15 contract), not one per consumer
        batcher = ReplicaPool.from_conf(conf, slo=slo)
        health = batcher.health()
        names = health["models"]
        pool_note = f" x{len(health['replicas'])} replicas"
    else:
        registry = ModelRegistry.from_conf(conf)
        batcher = BucketedMicrobatcher.from_conf(registry, conf)
        names = registry.names()
        pool_note = ""
    port = (args.http_port if args.http_port is not None
            else conf.get_int("serve.http.port", 8390))
    # GlobalServe (round 20): behind a fleet launcher the writer suffix
    # names this worker PROCESS (w<k> via AVENIR_WRITER_SUFFIX), so the
    # same suffix rides /metrics as the `worker` label — every scrape
    # surface in a fleet is distinguishable even with identical replica
    # sets (the router scrapes as worker="router")
    suffix = (conf.get("trace.writer.suffix")
              or tel.tracer().writer_suffix or None)
    http = ScoreHTTPServer(
        batcher, port=port, slo=slo,
        identity=fleet_identity(
            replica=suffix,
            tenant=conf.get("tenant.id"),
            worker=suffix)).start()
    print(f"serving {names} on "
          f"http://{http.address[0]}:{http.address[1]} "
          f"(buckets {batcher.buckets}){pool_note}"
          + (f" with {len(slo.rules)} SLO rule(s)" if slo else ""),
          flush=True)

    request_queue = conf.get("serve.request.queue")
    if request_queue:
        frontend = redis_score_frontend(
            batcher,
            host=conf.get("serve.redis.host", "localhost"),
            port=conf.get_int("serve.redis.port", 6379),
            request_queue=request_queue,
            response_queue=conf.get("serve.response.queue",
                                    "scoreResponseQueue"))
        threading.Thread(target=frontend.run, daemon=True,
                         name="serve-resp").start()
        print(f"RESP transport polling {request_queue!r}", flush=True)

    # SIGTERM is how an orchestrator stops a replica (the GraftFleet
    # deployment shape): without a handler the default action kills the
    # process mid-write and skips the shutdown snapshot below — treat it
    # exactly like Ctrl-C.  GraftBox first: the forensics bundle latches
    # with the in-flight table as it stood when the signal landed (no-op
    # when blackbox.dir is unset), THEN the graceful drain runs.
    import signal

    from avenir_tpu.telemetry import blackbox

    stop = threading.Event()

    def _on_term(*_):
        blackbox.on_signal("SIGTERM")
        stop.set()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:                       # pragma: no cover - non-main
        pass
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    finally:
        http.stop()
        batcher.close()
        # final counter snapshot into this replica's journal shard (no-op
        # untraced): the post-hoc SLO gate's counter metrics (shed.rate,
        # recompiles.total) and `telemetry metrics` need a snapshot — the
        # serving loop otherwise journals only spans and gauges
        tel.tracer().counters("serving", batcher.counters)
        print(json.dumps(batcher.stats()), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
