"""Bucketed microbatcher — the scoring plane's shape-discipline core.

Concurrent requests for one model are folded into padded power-of-two batch
buckets (``serve.bucket.sizes``), every (model, bucket) shape is compiled at
startup (``serve.warmup.on.start``), and steady-state serving therefore
NEVER recompiles — the compiler-first caching discipline of
"Compiler-First State Space Duality and Portable O(1) Autoregressive
Caching for Inference" (PAPERS.md) applied to this framework's classical
models.  The batcher diffs each entry's ``compile_keys`` after every batch
and publishes a ``recompiles`` counter so the invariant is *measured*, not
assumed (benchmarks/serving_qps.py asserts it is zero).

Latency/throughput policy:

- a batch dispatches as soon as a full ``max(bucket)`` is waiting, or when
  the OLDEST pending request ages past ``serve.flush.deadline.ms`` — the
  max-latency flush that keeps a lone request from waiting for company;
- each model's pending queue is bounded by ``serve.queue.depth``; a submit
  against a full queue is rejected with a typed :class:`ShedError` (the
  ``max.spout.pending`` analog — load is shed at the door, not absorbed
  until everything is slow);
- a request that ages past ``serve.request.timeout.ms`` before a batch
  picks it up fails with :class:`RequestTimeout`.

One dispatcher thread owns every device call: the accelerator serializes
batches anyway, and a single submitter keeps the jit cache and the CUDA/TPU
stream free of cross-thread interleaving.  ``submit`` may be called from any
number of frontend threads.

FleetServe (round 17): a batcher is now one REPLICA of a
:class:`~avenir_tpu.serving.pool.ReplicaPool` — ``name`` labels its spans,
errors and journal events; ``counters``/``latency`` may be shared across
the pool so ``/metrics`` aggregates for free; the dispatcher maintains a
``heartbeat`` the pool's deadline detection reads (:meth:`stalled`); and a
conf-armed :class:`~avenir_tpu.utils.retry.FaultPlan` can kill it through
two sites — ``serve.dispatch`` (replica dies mid-batch: every unfinished
request fails with the retryable :class:`ReplicaDownError`, the pool's
failover cue) and ``serve.heartbeat`` (the dispatcher wedges silently:
pending requests stay stranded until the pool's heartbeat deadline reaps
them) — so chaos drills arm replica loss from configuration alone.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from avenir_tpu import tenancy
from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.serving.errors import (
    ReplicaDownError,
    RequestError,
    RequestTimeout,
    ServingError,
    ShedError,
    TenantShedError,
)
from avenir_tpu.serving.registry import ModelRegistry
from avenir_tpu.telemetry import blackbox
from avenir_tpu.telemetry import profile as prof_mod
from avenir_tpu.telemetry import spans as tel
from avenir_tpu.utils.metrics import Counters, LatencyTracker, serving_stats
from avenir_tpu.utils.retry import FaultPlan, InjectedFault


class PendingRequest:
    """One in-flight request; ``wait`` blocks until scored (or failed).

    ``trace_ctx`` captures the submitter's span (None with tracing off):
    the dispatch thread can't see the submitting context, so the request's
    span is emitted retroactively with this parent — how a serving request
    joins the pipeline trace through the ScoringPlane stage.

    ``rid`` (FleetServe): an optional caller-assigned request id carried
    into the ``serve.request`` span, so a pool's failover dedupe — "this
    request scored exactly once, on exactly one replica" — is assertable
    from the journal.  ``probe`` marks a breaker half-open liveness probe:
    the dispatcher answers it without scoring (and without counters).

    ``tenant`` (GlobalServe): captured from the SUBMITTER's ambient
    labels, because the ``serve.request`` span is emitted by the
    dispatcher thread, whose own contextvars never saw the tenant — the
    attribute is what lets ``telemetry slo --label tenant=<id>`` gate one
    tenant's requests out of a merged fleet journal."""

    __slots__ = ("model", "line", "enqueued", "result", "error", "_done",
                 "trace_ctx", "rid", "probe", "tenant")

    def __init__(self, model: str, line: str, rid: Optional[str] = None,
                 probe: bool = False, tenant: Optional[str] = None):
        self.model = model
        self.line = line
        self.enqueued = time.monotonic()
        self.result: Optional[str] = None
        self.error: Optional[ServingError] = None
        self._done = threading.Event()
        self.trace_ctx = tel.tracer().current()
        self.rid = rid
        self.probe = probe
        self.tenant = tenant if tenant is not None \
            else tel.current_label("tenant")

    def finish(self, result: Optional[str] = None,
               error: Optional[ServingError] = None) -> None:
        # idempotent: a request that already scored must NEVER be
        # re-finished with a replica-death error (the at-most-once pillar
        # of pool failover — a done request is done)
        if self._done.is_set():
            return
        self.result = result
        self.error = error
        self._done.set()

    def wait(self, timeout_s: Optional[float] = None) -> str:
        if not self._done.wait(timeout_s):
            raise RequestTimeout(
                f"no response for {self.model!r} request within "
                f"{timeout_s}s (dispatcher wedged or closed?)")
        if self.error is not None:
            raise self.error
        return self.result  # type: ignore[return-value]


class BucketedMicrobatcher:
    def __init__(self, registry: ModelRegistry,
                 bucket_sizes: Sequence[int] = (1, 2, 4, 8, 16, 32, 64),
                 flush_deadline_ms: float = 5.0,
                 queue_depth: int = 1024,
                 request_timeout_ms: float = 1000.0,
                 warmup: bool = True,
                 counters: Optional[Counters] = None,
                 latency: Optional[Dict[str, LatencyTracker]] = None,
                 name: str = "",
                 tenant: str = "",
                 fault: Optional[FaultPlan] = None,
                 device=None,
                 on_batch_ok: Optional[Callable[[], None]] = None,
                 on_batch_error: Optional[Callable[[BaseException],
                                                   None]] = None):
        self.registry = registry
        self.buckets = sorted({int(b) for b in bucket_sizes})
        if not self.buckets or self.buckets[0] < 1:
            raise ConfigError(f"invalid serve.bucket.sizes {bucket_sizes!r}")
        self.max_bucket = self.buckets[-1]
        self.flush_deadline_s = float(flush_deadline_ms) / 1e3
        self.queue_depth = max(int(queue_depth), 1)
        self.request_timeout_s = float(request_timeout_ms) / 1e3
        self.counters = counters if counters is not None else Counters()
        # ``latency`` may be a POOL-shared dict (FleetServe): every replica
        # records into the same per-model trackers, so the pool's /metrics
        # and SLO evaluation aggregate without a merge step
        self.latency: Dict[str, LatencyTracker] = (
            latency if latency is not None else {})
        for model in registry.names():
            self.latency.setdefault(model, LatencyTracker())
        # FleetServe replica identity + failure machinery: ``name`` labels
        # spans/errors/events; ``fault`` is the conf-armed kill schedule
        # (shared across a pool so site counts are pool-wide); ``device``
        # pins this replica's dispatches (the dispatcher thread enters
        # jax.default_device(device) — one replica per local chip);
        # ``heartbeat`` is the dispatcher's liveness signal, updated every
        # loop wake and read by ReplicaPool.stalled-based deadline checks
        self.name = name
        # GraftPool (round 18): the tenant this serving plane belongs to
        # (``tenant.id``).  The dispatcher runs under the tenant's label
        # scope (every serve.request span/gauge it journals carries the
        # tenant), each batch dispatch draws an arbitrated device slot
        # under the tenant's contract, and door sheds are tenant-scoped:
        # they name the tenant + quota and carry the queue drain estimate
        # the HTTP frontend renders as Retry-After.
        self.tenant = tenant
        self.fault = fault
        self.device = device
        self.on_batch_ok = on_batch_ok
        self.on_batch_error = on_batch_error
        self.heartbeat = time.monotonic()
        self.failed = False
        self._dispatching = False
        # per-model EWMA of batch dispatch seconds — the queue drain
        # estimate behind a shed's Retry-After (satellite: a 429 tells
        # the client WHEN to come back, not just "go away")
        self._dispatch_ewma: Dict[str, float] = {}
        self._queues: Dict[str, Deque[PendingRequest]] = {
            name: deque() for name in registry.names()}
        # recompile accounting: the shared compile-key diff (telemetry,
        # generalized out of this file in round 10) — warmup primes it,
        # any fresh key afterwards counts under Serving.<name>::recompiles
        self._monitors: Dict[str, tel.CompileKeyMonitor] = {
            name: tel.CompileKeyMonitor(self.counters,
                                        group=f"Serving.{name}", scope=name)
            for name in registry.names()}
        self._cond = threading.Condition()
        self._stop = False
        # GraftBox: requests popped from their queues but not yet
        # scored — with the queues, the in-flight table a forensics
        # bundle snapshots (rid + tenant + queue age of everything this
        # replica would strand if it died right now)
        self._active: List[PendingRequest] = []
        self._bb_name = f"batcher-{name}" if name else \
            f"batcher-{id(self):x}"
        blackbox.register_provider(self._bb_name, self._blackbox_inflight,
                                   kind="inflight")
        # readiness (GraftFleet round 15): the /healthz probe's contract —
        # a load balancer must not route to a replica whose (model,
        # bucket) shapes are not compiled yet, or the first requests pay
        # the compile on the hot path.  False until warm() completes; a
        # deployment that disables serve.warmup.on.start stays NOT ready
        # until it calls warm() itself (scoring is never gated — only the
        # readiness signal).
        self.ready = False
        if warmup:
            self.warm()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"serve-dispatch-{name}" if name else "serve-dispatch")
        self._thread.start()

    @classmethod
    def from_conf(cls, registry: ModelRegistry, conf: JobConfig,
                  **kwargs) -> "BucketedMicrobatcher":
        """``kwargs`` passes through the FleetServe wiring (``name``,
        shared ``counters``/``latency``, ``device``, the dispatch
        callbacks).  A ``fault`` plan not supplied by the caller is armed
        from the conf's own ``fault.*`` keys, so a single-replica tier-1
        test kills its batcher through configuration alone."""
        if "fault" not in kwargs:
            kwargs["fault"] = FaultPlan.from_conf(conf)
        if "tenant" not in kwargs:
            kwargs["tenant"] = conf.get("tenant.id", "") or ""
        return cls(
            registry,
            bucket_sizes=conf.get_int_list("serve.bucket.sizes",
                                           [1, 2, 4, 8, 16, 32, 64]),
            flush_deadline_ms=conf.get_float("serve.flush.deadline.ms", 5.0),
            queue_depth=conf.get_int("serve.queue.depth", 1024),
            request_timeout_ms=conf.get_float("serve.request.timeout.ms",
                                              1000.0),
            warmup=conf.get_bool("serve.warmup.on.start", True),
            **kwargs,
        )

    # -- warmup / recompile accounting ---------------------------------------
    def warm(self) -> Dict[str, int]:
        """Compile every (model, bucket) shape; shapes seen here never count
        as recompiles later.  Completing marks the batcher ready (the
        /healthz readiness contract)."""
        warmed = self.registry.warmup(self.buckets)
        for name, entry in self.registry.items():
            self._monitors[name].prime(entry.compile_keys)
        self.ready = True
        return warmed

    # -- hot swap (any thread) -----------------------------------------------
    def swap(self, model: str, entry, warm: bool = True) -> int:
        """Zero-downtime model hot-swap with the compile barrier.

        Warms the INCOMING entry's bucket shapes and primes its recompile
        monitor BEFORE publishing it to the registry, so the first
        post-swap batch scores on already-compiled shapes — the
        zero-steady-state-recompiles invariant holds ACROSS a swap, not
        just between swaps.  In-flight batches hold the old entry object
        they resolved at dispatch and finish on the old params; every
        batch dispatched after the publish resolves the new entry.
        Documented exception to the one-dispatcher-thread rule: the
        warmup compiles run on the CALLER's thread concurrently with live
        dispatches (JAX is thread-safe; routing them through the
        dispatcher would stall the same batches behind the same compiles)
        — expect a p99 bump for the duration of a swap either way.
        ``warm=False`` (``serve.swap.warmup``) skips the barrier — the
        first post-swap batch then pays the compile on the hot path and
        the monitor counts it, which is exactly the visibility the
        default exists to avoid.  Returns the model's new version."""
        self.registry.get(model)          # raises UnknownModelError early
        if warm:
            for bucket in self.buckets:
                entry.warmup(int(bucket))
            self._monitors[model].prime(entry.compile_keys)
        version = self.registry.swap(model, entry)
        self.counters.increment(f"Serving.{model}", "swaps")
        tel.tracer().event("model.swap", model=model, version=version,
                           family=entry.family, warmed=bool(warm))
        # swap boundary: the outgoing entry's device buffers should be
        # collectable once in-flight batches drain — a leak across
        # repeated hot-swaps shows up in this gauge before it OOMs
        prof_mod.profiler().sample_device_memory("swap")
        return version

    # -- submission (any thread) ---------------------------------------------
    def submit_nowait(self, model: str, line: str,
                      rid: Optional[str] = None) -> PendingRequest:
        entry = self.registry.get(model)            # raises UnknownModelError
        del entry
        req = PendingRequest(model, line, rid=rid)
        shed_depth = None
        with self._cond:
            if self.failed:
                raise self._down_error("replica is down")
            if self._stop:
                raise ServingError("batcher is closed")
            queue = self._queues[model]
            if len(queue) >= self.queue_depth:
                self.counters.increment(f"Serving.{model}", "shed")
                if self.tenant:
                    self.counters.increment(f"Tenant.{self.tenant}", "shed")
                shed_depth = len(queue)
            else:
                queue.append(req)
                depth = len(queue)
                self._cond.notify()
        if shed_depth is None:
            # GraftBox: the submit door records straight to the flight
            # ring (trace.on or not, and outside the lock) — a SIGKILLed
            # replica's bundle shows WHICH rids were in flight
            blackbox.ring_record("serve.submit",
                                 {"rid": req.rid, "model": model,
                                  "tenant": req.tenant, "depth": depth})
        if shed_depth is not None:
            if self.tenant:
                # tenant-scoped door shed: booked under the tenant (above,
                # in the lock), journaled as tenant.shed and raised HERE —
                # outside the lock, so a shed storm's journal I/O never
                # serializes other submitters — carrying the queue drain
                # estimate (Retry-After) + the quota that fired
                retry_after = self.drain_estimate_s(model)
                tel.tracer().event(
                    "tenant.shed", tenant=self.tenant,
                    quota="serve.queue.depth",
                    waiting=shed_depth, inflight=0,
                    retry_after_ms=round(retry_after * 1e3, 1))
                raise self._attribute(TenantShedError(
                    f"{model!r} queue at depth {self.queue_depth} for "
                    f"tenant {self.tenant!r} — request shed "
                    f"(backpressure); retry after ~{retry_after:.2f}s",
                    tenant=self.tenant, quota="serve.queue.depth",
                    retry_after_s=retry_after), wait_s=0.0)
            raise self._attribute(ShedError(
                f"{model!r} queue at depth {self.queue_depth}"
                + (f" on replica {self.name!r}" if self.name else "")
                + " — request shed (backpressure)"), wait_s=0.0)
        return req

    def submit(self, model: str, line: str,
               timeout_s: Optional[float] = None) -> str:
        """Blocking submit: returns the response line or raises the typed
        error.  Default wait bound covers the request timeout plus dispatch
        slack so a wedged dispatcher surfaces as RequestTimeout, not a hang."""
        if timeout_s is None:
            timeout_s = self.request_timeout_s + 30.0
        return self.submit_nowait(model, line).wait(timeout_s)

    # -- dispatch loop (one thread) ------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.max_bucket

    def _ready(self, now: float) -> List[str]:
        out = []
        for name, queue in self._queues.items():
            if not queue:
                continue
            if (len(queue) >= self.max_bucket
                    or now - queue[0].enqueued >= self.flush_deadline_s):
                out.append(name)
        return out

    def _next_wait(self, now: float) -> Optional[float]:
        deadlines = [queue[0].enqueued + self.flush_deadline_s - now
                     for queue in self._queues.values() if queue]
        if not deadlines:
            return None                   # sleep until a submit notifies
        return max(min(deadlines), 0.0)

    def _loop(self) -> None:
        with contextlib.ExitStack() as stack:
            if self.tenant:
                # the dispatcher works AS the tenant: every span, gauge
                # and recompile event it journals carries the label, so
                # one merged fleet view attributes this plane's serving
                # cost to its owner
                stack.enter_context(tel.label_scope(tenant=self.tenant))
            if self.device is not None:
                import jax

                # replica-per-chip placement: every dispatch this thread
                # makes defaults onto this replica's device (params
                # committed elsewhere still win — jax array placement)
                stack.enter_context(jax.default_device(self.device))
            while True:
                with self._cond:
                    self.heartbeat = time.monotonic()
                    if self.fault is not None:
                        try:
                            self.fault.hit("serve.heartbeat")
                        except InjectedFault:
                            # the wedged-dispatcher drill: exit WITHOUT
                            # finishing pending work — the heartbeat goes
                            # stale and the pool's deadline detection is
                            # what has to reap the stranded queue
                            return
                    while not self._stop and \
                            not self._ready(time.monotonic()):
                        self._cond.wait(
                            timeout=self._next_wait(time.monotonic()))
                        self.heartbeat = time.monotonic()
                    if self._stop and not any(self._queues.values()):
                        return
                    ready = ([name for name, q in self._queues.items() if q]
                             if self._stop
                             else self._ready(time.monotonic()))
                    batches: List[Tuple[str, List[PendingRequest]]] = []
                    for name in ready:
                        queue = self._queues[name]
                        take = min(len(queue), self.max_bucket)
                        batches.append((name,
                                        [queue.popleft()
                                         for _ in range(take)]))
                    self._active = [r for _, rs in batches for r in rs]
                    self._dispatching = True
                try:
                    for i, (name, reqs) in enumerate(batches):
                        # refreshed PER BATCH (lock-free: a float store
                        # is atomic under the GIL, and the monitor only
                        # compares staleness) so a dispatcher working
                        # through several slow batches reads as busy,
                        # not wedged — only true silence past the
                        # deadline is a miss
                        self.heartbeat = time.monotonic()
                        try:
                            # GraftBox: a dispatch that wedges (stuck
                            # device call, deadlocked arbiter) trips the
                            # progress watchdog and captures a bundle
                            with blackbox.watchdog_guard("serve.dispatch"):
                                self._dispatch(name, reqs)
                        except Exception:  # noqa: BLE001
                            # replica-fatal, injected (serve.dispatch
                            # kill) or real: every unfinished request
                            # (this batch + everything queued) fails
                            # RETRYABLE so the pool can re-enqueue it on
                            # a survivor — waiting for the heartbeat
                            # deadline to reap a silently-dead loop
                            # would stall them for seconds instead
                            self._die([r for _, rs in batches[i:]
                                       for r in rs])
                            return
                finally:
                    with self._cond:
                        self._dispatching = False
                        self._active = []
                        self.heartbeat = time.monotonic()

    def _dispatch(self, model: str, reqs: List[PendingRequest]) -> None:
        scorable = [r for r in reqs if not r.probe]
        for req in reqs:
            if req.probe:
                # breaker half-open liveness probe: answered by the
                # dispatcher without scoring (and without counters) — it
                # proves THIS thread is alive and draining its queue
                req.finish(result="pong")
        if not scorable:
            return
        if self.fault is not None:
            # the replica-kill site: fires BEFORE any request of the
            # batch scores (InjectedFault propagates to _loop → _die),
            # so an injected death can never double-score a request
            self.fault.hit("serve.dispatch")
        group = f"Serving.{model}"
        now = time.monotonic()
        live: List[PendingRequest] = []
        for req in scorable:
            if now - req.enqueued > self.request_timeout_s:
                self.counters.increment(group, "timeouts")
                req.finish(error=self._attribute(RequestTimeout(
                    f"request waited past "
                    f"{self.request_timeout_s * 1e3:.0f} ms before dispatch"
                    + (f" on replica {self.name!r}" if self.name else "")),
                    wait_s=now - req.enqueued))
            else:
                live.append(req)
        if not live:
            return
        entry = self.registry.get(model)
        bucket = self._bucket_for(len(live))
        try:
            # GraftPool (round 18): the batch draws an arbitrated device
            # slot under this plane's tenant contract before it scores —
            # serve dispatches and batch/stream chunk folds share ONE
            # fair-queued pool.  Un-tenanted batchers pass through (the
            # shared null context).  The slot wait is bounded by the
            # request timeout (a tenant paced past it sheds typed rather
            # than stranding requests) and ticks the heartbeat while
            # queued — being PACED is not being WEDGED, and the pool's
            # deadline watch must not reap a merely-contended replica.
            with tenancy.pool().slot(tenant=self.tenant or None,
                                     timeout_s=self.request_timeout_s,
                                     on_wait=self._beat):
                t0 = time.monotonic()
                outs = entry.score_lines([r.line for r in live], bucket)
                dispatch_s = time.monotonic() - t0
        except TenantShedError as exc:
            # the tenant's pool share refused this batch before any row
            # scored: fail the whole batch typed — tenant-scoped, so the
            # other tenants' planes keep dispatching
            self.counters.increment(group, "shed", len(live))
            self._attribute(exc)
            for req in live:
                req.finish(error=exc)
            return
        except Exception as exc:
            # typed ServingErrors are REQUEST faults (bad rows); anything
            # else is an infrastructure fault the pool's breaker counts
            if self.on_batch_error is not None and \
                    not isinstance(exc, ServingError):
                self.on_batch_error(exc)
            # one bad row must not poison its coalesced batch neighbors:
            # re-score each request alone (smallest bucket — warmed, so no
            # recompile) so only the genuinely bad ones fail typed
            if len(live) > 1:
                self._dispatch_isolated(entry, group, live)
                return
            self.counters.increment(group, "errors")
            err = (exc if isinstance(exc, ServingError)
                   else RequestError(f"{type(exc).__name__}: {exc}"))
            live[0].finish(error=self._attribute(
                err, wait_s=time.monotonic() - live[0].enqueued))
            return
        prev = self._dispatch_ewma.get(model)
        self._dispatch_ewma[model] = (
            dispatch_s if prev is None else 0.8 * prev + 0.2 * dispatch_s)
        if self.on_batch_ok is not None:
            self.on_batch_ok()
        self._finish_scored(entry, group, model, live, outs, bucket,
                            dispatch_s)

    def _dispatch_isolated(self, entry, group: str,
                           reqs: List[PendingRequest]) -> None:
        """Failure-isolation path: score each request of a failed batch
        alone; good rows still succeed, bad rows carry their own error."""
        model = reqs[0].model
        bucket = self._bucket_for(1)
        for req in reqs:
            try:
                with tenancy.pool().slot(tenant=self.tenant or None,
                                         timeout_s=self.request_timeout_s,
                                         on_wait=self._beat):
                    outs = entry.score_lines([req.line], bucket)
            except TenantShedError as exc:
                self.counters.increment(group, "shed")
                req.finish(error=self._attribute(exc))
                continue
            except Exception as exc:
                if self.on_batch_error is not None and \
                        not isinstance(exc, ServingError):
                    self.on_batch_error(exc)
                self.counters.increment(group, "errors")
                err = (exc if isinstance(exc, ServingError)
                       else RequestError(f"{type(exc).__name__}: {exc}"))
                req.finish(error=self._attribute(
                    err, wait_s=time.monotonic() - req.enqueued))
                continue
            if self.on_batch_ok is not None:
                self.on_batch_ok()
            self._finish_scored(entry, group, model, [req], outs, bucket)

    def _finish_scored(self, entry, group: str, model: str,
                       live: List[PendingRequest], outs: List[str],
                       bucket: int,
                       dispatch_s: Optional[float] = None) -> None:
        # a shape outside the warmed set means this batch paid a compile
        # on the hot path — the invariant violation the counter exposes
        # (the monitor's key feed also registers each key as a GraftProf
        # program under site=<model>)
        self._monitors[model].observe(entry.compile_keys)
        done = time.monotonic()
        tracer = tel.tracer()
        prof = prof_mod.profiler()
        pid = None
        if prof.enabled:
            # the program this batch dispatched: the entry's compile key
            # for this bucket (every entry keys on (bucket, ...))
            pkey = next((k for k in entry.compile_keys
                         if k and k[0] == bucket), (bucket,))
            pid = prof_mod.program_id(model, pkey)
            if dispatch_s is not None:
                prof.sample(pkey, model, dispatch_s)
        tracker = self.latency[model]
        for req, out in zip(live, outs):
            req.finish(result=out)
            wait_s = done - req.enqueued
            tracker.record(wait_s)
            if tracer.enabled:
                # FleetServe attribution: which replica scored this
                # request and how long it sat queued — a shed storm or
                # p99 excursion is triaged to ONE replica from the
                # merged fleet journal
                attrs = {"model": model, "bucket": bucket,
                         "wait_ms": round(wait_s * 1e3, 3)}
                if self.name:
                    attrs["replica"] = self.name
                if req.rid is not None:
                    attrs["rid"] = req.rid
                if req.tenant:
                    attrs["tenant"] = req.tenant
                if pid is not None:
                    attrs["program"] = pid
                tracer.emit_span("serve.request", wait_s,
                                 parent=req.trace_ctx, attrs=attrs)
        self.counters.increment(group, "requests", len(live))
        self.counters.increment(group, "batches")
        self.counters.increment(group, f"bucket.{bucket}")
        if tracer.enabled:
            tracer.gauge(f"serve.queue.{model}", len(self._queues[model]))

    def _beat(self) -> None:
        """Heartbeat tick while queued on the tenant arbiter (a float
        store is atomic under the GIL — same contract as the per-batch
        refresh in ``_loop``): a paced dispatcher reads as busy, never
        as wedged, so only true silence past the deadline is a miss."""
        self.heartbeat = time.monotonic()

    # -- replica failure machinery (FleetServe, round 17) --------------------
    def _attribute(self, err: ServingError,
                   wait_s: Optional[float] = None) -> ServingError:
        """Stamp a typed error with this replica's identity, its tenant
        and the request's queue wait, so client-visible failures triage
        to the replica (and owner) that caused them without the journal."""
        err.replica = self.name or None
        if self.tenant and getattr(err, "tenant", None) in (None, ""):
            err.tenant = self.tenant
        if wait_s is not None:
            err.queue_wait_ms = round(wait_s * 1e3, 3)
        return err

    def drain_estimate_s(self, model: str) -> float:
        """How long this model's pending queue needs to drain: queued
        batches × (EWMA batch dispatch + the flush deadline) — the
        ``Retry-After`` a tenant-scoped shed carries.  Bounded by the
        arbiter's shared clamp policy; no dispatch observed yet reads as
        a nominal 50 ms batch."""
        from avenir_tpu.tenancy.arbiter import (
            RETRY_AFTER_MAX_S,
            RETRY_AFTER_MIN_S,
        )

        depth = len(self._queues[model])
        batches = max((depth + self.max_bucket - 1) // self.max_bucket, 1)
        est = batches * (self._dispatch_ewma.get(model, 0.05)
                         + self.flush_deadline_s)
        return min(max(est, RETRY_AFTER_MIN_S), RETRY_AFTER_MAX_S)

    def _down_error(self, reason: str,
                    req: Optional[PendingRequest] = None) -> ReplicaDownError:
        err = ReplicaDownError(
            (f"replica {self.name!r}: " if self.name else "") + reason)
        return self._attribute(
            err, wait_s=(time.monotonic() - req.enqueued)
            if req is not None else None)

    def _die(self, stranded: List[PendingRequest]) -> None:
        """serve.dispatch kill: mark the replica failed (new submissions
        are refused at the door) and fail every unfinished request —
        ``stranded`` (popped but unscored) plus everything still queued —
        with the RETRYABLE :class:`ReplicaDownError`, the pool's cue to
        re-enqueue them on survivors.  ``finish`` is idempotent, so a
        request that already scored can never be re-failed here."""
        with self._cond:
            self.failed = True
            queued = [r for q in self._queues.values() for r in q]
            for q in self._queues.values():
                q.clear()
            self._cond.notify_all()
        for req in stranded + queued:
            req.finish(error=self._down_error("died mid-batch", req))

    def mark_failed(self) -> None:
        """Pool-side declaration that this replica is dead (missed
        heartbeat deadline): refuse new submissions from now on."""
        with self._cond:
            self.failed = True
            self._cond.notify_all()

    def fail_pending(self, reason: str = "replica down") -> int:
        """Fail every QUEUED request with :class:`ReplicaDownError` (the
        pool reaps a wedged replica's stranded queue with this); returns
        how many requests were failed over."""
        with self._cond:
            reqs = [r for q in self._queues.values() for r in q]
            for q in self._queues.values():
                q.clear()
        for req in reqs:
            req.finish(error=self._down_error(reason, req))
        return len(reqs)

    def stalled(self, deadline_s: float) -> bool:
        """True when the dispatcher has WORK but its heartbeat is older
        than ``deadline_s`` — a wedged (or silently dead) dispatcher.
        An idle batcher is never stalled: with nothing to dispatch a
        stale heartbeat is just sleep."""
        with self._cond:
            busy = self._dispatching or any(self._queues.values())
            return busy and \
                (time.monotonic() - self.heartbeat) > float(deadline_s)

    def probe(self, timeout_s: float = 5.0) -> bool:
        """Breaker half-open liveness probe: push a no-op request through
        the REAL dispatch queue and wait for the dispatcher to answer it.
        True = the dispatch thread is alive and draining (the breaker may
        close); False = dead, wedged, or closed (stay open)."""
        if self.failed or not self._thread.is_alive():
            return False
        model = next(iter(self._queues), None)
        if model is None:
            return False
        req = PendingRequest(model, "", rid="probe", probe=True)
        with self._cond:
            if self._stop or self.failed:
                return False
            self._queues[model].append(req)
            self._cond.notify()
        try:
            req.wait(timeout_s)
            return True
        except ServingError:
            return False

    def health(self) -> Dict[str, object]:
        """The ``/healthz`` body: readiness (warmed AND not failed),
        loaded models, per-model queue depth vs cap, and each model's
        registry version — what a prober needs to see backpressure and
        rollout state at a glance."""
        ready = bool(self.ready) and not self.failed
        return {
            "status": "ok" if ready else "unavailable",
            "ready": ready,
            "models": self.registry.names(),
            "buckets": self.buckets,
            "queue": {name: {"depth": depth, "cap": self.queue_depth}
                      for name, depth in self.queue_depths().items()},
            "versions": {name: self.registry.version(name)
                         for name in self.registry.names()},
        }

    # -- observability / shutdown --------------------------------------------
    def stats(self, identity: Optional[Dict[str, str]] = None
              ) -> Dict[str, dict]:
        """Per-model serving stats; ``identity`` (process/replica — the
        frontend's scrape identity) rides into every row so N workers'
        stats stay distinguishable after fleet aggregation."""
        return serving_stats(self.counters, self.latency, identity=identity)

    def queue_depths(self) -> Dict[str, int]:
        """Per-model pending-queue depth — the ``/metrics`` gauges."""
        with self._cond:
            return {name: len(q) for name, q in self._queues.items()}

    def _blackbox_inflight(self) -> List[Dict[str, object]]:
        """The forensics bundle's in-flight table: every request this
        replica holds — popped-but-unscored first, then queued — with
        rid, tenant and queue age (capped: a flooded replica's bundle
        stays readable)."""
        now = time.monotonic()

        def row(req: PendingRequest, state: str) -> Dict[str, object]:
            return {"rid": req.rid, "model": req.model,
                    "tenant": req.tenant, "state": state,
                    "age_ms": round((now - req.enqueued) * 1e3, 1)}

        with self._cond:
            rows = [row(r, "dispatching") for r in self._active]
            for q in self._queues.values():
                rows.extend(row(r, "queued") for r in q)
        return rows[:512]

    def close(self) -> None:
        """Flush every pending request, then stop the dispatcher.  A
        dead/wedged dispatcher cannot flush — its leftovers fail typed
        (:class:`ReplicaDownError`) instead of hanging their callers."""
        with self._cond:
            if self._stop:
                return
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=60.0)
        if self.fail_pending("batcher closed with a dead dispatcher"):
            self.failed = True
        blackbox.unregister_provider(self._bb_name)

    def __enter__(self) -> "BucketedMicrobatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
