"""Bucketed microbatcher — the scoring plane's shape-discipline core.

Concurrent requests for one model are folded into padded power-of-two batch
buckets (``serve.bucket.sizes``), every (model, bucket) shape is compiled at
startup (``serve.warmup.on.start``), and steady-state serving therefore
NEVER recompiles — the compiler-first caching discipline of
"Compiler-First State Space Duality and Portable O(1) Autoregressive
Caching for Inference" (PAPERS.md) applied to this framework's classical
models.  The batcher diffs each entry's ``compile_keys`` after every batch
and publishes a ``recompiles`` counter so the invariant is *measured*, not
assumed (benchmarks/serving_qps.py asserts it is zero).

Latency/throughput policy:

- a batch dispatches as soon as a full ``max(bucket)`` is waiting, or when
  the OLDEST pending request ages past ``serve.flush.deadline.ms`` — the
  max-latency flush that keeps a lone request from waiting for company;
- each model's pending queue is bounded by ``serve.queue.depth``; a submit
  against a full queue is rejected with a typed :class:`ShedError` (the
  ``max.spout.pending`` analog — load is shed at the door, not absorbed
  until everything is slow);
- a request that ages past ``serve.request.timeout.ms`` before a batch
  picks it up fails with :class:`RequestTimeout`.

One dispatcher thread owns every device call: the accelerator serializes
batches anyway, and a single submitter keeps the jit cache and the CUDA/TPU
stream free of cross-thread interleaving.  ``submit`` may be called from any
number of frontend threads.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.serving.errors import (
    RequestError,
    RequestTimeout,
    ServingError,
    ShedError,
)
from avenir_tpu.serving.registry import ModelRegistry
from avenir_tpu.telemetry import profile as prof_mod
from avenir_tpu.telemetry import spans as tel
from avenir_tpu.utils.metrics import Counters, LatencyTracker, serving_stats


class PendingRequest:
    """One in-flight request; ``wait`` blocks until scored (or failed).

    ``trace_ctx`` captures the submitter's span (None with tracing off):
    the dispatch thread can't see the submitting context, so the request's
    span is emitted retroactively with this parent — how a serving request
    joins the pipeline trace through the ScoringPlane stage."""

    __slots__ = ("model", "line", "enqueued", "result", "error", "_done",
                 "trace_ctx")

    def __init__(self, model: str, line: str):
        self.model = model
        self.line = line
        self.enqueued = time.monotonic()
        self.result: Optional[str] = None
        self.error: Optional[ServingError] = None
        self._done = threading.Event()
        self.trace_ctx = tel.tracer().current()

    def finish(self, result: Optional[str] = None,
               error: Optional[ServingError] = None) -> None:
        self.result = result
        self.error = error
        self._done.set()

    def wait(self, timeout_s: Optional[float] = None) -> str:
        if not self._done.wait(timeout_s):
            raise RequestTimeout(
                f"no response for {self.model!r} request within "
                f"{timeout_s}s (dispatcher wedged or closed?)")
        if self.error is not None:
            raise self.error
        return self.result  # type: ignore[return-value]


class BucketedMicrobatcher:
    def __init__(self, registry: ModelRegistry,
                 bucket_sizes: Sequence[int] = (1, 2, 4, 8, 16, 32, 64),
                 flush_deadline_ms: float = 5.0,
                 queue_depth: int = 1024,
                 request_timeout_ms: float = 1000.0,
                 warmup: bool = True,
                 counters: Optional[Counters] = None):
        self.registry = registry
        self.buckets = sorted({int(b) for b in bucket_sizes})
        if not self.buckets or self.buckets[0] < 1:
            raise ConfigError(f"invalid serve.bucket.sizes {bucket_sizes!r}")
        self.max_bucket = self.buckets[-1]
        self.flush_deadline_s = float(flush_deadline_ms) / 1e3
        self.queue_depth = max(int(queue_depth), 1)
        self.request_timeout_s = float(request_timeout_ms) / 1e3
        self.counters = counters if counters is not None else Counters()
        self.latency: Dict[str, LatencyTracker] = {
            name: LatencyTracker() for name in registry.names()}
        self._queues: Dict[str, Deque[PendingRequest]] = {
            name: deque() for name in registry.names()}
        # recompile accounting: the shared compile-key diff (telemetry,
        # generalized out of this file in round 10) — warmup primes it,
        # any fresh key afterwards counts under Serving.<name>::recompiles
        self._monitors: Dict[str, tel.CompileKeyMonitor] = {
            name: tel.CompileKeyMonitor(self.counters,
                                        group=f"Serving.{name}", scope=name)
            for name in registry.names()}
        self._cond = threading.Condition()
        self._stop = False
        # readiness (GraftFleet round 15): the /healthz probe's contract —
        # a load balancer must not route to a replica whose (model,
        # bucket) shapes are not compiled yet, or the first requests pay
        # the compile on the hot path.  False until warm() completes; a
        # deployment that disables serve.warmup.on.start stays NOT ready
        # until it calls warm() itself (scoring is never gated — only the
        # readiness signal).
        self.ready = False
        if warmup:
            self.warm()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-dispatch")
        self._thread.start()

    @classmethod
    def from_conf(cls, registry: ModelRegistry,
                  conf: JobConfig) -> "BucketedMicrobatcher":
        return cls(
            registry,
            bucket_sizes=conf.get_int_list("serve.bucket.sizes",
                                           [1, 2, 4, 8, 16, 32, 64]),
            flush_deadline_ms=conf.get_float("serve.flush.deadline.ms", 5.0),
            queue_depth=conf.get_int("serve.queue.depth", 1024),
            request_timeout_ms=conf.get_float("serve.request.timeout.ms",
                                              1000.0),
            warmup=conf.get_bool("serve.warmup.on.start", True),
        )

    # -- warmup / recompile accounting ---------------------------------------
    def warm(self) -> Dict[str, int]:
        """Compile every (model, bucket) shape; shapes seen here never count
        as recompiles later.  Completing marks the batcher ready (the
        /healthz readiness contract)."""
        warmed = self.registry.warmup(self.buckets)
        for name, entry in self.registry.items():
            self._monitors[name].prime(entry.compile_keys)
        self.ready = True
        return warmed

    # -- hot swap (any thread) -----------------------------------------------
    def swap(self, model: str, entry, warm: bool = True) -> int:
        """Zero-downtime model hot-swap with the compile barrier.

        Warms the INCOMING entry's bucket shapes and primes its recompile
        monitor BEFORE publishing it to the registry, so the first
        post-swap batch scores on already-compiled shapes — the
        zero-steady-state-recompiles invariant holds ACROSS a swap, not
        just between swaps.  In-flight batches hold the old entry object
        they resolved at dispatch and finish on the old params; every
        batch dispatched after the publish resolves the new entry.
        Documented exception to the one-dispatcher-thread rule: the
        warmup compiles run on the CALLER's thread concurrently with live
        dispatches (JAX is thread-safe; routing them through the
        dispatcher would stall the same batches behind the same compiles)
        — expect a p99 bump for the duration of a swap either way.
        ``warm=False`` (``serve.swap.warmup``) skips the barrier — the
        first post-swap batch then pays the compile on the hot path and
        the monitor counts it, which is exactly the visibility the
        default exists to avoid.  Returns the model's new version."""
        self.registry.get(model)          # raises UnknownModelError early
        if warm:
            for bucket in self.buckets:
                entry.warmup(int(bucket))
            self._monitors[model].prime(entry.compile_keys)
        version = self.registry.swap(model, entry)
        self.counters.increment(f"Serving.{model}", "swaps")
        tel.tracer().event("model.swap", model=model, version=version,
                           family=entry.family, warmed=bool(warm))
        # swap boundary: the outgoing entry's device buffers should be
        # collectable once in-flight batches drain — a leak across
        # repeated hot-swaps shows up in this gauge before it OOMs
        prof_mod.profiler().sample_device_memory("swap")
        return version

    # -- submission (any thread) ---------------------------------------------
    def submit_nowait(self, model: str, line: str) -> PendingRequest:
        entry = self.registry.get(model)            # raises UnknownModelError
        del entry
        req = PendingRequest(model, line)
        with self._cond:
            if self._stop:
                raise ServingError("batcher is closed")
            queue = self._queues[model]
            if len(queue) >= self.queue_depth:
                self.counters.increment(f"Serving.{model}", "shed")
                raise ShedError(
                    f"{model!r} queue at depth {self.queue_depth} — "
                    f"request shed (backpressure)")
            queue.append(req)
            self._cond.notify()
        return req

    def submit(self, model: str, line: str,
               timeout_s: Optional[float] = None) -> str:
        """Blocking submit: returns the response line or raises the typed
        error.  Default wait bound covers the request timeout plus dispatch
        slack so a wedged dispatcher surfaces as RequestTimeout, not a hang."""
        if timeout_s is None:
            timeout_s = self.request_timeout_s + 30.0
        return self.submit_nowait(model, line).wait(timeout_s)

    # -- dispatch loop (one thread) ------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.max_bucket

    def _ready(self, now: float) -> List[str]:
        out = []
        for name, queue in self._queues.items():
            if not queue:
                continue
            if (len(queue) >= self.max_bucket
                    or now - queue[0].enqueued >= self.flush_deadline_s):
                out.append(name)
        return out

    def _next_wait(self, now: float) -> Optional[float]:
        deadlines = [queue[0].enqueued + self.flush_deadline_s - now
                     for queue in self._queues.values() if queue]
        if not deadlines:
            return None                   # sleep until a submit notifies
        return max(min(deadlines), 0.0)

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._stop and not self._ready(time.monotonic()):
                    self._cond.wait(timeout=self._next_wait(time.monotonic()))
                if self._stop and not any(self._queues.values()):
                    return
                ready = ([name for name, q in self._queues.items() if q]
                         if self._stop else self._ready(time.monotonic()))
                batches: List[Tuple[str, List[PendingRequest]]] = []
                for name in ready:
                    queue = self._queues[name]
                    take = min(len(queue), self.max_bucket)
                    batches.append((name,
                                    [queue.popleft() for _ in range(take)]))
            for name, reqs in batches:
                self._dispatch(name, reqs)

    def _dispatch(self, model: str, reqs: List[PendingRequest]) -> None:
        group = f"Serving.{model}"
        now = time.monotonic()
        live: List[PendingRequest] = []
        for req in reqs:
            if now - req.enqueued > self.request_timeout_s:
                self.counters.increment(group, "timeouts")
                req.finish(error=RequestTimeout(
                    f"request waited past "
                    f"{self.request_timeout_s * 1e3:.0f} ms before dispatch"))
            else:
                live.append(req)
        if not live:
            return
        entry = self.registry.get(model)
        bucket = self._bucket_for(len(live))
        try:
            t0 = time.monotonic()
            outs = entry.score_lines([r.line for r in live], bucket)
            dispatch_s = time.monotonic() - t0
        except Exception as exc:
            # one bad row must not poison its coalesced batch neighbors:
            # re-score each request alone (smallest bucket — warmed, so no
            # recompile) so only the genuinely bad ones fail typed
            if len(live) > 1:
                self._dispatch_isolated(entry, group, live)
                return
            self.counters.increment(group, "errors")
            err = (exc if isinstance(exc, ServingError)
                   else RequestError(f"{type(exc).__name__}: {exc}"))
            live[0].finish(error=err)
            return
        self._finish_scored(entry, group, model, live, outs, bucket,
                            dispatch_s)

    def _dispatch_isolated(self, entry, group: str,
                           reqs: List[PendingRequest]) -> None:
        """Failure-isolation path: score each request of a failed batch
        alone; good rows still succeed, bad rows carry their own error."""
        model = reqs[0].model
        bucket = self._bucket_for(1)
        for req in reqs:
            try:
                outs = entry.score_lines([req.line], bucket)
            except Exception as exc:
                self.counters.increment(group, "errors")
                req.finish(error=(exc if isinstance(exc, ServingError)
                                  else RequestError(
                                      f"{type(exc).__name__}: {exc}")))
                continue
            self._finish_scored(entry, group, model, [req], outs, bucket)

    def _finish_scored(self, entry, group: str, model: str,
                       live: List[PendingRequest], outs: List[str],
                       bucket: int,
                       dispatch_s: Optional[float] = None) -> None:
        # a shape outside the warmed set means this batch paid a compile
        # on the hot path — the invariant violation the counter exposes
        # (the monitor's key feed also registers each key as a GraftProf
        # program under site=<model>)
        self._monitors[model].observe(entry.compile_keys)
        done = time.monotonic()
        tracer = tel.tracer()
        prof = prof_mod.profiler()
        pid = None
        if prof.enabled:
            # the program this batch dispatched: the entry's compile key
            # for this bucket (every entry keys on (bucket, ...))
            pkey = next((k for k in entry.compile_keys
                         if k and k[0] == bucket), (bucket,))
            pid = prof_mod.program_id(model, pkey)
            if dispatch_s is not None:
                prof.sample(pkey, model, dispatch_s)
        tracker = self.latency[model]
        for req, out in zip(live, outs):
            req.finish(result=out)
            wait_s = done - req.enqueued
            tracker.record(wait_s)
            if tracer.enabled:
                attrs = {"model": model, "bucket": bucket}
                if pid is not None:
                    attrs["program"] = pid
                tracer.emit_span("serve.request", wait_s,
                                 parent=req.trace_ctx, attrs=attrs)
        self.counters.increment(group, "requests", len(live))
        self.counters.increment(group, "batches")
        self.counters.increment(group, f"bucket.{bucket}")
        if tracer.enabled:
            tracer.gauge(f"serve.queue.{model}", len(self._queues[model]))

    # -- observability / shutdown --------------------------------------------
    def stats(self, identity: Optional[Dict[str, str]] = None
              ) -> Dict[str, dict]:
        """Per-model serving stats; ``identity`` (process/replica — the
        frontend's scrape identity) rides into every row so N workers'
        stats stay distinguishable after fleet aggregation."""
        return serving_stats(self.counters, self.latency, identity=identity)

    def queue_depths(self) -> Dict[str, int]:
        """Per-model pending-queue depth — the ``/metrics`` gauges."""
        with self._cond:
            return {name: len(q) for name, q in self._queues.items()}

    def close(self) -> None:
        """Flush every pending request, then stop the dispatcher."""
        with self._cond:
            if self._stop:
                return
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=60.0)

    def __enter__(self) -> "BucketedMicrobatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
