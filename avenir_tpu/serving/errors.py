"""Typed serving-plane errors.

Every failure mode a client can observe has its own type, so front ends map
them to distinct transport codes (HTTP status / RESP error tag) and callers
can retry intelligently: shed and timeout are load signals (retry elsewhere
or later), unknown-model and bad-request are permanent for that request.
"""

from __future__ import annotations


class ServingError(RuntimeError):
    """Base of every scoring-plane failure."""

    code = "ERR"


class UnknownModelError(ServingError):
    """Request names a model the registry never loaded."""

    code = "UNKNOWN_MODEL"


class ShedError(ServingError):
    """Queue-depth backpressure: the model's pending queue is full, the
    request was rejected at submit (never enqueued) — the scoring-plane
    analog of Storm's ``max.spout.pending`` refusing new tuples."""

    code = "SHED"


class TenantShedError(ShedError):
    """GraftPool tenant-scoped admission refusal (round 18): the TENANT's
    contract fired — its queue share is full (``quota="queue.depth"``),
    its in-flight quota blocked past the deadline (``quota="deadline"``),
    or its serving door filled (``quota="serve.queue.depth"``) — so only
    THIS tenant's work is refused; every other tenant keeps its share of
    the pool.  Carries the attribution the client needs to back off
    intelligently: ``tenant``, ``quota`` (which contract limit fired) and
    ``retry_after_s`` (the shedding tenant's queue drain estimate — the
    HTTP frontend renders it as a ``Retry-After`` header)."""

    code = "TENANT_SHED"

    def __init__(self, message: str, tenant: str = "", quota: str = "",
                 retry_after_s: float = 0.0):
        super().__init__(message)
        self.tenant = tenant
        self.quota = quota
        self.retry_after_s = retry_after_s


class RequestTimeout(ServingError):
    """The request aged past ``serve.request.timeout.ms`` before a batch
    picked it up (sustained overload past what backpressure absorbs)."""

    code = "TIMEOUT"


class RequestError(ServingError):
    """The request payload itself is unservable (wrong column count,
    unknown sequence symbol, sequence longer than the padded length, ...)."""

    code = "BAD_REQUEST"


class ReplicaDownError(ServingError):
    """The replica holding this request died (injected kill, crashed
    dispatcher, missed heartbeat deadline) before the request scored.
    RETRYABLE by construction: a request only carries this error if its
    score never completed, so the pool may re-enqueue it on a survivor
    without risking a double score (``serving/pool.py`` failover)."""

    code = "REPLICA_DOWN"


class WorkerDownError(ReplicaDownError):
    """GlobalServe (``serving/global_pool.py``): the worker PROCESS
    holding this request died or stopped answering before a response
    landed — a refused/reset connection, or a worker-side 503 whose body
    carries the retryable ``REPLICA_DOWN`` code.  Subclasses
    :class:`ReplicaDownError` so the transport status (503) and the
    retryability contract are inherited: the router only raises this when
    no response arrived (or the worker itself vouched the request never
    scored), so a failover re-send cannot double-score.  ``worker`` names
    the process for client-side triage."""

    code = "WORKER_DOWN"

    def __init__(self, message: str, worker: str = ""):
        super().__init__(message)
        self.worker = worker
