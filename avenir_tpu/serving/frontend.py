"""Transport front ends for the scoring plane.

Two transports, both stdlib-only:

- :class:`ScoreHTTPServer` — a ``http.server`` JSON endpoint
  (``POST /score`` with ``{"model": ..., "rows": [...]}``) plus health and
  stats endpoints.  Typed serving errors map to distinct HTTP statuses so a
  load balancer can tell shed (429) from overload timeout (504) from a bad
  request (400).
- :class:`QueueScoreFrontend` — a RESP-list transport over the same
  push/pop queue surface the RL serving loop uses (``pipeline/resp.py``'s
  ``RedisListQueue``, or the in-proc queue for tests): clients LPUSH
  ``requestId,model,<csv row>`` onto a request list and collect
  ``requestId,<response line>`` (or ``requestId,ERR,<code>,<message>``)
  from a response list — so the reference's own Redis simulators can drive
  the scoring plane exactly like they drive the Storm topology
  (``ReinforcementLearnerTopology``).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Tuple

from avenir_tpu.serving.batcher import BucketedMicrobatcher, PendingRequest
from avenir_tpu.serving.errors import (
    ReplicaDownError,
    RequestError,
    RequestTimeout,
    ServingError,
    ShedError,
    UnknownModelError,
)

_HTTP_STATUS = {
    UnknownModelError: 404,
    ShedError: 429,
    RequestTimeout: 504,
    ReplicaDownError: 503,
    RequestError: 400,
}


def _status_for(err: ServingError) -> int:
    # MRO walk, not an exact-type lookup: subclassed typed errors (e.g.
    # the tenant-scoped TenantShedError) keep their base's transport
    # status — a shed is a 429 whoever shed it
    for klass in type(err).__mro__:
        if klass in _HTTP_STATUS:
            return _HTTP_STATUS[klass]
    return 500


def _error_body(err: ServingError) -> dict:
    """Typed error → JSON body, carrying the FleetServe attribution the
    batcher stamps (which replica shed/timed out this request and how
    long it waited) so a shed storm triages from client logs alone.
    GraftPool (round 18): a tenant-scoped shed additionally names the
    tenant, the contract quota that fired, and the queue drain estimate
    — a 429 is no longer anonymous to the client."""
    body = {"error": err.code, "message": str(err)}
    replica = getattr(err, "replica", None)
    if replica:
        body["replica"] = replica
    wait_ms = getattr(err, "queue_wait_ms", None)
    if wait_ms is not None:
        body["queue_wait_ms"] = wait_ms
    tenant = getattr(err, "tenant", None)
    if tenant:
        body["tenant"] = tenant
    quota = getattr(err, "quota", None)
    if quota:
        body["quota"] = quota
    retry_after = getattr(err, "retry_after_s", None)
    if retry_after:
        body["retry_after_ms"] = round(float(retry_after) * 1e3, 1)
    return body


def _retry_after_header(err: ServingError) -> dict:
    """``Retry-After`` (integer seconds, HTTP semantics — rounded UP so
    an honest client never re-arrives early) for errors carrying a queue
    drain estimate; ``{}`` otherwise."""
    retry_after = getattr(err, "retry_after_s", None)
    if not retry_after:
        return {}
    return {"Retry-After": str(max(int(-(-float(retry_after) // 1)), 1))}


class ScoreHTTPServer:
    """Threaded HTTP front end over a :class:`BucketedMicrobatcher` — or,
    FleetServe (round 17), a :class:`~avenir_tpu.serving.pool.ReplicaPool`
    (same duck-typed surface: submit/queue_depths/counters/latency/health).

    Concurrent POSTs are the microbatching win: each handler thread submits
    its rows and blocks, and the dispatcher folds every model's concurrent
    rows into one padded bucket.  Port 0 binds an ephemeral port (tests);
    ``serve.http.port`` configures a fixed one (docs/deployment.md).
    """

    def __init__(self, batcher: BucketedMicrobatcher,
                 host: str = "127.0.0.1", port: int = 0,
                 slo=None, identity=None):
        from avenir_tpu.telemetry import spans as _tel
        from avenir_tpu.telemetry.export import fleet_identity

        self.batcher = batcher
        self.started = time.monotonic()
        # GraftFleet (round 15): the scrape identity (process/replica
        # labels on every /metrics sample and /stats row) and an optional
        # SLO evaluator (telemetry/slo.py) rendering avenir_slo_burn_rate
        # gauges per scrape.  Default identity reuses the tracer's writer
        # suffix so scrape labels and journal shard names agree.
        self.identity = identity if identity is not None else fleet_identity(
            replica=_tel.tracer().writer_suffix or None,
            tenant=getattr(batcher, "tenant", "") or None)
        self.slo = slo
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):      # no per-request stderr spam
                pass

            def _send(self, status: int, payload: dict,
                      headers: Optional[dict] = None) -> None:
                self._send_text(status, json.dumps(payload),
                                "application/json", headers=headers)

            def _send_text(self, status: int, text: str,
                           content_type: str,
                           headers: Optional[dict] = None) -> None:
                body = text.encode()
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                for name, value in (headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/metrics":
                    # Prometheus text exposition of the same counters the
                    # journal snapshots and /stats reports as JSON —
                    # scrape-ready (telemetry/export.py); under
                    # profile.on the GraftProf device-memory gauges
                    # (avenir_device_bytes) ride the same page
                    from avenir_tpu.telemetry import profile as _profile
                    from avenir_tpu.telemetry.export import prometheus_text

                    depths = outer.batcher.queue_depths()
                    gauges = {f"serve.queue.{name}": float(depth)
                              for name, depth in depths.items()}
                    gauges["uptime.sec"] = time.monotonic() - outer.started
                    # FleetServe: a ReplicaPool adds its readiness and
                    # per-replica queue gauges to the same scrape page
                    pool_gauges = getattr(outer.batcher, "gauges", None)
                    if callable(pool_gauges):
                        gauges.update(pool_gauges())
                    body = prometheus_text(
                        counters=outer.batcher.counters,
                        latency=outer.batcher.latency,
                        gauges=gauges,
                        device_bytes=_profile.profiler().gauges(),
                        labels=outer.identity)
                    if outer.slo is not None:
                        # scrape-time SLO evaluation: burn-rate gauges on
                        # the same page, slo.violation journaled on each
                        # rule's transition into violation
                        rows = outer.slo.evaluate_live(
                            outer.batcher.counters, outer.batcher.latency,
                            depths, gauges=gauges)
                        slo_lines = []
                        outer.slo.render_prometheus(rows, slo_lines,
                                                    labels=outer.identity)
                        body += "\n".join(slo_lines) + "\n"
                    self._send_text(
                        200, body,
                        "text/plain; version=0.0.4; charset=utf-8")
                elif self.path == "/healthz":
                    # readiness probe (round 15): 503 until every model is
                    # loaded AND its (model, bucket) shapes are warmed —
                    # what a load balancer in front of a replica pool
                    # needs before routing traffic here.  The body comes
                    # from the serving plane's own ``health()``: queue
                    # depth vs cap and per-model versions always; behind
                    # a ReplicaPool (FleetServe, round 17) it's the
                    # AGGREGATE — green iff ≥ 1 replica is ready — plus
                    # one row per replica (ready, breaker state, queue
                    # depth vs cap, registry version), so a rolling swap
                    # or a tripped breaker is visible from one curl.
                    body = outer.batcher.health()
                    body["uptime_sec"] = round(
                        time.monotonic() - outer.started, 3)
                    ready = bool(body.get("ready"))
                    self._send(200 if ready else 503, body)
                elif self.path == "/stats":
                    self._send(200,
                               outer.batcher.stats(identity=outer.identity))
                else:
                    self._send(404, {"error": "NOT_FOUND",
                                     "message": self.path})

            def do_POST(self):
                if self.path == "/swap":
                    self._do_swap()
                    return
                if self.path != "/score":
                    self._send(404, {"error": "NOT_FOUND",
                                     "message": self.path})
                    return
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    model = req["model"]
                    rows = req["rows"]
                    if isinstance(rows, str):
                        rows = [rows]
                    # GlobalServe extras: a router upstream threads its
                    # attempt-qualified rids (journal accounting across
                    # the hop) and the submitter's tenant label (the
                    # worker's DRR arbitration + span attribution)
                    rids = req.get("rids")
                    tenant = req.get("tenant")
                    if rids is not None and (
                            not isinstance(rids, list)
                            or len(rids) != len(rows)):
                        raise ValueError(
                            f"rids must be a list of len(rows)="
                            f"{len(rows)} request ids")
                except (ValueError, KeyError, TypeError) as exc:
                    self._send(400, {
                        "error": "BAD_REQUEST",
                        "message": f"body must be JSON "
                                   f'{{"model": ..., "rows": [...]}}: {exc}'})
                    return
                try:
                    results = outer.score_rows(model, rows, rids=rids,
                                               tenant=tenant)
                except ServingError as err:
                    self._send(_status_for(err), _error_body(err),
                               headers=_retry_after_header(err))
                    return
                self._send(200, {"model": model, "results": results})

            def _do_swap(self):
                # GlobalServe rolling fleet swap lands here one worker at
                # a time: build the incoming entry from the posted props
                # and run the batcher/pool swap barrier
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    model = req["model"]
                    props = req.get("props") or {}
                    warm = bool(req.get("warm", True))
                    if not isinstance(props, dict):
                        raise ValueError("props must be an object")
                except (ValueError, KeyError, TypeError) as exc:
                    self._send(400, {
                        "error": "BAD_REQUEST",
                        "message": f"body must be JSON "
                                   f'{{"model": ..., "props": {{...}}}}: '
                                   f"{exc}"})
                    return
                try:
                    doc = outer.swap_model(model, props, warm=warm)
                except ServingError as err:
                    self._send(_status_for(err), _error_body(err),
                               headers=_retry_after_header(err))
                    return
                self._send(200, doc)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    def score_rows(self, model: str, rows: List[str],
                   rids: Optional[List[str]] = None,
                   tenant: Optional[str] = None) -> List[str]:
        """Submit all rows (they microbatch together), wait for all.  The
        first typed error aborts the call; rows already queued behind it
        still score and are discarded — shed/timeout accounting stays
        truthful either way.  ``rids`` (GlobalServe) pins each row's
        request id (else the plane assigns its own); ``tenant`` scopes the
        submits under that ambient tenant label so worker-local DRR
        arbitration and span attribution see the ORIGINAL submitter's
        tenant, not the router process."""
        import contextlib

        from avenir_tpu.telemetry import spans as _tel

        if rids is not None and len(rids) != len(rows):
            raise RequestError(
                f"rids must pair 1:1 with rows ({len(rids)} != {len(rows)})")
        scope = (_tel.label_scope(tenant=tenant) if tenant
                 else contextlib.nullcontext())
        with scope:
            pending: List[PendingRequest] = [
                self.batcher.submit_nowait(
                    model, row, rid=rids[i] if rids else None)
                for i, row in enumerate(rows)]
        return [p.wait(self.batcher.request_timeout_s + 30.0)
                for p in pending]

    def swap_model(self, model: str, props: dict,
                   warm: bool = True) -> dict:
        """``POST /swap`` body: build the incoming entry from ``props``
        (the posted keys are a self-contained job conf for the model's
        family loader) and hand it to the serving plane's swap barrier —
        a plain batcher warms-then-publishes, a ReplicaPool rolls replica
        by replica.  Returns the new version (for a pool: the SLOWEST
        replica's, i.e. the rollout is done when ``version`` moved)."""
        from avenir_tpu.core.config import ConfigError, JobConfig
        from avenir_tpu.serving.registry import FAMILIES

        roll = getattr(self.batcher, "swap_fleet", None)
        if callable(roll):
            # a GlobalRouter upstream: /swap IS the rolling fleet swap —
            # the router re-posts these props to each worker's /swap one
            # at a time, holding the ready floor between hops
            return roll(model, dict(props), warm=warm)
        loader = FAMILIES.get(model)
        if loader is None:
            raise UnknownModelError(
                f"unknown serving family {model!r} "
                f"(known: {sorted(FAMILIES)})")
        try:
            entry = loader.from_conf(JobConfig(dict(props)))
        except ConfigError as exc:
            raise RequestError(
                f"swap props for {model!r} rejected: {exc}") from exc
        result = self.batcher.swap(model, entry, warm=warm)
        if isinstance(result, dict):
            version = min(result.values()) if result else None
            return {"model": model, "version": version,
                    "versions": result}
        return {"model": model, "version": result}

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    def start(self) -> "ScoreHTTPServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="serve-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def __enter__(self) -> "ScoreHTTPServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class QueueScoreFrontend:
    """RESP-list (or in-proc queue) front end.

    ``requests``/``responses`` are any objects with the ``push``/``drain``
    queue surface (``pipeline/resp.py::RedisListQueue``,
    ``pipeline/streaming.py::InProcQueue``).  Message contract:

    - request:  ``<requestId>,<model>,<csv row>``  (split on the first two
      delimiters only — the payload keeps its own delimiters)
    - response: ``<requestId>,<response line>`` on success,
      ``<requestId>,ERR,<code>,<message>`` on a typed failure.
    """

    def __init__(self, batcher: BucketedMicrobatcher, requests, responses,
                 delim: str = ","):
        self.batcher = batcher
        self.requests = requests
        self.responses = responses
        self.delim = delim

    def _fail(self, rid: str, err: ServingError) -> None:
        msg = str(err).replace("\n", " ").replace(self.delim, ";")
        self.responses.push(
            self.delim.join([rid, "ERR", err.code, msg]))

    def poll_once(self) -> int:
        """Drain the request list, submit everything (so concurrent clients
        microbatch), then push responses; returns messages consumed."""
        msgs = self.requests.drain()
        pending: List[Tuple[str, PendingRequest]] = []
        for msg in msgs:
            parts = msg.split(self.delim, 2)
            if len(parts) != 3:
                self._fail(msg, RequestError(
                    "request must be 'requestId,model,<csv row>'"))
                continue
            rid, model, payload = parts
            try:
                pending.append((rid, self.batcher.submit_nowait(model,
                                                                payload)))
            except ServingError as err:
                self._fail(rid, err)
        for rid, req in pending:
            try:
                out = req.wait(self.batcher.request_timeout_s + 30.0)
            except ServingError as err:
                self._fail(rid, err)
                continue
            self.responses.push(f"{rid}{self.delim}{out}")
        return len(msgs)

    def run(self, max_messages: Optional[int] = None,
            idle_sleep_s: float = 0.005,
            idle_limit_s: Optional[float] = None) -> int:
        """Poll until ``max_messages`` are served, or the request list stays
        empty for ``idle_limit_s`` (None = poll forever)."""
        served = 0
        idle_since = time.monotonic()
        while max_messages is None or served < max_messages:
            n = self.poll_once()
            if n:
                served += n
                idle_since = time.monotonic()
                continue
            if idle_limit_s is not None and \
                    time.monotonic() - idle_since >= idle_limit_s:
                break
            time.sleep(idle_sleep_s)
        return served


def redis_score_frontend(batcher: BucketedMicrobatcher,
                         host: str = "localhost", port: int = 6379,
                         db: int = 0,
                         request_queue: str = "scoreRequestQueue",
                         response_queue: str = "scoreResponseQueue",
                         ) -> QueueScoreFrontend:
    """The Redis wiring of :class:`QueueScoreFrontend` over the in-tree
    stdlib RESP client — the scoring-plane twin of the RL loop's
    RedisEventSource/RedisActionWriter transports."""
    from avenir_tpu.pipeline.resp import RedisListQueue, RespClient

    client = RespClient(host, port, db=db)
    return QueueScoreFrontend(
        batcher,
        RedisListQueue(request_queue, client=client),
        RedisListQueue(response_queue,
                       client=RespClient(host, port, db=db)))
