"""GlobalServe — one logical serving frontend over a launched worker fleet.

FleetServe (round 17) made N replicas survive inside ONE process;
CrossGraft (round 16) launched N processes but only for the scan plane.
This module composes them: every worker process runs a full serving plane
(``python -m avenir_tpu.serving`` — a :class:`ReplicaPool` when ``pool.*``
is armed), and a :class:`GlobalRouter` fronts the fleet over the existing
HTTP transport, so the death of a whole OS process costs shed requests,
never an outage (the pjit/TPUv4 fleet-scoping discipline, arxiv
2204.06514, lifted to process granularity):

- **health-gated least-load routing** — each worker's ``/healthz`` is the
  routing feed (polled by the monitor thread): traffic goes to the
  routable worker with the fewest in-flight + queued requests;
- **worker-level circuit breaker** — ``fleet.pool.breaker.failures``
  consecutive transport failures open a worker's breaker; after
  ``fleet.pool.breaker.halfopen.ms`` a healthz probe decides closed vs
  open — the round-17 replica breaker, one level up;
- **process-death failover** — a request in flight to a dying worker
  fails with the retryable
  :class:`~avenir_tpu.serving.errors.WorkerDownError` (connection reset,
  or a worker-side 503 vouching the request never scored) and is re-sent
  to a survivor under a fresh attempt-qualified rid (``g<n>.a<k>``), at
  most ``fleet.pool.failover.retries`` times — never silent loss, and
  never a double score (a 2xx response is the ONLY delivery; each
  attempt's rid is distinct, so the merged journal proves exactly one
  scored span per delivered request — ``benchmarks/serving_soak.py``);
- **rolling fleet-wide hot-swap** — :meth:`GlobalRouter.swap_fleet` rolls
  the round-11 warmup barrier one WORKER at a time through each worker's
  ``POST /swap``, polling fleet readiness between hops so ready capacity
  never drops below ``fleet.pool.swap.floor``;
- **process-granularity autoscaling** — the round-17 burn-rate grammar
  under a new family (``fleet.pool.autoscale.*``): the router spawns or
  retires whole worker processes through its launcher-provided spawner.

Every transition journals golden-schema'd events — ``fleet.pool.worker.
down`` / ``fleet.pool.worker.up`` / ``fleet.pool.scale`` /
``fleet.pool.failover`` / ``fleet.pool.swap`` — into the ROUTER's journal
shard; worker shards carry the per-request ``serve.request`` spans, and
``telemetry merge`` folds them into the one fleet view the accounting and
the per-tenant ``telemetry slo --label tenant=<id>`` gates read
(docs/runbooks/worker_loss_triage.md).

The router duck-types the batcher's frontend surface (``submit_nowait`` /
``submit`` / ``queue_depths`` / ``counters`` / ``latency`` / ``stats`` /
``health`` / ``gauges``), so
:class:`~avenir_tpu.serving.frontend.ScoreHTTPServer` serves a fleet
unchanged — ``/healthz`` aggregates per-worker readiness rows and
``/metrics`` splices a ``worker`` label via ``fleet_identity``.

Tenancy stays GLOBAL: the router holds the conf's FULL ``tenant.*``
contracts and enforces each tenant's fleet-wide in-flight quota at its
door, while the launcher hands every worker a 1/N split of the same
contracts (:func:`~avenir_tpu.tenancy.contract.split_contracts`) so
worker-local DRR arbitration sums back to the declared global shares.
"""

from __future__ import annotations

import itertools
import json
import logging
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set

from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.serving.errors import (
    RequestError,
    RequestTimeout,
    ServingError,
    ShedError,
    TenantShedError,
    UnknownModelError,
    WorkerDownError,
)
from avenir_tpu.telemetry import blackbox
from avenir_tpu.telemetry import spans as tel
from avenir_tpu.utils.metrics import Counters, LatencyTracker, serving_stats

log = logging.getLogger(__name__)

# breaker states — same three-state circuit as serving/pool.py, one level up
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class WorkerClient:
    """Blocking stdlib HTTP client for ONE worker's serving plane.

    Wraps ``http.client`` (no third-party deps — the same constraint the
    RESP transport honors) and maps the worker's typed error bodies back
    to the SAME typed exceptions the in-process batcher raises, so the
    router's failure handling is transport-agnostic: a refused/reset
    connection or a worker-side 503 ``REPLICA_DOWN`` becomes the
    retryable :class:`WorkerDownError`; shed/timeout/unknown-model/bad-
    request stay typed and non-retryable."""

    def __init__(self, host: str, port: int, name: str = "",
                 timeout_s: float = 30.0):
        self.host = host
        self.port = int(port)
        self.name = name or f"{host}:{port}"
        self.timeout_s = float(timeout_s)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _request(self, method: str, path: str, payload: Optional[dict],
                 timeout_s: Optional[float],
                 ok_status: Sequence[int] = ()) -> dict:
        import http.client

        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=timeout_s if timeout_s is not None else self.timeout_s)
        try:
            body = json.dumps(payload).encode() if payload is not None \
                else None
            headers = {"Content-Type": "application/json"} if body else {}
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
            except (ConnectionError, socket.timeout,
                    http.client.HTTPException, OSError) as exc:
                # transport failure: no response landed, so the request
                # (if any) was NOT delivered — retryable by construction
                raise WorkerDownError(
                    f"worker {self.name!r} unreachable at {self.url}: "
                    f"{type(exc).__name__}: {exc}",
                    worker=self.name) from exc
            try:
                doc = json.loads(raw) if raw else {}
            except ValueError:
                doc = {}
            if resp.status < 400 or resp.status in ok_status:
                return doc
            raise self._typed_error(resp.status, doc)
        finally:
            conn.close()

    def _typed_error(self, status: int, doc: dict) -> ServingError:
        """The worker's JSON error body, re-raised as the batcher's own
        typed exception so ``PoolRequest``-style retry logic and the
        frontend's status mapping work unchanged across the hop."""
        code = doc.get("error", "")
        message = doc.get("message", f"HTTP {status} from {self.name}")
        if status == 503 or code in ("REPLICA_DOWN", "WORKER_DOWN"):
            # the worker itself vouches the request never scored (the
            # ReplicaDownError contract) — safe to fail over
            return WorkerDownError(
                f"worker {self.name!r}: {message}", worker=self.name)
        if status == 404 or code == "UNKNOWN_MODEL":
            return UnknownModelError(message)
        if status == 429 or code in ("SHED", "TENANT_SHED"):
            if doc.get("tenant"):
                return TenantShedError(
                    message, tenant=doc["tenant"],
                    quota=doc.get("quota", ""),
                    retry_after_s=float(doc.get("retry_after_ms", 0.0))
                    / 1e3)
            return ShedError(message)
        if status == 504 or code == "TIMEOUT":
            return RequestTimeout(message)
        if status == 400 or code == "BAD_REQUEST":
            return RequestError(message)
        return ServingError(message)

    def get(self, path: str, timeout_s: Optional[float] = None) -> dict:
        return self._request("GET", path, None, timeout_s)

    def healthz(self, timeout_s: Optional[float] = None) -> dict:
        """The worker's ``/healthz`` body (the routing feed).  A 503 is a
        VALID answer — up but not ready (warming, mid-swap) — so it
        returns the body instead of raising: only TRANSPORT failures
        raise WorkerDownError and count toward the breaker."""
        try:
            return self._request("GET", "/healthz", None, timeout_s,
                                 ok_status=(503,))
        except WorkerDownError:
            raise
        except ServingError:                   # pragma: no cover - defensive
            return {"ready": False}

    def score(self, model: str, rows: Sequence[str],
              rids: Optional[Sequence[str]] = None,
              tenant: Optional[str] = None,
              timeout_s: Optional[float] = None) -> List[str]:
        payload: Dict[str, object] = {"model": model, "rows": list(rows)}
        if rids:
            payload["rids"] = list(rids)
        if tenant:
            payload["tenant"] = tenant
        doc = self._request("POST", "/score", payload, timeout_s)
        return list(doc.get("results", []))

    def swap(self, model: str, props: Dict[str, str],
             warm: bool = True, timeout_s: Optional[float] = None) -> dict:
        return self._request("POST", "/swap",
                             {"model": model, "props": dict(props),
                              "warm": bool(warm)}, timeout_s)


class GlobalWorker:
    """One fleet member: a worker process's client + routing/breaker
    state.  ``proc`` is the launcher's process handle when the router owns
    the process (None for externally managed workers — tests front
    in-process HTTP servers)."""

    __slots__ = ("name", "client", "proc", "breaker", "consecutive",
                 "opened_at", "active", "dead", "inflight", "health")

    def __init__(self, name: str, client: WorkerClient, proc=None):
        self.name = name
        self.client = client
        self.proc = proc
        self.breaker = CLOSED
        self.consecutive = 0          # consecutive transport failures
        self.opened_at = 0.0
        self.active = True            # False once retired or dead
        self.dead = False             # process died — never comes back
        self.inflight = 0             # router-side in-flight request count
        self.health: Optional[dict] = None    # last /healthz body

    @property
    def routable(self) -> bool:
        """Health gate: traffic goes only to an active worker whose
        breaker is closed and whose last ``/healthz`` poll was green."""
        return (self.active and not self.dead and self.breaker == CLOSED
                and bool(self.health) and bool(self.health.get("ready")))

    def depth(self) -> int:
        """Routing load: router-side in-flight plus the worker's own
        queued depth from the last health poll."""
        queued = 0
        if self.health:
            for row in (self.health.get("queue") or {}).values():
                queued += int(row.get("depth", 0))
        return self.inflight + queued


class GlobalRequest:
    """The router's pending handle — same wait/finish contract as the
    batcher's :class:`PendingRequest`, with the failover loop running on
    the router's client threads instead of the caller's."""

    __slots__ = ("model", "line", "rid", "tenant", "result", "error",
                 "_done", "worker", "tried", "attempts")

    def __init__(self, model: str, line: str, rid: str,
                 tenant: Optional[str] = None):
        self.model = model
        self.line = line
        self.rid = rid
        self.tenant = tenant
        self.result: Optional[str] = None
        self.error: Optional[ServingError] = None
        self._done = threading.Event()
        self.worker = ""
        self.tried: Set[str] = set()
        self.attempts = 0             # failover re-sends so far

    def finish(self, result: Optional[str] = None,
               error: Optional[ServingError] = None) -> None:
        if self._done.is_set():       # idempotent — a done request is done
            return
        self.result = result
        self.error = error
        self._done.set()

    def wait(self, timeout_s: Optional[float] = None) -> str:
        if not self._done.wait(timeout_s):
            raise RequestTimeout(
                f"no fleet response for {self.model!r} request {self.rid} "
                f"within {timeout_s}s")
        if self.error is not None:
            raise self.error
        return self.result            # type: ignore[return-value]


class GlobalRouter:
    """N worker processes behind one routing door — the process-level
    twin of :class:`~avenir_tpu.serving.pool.ReplicaPool`.

    ``spawner()`` (launcher integration — :class:`WorkerSpawner`) builds
    and waits out one NEW worker process; the router calls it to replace
    dead workers and to grow under burn/queue pressure, and retires
    processes via SIGTERM when cold.  Without a spawner the fleet is
    fixed-size (tests front in-process servers)."""

    def __init__(self, workers: Sequence[GlobalWorker] = (), *,
                 spawner: Optional[Callable[[], GlobalWorker]] = None,
                 breaker_failures: int = 3,
                 heartbeat_ms: float = 2000.0,
                 halfopen_ms: float = 1000.0,
                 failover_retries: int = 1,
                 monitor_interval_ms: Optional[float] = None,
                 request_timeout_ms: float = 20000.0,
                 client_threads: int = 8,
                 autoscale: bool = False,
                 autoscale_min: int = 1,
                 autoscale_max: Optional[int] = None,
                 up_burn: float = 1.0,
                 down_burn: float = 0.25,
                 queue_frac: float = 0.5,
                 autoscale_interval_s: float = 5.0,
                 swap_floor: int = 1,
                 slo=None,
                 contracts: Optional[Dict[str, object]] = None,
                 counters: Optional[Counters] = None,
                 latency: Optional[Dict[str, LatencyTracker]] = None,
                 start_monitor: bool = True):
        from concurrent.futures import ThreadPoolExecutor

        self.spawner = spawner
        self.breaker_failures = max(int(breaker_failures), 1)
        self.heartbeat_s = float(heartbeat_ms) / 1e3
        self.halfopen_s = float(halfopen_ms) / 1e3
        self.failover_retries = max(int(failover_retries), 0)
        self.request_timeout_s = float(request_timeout_ms) / 1e3
        self.autoscale = bool(autoscale)
        self.autoscale_min = max(int(autoscale_min), 1)
        self.autoscale_max = int(autoscale_max) if autoscale_max else \
            max(len(workers), self.autoscale_min)
        self.up_burn = float(up_burn)
        self.down_burn = float(down_burn)
        self.queue_frac = float(queue_frac)
        self.autoscale_interval_s = float(autoscale_interval_s)
        self.swap_floor = max(int(swap_floor), 0)
        self.slo = slo
        # GLOBAL tenancy: the conf's FULL contracts enforced at the
        # router door (workers run 1/N splits — split_contracts)
        self.contracts = dict(contracts or {})
        self.counters = counters if counters is not None else Counters()
        self.latency: Dict[str, LatencyTracker] = (
            latency if latency is not None else {})
        self._lock = threading.Lock()
        self._workers: Dict[str, GlobalWorker] = {}
        self._tenant_inflight: Dict[str, int] = {}
        self._rid = itertools.count(1)
        self._last_scale = time.monotonic()
        self._spawning = False
        # model → the (props, warm) of the last fleet swap: a worker
        # spawned AFTER a rolling swap must come up on the swapped
        # version, not the conf's original artifact (ReplicaPool parity)
        self._swapped: Dict[str, tuple] = {}
        for w in workers:
            self._workers[w.name] = w
        # the client pool: each request's send/failover loop runs here so
        # concurrent single-row POSTs microbatch inside the workers
        self._pool = ThreadPoolExecutor(
            max_workers=max(int(client_threads), 1),
            thread_name_prefix="fleet-client")
        self._stop_evt = threading.Event()
        self.monitor_interval_s = (
            float(monitor_interval_ms) / 1e3 if monitor_interval_ms
            else max(self.heartbeat_s / 4.0, 0.05))
        # prime the routing feed so requests submitted before the first
        # monitor tick still see ready workers
        for w in list(self._workers.values()):
            self._poll_worker(w)
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True, name="fleet-monitor")
        # GraftBox: the router's forensics bundle carries the fleet
        # routing/breaker table (which workers were routable at death)
        self._bb_name = f"router-{id(self):x}"
        blackbox.register_provider(self._bb_name, self._blackbox_state)
        if start_monitor:
            self._monitor.start()

    # -- construction --------------------------------------------------------
    @classmethod
    def from_conf(cls, conf: JobConfig,
                  workers: Sequence[GlobalWorker] = (),
                  spawner: Optional[Callable[[], GlobalWorker]] = None,
                  **overrides) -> "GlobalRouter":
        """Build the router from ``fleet.pool.*`` keys — the round-17
        ``pool.autoscale.*`` grammar lifted to process granularity (see
        docs/jobs.md "GlobalServe").  ``overrides`` win over conf keys
        (tests pin e.g. ``start_monitor=False``)."""
        from avenir_tpu.telemetry.slo import SloEvaluator
        from avenir_tpu.tenancy.contract import contracts_from_conf

        kwargs = dict(
            spawner=spawner,
            breaker_failures=conf.get_int("fleet.pool.breaker.failures", 3),
            heartbeat_ms=conf.get_float("fleet.pool.heartbeat.ms", 2000.0),
            halfopen_ms=conf.get_float(
                "fleet.pool.breaker.halfopen.ms", 1000.0),
            failover_retries=conf.get_int("fleet.pool.failover.retries", 1),
            monitor_interval_ms=conf.get_float(
                "fleet.pool.monitor.interval.ms"),
            request_timeout_ms=conf.get_float("serve.request.timeout.ms",
                                              1000.0),
            client_threads=conf.get_int("fleet.pool.client.threads", 8),
            autoscale=conf.get_bool("fleet.pool.autoscale.on", False),
            autoscale_min=conf.get_int("fleet.pool.autoscale.min", 1),
            autoscale_max=conf.get_int("fleet.pool.autoscale.max", 0)
            or None,
            up_burn=conf.get_float("fleet.pool.autoscale.up.burn", 1.0),
            down_burn=conf.get_float("fleet.pool.autoscale.down.burn", 0.25),
            queue_frac=conf.get_float("fleet.pool.autoscale.queue.frac",
                                      0.5),
            autoscale_interval_s=conf.get_float(
                "fleet.pool.autoscale.interval.sec", 5.0),
            swap_floor=conf.get_int("fleet.pool.swap.floor", 1),
            slo=SloEvaluator.from_conf(conf),
            contracts=contracts_from_conf(conf),
        )
        kwargs.update(overrides)
        return cls(workers, **kwargs)

    # -- submission (any thread) ---------------------------------------------
    def submit_nowait(self, model: str, line: str,
                      rid: Optional[str] = None) -> GlobalRequest:
        tenant = tel.current_label("tenant")
        self._tenant_admit(model, tenant)
        req = GlobalRequest(model, line, rid=rid or f"g{next(self._rid)}",
                            tenant=tenant)
        with self._lock:
            any_ready = any(w.routable for w in self._workers.values())
        if not any_ready:
            self._tenant_release(tenant)
            self.counters.increment(f"Serving.{model}", "shed")
            self.counters.increment("Fleet", "no.ready")
            err = ShedError(
                f"no ready worker for {model!r} (request {req.rid}) — "
                f"shed at the fleet door")
            if tenant:
                err.tenant = tenant
            raise err
        self.counters.increment("Fleet", "submitted")
        self._pool.submit(self._run, req)
        return req

    def submit(self, model: str, line: str,
               timeout_s: Optional[float] = None) -> str:
        if timeout_s is None:
            timeout_s = self.request_timeout_s + 30.0
        return self.submit_nowait(model, line).wait(timeout_s)

    def _tenant_admit(self, model: str, tenant: Optional[str]) -> None:
        """Fleet-wide quota admission: the router holds the conf's FULL
        contracts, so a tenant's global in-flight ceiling is enforced at
        ONE door even though each worker only sees its 1/N split."""
        if not tenant:
            return
        contract = self.contracts.get(tenant)
        quota = getattr(contract, "max_inflight", 0) if contract else 0
        with self._lock:
            inflight = self._tenant_inflight.get(tenant, 0)
            if quota and inflight >= quota:
                self.counters.increment(f"Serving.{model}", "shed")
                self.counters.increment(f"Tenant.{tenant}", "shed")
                shed = TenantShedError(
                    f"tenant {tenant!r} at its fleet-wide in-flight quota "
                    f"({quota}) — request shed at the router door",
                    tenant=tenant, quota="fleet.max.inflight",
                    retry_after_s=0.05)
            else:
                self._tenant_inflight[tenant] = inflight + 1
                return
        tel.tracer().event("tenant.shed", tenant=tenant,
                           quota="fleet.max.inflight", waiting=0,
                           inflight=inflight,
                           retry_after_ms=round(shed.retry_after_s * 1e3, 1))
        raise shed

    def _tenant_release(self, tenant: Optional[str]) -> None:
        if not tenant:
            return
        with self._lock:
            n = self._tenant_inflight.get(tenant, 0)
            if n > 1:
                self._tenant_inflight[tenant] = n - 1
            else:
                self._tenant_inflight.pop(tenant, None)

    # -- routing + the per-request send/failover loop ------------------------
    def _choose(self, exclude: Set[str] = frozenset()
                ) -> Optional[GlobalWorker]:
        """Least-load routing over the health-gated worker set."""
        with self._lock:
            cands = [w for w in self._workers.values()
                     if w.routable and w.name not in exclude]
            if not cands:
                return None
            return min(cands, key=lambda w: w.depth())

    def _run(self, req: GlobalRequest) -> None:
        """One request's whole life on a client thread: choose, send,
        and on worker death re-send to a survivor under an attempt-
        qualified rid — the journal-provable failover loop."""
        try:
            self._run_attempts(req)
        except Exception as exc:               # noqa: BLE001 - last resort
            req.finish(error=RequestError(f"{type(exc).__name__}: {exc}"))
        finally:
            self._tenant_release(req.tenant)

    def _run_attempts(self, req: GlobalRequest) -> None:
        prev = ""
        while True:
            worker = self._choose(exclude=req.tried)
            if worker is None and req.tried:
                # every distinct worker tried (or none ready among the
                # untried): widen to ANY routable worker before shedding —
                # a 2-worker fleet that lost one must keep retrying on
                # the survivor
                worker = self._choose()
            if worker is None:
                self.counters.increment(f"Serving.{req.model}", "shed")
                self.counters.increment("Fleet", "no.ready")
                req.finish(error=ShedError(
                    f"no ready worker for {req.model!r} "
                    f"(request {req.rid}) — shed at the fleet door"))
                return
            if req.attempts > 0:
                self.counters.increment("Fleet", "failovers")
                tel.tracer().event("fleet.pool.failover", rid=req.rid,
                                   model=req.model,
                                   **{"from": prev, "to": worker.name},
                                   attempt=req.attempts)
            req.worker = worker.name
            req.tried.add(worker.name)
            with self._lock:
                worker.inflight += 1
            t0 = time.monotonic()
            try:
                outs = worker.client.score(
                    req.model, [req.line],
                    rids=[f"{req.rid}.a{req.attempts}"],
                    tenant=req.tenant,
                    timeout_s=self.request_timeout_s + 30.0)
            except WorkerDownError as err:
                self._on_worker_error(worker)
                prev = worker.name
                req.attempts += 1
                if req.attempts > self.failover_retries:
                    self.counters.increment(f"Serving.{req.model}", "shed")
                    self.counters.increment("Fleet", "failover.exhausted")
                    req.finish(error=ShedError(
                        f"request {req.rid} for {req.model!r} lost its "
                        f"worker {req.attempts} time(s) — fleet.pool."
                        f"failover.retries={self.failover_retries} "
                        f"exhausted, request shed ({err})"))
                    return
                continue
            except ServingError as err:
                # typed, non-retryable: shed/timeout/unknown/bad-request
                req.finish(error=err)
                return
            finally:
                with self._lock:
                    worker.inflight = max(worker.inflight - 1, 0)
            self._on_worker_ok(worker)
            if not outs:
                req.finish(error=RequestError(
                    f"worker {worker.name!r} returned no result for "
                    f"request {req.rid}"))
                return
            self.latency.setdefault(
                req.model, LatencyTracker()).record(time.monotonic() - t0)
            self.counters.increment(f"Serving.{req.model}", "requests")
            req.finish(result=outs[0])
            return

    # -- breaker bookkeeping -------------------------------------------------
    def _on_worker_ok(self, worker: GlobalWorker) -> None:
        with self._lock:
            worker.consecutive = 0

    def _on_worker_error(self, worker: GlobalWorker) -> None:
        trip = False
        with self._lock:
            worker.consecutive += 1
            if worker.breaker == CLOSED and \
                    worker.consecutive >= self.breaker_failures:
                worker.breaker = OPEN
                worker.opened_at = time.monotonic()
                trip = True
        if trip:
            self.counters.increment("Fleet", "breaker.trips")
            tel.tracer().event("fleet.pool.worker.down", worker=worker.name,
                               reason="breaker", pending=0)
            # GraftBox: snapshot what the ROUTER saw the moment the
            # breaker opened — ring tail, routing table, in-flight rids —
            # without spending the router's own crash latch (no-op when
            # blackbox.dir is unset)
            blackbox.capture(f"breaker:{worker.name}")

    # -- supervision (monitor thread; public for deterministic tests) --------
    def monitor_once(self) -> None:
        """One supervision tick: detect dead processes, refresh every
        worker's health feed, run half-open probes, autoscale."""
        now = time.monotonic()
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            if w.dead or not w.active:
                continue
            if w.proc is not None and w.proc.poll() is not None:
                # the PROCESS died (SIGKILL, crash): out of rotation now.
                # Its in-flight requests fail over themselves — each
                # blocked POST gets a reset and re-sends to a survivor —
                # so `pending` records how many were stranded mid-hop.
                with self._lock:
                    w.dead = True
                    w.active = False
                    w.breaker = OPEN
                    pending = w.inflight
                self.counters.increment("Fleet", "workers.lost")
                tel.tracer().event("fleet.pool.worker.down", worker=w.name,
                                   reason="died", pending=pending)
                continue
            self._poll_worker(w, now=now)
        if self.autoscale and \
                now - self._last_scale >= self.autoscale_interval_s:
            self._last_scale = now
            self.autoscale_once()

    def _poll_worker(self, w: GlobalWorker,
                     now: Optional[float] = None) -> None:
        """Refresh one worker's ``/healthz`` feed; a transport failure
        counts toward the breaker, a 200 closes a half-open breaker."""
        now = time.monotonic() if now is None else now
        try:
            body = w.client.healthz(timeout_s=min(self.heartbeat_s, 5.0))
        except WorkerDownError:
            with self._lock:
                w.health = None
            self._on_worker_error(w)
            return
        with self._lock:
            w.health = body
            w.consecutive = 0
            reopen = (w.breaker == OPEN
                      and now - w.opened_at >= self.halfopen_s
                      and bool(body.get("ready")))
            if reopen:
                w.breaker = CLOSED
        if reopen:
            self.counters.increment("Fleet", "breaker.closes")
            tel.tracer().event("fleet.pool.worker.up", worker=w.name,
                               reason="probe")

    def _monitor_loop(self) -> None:
        while not self._stop_evt.wait(self.monitor_interval_s):
            try:
                self.monitor_once()
            except Exception:                      # pragma: no cover
                log.exception("fleet monitor tick failed")

    # -- process-granularity autoscaling -------------------------------------
    def autoscale_once(self) -> None:
        """The round-17 burn-rate autoscaler at process granularity:
        replace lost capacity below ``fleet.pool.autoscale.min``, spawn a
        worker on burn/queue pressure up to ``fleet.pool.autoscale.max``,
        SIGTERM the newest worker when cold — each decision journals a
        golden-schema'd ``fleet.pool.scale`` event."""
        with self._lock:
            live = [w for w in self._workers.values() if w.active]
            ready = [w for w in live if w.routable]
            spawning = self._spawning
        depths = self.queue_depths()
        total_depth = sum(depths.values())
        cap = 0
        for w in ready:
            for row in ((w.health or {}).get("queue") or {}).values():
                cap += int(row.get("cap", 0))
        frac = (total_depth / cap) if cap else 1.0
        burn = 0.0
        if self.slo is not None:
            rows = self.slo.evaluate_live(self.counters, self.latency,
                                          depths)
            burns = [row["burn_rate"] for row in rows
                     if row["burn_rate"] is not None]
            burn = max(burns) if burns else 0.0
        tracer = tel.tracer()
        tracer.gauge("fleet.workers.ready", len(ready))
        tracer.gauge("fleet.workers.active", len(live))
        tracer.gauge("fleet.burn.max", round(burn, 6))
        if spawning or self.spawner is None:
            return
        if len(ready) < self.autoscale_min:
            # lost capacity: replace without waiting for pressure — what
            # turns a SIGKILLed worker into shed requests, not an outage
            self._spawn_async("replace")
            self._scale_event("up", len(ready), len(live) + 1, burn, frac,
                              "replace")
        elif (burn >= self.up_burn or frac >= self.queue_frac) and \
                len(live) < self.autoscale_max:
            reason = "burn" if burn >= self.up_burn else "queue"
            self._spawn_async(reason)
            self._scale_event("up", len(ready), len(live) + 1, burn, frac,
                              reason)
        elif burn <= self.down_burn and frac <= 0.05 and \
                len(ready) > self.autoscale_min:
            victim = ready[-1]        # newest ready worker drains out
            self.retire(victim, reason="scale.down")
            self._scale_event("down", len(ready) - 1, len(live) - 1, burn,
                              frac, "cold")

    def _scale_event(self, direction: str, ready: int, total: int,
                     burn: float, frac: float, reason: str) -> None:
        self.counters.increment("Fleet", f"scale.{direction}")
        tel.tracer().event("fleet.pool.scale", direction=direction,
                           ready=ready, total=total, burn=round(burn, 6),
                           queue_frac=round(frac, 6), reason=reason)

    def _spawn_async(self, reason: str) -> None:
        """Spawn a worker PROCESS off the monitor thread: bring-up is
        seconds (interpreter + model load + warmup), and heartbeat
        detection on the rest of the fleet must keep ticking meanwhile."""
        with self._lock:
            if self._spawning:
                return
            self._spawning = True
        threading.Thread(target=self._spawn_blocking, args=(reason,),
                         daemon=True, name="fleet-spawn").start()

    def _spawn_blocking(self, reason: str) -> None:
        try:
            worker = self.spawner()
            with self._lock:
                swapped = dict(self._swapped)
            for model, (props, warm) in swapped.items():
                # catch the newcomer up to the fleet's swapped versions
                # (the ReplicaPool._swapped discipline, one level up)
                try:
                    worker.client.swap(model, props, warm=warm)
                except ServingError:           # pragma: no cover
                    log.exception("post-spawn swap of %r failed", model)
            self._poll_worker(worker)
            with self._lock:
                self._workers[worker.name] = worker
            self.counters.increment("Fleet", "workers.spawned")
            tel.tracer().event("fleet.pool.worker.up", worker=worker.name,
                               reason=reason)
        except Exception:                          # noqa: BLE001
            log.exception("fleet worker spawn failed")
        finally:
            with self._lock:
                self._spawning = False

    def retire(self, worker: GlobalWorker, reason: str = "retire") -> None:
        """Take a worker out of rotation and SIGTERM its process (the
        worker's own handler drains, snapshots counters and closes its
        journal shard — serving/__main__.py)."""
        with self._lock:
            worker.active = False
        self.counters.increment("Fleet", "workers.retired")
        tel.tracer().event("fleet.pool.worker.down", worker=worker.name,
                           reason=reason, pending=0)
        if worker.proc is not None and worker.proc.poll() is None:
            worker.proc.terminate()

    # -- rolling fleet-wide hot-swap -----------------------------------------
    def swap_fleet(self, model: str, props: Dict[str, str],
                   warm: bool = True, floor: Optional[int] = None,
                   settle_timeout_s: float = 30.0) -> Dict[str, object]:
        """Roll a model swap across the fleet ONE worker at a time
        through each worker's ``POST /swap`` (inside, the round-11 warmup
        barrier — or the pool's own rolling swap — keeps that worker
        serving).  Between hops the router polls fleet readiness and
        refuses to proceed while ready capacity sits below ``floor``
        (``fleet.pool.swap.floor``), so the observable guarantee is
        end-to-end: ready workers never drop below the floor during the
        rollout.  Returns per-worker versions plus the minimum ready
        count observed (the soak's acceptance)."""
        floor = self.swap_floor if floor is None else int(floor)
        with self._lock:
            targets = [w for w in self._workers.values()
                       if w.active and not w.dead]
            self._swapped[model] = (dict(props), bool(warm))
        versions: Dict[str, object] = {}
        min_ready: Optional[int] = None
        for w in targets:
            ready = self._settled_ready(floor, settle_timeout_s)
            min_ready = ready if min_ready is None else min(min_ready, ready)
            if ready < floor:
                raise ShedError(
                    f"fleet ready capacity {ready} below the swap floor "
                    f"{floor} — rolling swap halted before {w.name!r}")
            doc = w.client.swap(model, props, warm=warm)
            version = doc.get("version")
            versions[w.name] = version
            tel.tracer().event("fleet.pool.swap", worker=w.name,
                               model=model, version=version, ready=ready,
                               floor=floor)
            self.counters.increment("Fleet", "swaps")
        ready = self._settled_ready(floor, settle_timeout_s)
        if min_ready is not None:
            min_ready = min(min_ready, ready)
        return {"model": model, "versions": versions,
                "min_ready": min_ready if min_ready is not None else ready,
                "floor": floor}

    def _settled_ready(self, floor: int, timeout_s: float) -> int:
        """Fresh ready count (every active worker re-polled); waits up to
        ``timeout_s`` for the count to reach ``floor`` before giving up
        and returning the last observation."""
        deadline = time.monotonic() + max(timeout_s, 0.0)
        while True:
            with self._lock:
                workers = [w for w in self._workers.values()
                           if w.active and not w.dead]
            for w in workers:
                self._poll_worker(w)
            with self._lock:
                ready = sum(1 for w in self._workers.values()
                            if w.routable)
            if ready >= floor or time.monotonic() >= deadline:
                return ready
            time.sleep(0.1)

    # -- the batcher-compatible frontend surface -----------------------------
    @property
    def ready(self) -> bool:
        with self._lock:
            return any(w.routable for w in self._workers.values())

    @property
    def buckets(self) -> List[int]:
        with self._lock:
            for w in self._workers.values():
                if w.health and w.health.get("buckets"):
                    return list(w.health["buckets"])
        return []

    def queue_depths(self) -> Dict[str, int]:
        """Per-model queued depth SUMMED across routable workers (from
        the health feed) — the ``serve.queue.<model>`` gauges."""
        out: Dict[str, int] = {}
        with self._lock:
            workers = [w for w in self._workers.values() if w.routable]
        for w in workers:
            for model, row in ((w.health or {}).get("queue") or {}).items():
                out[model] = out.get(model, 0) + int(row.get("depth", 0))
        return out

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            workers = list(self._workers.values())
        out = {
            "fleet.workers.ready": float(
                sum(1 for w in workers if w.routable)),
            "fleet.workers.active": float(
                sum(1 for w in workers if w.active)),
        }
        for w in workers:
            if w.active:
                out[f"fleet.queue.{w.name}"] = float(w.depth())
        return out

    def health(self) -> Dict[str, object]:
        """The fleet ``/healthz`` body: green iff ≥ 1 worker is ready,
        plus one row per worker — the satellite's aggregate readiness
        contract, rendered by the unchanged HTTP frontend."""
        with self._lock:
            workers = list(self._workers.values())
        rows = []
        models: Set[str] = set()
        versions: Dict[str, int] = {}
        buckets: List[int] = []
        queue: Dict[str, Dict[str, int]] = {}
        any_ready = False
        for w in workers:
            h = w.health or {}
            routable = w.routable
            any_ready |= routable
            rows.append({"worker": w.name, "url": w.client.url,
                         "ready": routable, "breaker": w.breaker,
                         "active": w.active, "alive": not w.dead,
                         "inflight": w.inflight,
                         "queue": h.get("queue", {}),
                         "versions": h.get("versions", {})})
            models.update(h.get("models", []))
            if h.get("buckets"):
                buckets = list(h["buckets"])
            if w.active and not w.dead:
                for m, row in (h.get("queue") or {}).items():
                    agg = queue.setdefault(m, {"depth": 0, "cap": 0})
                    agg["depth"] += int(row.get("depth", 0))
                    agg["cap"] += int(row.get("cap", 0))
                for m, v in (h.get("versions") or {}).items():
                    # conservative rollout view: a fleet swap has landed
                    # when the SLOWEST live worker runs the new version
                    versions[m] = min(versions.get(m, v), v)
        return {
            "status": "ok" if any_ready else "unavailable",
            "ready": any_ready,
            "models": sorted(models),
            "buckets": buckets,
            "queue": queue,
            "versions": versions,
            "workers": rows,
        }

    def stats(self, identity: Optional[Dict[str, str]] = None
              ) -> Dict[str, dict]:
        out = serving_stats(self.counters, self.latency, identity=identity)
        with self._lock:
            workers = list(self._workers.values())
        fleet_counters = self.counters.as_dict().get("Fleet", {})
        out["fleet"] = {
            "workers": sum(1 for w in workers if w.active),
            "ready": sum(1 for w in workers if w.routable),
            **{k: v for k, v in sorted(fleet_counters.items())},
        }
        return out

    def _blackbox_state(self) -> List[Dict[str, object]]:
        """The bundle's fleet-state rows: worker name, routable, breaker
        state, consecutive failures, in-flight count."""
        with self._lock:
            workers = list(self._workers.values())
        return [{"worker": w.name, "routable": w.routable,
                 "breaker": w.breaker, "active": w.active,
                 "alive": not w.dead, "consecutive": w.consecutive,
                 "inflight": w.inflight}
                for w in workers]

    def close(self, retire_workers: bool = True,
              grace_s: float = 15.0) -> None:
        """Stop supervision and the client pool; with
        ``retire_workers``, SIGTERM every owned process and reap it
        (escalating to SIGKILL past ``grace_s``)."""
        self._stop_evt.set()
        if self._monitor.is_alive():
            self._monitor.join(timeout=10.0)
        self._pool.shutdown(wait=True)
        blackbox.unregister_provider(self._bb_name)
        if not retire_workers:
            return
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            if w.proc is not None and w.proc.poll() is None:
                w.proc.terminate()
        deadline = time.monotonic() + grace_s
        for w in workers:
            if w.proc is None:
                continue
            while w.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if w.proc.poll() is None:
                w.proc.kill()

    def __enter__(self) -> "GlobalRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class WorkerSpawner:
    """Launcher integration: spawns ONE serving worker process per call
    (``python -m avenir_tpu.serving --conf <props> -D …``) on a fresh
    port, with its own journal-shard suffix (``w<k>``) and the fleet's
    shared ``trace.run.id`` — so every worker's shard lands in the SAME
    run and one ``telemetry merge`` holds the whole serving fleet
    (the satellite-2 contract).  Blocks until the worker's ``/healthz``
    answers (ready or not — the router's health gate takes over from
    there)."""

    def __init__(self, conf_path: str, run_id: str, *,
                 overrides: Optional[Dict[str, str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 host: str = "127.0.0.1",
                 ready_timeout_s: float = 180.0,
                 echo: bool = True):
        self.conf_path = conf_path
        self.run_id = run_id
        self.overrides = dict(overrides or {})
        self.env = env
        self.host = host
        self.ready_timeout_s = float(ready_timeout_s)
        self.echo = echo
        self._index = itertools.count(0)
        self._lock = threading.Lock()

    def spawn(self) -> GlobalWorker:
        import os
        import subprocess
        import sys

        from avenir_tpu.launch import ENV_SUFFIX, free_port

        with self._lock:
            k = next(self._index)
        name = f"w{k}"
        port = free_port()
        cmd = [sys.executable, "-m", "avenir_tpu.serving",
               "--conf", self.conf_path, "--http-port", str(port),
               "-D", f"trace.run.id={self.run_id}"]
        for key, value in sorted(self.overrides.items()):
            cmd += ["-D", f"{key}={value}"]
        env = dict(os.environ if self.env is None else self.env)
        # the launcher's per-process shard contract: the worker adopts
        # AVENIR_WRITER_SUFFIX as trace.writer.suffix (spans.configure)
        env[ENV_SUFFIX] = name
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        threading.Thread(target=self._pump, args=(name, proc),
                         daemon=True, name=f"fleet-pump-{name}").start()
        client = WorkerClient(self.host, port, name=name)
        worker = GlobalWorker(name, client, proc=proc)
        self._wait_up(worker)
        return worker

    def _pump(self, name: str, proc) -> None:
        try:
            for line in proc.stdout:
                if self.echo:
                    print(f"[{name}] {line}", end="", flush=True)
        # stdout relay only: the pipe breaking (worker SIGKILLed, fleet
        # teardown) is the expected end of this thread, and the monitor
        # journals the worker's death itself
        # graftlint: disable=GL012
        except Exception:                          # noqa: BLE001
            pass

    def _wait_up(self, worker: GlobalWorker) -> None:
        """Poll the newborn's ``/healthz`` until it ANSWERS (model load +
        warmup take seconds); a process that dies first raises typed."""
        deadline = time.monotonic() + self.ready_timeout_s
        while time.monotonic() < deadline:
            if worker.proc.poll() is not None:
                raise WorkerDownError(
                    f"worker {worker.name!r} exited "
                    f"{worker.proc.returncode} during bring-up",
                    worker=worker.name)
            try:
                worker.health = worker.client.healthz(timeout_s=2.0)
                if worker.health.get("ready"):
                    return
            except WorkerDownError:
                pass                      # not listening yet
            time.sleep(0.2)
        raise WorkerDownError(
            f"worker {worker.name!r} not ready within "
            f"{self.ready_timeout_s:g}s", worker=worker.name)


def serve_fleet(conf_path: str, nprocs: int, *,
                http_port: Optional[int] = None,
                stop_event: Optional[threading.Event] = None,
                echo: bool = True) -> int:
    """The launcher's ``--serve`` mode body: bring up ``nprocs`` serving
    worker processes from ``conf_path``, front them with a
    :class:`GlobalRouter` behind the standard HTTP frontend
    (``fleet.http.port``, default 8490), run until SIGTERM/Ctrl-C (or
    ``stop_event`` — tests), then tear the fleet down and merge every
    shard — workers' ``w<k>`` suffixes, tenant suffixes and the router's
    own ``router`` shard — into one ``fleet-<run>.jsonl``
    (docs/deployment.md "Cross-host serving")."""
    import signal

    from avenir_tpu.launch import merge_fleet_journal
    from avenir_tpu.serving.frontend import ScoreHTTPServer
    from avenir_tpu.telemetry.export import fleet_identity
    from avenir_tpu.telemetry.slo import SloEvaluator
    from avenir_tpu.tenancy.contract import split_contracts

    if nprocs < 1:
        raise ConfigError(f"--serve needs nprocs >= 1, got {nprocs}")
    conf = JobConfig.from_file(conf_path)
    run_id = tel.fleet_run_id(conf)
    journal_dir = conf.get("trace.journal.dir") or "."
    # the router journals to its OWN shard of the same run: pin the
    # shared run id and a `router` writer suffix before configure
    router_conf = JobConfig(dict(conf.props), prefix=conf.prefix)
    router_conf.set("trace.run.id", run_id)
    if not router_conf.get("trace.writer.suffix"):
        router_conf.set("trace.writer.suffix", "router")
    tel.configure(router_conf)
    # global tenancy: each worker runs a 1/N split of the declared
    # contracts; the router keeps the full ones for door admission
    spawner = WorkerSpawner(conf_path, run_id,
                            overrides=split_contracts(conf, nprocs),
                            echo=echo)
    workers = [spawner.spawn() for _ in range(nprocs)]
    router = GlobalRouter.from_conf(conf, workers=workers,
                                    spawner=spawner.spawn)
    port = (http_port if http_port is not None
            else conf.get_int("fleet.http.port", 8490))
    http = ScoreHTTPServer(
        router, port=port, slo=SloEvaluator.from_conf(conf),
        identity=fleet_identity(worker="router")).start()
    health = router.health()
    print(f"GlobalServe fronting {len(workers)} worker(s) "
          f"({health['models']}) on "
          f"http://{http.address[0]}:{http.address[1]}", flush=True)
    stop = stop_event if stop_event is not None else threading.Event()
    try:
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
    except ValueError:                       # pragma: no cover - non-main
        pass
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    finally:
        http.stop()
        router.close()
        tel.tracer().counters("fleet", router.counters)
        tel.tracer().disable()
        # GraftBox: finalize + journal dead workers' bundles BEFORE the
        # merge, so the merged fleet journal carries exactly one
        # bundle.written per dead worker (a SIGKILLed worker ran no hook
        # — its live bundle is all the evidence there is)
        bb_dir = conf.get("blackbox.dir")
        if bb_dir:
            for rec in blackbox.sweep(bb_dir, journal_dir=journal_dir,
                                      run_id=run_id):
                print(f"[fleet] blackbox bundle: {rec['dir']} "
                      f"({rec['reason']})", flush=True)
        merged = merge_fleet_journal(journal_dir, run_id=run_id)
        if merged:
            print(f"[fleet] merged journal: {merged}", flush=True)
        print(json.dumps(router.stats()), flush=True)
    return 0
