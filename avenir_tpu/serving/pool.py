"""FleetServe — the fault-tolerant replica pool behind one frontend.

Every serving primitive existed before this module — versioned hot-swap
with a warmup barrier (round 11), the ``/healthz`` readiness probe and
``process``/``replica`` metric labels (round 15), the live
``avenir_slo_burn_rate`` evaluator (round 15), the conf-driven ``fault.*``
injection family (round 16) — but the plane was ONE
:class:`~avenir_tpu.serving.batcher.BucketedMicrobatcher` on one device:
a single wedged dispatcher took down all traffic.  :class:`ReplicaPool`
makes failure the first-class, tested path (fleet-scoping discipline per
the pjit/TPUv4 playbook, arxiv 2204.06514):

- **health-gated routing** — requests go to the least-queue-depth replica
  whose readiness is green (warmed, not failed, breaker closed);
- **per-replica circuit breaker** — ``pool.breaker.failures`` consecutive
  infrastructure dispatch errors (typed request faults never count) or a
  missed ``pool.heartbeat.ms`` deadline open the breaker; after
  ``pool.breaker.halfopen.ms`` it half-opens and a liveness probe through
  the replica's REAL dispatch queue decides closed vs open;
- **failover** — a replica dying mid-batch fails its unfinished requests
  with the retryable :class:`~avenir_tpu.serving.errors.ReplicaDownError`
  and the pool re-enqueues each on a survivor, at most
  ``pool.failover.retries`` times per request, else a typed
  :class:`~avenir_tpu.serving.errors.ShedError` — never silent loss, and
  never a double score (a request only carries ReplicaDownError if its
  score never completed; ``PendingRequest.finish`` is idempotent);
- **rolling hot-swap** — :meth:`ReplicaPool.swap` rolls the round-11 swap
  barrier one replica at a time, so capacity never drops to zero and the
  zero-steady-state-recompiles invariant holds across the rollout;
- **burn-rate autoscaling** — ``pool.autoscale.*`` grows/shrinks the
  active set from the live ``avenir_slo_burn_rate`` rows and the
  queue-depth gauges, and replaces dead replicas so a kill costs shed
  requests, never an outage.

Every transition journals golden-schema'd events — ``pool.replica.down``,
``pool.replica.up``, ``pool.scale``, ``pool.failover`` — so a chaos soak
(``benchmarks/serving_soak.py``) is triaged from the merged fleet journal
(docs/runbooks/replica_loss_triage.md).

The pool duck-types the batcher's frontend surface (``submit_nowait`` /
``submit`` / ``queue_depths`` / ``counters`` / ``latency`` / ``stats`` /
``health``), so :class:`~avenir_tpu.serving.frontend.ScoreHTTPServer` and
:class:`~avenir_tpu.serving.frontend.QueueScoreFrontend` serve a pool
unchanged.  ``counters`` and the per-model latency trackers are SHARED
across replicas, so ``/metrics`` and SLO evaluation aggregate for free.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Set

from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.serving.batcher import BucketedMicrobatcher, PendingRequest
from avenir_tpu.serving.errors import (
    ReplicaDownError,
    ServingError,
    ShedError,
)
from avenir_tpu.telemetry import blackbox
from avenir_tpu.telemetry import spans as tel
from avenir_tpu.utils.metrics import Counters, LatencyTracker, serving_stats
from avenir_tpu.utils.retry import FaultPlan

log = logging.getLogger(__name__)

# breaker states — the classic three-state circuit
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class Replica:
    """One pool member: a batcher plus its routing/breaker state."""

    __slots__ = ("name", "batcher", "breaker", "consecutive", "opened_at",
                 "active", "dead")

    def __init__(self, name: str, batcher: BucketedMicrobatcher):
        self.name = name
        self.batcher = batcher
        self.breaker = CLOSED
        self.consecutive = 0              # consecutive infra dispatch errors
        self.opened_at = 0.0
        self.active = True                # False once retired or dead
        self.dead = False                 # died/wedged — never comes back

    @property
    def routable(self) -> bool:
        """Health gate: traffic goes only to an active, warmed, breaker-
        closed replica whose dispatcher has not failed."""
        return (self.active and self.breaker == CLOSED
                and self.batcher.ready and not self.batcher.failed)

    def depth(self) -> int:
        return sum(self.batcher.queue_depths().values())


class PoolRequest:
    """The pool's pending handle: delegates to the current replica's
    :class:`PendingRequest` and fails over on replica death.

    ``wait`` re-enqueues the request on a survivor each time the holding
    replica dies (at most ``pool.failover.retries`` times), so the caller
    sees either the scored line or one typed error — a replica loss is
    shed requests at worst, never a hang and never a silent drop."""

    __slots__ = ("pool", "model", "line", "rid", "inner", "replica",
                 "tried", "attempts")

    def __init__(self, pool: "ReplicaPool", model: str, line: str, rid: str):
        self.pool = pool
        self.model = model
        self.line = line
        self.rid = rid
        self.inner: Optional[PendingRequest] = None
        self.replica: str = ""
        self.tried: Set[str] = set()
        self.attempts = 0                 # failover re-enqueues so far

    def wait(self, timeout_s: Optional[float] = None) -> str:
        if timeout_s is None:
            timeout_s = self.pool.request_timeout_s + 30.0
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                return self.inner.wait(
                    max(deadline - time.monotonic(), 0.001))
            except ReplicaDownError:
                # the replica died before this request scored: re-enqueue
                # on a survivor (raises typed ShedError when retries are
                # exhausted or no survivor is ready)
                self.pool._failover(self)


class ReplicaPool:
    """N :class:`BucketedMicrobatcher` replicas behind one routing door.

    ``factory(name, **wiring)`` builds one replica's batcher; the pool
    passes the shared wiring (``counters``, ``latency``, ``fault``, the
    breaker callbacks, optionally a pinned ``device``) through it, so
    every replica reports into one aggregate and one fault schedule spans
    the pool ("kill the N-th dispatch" is pool-wide).
    """

    def __init__(self, factory: Callable[..., BucketedMicrobatcher],
                 replicas: int = 2, *,
                 counters: Optional[Counters] = None,
                 latency: Optional[Dict[str, LatencyTracker]] = None,
                 fault: Optional[FaultPlan] = None,
                 devices: Optional[List] = None,
                 breaker_failures: int = 3,
                 heartbeat_ms: float = 2000.0,
                 halfopen_ms: float = 1000.0,
                 probe_timeout_ms: float = 5000.0,
                 failover_retries: int = 1,
                 monitor_interval_ms: Optional[float] = None,
                 autoscale: bool = False,
                 autoscale_min: int = 1,
                 autoscale_max: Optional[int] = None,
                 up_burn: float = 1.0,
                 down_burn: float = 0.25,
                 queue_frac: float = 0.5,
                 autoscale_interval_s: float = 5.0,
                 slo=None,
                 tenant: str = "",
                 start_monitor: bool = True):
        if replicas < 1:
            raise ConfigError(f"pool.replicas must be >= 1, got {replicas}")
        self._factory = factory
        # GraftPool (round 18): the tenant this pool serves (tenant.id) —
        # each replica's batcher reads the same conf key itself; the pool
        # carries it so door sheds ("no ready replica") attribute too
        self.tenant = tenant
        self.counters = counters if counters is not None else Counters()
        self.latency: Dict[str, LatencyTracker] = (
            latency if latency is not None else {})
        self.fault = fault
        self._devices = list(devices) if devices else []
        self.breaker_failures = max(int(breaker_failures), 1)
        self.heartbeat_s = float(heartbeat_ms) / 1e3
        self.halfopen_s = float(halfopen_ms) / 1e3
        self.probe_timeout_s = float(probe_timeout_ms) / 1e3
        self.failover_retries = max(int(failover_retries), 0)
        self.autoscale = bool(autoscale)
        self.autoscale_min = max(int(autoscale_min), 1)
        self.autoscale_max = int(autoscale_max) if autoscale_max else \
            max(replicas, self.autoscale_min)
        self.up_burn = float(up_burn)
        self.down_burn = float(down_burn)
        self.queue_frac = float(queue_frac)
        self.autoscale_interval_s = float(autoscale_interval_s)
        self.slo = slo
        self._lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}
        # model → the entry the pool last swapped in: a replica spawned
        # AFTER a rolling swap (autoscale growth, replacement) must come
        # up on the swapped version, not re-load the conf's original
        # artifact — else it would silently serve stale predictions
        self._swapped: Dict[str, object] = {}
        self._next_index = 0
        self._rid = itertools.count(1)
        self._last_scale = time.monotonic()
        for _ in range(replicas):
            self._spawn(reason="start", journal=False)
        # the supervisor: heartbeat deadlines, breaker half-open probes,
        # dead-replica reaping + replacement, autoscaling — one thread,
        # ticking a few times per heartbeat window
        self._stop_evt = threading.Event()
        self.monitor_interval_s = (
            float(monitor_interval_ms) / 1e3 if monitor_interval_ms
            else max(self.heartbeat_s / 4.0, 0.02))
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True, name="pool-monitor")
        # GraftBox: a forensics bundle snapshots this pool's routing/
        # breaker table (which replicas were routable at death)
        self._bb_name = f"pool-{id(self):x}"
        blackbox.register_provider(self._bb_name, self._blackbox_state)
        if start_monitor:
            self._monitor.start()

    # -- construction --------------------------------------------------------
    @classmethod
    def from_conf(cls, conf: JobConfig, registry_factory=None,
                  **overrides) -> "ReplicaPool":
        """Build the pool from ``pool.*`` keys.  ``pool.replicas``
        defaults to one replica per local device (the FleetServe shape);
        on a CPU/host-mesh rig set it explicitly to share devices.
        ``registry_factory`` overrides how each replica loads its models
        (tests); default is one ``ModelRegistry.from_conf`` per replica —
        each replica holds its OWN registry, which is what lets a hot
        swap roll one replica at a time.  ``overrides`` win over conf
        keys (tests pin e.g. ``start_monitor=False``)."""
        from avenir_tpu.serving.registry import ModelRegistry
        from avenir_tpu.telemetry.slo import SloEvaluator

        n = conf.get_int("pool.replicas", 0) or 0
        devices = None
        if n <= 0 or conf.get_bool("pool.pin.devices", False):
            try:
                import jax

                local = jax.local_devices()
            except Exception:                      # pragma: no cover
                local = []
            if n <= 0:
                n = max(len(local), 1)
            if conf.get_bool("pool.pin.devices", False):
                devices = local

        def factory(name: str, **wiring) -> BucketedMicrobatcher:
            registry = (registry_factory() if registry_factory is not None
                        else ModelRegistry.from_conf(conf))
            return BucketedMicrobatcher.from_conf(registry, conf,
                                                  name=name, **wiring)

        kwargs = dict(
            replicas=n,
            fault=FaultPlan.from_conf(conf),
            devices=devices,
            breaker_failures=conf.get_int("pool.breaker.failures", 3),
            heartbeat_ms=conf.get_float("pool.heartbeat.ms", 2000.0),
            halfopen_ms=conf.get_float("pool.breaker.halfopen.ms", 1000.0),
            probe_timeout_ms=conf.get_float("pool.probe.timeout.ms", 5000.0),
            failover_retries=conf.get_int("pool.failover.retries", 1),
            monitor_interval_ms=conf.get_float("pool.monitor.interval.ms"),
            autoscale=conf.get_bool("pool.autoscale.on", False),
            autoscale_min=conf.get_int("pool.autoscale.min", 1),
            autoscale_max=conf.get_int("pool.autoscale.max", 0) or None,
            up_burn=conf.get_float("pool.autoscale.up.burn", 1.0),
            down_burn=conf.get_float("pool.autoscale.down.burn", 0.25),
            queue_frac=conf.get_float("pool.autoscale.queue.frac", 0.5),
            autoscale_interval_s=conf.get_float(
                "pool.autoscale.interval.sec", 5.0),
            slo=SloEvaluator.from_conf(conf),
            tenant=conf.get("tenant.id", "") or "",
        )
        kwargs.update(overrides)
        replicas = kwargs.pop("replicas")
        return cls(factory, replicas, **kwargs)

    def _spawn(self, reason: str, journal: bool = True) -> Replica:
        name = f"r{self._next_index}"
        wiring = dict(
            counters=self.counters, latency=self.latency, fault=self.fault,
            on_batch_ok=lambda n=name: self._on_batch_ok(n),
            on_batch_error=lambda exc, n=name: self._on_batch_error(n, exc))
        if self._devices:
            wiring["device"] = self._devices[
                self._next_index % len(self._devices)]
        self._next_index += 1
        replica = Replica(name, self._factory(name, **wiring))
        with self._lock:
            swapped = dict(self._swapped)
        for model, entry in swapped.items():
            # catch the newcomer up to the pool's current versions (the
            # same warmup barrier a rolling swap runs)
            replica.batcher.swap(model, entry)
        with self._lock:
            self._replicas[name] = replica
        if journal:
            tel.tracer().event("pool.replica.up", replica=name,
                               reason=reason)
        return replica

    # -- routing + submission (any thread) -----------------------------------
    def _choose(self, exclude: Set[str] = frozenset()
                ) -> Optional[Replica]:
        """Least-queue-depth routing over the health-gated replica set."""
        with self._lock:
            cands = [r for r in self._replicas.values()
                     if r.routable and r.name not in exclude]
        if not cands:
            return None
        return min(cands, key=lambda r: r.depth())

    def _submit_on(self, req: PoolRequest) -> None:
        """Bind ``req`` to the best ready replica (raises typed ShedError
        when none is).  A replica dying between choose and submit is
        skipped, not counted against the request's failover budget."""
        while True:
            replica = self._choose(exclude=req.tried)
            if replica is None:
                self.counters.increment(f"Serving.{req.model}", "shed")
                self.counters.increment("Pool", "no.ready")
                err = ShedError(
                    f"no ready replica for {req.model!r} "
                    f"(request {req.rid}) — shed at the pool door")
                if self.tenant:
                    err.tenant = self.tenant
                raise err
            try:
                req.inner = replica.batcher.submit_nowait(
                    req.model, req.line, rid=req.rid)
            except ReplicaDownError:
                req.tried.add(replica.name)   # raced a death; try the next
                continue
            except ServingError as err:
                if type(err) is ServingError:
                    # raced a scale-down close ("batcher is closed"):
                    # skip to a survivor like the death race above —
                    # typed errors (shed/unknown-model/...) still
                    # propagate to the caller
                    req.tried.add(replica.name)
                    continue
                raise
            req.replica = replica.name
            req.tried.add(replica.name)
            return

    def submit_nowait(self, model: str, line: str,
                      rid: Optional[str] = None) -> PoolRequest:
        # caller-assigned rid (GlobalServe: the router's attempt-qualified
        # ``g<n>.a<k>``) wins over the pool's own ``q<n>`` — the one id
        # that threads the request through the merged fleet journal
        req = PoolRequest(self, model, line,
                          rid=rid or f"q{next(self._rid)}")
        self.counters.increment("Pool", "submitted")
        self._submit_on(req)
        return req

    def submit(self, model: str, line: str,
               timeout_s: Optional[float] = None) -> str:
        return self.submit_nowait(model, line).wait(timeout_s)

    def _failover(self, req: PoolRequest) -> None:
        """Re-enqueue a request whose replica died; at most
        ``pool.failover.retries`` re-enqueues per request, then a typed
        ShedError — never silent loss (the caller always gets a result
        or one typed error) and never a double score (only unscored
        requests carry ReplicaDownError)."""
        req.attempts += 1
        self.counters.increment("Pool", "failovers")
        if req.attempts > self.failover_retries:
            self.counters.increment(f"Serving.{req.model}", "shed")
            self.counters.increment("Pool", "failover.exhausted")
            raise ShedError(
                f"request {req.rid} for {req.model!r} lost its replica "
                f"{req.attempts} time(s) — pool.failover.retries="
                f"{self.failover_retries} exhausted, request shed")
        prev = req.replica
        self._submit_on(req)              # raises ShedError when none ready
        tel.tracer().event("pool.failover", rid=req.rid, model=req.model,
                           **{"from": prev, "to": req.replica},
                           attempt=req.attempts)

    # -- breaker callbacks (replica dispatch threads) ------------------------
    def _on_batch_ok(self, name: str) -> None:
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                return
            r.consecutive = 0

    def _on_batch_error(self, name: str, exc: BaseException) -> None:
        trip = False
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                return
            r.consecutive += 1
            if r.breaker == CLOSED and \
                    r.consecutive >= self.breaker_failures:
                r.breaker = OPEN
                r.opened_at = time.monotonic()
                trip = True
        if trip:
            self.counters.increment("Pool", "breaker.trips")
            tel.tracer().event("pool.replica.down", replica=name,
                               reason="breaker", pending=0)

    # -- supervision (monitor thread; public for deterministic tests) --------
    def monitor_once(self) -> None:
        """One supervision tick: reap dead/stalled replicas (failing their
        stranded requests over), run half-open probes, autoscale."""
        now = time.monotonic()
        with self._lock:
            replicas = list(self._replicas.values())
        for r in replicas:
            if r.dead or not r.active:
                continue
            b = r.batcher
            if b.failed or b.stalled(self.heartbeat_s):
                # a dead dispatcher (injected kill) or a wedged one (work
                # pending, heartbeat past the deadline): take it out of
                # rotation and fail its stranded queue over to survivors
                reason = "died" if b.failed else "heartbeat"
                r.dead = True
                r.active = False
                r.breaker = OPEN
                b.mark_failed()
                pending = b.fail_pending(
                    "missed pool.heartbeat.ms deadline" if reason ==
                    "heartbeat" else "replica died")
                self.counters.increment("Pool", "replicas.lost")
                tel.tracer().event("pool.replica.down", replica=r.name,
                                   reason=reason, pending=pending)
                continue
            if r.breaker == OPEN and now - r.opened_at >= self.halfopen_s:
                # half-open: one probe request through the replica's real
                # dispatch queue decides — alive again closes the
                # breaker.  The probe blocks up to pool.probe.timeout.ms,
                # so it runs OFF the supervision thread: heartbeat
                # deadlines on other replicas must not wait behind a
                # hung probe.  HALF_OPEN set first = at most one probe
                # in flight per replica (later ticks see != OPEN).
                with self._lock:
                    r.breaker = HALF_OPEN
                threading.Thread(target=self._probe_replica, args=(r,),
                                 daemon=True,
                                 name=f"pool-probe-{r.name}").start()
        if self.autoscale and \
                now - self._last_scale >= self.autoscale_interval_s:
            self._last_scale = now
            self.autoscale_once()

    def _probe_replica(self, r: Replica) -> None:
        try:
            alive = r.batcher.probe(self.probe_timeout_s)
        except Exception:                 # noqa: BLE001
            # a raising probe must route to the failure branch: a dead
            # thread here would strand the replica HALF_OPEN forever
            # (ticks only probe while the breaker reads OPEN)
            alive = False
        if alive:
            with self._lock:
                r.breaker = CLOSED
                r.consecutive = 0
            self.counters.increment("Pool", "breaker.closes")
            tel.tracer().event("pool.replica.up", replica=r.name,
                               reason="probe")
        else:
            with self._lock:
                r.breaker = OPEN
                r.opened_at = time.monotonic()

    def autoscale_once(self) -> None:
        """One autoscaler evaluation over the live burn-rate rows and the
        queue-depth gauges: replace lost capacity below
        ``pool.autoscale.min``, grow on burn/queue pressure up to
        ``pool.autoscale.max``, shrink when cold — each decision journals
        a golden-schema'd ``pool.scale`` event."""
        with self._lock:
            live = [r for r in self._replicas.values() if r.active]
        ready = [r for r in live if r.routable]
        depths = self.queue_depths()
        total_depth = sum(depths.values())
        cap = sum(r.batcher.queue_depth for r in ready)
        frac = (total_depth / cap) if cap else 1.0
        burn = 0.0
        if self.slo is not None:
            rows = self.slo.evaluate_live(self.counters, self.latency,
                                          depths)
            burns = [row["burn_rate"] for row in rows
                     if row["burn_rate"] is not None]
            burn = max(burns) if burns else 0.0
        tracer = tel.tracer()
        tracer.gauge("pool.replicas.ready", len(ready))
        tracer.gauge("pool.replicas.active", len(live))
        tracer.gauge("pool.burn.max", round(burn, 6))
        if len(ready) < self.autoscale_min:
            # lost capacity: replace, don't wait for pressure — this is
            # what turns a replica kill into shed requests, not an outage
            self._spawn(reason="replace")
            self._scale_event("up", len(ready) + 1, len(live) + 1, burn,
                              frac, "replace")
        elif (burn >= self.up_burn or frac >= self.queue_frac) and \
                len(live) < self.autoscale_max:
            reason = "burn" if burn >= self.up_burn else "queue"
            self._spawn(reason=reason)
            self._scale_event("up", len(ready) + 1, len(live) + 1, burn,
                              frac, reason)
        elif burn <= self.down_burn and frac <= 0.05 and \
                len(ready) > self.autoscale_min:
            victim = ready[-1]            # newest ready replica drains out
            with self._lock:
                victim.active = False     # out of rotation first…
            # …then drain in-flight work OFF the supervision thread (a
            # close joins the dispatcher — up to its flush — and the
            # heartbeat watch must keep ticking meanwhile)
            threading.Thread(target=victim.batcher.close, daemon=True,
                             name=f"pool-drain-{victim.name}").start()
            self.counters.increment("Pool", "scaled.down")
            tel.tracer().event("pool.replica.down", replica=victim.name,
                               reason="scale.down", pending=0)
            self._scale_event("down", len(ready) - 1, len(live) - 1, burn,
                              frac, "cold")

    def _scale_event(self, direction: str, ready: int, total: int,
                     burn: float, frac: float, reason: str) -> None:
        self.counters.increment("Pool", f"scale.{direction}")
        tel.tracer().event("pool.scale", direction=direction, ready=ready,
                           total=total, burn=round(burn, 6),
                           queue_frac=round(frac, 6), reason=reason)

    def _monitor_loop(self) -> None:
        while not self._stop_evt.wait(self.monitor_interval_s):
            try:
                self.monitor_once()
            except Exception:                      # pragma: no cover
                log.exception("pool monitor tick failed")

    # -- rolling hot-swap ----------------------------------------------------
    def swap(self, model: str, entry, warm: bool = True) -> Dict[str, int]:
        """Pool-wide versioned hot-swap, rolled ONE replica at a time:
        each replica warms the incoming entry's bucket shapes before
        publishing (the round-11 barrier), and while it warms every other
        replica keeps serving — capacity never drops to zero mid-swap.
        Returns each live replica's new version.  The entry is
        remembered so a replica spawned LATER (autoscale growth,
        replacement) comes up on it too, not on the conf's original
        artifact."""
        versions: Dict[str, int] = {}
        with self._lock:
            self._swapped[model] = entry
            replicas = [r for r in self._replicas.values()
                        if r.active and not r.batcher.failed]
        for r in replicas:
            versions[r.name] = r.batcher.swap(model, entry, warm=warm)
        return versions

    # -- the batcher-compatible frontend surface -----------------------------
    @property
    def ready(self) -> bool:
        """Aggregate readiness: green iff at least ONE replica routes."""
        with self._lock:
            return any(r.routable for r in self._replicas.values())

    @property
    def request_timeout_s(self) -> float:
        with self._lock:
            if not self._replicas:
                return 1.0
            return max(r.batcher.request_timeout_s
                       for r in self._replicas.values())

    @property
    def buckets(self) -> List[int]:
        with self._lock:
            for r in self._replicas.values():
                return r.batcher.buckets
        return []

    def queue_depths(self) -> Dict[str, int]:
        """Per-model pending depth SUMMED across live replicas — the
        ``serve.queue.<model>`` gauges a pool frontend exposes."""
        out: Dict[str, int] = {}
        with self._lock:
            replicas = [r for r in self._replicas.values()
                        if r.active and not r.batcher.failed]
        for r in replicas:
            for model, depth in r.batcher.queue_depths().items():
                out[model] = out.get(model, 0) + depth
        return out

    def gauges(self) -> Dict[str, float]:
        """Pool-level ``/metrics`` gauges: readiness and per-replica
        queue depth, so a rolling swap or tripped breaker is visible on
        the scrape page, not just in the journal."""
        with self._lock:
            replicas = list(self._replicas.values())
        out = {
            "pool.replicas.ready": float(
                sum(1 for r in replicas if r.routable)),
            "pool.replicas.active": float(
                sum(1 for r in replicas if r.active)),
        }
        for r in replicas:
            if r.active:
                out[f"pool.queue.{r.name}"] = float(r.depth())
        return out

    def health(self) -> Dict[str, object]:
        """The pool-mode ``/healthz`` body: aggregate readiness (green
        iff ≥ 1 replica is ready) plus one row per replica — ready,
        breaker state, queue depth vs cap, registry versions — so a
        rolling swap or a tripped breaker is visible from one curl."""
        with self._lock:
            replicas = list(self._replicas.values())
        rows = []
        models: Set[str] = set()
        versions: Dict[str, int] = {}
        buckets: List[int] = []
        any_ready = False
        cap = 0
        for r in replicas:
            h = r.batcher.health()
            routable = r.routable
            any_ready |= routable
            rows.append({"replica": r.name, "ready": routable,
                         "breaker": r.breaker, "active": r.active,
                         "queue": h["queue"], "versions": h["versions"]})
            models.update(h["models"])
            buckets = h["buckets"]
            if r.active and not r.batcher.failed:
                cap += r.batcher.queue_depth
                for m, v in h["versions"].items():
                    # the conservative rollout view: a swap has "landed"
                    # when the SLOWEST live replica runs the new version
                    versions[m] = min(versions.get(m, v), v)
        depths = self.queue_depths()
        return {
            "status": "ok" if any_ready else "unavailable",
            "ready": any_ready,
            "models": sorted(models),
            "buckets": buckets,
            "queue": {m: {"depth": d, "cap": cap} for m, d in
                      depths.items()},
            "versions": versions,
            "replicas": rows,
        }

    def stats(self, identity: Optional[Dict[str, str]] = None
              ) -> Dict[str, dict]:
        """The shared serving-stats schema over the POOL aggregate (the
        counters/latency every replica reports into), plus a ``pool``
        row: replica counts, failovers, breaker trips."""
        out = serving_stats(self.counters, self.latency, identity=identity)
        with self._lock:
            replicas = list(self._replicas.values())
        pool_counters = self.counters.as_dict().get("Pool", {})
        out["pool"] = {
            "replicas": sum(1 for r in replicas if r.active),
            "ready": sum(1 for r in replicas if r.routable),
            **{k: v for k, v in sorted(pool_counters.items())},
        }
        return out

    def _blackbox_state(self) -> List[Dict[str, object]]:
        """The bundle's pool-state rows: name, routable, breaker state,
        consecutive failures, queue depth per replica."""
        with self._lock:
            replicas = list(self._replicas.values())
        return [{"replica": r.name, "routable": r.routable,
                 "breaker": r.breaker, "active": r.active,
                 "consecutive": r.consecutive, "depth": r.depth()}
                for r in replicas]

    def close(self) -> None:
        """Stop supervision, then drain and close every replica."""
        self._stop_evt.set()
        if self._monitor.is_alive():
            self._monitor.join(timeout=10.0)
        with self._lock:
            replicas = list(self._replicas.values())
        for r in replicas:
            r.batcher.close()
        blackbox.unregister_provider(self._bb_name)

    def __enter__(self) -> "ReplicaPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
