"""Model registry — every trained family servable from device-resident params.

The reference's entire prediction surface is offline map-only MR jobs
(``BayesianPredictor``, ``ViterbiStatePredictor``, ``NearestNeighbor`` —
SURVEY §2): a trained model can only score a *file*.  This module turns each
trained artifact into a :class:`ServableModel` — parameters uploaded to the
device ONCE at load, scoring jit-compiled against the microbatcher's fixed
bucket shapes — and a :class:`ModelRegistry` mapping model names to entries.

Parity contract (tests/test_serving.py): every servable routes scoring
through the SAME model-layer predict entry its batch job uses
(``models.naive_bayes.predict_batch``, ``models.tree.predict_fn``,
``models.knn.KNN.predict``, ``models.markov.ViterbiStatePredictor``,
``models.logistic.predict_batch``) and formats its response exactly like the
job's output line, so serving responses are byte-identical to the batch
predictions for the same rows.  Pad rows added by the batcher are sliced off
before formatting — they can never leak into a response.

Artifact handoff reuses the jobs' own config keys (``bayesian.model.file.path``,
``coeff.file.path``, ``tree.model.file.path``, ``training.data.path``,
``hmm.model.file.path``), so a pipeline stage's output artifact plugs straight
into ``serve.models`` (see ``serving/replay.py`` for the driver stage).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.core.csv_io import read_csv_string
from avenir_tpu.core.encoding import (DatasetEncoder, EncodedDataset,
                                      pad_ballast)
from avenir_tpu.jobs.base import Job, read_lines
from avenir_tpu.serving.errors import RequestError


# ---------------------------------------------------------------------------
# shared plumbing
# ---------------------------------------------------------------------------

def _pad_ds(ds: EncodedDataset, pad_to: int) -> EncodedDataset:
    """Pad the batch axis with neutral zero rows up to the bucket size; the
    caller slices outputs back to the real row count, so pad rows are pure
    shape ballast (mask-by-slicing — a pad row's score is never read).
    Routes through the shared :func:`~avenir_tpu.core.encoding.pad_ballast`
    contract with ``fill=0``: scoring pad rows stay in-vocabulary (an
    all-zero request row scores without error), unlike count-path ballast
    whose −1 labels must drop out of every table."""
    if pad_to < ds.num_rows:
        raise ValueError(f"batch of {ds.num_rows} rows exceeds bucket {pad_to}")
    return pad_ballast(ds, pad_to, fill=0)


def _blank_ds(enc: DatasetEncoder, n: int) -> EncodedDataset:
    """An all-zeros encoded batch of ``n`` rows in ``enc``'s code space —
    the warmup operand that compiles a bucket shape without real traffic."""
    return EncodedDataset(
        codes=np.zeros((n, len(enc.binned_fields)), np.int32),
        cont=np.zeros((n, len(enc.cont_fields)), np.float32),
        labels=None, ids=None,
        n_bins=np.array([enc.n_bins[f.ordinal] for f in enc.binned_fields],
                        np.int32),
        class_values=list(enc.class_values),
        binned_ordinals=[f.ordinal for f in enc.binned_fields],
        cont_ordinals=[f.ordinal for f in enc.cont_fields])


def _parse_rows(lines: Sequence[str], delim: str,
                max_ordinal: int) -> np.ndarray:
    """Request payloads → [N, ncols] field array, with the data errors a
    batch job would throw surfaced as typed :class:`RequestError` instead.
    A raise here fails the whole padded batch; the batcher then isolates —
    re-scores each member alone — so one bad request never poisons its
    coalesced neighbors (``BucketedMicrobatcher._dispatch_isolated``)."""
    try:
        rows = read_csv_string("\n".join(lines), delim=delim)
    except ValueError as e:
        raise RequestError(f"unparseable request rows: {e}") from None
    if rows.shape[0] != len(lines):
        raise RequestError("blank request rows are not servable")
    if rows.shape[1] <= max_ordinal:
        raise RequestError(
            f"request rows carry {rows.shape[1]} fields but the schema "
            f"reads ordinal {max_ordinal}")
    return rows


def _complete_encoder(conf: JobConfig) -> DatasetEncoder:
    """A transform-ready encoder straight from the schema: online scoring
    has no training pass to fit vocabularies from, so the schema must fully
    specify them (the same contract streaming training already imposes)."""
    enc = Job.encoder_for(conf)
    if not enc.schema_complete(with_labels=False) or not enc.class_values:
        raise ConfigError(
            "serving requires a schema-complete encoder (categorical "
            "cardinality / numeric min+max+bucketWidth, and class "
            "cardinality) — online requests cannot fit a vocabulary")
    return enc


class ServableModel:
    """One loaded model: device-resident params + a fixed-shape scorer.

    ``compile_keys`` records every (bucket, ...) shape this entry has
    dispatched — the batcher diffs it after each batch to count steady-state
    recompiles (zero after warmup is the serving plane's core invariant).
    """

    family: str = ""

    def __init__(self) -> None:
        self.compile_keys: Set[Tuple] = set()

    def score_lines(self, lines: Sequence[str], pad_to: int) -> List[str]:
        """Score ``lines`` (raw CSV request rows) padded to ``pad_to``;
        returns exactly ``len(lines)`` response lines."""
        raise NotImplementedError

    def warmup(self, pad_to: int) -> None:
        """Compile the ``pad_to`` bucket shape on a blank batch."""
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Naive Bayes
# ---------------------------------------------------------------------------

class NaiveBayesServable(ServableModel):
    """BayesianPredictor's scoring path online: response line =
    ``<request row>,<predictedClass>[,ambiguous]`` — exactly the job's
    output row (bayesian/BayesianPredictor.java:319-391 semantics,
    including cost-based arbitration and the ambiguity flag)."""

    family = "naiveBayes"

    def __init__(self, model, encoder: DatasetEncoder, delim: str = ",",
                 cost: Optional[np.ndarray] = None,
                 ambiguity_threshold: Optional[float] = None):
        super().__init__()
        self.model = model
        self.enc = encoder
        self.delim = delim
        self.cost = cost
        self.ambiguity_threshold = ambiguity_threshold
        model.scoring_params()            # device upload happens at load

    @classmethod
    def from_conf(cls, conf: JobConfig) -> "NaiveBayesServable":
        from avenir_tpu.jobs.bayesian import _cost_matrix
        from avenir_tpu.models import naive_bayes as nb

        path = conf.get("bayesian.model.file.path")
        if not path:
            raise ConfigError("serving naiveBayes requires "
                              "bayesian.model.file.path")
        enc = _complete_encoder(conf)
        model = nb.model_from_lines(read_lines(path), enc,
                                    delim=conf.field_delim)
        threshold = conf.get_float("class.prob.diff.threshold")
        if threshold is not None and threshold > 1.0:
            threshold /= 100.0            # reference thresholds are % ints
        cost = (_cost_matrix(conf, model.class_values)
                if conf.get_bool("use.cost.based.classifier") else None)
        return cls(model, enc, delim=conf.field_delim, cost=cost,
                   ambiguity_threshold=threshold)

    def _score_ds(self, ds: EncodedDataset):
        from avenir_tpu.models import naive_bayes as nb

        return nb.NaiveBayes().predict(
            self.model, ds, cost=self.cost,
            ambiguity_threshold=self.ambiguity_threshold)

    def score_lines(self, lines: Sequence[str], pad_to: int) -> List[str]:
        rows = _parse_rows(lines, self.delim, self.enc.max_ordinal(False))
        ds = _pad_ds(self.enc.transform(rows, with_labels=False), pad_to)
        self.compile_keys.add((pad_to,))
        result = self._score_ds(ds)
        out = []
        for i, line in enumerate(lines):
            items = [line, self.model.class_values[int(result.predicted[i])]]
            if result.ambiguous is not None and bool(result.ambiguous[i]):
                items.append("ambiguous")
            out.append(self.delim.join(items))
        return out

    def warmup(self, pad_to: int) -> None:
        self.compile_keys.add((pad_to,))
        self._score_ds(_blank_ds(self.enc, pad_to))


# ---------------------------------------------------------------------------
# logistic regression
# ---------------------------------------------------------------------------

class LogisticServable(ServableModel):
    """Online LR scoring from the coefficient-history artifact.  The
    reference never had an LR scoring job (coefficients went to generic
    chombo tooling), so the response format is this port's own:
    ``<request row>,<0|1>,<probability .6f>``."""

    family = "logistic"

    def __init__(self, weights: np.ndarray, encoder: DatasetEncoder,
                 delim: str = ",", threshold: float = 0.5):
        import jax.numpy as jnp

        super().__init__()
        self.enc = encoder
        self.delim = delim
        self.threshold = threshold
        self.weights = jnp.asarray(np.asarray(weights), jnp.float32)

    @classmethod
    def from_conf(cls, conf: JobConfig) -> "LogisticServable":
        from avenir_tpu.models import logistic as mlr

        path = conf.get("coeff.file.path")
        if not path:
            raise ConfigError("serving logistic requires coeff.file.path")
        model = mlr.LogisticRegressionModel.from_history_lines(
            read_lines(path), delim=conf.field_delim)
        return cls(model.weights, _complete_encoder(conf),
                   delim=conf.field_delim,
                   threshold=conf.get_float("decision.threshold", 0.5))

    def _design(self, ds: EncodedDataset) -> np.ndarray:
        from avenir_tpu.models import logistic as mlr

        x = mlr.design_matrix(ds)
        if x.shape[1] != self.weights.shape[0]:
            raise ConfigError(
                f"design width {x.shape[1]} != coefficient count "
                f"{self.weights.shape[0]} — the schema does not match the "
                f"one the coefficients were trained under")
        return x

    def score_lines(self, lines: Sequence[str], pad_to: int) -> List[str]:
        from avenir_tpu.models import logistic as mlr

        rows = _parse_rows(lines, self.delim, self.enc.max_ordinal(False))
        x = self._design(self.enc.transform(rows, with_labels=False))
        x = np.pad(x, ((0, pad_to - x.shape[0]), (0, 0)))
        self.compile_keys.add((pad_to,))
        probs, pred = mlr.predict_batch(self.weights, x,
                                        threshold=self.threshold)
        return [f"{line}{self.delim}{int(pred[i])}{self.delim}{probs[i]:.6f}"
                for i, line in enumerate(lines)]

    def warmup(self, pad_to: int) -> None:
        from avenir_tpu.models import logistic as mlr

        self.compile_keys.add((pad_to,))
        mlr.predict_batch(self.weights,
                          np.zeros((pad_to, int(self.weights.shape[0])),
                                   np.float32),
                          threshold=self.threshold)


# ---------------------------------------------------------------------------
# decision tree
# ---------------------------------------------------------------------------

class TreeServable(ServableModel):
    """DecisionTreeBuilder's scoring mode online: the saved JSON model (with
    its embedded train-time encoder state) drives the jitted node walker;
    response line = ``<fields...>,<predictedClass>`` exactly as
    jobs/tree.py::_predict writes it."""

    family = "tree"

    def __init__(self, model, encoder: DatasetEncoder, delim: str = ","):
        from avenir_tpu.models import tree as dtree

        super().__init__()
        self.model = model
        self.enc = encoder
        self.delim = delim
        self.walk = dtree.predict_fn(model)   # holds device-resident tables
        # the walker's arrays pad to pow-2 depth/node/segment buckets and
        # the compiled program keys on those SHAPES (models/tree.py::
        # _tree_walk), so the compile key carries the bucket signature:
        # a hot-swap onto a retrained tree inside the same buckets is
        # provably recompile-free (the monitor sees no fresh key and the
        # walker's jit cache is reused), while a bucket change is counted
        self._shape_sig = dtree.predict_shape_signature(model)

    @classmethod
    def from_conf(cls, conf: JobConfig) -> "TreeServable":
        import json

        from avenir_tpu.models import tree as dtree

        path = conf.get("tree.model.file.path")
        if not path:
            raise ConfigError("serving tree requires tree.model.file.path")
        model_lines = read_lines(path)
        model = dtree.DecisionTreeModel.from_string(model_lines[0])
        enc = Job.encoder_for(conf)
        if len(model_lines) > 1:
            enc.load_state_dict(json.loads(model_lines[1])["encoder"])
        elif not (enc.schema_complete(with_labels=False) and enc.class_values):
            raise ConfigError(
                "tree model file has no encoder-state line and the schema "
                "does not fully specify the encoding — re-train with this "
                "version to embed encoder state")
        return cls(model, enc, delim=conf.field_delim)

    def score_lines(self, lines: Sequence[str], pad_to: int) -> List[str]:
        import jax.numpy as jnp

        rows = _parse_rows(lines, self.delim, self.enc.max_ordinal(False))
        ds = _pad_ds(self.enc.transform(rows, with_labels=False), pad_to)
        self.compile_keys.add((pad_to,) + self._shape_sig)
        pred, _distr = self.walk(jnp.asarray(ds.codes))
        pred = np.asarray(pred)
        return [self.delim.join(list(r) + [self.model.class_values[int(p)]])
                for r, p in zip(rows, pred[:len(lines)])]

    def warmup(self, pad_to: int) -> None:
        import jax.numpy as jnp

        self.compile_keys.add((pad_to,) + self._shape_sig)
        self.walk(jnp.asarray(_blank_ds(self.enc, pad_to).codes))


# ---------------------------------------------------------------------------
# k nearest neighbors
# ---------------------------------------------------------------------------

class KNNServable(ServableModel):
    """NearestNeighbor classification online: the reference set is uploaded
    once (KNNModel caches its device tiles across queries), requests score
    through the same tiled top-k + kernel-weighted vote the batch job runs;
    response line = ``<request row>,<predictedClass>``.  Regression mode
    stays batch-only (it needs per-call input-variable columns)."""

    family = "knn"

    def __init__(self, est, model, encoder: DatasetEncoder, delim: str = ","):
        super().__init__()
        self.est = est
        self.model = model
        self.enc = encoder
        self.delim = delim

    @classmethod
    def from_conf(cls, conf: JobConfig) -> "KNNServable":
        from avenir_tpu.jobs.bayesian import _cost_matrix
        from avenir_tpu.models import knn as mknn
        from avenir_tpu.models import naive_bayes as nb

        train_path = conf.get("training.data.path")
        if not train_path:
            raise ConfigError("serving knn requires training.data.path")
        enc, train_ds, _rows = Job.encode_input(conf, train_path,
                                                need_rows=False)
        class_cond = (conf.get_bool("class.condition.weighted", False)
                      or conf.get_bool("class.condtion.weighted", False))
        class_probs = None
        if class_cond:
            model_path = conf.get("bayesian.model.file.path")
            if not model_path:
                raise ConfigError("class-conditional weighting requires "
                                  "bayesian.model.file.path")
            bayes = nb.model_from_lines(read_lines(model_path), enc,
                                        delim=conf.field_delim)
            class_probs = nb.NaiveBayes().predict(bayes, train_ds).probs
        cost = (_cost_matrix(conf, train_ds.class_values)
                if conf.get_bool("use.cost.based.classifier") else None)
        est = mknn.KNN(
            k=conf.get_int("top.match.count", 10),
            kernel=conf.get("kernel.function", "none"),
            kernel_sigma=conf.get_float("kernel.param", 0.3),
            inverse_distance=conf.get_bool("inverse.distance.weighted", False),
            class_cond_weighting=class_cond,
            decision_threshold=conf.get_float("decision.threshold"),
            pos_class=conf.get("positive.class.value"),
            cost=cost,
            search_mode=conf.get("knn.search.mode", "exact"),
            mesh=Job.auto_mesh(conf),      # the batch job's own placement
        )
        model = est.fit(train_ds, class_probs=class_probs)
        return cls(est, model, enc, delim=conf.field_delim)

    def score_lines(self, lines: Sequence[str], pad_to: int) -> List[str]:
        rows = _parse_rows(lines, self.delim, self.enc.max_ordinal(False))
        ds = _pad_ds(self.enc.transform(rows, with_labels=False), pad_to)
        self.compile_keys.add((pad_to,))
        result = self.est.predict(self.model, ds)
        return [
            f"{line}{self.delim}"
            f"{self.model.class_values[int(result.predicted[i])]}"
            for i, line in enumerate(lines)]

    def warmup(self, pad_to: int) -> None:
        self.compile_keys.add((pad_to,))
        self.est.predict(self.model, _blank_ds(self.enc, pad_to))


# ---------------------------------------------------------------------------
# Markov / Viterbi
# ---------------------------------------------------------------------------

class ViterbiServable(ServableModel):
    """ViterbiStatePredictor online: request rows are ``id[,...],obs,...``
    sequences (``skip.field.count`` leading id fields), decoded against a
    FIXED time axis (``serve.sequence.pad.len``) so every bucket compiles
    one [bucket, padLen] program — padded steps are max-plus identities, so
    paths are byte-identical to the batch job's variable-length decode.
    Response line matches the job: ``id,state,...`` (or ``obs:state`` pairs
    under ``output.state.only=false``)."""

    family = "viterbi"

    def __init__(self, predictor, delim: str = ",", in_delim: str = ",",
                 skip: int = 1, pad_len: int = 64):
        super().__init__()
        self.predictor = predictor
        self.delim = delim
        self.in_delim = in_delim          # the job's field.delim.regex split
        self.skip = max(int(skip), 1)
        self.pad_len = int(pad_len)
        self._known = set(predictor.decoder.model.observations)

    @classmethod
    def from_conf(cls, conf: JobConfig) -> "ViterbiServable":
        from avenir_tpu.models import markov as mk

        path = (conf.get("hmm.model.file.path")
                or conf.get("model.file.path"))
        if not path:
            raise ConfigError("serving viterbi requires hmm.model.file.path")
        model = mk.HMMModel.from_lines(read_lines(path),
                                       delim=conf.field_delim)
        predictor = mk.ViterbiStatePredictor(
            model, mesh=Job.auto_mesh(conf),
            pair_output=not conf.get_bool("output.state.only", True),
            delim=conf.field_delim)
        return cls(predictor, delim=conf.field_delim,
                   in_delim=conf.field_delim_regex,
                   skip=conf.get_int("skip.field.count", 1),
                   pad_len=conf.get_int("serve.sequence.pad.len", 64))

    def _rows(self, lines: Sequence[str]) -> List[List[str]]:
        rows = []
        for line in lines:
            parts = line.split(self.in_delim)
            if len(parts) <= self.skip:
                raise RequestError(
                    f"sequence row needs at least {self.skip + 1} fields "
                    f"(ids + one observation): {line!r}")
            seq = [t for t in parts[self.skip:] if t != ""]
            if len(seq) > self.pad_len:
                raise RequestError(
                    f"sequence of {len(seq)} observations exceeds "
                    f"serve.sequence.pad.len={self.pad_len}")
            unknown = [t for t in seq if t not in self._known]
            if unknown:
                raise RequestError(
                    f"unknown observation symbol(s) {unknown[:3]} — model "
                    f"vocabulary has {len(self._known)} symbols")
            rows.append([self.delim.join(parts[:self.skip])] + seq)
        return rows

    def score_lines(self, lines: Sequence[str], pad_to: int) -> List[str]:
        rows = self._rows(lines)
        rows += [[""] for _ in range(pad_to - len(rows))]   # empty-seq pads
        self.compile_keys.add((pad_to, self.pad_len))
        return self.predictor.predict_lines(rows,
                                            pad_to=self.pad_len)[:len(lines)]

    def warmup(self, pad_to: int) -> None:
        self.compile_keys.add((pad_to, self.pad_len))
        self.predictor.predict_lines([[""] for _ in range(pad_to)],
                                     pad_to=self.pad_len)


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

FAMILIES: Dict[str, type] = {
    cls.family: cls
    for cls in (NaiveBayesServable, LogisticServable, TreeServable,
                KNNServable, ViterbiServable)
}


class ModelRegistry:
    """name → :class:`ServableModel`; the scoring plane's model namespace.

    Entries are VERSIONED: :meth:`swap` atomically replaces a loaded entry
    with a freshly built one (the drift→retrain→hot-swap seam,
    ``stream/controller.py``) and bumps the model's version.  ``get`` hands
    out the entry object itself, so a dispatch that already resolved the
    old entry finishes scoring on the old params while every later ``get``
    sees the new ones — zero-downtime swap with no request ever observing
    half a model.  Use :meth:`~avenir_tpu.serving.batcher.BucketedMicrobatcher.swap`
    rather than calling this directly under a live batcher: the batcher
    warms the incoming entry's bucket shapes BEFORE publishing it (the
    swap barrier), so the zero-steady-state-recompiles invariant survives
    the swap."""

    def __init__(self) -> None:
        import threading

        self._entries: Dict[str, ServableModel] = {}
        self._versions: Dict[str, int] = {}
        self._lock = threading.Lock()

    def add(self, name: str, entry: ServableModel) -> "ModelRegistry":
        with self._lock:
            self._entries[name] = entry
            self._versions[name] = self._versions.get(name, 0) + 1
        return self

    def get(self, name: str) -> ServableModel:
        entry = self._entries.get(name)
        if entry is None:
            from avenir_tpu.serving.errors import UnknownModelError
            raise UnknownModelError(
                f"unknown model {name!r}; loaded: {sorted(self._entries)}")
        return entry

    def swap(self, name: str, entry: ServableModel) -> int:
        """Atomically replace a LOADED entry; returns the new version.
        Swapping an unknown name raises (publish new models with ``add`` —
        a swap that silently creates a model would hide a routing typo)."""
        from avenir_tpu.serving.errors import UnknownModelError

        with self._lock:
            if name not in self._entries:
                raise UnknownModelError(
                    f"cannot swap unknown model {name!r}; loaded: "
                    f"{sorted(self._entries)}")
            self._entries[name] = entry
            self._versions[name] += 1
            return self._versions[name]

    def version(self, name: str) -> int:
        """The entry's version (1 = initial load, +1 per swap)."""
        self.get(name)                    # raises UnknownModelError
        return self._versions[name]

    def names(self) -> List[str]:
        return sorted(self._entries)

    def items(self):
        return sorted(self._entries.items())

    @classmethod
    def from_conf(cls, conf: JobConfig) -> "ModelRegistry":
        """Load every family named in ``serve.models`` from its job-contract
        artifact keys (one entry per family, named by the family id)."""
        families = conf.get_list("serve.models")
        if not families:
            raise ConfigError(
                f"serve.models not set — name the families to load "
                f"(known: {sorted(FAMILIES)})")
        registry = cls()
        for family in families:
            loader = FAMILIES.get(family)
            if loader is None:
                raise ConfigError(
                    f"unknown serving family {family!r} in serve.models "
                    f"(known: {sorted(FAMILIES)})")
            registry.add(family, loader.from_conf(conf))
        return registry

    def warmup(self, buckets: Sequence[int]) -> Dict[str, int]:
        """Compile every (model, bucket) shape up front; returns the number
        of shapes warmed per model — after this, steady-state serving must
        record zero recompiles."""
        warmed = {}
        for name, entry in self.items():
            before = len(entry.compile_keys)
            for bucket in buckets:
                entry.warmup(int(bucket))
            warmed[name] = len(entry.compile_keys) - before
        return warmed
