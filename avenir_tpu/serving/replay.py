"""ScoringPlane job — the pipeline driver's ``serve`` stage.

Replays a CSV artifact through the ONLINE scoring plane (registry +
bucketed microbatcher) and writes the responses as a batch output artifact.
Two uses:

- in a :class:`~avenir_tpu.pipeline.driver.Pipeline`, a trained artifact
  hands off to serving in the same DAG (``Stage("serve", "ScoringPlane",
  input="test", output="scored", props={"serve.models": "naiveBayes",
  "bayesian.model.file.path": "@bayes_model"}, uses=("bayes_model",))``);
- as the parity oracle: the replay output must be byte-identical to the
  corresponding batch predictor job's output on the same rows
  (tests/test_serving.py asserts it for every family).

In-flight requests are capped below the queue depth, so a replay can never
shed against itself — backpressure is for *concurrent* online clients.
"""

from __future__ import annotations

from collections import deque

from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.jobs.base import Job, read_lines, write_output
from avenir_tpu.utils.metrics import Counters


class ScoringPlane(Job):
    """Replay ``input`` through the serving plane for ``serve.replay.model``
    (defaults to the single loaded family); merges the serving counters —
    requests, batch-size histogram, recompiles — into the job counters."""

    name = "ScoringPlane"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        from avenir_tpu.serving.batcher import BucketedMicrobatcher
        from avenir_tpu.serving.registry import ModelRegistry

        registry = ModelRegistry.from_conf(conf)
        model = conf.get("serve.replay.model")
        if not model:
            names = registry.names()
            if len(names) != 1:
                raise ConfigError(
                    f"serve.replay.model must pick one of the loaded "
                    f"models {names}")
            model = names[0]
        batcher = BucketedMicrobatcher.from_conf(registry, conf)
        lines = read_lines(input_path)
        max_inflight = max(batcher.queue_depth - 1, 1)
        from avenir_tpu.telemetry import spans as tel

        # every submit below runs inside this job's span, so each request's
        # PendingRequest captures it and the serving spans join THIS trace
        tel.tracer().event("serve.replay", model=model, rows=len(lines),
                           max_inflight=max_inflight)
        outs = [None] * len(lines)
        wait_s = batcher.request_timeout_s + 30.0
        pending = deque()
        try:
            for i, line in enumerate(lines):
                if len(pending) >= max_inflight:
                    j, req = pending.popleft()
                    outs[j] = req.wait(wait_s)
                pending.append((i, batcher.submit_nowait(model, line)))
            for j, req in pending:
                outs[j] = req.wait(wait_s)
        finally:
            batcher.close()
        write_output(output_path, outs)
        counters.merge(batcher.counters)
        counters.set("Records", "Processed", len(outs))
        for name, stats in batcher.stats().items():
            counters.set(f"Serving.{name}", "p99_us",
                         int(stats["p99_ms"] * 1000))
            counters.set(f"Serving.{name}", "p50_us",
                         int(stats["p50_ms"] * 1000))
