"""StreamGraft — the continuous-analytics plane (ROADMAP item 4).

Sliding-window SharedScan consumers over infinite row streams
(:mod:`~avenir_tpu.stream.windows`), count-distribution drift detection
(:mod:`~avenir_tpu.stream.drift`), and the drift→retrain→hot-swap
controller closing the train→deploy loop through ServeGraft
(:mod:`~avenir_tpu.stream.controller`).  ``StreamAnalytics``
(:mod:`~avenir_tpu.stream.job`) is the pipeline-stage face.
"""

from avenir_tpu.stream.controller import RETRAIN_JOBS, DriftRetrainController
from avenir_tpu.stream.drift import DriftDetector, DriftEvent
from avenir_tpu.stream.job import StreamAnalytics, consumers_from_conf
from avenir_tpu.stream.windows import (
    ClassDistributionConsumer,
    WindowCheckpointer,
    WindowedScan,
    WindowResult,
)

__all__ = [
    "ClassDistributionConsumer",
    "DriftDetector",
    "DriftEvent",
    "DriftRetrainController",
    "RETRAIN_JOBS",
    "StreamAnalytics",
    "WindowCheckpointer",
    "WindowedScan",
    "WindowResult",
    "consumers_from_conf",
]
