"""Drift→retrain→hot-swap — closing the train→deploy loop online.

The reference closes this loop only for Storm RL (the learner updates in
the bolt); every supervised model retrains offline and redeploys by hand.
:class:`DriftRetrainController` automates the supervised case end to end:

1. every completed window flows through the :class:`~avenir_tpu.stream.drift.DriftDetector`;
2. on SUSTAINED drift, the controller writes the window's retained rows to
   a per-event workspace under ``stream.retrain.dir`` and runs the model's
   OWN batch fit job over them (the same job a pipeline stage runs — not a
   shadow trainer, so the retrained artifact is byte-compatible with every
   offline tool);
3. the fresh artifact is loaded through the family's servable loader and
   hot-swapped into the live scoring plane via the batcher's swap barrier
   (:meth:`~avenir_tpu.serving.batcher.BucketedMicrobatcher.swap`):
   the incoming entry's bucket shapes compile BEFORE publish, in-flight
   requests finish on the old params, and the registry version bumps.

Drift-to-swap latency is measured per event (``last_swap_s``) and published
by ``benchmarks/streaming_soak.py``.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.stream.drift import DriftDetector, DriftEvent
from avenir_tpu.stream.windows import WindowResult
from avenir_tpu.telemetry import spans as tel
from avenir_tpu.utils.metrics import Counters

# family → (batch fit job, the artifact key its servable loader reads) —
# the SAME job/key contract ServeGraft's registry documents, so a retrain
# artifact is indistinguishable from a pipeline stage's output
RETRAIN_JOBS = {
    "naiveBayes": ("BayesianDistribution", "bayesian.model.file.path"),
    "logistic": ("LogisticRegressionJob", "coeff.file.path"),
    "tree": ("DecisionTreeBuilder", "tree.model.file.path"),
}


class DriftRetrainController:
    """Window tap: detector → batch refit over retained rows → hot-swap."""

    def __init__(self, conf: JobConfig, batcher, detector: DriftDetector,
                 model: Optional[str] = None,
                 counters: Optional[Counters] = None):
        self.conf = conf
        self.batcher = batcher
        self.detector = detector
        self.model = model or conf.get("stream.retrain.model", "naiveBayes")
        self.workdir = conf.get("stream.retrain.dir")
        if not self.workdir:
            raise ConfigError(
                "drift retraining requires stream.retrain.dir (the "
                "workspace retrain inputs and artifacts are staged under)")
        family = batcher.registry.get(self.model).family
        if family not in RETRAIN_JOBS:
            raise ConfigError(
                f"no retrain job mapped for serving family {family!r}; "
                f"retrainable: {sorted(RETRAIN_JOBS)}")
        self.family = family
        self.job_name, self.artifact_key = RETRAIN_JOBS[family]
        self.counters = counters if counters is not None else Counters()
        self.swaps = 0
        self.last_swap_s: Optional[float] = None
        self.last_version: Optional[int] = None

    def on_window(self, window: WindowResult) -> Optional[int]:
        """Feed one completed window; returns the new model version when
        this window tripped a retrain+swap, else None.

        The firing is committed into the detector (rebase + streak reset)
        only AFTER the retrain+swap landed: a deferred or failed response
        leaves the firing unconsumed, so a one-time step change keeps
        re-firing on subsequent (fully-retained) windows instead of
        silently becoming the new reference with the stale model still
        serving."""
        event = self.detector.update(window, commit=False)
        if event is None:
            return None
        try:
            version = self.retrain_and_swap(window, event)
        except ConfigError:
            raise                    # misconfiguration never self-heals
        except Exception as exc:
            # a transient retrain/load/swap failure (full disk, malformed
            # artifact, warmup OOM) must not kill the live analytics
            # plane: the firing stays unconsumed, so sustained drift
            # re-fires on the next window against the old reference
            self.counters.increment("Stream", "retrain.failed")
            tel.tracer().event("drift.retrain.failed", window=window.index,
                               model=self.model,
                               error=f"{type(exc).__name__}: {exc}")
            return None
        if version is not None:
            self.detector.commit_fire(window.tables)
        return version

    def _artifact_value(self, artifact: str) -> str:
        """What ``self.artifact_key`` must point at for this family — THE
        single definition shared by the fit conf and the servable-loader
        conf, so the swap always loads exactly what the retrain wrote."""
        if self.family == "logistic":
            # the LR job WRITES through its artifact key rather than the
            # output path
            return os.path.join(artifact, "coeff.txt")
        return artifact

    def _train_conf(self, artifact: str) -> JobConfig:
        """A minimal batch-fit conf derived from the live one.  Keys that
        must NOT leak from the serving/stream conf into the fit: the
        family's own artifact key (a set ``tree.model.file.path`` flips
        DecisionTreeBuilder into its PREDICT mode — the retrain would
        score rows with the old model instead of training), and the live
        stream's durability/fault keys (a set ``stream.checkpoint.dir``
        would point the fit's own StreamCheckpointer at the stream's
        pane-ring snapshot directory — tag conflict or sweep either way)."""
        drop = {self.artifact_key, "stream.checkpoint.dir", "stream.resume",
                "stream.fault.crash.after.chunks",
                "stream.fault.crash.after.panes"}
        # JobConfig accepts every key both bare and prefix-namespaced
        # (``avenir.tree.model.file.path`` == ``tree.model.file.path``), so
        # the namespaced spelling leaks through a bare-only drop set
        drop |= {f"{self.conf.prefix}.{k}" for k in tuple(drop)}
        conf = JobConfig({k: v for k, v in self.conf.props.items()
                          if k not in drop}, prefix=self.conf.prefix)
        if self.family == "logistic":
            conf.set(self.artifact_key, self._artifact_value(artifact))
        return conf

    def retrain_and_swap(self, window: WindowResult,
                         event: DriftEvent) -> Optional[int]:
        """The drift response: batch fit over the window's rows, publish,
        swap.  Raises if the scan does not retain rows at all — a detector
        wired to a retraining controller needs
        ``WindowedScan(retain_rows=True)``.  A retaining window whose raw
        rows are nevertheless missing (it contains panes restored from a
        checkpoint — snapshots persist counts, not rows) DEFERS instead:
        the firing is dropped, and genuinely sustained drift re-fires
        against the rebased reference on fully-retained windows."""
        if not window.lines:
            if not window.retained:
                raise ConfigError(
                    "drift fired but the scan does not retain rows — "
                    "construct the WindowedScan with retain_rows=True "
                    "(stream.retain.rows) when a DriftRetrainController "
                    "is attached")
            self.counters.increment("Stream", "retrain.deferred")
            return None
        from avenir_tpu.jobs import get_job          # lazy: avoid the cycle
        from avenir_tpu.serving.registry import FAMILIES

        t0 = time.perf_counter()
        # workspace per firing, keyed by window index (monotonic within a
        # run; two firings can never share a window)
        stage_dir = os.path.join(self.workdir, f"retrain-w{window.index}")
        os.makedirs(stage_dir, exist_ok=True)
        input_path = os.path.join(stage_dir, "input.csv")
        with open(input_path, "w") as fh:
            for line in window.lines:
                fh.write(line)
                fh.write("\n")
        artifact = os.path.join(stage_dir, "model")
        get_job(self.job_name).run(self._train_conf(artifact), input_path,
                                   artifact)
        serve_conf = JobConfig(dict(self.conf.props), prefix=self.conf.prefix)
        serve_conf.set(self.artifact_key, self._artifact_value(artifact))
        entry = FAMILIES[self.family].from_conf(serve_conf)
        version = self.batcher.swap(
            self.model, entry,
            warm=self.conf.get_bool("serve.swap.warmup", True))
        dur = time.perf_counter() - t0
        self.swaps += 1
        self.last_swap_s = dur
        self.last_version = version
        self.counters.increment("Stream", "retrains")
        tel.tracer().event("drift.retrain", window=window.index,
                           model=self.model, version=version,
                           rows=len(window.lines), dur_ms=round(dur * 1e3, 3))
        return version
