"""Distribution-drift detection over windowed count tables.

The detector never touches rows: it reads the class-count vector and the
per-feature bin marginals ALREADY aggregated for the window's consumers
(``ScanTables`` — counts held on device once, folded to host int64), so
drift detection is a handful of tiny host-side vector ops per window.

Divergence metrics (``stream.drift.metric``):

- ``js``  — Jensen–Shannon divergence (log2, so bounded in [0, 1]) between
  the window's distribution and the reference window's;
- ``chisquare`` — a scale-free Pearson form over the probability vectors,
  Σ (p−q)²/q (the counts' chi-square statistic divided by n).

The score is the MAX over the monitored distributions
(``stream.drift.source``: the class distribution, every feature's bin
marginal, or both) — drift in any single feature is drift.

Hysteresis: a window past ``stream.drift.threshold`` extends a streak; only
``stream.drift.min.windows`` CONSECUTIVE drifted windows fire a
:class:`DriftEvent` (one noisy window never triggers a retrain).  On fire,
the reference rebases to the firing window — the new regime becomes normal
— and the streak resets.  Every scored window journals a ``drift.window``
event; a fire journals ``drift.detected`` (GraftTrace schema,
docs/observability.md).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.pipeline.scan import ScanTables
from avenir_tpu.telemetry import spans as tel
from avenir_tpu.utils.metrics import Counters

_EPS = 1e-12

METRICS = ("js", "chisquare")
SOURCES = ("class", "features", "both")


def js_divergence(p: np.ndarray, q: np.ndarray) -> float:
    """Jensen–Shannon divergence between two probability vectors (log2)."""
    p = np.asarray(p, np.float64)
    q = np.asarray(q, np.float64)
    m = 0.5 * (p + q)

    def kl(a, b):
        mask = a > 0
        return float(np.sum(a[mask] * np.log2(a[mask] / np.maximum(b[mask],
                                                                   _EPS))))

    return 0.5 * kl(p, m) + 0.5 * kl(q, m)


def chisquare_divergence(p: np.ndarray, q: np.ndarray) -> float:
    """Σ (p−q)²/q over probability vectors — the Pearson statistic of the
    window counts against the reference distribution, divided by n.

    Both vectors are additively smoothed (half a pseudo-count spread over
    the support) before the division: a category present in the window
    but absent from the sampled reference window must read as moderate
    divergence, not an ε-denominator blow-up that fires the detector on a
    single rare-category row."""
    p = np.asarray(p, np.float64)
    q = np.asarray(q, np.float64)
    k = max(len(q), 1)
    alpha = 0.5 / k
    p = (p + alpha) / (1.0 + 0.5)
    q = (q + alpha) / (1.0 + 0.5)
    return float(np.sum((p - q) ** 2 / q))


_METRIC_FNS = {"js": js_divergence, "chisquare": chisquare_divergence}


@dataclass
class DriftEvent:
    """A sustained-drift firing: the window that tripped it, the score, and
    how many consecutive windows exceeded the threshold."""

    window: int
    divergence: float
    streak: int
    threshold: float


class DriftDetector:
    """Per-window divergence against a reference window, with hysteresis.

    The FIRST non-empty window becomes the reference; each later non-empty
    window is scored against it.  ``update`` returns a :class:`DriftEvent`
    when drift is sustained, else None.  Empty windows neither score nor
    extend the streak (no rows = no evidence)."""

    def __init__(self, threshold: float, min_windows: int = 2,
                 metric: str = "js", source: str = "both",
                 counters: Optional[Counters] = None):
        if metric not in _METRIC_FNS:
            raise ConfigError(
                f"unknown stream.drift.metric {metric!r}; known: {METRICS}")
        if source not in SOURCES:
            raise ConfigError(
                f"unknown stream.drift.source {source!r}; known: {SOURCES}")
        if threshold <= 0:
            raise ConfigError(
                f"stream.drift.threshold must be > 0, got {threshold}")
        self.threshold = float(threshold)
        self.min_windows = max(int(min_windows), 1)
        self.metric = metric
        self.source = source
        self.counters = counters if counters is not None else Counters()
        self.streak = 0
        self.fired = 0
        self.last_divergence: Optional[float] = None
        self._reference: Optional[List[np.ndarray]] = None

    @classmethod
    def from_conf(cls, conf: JobConfig,
                  counters: Optional[Counters] = None
                  ) -> Optional["DriftDetector"]:
        """A detector when ``stream.drift.threshold`` is set; else None."""
        threshold = conf.get_float("stream.drift.threshold")
        if threshold is None:
            return None
        return cls(threshold,
                   min_windows=conf.get_int("stream.drift.min.windows", 2),
                   metric=conf.get("stream.drift.metric", "js"),
                   source=conf.get("stream.drift.source", "both"),
                   counters=counters)

    # -- distributions --------------------------------------------------------
    def _distributions(self, tables: ScanTables) -> List[np.ndarray]:
        """The monitored probability vectors of one window, in a fixed
        order: [class?, feature 0 marginal?, feature 1 marginal?, ...].

        ``source="features"`` with no [F, B, C] table in the window is a
        LOUD error: it means no registered consumer aggregates feature
        counts, so the detector would score 0.0 forever while the operator
        believes covariate-shift monitoring is armed.  ``source="both"``
        degrades to class-only in that case by design (class counts are
        always aggregated) — documented in docs/jobs.md."""
        if self.source == "features" and tables.fbc is None:
            raise ConfigError(
                "stream.drift.source=features but no registered consumer "
                "aggregates the [F, B, C] feature count table — add a "
                "counting consumer (naiveBayes / mutualInfo / cramer) to "
                "stream.consumers, or monitor source=class")
        out: List[np.ndarray] = []
        if self.source in ("class", "both"):
            counts = np.asarray(tables.class_counts, np.float64)
            out.append(counts / max(counts.sum(), _EPS))
        if self.source in ("features", "both") and tables.fbc is not None:
            marginals = np.asarray(tables.fbc, np.float64).sum(axis=2)  # [F,B]
            for i in range(marginals.shape[0]):
                row = marginals[i, :int(tables.meta.n_bins[i])]
                out.append(row / max(row.sum(), _EPS))
        return out

    def divergence(self, tables: ScanTables) -> float:
        """Max divergence of this window's distributions vs the reference
        (0.0 before a reference exists)."""
        if self._reference is None:
            return 0.0
        fn = _METRIC_FNS[self.metric]
        return max((fn(p, q) for p, q in
                    zip(self._distributions(tables), self._reference)),
                   default=0.0)

    def rebase(self, tables: ScanTables) -> None:
        """Make this window the reference distribution (initial window, or
        the post-retrain regime)."""
        self._reference = self._distributions(tables)

    # -- checkpointable state (rides the WindowCheckpointer snapshot) ---------
    def state(self) -> dict:
        """Reference distributions + hysteresis cursors — everything a
        resumed stream needs so its drift sequence matches an
        uninterrupted run's over the remaining windows."""
        return {
            "streak": self.streak,
            "fired": self.fired,
            "last": self.last_divergence,
            "reference": (list(self._reference)
                          if self._reference is not None else None),
        }

    def load(self, state: dict) -> None:
        self.streak = int(state["streak"])
        self.fired = int(state["fired"])
        last = state["last"]
        self.last_divergence = None if last is None else float(last)
        ref = state["reference"]
        self._reference = ([np.asarray(r) for r in ref]
                           if ref is not None else None)

    # -- the per-window step --------------------------------------------------
    def update(self, window, commit: bool = True) -> Optional[DriftEvent]:
        """Score one :class:`~avenir_tpu.stream.windows.WindowResult`;
        returns a :class:`DriftEvent` when drift is sustained.

        ``commit=False`` leaves the firing UNCONSUMED: the reference does
        not rebase and the streak keeps growing, so the very next drifted
        window fires again.  A caller whose drift response can fail or
        defer (the retrain controller) scores with ``commit=False`` and
        calls :meth:`commit_fire` only once the response actually landed —
        otherwise a one-time step change whose first firing was deferred
        would become the rebased "normal" and never re-fire."""
        if window.rows == 0:
            # no evidence — reset the published score so a consumer of
            # per-window drift lines never reads the PREVIOUS window's
            # divergence attributed to this one
            self.last_divergence = 0.0
            return None
        if self._reference is None:
            self.rebase(window.tables)
            self.last_divergence = 0.0
            return None
        d = self.divergence(window.tables)
        self.last_divergence = d
        drifted = d > self.threshold
        self.streak = self.streak + 1 if drifted else 0
        tel.tracer().event("drift.window", window=window.index,
                           divergence=round(d, 6),
                           threshold=self.threshold, streak=self.streak)
        if self.streak < self.min_windows:
            return None
        event = DriftEvent(window=window.index, divergence=d,
                           streak=self.streak, threshold=self.threshold)
        self.fired += 1
        self.counters.increment("Stream", "drift.detected")
        tel.tracer().event("drift.detected", window=window.index,
                           divergence=round(d, 6),
                           threshold=self.threshold, windows=self.streak)
        if commit:
            self.commit_fire(window.tables)
        return event

    def commit_fire(self, tables: ScanTables) -> None:
        """Consume a firing: the drifted regime becomes the new normal
        (without a rebase the detector would re-fire every window forever)
        and the streak resets."""
        self.rebase(tables)
        self.streak = 0
