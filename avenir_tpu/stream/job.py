"""StreamAnalytics job — windowed streaming analytics as a pipeline stage.

Replays a CSV artifact through :class:`~avenir_tpu.stream.windows.WindowedScan`
via the in-proc queue transport (the same push/pop surface a live RESP
source drives), and writes one deterministic summary block per window:
window identity, the class distribution, and — when a drift threshold is
configured — the window's divergence and detection state.  The job is the
batch-replayable face of the continuous plane: the same windows a live
stream would emit, reproducible from a file (and the seam the
kill-and-resume tests drive).

No reference analog: the reference cannot express continuous sliding-window
analytics at all — its statistics jobs are whole-file batch scans (SURVEY
§0); its only online path is the Storm RL topology.
"""

from __future__ import annotations

import os
from typing import List

from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.jobs.base import Job, output_target
from avenir_tpu.pipeline import scan
from avenir_tpu.pipeline.streaming import InProcQueue
from avenir_tpu.stream.drift import DriftDetector
from avenir_tpu.stream.windows import (
    ClassDistributionConsumer,
    WindowCheckpointer,
    WindowedScan,
)
from avenir_tpu.utils.metrics import Counters

# stream.consumers ids → consumer factories (conf-parameterized where the
# batch job is)
CONSUMER_IDS = ("classDistribution", "naiveBayes", "mutualInfo", "cramer",
                "fisher")


def consumers_from_conf(conf: JobConfig) -> List[scan.ScanConsumer]:
    out: List[scan.ScanConsumer] = []
    for cid in conf.get_list("stream.consumers", ["classDistribution"]):
        if cid == "classDistribution":
            out.append(ClassDistributionConsumer(name=cid))
        elif cid == "naiveBayes":
            out.append(scan.NaiveBayesConsumer(
                laplace=conf.get_float("laplace.smoothing", 1.0), name=cid))
        elif cid == "mutualInfo":
            out.append(scan.MutualInfoConsumer(name=cid))
        elif cid == "cramer":
            out.append(scan.CorrelationConsumer(against_class=True, name=cid))
        elif cid == "fisher":
            out.append(scan.FisherConsumer(name=cid))
        else:
            raise ConfigError(
                f"unknown stream consumer {cid!r}; known: {CONSUMER_IDS}")
    return out


class StreamAnalytics(Job):
    """Windowed scan replay: ``input`` rows → per-window summary lines."""

    name = "StreamAnalytics"

    def execute(self, conf: JobConfig, input_path: str, output_path: str,
                counters: Counters) -> None:
        enc = self.encoder_for(conf)
        pane_rows = conf.get_int("stream.pane.rows", 1024)
        window_panes = conf.get_int("stream.window.panes", 1)
        from avenir_tpu.parallel.shard import ShardSpec

        shard = ShardSpec.from_conf(conf)
        if shard is not None:
            shard.announce()     # journal the hardware identity (round 12)
        detector = DriftDetector.from_conf(conf, counters)
        # one conf-driven fault plan shared by every seam (round 16):
        # fold boundaries (WindowedScan) and checkpoint save/restore
        # (WindowCheckpointer) count against the same schedule
        from avenir_tpu.utils.retry import FaultPlan

        fault = FaultPlan.from_conf(conf)
        ckpt = WindowCheckpointer.from_conf(conf, fault=fault)
        if ckpt is not None and detector is not None:
            # the detector's reference/streak ride the ring snapshot: the
            # on_window callback below runs at EMISSION, before the pane's
            # snapshot, so a resumed run's drift sequence is byte-identical
            # to an uninterrupted one
            ckpt.attach("drift", detector)
        delim = conf.field_delim
        # CrossGraft: under a global shard plan every process folds the
        # same windows to the same replicated totals — single-writer
        # output protocol (process 0 writes; non-writers stream to
        # devnull).  _window_lines still runs EVERYWHERE: it advances the
        # drift detector, whose state rides each process's checkpoint
        # snapshot — skipping it on non-writers would desynchronize the
        # replicated detector state the elastic resume relies on
        writer = self.is_output_writer()

        def handle(window):
            for ln in self._window_lines(window, detector, delim):
                out_fh.write(ln)
                out_fh.write("\n")

        ws = WindowedScan(
            enc, consumers_from_conf(conf), pane_rows,
            window_panes=window_panes,
            slide_panes=conf.get_int("stream.slide.panes", window_panes),
            delim=conf.field_delim_regex,
            mesh=None if shard is not None else self.auto_mesh(conf),
            shard=shard,
            pad_pow2=conf.get_bool("stream.pane.pad.pow2", True),
            retain_rows=conf.get_bool("stream.retain.rows", False),
            counters=counters, checkpointer=ckpt,
            crash_after_panes=conf.get_int("stream.fault.crash.after.panes",
                                           0),
            on_window=handle, fault=fault,
            pack_on=conf.get_bool("scan.pack.on", True),
            pack_max_width=conf.get_int("scan.pack.max.width", 0) or None)
        skip = ckpt.restore_into(ws) if ckpt is not None else 0
        if conf.get_bool("stream.warmup.on.start", True):
            ws.warm()
        queue = InProcQueue(conf.get_int("stream.queue.depth",
                                         InProcQueue.DEFAULT_DEPTH))
        # window blocks stream to a sibling .inprogress file as they close
        # (output-side memory stays O(window) like the input side), renamed
        # into the real artifact only on clean completion: a failed run
        # leaves no output path the driver's resume-skip could mistake for
        # a completed stage, and never truncates a previous good artifact
        tmp_path = output_path.rstrip(os.sep) + ".inprogress"
        parent = os.path.dirname(tmp_path)
        if parent and writer:
            os.makedirs(parent, exist_ok=True)
        out_fh = open(tmp_path, "w") if writer else open(os.devnull, "w")
        step = max(min(queue.depth or pane_rows, pane_rows), 1)
        batch: List[str] = []
        try:
            for line in self._iter_lines(input_path, skip):
                batch.append(line)
                if len(batch) >= step:
                    queue.push_all(batch)
                    batch.clear()
                    ws.pump(queue)
            queue.push_all(batch)
            ws.pump(queue)
            ws.flush()
        finally:
            out_fh.close()
        if writer:
            os.replace(tmp_path, output_target(output_path))
        if ckpt is not None:
            ckpt.finish()                # clean completion: sweep snapshots
        counters.set("Records", "Processed", ws.rows_consumed)

    @staticmethod
    def _iter_lines(input_path: str, skip: int):
        """Non-blank input lines after the resume cursor, streamed — the
        replay never materializes the whole artifact (the stream plane's
        O(window) memory claim holds at the job level too)."""
        from avenir_tpu.jobs.base import input_files

        seen = 0
        for path in input_files(input_path):
            with open(path) as fh:
                for raw in fh:
                    line = raw.rstrip("\r\n")
                    if not line.strip():
                        continue
                    seen += 1
                    if seen > skip:
                        yield line

    @staticmethod
    def _window_lines(window, detector, delim: str) -> List[str]:
        out = [delim.join(
            [f"w={window.index}",
             f"panes={window.first_pane}-{window.last_pane}",
             f"rows={window.rows}"])]
        summary = window.results.get("classDistribution")
        if summary is not None:
            for value, count in zip(summary["classes"], summary["counts"]):
                out.append(delim.join(
                    [f"w={window.index}", "class", value, str(int(count))]))
        if detector is not None:
            fired = detector.update(window) is not None
            div = detector.last_divergence
            out.append(delim.join(
                [f"w={window.index}", "drift",
                 f"{0.0 if div is None else div:.6f}",
                 "detected" if fired else "ok"]))
        return out


# self-registration (see the matching comment at the bottom of
# jobs/__init__.py): by the time this body line runs, avenir_tpu.jobs has
# REGISTRY/JOB_CLASSES bound no matter which side of the cycle was
# imported first
from avenir_tpu.jobs import JOB_CLASSES, REGISTRY  # noqa: E402

JOB_CLASSES.append(StreamAnalytics)
REGISTRY[StreamAnalytics.name] = StreamAnalytics
