"""StreamGraft windows — constant-memory sliding-window analytics over the
SharedScan fold.

The reference's only online path is the Storm RL topology; every analytical
statistic is a batch replay over HDFS files (SURVEY §0).  This module makes
the continuous case first-class: a :class:`WindowedScan` pulls micro-batches
of raw CSV rows from any queue transport (``pipeline/streaming.py``'s
``InProcQueue`` / ``RedisListQueue`` — the push/pop surface the reference's
spout uses), encodes them through the existing chunk path, and folds each
*pane* through :class:`~avenir_tpu.pipeline.scan.ChunkFolder` — the SAME
per-chunk gram/moments pass every batch SharedScan runs — into a
ring-buffered per-pane accumulator state.

Windows are pane-composed:

- a **pane** is ``pane_rows`` consecutive rows, folded once on arrival into
  its own fingerprinted count state (int64/float64 host totals);
- a **tumbling** window is ``window_panes`` panes with
  ``slide_panes == window_panes``;
- a **sliding** window overlaps: every ``slide_panes`` panes, the last
  ``window_panes`` pane states are merged (pure host adds of already-folded
  totals — each row is encoded and dispatched exactly ONCE no matter how
  many windows contain it, the O(1)-state incremental discipline of
  PAPERS.md's constant-memory caching applied to count analytics).

A window finalizes through the consumers' data-free constructors
(``result_from_counts`` / ``model_from_counts``), so a window's result is
byte-identical to a batch SharedScan over the same rows — the acceptance
oracle (tests/test_stream.py).  Scope of that claim: exact ALWAYS for
every count-derived table (integer accumulation); for continuous moments
the per-pane float32 partial sums merge in float64, so equality with a
single-chunk batch fold additionally needs the partial sums exact (e.g.
values on a coarse binary grid, as the tests construct) or the batch
oracle fed the same pane chunking — general real-valued data can differ
in the last float bit, exactly like any re-chunked streaming fit.

Shape discipline: panes are padded to power-of-two row buckets
(``stream.pane.pad.pow2``) with rows whose label is −1 — the row-validity
contract drops such rows from EVERY table on both the kernel and einsum
paths, so padding changes no counts while keeping the compiled-shape set
finite.  ``warm()`` pre-compiles every bucket shape and primes a
:class:`~avenir_tpu.telemetry.spans.CompileKeyMonitor`, so steady-state
streaming (ragged tail panes included) recompiles ZERO times — measured,
not assumed (``benchmarks/streaming_soak.py``).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from avenir_tpu.core.config import ConfigError, JobConfig
from avenir_tpu.core.csv_io import read_csv_string
from avenir_tpu.core.encoding import (DatasetEncoder, EncodedDataset,
                                      pad_ballast)
from avenir_tpu.ops import agg
from avenir_tpu.pipeline import scan
from avenir_tpu.telemetry import spans as tel
from avenir_tpu.utils.metrics import Counters


class ClassDistributionConsumer(scan.ScanConsumer):
    """The lightest windowed read-out: (class value → count, fraction) of
    the window — the summary the drift detector reasons over, exposed as a
    consumer so jobs can publish it per window without carrying a model."""

    needs_bin = False

    def finalize(self, t: scan.ScanTables):
        counts = np.asarray(t.class_counts, np.int64)
        total = int(counts.sum())
        return {
            "classes": list(t.meta.class_values),
            "counts": counts,
            "fractions": (counts / total if total else
                          np.zeros_like(counts, np.float64)),
            "rows": t.rows,
        }


class WindowResult:
    """One emitted window: identity, the shared tables, and every
    consumer's finalized result (``results[name]``).  ``lines`` carries the
    window's raw rows when the scan retains them (the retrain corpus);
    None otherwise — including, with ``retained`` still True, for windows
    containing panes restored from a checkpoint, whose raw rows were
    deliberately not persisted (consumers use the flag to tell "retention
    off" from "rows lost to a resume")."""

    __slots__ = ("index", "first_pane", "last_pane", "rows", "tables",
                 "results", "lines", "retained")

    def __init__(self, index: int, first_pane: int, last_pane: int,
                 rows: int, tables: scan.ScanTables,
                 results: Dict[str, Any], lines: Optional[List[str]],
                 retained: bool = False):
        self.index = index
        self.first_pane = first_pane
        self.last_pane = last_pane
        self.rows = rows
        self.tables = tables
        self.results = results
        self.lines = lines
        self.retained = retained


def _meta_ds(enc: DatasetEncoder) -> EncodedDataset:
    """Zero-row shape metadata in ``enc``'s code space — what ChunkFolder
    needs to pick its routing before any pane arrives (the streaming
    analog of ``peek_chunks``; labels present, the scan contract)."""
    nb = len(enc.binned_fields)
    return EncodedDataset(
        codes=np.zeros((0, nb), np.int32),
        cont=np.zeros((0, len(enc.cont_fields)), np.float32),
        labels=np.zeros(0, np.int32), ids=None,
        n_bins=np.array([enc.n_bins[f.ordinal] for f in enc.binned_fields],
                        np.int32),
        class_values=list(enc.class_values),
        binned_ordinals=[f.ordinal for f in enc.binned_fields],
        cont_ordinals=[f.ordinal for f in enc.cont_fields])


def _pow2_buckets(pane_rows: int) -> List[int]:
    out = [1]
    while out[-1] < pane_rows:
        out.append(out[-1] * 2)
    return out


class WindowedScan:
    """Sliding/tumbling-window SharedScan consumer over a row stream.

    ``feed(lines)`` (or ``pump(queue)``) ingests raw CSV rows; every
    ``pane_rows`` rows close a pane (encode → pad → fold); every window
    boundary merges the ring's pane states and finalizes the registered
    consumers.  Returns the :class:`WindowResult` list each call emitted.

    ``close_pane()`` force-closes the current pane regardless of fill —
    the seam for time-driven panes (a wall-clock ticker calls it on the
    period), which is also how EMPTY panes and empty windows arise.
    ``flush()`` closes a non-empty ragged tail pane at end of stream.
    """

    def __init__(self, encoder: DatasetEncoder,
                 consumers: Sequence[scan.ScanConsumer],
                 pane_rows: int, window_panes: int = 1,
                 slide_panes: Optional[int] = None, delim: str = ",",
                 mesh=None, pad_pow2: bool = True, retain_rows: bool = False,
                 counters: Optional[Counters] = None,
                 checkpointer: Optional["WindowCheckpointer"] = None,
                 crash_after_panes: int = 0, on_window=None, shard=None,
                 fault=None, pack_on: bool = True,
                 pack_max_width: Optional[int] = None):
        if not encoder.schema_complete(with_labels=True) or \
                not encoder.class_values:
            raise ConfigError(
                "windowed streaming requires a schema-complete encoder "
                "(closed vocabularies, numeric ranges, class cardinality) — "
                "a single-pass stream cannot fit a vocabulary")
        if pane_rows < 1:
            raise ConfigError(f"stream.pane.rows must be >= 1, got {pane_rows}")
        if window_panes < 1:
            raise ConfigError(
                f"stream.window.panes must be >= 1, got {window_panes}")
        slide = window_panes if slide_panes is None else int(slide_panes)
        if not 1 <= slide <= window_panes:
            raise ConfigError(
                f"stream.slide.panes must be in [1, window.panes="
                f"{window_panes}], got {slide}")
        self.enc = encoder
        self.pane_rows = int(pane_rows)
        self.window_panes = int(window_panes)
        self.slide_panes = slide
        self.delim = delim
        self.pad_pow2 = bool(pad_pow2)
        self.retain_rows = bool(retain_rows)
        self.counters = counters if counters is not None else Counters()
        self.checkpointer = checkpointer
        self.crash_after = int(crash_after_panes)
        # conf-driven fault plan (utils/retry.FaultPlan, round 16): the
        # "fold" site fires at non-empty pane fold boundaries — the
        # mid-fold kill the preemption drill injects
        self.fault = fault
        # invoked per window AT EMISSION — i.e. BEFORE the pane's
        # checkpoint snapshot is written, so state the callback mutates
        # (a drift detector attached to the checkpointer) rides the SAME
        # snapshot and a resume replays neither side twice
        self.on_window = on_window
        self.meta = _meta_ds(encoder)
        # a ShardSpec gives the pane fold the SAME mesh-sharded dispatch
        # batch SharedScan runs (windows inherit sharding through
        # ChunkFolder — no stream-side parallel code at all); the fold
        # ballast-pads each pow-2 pane on to its shard target, so the
        # compiled-shape set stays finite and warm() covers it
        # PackGraft (round 16): panes inherit block-diagonal gram packing
        # through ChunkFolder's pack planner — zero stream-side fold code
        self.folder = scan.ChunkFolder(consumers, self.meta, mesh=mesh,
                                       shard=shard, counters=self.counters,
                                       pack_on=pack_on,
                                       pack_max_width=pack_max_width)
        self.buckets = _pow2_buckets(self.pane_rows)
        self._monitor = tel.CompileKeyMonitor(self.counters, group="Stream",
                                              scope="stream.pane")
        # the ring: the last window_panes pane records — the ONLY per-row
        # state the scan retains, so memory is O(window), never O(stream)
        self._ring: deque = deque(maxlen=self.window_panes)
        self._pane_buf: List[str] = []
        self.panes_closed = 0
        self.windows_emitted = 0
        self.rows_consumed = 0            # rows in CLOSED panes (resume seam)

    # -- warmup ---------------------------------------------------------------
    def warm(self) -> int:
        """Compile every pane bucket shape on a blank fold (labels −1, so
        nothing counts) and prime the recompile monitor; after this,
        steady-state panes — ragged tails included — must recompile zero
        times.  Returns the number of shapes warmed."""
        from avenir_tpu.telemetry import profile as _profile

        prof = _profile.profiler()
        throwaway = agg.Accumulator()
        for bucket in self.buckets:
            ds = self._blank_pane(bucket)
            key = self._pane_key(ds)
            if prof.enabled:
                # AOT cost-probe BEFORE the prime: the profiler keeps the
                # FIRST (site, key) observation, and the prime registers
                # shapes-only — a packed/kernel pane must never degrade
                # to source:"shapes" just because warm() ran first
                probe = self.folder.cost_probe(ds)
                if probe is not None:
                    prof.observe(key, site=self._monitor.scope,
                                 lowerable=probe[0], args=probe[1])
            self._monitor.prime([key])
            self.folder.fold(ds, throwaway)
        return len(self.buckets)

    def _pane_key(self, ds: EncodedDataset):
        """The pane's compile/program key: dispatch shapes + the folder's
        routing tag — packed panes register under the composite
        (site, pack-signature) identity, so the roofline table attributes
        MFU to the packed dispatch and a pack-width change is a fresh
        program, not a silent recompile of the old one."""
        return tel.CompileKeyMonitor.shape_key(
            ds.codes, ds.labels, ds.cont) + (
            self.folder.program_tag or "moments",)

    def _blank_pane(self, n: int) -> EncodedDataset:
        m = self.meta
        return EncodedDataset(
            codes=np.zeros((n, m.num_binned), np.int32),
            cont=np.zeros((n, m.num_cont), np.float32),
            labels=np.full(n, -1, np.int32), ids=None,
            n_bins=m.n_bins, class_values=m.class_values,
            binned_ordinals=m.binned_ordinals, cont_ordinals=m.cont_ordinals)

    # -- ingest ---------------------------------------------------------------
    def feed(self, lines: Sequence[str]) -> List[WindowResult]:
        """Ingest raw CSV rows; returns the windows this call completed."""
        out: List[WindowResult] = []
        for line in lines:
            self._pane_buf.append(line)
            if len(self._pane_buf) >= self.pane_rows:
                out.extend(self.close_pane())
        return out

    def pump(self, queue, max_rows: Optional[int] = None
             ) -> List[WindowResult]:
        """Drain a queue transport (InProcQueue / RedisListQueue pop
        surface) into the scan; stops at queue-empty or ``max_rows``.
        Rows are drained first and fed as ONE batch — the buffered slice
        is bounded by the queue's own depth cap, and the hot ingest path
        pays one feed() call per drain instead of one per row."""
        drained: List[str] = []
        while max_rows is None or len(drained) < max_rows:
            msg = queue.pop()
            if msg is None:
                break
            drained.append(msg)
        return self.feed(drained) if drained else []

    def flush(self) -> List[WindowResult]:
        """Close a non-empty ragged tail pane (end of stream)."""
        if not self._pane_buf:
            return []
        return self.close_pane()

    def close_pane(self) -> List[WindowResult]:
        """Close the current pane (even empty — the time-driven tick),
        fold it, and emit any window ending here.

        GraftBox: a watchdog-guarded seam — a pane close that wedges
        (encode, fold, or checkpoint stuck) past ``blackbox.watchdog.sec``
        journals ``hang.detected`` and captures a forensics bundle."""
        from avenir_tpu.telemetry import blackbox

        with blackbox.watchdog_guard("pane"):
            return self._close_pane()

    def _close_pane(self) -> List[WindowResult]:
        lines = self._pane_buf
        self._pane_buf = []
        acc = agg.Accumulator()
        from avenir_tpu.telemetry import profile as _profile

        prof = _profile.profiler()
        if lines:
            if self.fault is not None:
                # mid-fold kill: the popped pane's rows are past the
                # cursor (rows_consumed counts CLOSED panes only), so a
                # resume re-feeds them — nothing is lost or double-counted
                self.fault.hit("fold")
            ds = self._encode(lines)
            ds = self._pad(ds)
            key = self._pane_key(ds)
            # the monitor's key feed doubles as the GraftProf program
            # registration (site = this monitor's scope); the cost probe
            # runs first — first observation wins, and an unwarmed pane
            # shape must still register with AOT cost where the routing
            # is single-dispatch
            if prof.enabled:
                probe = self.folder.cost_probe(ds)
                if probe is not None:
                    prof.observe(key, site=self._monitor.scope,
                                 lowerable=probe[0], args=probe[1])
            self._monitor.observe([key])
            t0 = time.perf_counter()
            self.folder.fold(ds, acc)
            if prof.enabled:
                prof.sample(key, self._monitor.scope,
                            time.perf_counter() - t0)
        if prof.enabled:
            # pane boundary: the seam where an HBM leak across stream
            # windows (pane ring growth, model hot-swap debris) shows up
            prof.sample_device_memory("pane")
        self._ring.append({"pane": self.panes_closed, "rows": len(lines),
                           "state": acc.state(),
                           "lines": list(lines) if self.retain_rows else None})
        self.panes_closed += 1
        self.rows_consumed += len(lines)
        self.counters.increment("Stream", "panes")
        self.counters.increment("Stream", "rows", len(lines))
        out = self._emit_windows()
        if self.checkpointer is not None:
            self.checkpointer.maybe_save(self)
        # legacy knob, kept distinct from the FaultPlan "fold" site on
        # purpose: stream.fault.crash.after.panes fires AFTER the pane
        # reached the ring and its snapshot was saved (the round-11
        # kill-AFTER-durability drill), while fault.fold.crash.after
        # fires BEFORE the fold (mid-fold preemption) and journals
        # fault.injected — different drills, both pinned by tests
        if self.crash_after and self.panes_closed >= self.crash_after:
            raise RuntimeError(
                f"stream.fault.crash.after.panes={self.crash_after}: "
                f"injected crash after pane {self.panes_closed - 1}")
        return out

    def _encode(self, lines: List[str]) -> EncodedDataset:
        rows = read_csv_string("\n".join(lines), delim=self.delim)
        return self.enc.transform(rows, with_labels=True)

    def _pad(self, ds: EncodedDataset) -> EncodedDataset:
        """Pad the pane to its power-of-two row bucket with ballast rows
        (label −1 — ``core.encoding.pad_ballast``, the one shared fill
        contract): out-of-range labels drop out of EVERY count table (both
        gram and einsum paths share the drop-invalid contract), so the pad
        is pure shape ballast and the compiled-shape set stays finite."""
        if not self.pad_pow2:
            return ds
        return pad_ballast(ds,
                           next(b for b in self.buckets if b >= ds.num_rows))

    # -- window emission ------------------------------------------------------
    def _emit_windows(self) -> List[WindowResult]:
        if self.panes_closed < self.window_panes or \
                (self.panes_closed - self.window_panes) % self.slide_panes:
            return []
        merged = agg.Accumulator()
        rows = 0
        lines: Optional[List[str]] = [] if self.retain_rows else None
        for rec in self._ring:
            for key, val in rec["state"].items():
                merged.add(key, val)
            rows += rec["rows"]
            if lines is not None:
                if rec["lines"] is None:
                    lines = None          # restored pane: rows not retained
                else:
                    lines.extend(rec["lines"])
        tables = self.folder.tables(merged, rows)
        results = {c.name: c.finalize(tables) for c in self.folder.consumers}
        window = WindowResult(
            index=self.windows_emitted,
            first_pane=self.panes_closed - self.window_panes,
            last_pane=self.panes_closed - 1,
            rows=rows, tables=tables, results=results, lines=lines,
            retained=self.retain_rows)
        self.windows_emitted += 1
        self.counters.increment("Stream", "windows")
        if self.on_window is not None:
            self.on_window(window)
        return [window]

    # -- checkpointable state -------------------------------------------------
    def state(self) -> dict:
        """The windowed accumulator ring + progress cursors — everything a
        resumed scan needs to reproduce the remaining windows byte-for-byte
        when re-fed from row ``rows_consumed``.  Raw retained lines are NOT
        persisted (they exist for retraining, not correctness); the open
        pane's buffered rows are NOT persisted either — the cursor points
        at the last closed pane boundary, so a resume re-feeds them.
        ``"shard"`` records the mesh topology the panes were folded under
        (ElasticGraft, round 16): a resharded resume routes through the
        redistribution transform instead of tripping the foreign-g:-key
        refusal with a confusing message."""
        return {
            "pane": self.panes_closed,
            "windows": self.windows_emitted,
            "rows_consumed": self.rows_consumed,
            "shard": self.folder.g_suffix,
            "ring": [{"pane": rec["pane"], "rows": rec["rows"],
                      "state": dict(rec["state"])} for rec in self._ring],
        }

    def load(self, state: dict) -> None:
        self.panes_closed = int(state["pane"])
        self.windows_emitted = int(state["windows"])
        self.rows_consumed = int(state["rows_consumed"])
        self._ring.clear()
        for rec in state["ring"]:
            self._ring.append({"pane": int(rec["pane"]),
                               "rows": int(rec["rows"]),
                               "state": {k: np.asarray(v)
                                         for k, v in rec["state"].items()},
                               "lines": None})
        self._pane_buf = []


class WindowCheckpointer:
    """Mid-stream durability for the windowed ring — the StreamCheckpointer
    discipline applied to pane-granular state.

    Snapshots (every ``stream.checkpoint.interval.panes`` closed panes) hold
    the ring + cursors under the SAME conf-derived run fingerprint the
    streaming jobs use (``StreamCheckpointer.run_id_from_conf`` — GL002:
    accumulator state never persists without its configuration identity);
    restore rejects a snapshot written by a different configuration loudly.
    A resumed scan re-fed from row ``rows_consumed`` reproduces the
    remaining windows byte-for-byte (tests/test_stream.py kill-and-resume).
    """

    def __init__(self, directory: str, run_id: str = "",
                 interval_panes: int = 8, resume: bool = False,
                 reshard: bool = False, fault=None):
        from avenir_tpu.utils.checkpoint import CheckpointManager

        self.directory = directory
        self.run_id = run_id
        self.interval = max(int(interval_panes), 1)
        # ElasticGraft (round 16): shard.reshard.on.restore — redistribute
        # a snapshot written under a different mesh topology onto this
        # run's (checkpoint/reshard.py) instead of refusing it.  Default
        # OFF: crossing a topology boundary silently is never the default
        self.reshard = bool(reshard)
        self.fault = fault               # utils/retry.FaultPlan or None
        self.mgr = CheckpointManager(directory, keep=2)
        self._components: Dict[str, Any] = {}
        self.restored: Optional[dict] = None
        if resume:
            if self.fault is not None:
                self.fault.hit("checkpoint.restore")
            state = self.mgr.restore()
            if state is not None:
                snap_run = str(state.get("run", ""))
                if snap_run and run_id and snap_run != run_id:
                    raise ConfigError(
                        f"stream snapshot in {directory!r} was written by "
                        f"run {snap_run!r}, not this run {run_id!r} — the "
                        f"configuration changed since the checkpoint; clear "
                        f"the directory and restart the stream")
                self.restored = state

    @classmethod
    def from_conf(cls, conf: JobConfig,
                  fault=None) -> Optional["WindowCheckpointer"]:
        from avenir_tpu.jobs.base import Job, StreamCheckpointer

        directory = conf.get("stream.checkpoint.dir")
        if not directory:
            return None
        # CrossGraft: in a multi-process run every process snapshots its
        # own (identical, replicated) ring under a process subdirectory —
        # the StreamCheckpointer proc-scoping discipline — so two
        # journal-writing processes never contend for one snapshot file.
        # Like StreamCheckpointer, the subdirectory name PINS the process
        # count: a conf-driven relaunch at a different nprocs finds no
        # snapshot and restarts cleanly from zero; a deliberate
        # kill-on-N → resume-on-M restore points stream.checkpoint.dir
        # at the proc subdirectory itself (shard.reshard.on.restore then
        # redistributes the process-qualified ring — the drill
        # tests/test_multiprocess.py::test_crossgraft_* runs)
        pid, nprocs = Job.process_grid()
        if nprocs > 1:
            if nprocs >= 10 ** 3:      # fixed-width name contract (GL003)
                raise ConfigError(
                    f"{nprocs} processes exceeds the proc-NNN-of-NNN "
                    f"3-digit checkpoint-subdirectory width")
            import os as _os

            directory = _os.path.join(
                directory, f"proc-{pid:03d}-of-{nprocs:03d}")
        return cls(
            directory,
            run_id=StreamCheckpointer.run_id_from_conf(conf),
            interval_panes=conf.get_int("stream.checkpoint.interval.panes", 8),
            resume=conf.get_bool("stream.resume", False),
            reshard=conf.get_bool("shard.reshard.on.restore", False),
            fault=fault)

    def attach(self, key: str, component) -> None:
        """Register a sidecar whose ``state()``/``load()`` rides the ring
        snapshot (the drift detector: its reference window and streak must
        resume WITH the windows, or a resumed run's drift sequence would
        diverge from an uninterrupted one).  Attach before
        :meth:`restore_into`."""
        self._components[key] = component

    def restore_into(self, ws: WindowedScan) -> int:
        """Load the restored snapshot (if any) into ``ws`` and every
        attached component; returns the row cursor the caller must re-feed
        from (0 on a fresh start).

        Elastic restore (round 16): a snapshot written under a DIFFERENT
        mesh topology than ``ws`` folds under is redistributed through
        ``ChunkFolder.adopt_state`` when ``shard.reshard.on.restore`` is
        set (journaled as ``checkpoint.reshard``) and refused loudly
        otherwise — never folded silently.  Same-topology snapshots load
        exactly as before, byte-for-byte."""
        if self.restored is None:
            return 0
        state = self.restored
        from avenir_tpu.checkpoint import reshard as _reshard

        snap_sfx = _reshard.snapshot_suffix(state)
        cur_sfx = ws.folder.g_suffix
        # the gate triggers on the KEY FAMILY, not just the mesh suffix:
        # a kernel↔einsum ROUTING crossing at the same topology (a
        # TPU-written snapshot restored on a CPU host) re-keys too, and
        # loading it unadopted would silently drop post-resume counts
        # from the merged window tables — the exact hazard class the
        # foreign-key refusal exists for
        ring = state.get("ring") or []
        mismatch = any(
            not ws.folder.state_matches_routing(rec.get("state") or {})
            for rec in ring)
        if mismatch:
            snap_einsum = any("fc" in (rec.get("state") or {})
                              for rec in ring)
            if snap_einsum and ws.folder.step != "einsum":
                # einsum→gram is genuinely non-portable (pair tensors
                # outside the persisted union were never aggregated) —
                # recommending the reshard gate here would dead-end in
                # the same ReshardError adopt_state raises
                raise ConfigError(
                    f"stream snapshot in {self.directory!r} was written "
                    f"under the chunked-einsum count routing ('fc'/"
                    f"'pcc<off>' keys) but this run folds the fused "
                    f"gram — einsum counts cannot be promoted onto a "
                    f"gram routing; resume on a matching routing (e.g. "
                    f"the unsharded CPU path), or clear the directory "
                    f"and restart the stream")
            if not self.reshard:
                if snap_sfx is not None and snap_sfx != cur_sfx:
                    written, reads = (_reshard.describe(snap_sfx),
                                      _reshard.describe(cur_sfx))
                else:
                    written = "the fused gram routing"
                    reads = ("the chunked-einsum count routing"
                             if ws.folder.step == "einsum"
                             else "a differently-keyed gram routing")
                raise ConfigError(
                    f"stream snapshot in {self.directory!r} was written "
                    f"under {written!r} but this run folds under "
                    f"{reads!r} — set shard.reshard.on.restore=true to "
                    f"redistribute the snapshot onto the new layout "
                    f"(ElasticGraft, "
                    f"docs/runbooks/preemption_recovery.md), or clear "
                    f"the directory and restart the stream")
            rekeyed: List[str] = []
            for rec in ring:
                rec["state"], moved = ws.folder.adopt_state(rec["state"])
                rekeyed.extend(moved)
            state["shard"] = cur_sfx
            _reshard.journal_reshard(
                snap_sfx if snap_sfx is not None else "", cur_sfx,
                len(rekeyed), directory=self.directory, run=self.run_id)
        ws.load(state)
        extras = state.get("extras") or {}
        for key, component in self._components.items():
            if key in extras:
                component.load(extras[key])
        tel.tracer().event("checkpoint.restore", dir=self.directory,
                           run=self.run_id, rows=ws.rows_consumed,
                           chunk=ws.panes_closed)
        return ws.rows_consumed

    def maybe_save(self, ws: WindowedScan) -> None:
        if ws.panes_closed and ws.panes_closed % self.interval == 0:
            self.save(ws)

    def save(self, ws: WindowedScan) -> None:
        if self.fault is not None:
            # BEFORE any write: an injected save-crash must leave the
            # previous snapshot whole (save_state is atomic anyway; the
            # site exists to drill the window before it runs at all)
            self.fault.hit("checkpoint.save")
        # "run" fingerprints the writing configuration (GL002): restore
        # rejects a snapshot whose run id differs
        state = ws.state()
        state["run"] = self.run_id
        if self._components:
            state["extras"] = {key: component.state()
                               for key, component in self._components.items()}
        self.mgr.save(ws.panes_closed, state)
        tel.tracer().event("checkpoint.save", dir=self.directory,
                           run=self.run_id, rows=ws.rows_consumed,
                           chunk=ws.panes_closed)

    def finish(self) -> None:
        """Remove the snapshots after a cleanly completed stream (the
        manager also removes the then-empty directory)."""
        self.mgr.clear()
