"""GraftTrace — run-scoped tracing, the event journal, and metrics export.

Three pieces (docs/observability.md ties them together with the existing
profiling/counters machinery):

- ``spans``: a process-wide :class:`Tracer` (no-op until ``trace.on``)
  handing out contextvar-propagated :class:`Span`\\ s, plus the
  generalized :class:`CompileKeyMonitor` recompile detector;
- ``journal``: the append-only JSONL run journal (single-writer,
  rotation-bounded, torn-tail tolerant) every span and event lands in;
- ``export``: Prometheus text rendering of Counters + latency trackers +
  gauges + device-memory bytes, served from the scoring plane's
  ``/metrics`` route;
- ``profile``: GraftProf (round 14) — the compiled-program registry
  (AOT cost analysis per distinct compile key, per-program wall totals)
  and device-memory gauges, free until ``profile.on``;
- ``sentinel``: the perf-regression gate over bench artifacts
  (``telemetry regress``; bench.py embeds its verdict in-process);
- ``slo``: GraftFleet (round 15) — declarative ``slo.<name>.*`` rules
  evaluated live on ``/metrics`` (burn-rate gauges) and post-hoc as the
  ``telemetry slo`` CI gate.

GraftFleet (round 15) also federates the journal: every process of a
multi-process run (and every ``trace.writer.suffix`` replica) writes
its own stamped shard sharing one run/trace id, reassembled by
``telemetry merge`` / :func:`merge_journals`.

``python -m avenir_tpu.telemetry <journal>`` renders a run's span tree;
``merge`` / ``skew`` / ``slo`` / ``profile`` / ``metrics`` / ``regress``
subcommands render the fleet view, the straggler table, the SLO
verdict, the roofline table, the post-hoc Prometheus snapshot, and the
regression verdict.
"""

from avenir_tpu.telemetry.journal import (
    Journal,
    find_shards,
    latest_journal,
    merge_journals,
    merge_shards,
    read_events,
)
from avenir_tpu.telemetry.profile import (
    CompiledProgramRegistry,
    Profiler,
    profiler,
)
from avenir_tpu.telemetry.spans import (
    NOOP_SPAN,
    CompileKeyMonitor,
    Span,
    Tracer,
    configure,
    fleet_run_id,
    tracer,
)

__all__ = [
    "CompileKeyMonitor",
    "CompiledProgramRegistry",
    "Journal",
    "NOOP_SPAN",
    "Profiler",
    "Span",
    "Tracer",
    "configure",
    "find_shards",
    "fleet_run_id",
    "latest_journal",
    "merge_journals",
    "merge_shards",
    "profiler",
    "read_events",
    "tracer",
]
