"""GraftTrace — run-scoped tracing, the event journal, and metrics export.

Three pieces (docs/observability.md ties them together with the existing
profiling/counters machinery):

- ``spans``: a process-wide :class:`Tracer` (no-op until ``trace.on``)
  handing out contextvar-propagated :class:`Span`\\ s, plus the
  generalized :class:`CompileKeyMonitor` recompile detector;
- ``journal``: the append-only JSONL run journal (single-writer,
  rotation-bounded, torn-tail tolerant) every span and event lands in;
- ``export``: Prometheus text rendering of Counters + latency trackers +
  gauges, served from the scoring plane's ``/metrics`` route.

``python -m avenir_tpu.telemetry <journal>`` renders a run's span tree.
"""

from avenir_tpu.telemetry.journal import Journal, latest_journal, read_events
from avenir_tpu.telemetry.spans import (
    NOOP_SPAN,
    CompileKeyMonitor,
    Span,
    Tracer,
    configure,
    tracer,
)

__all__ = [
    "CompileKeyMonitor",
    "Journal",
    "NOOP_SPAN",
    "Span",
    "Tracer",
    "configure",
    "latest_journal",
    "read_events",
    "tracer",
]
