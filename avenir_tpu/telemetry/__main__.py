"""GraftTrace/GraftProf journal CLI — ``python -m avenir_tpu.telemetry``.

Subcommands (the bare ``<journal>`` form keeps rendering the span tree):

- ``<journal>`` / ``tree <journal>`` — per-trace span tree: one line per
  span with its wall duration, the slowest root→leaf path highlighted
  (``◀`` — the first place to look in a slow run), still-open spans
  flagged (``OPEN`` — the first place to look in a *wedged* run), counter
  deltas between successive snapshots of the same scope, and a one-line
  tally of the free events (checkpoints, recompiles, gauges, canaries).
- ``profile <journal>`` — the GraftProf roofline table: one row per
  compiled program (``program.compiled`` + cumulative ``program.profile``
  events) with dispatch counts, wall time, achieved FLOP/s and an MFU
  column against the canary-derived peak (the journal's best 4096³ bf16
  matmul canary; ``--peak-tflops`` overrides).  FLOPs are XLA cost-model
  estimates — roofline/regression material, not hardware counters.
- ``metrics <journal>`` — the journal's LAST counter/gauge/device-memory
  snapshot as Prometheus text, so batch-only and crashed runs are
  scrapeable post-hoc (``/metrics`` only exists while the serving
  frontend runs).
- ``regress <bench.json...> --baseline <artifact>`` — the perf-regression
  sentinel (``telemetry/sentinel.py``); exits 0/1/3.

Stdlib-only — usable on a machine with no JAX installed.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from avenir_tpu.telemetry.journal import read_events


class SpanNode:
    def __init__(self, span_id: str, name: str, parent: Optional[str],
                 attrs: dict, ts: float):
        self.span_id = span_id
        self.name = name
        self.parent = parent
        self.attrs = dict(attrs or {})
        self.ts = ts
        self.dur_ms: Optional[float] = None     # None = never closed
        self.status = "open"
        self.children: List["SpanNode"] = []


def build_traces(events: List[dict]) -> Dict[str, List[SpanNode]]:
    """trace id → roots (in open order), children attached."""
    nodes: Dict[str, SpanNode] = {}
    traces: Dict[str, List[SpanNode]] = {}
    for event in events:
        ev = event.get("ev")
        if ev == "span.open":
            node = SpanNode(event.get("span", "?"), event.get("name", "?"),
                            event.get("parent"), event.get("attrs", {}),
                            event.get("at", event.get("ts", 0.0)))
            nodes[node.span_id] = node
            parent = nodes.get(node.parent) if node.parent else None
            if parent is not None:
                parent.children.append(node)
            else:
                traces.setdefault(event.get("trace", "?"), []).append(node)
        elif ev == "span.close":
            node = nodes.get(event.get("span", ""))
            if node is not None:
                node.dur_ms = event.get("dur_ms")
                node.status = event.get("status", "ok")
                node.attrs.update(event.get("attrs", {}))
    return traces


def slowest_path(root: SpanNode) -> set:
    """Span ids on the root's max-duration descent — open spans sort as
    infinitely slow (a wedged child IS the slow path)."""
    marked = set()
    node = root
    while node is not None:
        marked.add(node.span_id)
        node = max(node.children, key=lambda ch: (
            ch.dur_ms is None, ch.dur_ms or 0.0), default=None)
    return marked

_INTERESTING_ATTRS = ("job", "stages", "chunks", "rows", "bucket", "model")


def _render_node(node: SpanNode, prefix: str, is_last: bool, hot: set,
                 out: List[str]) -> None:
    connector = "" if not prefix and is_last is None else (
        "└─ " if is_last else "├─ ")
    dur = ("OPEN" if node.dur_ms is None else f"{node.dur_ms:.1f} ms")
    extra = " ".join(f"{k}={node.attrs[k]}" for k in _INTERESTING_ATTRS
                     if k in node.attrs)
    mark = "  ◀" if node.span_id in hot else ""
    bad = f"  [{node.status}]" if node.status not in ("ok", "open") else ""
    label = f"{prefix}{connector}{node.name}"
    pad = max(44 - len(label), 1)
    out.append(f"{label}{' ' * pad}{dur:>10}{mark}{bad}"
               + (f"  ({extra})" if extra else ""))
    child_prefix = prefix + ("" if not prefix and is_last is None else
                             ("   " if is_last else "│  "))
    for i, child in enumerate(node.children):
        _render_node(child, child_prefix, i == len(node.children) - 1,
                     hot, out)


def counter_deltas(events: List[dict]) -> List[str]:
    """Per-scope deltas between successive counter snapshots (the first
    snapshot of a scope reads as a delta from zero)."""
    prev: Dict[str, Dict[str, Dict[str, int]]] = {}
    out: List[str] = []
    for event in events:
        if event.get("ev") != "counters":
            continue
        scope = event.get("scope", "?")
        groups = event.get("groups", {})
        before = prev.get(scope, {})
        for group in sorted(groups):
            for name in sorted(groups[group]):
                delta = groups[group][name] - before.get(group, {}).get(
                    name, 0)
                if delta:
                    out.append(f"  [{scope}] {group}::{name} +{delta}")
        prev[scope] = groups
    return out


def render(events: List[dict], trace_filter: Optional[str] = None
           ) -> List[str]:
    traces = build_traces(events)
    out: List[str] = []
    for trace_id, roots in traces.items():
        if trace_filter and trace_id != trace_filter:
            continue
        for root in roots:
            total = ("OPEN" if root.dur_ms is None
                     else f"{root.dur_ms:.1f} ms")
            out.append(f"trace {trace_id}  ({root.name}, {total})")
            _render_node(root, "", None, slowest_path(root), out)
            out.append("")
    deltas = counter_deltas(events)
    if deltas:
        out.append("counter deltas:")
        out.extend(deltas)
        out.append("")
    tally: Dict[str, int] = {}
    for event in events:
        ev = event.get("ev", "?")
        if ev not in ("span.open", "span.close", "counters"):
            tally[ev] = tally.get(ev, 0) + 1
    if tally:
        out.append("events: " + " · ".join(
            f"{n} {ev}" for ev, n in sorted(tally.items())))
    return out


# ---------------------------------------------------------------------------
# GraftProf renderers (round 14)
# ---------------------------------------------------------------------------

# one 4096³ bf16 matmul canary call = 2·4096³ FLOPs (utils/rig_canary.py)
_CANARY_FLOPS_PER_CALL = 2.0 * 4096 ** 3


def canary_peak_flops(events: List[dict]) -> Optional[float]:
    """Peak FLOP/s derived from the journal's best (lowest-ms) matmul
    canary reading — the denominator of the profile table's MFU column.
    None when the journal carries no positive canary reading."""
    best = None
    for event in events:
        if event.get("ev") != "canary":
            continue
        ms = event.get("ms")
        if isinstance(ms, (int, float)) and ms > 0:
            best = ms if best is None else min(best, ms)
    if best is None:
        return None
    return _CANARY_FLOPS_PER_CALL / (best / 1e3)


def render_profile(events: List[dict],
                   peak_flops: Optional[float] = None) -> List[str]:
    """The per-program roofline table from ``program.compiled`` (cost
    fields) + ``program.profile`` (cumulative dispatch/wall totals — the
    LAST event per program wins) events."""
    programs: Dict[str, dict] = {}
    for event in events:
        ev = event.get("ev")
        if ev == "program.compiled":
            rec = programs.setdefault(event.get("key", "?"), {})
            rec.update(site=event.get("site", "?"),
                       flops=event.get("flops"),
                       bytes_accessed=event.get("bytes_accessed"),
                       output_bytes=event.get("output_bytes"),
                       temp_bytes=event.get("temp_bytes"),
                       source=event.get("source", "shapes"),
                       shapes=event.get("shapes", ""))
        elif ev == "program.profile":
            rec = programs.setdefault(event.get("key", "?"), {})
            rec["site"] = event.get("site", rec.get("site", "?"))
            rec["dispatches"] = event.get("dispatches", 0)
            rec["wall_ms"] = event.get("wall_ms", 0.0)
    if not programs:
        return ["journal carries no program.compiled/profile events "
                "(profile.on unset, or the run predates GraftProf)"]
    peak_src = "--peak-tflops override"
    if peak_flops is None:
        peak_flops = canary_peak_flops(events)
        peak_src = "canary-derived; best matmul canary in this journal"
    out = [f"{'program':<12} {'site':<14} {'disp':>6} {'wall ms':>10} "
           f"{'ms/disp':>8} {'GFLOP/s':>9} {'MFU%':>6} {'GB/s':>7}  cost"]
    ordered = sorted(programs.items(),
                     key=lambda kv: -(kv[1].get("wall_ms") or 0.0))
    for key, rec in ordered:
        n = rec.get("dispatches", 0)
        wall_ms = rec.get("wall_ms") or 0.0
        flops = rec.get("flops")
        gflops = mfu = gbps = "-"
        if n and wall_ms > 0 and isinstance(flops, (int, float)):
            achieved = flops * n / (wall_ms / 1e3)
            gflops = f"{achieved / 1e9:.1f}"
            if peak_flops:
                mfu = f"{100.0 * achieved / peak_flops:.2f}"
        ba = rec.get("bytes_accessed")
        if n and wall_ms > 0 and isinstance(ba, (int, float)):
            gbps = f"{ba * n / (wall_ms / 1e3) / 1e9:.2f}"
        out.append(f"{key:<12} {rec.get('site', '?'):<14} {n:>6} "
                   f"{wall_ms:>10.1f} "
                   f"{(wall_ms / n if n else 0.0):>8.2f} {gflops:>9} "
                   f"{mfu:>6} {gbps:>7}  {rec.get('source', 'shapes')}")
    if peak_flops:
        out.append(f"peak: {peak_flops / 1e12:.2f} TFLOP/s ({peak_src})")
    else:
        out.append("peak: unknown — no matmul canary event in this journal "
                   "(pass --peak-tflops); MFU column empty")
    out.append("flops/bytes are XLA cost-model ESTIMATES captured at "
               "compile time, not hardware counters")
    return out


class _Groups:
    """Duck-typed Counters stand-in (``as_dict`` only) so the stdlib CLI
    can reuse export.render_counters without importing numpy."""

    def __init__(self, groups: dict):
        self._groups = groups

    def as_dict(self) -> dict:
        return self._groups


def render_metrics(events: List[dict]) -> str:
    """The journal's LAST counter snapshot, gauge readings and
    device-memory samples as Prometheus text — the post-hoc ``/metrics``
    for batch-only and crashed runs."""
    from avenir_tpu.telemetry.export import prometheus_text

    last_counters: Optional[dict] = None
    scope = None
    gauges: Dict[str, float] = {}
    device_bytes: Dict[tuple, float] = {}
    for event in events:
        ev = event.get("ev")
        if ev == "counters":
            last_counters = event.get("groups", {})
            scope = event.get("scope")
        elif ev == "gauge":
            gauges[str(event.get("name", "?"))] = float(
                event.get("value", 0.0))
        elif ev == "device.memory":
            dev = str(event.get("device", "?"))
            device_bytes[(dev, "bytes_in_use")] = float(
                event.get("bytes_in_use", 0))
            device_bytes[(dev, "peak_bytes")] = float(
                event.get("peak_bytes", 0))
    if last_counters is None and not gauges and not device_bytes:
        return ("# journal carries no counters/gauge/device.memory "
                "snapshots to render\n")
    head = f"# last counter snapshot scope: {scope}\n" if scope else ""
    return head + prometheus_text(
        counters=_Groups(last_counters) if last_counters is not None
        else None,
        gauges=gauges or None, device_bytes=device_bytes or None)


def main(argv: List[str]) -> int:
    # subcommand dispatch with the legacy bare-journal form preserved
    commands = ("tree", "profile", "metrics", "regress")
    if argv and argv[0] in commands:
        cmd, rest = argv[0], argv[1:]
    else:
        cmd, rest = "tree", list(argv)
    if cmd == "regress":
        from avenir_tpu.telemetry.sentinel import cli as regress_cli

        return regress_cli(rest)

    ap = argparse.ArgumentParser(
        prog=f"python -m avenir_tpu.telemetry {cmd}".rstrip(),
        description="Render a GraftTrace/GraftProf run journal")
    ap.add_argument("journal", help="run-*.jsonl journal file")
    if cmd == "tree":
        ap.add_argument("--trace", default=None,
                        help="render only this trace id")
        ap.add_argument("--json", action="store_true", dest="as_json",
                        help="dump the decoded events as a JSON array")
    elif cmd == "profile":
        ap.add_argument("--peak-tflops", type=float, default=None,
                        help="override the canary-derived peak (TFLOP/s)")
    args = ap.parse_args(rest)
    try:
        events = read_events(args.journal)
    except OSError as exc:
        print(f"cannot read journal: {exc}", file=sys.stderr)
        return 2
    try:
        if cmd == "profile":
            peak = (args.peak_tflops * 1e12
                    if args.peak_tflops is not None else None)
            for line in render_profile(events, peak_flops=peak):
                print(line)
            return 0
        if cmd == "metrics":
            print(render_metrics(events), end="")
            return 0
        if args.as_json:
            print(json.dumps(events))
            return 0
        if not events:
            print("journal carries no decodable events", file=sys.stderr)
            return 1
        for line in render(events, trace_filter=args.trace):
            print(line)
    except BrokenPipeError:                # | head closed the pipe: fine
        sys.stderr.close()                 # suppress the shutdown warning
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
