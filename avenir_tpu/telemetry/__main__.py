"""GraftTrace/GraftProf/GraftFleet journal CLI —
``python -m avenir_tpu.telemetry``.

Subcommands (the bare ``<journal>`` form keeps rendering the span tree):

- ``<journal>`` / ``tree <journal>`` — per-trace span tree: one line per
  span with its wall duration, the slowest root→leaf path highlighted
  (``◀`` — the first place to look in a slow run), still-open spans
  flagged (``OPEN`` — the first place to look in a *wedged* run), counter
  deltas between successive snapshots of the same scope, a durability
  timeline (checkpoint saves/restores, ElasticGraft ``checkpoint.reshard``
  topology crossings, ``fault.injected`` drill kills — the preemption
  story in time order, round 16 — and the FleetServe pool lifecycle:
  ``pool.replica.down``/``up``, ``pool.scale``, round 17), and a
  one-line tally of the free events (checkpoints, recompiles, gauges,
  canaries).
  A merged fleet view (≥ 2 writers) attributes every span to its writer
  (``proc=…``/``replica=…``).
- ``merge <dir>`` — GraftFleet federation (round 15): time-order one
  run's per-process journal shards (``run-<id>.proc-<k>[-<sfx>].jsonl``)
  into one fleet view, tolerating torn tails and shards missing from
  crashed/preempted workers.  Writes ``fleet-<id>.jsonl`` (never matches
  the ``run-*`` shard pattern, so re-merging cannot double-count) which
  every other subcommand renders; ``--stdout`` streams the JSONL
  instead, ``--run`` picks a run when the directory holds several.
- ``skew <journal>`` — the straggler table: per-device chunk-time
  distribution from ``shard.skew`` events (``parallel/skew.py``), the
  slowest device highlighted and threshold-flagged probes counted.
- ``slo <journal>`` — the SLO gate (``telemetry/slo.py``): evaluate
  ``slo.<name>.*`` rules (``--conf`` properties file and/or inline
  ``--rule NAME=METRIC<=TARGET``) over the journal; exits 0 clean / 1
  violated — the CI verdict the serving soak harness closes on.
  ``--label KEY=VALUE`` (round 18) restricts evaluation to events
  carrying that label — ``--label tenant=<id>`` computes one tenant's
  verdict from a merged multi-tenant fleet journal.
- ``profile <journal>`` — the GraftProf roofline table: one row per
  compiled program (``program.compiled`` + cumulative ``program.profile``
  events) with dispatch counts, wall time, achieved FLOP/s and an MFU
  column against the canary-derived peak (the journal's best 4096³ bf16
  matmul canary; ``--peak-tflops`` overrides).  FLOPs are XLA cost-model
  estimates — roofline/regression material, not hardware counters.
- ``metrics <journal>`` — the journal's LAST counter/gauge/device-memory
  snapshot as Prometheus text, so batch-only and crashed runs are
  scrapeable post-hoc (``/metrics`` only exists while the serving
  frontend runs).
- ``regress <bench.json...> --baseline <artifact>`` — the perf-regression
  sentinel (``telemetry/sentinel.py``); exits 0/1/3.
- ``diff <a.jsonl> <b.jsonl>`` — GraftBox cross-run regression diff
  (round 21): per-program dispatch-count / wall / ms-per-dispatch / MFU
  deltas (each side's MFU against its OWN canary peak) and per-stage
  span wall deltas between two runs' journals, sorted by |Δwall| — the
  first table to read when a run got slower
  (docs/runbooks/perf_regression_triage.md).
- ``bundle <dir>`` — render a GraftBox forensics bundle
  (``bundle-<run>-<writer>/`` dumped on crash / fatal signal / watchdog
  trip, or swept from a SIGKILLed worker): cause + writer identity, the
  flight-ring tail, the slowest still-open span, thread stacks, the
  in-flight request table and breaker/pool/watchdog state
  (docs/runbooks/postmortem_triage.md).

Stdlib-only — usable on a machine with no JAX installed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

from avenir_tpu.telemetry.journal import read_events


def _writer_of(event: dict) -> str:
    """The writer-identity tag an event's GraftFleet stamp encodes:
    ``p<proc>[-<replica>]``, or '' for pre-fleet journals."""
    if "proc" not in event:
        return ""
    tag = f"p{event.get('proc')}"
    if event.get("replica"):
        tag += f"-{event['replica']}"
    return tag


class SpanNode:
    def __init__(self, span_id: str, name: str, parent: Optional[str],
                 attrs: dict, ts: float, writer: str = ""):
        self.span_id = span_id
        self.name = name
        self.parent = parent
        self.attrs = dict(attrs or {})
        self.ts = ts
        self.writer = writer                    # GraftFleet attribution
        self.dur_ms: Optional[float] = None     # None = never closed
        self.status = "open"
        self.children: List["SpanNode"] = []


def build_traces(events: List[dict]) -> Dict[str, List[SpanNode]]:
    """trace id → roots (in open order), children attached."""
    nodes: Dict[str, SpanNode] = {}
    traces: Dict[str, List[SpanNode]] = {}
    for event in events:
        ev = event.get("ev")
        if ev == "span.open":
            node = SpanNode(event.get("span", "?"), event.get("name", "?"),
                            event.get("parent"), event.get("attrs", {}),
                            event.get("at", event.get("ts", 0.0)),
                            writer=_writer_of(event))
            nodes[node.span_id] = node
            parent = nodes.get(node.parent) if node.parent else None
            if parent is not None:
                parent.children.append(node)
            else:
                traces.setdefault(event.get("trace", "?"), []).append(node)
        elif ev == "span.close":
            node = nodes.get(event.get("span", ""))
            if node is not None:
                node.dur_ms = event.get("dur_ms")
                node.status = event.get("status", "ok")
                node.attrs.update(event.get("attrs", {}))
    return traces


def slowest_path(root: SpanNode) -> set:
    """Span ids on the root's max-duration descent — open spans sort as
    infinitely slow (a wedged child IS the slow path)."""
    marked = set()
    node = root
    while node is not None:
        marked.add(node.span_id)
        node = max(node.children, key=lambda ch: (
            ch.dur_ms is None, ch.dur_ms or 0.0), default=None)
    return marked

_INTERESTING_ATTRS = ("job", "stages", "chunks", "rows", "bucket", "model")


def _render_node(node: SpanNode, prefix: str, is_last: bool, hot: set,
                 out: List[str], show_writer: bool = False) -> None:
    connector = "" if not prefix and is_last is None else (
        "└─ " if is_last else "├─ ")
    dur = ("OPEN" if node.dur_ms is None else f"{node.dur_ms:.1f} ms")
    extra = " ".join(f"{k}={node.attrs[k]}" for k in _INTERESTING_ATTRS
                     if k in node.attrs)
    if show_writer and node.writer:
        extra = f"{node.writer}" + (f" {extra}" if extra else "")
    mark = "  ◀" if node.span_id in hot else ""
    bad = f"  [{node.status}]" if node.status not in ("ok", "open") else ""
    label = f"{prefix}{connector}{node.name}"
    pad = max(44 - len(label), 1)
    out.append(f"{label}{' ' * pad}{dur:>10}{mark}{bad}"
               + (f"  ({extra})" if extra else ""))
    child_prefix = prefix + ("" if not prefix and is_last is None else
                             ("   " if is_last else "│  "))
    for i, child in enumerate(node.children):
        _render_node(child, child_prefix, i == len(node.children) - 1,
                     hot, out, show_writer=show_writer)


def counter_deltas(events: List[dict]) -> List[str]:
    """Per-scope deltas between successive counter snapshots (the first
    snapshot of a scope reads as a delta from zero).  Scopes are keyed
    per WRITER in a merged fleet view — two processes' snapshots of the
    same scope are distinct series, not one interleaved one — with the
    ``@writer`` tag shown only when the view actually holds more than
    one writer (a plain single-process journal keeps the round-10
    rendering)."""
    writers = {_writer_of(e) for e in events if e.get("ev") == "counters"}
    tag_writers = len(writers) > 1
    prev: Dict[tuple, Dict[str, Dict[str, int]]] = {}
    out: List[str] = []
    for event in events:
        if event.get("ev") != "counters":
            continue
        writer = _writer_of(event)
        scope = event.get("scope", "?")
        label = f"{scope}@{writer}" if writer and tag_writers else scope
        groups = event.get("groups", {})
        before = prev.get((scope, writer), {})
        for group in sorted(groups):
            for name in sorted(groups[group]):
                delta = groups[group][name] - before.get(group, {}).get(
                    name, 0)
                if delta:
                    out.append(f"  [{label}] {group}::{name} +{delta}")
        prev[(scope, writer)] = groups
    return out


def durability_lines(events: List[dict]) -> List[str]:
    """The run's durability timeline (round 16): checkpoint lifecycle,
    ElasticGraft topology crossings, injected drill faults and — round
    17 — the FleetServe replica-pool lifecycle, in journal order —
    `fault.injected → pool.replica.down → pool.failover → pool.scale`
    reads straight down, which is how a replica loss is triaged
    (docs/runbooks/replica_loss_triage.md)."""
    out: List[str] = []
    for e in events:
        ev = e.get("ev")
        if ev in ("checkpoint.save", "checkpoint.restore"):
            detail = (f"run={e.get('run', '?')} chunk={e.get('chunk', '?')} "
                      f"rows={e.get('rows', '?')}"
                      if "chunk" in e else
                      f"scope={e.get('scope', '?')}")
            out.append(f"  {ev:<20} {detail}")
        elif ev == "checkpoint.reshard":
            out.append(f"  {ev:<20} {e.get('src', '?')} -> "
                       f"{e.get('dst', '?')} ({e.get('keys', 0)} key(s)) "
                       f"run={e.get('run', '?')}")
        elif ev == "fault.injected":
            out.append(f"  {ev:<20} site={e.get('site', '?')} "
                       f"hit={e.get('hit', '?')}")
        elif ev in ("pool.replica.down", "pool.replica.up"):
            pending = (f" pending={e['pending']}"
                       if e.get("pending") else "")
            out.append(f"  {ev:<20} replica={e.get('replica', '?')} "
                       f"reason={e.get('reason', '?')}{pending}")
        elif ev == "pool.scale":
            out.append(f"  {ev:<20} {e.get('direction', '?')} -> "
                       f"{e.get('ready', '?')} ready "
                       f"(burn={e.get('burn', '?')} "
                       f"queue_frac={e.get('queue_frac', '?')} "
                       f"reason={e.get('reason', '?')})")
        elif ev == "tenant.admitted":
            out.append(f"  {ev:<20} tenant={e.get('tenant', '?')} "
                       f"share={e.get('share', '?')} "
                       f"priority={e.get('priority', '?')}")
        elif ev == "tenant.throttled":
            out.append(f"  {ev:<20} tenant={e.get('tenant', '?')} "
                       f"reason={e.get('reason', '?')} "
                       f"waiting={e.get('waiting', '?')}")
        elif ev == "tenant.shed":
            out.append(f"  {ev:<20} tenant={e.get('tenant', '?')} "
                       f"quota={e.get('quota', '?')} "
                       f"waiting={e.get('waiting', '?')} "
                       f"retry_after_ms={e.get('retry_after_ms', '?')}")
    return out


def render(events: List[dict], trace_filter: Optional[str] = None
           ) -> List[str]:
    traces = build_traces(events)
    # writer attribution only when the view actually federates ≥2
    # writers — a single-process journal keeps its round-10 rendering
    writers = {_writer_of(e) for e in events if e.get("ev") == "span.open"}
    show_writer = len(writers) > 1
    out: List[str] = []
    for trace_id, roots in traces.items():
        if trace_filter and trace_id != trace_filter:
            continue
        for root in roots:
            total = ("OPEN" if root.dur_ms is None
                     else f"{root.dur_ms:.1f} ms")
            out.append(f"trace {trace_id}  ({root.name}, {total})")
            _render_node(root, "", None, slowest_path(root), out,
                         show_writer=show_writer)
            out.append("")
    deltas = counter_deltas(events)
    if deltas:
        out.append("counter deltas:")
        out.extend(deltas)
        out.append("")
    durability = durability_lines(events)
    if durability:
        out.append("durability timeline:")
        out.extend(durability)
        out.append("")
    tally: Dict[str, int] = {}
    for event in events:
        ev = event.get("ev", "?")
        if ev not in ("span.open", "span.close", "counters"):
            tally[ev] = tally.get(ev, 0) + 1
    if tally:
        out.append("events: " + " · ".join(
            f"{n} {ev}" for ev, n in sorted(tally.items())))
    return out


# ---------------------------------------------------------------------------
# GraftProf renderers (round 14)
# ---------------------------------------------------------------------------

# one 4096³ bf16 matmul canary call = 2·4096³ FLOPs (utils/rig_canary.py)
_CANARY_FLOPS_PER_CALL = 2.0 * 4096 ** 3


def canary_peak_flops(events: List[dict]) -> Optional[float]:
    """Peak FLOP/s derived from the journal's best (lowest-ms) matmul
    canary reading — the denominator of the profile table's MFU column.
    None when the journal carries no positive canary reading."""
    best = None
    for event in events:
        if event.get("ev") != "canary":
            continue
        ms = event.get("ms")
        if isinstance(ms, (int, float)) and ms > 0:
            best = ms if best is None else min(best, ms)
    if best is None:
        return None
    return _CANARY_FLOPS_PER_CALL / (best / 1e3)


def collect_programs(events: List[dict]) -> Dict[str, dict]:
    """Program key → merged record from ``program.compiled`` (cost
    fields) + ``program.profile`` (cumulative dispatch/wall totals — the
    LAST event per program wins).  Shared by the ``profile`` table and
    the ``diff`` cross-run comparison."""
    programs: Dict[str, dict] = {}
    for event in events:
        ev = event.get("ev")
        if ev == "program.compiled":
            rec = programs.setdefault(event.get("key", "?"), {})
            rec.update(site=event.get("site", "?"),
                       flops=event.get("flops"),
                       bytes_accessed=event.get("bytes_accessed"),
                       output_bytes=event.get("output_bytes"),
                       temp_bytes=event.get("temp_bytes"),
                       source=event.get("source", "shapes"),
                       shapes=event.get("shapes", ""))
        elif ev == "program.profile":
            rec = programs.setdefault(event.get("key", "?"), {})
            rec["site"] = event.get("site", rec.get("site", "?"))
            rec["dispatches"] = event.get("dispatches", 0)
            rec["wall_ms"] = event.get("wall_ms", 0.0)
    return programs


def render_profile(events: List[dict],
                   peak_flops: Optional[float] = None) -> List[str]:
    """The per-program roofline table from ``program.compiled`` (cost
    fields) + ``program.profile`` (cumulative dispatch/wall totals — the
    LAST event per program wins) events."""
    programs = collect_programs(events)
    if not programs:
        return ["journal carries no program.compiled/profile events "
                "(profile.on unset, or the run predates GraftProf)"]
    peak_src = "--peak-tflops override"
    if peak_flops is None:
        peak_flops = canary_peak_flops(events)
        peak_src = "canary-derived; best matmul canary in this journal"
    out = [f"{'program':<12} {'site':<14} {'disp':>6} {'wall ms':>10} "
           f"{'ms/disp':>8} {'GFLOP/s':>9} {'MFU%':>6} {'GB/s':>7}  cost"]
    ordered = sorted(programs.items(),
                     key=lambda kv: -(kv[1].get("wall_ms") or 0.0))
    for key, rec in ordered:
        n = rec.get("dispatches", 0)
        wall_ms = rec.get("wall_ms") or 0.0
        flops = rec.get("flops")
        gflops = mfu = gbps = "-"
        if n and wall_ms > 0 and isinstance(flops, (int, float)):
            achieved = flops * n / (wall_ms / 1e3)
            gflops = f"{achieved / 1e9:.1f}"
            if peak_flops:
                mfu = f"{100.0 * achieved / peak_flops:.2f}"
        ba = rec.get("bytes_accessed")
        if n and wall_ms > 0 and isinstance(ba, (int, float)):
            gbps = f"{ba * n / (wall_ms / 1e3) / 1e9:.2f}"
        out.append(f"{key:<12} {rec.get('site', '?'):<14} {n:>6} "
                   f"{wall_ms:>10.1f} "
                   f"{(wall_ms / n if n else 0.0):>8.2f} {gflops:>9} "
                   f"{mfu:>6} {gbps:>7}  {rec.get('source', 'shapes')}")
    if peak_flops:
        out.append(f"peak: {peak_flops / 1e12:.2f} TFLOP/s ({peak_src})")
    else:
        out.append("peak: unknown — no matmul canary event in this journal "
                   "(pass --peak-tflops); MFU column empty")
    out.append("flops/bytes are XLA cost-model ESTIMATES captured at "
               "compile time, not hardware counters")
    return out


# ---------------------------------------------------------------------------
# GraftBox renderers (round 21): cross-run diff + forensics bundles
# ---------------------------------------------------------------------------

def stage_walls(events: List[dict]) -> Dict[str, List[float]]:
    """Span name → [count, total wall ms] over every closed span — the
    per-stage half of the cross-run diff (``fold``/``pane``/``dispatch``
    spans are the pipeline stages)."""
    names: Dict[str, str] = {}
    agg: Dict[str, List[float]] = {}
    for e in events:
        ev = e.get("ev")
        if ev == "span.open":
            names[e.get("span", "?")] = e.get("name", "?")
        elif ev == "span.close":
            dur = e.get("dur_ms")
            if isinstance(dur, (int, float)):
                name = names.get(e.get("span", ""), e.get("name", "?"))
                row = agg.setdefault(name, [0, 0.0])
                row[0] += 1
                row[1] += float(dur)
    return agg


def _program_mfu(rec: dict, peak_flops: Optional[float]) -> Optional[float]:
    n = rec.get("dispatches", 0)
    wall_ms = rec.get("wall_ms") or 0.0
    flops = rec.get("flops")
    if n and wall_ms > 0 and isinstance(flops, (int, float)) and peak_flops:
        return 100.0 * flops * n / (wall_ms / 1e3) / peak_flops
    return None


def render_diff(events_a: List[dict], events_b: List[dict],
                label_a: str = "A", label_b: str = "B") -> List[str]:
    """The cross-run regression table: per-program dispatch / wall /
    ms-per-dispatch / MFU deltas (each side's MFU against its OWN canary
    peak — a slower machine is not a regression) and per-stage span wall
    deltas, both sorted by |Δwall| so the biggest mover reads first."""
    progs_a, progs_b = collect_programs(events_a), collect_programs(events_b)
    peak_a, peak_b = canary_peak_flops(events_a), canary_peak_flops(events_b)
    out: List[str] = [f"A = {label_a}", f"B = {label_b}", ""]

    def fnum(v: Optional[float], spec: str = ".1f") -> str:
        return "-" if v is None else format(v, spec)

    keys = sorted(set(progs_a) | set(progs_b),
                  key=lambda k: -abs((progs_b.get(k, {}).get("wall_ms")
                                      or 0.0)
                                     - (progs_a.get(k, {}).get("wall_ms")
                                        or 0.0)))
    if keys:
        out.append(f"{'program':<12} {'disp A':>7} {'disp B':>7} "
                   f"{'wall A':>9} {'wall B':>9} {'Δwall ms':>9} "
                   f"{'Δms/disp':>9} {'MFU%A':>6} {'MFU%B':>6}")
        for key in keys:
            ra, rb = progs_a.get(key, {}), progs_b.get(key, {})
            na, nb = ra.get("dispatches", 0), rb.get("dispatches", 0)
            wa = ra.get("wall_ms") or 0.0
            wb = rb.get("wall_ms") or 0.0
            pa = (wa / na) if na else None
            pb = (wb / nb) if nb else None
            dper = (pb - pa) if pa is not None and pb is not None else None
            out.append(
                f"{key:<12} {na:>7} {nb:>7} {wa:>9.1f} {wb:>9.1f} "
                f"{wb - wa:>+9.1f} {fnum(dper, '+9.2f') :>9} "
                f"{fnum(_program_mfu(ra, peak_a), '.2f'):>6} "
                f"{fnum(_program_mfu(rb, peak_b), '.2f'):>6}")
        out.append("")
    else:
        out.append("no program.compiled/profile events on either side "
                   "(profile.on unset in both runs); program table empty")
        out.append("")

    stages_a, stages_b = stage_walls(events_a), stage_walls(events_b)
    names = sorted(set(stages_a) | set(stages_b),
                   key=lambda n: -abs(stages_b.get(n, [0, 0.0])[1]
                                      - stages_a.get(n, [0, 0.0])[1]))
    if names:
        out.append(f"{'stage':<28} {'n A':>6} {'n B':>6} "
                   f"{'wall A':>10} {'wall B':>10} {'Δwall ms':>10}")
        for name in names:
            ca, wa = stages_a.get(name, [0, 0.0])
            cb, wb = stages_b.get(name, [0, 0.0])
            out.append(f"{name:<28} {ca:>6} {cb:>6} {wa:>10.1f} "
                       f"{wb:>10.1f} {wb - wa:>+10.1f}")
    else:
        out.append("no closed spans on either side (trace.on unset in "
                   "both runs); stage table empty")
    out.append("")
    out.append("Δ = B - A; MFU against each side's own canary peak "
               + f"(A: {fnum(peak_a and peak_a / 1e12, '.2f')} TFLOP/s, "
               + f"B: {fnum(peak_b and peak_b / 1e12, '.2f')} TFLOP/s)")
    return out


def diff_cli(rest: List[str]) -> int:
    """``diff <a.jsonl> <b.jsonl>`` — the cross-run regression diff."""
    ap = argparse.ArgumentParser(
        prog="python -m avenir_tpu.telemetry diff",
        description="Per-program / per-stage dispatch, wall and MFU "
                    "deltas between two runs' journals (Δ = B - A)")
    ap.add_argument("a", help="baseline journal (run-*.jsonl or merged "
                              "fleet view)")
    ap.add_argument("b", help="candidate journal to compare against it")
    args = ap.parse_args(rest)
    try:
        events_a = read_events(args.a)
        events_b = read_events(args.b)
    except OSError as exc:
        print(f"cannot read journal: {exc}", file=sys.stderr)
        return 2
    for line in render_diff(events_a, events_b,
                            label_a=args.a, label_b=args.b):
        print(line)
    return 0


def _load_json(path: str, default):
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return default


def _read_ring(path: str) -> List[dict]:
    out: List[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except ValueError:       # torn tail: SIGKILL mid-write
                        pass
    except OSError:
        pass
    return out


def _ring_line(rec: dict, t_end: float) -> str:
    fields = " ".join(f"{k}={rec[k]}" for k in rec
                      if k not in ("ts", "ev"))
    dt = rec.get("ts", t_end) - t_end
    return (f"  {dt:>+9.3f}s  {rec.get('ev', '?'):<22}"
            + (f"  {fields}" if fields else ""))


def open_spans_in_ring(ring: List[dict]) -> List[dict]:
    """``span.open`` entries with no matching ``span.close`` in the ring,
    oldest first — a wedged run's stuck stage.  Empty when the run traced
    nothing (``trace.on`` off records no span seams in the ring)."""
    opens: Dict[str, dict] = {}
    for rec in ring:
        ev = rec.get("ev")
        if ev == "span.open":
            opens[rec.get("span", "?")] = rec
        elif ev == "span.close":
            opens.pop(rec.get("span", ""), None)
    return sorted(opens.values(), key=lambda r: r.get("ts", 0.0))


def render_bundle(bundle_dir: str, tail: int = 20,
                  stack_lines: int = 40) -> List[str]:
    """The whole post-mortem from one forensics bundle directory: cause,
    flight-ring tail, slowest open span, in-flight requests, pool /
    breaker / watchdog state, device memory, thread stacks."""
    meta = _load_json(os.path.join(bundle_dir, "meta.json"), {})
    ring = _read_ring(os.path.join(bundle_dir, "ring.jsonl"))
    out = [f"bundle {bundle_dir}"]
    out.append(f"  reason={meta.get('reason') or '?'} "
               f"status={meta.get('status', '?')} "
               f"writer={meta.get('writer', '?')} "
               f"run={meta.get('run', '?')} pid={meta.get('pid', '?')} "
               f"journaled={meta.get('journaled', False)}")
    if meta.get("argv"):
        out.append(f"  argv: {' '.join(str(a) for a in meta['argv'])}")
    out.append("")

    t_end = ring[-1].get("ts", 0.0) if ring else 0.0
    shown = ring[-tail:]
    out.append(f"flight ring — last {len(shown)} of {len(ring)} event(s), "
               "times relative to the newest:")
    for rec in shown:
        out.append(_ring_line(rec, t_end))
    if not ring:
        out.append("  (empty)")
    out.append("")

    open_spans = open_spans_in_ring(ring)
    if open_spans:
        oldest = open_spans[0]
        age = t_end - oldest.get("ts", t_end)
        out.append(f"slowest open span: {oldest.get('name', '?')} "
                   f"(span={oldest.get('span', '?')}, open {age:.3f}s "
                   "before the ring's newest event)")
        for rec in open_spans[1:]:
            out.append(f"  also open: {rec.get('name', '?')} "
                       f"(+{t_end - rec.get('ts', t_end):.3f}s)")
        out.append("")

    inflight = _load_json(os.path.join(bundle_dir, "inflight.json"), {})
    rows = [(src, row) for src, got in sorted(inflight.items())
            for row in (got if isinstance(got, list) else [got])]
    if rows:
        out.append(f"in-flight requests ({len(rows)}):")
        for src, row in rows:
            if isinstance(row, dict):
                detail = " ".join(f"{k}={v}" for k, v in row.items())
            else:
                detail = str(row)
            out.append(f"  [{src}] {detail}")
        out.append("")

    state = _load_json(os.path.join(bundle_dir, "state.json"), {})
    dog = state.get("watchdog") or {}
    if dog.get("sec"):
        active = dog.get("active") or {}
        sites = " ".join(f"{s}({v.get('active_s', '?')}s)"
                         for s, v in sorted(active.items()))
        out.append(f"watchdog: threshold={dog.get('sec')}s "
                   f"silent={dog.get('silent_s', '?')}s "
                   f"tripped={dog.get('tripped', False)}"
                   + (f" active: {sites}" if sites else ""))
    for src in sorted(state):
        if src in ("watchdog",):
            continue
        got = state[src]
        if isinstance(got, list):
            for row in got:
                detail = (" ".join(f"{k}={v}" for k, v in row.items())
                          if isinstance(row, dict) else str(row))
                out.append(f"  [{src}] {detail}")
        elif got is not None:
            out.append(f"  [{src}] {json.dumps(got, default=repr)}")
    if dog.get("sec") or any(s != "watchdog" for s in state):
        out.append("")

    memory = _load_json(os.path.join(bundle_dir, "memory.json"), {})
    gauges = memory.get("device_memory") or {}
    if gauges:
        out.append("device memory: " + " ".join(
            f"{k}={v}" for k, v in sorted(gauges.items())))
        out.append("")

    try:
        with open(os.path.join(bundle_dir, "stacks.txt"), "r",
                  encoding="utf-8") as fh:
            stacks = fh.read().splitlines()
    except OSError:
        stacks = []
    if stacks:
        out.append("stacks:")
        for line in stacks[:stack_lines]:
            out.append(f"  {line}")
        if len(stacks) > stack_lines:
            out.append(f"  … {len(stacks) - stack_lines} more line(s) in "
                       f"{os.path.join(bundle_dir, 'stacks.txt')}")
    return out


def bundle_cli(rest: List[str]) -> int:
    """``bundle <dir>`` — render a GraftBox forensics bundle."""
    ap = argparse.ArgumentParser(
        prog="python -m avenir_tpu.telemetry bundle",
        description="Render a GraftBox forensics bundle "
                    "(bundle-<run>-<writer>/) as a post-mortem: cause, "
                    "flight-ring tail, open spans, in-flight requests, "
                    "pool/breaker/watchdog state, thread stacks")
    ap.add_argument("directory", help="bundle-<run>-<writer> directory")
    ap.add_argument("--tail", type=int, default=20,
                    help="flight-ring events to show (default 20)")
    ap.add_argument("--stack-lines", type=int, default=40,
                    help="stack-trace lines to show (default 40)")
    args = ap.parse_args(rest)
    if not os.path.isfile(os.path.join(args.directory, "meta.json")):
        print(f"{args.directory!r} is not a forensics bundle "
              "(no meta.json)", file=sys.stderr)
        return 2
    for line in render_bundle(args.directory, tail=max(args.tail, 1),
                              stack_lines=max(args.stack_lines, 1)):
        print(line)
    return 0


# ---------------------------------------------------------------------------
# GraftFleet renderers (round 15)
# ---------------------------------------------------------------------------

def render_skew(events: List[dict]) -> List[str]:
    """The straggler table from ``shard.skew`` events: per-device
    chunk-time distribution (count/mean/p50/max ms), the slowest device
    highlighted (``◀``), and threshold-flagged probes tallied — the
    post-hoc half of ``parallel/skew.py``."""
    probes = [e for e in events if e.get("ev") == "shard.skew"
              and isinstance(e.get("device_ms"), list)]
    if not probes:
        return ["journal carries no shard.skew events (profile.on unset, "
                "no shard.* topology, or the run predates GraftFleet)"]
    per_device: Dict[int, List[float]] = {}
    flag_count: Dict[int, int] = {}
    labels: Dict[int, str] = {}
    flagged_probes = 0
    threshold = probes[-1].get("threshold")
    for e in probes:
        ms = [float(v) for v in e["device_ms"]]
        for d, v in enumerate(ms):
            per_device.setdefault(d, []).append(v)
        if e.get("flagged"):
            flagged_probes += 1
            slow = ms.index(max(ms))
            flag_count[slow] = flag_count.get(slow, 0) + 1
            labels.setdefault(slow, str(e.get("slowest", slow)))

    # the ONE percentile definition (utils/metrics via slo's numpy-free
    # fallback) — not a third private median in the same package
    from avenir_tpu.telemetry.slo import _percentile

    def p50(vals: List[float]) -> float:
        return _percentile(vals, 50.0)

    means = {d: sum(v) / len(v) for d, v in per_device.items()}
    slowest_dev = max(means, key=lambda d: means[d])
    out = [f"{'device':<14} {'probes':>7} {'mean ms':>9} {'p50 ms':>9} "
           f"{'max ms':>9} {'flags':>6}"]
    for d in sorted(per_device):
        vals = per_device[d]
        mark = "  ◀ slowest" if d == slowest_dev else ""
        out.append(f"{labels.get(d, f'dev:{d}'):<14} {len(vals):>7} "
                   f"{means[d]:>9.3f} {p50(vals):>9.3f} {max(vals):>9.3f} "
                   f"{flag_count.get(d, 0):>6}{mark}")
    out.append(f"probes: {len(probes)} · flagged: {flagged_probes}"
               + (f" (threshold max/min > {threshold:g})"
                  if isinstance(threshold, (int, float)) else ""))
    out.append("times are sampled probe dispatches of the per-device gram "
               "(parallel/skew.py) — skew RATIOS attribute stragglers; "
               "absolute ms excludes collective overlap")
    return out


def merge_cli(rest: List[str]) -> int:
    """``merge <dir>`` — reassemble one run's journal shards into a
    fleet view file (or stdout)."""
    ap = argparse.ArgumentParser(
        prog="python -m avenir_tpu.telemetry merge",
        description="Merge a run's per-process journal shards into one "
                    "time-ordered fleet view")
    ap.add_argument("directory", help="directory holding run-*.jsonl shards")
    ap.add_argument("--run", default=None,
                    help="run id to merge (default: most recently written)")
    ap.add_argument("--out", default=None,
                    help="output path (default <dir>/fleet-<run>.jsonl)")
    ap.add_argument("--stdout", action="store_true",
                    help="stream merged JSONL to stdout instead of a file")
    args = ap.parse_args(rest)
    from avenir_tpu.telemetry.journal import merge_journals

    run_id, shards, events = merge_journals(args.directory, run_id=args.run)
    if run_id is None:
        print(f"no run-*.jsonl journal shards under {args.directory!r}"
              + (f" for run {args.run!r}" if args.run else ""),
              file=sys.stderr)
        return 2
    lines = [json.dumps(e, separators=(",", ":")) for e in events]
    if args.stdout:
        for line in lines:
            print(line)
        return 0
    out_path = args.out or os.path.join(args.directory,
                                        f"fleet-{run_id}.jsonl")
    with open(out_path, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + ("\n" if lines else ""))
    writers = sorted({w for w in (_writer_of(e) for e in events) if w})
    print(f"run {run_id}: merged {len(shards)} shard(s), "
          f"{len(events)} events"
          + (f", writers {', '.join(writers)}" if writers else "")
          + f" -> {out_path}")
    return 0


def slo_cli(rest: List[str]) -> int:
    """``slo <journal>`` — the post-hoc SLO gate; exits 0 clean /
    1 violated / 2 usage."""
    from avenir_tpu.telemetry import slo as slo_mod

    ap = argparse.ArgumentParser(
        prog="python -m avenir_tpu.telemetry slo",
        description="Evaluate slo.<name>.* rules over a run journal "
                    "(exit 0 clean, 1 violated)")
    ap.add_argument("journal", help="run-*.jsonl or merged fleet view")
    ap.add_argument("--conf", default=None,
                    help="properties file carrying slo.<name>.* rules")
    ap.add_argument("--rule", action="append", default=[],
                    metavar="NAME=METRIC<=TARGET",
                    help="inline rule (repeatable; >= for lower bounds)")
    ap.add_argument("--label", action="append", default=[],
                    metavar="KEY=VALUE",
                    help="evaluate only events carrying this label "
                         "(repeatable; e.g. tenant=analytics — the "
                         "per-tenant verdict over a merged fleet journal)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the full summary as JSON")
    args = ap.parse_args(rest)
    labels = {}
    for spec in args.label:
        key, eq, value = spec.partition("=")
        if not key or not eq:
            print(f"--label expects KEY=VALUE, got {spec!r}",
                  file=sys.stderr)
            return 2
        labels[key] = value
    rules = []
    if args.conf:
        from avenir_tpu.core.config import ConfigError, JobConfig

        try:
            rules.extend(slo_mod.rules_from_conf(
                JobConfig.from_file(args.conf)))
        except (OSError, ConfigError) as exc:
            print(f"cannot load SLO rules: {exc}", file=sys.stderr)
            return 2
    for spec in args.rule:
        try:
            rules.append(slo_mod.parse_rule_spec(spec))
        except ValueError as exc:
            print(str(exc), file=sys.stderr)
            return 2
    if not rules:
        print("no SLO rules: pass --conf <properties> and/or "
              "--rule NAME=METRIC<=TARGET", file=sys.stderr)
        return 2
    try:
        events = read_events(args.journal)
    except OSError as exc:
        print(f"cannot read journal: {exc}", file=sys.stderr)
        return 2
    if labels:
        events = slo_mod.filter_events_by_labels(events, labels)
    summary = slo_mod.evaluate_events(events, rules)
    if args.as_json:
        print(json.dumps(summary))
    else:
        scope = ("".join(f" [{k}={v}]" for k, v in sorted(labels.items()))
                 if labels else "")
        print(f"{args.journal}{scope}: {summary['verdict'].upper()}")
        for row in summary["rules"]:
            burn = ("-" if row["burn_rate"] is None
                    else f"{row['burn_rate']:.3f}")
            bound = "<=" if row["op"] == "max" else ">="
            print(f"  {row['verdict']:>9}  {row['slo']:<16} "
                  f"{row['metric']:<24} {row['value']} {bound} "
                  f"{row['target']:g}  burn {burn}")
    return 1 if summary["verdict"] == "violation" else 0


class _Groups:
    """Duck-typed Counters stand-in (``as_dict`` only) so the stdlib CLI
    can reuse export.render_counters without importing numpy."""

    def __init__(self, groups: dict):
        self._groups = groups

    def as_dict(self) -> dict:
        return self._groups


def render_metrics(events: List[dict]) -> str:
    """The journal's LAST counter snapshot, gauge readings and
    device-memory samples as Prometheus text — the post-hoc ``/metrics``
    for batch-only and crashed runs."""
    from avenir_tpu.telemetry.export import prometheus_text

    last_counters: Optional[dict] = None
    scope = None
    gauges: Dict[str, float] = {}
    device_bytes: Dict[tuple, float] = {}
    for event in events:
        ev = event.get("ev")
        if ev == "counters":
            last_counters = event.get("groups", {})
            scope = event.get("scope")
        elif ev == "gauge":
            gauges[str(event.get("name", "?"))] = float(
                event.get("value", 0.0))
        elif ev == "device.memory":
            dev = str(event.get("device", "?"))
            device_bytes[(dev, "bytes_in_use")] = float(
                event.get("bytes_in_use", 0))
            device_bytes[(dev, "peak_bytes")] = float(
                event.get("peak_bytes", 0))
    if last_counters is None and not gauges and not device_bytes:
        return ("# journal carries no counters/gauge/device.memory "
                "snapshots to render\n")
    head = f"# last counter snapshot scope: {scope}\n" if scope else ""
    return head + prometheus_text(
        counters=_Groups(last_counters) if last_counters is not None
        else None,
        gauges=gauges or None, device_bytes=device_bytes or None)


def main(argv: List[str]) -> int:
    # subcommand dispatch with the legacy bare-journal form preserved
    commands = ("tree", "profile", "metrics", "regress", "merge", "skew",
                "slo", "diff", "bundle")
    if argv and argv[0] in commands:
        cmd, rest = argv[0], argv[1:]
    else:
        cmd, rest = "tree", list(argv)
    if cmd == "regress":
        from avenir_tpu.telemetry.sentinel import cli as regress_cli

        return regress_cli(rest)
    if cmd == "merge":
        return merge_cli(rest)
    if cmd == "slo":
        return slo_cli(rest)
    if cmd == "diff":
        return diff_cli(rest)
    if cmd == "bundle":
        return bundle_cli(rest)

    ap = argparse.ArgumentParser(
        prog=f"python -m avenir_tpu.telemetry {cmd}".rstrip(),
        description="Render a GraftTrace/GraftProf run journal")
    ap.add_argument("journal", help="run-*.jsonl journal file")
    if cmd == "tree":
        ap.add_argument("--trace", default=None,
                        help="render only this trace id")
        ap.add_argument("--json", action="store_true", dest="as_json",
                        help="dump the decoded events as a JSON array")
    elif cmd == "profile":
        ap.add_argument("--peak-tflops", type=float, default=None,
                        help="override the canary-derived peak (TFLOP/s)")
    args = ap.parse_args(rest)
    try:
        events = read_events(args.journal)
    except OSError as exc:
        print(f"cannot read journal: {exc}", file=sys.stderr)
        return 2
    try:
        if cmd == "profile":
            peak = (args.peak_tflops * 1e12
                    if args.peak_tflops is not None else None)
            for line in render_profile(events, peak_flops=peak):
                print(line)
            return 0
        if cmd == "metrics":
            print(render_metrics(events), end="")
            return 0
        if cmd == "skew":
            for line in render_skew(events):
                print(line)
            return 0
        if args.as_json:
            print(json.dumps(events))
            return 0
        if not events:
            print("journal carries no decodable events", file=sys.stderr)
            return 1
        for line in render(events, trace_filter=args.trace):
            print(line)
    except BrokenPipeError:                # | head closed the pipe: fine
        sys.stderr.close()                 # suppress the shutdown warning
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
