"""GraftTrace journal viewer — ``python -m avenir_tpu.telemetry <journal>``.

Renders a run journal (``telemetry/journal.py`` JSONL) as a per-trace span
tree: one line per span with its wall duration, the slowest root→leaf path
highlighted (``◀`` — the first place to look in a slow run), still-open
spans flagged (``OPEN`` — the first place to look in a *wedged* run),
counter deltas between successive snapshots of the same scope, and a
one-line tally of the free events (checkpoints, recompiles, gauges,
canaries).  Stdlib-only — usable on a machine with no JAX installed.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from avenir_tpu.telemetry.journal import read_events


class SpanNode:
    def __init__(self, span_id: str, name: str, parent: Optional[str],
                 attrs: dict, ts: float):
        self.span_id = span_id
        self.name = name
        self.parent = parent
        self.attrs = dict(attrs or {})
        self.ts = ts
        self.dur_ms: Optional[float] = None     # None = never closed
        self.status = "open"
        self.children: List["SpanNode"] = []


def build_traces(events: List[dict]) -> Dict[str, List[SpanNode]]:
    """trace id → roots (in open order), children attached."""
    nodes: Dict[str, SpanNode] = {}
    traces: Dict[str, List[SpanNode]] = {}
    for event in events:
        ev = event.get("ev")
        if ev == "span.open":
            node = SpanNode(event.get("span", "?"), event.get("name", "?"),
                            event.get("parent"), event.get("attrs", {}),
                            event.get("at", event.get("ts", 0.0)))
            nodes[node.span_id] = node
            parent = nodes.get(node.parent) if node.parent else None
            if parent is not None:
                parent.children.append(node)
            else:
                traces.setdefault(event.get("trace", "?"), []).append(node)
        elif ev == "span.close":
            node = nodes.get(event.get("span", ""))
            if node is not None:
                node.dur_ms = event.get("dur_ms")
                node.status = event.get("status", "ok")
                node.attrs.update(event.get("attrs", {}))
    return traces


def slowest_path(root: SpanNode) -> set:
    """Span ids on the root's max-duration descent — open spans sort as
    infinitely slow (a wedged child IS the slow path)."""
    marked = set()
    node = root
    while node is not None:
        marked.add(node.span_id)
        node = max(node.children, key=lambda ch: (
            ch.dur_ms is None, ch.dur_ms or 0.0), default=None)
    return marked

_INTERESTING_ATTRS = ("job", "stages", "chunks", "rows", "bucket", "model")


def _render_node(node: SpanNode, prefix: str, is_last: bool, hot: set,
                 out: List[str]) -> None:
    connector = "" if not prefix and is_last is None else (
        "└─ " if is_last else "├─ ")
    dur = ("OPEN" if node.dur_ms is None else f"{node.dur_ms:.1f} ms")
    extra = " ".join(f"{k}={node.attrs[k]}" for k in _INTERESTING_ATTRS
                     if k in node.attrs)
    mark = "  ◀" if node.span_id in hot else ""
    bad = f"  [{node.status}]" if node.status not in ("ok", "open") else ""
    label = f"{prefix}{connector}{node.name}"
    pad = max(44 - len(label), 1)
    out.append(f"{label}{' ' * pad}{dur:>10}{mark}{bad}"
               + (f"  ({extra})" if extra else ""))
    child_prefix = prefix + ("" if not prefix and is_last is None else
                             ("   " if is_last else "│  "))
    for i, child in enumerate(node.children):
        _render_node(child, child_prefix, i == len(node.children) - 1,
                     hot, out)


def counter_deltas(events: List[dict]) -> List[str]:
    """Per-scope deltas between successive counter snapshots (the first
    snapshot of a scope reads as a delta from zero)."""
    prev: Dict[str, Dict[str, Dict[str, int]]] = {}
    out: List[str] = []
    for event in events:
        if event.get("ev") != "counters":
            continue
        scope = event.get("scope", "?")
        groups = event.get("groups", {})
        before = prev.get(scope, {})
        for group in sorted(groups):
            for name in sorted(groups[group]):
                delta = groups[group][name] - before.get(group, {}).get(
                    name, 0)
                if delta:
                    out.append(f"  [{scope}] {group}::{name} +{delta}")
        prev[scope] = groups
    return out


def render(events: List[dict], trace_filter: Optional[str] = None
           ) -> List[str]:
    traces = build_traces(events)
    out: List[str] = []
    for trace_id, roots in traces.items():
        if trace_filter and trace_id != trace_filter:
            continue
        for root in roots:
            total = ("OPEN" if root.dur_ms is None
                     else f"{root.dur_ms:.1f} ms")
            out.append(f"trace {trace_id}  ({root.name}, {total})")
            _render_node(root, "", None, slowest_path(root), out)
            out.append("")
    deltas = counter_deltas(events)
    if deltas:
        out.append("counter deltas:")
        out.extend(deltas)
        out.append("")
    tally: Dict[str, int] = {}
    for event in events:
        ev = event.get("ev", "?")
        if ev not in ("span.open", "span.close", "counters"):
            tally[ev] = tally.get(ev, 0) + 1
    if tally:
        out.append("events: " + " · ".join(
            f"{n} {ev}" for ev, n in sorted(tally.items())))
    return out


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m avenir_tpu.telemetry",
        description="Render a GraftTrace run journal as a span tree")
    ap.add_argument("journal", help="run-*.jsonl journal file")
    ap.add_argument("--trace", default=None,
                    help="render only this trace id")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="dump the decoded events as a JSON array instead")
    args = ap.parse_args(argv)
    try:
        events = read_events(args.journal)
    except OSError as exc:
        print(f"cannot read journal: {exc}", file=sys.stderr)
        return 2
    try:
        if args.as_json:
            print(json.dumps(events))
            return 0
        if not events:
            print("journal carries no decodable events", file=sys.stderr)
            return 1
        for line in render(events, trace_filter=args.trace):
            print(line)
    except BrokenPipeError:                # | head closed the pipe: fine
        sys.stderr.close()                 # suppress the shutdown warning
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
