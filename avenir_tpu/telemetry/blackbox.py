"""GraftBox — the always-on flight recorder and crash/hang forensics plane.

Three pieces, one operational story (docs/runbooks/postmortem_triage.md):

- **flight ring**: a bounded in-process deque of schema'd events that
  records at every journal-emit seam EVEN WHEN ``trace.on`` is off —
  the tracer's disabled paths and the serving door feed it directly
  (:func:`ring_record` is one time read + one deque append, GIL-safe
  with no lock; ``benchmarks/telemetry_overhead.py`` publishes
  ``ring_record_ns`` and re-asserts the off-state span-site bound).
  The ring is ALWAYS live; ``blackbox.ring.events`` bounds it.
- **forensics bundles**: with ``blackbox.dir`` set, :func:`arm` (called
  by ``spans.configure``) starts a live spill thread that keeps
  ``<dir>/bundle-<run>-<writer>/`` current — ring contents, all-thread
  stacks (``faulthandler``), the batcher/pool in-flight request table,
  breaker/pool/arbiter state, device-memory + compiled-program
  snapshots, and the conf fingerprint — each file written atomically
  (tmp + ``os.replace``) so a SIGKILL mid-write can never tear it.  An
  unhandled exception, a fatal signal, or a watchdog trip latches the
  bundle ``final`` (and journals ``bundle.written`` when tracing is
  on); a clean exit removes the live bundle.  A SIGKILLed process runs
  NO hook — its live bundle simply survives, and :func:`sweep` (the
  launcher/GlobalServe teardown) finalizes dead workers' bundles and
  journals exactly one ``bundle.written`` per dead worker into a sweep
  shard of the run, BEFORE the fleet merge.
- **progress watchdog**: the long-running seams hold
  :func:`watchdog_guard` regions (``ChunkFolder.fold``, pane closes,
  ``BucketedMicrobatcher._dispatch``, the job runner) and any guard
  active with NO progress for ``blackbox.watchdog.sec`` journals
  ``hang.detected`` (naming the oldest silent site) and captures the
  bundle — a wedged process explains itself before the operator
  attaches a debugger.

Deliberately stdlib-only at import (the launcher imports this from its
supervisor path) and free when unconfigured: the ring append is the only
always-on cost, and the off path of every hook is one attribute check.
"""

from __future__ import annotations

import atexit
import contextlib
import faulthandler
import json
import os
import shutil
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

# launch env contract (launch/__init__.py) — literal here so this module
# never imports the launcher (which imports us for the teardown sweep)
_ENV_PID = "AVENIR_PROCESS_ID"
_ENV_SUFFIX = "AVENIR_WRITER_SUFFIX"

DEFAULT_RING_EVENTS = 4096

# -- the flight ring ---------------------------------------------------------

_RING: "deque[Tuple[float, str, Optional[dict]]]" = deque(
    maxlen=DEFAULT_RING_EVENTS)


def ring_record(ev: str, fields: Optional[dict] = None) -> None:
    """Append one event to the flight ring — the always-on hot path.

    One ``time.time()`` read, one tuple, one (GIL-atomic) bounded-deque
    append; no lock, no serialization, no branching on configuration.
    The tracer's emit seams call this on BOTH sides of ``trace.on``, and
    instrumentation that must stay visible with tracing off (the serving
    submit door) calls it directly."""
    _RING.append((time.time(), ev, fields))


def ring_snapshot() -> List[Dict[str, Any]]:
    """The ring's contents, oldest first, as journal-shaped dicts."""
    out = []
    for ts, ev, fields in list(_RING):
        rec = {"ts": round(ts, 6), "ev": ev}
        if fields:
            rec.update(fields)
        out.append(rec)
    return out


def ring_clear() -> None:
    _RING.clear()


def _ring_resize(cap: int) -> None:
    global _RING
    cap = max(int(cap), 16)
    if _RING.maxlen == cap:
        return
    _RING = deque(_RING, maxlen=cap)


# -- live-state providers ----------------------------------------------------

# name -> (kind, callable); kind "inflight" feeds the bundle's in-flight
# request table, anything else lands under state.json.  Providers are
# registered by the serving batcher/pools and unregistered on close; a
# crashed owner that never closed is exactly when we want its snapshot.
_PROVIDERS: Dict[str, Tuple[str, Callable[[], Any]]] = {}
_PROVIDERS_LOCK = threading.Lock()


def register_provider(name: str, fn: Callable[[], Any],
                      kind: str = "state") -> None:
    """Register a zero-arg snapshot callable rendered into every bundle
    spill (``kind="inflight"`` → inflight.json, else state.json)."""
    with _PROVIDERS_LOCK:
        _PROVIDERS[name] = (kind, fn)


def unregister_provider(name: str) -> None:
    with _PROVIDERS_LOCK:
        _PROVIDERS.pop(name, None)


def _provider_snapshot(kind: str) -> Dict[str, Any]:
    with _PROVIDERS_LOCK:
        items = [(n, f) for n, (k, f) in _PROVIDERS.items() if k == kind]
    out: Dict[str, Any] = {}
    for name, fn in items:
        try:
            out[name] = fn()
        except Exception as exc:  # a dying owner must not kill the spill
            out[name] = f"provider failed: {type(exc).__name__}: {exc}"
    return out


# -- the progress watchdog ---------------------------------------------------

class Watchdog:
    """Trips when any guarded seam is active but NOTHING has progressed
    for ``sec`` — one global progress clock (every guard enter/exit and
    every :func:`watchdog_beat` advances it), so a fleet of busy seams
    never false-trips while one wedged `score_lines` still does."""

    def __init__(self):
        self.sec = 0.0
        self._lock = threading.Lock()
        self._guards: Dict[str, List[float]] = {}   # site -> [depth, t0]
        self.last_progress = time.monotonic()
        self._tripped = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def arm(self, sec: float) -> None:
        self.sec = float(sec)
        if self.sec <= 0 or (
                self._thread is not None and self._thread.is_alive()):
            return
        self._stop.clear()
        self.last_progress = time.monotonic()
        self._thread = threading.Thread(
            target=self._watch, daemon=True, name="graftbox-watchdog")
        self._thread.start()

    def disarm(self) -> None:
        self.sec = 0.0
        self._stop.set()
        thread = self._thread
        self._thread = None
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)
        with self._lock:
            self._guards.clear()
        self._tripped = False

    def enter(self, site: str) -> None:
        now = time.monotonic()
        with self._lock:
            cell = self._guards.get(site)
            if cell is None:
                self._guards[site] = [1.0, now]
            else:
                cell[0] += 1
        self.last_progress = now

    def exit(self, site: str) -> None:
        now = time.monotonic()
        with self._lock:
            cell = self._guards.get(site)
            if cell is not None:
                cell[0] -= 1
                if cell[0] <= 0:
                    del self._guards[site]
        self.last_progress = now

    def beat(self) -> None:
        self.last_progress = time.monotonic()

    def snapshot(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            active = {site: {"depth": int(cell[0]),
                             "active_s": round(now - cell[1], 3)}
                      for site, cell in self._guards.items()}
        return {"sec": self.sec, "active": active,
                "silent_s": round(now - self.last_progress, 3),
                "tripped": self._tripped}

    def _watch(self) -> None:
        interval = min(max(self.sec / 4.0, 0.05), 1.0)
        while not self._stop.wait(interval):
            try:
                self.check_once()
            except Exception:                      # never kill the checker
                ring_record("blackbox.error",
                            {"site": "watchdog", "exc": "check failed"})

    def check_once(self) -> None:
        """One deadline check (public for deterministic tests)."""
        if self.sec <= 0:
            return
        now = time.monotonic()
        silent = now - self.last_progress
        if silent <= self.sec:
            self._tripped = False             # progress resumed: re-latch
            return
        with self._lock:
            active = [(cell[1], site)
                      for site, cell in self._guards.items()]
        if not active or self._tripped:
            return
        self._tripped = True                  # one trip per excursion
        site = min(active)[1]                 # the oldest silent seam
        # the emit seam records to the flight ring on BOTH sides of
        # trace.on — no explicit ring_record here or the off state
        # would hold the event twice
        from avenir_tpu.telemetry import spans as tel

        tel.tracer().event("hang.detected", site=site,
                           silent_s=round(silent, 3), threshold=self.sec)
        _BOX.finalize(f"hang:{site}")


_WATCHDOG = Watchdog()
_NULL_GUARD = contextlib.nullcontext()


class _Guard:
    __slots__ = ("site",)

    def __init__(self, site: str):
        self.site = site

    def __enter__(self):
        _WATCHDOG.enter(self.site)
        return self

    def __exit__(self, *exc):
        _WATCHDOG.exit(self.site)
        return False


def watchdog_guard(site: str):
    """Mark a long-running seam: while the region is open the watchdog
    holds this process accountable for progress.  Off (the default — no
    ``blackbox.watchdog.sec``): the shared inert context, one attribute
    check, no allocation."""
    if _WATCHDOG.sec <= 0:
        return _NULL_GUARD
    return _Guard(site)


def watchdog_beat() -> None:
    """Progress tick from inside a guarded region (chunk loops, queue
    waits): being slow is not being wedged."""
    if _WATCHDOG.sec > 0:
        _WATCHDOG.beat()


# -- the bundle writer -------------------------------------------------------

def _atomic_write(path: str, data: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(data)
    os.replace(tmp, path)


def _json_dumps(obj: Any) -> str:
    return json.dumps(obj, separators=(",", ":"), default=repr)


class BlackBox:
    """The per-process forensics writer: armed by ``blackbox.dir``, it
    keeps a live bundle current and latches it ``final`` exactly once —
    on crash, fatal signal, or watchdog trip (first cause wins)."""

    def __init__(self):
        self.armed = False
        self.dir: Optional[str] = None
        self.bundle_path: Optional[str] = None
        self.run = ""
        self.writer = ""
        self.flush_sec = 1.0
        self.conf_props: Dict[str, str] = {}
        self._finalized = threading.Event()
        self._journaled = False
        self._reason = ""
        self._stop = threading.Event()
        self._flusher: Optional[threading.Thread] = None
        self._prev_excepthook = None
        self._prev_threadhook = None
        self._prev_sigterm = None
        self._sigterm_installed = False
        self._atexit_registered = False
        self._capture_seq = 0

    # -- identity ------------------------------------------------------------
    @staticmethod
    def _process_index() -> int:
        env = os.environ.get(_ENV_PID)
        if env:
            try:
                return int(env)
            except ValueError:
                return 0
        if "jax" in sys.modules:       # never pay a jax import for identity
            try:
                return sys.modules["jax"].process_index()
            except Exception:
                return 0
        return 0

    def _resolve_identity(self, conf) -> None:
        from avenir_tpu.telemetry import spans as tel

        self.run = tel.fleet_run_id(conf)
        proc = self._process_index()
        suffix = (conf.get("trace.writer.suffix", "")
                  or os.environ.get(_ENV_SUFFIX, "")
                  or conf.get("tenant.id", "") or "")
        self.writer = f"proc-{proc}" + (f"-{suffix}" if suffix else "")

    # -- lifecycle -----------------------------------------------------------
    def arm(self, conf) -> None:
        if self.armed:
            return
        bb_dir = conf.get("blackbox.dir")
        if not bb_dir:
            return
        self.dir = bb_dir
        self.flush_sec = conf.get_float("blackbox.flush.sec", 1.0)
        self._resolve_identity(conf)
        self.conf_props = {str(k): str(v) for k, v in conf.props.items()}
        self.bundle_path = os.path.join(
            bb_dir, f"bundle-{self.run}-{self.writer}")
        os.makedirs(self.bundle_path, exist_ok=True)
        self._finalized.clear()
        self._journaled = False
        self._reason = ""
        self.armed = True
        self._install_hooks()
        self.spill("live")                   # a bundle exists from t=0
        if self.flush_sec > 0:
            self._stop.clear()
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True, name="graftbox-flush")
            self._flusher.start()

    def _install_hooks(self) -> None:
        if self._prev_excepthook is None:
            self._prev_excepthook = sys.excepthook
            sys.excepthook = self._excepthook
        if self._prev_threadhook is None:
            self._prev_threadhook = threading.excepthook
            threading.excepthook = self._threadhook
        if not self._sigterm_installed:
            try:
                self._prev_sigterm = signal.signal(
                    signal.SIGTERM, self._sigterm)
                self._sigterm_installed = True
            except ValueError:     # non-main thread: the host CLI owns it
                self._prev_sigterm = None
        if not self._atexit_registered:
            atexit.register(self._atexit)
            self._atexit_registered = True

    def _excepthook(self, exc_type, exc, tb) -> None:
        try:
            text = "".join(traceback.format_exception(exc_type, exc, tb))
            self.finalize(f"crash:{exc_type.__name__}", exc_text=text)
        except Exception:
            ring_record("blackbox.error", {"site": "excepthook"})
        prev = self._prev_excepthook or sys.__excepthook__
        prev(exc_type, exc, tb)

    def _threadhook(self, args) -> None:
        try:
            if args.exc_type is not SystemExit:
                text = "".join(traceback.format_exception(
                    args.exc_type, args.exc_value, args.exc_traceback))
                self.finalize(
                    f"crash:{args.exc_type.__name__}:thread", exc_text=text)
        except Exception:
            ring_record("blackbox.error", {"site": "threadhook"})
        prev = self._prev_threadhook or threading.__excepthook__
        prev(args)

    def _sigterm(self, signum, frame) -> None:
        self.finalize("signal:SIGTERM")
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        elif prev is not None:           # SIG_DFL/SIG_IGN: replay faithfully
            signal.signal(signal.SIGTERM, prev)
            os.kill(os.getpid(), signal.SIGTERM)

    def _atexit(self) -> None:
        # clean exit: a run that neither crashed, hung, nor was signalled
        # leaves NO bundle — the live spill is removed, not finalized
        self._stop.set()
        if self.armed and not self._finalized.is_set() and self.bundle_path:
            shutil.rmtree(self.bundle_path, ignore_errors=True)
            self.armed = False

    def _flush_loop(self) -> None:
        while not self._stop.wait(self.flush_sec):
            try:
                if not self._finalized.is_set():
                    self.spill("live")
            except Exception:            # spill failure must not kill owner
                ring_record("blackbox.error", {"site": "flush"})

    # -- the bundle itself ---------------------------------------------------
    def spill(self, status: str, reason: str = "", exc_text: str = "",
              path: Optional[str] = None) -> None:
        """Write every bundle file, each atomically (a SIGKILL between
        files leaves the previous consistent versions)."""
        bundle = path or self.bundle_path
        if bundle is None:
            return
        os.makedirs(bundle, exist_ok=True)
        snap = ring_snapshot()
        lines = [_json_dumps(rec) for rec in snap]
        _atomic_write(os.path.join(bundle, "ring.jsonl"),
                      "\n".join(lines) + ("\n" if lines else ""))
        self._spill_stacks(os.path.join(bundle, "stacks.txt"), exc_text)
        _atomic_write(os.path.join(bundle, "inflight.json"),
                      _json_dumps(_provider_snapshot("inflight")))
        _atomic_write(os.path.join(bundle, "state.json"),
                      _json_dumps(self._state_snapshot()))
        _atomic_write(os.path.join(bundle, "memory.json"),
                      _json_dumps(self._memory_snapshot()))
        _atomic_write(os.path.join(bundle, "conf.json"),
                      _json_dumps({"run": self.run, "writer": self.writer,
                                   "props": self.conf_props}))
        _atomic_write(os.path.join(bundle, "meta.json"), _json_dumps({
            "status": status, "reason": reason or self._reason,
            "ts": round(time.time(), 6), "pid": os.getpid(),
            "run": self.run, "writer": self.writer,
            "argv": list(sys.argv), "journaled": self._journaled,
            "events": len(snap)}))

    @staticmethod
    def _spill_stacks(path: str, exc_text: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            if exc_text:
                fh.write(exc_text)
                fh.write("\n--- all threads ---\n")
            try:
                faulthandler.dump_traceback(file=fh, all_threads=True)
            except Exception:
                fh.write("faulthandler unavailable\n")
        os.replace(tmp, path)

    def _state_snapshot(self) -> Dict[str, Any]:
        state: Dict[str, Any] = {"watchdog": _WATCHDOG.snapshot()}
        state.update(_provider_snapshot("state"))
        try:
            from avenir_tpu import tenancy

            pool = tenancy.pool()
            state["arbiter"] = {"stats": pool.stats(),
                                "queues": pool.queue_depths()}
        except Exception:
            state["arbiter"] = None
        return state

    @staticmethod
    def _memory_snapshot() -> Dict[str, Any]:
        try:
            from avenir_tpu.telemetry import profile as prof_mod

            prof = prof_mod.profiler()
            gauges = {f"{dev}/{kind}": val
                      for (dev, kind), val in prof.gauges().items()}
            return {"device_memory": gauges, "programs": prof.stats()}
        except Exception:
            return {"device_memory": {}, "programs": {}}

    # -- latching ------------------------------------------------------------
    def finalize(self, reason: str, exc_text: str = "") -> Optional[str]:
        """Latch the bundle ``final`` — once per process, first cause
        wins — and journal ``bundle.written`` when tracing is on.
        Returns the bundle path (None when unarmed/already latched)."""
        if not self.armed or self._finalized.is_set():
            return None
        self._finalized.set()
        self._reason = reason
        self._stop.set()
        events = len(_RING)
        try:
            from avenir_tpu.telemetry import spans as tel

            tracer = tel.tracer()
            if tracer.enabled and tracer.journal is not None:
                # the emit seam rings it too — one ring entry either way
                tracer.event("bundle.written", dir=self.bundle_path,
                             reason=reason, events=events)
                self._journaled = True
        except Exception:                    # dying: the bundle still lands
            ring_record("blackbox.error", {"site": "finalize.journal"})
        if not self._journaled:
            ring_record("bundle.written", {"dir": self.bundle_path,
                                           "reason": reason,
                                           "events": events})
        try:
            self.spill("final", reason=reason, exc_text=exc_text)
        except Exception:
            return None
        return self.bundle_path

    def capture(self, reason: str) -> Optional[str]:
        """A NON-latching one-shot bundle (``<bundle>-c<n>/``) — the
        GlobalRouter's breaker-open snapshot: the router records what it
        saw without spending its own crash latch."""
        if not self.armed or self.bundle_path is None:
            return None
        self._capture_seq += 1
        path = f"{self.bundle_path}-c{self._capture_seq}"
        events = len(_RING)
        journaled = self._journaled
        try:
            from avenir_tpu.telemetry import spans as tel

            tracer = tel.tracer()
            if tracer.enabled and tracer.journal is not None:
                tracer.event("bundle.written", dir=path, reason=reason,
                             events=events)
                journaled = True
        except Exception:
            journaled = False
        if not journaled:
            ring_record("bundle.written", {"dir": path, "reason": reason,
                                           "events": events})
        try:
            prev, self._journaled = self._journaled, journaled
            self.spill("final", reason=reason, path=path)
            self._journaled = prev
        except Exception:
            return None
        return path

    def reset(self) -> None:
        """Tear down hooks/threads and disarm — test isolation."""
        self._stop.set()
        if self._flusher is not None and self._flusher.is_alive():
            self._flusher.join(timeout=5.0)
        self._flusher = None
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        if self._prev_threadhook is not None:
            threading.excepthook = self._prev_threadhook
            self._prev_threadhook = None
        if self._sigterm_installed:
            try:
                signal.signal(signal.SIGTERM,
                              self._prev_sigterm or signal.SIG_DFL)
            except ValueError:
                pass
            self._sigterm_installed = False
            self._prev_sigterm = None
        self.armed = False
        self.dir = None
        self.bundle_path = None
        self._finalized.clear()
        self._journaled = False
        self._reason = ""
        self._capture_seq = 0
        _WATCHDOG.disarm()


_BOX = BlackBox()


def box() -> BlackBox:
    return _BOX


def configure(conf) -> None:
    """GraftBox's slice of ``telemetry.configure`` — called for every
    tracer configure with the same conf.  Cheap when unconfigured: three
    dict lookups, no threads, no files."""
    ring_cap = conf.get_int("blackbox.ring.events", 0)
    if ring_cap:
        _ring_resize(ring_cap)
    wd_sec = conf.get_float("blackbox.watchdog.sec", 0.0)
    if wd_sec > 0:
        _WATCHDOG.arm(wd_sec)
    _BOX.arm(conf)


def finalize(reason: str, exc_text: str = "") -> Optional[str]:
    return _BOX.finalize(reason, exc_text=exc_text)


def capture(reason: str) -> Optional[str]:
    return _BOX.capture(reason)


def on_signal(name: str) -> None:
    """Host-CLI signal handlers (the serving frontend owns SIGTERM) call
    this before their own shutdown path — no-op when unarmed."""
    _BOX.finalize(f"signal:{name}")


def reset() -> None:
    _BOX.reset()


# -- the teardown sweep ------------------------------------------------------

def read_meta(bundle_path: str) -> Dict[str, Any]:
    try:
        with open(os.path.join(bundle_path, "meta.json"),
                  encoding="utf-8") as fh:
            return json.load(fh)
    except Exception:
        return {}


def _pid_alive(pid: Optional[int]) -> bool:
    if not pid:
        return False
    try:
        os.kill(int(pid), 0)
    except (OSError, ValueError):
        return False
    return True


def sweep(blackbox_dir: str, journal_dir: Optional[str] = None,
          run_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Finalize dead processes' bundles and journal the unjournaled ones.

    The launcher/GlobalServe teardown calls this BEFORE the fleet merge:
    every ``bundle-*`` whose writing pid is gone is marked ``swept``,
    and each bundle no process journaled (a SIGKILL runs no hook; a
    crash with tracing off has no journal) gets exactly ONE
    ``bundle.written`` appended to a sweep shard of the run
    (``run-<id>.proc-<k>-sweep.jsonl``) so the merged fleet journal
    accounts for every dead worker.  Idempotent: swept-and-journaled
    bundles are reported but never re-journaled.  Returns one record per
    surviving bundle (dir/reason/status/events/journaled)."""
    if not blackbox_dir or not os.path.isdir(blackbox_dir):
        return []
    found = []
    for name in sorted(os.listdir(blackbox_dir)):
        path = os.path.join(blackbox_dir, name)
        if not name.startswith("bundle-") or not os.path.isdir(path):
            continue
        meta = read_meta(path)
        if not meta:
            continue
        pid = meta.get("pid")
        if pid == os.getpid() or (meta.get("status") == "live"
                                  and _pid_alive(pid)):
            continue                       # writer still running: not ours
        found.append((path, meta))
    swept: List[Dict[str, Any]] = []
    journal = None
    try:
        for path, meta in found:
            status = meta.get("status")
            reason = meta.get("reason") or (
                "killed" if status == "live" else "unknown")
            if status == "live":
                meta["status"] = "swept"
                meta["reason"] = reason
            if not meta.get("journaled") and journal_dir:
                if journal is None:
                    journal = _sweep_journal(journal_dir,
                                             run_id or meta.get("run"))
                if journal is not None:
                    journal.emit("bundle.written", trace=None, span=None,
                                 dir=path, reason=reason,
                                 events=int(meta.get("events") or 0))
                    meta["journaled"] = True
            try:
                _atomic_write(os.path.join(path, "meta.json"),
                              _json_dumps(meta))
            except Exception:
                ring_record("blackbox.error", {"site": "sweep", "dir": path})
            swept.append({"dir": path, "reason": meta.get("reason"),
                          "status": meta.get("status"),
                          "events": meta.get("events"),
                          "journaled": bool(meta.get("journaled")),
                          "writer": meta.get("writer")})
    finally:
        if journal is not None:
            journal.close()
    return swept


def _sweep_journal(journal_dir: str, run_id: Optional[str]):
    """The sweeper's own journal shard — raw (the sweeping process's
    tracer may be off or pointed elsewhere), named so ``find_shards``
    merges it with the run it accounts for."""
    import socket

    from avenir_tpu.telemetry.journal import Journal

    rid = run_id or "sweep"
    proc = BlackBox._process_index()
    path = os.path.join(journal_dir, f"run-{rid}.proc-{proc}-sweep.jsonl")
    try:
        os.makedirs(journal_dir, exist_ok=True)
        return Journal(path, stamp={"proc": proc,
                                    "host": socket.gethostname()})
    except Exception:
        return None
