"""Prometheus text-format rendering of the in-process observability state.

The one scrapeable surface over the three metric shapes the framework
already has: :class:`~avenir_tpu.utils.metrics.Counters` (named counter
groups — the Hadoop-counter stand-in), per-model
:class:`~avenir_tpu.utils.metrics.LatencyTracker` percentiles, and
point-in-time gauges (queue depths).  Served from the scoring-plane
frontend's ``/metrics`` route (``serving/frontend.py``) in the Prometheus
text exposition format (version 0.0.4), so a stock Prometheus scrape —
or ``curl`` — reads the same counters the job layer prints and the
journal snapshots.

Counter groups/names keep their in-tree dotted spelling as label values
(``group="Serving.naiveBayes", name="bucket.8"``) rather than being
mangled into metric names — the cardinality lives in labels, and the
label values round-trip exactly to what ``Counters.as_dict`` reports.

GraftFleet (round 15): every sample can carry writer-identity labels
(``process``/``replica`` — :func:`fleet_identity`) so federated scrapes
from N workers/replicas of one deployment never collide on identical
series names.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional


def _escape(value: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_text(labels: Optional[Mapping[str, str]]) -> str:
    """The writer-identity label prefix spliced into every sample:
    ``'process="1",replica="a",'`` (trailing comma so metric-specific
    labels append directly), or ``''`` when no identity was given."""
    if not labels:
        return ""
    return "".join(f'{k}="{_escape(v)}",' for k, v in sorted(labels.items()))


def fleet_identity(replica: Optional[str] = None,
                   tenant: Optional[str] = None,
                   worker: Optional[str] = None) -> Dict[str, str]:
    """This writer's scrape identity: the jax process index (0 outside a
    distributed run — guarded, never initializes a backend by surprise)
    plus the replica/worker suffix when the deployment sets one
    (``trace.writer.suffix`` — the same knob that names the journal
    shard, so scrape labels and shard names agree) and — GraftPool,
    round 18 — the tenant a dedicated serving plane belongs to
    (``tenant.id``), so per-tenant scrapes never collide on series.

    ``worker`` (GlobalServe, this round) names the serving PROCESS in a
    launched fleet — ``w<k>`` on workers, ``router`` on the global
    frontend — so every ``/metrics`` scrape in the fleet is
    distinguishable even when two workers run identical replica sets."""
    proc = 0
    try:
        import jax

        proc = jax.process_index()
    except Exception:                              # pragma: no cover
        pass
    out = {"process": str(proc)}
    if replica:
        out["replica"] = str(replica)
    if tenant:
        out["tenant"] = str(tenant)
    if worker:
        out["worker"] = str(worker)
    return out


def render_counters(counters, lines: List[str],
                    labels: Optional[Mapping[str, str]] = None) -> None:
    base = _label_text(labels)
    lines.append("# HELP avenir_counter_total Named job/serving counters "
                 "(Counters groups).")
    lines.append("# TYPE avenir_counter_total counter")
    groups = counters.as_dict()
    for group in sorted(groups):
        for name in sorted(groups[group]):
            lines.append(
                f'avenir_counter_total{{{base}group="{_escape(group)}",'
                f'name="{_escape(name)}"}} {groups[group][name]}')


def render_latency(latency: Mapping[str, object], lines: List[str],
                   labels: Optional[Mapping[str, str]] = None) -> None:
    base = _label_text(labels)
    lines.append("# HELP avenir_latency_seconds Request latency over the "
                 "retained ring window.")
    lines.append("# TYPE avenir_latency_seconds summary")
    for model in sorted(latency):
        tracker = latency[model]
        for q in (50.0, 99.0):
            lines.append(
                f'avenir_latency_seconds{{{base}model="{_escape(model)}",'
                f'quantile="{q / 100.0:g}"}} {tracker.percentile(q):.6g}')
        lines.append(
            f'avenir_latency_seconds_count{{{base}model="{_escape(model)}"}} '
            f"{tracker.count}")


def render_gauges(gauges: Mapping[str, float], lines: List[str],
                  labels: Optional[Mapping[str, str]] = None) -> None:
    base = _label_text(labels)
    lines.append("# HELP avenir_gauge Point-in-time gauges (queue depths, "
                 "uptime).")
    lines.append("# TYPE avenir_gauge gauge")
    for name in sorted(gauges):
        lines.append(
            f'avenir_gauge{{{base}name="{_escape(name)}"}} {gauges[name]:g}')


def render_device_bytes(device_bytes: Mapping, lines: List[str],
                        labels: Optional[Mapping[str, str]] = None) -> None:
    """GraftProf device-memory gauges: ``{(device, kind): bytes}`` from
    :meth:`telemetry.profile.Profiler.gauges` — ``kind`` is
    ``bytes_in_use`` / ``peak_bytes`` as ``device.memory_stats()``
    reports them."""
    base = _label_text(labels)
    lines.append("# HELP avenir_device_bytes Device memory "
                 "(device.memory_stats) sampled at dispatch boundaries.")
    lines.append("# TYPE avenir_device_bytes gauge")
    for device, kind in sorted(device_bytes):
        lines.append(
            f'avenir_device_bytes{{{base}device="{_escape(device)}",'
            f'kind="{_escape(kind)}"}} {device_bytes[(device, kind)]:g}')


def prometheus_text(counters=None,
                    latency: Optional[Mapping[str, object]] = None,
                    gauges: Optional[Mapping[str, float]] = None,
                    device_bytes: Optional[Mapping] = None,
                    labels: Optional[Mapping[str, str]] = None) -> str:
    """The full exposition document; any section may be omitted.
    ``labels`` (process/replica identity) splice into every sample."""
    lines: List[str] = []
    if counters is not None:
        render_counters(counters, lines, labels=labels)
    if latency:
        render_latency(latency, lines, labels=labels)
    if gauges:
        render_gauges(gauges, lines, labels=labels)
    if device_bytes:
        render_device_bytes(device_bytes, lines, labels=labels)
    return "\n".join(lines) + "\n"
