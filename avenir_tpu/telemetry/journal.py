"""Append-only JSONL event journal — the durable half of GraftTrace.

One journal file per traced run.  Every event is one JSON object on one
line (``{"ev": ..., "ts": ..., ...}``), written append-only and flushed
per event so a wedged or killed run leaves a readable timeline up to the
moment it died — the diagnostic the Hadoop job UI gave the reference and
this port lacked (ISSUE 5).  Three disciplines:

- **single writer**: the journal takes the existing advisory
  :class:`~avenir_tpu.utils.locking.FileLock` on open and holds it for its
  lifetime, so a second process appending to the same file is *detected*
  (LockHeldError) instead of interleaving torn lines; in multi-process
  runs only process 0 opens a journal at all
  (``telemetry.spans.configure``).
- **rotation-bounded**: when the file would exceed
  ``telemetry.journal.max.mb`` the current file rotates to ``<path>.1``
  (replacing the previous rotation), so a long-lived serving process
  cannot grow the journal without bound.
- **torn-tail tolerance**: a crash mid-``write`` leaves at most one
  partial final line; :func:`read_events` skips it (and any other
  undecodable line) so every event that was fully written stays
  readable.

GraftFleet (round 15) adds the SHARD layer on top: a multi-process (or
replica-pool) run writes one journal shard per writer —
``run-<id>.proc-<k>[-<suffix>].jsonl``, every event stamped with the
writer identity (``stamp``) — and :func:`merge_shards` /
:func:`find_shards` reassemble a run's shards into one time-ordered
fleet view, tolerating torn tails and shards missing entirely (a
crashed or preempted worker's shard simply ends early; its open spans
render as ``OPEN``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from avenir_tpu.utils.locking import FileLock


class Journal:
    """Single-writer append-only JSONL sink.

    ``emit`` is thread-safe (serving dispatch threads, feeder workers and
    the pipeline thread all write to the one run journal); cross-process
    exclusion is the FileLock's job.
    """

    def __init__(self, path: str, max_bytes: int = 64 << 20,
                 lock_timeout_s: float = 0.0,
                 stamp: Optional[Dict[str, object]] = None):
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        self.path = path
        self.max_bytes = max(int(max_bytes), 1 << 12)
        # writer-identity stamp merged into EVERY record (GraftFleet):
        # proc/host/replica, so a merged fleet view attributes each event
        # to the process that wrote it without parsing shard filenames
        self.stamp = dict(stamp or {})
        self._mutex = threading.Lock()
        # held for the journal's lifetime: a concurrent writer raises
        # LockHeldError here instead of silently interleaving lines
        self._flock = FileLock(path, timeout_s=lock_timeout_s).acquire()
        self._fh = open(path, "a", encoding="utf-8")
        self.events_written = 0

    def emit(self, ev: str, **fields) -> None:
        """Append one event; ``ev`` is the event type, ``ts`` is stamped
        here.  Non-serializable field values degrade to ``repr`` rather
        than losing the event."""
        record: Dict[str, object] = {"ev": ev, "ts": round(time.time(), 6)}
        record.update(self.stamp)
        record.update(fields)
        try:
            line = json.dumps(record, separators=(",", ":"))
        except (TypeError, ValueError):
            line = json.dumps({k: (v if isinstance(
                v, (str, int, float, bool, type(None))) else repr(v))
                for k, v in record.items()}, separators=(",", ":"))
        with self._mutex:
            if self._fh.closed:
                return                     # emit after close: drop, not crash
            if self._fh.tell() + len(line) + 1 > self.max_bytes:
                self._rotate()
            self._fh.write(line)
            self._fh.write("\n")
            self._fh.flush()
            self.events_written += 1

    def _rotate(self) -> None:
        """Roll the full file to ``<path>.1`` (replacing the previous
        rotation) and start fresh — append-only within a file, bounded
        across the pair."""
        self._fh.close()
        os.replace(self.path, self.path + ".1")
        self._fh = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        with self._mutex:
            if not self._fh.closed:
                self._fh.flush()
                self._fh.close()
            self._flock.release()

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def iter_events(path: str) -> Iterator[dict]:
    """Yield every decodable event of a journal file in write order.

    A truncated final line (crash mid-write) or any other undecodable
    line is skipped — the journal contract is that every *fully written*
    event survives, not that the file as a whole is one valid document."""
    with open(path, encoding="utf-8", errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except ValueError:
                continue                  # torn tail / corrupt line
            if isinstance(event, dict):
                yield event


def read_events(path: str, with_rotated: bool = True) -> List[dict]:
    """All events of a journal (rotated ``<path>.1`` first when present,
    so the list stays in write order across a rotation)."""
    out: List[dict] = []
    if with_rotated and os.path.exists(path + ".1"):
        out.extend(iter_events(path + ".1"))
    out.extend(iter_events(path))
    return out


def latest_journal(directory: str) -> Optional[str]:
    """The most recently modified ``run-*.jsonl`` under ``directory``."""
    try:
        names = [n for n in os.listdir(directory)
                 if n.startswith("run-") and n.endswith(".jsonl")]
    except OSError:
        return None
    if not names:
        return None
    return os.path.join(directory, max(
        names, key=lambda n: os.path.getmtime(os.path.join(directory, n))))


# ---------------------------------------------------------------------------
# GraftFleet shard discovery + federation (round 15)
# ---------------------------------------------------------------------------

def shard_run_id(name: str) -> Optional[str]:
    """The run id a shard filename encodes: ``run-<id>.jsonl`` (legacy
    single-writer) or ``run-<id>.proc-<k>[-<suffix>].jsonl`` (fleet
    shard); None for anything else (rotations, merged outputs)."""
    if not name.startswith("run-") or not name.endswith(".jsonl"):
        return None
    body = name[len("run-"):-len(".jsonl")]
    return body.split(".proc-", 1)[0] if body else None


def find_shards(directory: str,
                run_id: Optional[str] = None) -> Dict[str, List[str]]:
    """run id → sorted shard paths under ``directory``.  Tolerates
    missing shards trivially (a crashed/preempted worker's shard simply
    is not there); ``run_id`` filters to one run."""
    out: Dict[str, List[str]] = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        rid = shard_run_id(name)
        if rid is None or (run_id is not None and rid != run_id):
            continue
        out.setdefault(rid, []).append(os.path.join(directory, name))
    return out


def merge_shards(paths: List[str]) -> List[dict]:
    """One time-ordered fleet view from a run's shard files.

    Reads every shard through :func:`read_events` (rotations included,
    torn tails skipped) and stably sorts by the event's effective time
    (``at`` when a retroactive event carries one, else ``ts``) — within
    one timestamp, shard order then write order is preserved, so a
    parent's ``span.open`` never sorts after its same-tick child from
    the same shard."""
    merged: List[dict] = []
    for path in paths:
        merged.extend(read_events(path))
    merged.sort(key=lambda e: float(e.get("at", e.get("ts", 0.0)) or 0.0))
    return merged


def merge_journals(directory: str, run_id: Optional[str] = None
                   ) -> Tuple[Optional[str], List[str], List[dict]]:
    """(run id, shard paths, merged events) for one run under
    ``directory``: the given ``run_id``, or the run whose newest shard
    was most recently written."""
    shards = find_shards(directory, run_id=run_id)
    if not shards:
        return None, [], []
    if run_id is None:
        run_id = max(shards, key=lambda rid: max(
            os.path.getmtime(p) for p in shards[rid]))
    paths = shards[run_id]
    return run_id, paths, merge_shards(paths)
