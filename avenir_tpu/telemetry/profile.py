"""GraftProf — the device-cost profiling plane (round 14).

GraftTrace (round 10) answers *where wall-time went*; this module answers
*what the device did for it*.  Three pieces, all free until ``profile.on``:

- :class:`CompiledProgramRegistry` — the process-wide compiled-program
  table.  Every dispatch seam that already feeds a
  :class:`~avenir_tpu.telemetry.spans.CompileKeyMonitor` (batch chunk
  streams, stream panes, the serving batcher) registers its compile keys
  here too; on each *new* ``(site, key)`` the registry captures the
  program's JAX AOT cost analysis — FLOPs estimate, bytes accessed,
  output/temp HBM bytes via ``lowered.compile().cost_analysis()`` /
  ``.memory_analysis()`` — and journals one golden-schema'd
  ``program.compiled`` event.  The capture is guarded end to end: a
  backend without cost analysis (or a seam that cannot hand over a
  lowerable) degrades to a shapes-only record, never raises.  Per-dispatch
  wall samples accumulate per program and flush to the journal as
  cumulative ``program.profile`` events (every
  ``_FLUSH_EVERY`` samples and at ``Tracer.disable``), so
  ``python -m avenir_tpu.telemetry profile <journal>`` can render a
  roofline-style table — dispatch counts, achieved FLOP/s, and an MFU
  column against the canary-derived peak — without a per-dispatch journal
  line.
- **Device memory gauges** — :meth:`Profiler.sample_device_memory` reads
  ``device.memory_stats()`` per local device at chunk/pane/swap/staging
  boundaries (a no-op where the backend reports nothing, e.g. this
  container's CPU transport), journals ``device.memory`` events and feeds
  the ``avenir_device_bytes{device=...,kind=...}`` gauges the serving
  ``/metrics`` route exposes — an HBM leak across stream windows or model
  hot-swaps becomes visible before it OOMs.
- ``configure(conf)`` — wired through ``telemetry.spans.configure`` so
  every entry point that configures tracing (driver, jobs, serving CLI)
  also configures profiling from the same conf.

Cost-capture honesty notes:

- flops/bytes are the XLA **cost model's estimates** for the compiled
  program, not hardware counters — good for rooflines and regressions,
  not for billing (docs/observability.md spells out the caveats).
- the AOT capture lowers+compiles the program once per distinct key; that
  duplicate compile is the price of the cost tables and is why profiling
  is opt-in (``profile.on``), never ambient.
- program identity is ``(site, compile key)``: two seams dispatching the
  same shapes are different programs, and the serving batcher's
  per-model keys never collide across models.

Stdlib + in-package imports only at module scope — JAX is imported
lazily inside the capture paths, so the journal CLI stays runnable on a
machine with no JAX installed.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, List, Optional, Tuple

from avenir_tpu.telemetry import spans as tel

_FLUSH_EVERY = 64          # journal a cumulative program.profile this often


def program_id(site: str, key) -> str:
    """Stable short id for a ``(site, compile key)`` program — what span
    ``program=`` attrs and journal events carry instead of the raw
    (arbitrarily long) shape tuple."""
    digest = hashlib.sha1(f"{site}|{key!r}".encode()).hexdigest()[:10]
    return f"p{digest}"


def aot_cost(lowerable, args: Tuple = (), kwargs: Optional[dict] = None
             ) -> Optional[Dict[str, Optional[float]]]:
    """JAX AOT cost/memory analysis for ``lowerable(*args, **kwargs)``.

    ``lowerable`` is a jitted callable (anything with ``.lower``).  Every
    step is guarded: a backend whose compiled executable exposes no
    ``cost_analysis``/``memory_analysis`` (or a lowerable that refuses the
    given operands) returns None — the registry then records a shapes-only
    program, never an exception."""
    if lowerable is None or not hasattr(lowerable, "lower"):
        return None
    try:
        compiled = lowerable.lower(*args, **(kwargs or {})).compile()
    except Exception:
        return None
    out: Dict[str, Optional[float]] = {
        "flops": None, "bytes_accessed": None,
        "output_bytes": None, "temp_bytes": None,
    }
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if isinstance(ca, dict):
            if ca.get("flops") is not None:
                out["flops"] = float(ca["flops"])
            if ca.get("bytes accessed") is not None:
                out["bytes_accessed"] = float(ca["bytes accessed"])
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            out["output_bytes"] = float(
                getattr(ma, "output_size_in_bytes", 0) or 0)
            out["temp_bytes"] = float(
                getattr(ma, "temp_size_in_bytes", 0) or 0)
    except Exception:
        pass
    if all(v is None for v in out.values()):
        return None
    return out


class Profiler:
    """Process-wide program registry + device-memory gauges.

    Disabled (one attribute check at every seam) until :meth:`enable`;
    ``configure(conf)`` wires it from ``profile.*`` keys.  All mutation is
    lock-guarded: the serving dispatcher, stream pane folds and batch
    chunk loops register and sample concurrently."""

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        # (site, key) → program record; insertion order = discovery order
        self._programs: Dict[Tuple[str, Any], dict] = {}
        self._gauges: Dict[Tuple[str, str], float] = {}
        self._mem_every = 1
        self._mem_calls: Dict[str, int] = {}

    # -- lifecycle -----------------------------------------------------------
    def enable(self, memory_sample: int = 1) -> "Profiler":
        with self._lock:
            self.enabled = True
            self._mem_every = max(int(memory_sample), 0)
        return self

    def disable(self) -> None:
        """Drop all registered state (run teardown, tests).  Does NOT
        flush — ``Tracer.disable`` flushes first, then calls this."""
        with self._lock:
            self.enabled = False
            self._programs.clear()
            self._gauges.clear()
            self._mem_calls.clear()

    # -- program registry ----------------------------------------------------
    def observe(self, key, site: str, lowerable=None, args: Tuple = (),
                kwargs: Optional[dict] = None) -> Optional[str]:
        """Register a dispatch program; returns its id (None when
        disabled).  The first observation of a ``(site, key)`` — and only
        the first, even under racing threads — captures AOT cost analysis
        and journals ``program.compiled``; later observations are a dict
        hit."""
        if not self.enabled:
            return None
        with self._lock:
            rec = self._programs.get((site, key))
            if rec is not None:
                return rec["id"]
            pid = program_id(site, key)
            rec = {"id": pid, "site": site, "key": key, "cost": None,
                   "dispatches": 0, "wall_s": 0.0, "flushed": 0}
            self._programs[(site, key)] = rec
        # cost capture outside the lock: lowering+compiling can take
        # arbitrarily long and other seams must keep registering.  The
        # record is already published, so a racing observe() of the same
        # key returns the id immediately and never double-journals.
        cost = aot_cost(lowerable, args, kwargs)
        rec["cost"] = cost
        tel.tracer().event(
            "program.compiled", key=pid, site=site,
            flops=(cost or {}).get("flops"),
            bytes_accessed=(cost or {}).get("bytes_accessed"),
            output_bytes=(cost or {}).get("output_bytes"),
            temp_bytes=(cost or {}).get("temp_bytes"),
            source="aot" if cost is not None else "shapes",
            shapes=repr(key)[:512])
        return pid

    def sample(self, key, site: str, dur_s: float) -> None:
        """Accumulate one dispatch's wall time against its program
        (auto-registering shapes-only if the seam never observed it)."""
        if not self.enabled:
            return
        with self._lock:
            rec = self._programs.get((site, key))
        if rec is None:
            self.observe(key, site)
            with self._lock:
                rec = self._programs.get((site, key))
            if rec is None:                      # disabled mid-flight
                return
        with self._lock:
            rec["dispatches"] += 1
            rec["wall_s"] += float(dur_s)
            due = rec["dispatches"] - rec["flushed"] >= _FLUSH_EVERY
            if due:
                rec["flushed"] = rec["dispatches"]
                snap = (rec["id"], rec["site"], rec["dispatches"],
                        rec["wall_s"])
        if due:
            self._emit_profile(*snap)

    @staticmethod
    def _emit_profile(pid: str, site: str, dispatches: int,
                      wall_s: float) -> None:
        tel.tracer().event("program.profile", key=pid, site=site,
                           dispatches=dispatches,
                           wall_ms=round(wall_s * 1e3, 3))

    def flush(self) -> None:
        """Journal a cumulative ``program.profile`` event for every
        program with unflushed samples — called by ``Tracer.disable``
        before the journal closes, and usable explicitly (bench.py)."""
        if not self.enabled:
            return
        with self._lock:
            snaps = []
            for rec in self._programs.values():
                if rec["dispatches"] > rec["flushed"]:
                    rec["flushed"] = rec["dispatches"]
                    snaps.append((rec["id"], rec["site"],
                                  rec["dispatches"], rec["wall_s"]))
        for snap in snaps:
            self._emit_profile(*snap)

    def stats(self) -> List[dict]:
        """In-process program table snapshot (id, site, cost, dispatches,
        wall_ms) — discovery order."""
        with self._lock:
            return [{"id": rec["id"], "site": rec["site"],
                     "cost": dict(rec["cost"]) if rec["cost"] else None,
                     "dispatches": rec["dispatches"],
                     "wall_ms": round(rec["wall_s"] * 1e3, 3)}
                    for rec in self._programs.values()]

    # -- device memory gauges ------------------------------------------------
    def sample_device_memory(self, site: str, devices=None) -> None:
        """Sample ``memory_stats()`` of every local device into the gauge
        table + journal (one ``device.memory`` event per device).  No-op
        when the backend reports nothing (CPU transports return None) or
        when this site's sampling interval (``profile.memory.sample``)
        says skip.  Never raises — a flaky PJRT stats call must not kill
        the dispatch path that sampled it."""
        if not self.enabled:
            return
        with self._lock:
            if not self._mem_every:
                return
            n = self._mem_calls.get(site, 0)
            self._mem_calls[site] = n + 1
            if n % self._mem_every:
                return
        try:
            if devices is None:
                import jax

                devices = jax.local_devices()
            for dev in devices:
                stats = getattr(dev, "memory_stats", lambda: None)()
                if not isinstance(stats, dict):
                    continue
                in_use = stats.get("bytes_in_use")
                if in_use is None:
                    continue
                peak = stats.get("peak_bytes_in_use", in_use)
                label = f"{getattr(dev, 'platform', 'dev')}:" \
                        f"{getattr(dev, 'id', 0)}"
                with self._lock:
                    self._gauges[(label, "bytes_in_use")] = float(in_use)
                    self._gauges[(label, "peak_bytes")] = float(peak)
                tel.tracer().event("device.memory", site=site, device=label,
                                   bytes_in_use=int(in_use),
                                   peak_bytes=int(peak))
        except Exception:                          # pragma: no cover
            pass

    def gauges(self) -> Dict[Tuple[str, str], float]:
        """{(device, kind): bytes} — the ``avenir_device_bytes`` gauge set
        ``/metrics`` renders (empty until a device reports stats)."""
        with self._lock:
            return dict(self._gauges)


# the registry role under its own name — the Profiler IS the
# compiled-program registry plus the gauge table; seam docstrings and
# the ISSUE spec refer to it by this name
CompiledProgramRegistry = Profiler

_PROFILER = Profiler()


def profiler() -> Profiler:
    """The process profiler (disabled, hence free, until configured)."""
    return _PROFILER


def configure(conf) -> Profiler:
    """Enable the process profiler from ``profile.*`` conf keys; one dict
    lookup when ``profile.on`` is unset.  Reached through
    ``telemetry.spans.configure`` so every tracing entry point configures
    both planes from the same conf."""
    p = _PROFILER
    if p.enabled or not conf.get_bool("profile.on", False):
        return p
    return p.enable(memory_sample=conf.get_int("profile.memory.sample", 1))
