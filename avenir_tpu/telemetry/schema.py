"""The golden journal-event schema — ONE source of truth for event shapes.

Moved here from ``tests/test_telemetry.py`` (round 21) so the shape
contract is owned by the telemetry package and consumed from two sides:

- ``tests/test_telemetry.py::test_golden_event_shapes`` emits every event
  and asserts the journal's key sets match these exactly (tier-1 gate);
- graftlint's GL007 cross-checks every ``emit("x.y")`` literal in the
  tree against :data:`GOLDEN_EVENT_KEYS` and, conversely, that every
  schema event still has a live emit site — the same generated-registry
  discipline GL004 applies to config keys.

This module is deliberately stdlib-only with NO package imports: the
analyzer loads it standalone (``importlib.util.spec_from_file_location``)
and must never pull in jax.

Each entry maps an event name to its exact journal key set, excluding
the writer-identity stamp (:data:`STAMP_KEYS`) that rides every record.
Events with more than one legitimate producer shape (``checkpoint.save``
/ ``checkpoint.restore`` are written by both the stream checkpointer and
the RL supervisor with different fields) list the extra shapes in
:data:`EVENT_SHAPE_VARIANTS`; consumers should use :func:`event_shapes`.
"""

from typing import Dict, FrozenSet, Set, Tuple

GOLDEN_EVENT_KEYS: Dict[str, Set[str]] = {
    "span.open": {"ev", "ts", "trace", "span", "parent", "name", "attrs"},
    "span.close": {"ev", "ts", "trace", "span", "name", "dur_ms", "status",
                   "attrs"},
    "counters": {"ev", "ts", "trace", "span", "scope", "groups"},
    "gauge": {"ev", "ts", "trace", "span", "name", "value"},
    "recompile": {"ev", "ts", "trace", "span", "scope", "keys"},
    "checkpoint.save": {"ev", "ts", "trace", "span", "dir", "run", "rows",
                        "chunk"},
    # the stream checkpointer's restore record (stream/windows.py and
    # jobs/base.py share the shape) — the RL supervisor's variant lives
    # in EVENT_SHAPE_VARIANTS
    "checkpoint.restore": {"ev", "ts", "trace", "span", "dir", "run",
                           "rows", "chunk"},
    # the RL supervisor's restart record (pipeline/streaming.py): which
    # scope restarted, the cumulative restart count, and the error that
    # killed the previous incarnation
    "server.restart": {"ev", "ts", "trace", "span", "scope", "restarts",
                       "error"},
    # skipped-stage reporting (pipeline/driver.py): a stage whose output
    # artifact already exists is skipped, journaled with the artifact path
    "stage.skipped": {"ev", "ts", "trace", "span", "stage", "output"},
    # serving-plane replay (serving/replay.py): one record per replayed
    # request log
    "serve.replay": {"ev", "ts", "trace", "span", "model", "rows",
                     "max_inflight"},
    # the bench canary (bench.py): a tiny fixed device program timed
    # before and after the measured passes, so interference shows up in
    # the artifact
    "canary": {"ev", "ts", "trace", "span", "ms", "when"},
    # GraftFleet (round 15): per-device straggler probes
    # (parallel/skew.py — flagged when max/min exceeds the threshold),
    # cross-process collective-wait attribution (parallel/mesh.py), and
    # the SLO evaluator's transition-into-violation record
    # (telemetry/slo.py) — docs/observability.md event table
    "shard.skew": {"ev", "ts", "trace", "span", "chunk", "device_ms",
                   "max_ms", "min_ms", "ratio", "threshold", "slowest",
                   "flagged"},
    "collective.wait": {"ev", "ts", "trace", "span", "site", "wall_ms",
                        "bytes", "procs"},
    "slo.violation": {"ev", "ts", "trace", "span", "slo", "metric",
                      "value", "target", "burn_rate"},
    # the StreamGraft lifecycle (round 11): windowed drift scoring, the
    # sustained-drift firing, the retrain completion, and the serving
    # plane's hot swap — docs/observability.md event table
    "drift.window": {"ev", "ts", "trace", "span", "window", "divergence",
                     "threshold", "streak"},
    "drift.detected": {"ev", "ts", "trace", "span", "window", "divergence",
                       "threshold", "windows"},
    "drift.retrain": {"ev", "ts", "trace", "span", "window", "model",
                      "version", "rows", "dur_ms"},
    "drift.retrain.failed": {"ev", "ts", "trace", "span", "window", "model",
                             "error"},
    "model.swap": {"ev", "ts", "trace", "span", "model", "version",
                   "family", "warmed"},
    # ShardGraft (round 12): the run's hardware identity — journaled at
    # run start so every bench/journal artifact self-describes what it
    # ran on (device kind, mesh shape, axis names; CrossGraft added the
    # process count — a global mesh's axes carry the proc axis too)
    "shard.topology": {"ev", "ts", "trace", "span", "devices",
                       "device_kind", "mesh", "axes", "procs"},
    # CrossGraft (round 16): one coordinator-join record per worker —
    # the hardened bounded join (parallel/mesh.py::journal_fleet_join);
    # proc/host identity rides the GraftFleet stamp
    "fleet.join": {"ev", "ts", "trace", "span", "coordinator", "nprocs",
                   "attempts", "wall_ms"},
    # GraftProf (round 14): the compiled-program registry (one event per
    # distinct (site, compile key) with AOT cost fields — null when the
    # backend degrades to shapes-only), the cumulative per-program wall
    # totals, device-memory gauges, the bench sentinel's verdict, and the
    # per-stage XProf capture path — docs/observability.md event table
    "program.compiled": {"ev", "ts", "trace", "span", "key", "site",
                         "flops", "bytes_accessed", "output_bytes",
                         "temp_bytes", "source", "shapes"},
    "program.profile": {"ev", "ts", "trace", "span", "key", "site",
                        "dispatches", "wall_ms"},
    "device.memory": {"ev", "ts", "trace", "span", "site", "device",
                      "bytes_in_use", "peak_bytes"},
    "bench.regression": {"ev", "ts", "trace", "span", "verdict", "compared",
                         "regressed", "skipped", "missing", "baseline"},
    "xla.trace": {"ev", "ts", "trace", "span", "stage", "dir"},
    # ElasticGraft (round 16): a restore-time topology crossing — the
    # suffix a snapshot was written under, the one it was redistributed
    # onto, and how many accumulator entries moved
    # (checkpoint/reshard.py::journal_reshard) — and the conf-driven
    # fault family's injected-kill record (utils/retry.py::FaultPlan,
    # journaled BEFORE the raise so a killed run's journal explains
    # itself) — docs/observability.md event table
    "checkpoint.reshard": {"ev", "ts", "trace", "span", "dir", "run",
                           "src", "dst", "keys"},
    "fault.injected": {"ev", "ts", "trace", "span", "site", "hit"},
    # FleetServe (round 17): the replica pool's lifecycle — a replica
    # leaving rotation (died / heartbeat / breaker / scale.down, with how
    # many stranded requests were failed over), a replica entering it
    # (start / probe / replace / scale-up), an autoscaler decision over
    # the burn/queue gauges, and one request's failover hop — the events
    # docs/runbooks/replica_loss_triage.md walks (serving/pool.py)
    "pool.replica.down": {"ev", "ts", "trace", "span", "replica",
                          "reason", "pending"},
    "pool.replica.up": {"ev", "ts", "trace", "span", "replica", "reason"},
    "pool.scale": {"ev", "ts", "trace", "span", "direction", "ready",
                   "total", "burn", "queue_frac", "reason"},
    "pool.failover": {"ev", "ts", "trace", "span", "rid", "model",
                      "from", "to", "attempt"},
    # GlobalServe (round 20): the FleetServe lifecycle one level up —
    # worker PROCESSES joining/leaving the serving fleet (died/breaker/
    # retire vs spawn/probe), the burn-rate autoscaler at process
    # granularity, per-request failover hops ACROSS processes (`rid` is
    # the router's attempt-qualified id — the zero-lost/zero-double key
    # of the merged-journal accounting), and the rolling fleet-wide swap
    # with the ready-capacity floor it held (serving/global_pool.py).
    "fleet.pool.worker.down": {"ev", "ts", "trace", "span", "worker",
                               "reason", "pending"},
    "fleet.pool.worker.up": {"ev", "ts", "trace", "span", "worker",
                             "reason"},
    "fleet.pool.scale": {"ev", "ts", "trace", "span", "direction", "ready",
                         "total", "burn", "queue_frac", "reason"},
    "fleet.pool.failover": {"ev", "ts", "trace", "span", "rid", "model",
                            "from", "to", "attempt"},
    "fleet.pool.swap": {"ev", "ts", "trace", "span", "worker", "model",
                        "version", "ready", "floor"},
    # GraftPool (round 18): the tenant-arbitration lifecycle — a tenant's
    # contract admitted onto the pool (once per journal), the throttle
    # latch firing per excursion (quota/priority/share/backlog pacing),
    # and a tenant-scoped shed carrying the quota that fired plus the
    # queue drain estimate the HTTP 429's Retry-After renders
    # (tenancy/arbiter.py + serving/batcher.py's door shed — same shape)
    "tenant.admitted": {"ev", "ts", "trace", "span", "tenant", "share",
                        "priority", "max_inflight", "queue_depth"},
    "tenant.throttled": {"ev", "ts", "trace", "span", "tenant", "reason",
                         "waiting", "inflight"},
    "tenant.shed": {"ev", "ts", "trace", "span", "tenant", "quota",
                    "waiting", "inflight", "retry_after_ms"},
    # GraftBox (this round): the forensics plane — one record per
    # finalized crash/hang/signal bundle (self-journaled by the dying
    # process when tracing is on, else appended by the teardown sweep's
    # shard — telemetry/blackbox.py), and the progress watchdog's trip
    # record naming the oldest silent seam — docs/observability.md
    # event table, docs/runbooks/postmortem_triage.md
    "bundle.written": {"ev", "ts", "trace", "span", "dir", "reason",
                       "events"},
    "hang.detected": {"ev", "ts", "trace", "span", "site", "silent_s",
                      "threshold"},
    # PlanGraft (round 19): the planner's one record of what it decided
    # before anything executed — unit/stage shape, which rewrites fired,
    # and the summed AOT estimate (null when the backend degraded to
    # shapes-only) — pipeline/plan.py::journal_plan
    "plan.compiled": {"ev", "ts", "trace", "span", "units", "stages",
                      "fused", "rewrites", "source", "est_flops",
                      "est_bytes"},
}

# Extra legitimate shapes for events with more than one producer: the RL
# serving supervisor (pipeline/streaming.py) checkpoints its restart
# ledger with {scope, events} where the stream checkpointer writes
# {dir, run, rows, chunk}.
EVENT_SHAPE_VARIANTS: Dict[str, Tuple[FrozenSet[str], ...]] = {
    "checkpoint.save": (
        frozenset({"ev", "ts", "trace", "span", "scope", "events"}),),
    "checkpoint.restore": (
        frozenset({"ev", "ts", "trace", "span", "scope", "events"}),),
}

# GraftFleet (round 15): EVERY journaled event additionally carries the
# writer-identity stamp — process index + host (and `replica` when a
# writer suffix is set) — so a merged fleet view attributes each event
# without parsing shard filenames
STAMP_KEYS: Set[str] = {"proc", "host"}

# Events documented as once-per-run (per journal): their producers must
# go through ``Tracer.event_once`` (or an equivalent latch) so restarts,
# retries, and per-chunk paths can't spam duplicates.  graftlint's GL011
# flags plain ``.event()`` emissions of these names.
EVENT_ONCE: Set[str] = {"shard.topology", "fleet.join", "tenant.admitted"}


def event_shapes(ev: str) -> Tuple[FrozenSet[str], ...]:
    """Every allowed key set for ``ev`` (stamp keys excluded)."""
    base = (frozenset(GOLDEN_EVENT_KEYS[ev]),)
    return base + EVENT_SHAPE_VARIANTS.get(ev, ())
