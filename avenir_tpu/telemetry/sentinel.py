"""Perf-regression sentinel — the consumer the BENCH_r*.json trajectory
never had.

Every round publishes bench artifacts, and until now a regression like
the r05 ``families.tree`` 0.21× row was only caught when a human reread
BASELINE.md.  This module turns the trajectory into an automated gate:

    python -m avenir_tpu.telemetry regress BENCH_new.json \
        --baseline BENCH_prev.json [--tolerance-pct 25] \
        [--tolerance families.tree=40]

compares the canary-conditioned metrics of a capture against a baseline
artifact within per-metric tolerance bands and exits 0 (pass) / 1
(regression) / 3 (skip: every comparable metric was canary-flagged).
``bench.py`` runs :func:`bench_verdict` in-process at the end of a
capture, so every future artifact carries its own verdict and journals a
``bench.regression`` event when tracing is on.

Canary conditioning (the BASELINE.md interpretation contract, reused —
never reimplemented): a metric whose capture is canary-flagged — its
``value_canary_clean`` is null (no rig-clean pass) or its fresh matmul
canary exceeds the healthy threshold — is **skipped with a verdict**,
not compared: a contended rig indicts the rig, and comparing its numbers
would either mask a real regression or invent one.

All metrics here are rates (higher is better); a regression is
``value < baseline * (1 - tolerance_pct/100)``.  Stdlib-only.
"""

from __future__ import annotations

import fnmatch
import json
from typing import Dict, List, Optional

# the BASELINE.md interpretation contract: matmul canary ≲ 7 ms reads
# healthy; the contended regime reads 10-100x higher (bench.py uses the
# same bound for value_canary_clean)
CANARY_HEALTHY_MS = 7.0

DEFAULT_TOLERANCE_PCT = 25.0

EXIT_PASS = 0
EXIT_REGRESSION = 1
EXIT_SKIP = 3


def _line(artifact: dict) -> dict:
    """Unwrap a driver capture (``{"parsed": {...}}``) to the bench line."""
    if isinstance(artifact, dict) and isinstance(artifact.get("parsed"),
                                                 dict):
        return artifact["parsed"]
    return artifact if isinstance(artifact, dict) else {}


def _canary_flagged(row: dict) -> bool:
    """A row is rig-flagged when its fresh matmul canary (scalar form —
    knn, the primary) exceeds the healthy bound, or when it carries a
    per-pass canary list (family_bench rows) with NO rig-clean pass."""
    canary = row.get("canary_matmul_4096_bf16_ms")
    if isinstance(canary, (int, float)) and canary > CANARY_HEALTHY_MS:
        return True
    per_pass = row.get("canary_per_pass_ms")
    if isinstance(per_pass, (list, tuple)) and per_pass:
        readings = [c for c in per_pass if isinstance(c, (int, float))]
        return bool(readings) and min(readings) > CANARY_HEALTHY_MS
    return False


def _row_entry(row: dict) -> Optional[dict]:
    """One comparable row honoring the ``value_canary_clean`` convention
    (field present → IT is the value, null → flagged; absent → raw value
    conditioned on the row's own canary readings).  None = no row."""
    flagged = False
    value = row.get("value")
    if "value_canary_clean" in row:
        value = row.get("value_canary_clean")
        flagged = value is None
    elif _canary_flagged(row):
        flagged = True
    if isinstance(value, (int, float)) or flagged:
        return {"value": value, "unit": row.get("unit"),
                "canary_flagged": flagged}
    return None


def extract_metrics(artifact: dict) -> Dict[str, dict]:
    """``{metric name: {value, unit, canary_flagged}}`` from a bench line
    (or driver wrapper).  The primary metric honors the
    ``value_canary_clean`` convention: when the field exists, IT is the
    comparable value and null means canary-flagged; older artifacts
    (pre-round-7) fall back to the raw value conditioned on the pre-run
    canary.  Rows without a numeric value are omitted."""
    line = _line(artifact)
    out: Dict[str, dict] = {}
    if not isinstance(line.get("metric"), str):
        return out

    entry = _row_entry(line)
    if entry is not None:
        out[line["metric"]] = entry

    knn = line.get("knn")
    if isinstance(knn, dict) and isinstance(knn.get("value"), (int, float)):
        out["knn"] = {"value": knn["value"], "unit": knn.get("unit"),
                      "canary_flagged": _canary_flagged(knn)}

    families = line.get("families")
    if isinstance(families, dict):
        for fam in sorted(families):
            row = families[fam]
            if isinstance(row, dict) and isinstance(row.get("value"),
                                                    (int, float)):
                out[f"families.{fam}"] = {
                    "value": row["value"], "unit": row.get("unit"),
                    "canary_flagged": _canary_flagged(row)}

    # PackGraft (round 16): the wide_schema --path pack sweep publishes a
    # nested "packed" block — per-row dicts keyed by sub-metric name,
    # each honoring the same value_canary_clean/per-pass conventions as
    # the primary (pack_speedup carries no canary fields by design: both
    # sides of the ratio share the rig, so contention divides out)
    packed = line.get("packed")
    if isinstance(packed, dict):
        for name in sorted(packed):
            row = packed[name]
            if isinstance(row, dict):
                entry = _row_entry(row)
                if entry is not None:
                    out[f"packed.{name}"] = entry

    # PlanGraft (round 19): the e2e bench's planned-vs-staged section
    # publishes a nested "planned" block the same way — plan_speedup is
    # the banded row (a shared-rig ratio, so no canary fields, exactly
    # like pack_speedup); scan-second rows ride the conventions above
    planned = line.get("planned")
    if isinstance(planned, dict):
        for name in sorted(planned):
            row = planned[name]
            if isinstance(row, dict):
                entry = _row_entry(row)
                if entry is not None:
                    out[f"planned.{name}"] = entry
    return out


def evaluate(current: dict, baseline: dict,
             tolerance_pct: float = DEFAULT_TOLERANCE_PCT,
             per_metric: Optional[Dict[str, float]] = None) -> dict:
    """Compare a capture against a baseline artifact.

    Returns ``{"verdict", "compared", "regressed", "skipped", "missing",
    "rows"}`` where verdict is ``pass`` / ``regression`` / ``skip``
    (nothing comparable survived canary conditioning) / ``no_baseline``
    (the baseline carries no comparable metrics — e.g. a bands-less
    BASELINE.json).  Per-row verdicts: ``pass``, ``regression``,
    ``skipped_canary`` (either side flagged), ``no_baseline``,
    ``skipped_optional``, and ``missing`` — a metric the baseline gates
    but the capture no longer emits, which fails the gate like a
    regression (a capture that silently stops producing a gated row must
    not pass by omission).  The baseline may declare
    ``{"sentinel": {"optional": ["packed.*", ...]}}`` glob patterns:
    bands for rows only SOME benchmarks emit (the packed sweep's) — an
    absent optional row is ``skipped_optional`` instead of failing every
    capture from a benchmark that never produces it, but it IS still
    compared whenever present."""
    cur = extract_metrics(current)
    base = extract_metrics(baseline)
    per_metric = per_metric or {}
    gates = _line(baseline).get("sentinel")
    optional = (gates.get("optional", [])
                if isinstance(gates, dict) else [])
    rows: List[dict] = []
    regressed: List[str] = []
    skipped: List[str] = []
    missing: List[str] = []
    compared = 0
    for name in base:
        if name not in cur:
            if any(fnmatch.fnmatch(name, pat) for pat in optional
                   if isinstance(pat, str)):
                skipped.append(name)
                rows.append({"metric": name, "value": None,
                             "baseline": base[name]["value"],
                             "tolerance_pct": None, "ratio": None,
                             "verdict": "skipped_optional"})
                continue
            missing.append(name)
            rows.append({"metric": name, "value": None,
                         "baseline": base[name]["value"],
                         "tolerance_pct": None, "ratio": None,
                         "verdict": "missing"})
    for name, m in cur.items():
        b = base.get(name)
        tol = float(per_metric.get(name, tolerance_pct))
        row = {"metric": name, "value": m["value"],
               "baseline": b["value"] if b else None,
               "tolerance_pct": tol, "ratio": None}
        if m["canary_flagged"] or (b is not None and b["canary_flagged"]):
            row["verdict"] = "skipped_canary"
            skipped.append(name)
        elif b is None or not isinstance(b["value"], (int, float)) \
                or b["value"] <= 0:
            row["verdict"] = "no_baseline"
        else:
            compared += 1
            row["ratio"] = round(m["value"] / b["value"], 4)
            if m["value"] < b["value"] * (1.0 - tol / 100.0):
                row["verdict"] = "regression"
                regressed.append(name)
            else:
                row["verdict"] = "pass"
        rows.append(row)
    if regressed or missing:
        verdict = "regression"
    elif compared:
        verdict = "pass"
    elif skipped:
        verdict = "skip"
    else:
        verdict = "no_baseline"
    return {"verdict": verdict, "compared": compared, "regressed": regressed,
            "skipped": skipped, "missing": missing, "rows": rows}


def journal_verdict(summary: dict, baseline_name: str) -> None:
    """Journal a golden-schema'd ``bench.regression`` event (no-op with
    tracing off)."""
    from avenir_tpu.telemetry import spans as tel

    tel.tracer().event("bench.regression", verdict=summary["verdict"],
                       compared=summary["compared"],
                       regressed=summary["regressed"],
                       skipped=summary["skipped"],
                       missing=summary.get("missing", []),
                       baseline=baseline_name)


def bench_verdict(line: dict, baseline_path: str,
                  tolerance_pct: float = DEFAULT_TOLERANCE_PCT) -> dict:
    """The in-process gate bench.py embeds in its artifact: evaluate
    ``line`` against the artifact at ``baseline_path`` (missing/unreadable
    baseline → a ``no_baseline`` verdict, never an exception — the capture
    must publish either way) and journal the verdict."""
    try:
        with open(baseline_path, encoding="utf-8") as fh:
            baseline = json.load(fh)
    except (OSError, ValueError):
        summary = {"verdict": "no_baseline", "compared": 0, "regressed": [],
                   "skipped": [], "missing": [], "rows": []}
        journal_verdict(summary, baseline_path)
        return {"verdict": "no_baseline", "baseline": baseline_path,
                "compared": 0, "regressed": [], "skipped": [],
                "missing": []}
    summary = evaluate(line, baseline, tolerance_pct=tolerance_pct)
    journal_verdict(summary, baseline_path)
    return {"verdict": summary["verdict"], "baseline": baseline_path,
            "compared": summary["compared"],
            "regressed": summary["regressed"],
            "skipped": summary["skipped"],
            "missing": summary["missing"]}


def exit_code(verdict: str) -> int:
    if verdict == "regression":
        return EXIT_REGRESSION
    if verdict == "skip":
        return EXIT_SKIP
    return EXIT_PASS


def cli(argv: List[str]) -> int:
    """``python -m avenir_tpu.telemetry regress <bench.json...>
    --baseline <artifact>`` — prints one verdict line per metric plus a
    JSON summary, exits 0/1/3 (pass/regression/all-skipped)."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m avenir_tpu.telemetry regress",
        description="Gate bench captures against a baseline artifact")
    ap.add_argument("artifacts", nargs="+", help="bench JSON capture(s)")
    ap.add_argument("--baseline", required=True,
                    help="baseline bench JSON artifact")
    ap.add_argument("--tolerance-pct", type=float,
                    default=DEFAULT_TOLERANCE_PCT,
                    help="allowed drop below baseline (default 25)")
    ap.add_argument("--tolerance", action="append", default=[],
                    metavar="METRIC=PCT",
                    help="per-metric tolerance override (repeatable)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the full summary as JSON")
    args = ap.parse_args(argv)
    per_metric: Dict[str, float] = {}
    for spec in args.tolerance:
        name, _, pct = spec.partition("=")
        try:
            per_metric[name] = float(pct)
        except ValueError:
            # a usage error must exit 2, never masquerade as exit 1
            # (the REGRESSION code a CI gate acts on); catches both a
            # missing '=' (empty pct) and a non-numeric pct
            print(f"--tolerance expects METRIC=PCT, got {spec!r}",
                  file=sys.stderr)
            return 2
    try:
        with open(args.baseline, encoding="utf-8") as fh:
            baseline = json.load(fh)
    except (OSError, ValueError) as exc:
        print(f"cannot read baseline: {exc}", file=sys.stderr)
        return 2
    worst = "no_baseline"
    rank = {"no_baseline": 0, "pass": 1, "skip": 2, "regression": 3}
    summaries = []
    for path in args.artifacts:
        try:
            with open(path, encoding="utf-8") as fh:
                current = json.load(fh)
        except (OSError, ValueError) as exc:
            print(f"cannot read artifact: {exc}", file=sys.stderr)
            return 2
        summary = evaluate(current, baseline,
                           tolerance_pct=args.tolerance_pct,
                           per_metric=per_metric)
        summary["artifact"] = path
        summaries.append(summary)
        if rank[summary["verdict"]] > rank[worst]:
            worst = summary["verdict"]
        if not args.as_json:
            print(f"{path}: {summary['verdict'].upper()} "
                  f"(compared={summary['compared']} "
                  f"regressed={len(summary['regressed'])} "
                  f"skipped={len(summary['skipped'])} "
                  f"missing={len(summary['missing'])})")
            for row in summary["rows"]:
                ratio = ("-" if row["ratio"] is None
                         else f"{row['ratio']:.3f}x")
                tol = ("-" if row["tolerance_pct"] is None
                       else f"{row['tolerance_pct']:g}%")
                print(f"  {row['verdict']:>15}  {row['metric']:<32} "
                      f"{row['value']} vs {row['baseline']}  {ratio} "
                      f"(tol {tol})")
    if args.as_json:
        print(json.dumps(summaries))
    return exit_code(worst)
