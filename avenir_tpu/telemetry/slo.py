"""GraftFleet SLO evaluator — declarative service-level objectives over
the observability planes the framework already publishes.

The north-star serving claim ("heavy traffic from millions of users",
ROADMAP item 2) needs a machine-checkable gate, not prose: this module
turns ``slo.<name>.*`` config rules into pass/fail verdicts over the
SAME counter/gauge/latency state GraftTrace journals and ``/metrics``
exposes, evaluated two ways:

- **live** — :class:`SloEvaluator` runs at every ``/metrics`` scrape
  against the batcher's in-process state; each rule renders an
  ``avenir_slo_burn_rate{slo=...,metric=...}`` gauge (observed/target —
  > 1 means the objective is burning) and a transition INTO violation
  journals one golden-schema'd ``slo.violation`` event (re-armed when
  the rule recovers, so a flapping SLO journals each excursion once);
- **post-hoc** — ``python -m avenir_tpu.telemetry slo <journal>``
  evaluates the same rules over a run journal's events (``serve.request``
  span closes for latency percentiles, ``counters`` snapshots for
  shed/recompile totals, ``gauge`` events for queue depths) within each
  rule's trailing window, and exits 0/1 — the CI gate the item-2 soak
  harness closes on.

Rule grammar (properties file, the reference's ``-D`` contract)::

    slo.p99.metric=p99.latency.ms     # what to measure
    slo.p99.target=50                 # the objective
    slo.p99.op=max                    # max (default): value <= target
                                      # min: value >= target
    slo.p99.window.sec=300            # trailing window (post-hoc; default
                                      #   slo.window.sec, else whole run)

Built-in metrics — exactly the four the item-2 soak harness must gate
on, plus generic escapes:

- ``p99.latency.ms`` / ``p50.latency.ms`` — percentile over
  ``serve.request`` wall times (the shared percentile definition,
  ``utils/metrics.percentile_of``, with a stdlib fallback so the journal
  CLI stays runnable without numpy);
- ``shed.rate`` — shed / (requests + shed) across ``Serving.*`` groups;
- ``queue.depth`` — max pending-queue depth observed (live: the
  batcher's queues; post-hoc: ``serve.queue.*`` gauge events);
- ``recompiles.total`` — the steady-state recompile total (every
  ``recompiles`` counter summed; target 0 is the serving invariant —
  the ``steady_state_recompiles_total`` gate);
- ``counter:<Group>:<name>`` / ``gauge:<name>`` — any raw counter or
  journaled gauge.

A rule whose metric has no data (e.g. a p99 rule over a run that served
nothing) reports ``no_data`` and does NOT fail the gate — absence of
traffic is not an SLO violation; the soak harness guarantees traffic.

Stdlib-only at import (``core.config`` is stdlib; numpy is reached for
lazily) so ``python -m avenir_tpu.telemetry`` keeps working on a machine
with no JAX/numpy installed.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional

_RULE_KEY_RE = re.compile(r"^slo\.([A-Za-z0-9_-]+)\.metric$")

# burn rate reported when the target is 0 and the value is not (a
# violated zero-target rule has no finite observed/target ratio)
_BURN_CAP = 1e9


def _percentile(values: List[float], q: float) -> float:
    """numpy's linear-interpolation percentile (the one definition,
    ``utils/metrics.percentile_of``) with a stdlib fallback computing the
    same formula — the CLI must run without numpy installed."""
    if not values:
        return 0.0
    try:
        from avenir_tpu.utils.metrics import percentile_of

        return percentile_of(values, q)
    except ImportError:                            # pragma: no cover
        s = sorted(float(v) for v in values)
        k = (len(s) - 1) * q / 100.0
        lo, hi = math.floor(k), math.ceil(k)
        if lo == hi:
            return s[int(k)]
        return s[lo] + (s[hi] - s[lo]) * (k - lo)


@dataclass(frozen=True)
class SloRule:
    """One declarative objective: measure ``metric``, require it ``op``
    (max: <=, min: >=) ``target`` over the trailing ``window_sec``."""

    name: str
    metric: str
    target: float
    op: str = "max"
    window_sec: Optional[float] = None

    def check(self, value: Optional[float]) -> dict:
        """The rule's verdict row for one observed value (None = the
        metric had no data)."""
        row = {"slo": self.name, "metric": self.metric, "value": value,
               "target": self.target, "op": self.op,
               "window_sec": self.window_sec, "burn_rate": None}
        if value is None:
            row["verdict"] = "no_data"
            return row
        value = float(value)
        row["value"] = round(value, 6)
        if self.op == "min":
            violated = value < self.target
            burn = (self.target / value if value > 0
                    else (0.0 if self.target <= 0 else _BURN_CAP))
        else:
            violated = value > self.target
            burn = (value / self.target if self.target > 0
                    else (0.0 if value <= 0 else _BURN_CAP))
        row["burn_rate"] = round(min(burn, _BURN_CAP), 6)
        row["verdict"] = "violation" if violated else "pass"
        return row


def rules_from_conf(conf) -> List[SloRule]:
    """Every ``slo.<name>.metric`` rule in the conf (bare or
    prefix-namespaced — ``avenir.slo.x.metric`` == ``slo.x.metric``),
    sorted by name.  A rule without a numeric ``slo.<name>.target``
    raises ConfigError — a silent unbounded objective gates nothing."""
    from avenir_tpu.core.config import ConfigError

    default_window = conf.get_float("slo.window.sec")
    names = set()
    for key in conf.props:
        bare = key[len(conf.prefix) + 1:] if key.startswith(
            conf.prefix + ".") else key
        m = _RULE_KEY_RE.match(bare)
        if m:
            names.add(m.group(1))
    rules: List[SloRule] = []
    for name in sorted(names):
        metric = conf.get(f"slo.{name}.metric")
        target = conf.get_float(f"slo.{name}.target")
        if target is None:
            raise ConfigError(
                f"slo.{name}.metric={metric!r} has no numeric "
                f"slo.{name}.target — an SLO without a target gates "
                f"nothing")
        op = (conf.get(f"slo.{name}.op", "max") or "max").strip().lower()
        if op not in ("max", "min"):
            raise ConfigError(
                f"slo.{name}.op={op!r} must be 'max' (value <= target) "
                f"or 'min' (value >= target)")
        rules.append(SloRule(
            name=name, metric=metric, target=float(target), op=op,
            window_sec=conf.get_float(f"slo.{name}.window.sec",
                                      default_window)))
    return rules


def parse_rule_spec(spec: str) -> SloRule:
    """CLI inline rule: ``NAME=METRIC<=TARGET`` or ``NAME=METRIC>=TARGET``
    (the ``--rule`` escape so CI can gate without a properties file)."""
    name, eq, body = spec.partition("=")
    m = re.match(r"^(.*?)(<=|>=)([-+0-9.eE]+)$", body) if eq else None
    if not name or m is None:
        raise ValueError(
            f"--rule expects NAME=METRIC<=TARGET or NAME=METRIC>=TARGET, "
            f"got {spec!r}")
    return SloRule(name=name, metric=m.group(1),
                   target=float(m.group(3)),
                   op="max" if m.group(2) == "<=" else "min")


def filter_events_by_labels(events: List[dict],
                            labels: Mapping[str, str]) -> List[dict]:
    """Events carrying EVERY given label — matched against the record's
    top-level fields (the GraftPool ``label_scope`` stamp / the
    per-process ``tenant.id`` journal stamp) or its span ``attrs``.

    The ``telemetry slo --label tenant=<id>`` seam (round 18): one
    merged fleet journal holds every tenant's events, and a per-tenant
    verdict evaluates the same rules over just that tenant's slice —
    unlabeled events (another tenant's, or infrastructure outside any
    scope) are excluded, so tenant A's shed storm can never fail tenant
    B's gate."""
    def match(event: dict) -> bool:
        attrs = event.get("attrs") or {}
        for key, value in labels.items():
            if str(event.get(key)) == value:
                continue
            if str(attrs.get(key)) == value:
                continue
            return False
        return True

    return [e for e in events if match(e)]


# ---------------------------------------------------------------------------
# metric extraction — post-hoc (journal events)
# ---------------------------------------------------------------------------

def _last_counter_groups(events: List[dict]) -> Dict[str, Dict[str, int]]:
    """The LAST ``counters`` snapshot per WRITER, groups summed across
    writers.

    One snapshot per writer — not per scope: a single traced pipeline
    journals the same totals under several scopes (per-stage snapshots,
    the per-job snapshot, and the run-level ``pipeline`` rollup which is
    already the ``merge_add`` sum of every stage), so summing scopes
    would read a clean run as 2-3x its real counts and fail a counter
    SLO falsely.  A writer's chronologically last snapshot is its most
    complete view (the pipeline rollup for driver runs, the job
    snapshot for standalone runs); across DIFFERENT writers of a merged
    fleet journal the totals are disjoint and add."""
    last: Dict[tuple, dict] = {}
    for e in events:
        if e.get("ev") != "counters":
            continue
        key = (e.get("proc"), e.get("host"), e.get("replica"))
        last[key] = e.get("groups", {})
    out: Dict[str, Dict[str, int]] = {}
    for groups in last.values():
        for group, vals in groups.items():
            g = out.setdefault(group, {})
            for name, value in vals.items():
                if isinstance(value, (int, float)):
                    g[name] = g.get(name, 0) + value
    return out


def _shed_rate(groups: Mapping[str, Mapping[str, float]]) -> Optional[float]:
    requests = shed = 0.0
    seen = False
    for group, vals in groups.items():
        if not group.startswith("Serving."):
            continue
        seen = True
        requests += float(vals.get("requests", 0))
        shed += float(vals.get("shed", 0))
    if not seen:
        return None
    total = requests + shed
    return shed / total if total > 0 else 0.0


def _recompiles_total(groups: Mapping[str, Mapping[str, float]]
                      ) -> Optional[float]:
    if not groups:
        return None
    return float(sum(vals.get("recompiles", 0) for vals in groups.values()))


def metric_from_events(metric: str, events: List[dict]) -> Optional[float]:
    """One metric's value over a (window-filtered) event list; None when
    the journal carries no data for it."""
    if metric in ("p99.latency.ms", "p50.latency.ms"):
        durs = [e["dur_ms"] for e in events
                if e.get("ev") == "span.close"
                and e.get("name") == "serve.request"
                and isinstance(e.get("dur_ms"), (int, float))]
        if not durs:
            return None
        return _percentile(durs, 99.0 if metric.startswith("p99") else 50.0)
    if metric == "queue.depth":
        depths = [e.get("value") for e in events
                  if e.get("ev") == "gauge"
                  and str(e.get("name", "")).startswith("serve.queue.")
                  and isinstance(e.get("value"), (int, float))]
        return max(depths) if depths else None
    if metric == "shed.rate":
        return _shed_rate(_last_counter_groups(events))
    if metric == "recompiles.total":
        return _recompiles_total(_last_counter_groups(events))
    if metric.startswith("counter:"):
        parts = metric.split(":", 2)
        if len(parts) != 3:
            return None
        groups = _last_counter_groups(events)
        if parts[1] not in groups:
            return None
        return float(groups[parts[1]].get(parts[2], 0))
    if metric.startswith("gauge:"):
        name = metric.split(":", 1)[1]
        vals = [e.get("value") for e in events
                if e.get("ev") == "gauge" and e.get("name") == name
                and isinstance(e.get("value"), (int, float))]
        return float(vals[-1]) if vals else None
    return None


def evaluate_events(events: List[dict], rules: List[SloRule]) -> dict:
    """Post-hoc verdict over a journal's events: per rule, filter to its
    trailing window (anchored at the journal's LAST event — a crashed
    run's window ends where the run died) and check the target.  Returns
    ``{"verdict", "rules"}`` where verdict is ``violation`` when any
    rule fails, ``pass`` when at least one evaluates clean and none
    fail, ``no_data`` when nothing was measurable, ``no_rules`` when
    the rule set is empty."""
    if not rules:
        return {"verdict": "no_rules", "rules": []}
    t_end = max((float(e.get("ts", 0.0) or 0.0) for e in events),
                default=0.0)
    rows = []
    for rule in rules:
        if rule.window_sec:
            cutoff = t_end - float(rule.window_sec)
            windowed = [e for e in events
                        if float(e.get("ts", 0.0) or 0.0) >= cutoff]
        else:
            windowed = events
        rows.append(rule.check(metric_from_events(rule.metric, windowed)))
    if any(r["verdict"] == "violation" for r in rows):
        verdict = "violation"
    elif any(r["verdict"] == "pass" for r in rows):
        verdict = "pass"
    else:
        verdict = "no_data"
    return {"verdict": verdict, "rules": rows}


# ---------------------------------------------------------------------------
# live evaluation — the serving /metrics seam
# ---------------------------------------------------------------------------

class SloEvaluator:
    """Scrape-time rule evaluation over the batcher's in-process state.

    Stateless per scrape except the violation latch: a rule journals
    ``slo.violation`` exactly once per excursion (on the transition into
    violation; recovery re-arms it), so a scraped-every-15s violating SLO
    does not flood the journal."""

    def __init__(self, rules: List[SloRule]):
        import threading

        self.rules = list(rules)
        # the latch is shared across ThreadingHTTPServer handler threads:
        # without the lock, two concurrent scrapes on the transition tick
        # would both journal the same excursion
        self._lock = threading.Lock()
        self._violating: set = set()

    @classmethod
    def from_conf(cls, conf) -> Optional["SloEvaluator"]:
        rules = rules_from_conf(conf)
        return cls(rules) if rules else None

    def _live_value(self, metric: str, counters, latency,
                    queue_depths: Mapping[str, int],
                    gauges: Optional[Mapping[str, float]] = None
                    ) -> Optional[float]:
        if metric in ("p99.latency.ms", "p50.latency.ms"):
            q = 99.0 if metric.startswith("p99") else 50.0
            vals = [t.percentile(q) * 1e3 for t in latency.values()
                    if t.count > 0]
            return max(vals) if vals else None
        if metric == "queue.depth":
            return float(max(queue_depths.values())) if queue_depths else None
        groups = counters.as_dict()
        if metric == "shed.rate":
            return _shed_rate(groups)
        if metric == "recompiles.total":
            return _recompiles_total(groups)
        if metric.startswith("counter:"):
            parts = metric.split(":", 2)
            if len(parts) != 3 or parts[1] not in groups:
                return None
            return float(groups[parts[1]].get(parts[2], 0))
        if metric.startswith("gauge:"):
            # any gauge the scrape computes (the frontend passes its full
            # gauge page: serve.queue.<model>, uptime.sec); bare callers
            # without a gauges map still resolve the queue-depth family
            name = metric.split(":", 1)[1]
            if gauges is not None and name in gauges:
                return float(gauges[name])
            if name.startswith("serve.queue."):
                depth = queue_depths.get(name[len("serve.queue."):])
                return float(depth) if depth is not None else None
            return None
        return None

    def evaluate_live(self, counters, latency,
                      queue_depths: Mapping[str, int],
                      gauges: Optional[Mapping[str, float]] = None
                      ) -> List[dict]:
        """Verdict rows against live serving state; journals
        ``slo.violation`` on each rule's transition into violation
        (latched under a lock — concurrent scrapes journal one event per
        excursion, not one per scraper)."""
        from avenir_tpu.telemetry import spans as tel

        rows = []
        fire: List[dict] = []
        for rule in self.rules:
            row = rule.check(self._live_value(
                rule.metric, counters, latency, queue_depths,
                gauges=gauges))
            rows.append(row)
            with self._lock:
                if row["verdict"] == "violation":
                    if rule.name not in self._violating:
                        self._violating.add(rule.name)
                        fire.append(row)
                else:
                    self._violating.discard(rule.name)
        for row in fire:
            tel.tracer().event(
                "slo.violation", slo=row["slo"], metric=row["metric"],
                value=row["value"], target=row["target"],
                burn_rate=row["burn_rate"])
        return rows

    @staticmethod
    def render_prometheus(rows: List[dict], lines: List[str],
                          labels: Optional[Mapping[str, str]] = None
                          ) -> None:
        """``avenir_slo_burn_rate`` gauges for the ``/metrics`` page —
        observed/target per rule (> 1 = violating; ``no_data`` rules are
        omitted, absence of traffic is not a burn)."""
        from avenir_tpu.telemetry.export import _escape, _label_text

        base = _label_text(labels)
        lines.append("# HELP avenir_slo_burn_rate Observed/target per SLO "
                     "rule (> 1 = violating).")
        lines.append("# TYPE avenir_slo_burn_rate gauge")
        for row in rows:
            if row["burn_rate"] is None:
                continue
            lines.append(
                f'avenir_slo_burn_rate{{{base}slo="{_escape(row["slo"])}",'
                f'metric="{_escape(row["metric"])}"}} {row["burn_rate"]:g}')


# ---------------------------------------------------------------------------
# bench.py embedding — the post-run verdict next to the sentinel's
# ---------------------------------------------------------------------------

def bench_verdict(journal_path: Optional[str],
                  conf_path: Optional[str]) -> dict:
    """The SLO summary bench.py embeds in its artifact: rules from the
    ``AVENIR_SLO_CONF`` properties file evaluated over the capture's own
    journal.  No rules configured → ``no_rules``; an unreadable or
    malformed rules file → ``rules_error``; rules but no journal
    (``AVENIR_TRACE_DIR`` unset) → ``no_journal`` — the capture publishes
    in every case, mirroring the sentinel's never-fail-the-capture
    contract.  Violated rules journal ``slo.violation`` (the bench owns
    its journal; no-op when tracing is off)."""
    if not conf_path:
        return {"verdict": "no_rules", "rules": []}
    from avenir_tpu.core.config import ConfigError, JobConfig

    try:
        rules = rules_from_conf(JobConfig.from_file(conf_path))
    except (OSError, ConfigError) as exc:
        # an unreadable OR malformed rules file must not kill the
        # capture after all its measurement — surface it as a verdict
        return {"verdict": "rules_error", "error": str(exc), "rules": []}
    if not rules:
        return {"verdict": "no_rules", "rules": []}
    if not journal_path:
        return {"verdict": "no_journal", "rules": []}
    from avenir_tpu.telemetry import spans as tel
    from avenir_tpu.telemetry.journal import read_events

    summary = evaluate_events(read_events(journal_path), rules)
    for row in summary["rules"]:
        if row["verdict"] == "violation":
            tel.tracer().event(
                "slo.violation", slo=row["slo"], metric=row["metric"],
                value=row["value"], target=row["target"],
                burn_rate=row["burn_rate"])
    return summary
