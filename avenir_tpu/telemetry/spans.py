"""Run-scoped structured tracing — spans, the process tracer, and the
generalized recompile monitor.

One trace id per run, one span per unit of work: ``Pipeline.run`` opens a
root span, each stage/job/chunk/dispatch/serving-request opens a child, and
every open/close is journaled (``telemetry/journal.py``) so a slow or
wedged run reads as ONE tree (``python -m avenir_tpu.telemetry <journal>``)
instead of five unrelated artifacts.  Design constraints:

- **off by default is free**: the process :class:`Tracer` is a no-op until
  ``trace.on`` enables it — ``span()`` then returns a shared inert span
  object, so the hot paths pay one attribute check and no allocation
  (asserted against the published nb_mi band; measured in
  ``benchmarks/telemetry_overhead.py``).
- **contextvar propagation**: the current span rides a ``contextvars``
  variable, so nesting needs no plumbing and concurrent threads never
  share a current span.  Work that *crosses* threads (DeviceFeeder
  workers, the serving dispatch thread) captures the submitting context
  explicitly and emits its spans retroactively (:meth:`Tracer.emit_span`)
  with that parent — the seam that lets a serving request join the
  pipeline trace through the ScoringPlane stage.
- **honest wall times**: JAX dispatch is async, so a span measuring
  device work registers its output via :meth:`Span.block_on` and the
  close performs the host fetch through the existing
  ``profiling.device_sync`` discipline (``jax.block_until_ready`` is a
  no-op on some transports — BASELINE.md "Timing methodology").
- **single-writer journal SHARDS** (GraftFleet, round 15): in
  multi-process runs every process journals to its OWN shard
  (``run-<id>.proc-<k>.jsonl``, each single-writer under its own
  FileLock) instead of process 0 journaling and the workers dropping
  their spans; serving replicas and fleet workers that are not
  jax-distributed get the same treatment via ``trace.writer.suffix``.
  Every event is stamped with ``proc``/``host`` (and ``replica`` when a
  suffix is set), all shards share one conf-derived run id and root
  trace id, and ``python -m avenir_tpu.telemetry merge <dir>``
  time-orders the shards into one fleet view.

:class:`CompileKeyMonitor` generalizes the serving batcher's compile-key
diff (round 9) so *batch* chunk loops get the same measured ``recompiles``
counter: feed each dispatch's shape/compile keys through ``observe`` and
any key outside the primed set increments the counter and journals a
``recompile`` event.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading
import time
from typing import Any, Dict, Iterable, Iterator, Optional

from avenir_tpu.telemetry import blackbox as _blackbox
from avenir_tpu.telemetry.journal import Journal

_CURRENT: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "avenir_tpu_current_span", default=None)

# GraftPool (round 18): ambient journal labels.  A tenant's workload runs
# under ``label_scope(tenant=...)`` and EVERY event emitted from inside —
# span opens/closes, counter snapshots, gauges, recompiles, sheds — is
# stamped with the label at emit time, so one merged fleet journal
# attributes every span and every shed to its tenant without per-seam
# plumbing.  Independent of ``trace.on``: the tenancy arbiter reads the
# ambient ``tenant`` label even when nothing journals.
_LABELS: "contextvars.ContextVar[Optional[Dict[str, Any]]]" = \
    contextvars.ContextVar("avenir_tpu_trace_labels", default=None)


def current_labels() -> Dict[str, Any]:
    """A copy of the ambient label set ({} outside any scope)."""
    return dict(_LABELS.get() or {})


def current_label(key: str) -> Optional[Any]:
    """One ambient label (no dict copy — the arbiter's hot-path read)."""
    labels = _LABELS.get()
    return labels.get(key) if labels else None


@contextlib.contextmanager
def label_scope(**labels) -> Iterator[None]:
    """Attach journal labels to everything emitted inside the scope.
    Scopes nest (inner wins on a shared key); ``None`` values are
    dropped, so ``label_scope(tenant=conf.get("tenant.id"))`` is a
    no-op scope when the conf names no tenant."""
    live = {k: v for k, v in labels.items() if v is not None}
    merged = {**(_LABELS.get() or {}), **live}
    token = _LABELS.set(merged)
    try:
        yield
    finally:
        _LABELS.reset(token)


class Span:
    """One unit of work: identity (trace/span/parent ids), a name, attrs,
    and wall times.  Mutate attrs via :meth:`set`; register async device
    output via :meth:`block_on` so the close time is honest."""

    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "attrs", "ts", "_t0", "dur_ms", "status", "_pending")

    def __init__(self, tracer: "Tracer", trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str,
                 attrs: Optional[Dict[str, Any]]):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.attrs: Dict[str, Any] = dict(attrs or {})
        self.ts = time.time()
        self._t0 = time.perf_counter()
        self.dur_ms: Optional[float] = None
        self.status = "ok"
        self._pending = None

    @property
    def enabled(self) -> bool:
        return True

    def set(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def block_on(self, value):
        """Register the span's device output; host-synced at close so the
        recorded duration covers the compute, not just the dispatch."""
        self._pending = value
        return value

    def event(self, ev: str, **fields) -> None:
        """Journal an event carrying this span's identity."""
        self.tracer._journal_emit(ev, trace=self.trace_id,
                                  span=self.span_id, **fields)

    def _close(self) -> None:
        if self._pending is not None:
            from avenir_tpu.utils.profiling import device_sync

            device_sync(self._pending)
            self._pending = None
        self.dur_ms = (time.perf_counter() - self._t0) * 1e3


class _NoopSpan:
    """The shared inert span handed out while tracing is off — every
    operation is a no-op, so instrumented code needs no ``if`` guards."""

    __slots__ = ()
    enabled = False
    trace_id = span_id = parent_id = None
    attrs: Dict[str, Any] = {}

    def set(self, key, value):
        return self

    def block_on(self, value):
        return value

    def event(self, ev, **fields):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


def _new_id(prefix: str) -> str:
    return prefix + os.urandom(6).hex()


class Tracer:
    """Process-wide span factory + journal front.  Disabled (free) until
    :meth:`enable`; ``configure(conf)`` wires it from ``trace.*`` keys."""

    def __init__(self):
        self.enabled = False
        self.journal: Optional[Journal] = None
        self._seq = itertools.count(1)           # thread-safe in CPython
        self._lock = threading.Lock()
        self._once: set = set()                  # event_once keys, per journal
        # GraftFleet identity (round 15): the journal stamp every event
        # carries, the span-id prefix that keeps ids unique across a
        # fleet's shards, and the shared root trace id that makes a
        # multi-process run ONE trace in the merged view
        self.stamp: dict = {}
        self.process_index = 0
        self.writer_suffix = ""
        self._span_prefix = ""
        self._root_trace: Optional[str] = None

    # -- lifecycle -----------------------------------------------------------
    def enable(self, journal_dir: Optional[str] = None,
               max_bytes: int = 64 << 20, run_id: Optional[str] = None,
               suffix: str = "", tenant: str = "") -> "Tracer":
        """Turn tracing on; with ``journal_dir``, open the run journal
        there (single-writer, rotation-bounded).

        Plain form (no ``run_id``/``suffix``, process 0): the legacy
        ``run-<random>.jsonl`` single-process journal.  Fleet form — a
        shared ``run_id`` (every process of a run must agree; ``configure``
        derives it from the conf), a ``suffix`` naming a replica/worker
        that is not jax-distributed, or a non-zero ``jax.process_index()``
        — opens this writer's SHARD ``run-<id>.proc-<k>[-<suffix>].jsonl``,
        stamps every event with ``proc``/``host``/``replica``, prefixes
        span ids with the writer identity (ids stay unique across the
        merged fleet view), and roots new traces at the run-derived trace
        id so all shards share ONE trace."""
        proc = 0
        try:
            import jax

            proc = jax.process_index()
        except Exception:                          # pragma: no cover
            pass
        import socket

        with self._lock:
            if self.enabled:
                return self
            self.process_index = proc
            self.writer_suffix = suffix or ""
            self.stamp = {"proc": proc, "host": socket.gethostname()}
            if suffix:
                self.stamp["replica"] = suffix
            if tenant:
                # GraftPool (round 18): a process dedicated to one tenant
                # (tenant.id in its conf) stamps every record — the
                # multi-process twin of the in-process label_scope
                self.stamp["tenant"] = tenant
            fleet = bool(run_id) or bool(suffix) or proc != 0
            if fleet:
                writer = f"proc-{proc}" + (f"-{suffix}" if suffix else "")
                name = f"run-{run_id or _new_id('')}.{writer}.jsonl"
                self._span_prefix = f"p{proc}" + \
                    (f"-{suffix}" if suffix else "") + "."
                self._root_trace = f"t{run_id}" if run_id else None
            else:
                name = f"run-{_new_id('')}.jsonl"
                self._span_prefix = ""
                self._root_trace = None
            if journal_dir:
                self.journal = Journal(os.path.join(journal_dir, name),
                                       max_bytes=max_bytes,
                                       stamp=self.stamp)
            self._once.clear()                   # fresh journal, fresh onces
            self.enabled = True
        return self

    def disable(self) -> None:
        """Turn tracing off and close the journal (tests, run teardown).
        The profiler flushes its cumulative program.profile totals into
        the journal FIRST (its accounting rides this journal), then drops
        its state — the two planes share one lifecycle."""
        from avenir_tpu.telemetry import profile as _profile

        prof = _profile.profiler()
        prof.flush()
        prof.disable()
        with self._lock:
            self.enabled = False
            self._once.clear()
            self._span_prefix = ""
            self._root_trace = None
            self.writer_suffix = ""
            self.stamp = {}
            if self.journal is not None:
                self.journal.close()
                self.journal = None

    @property
    def journal_path(self) -> Optional[str]:
        return self.journal.path if self.journal is not None else None

    # -- span factory --------------------------------------------------------
    def current(self) -> Optional[Span]:
        """The context's live span (cross-thread parent capture), or None
        when tracing is off or no span is open."""
        return _CURRENT.get() if self.enabled else None

    def span(self, name: str, attrs: Optional[Dict[str, Any]] = None,
             parent: Optional[Span] = None):
        """Open a child of the context's current span (or of ``parent``
        when crossing a thread); a span with no parent roots a new trace.
        Disabled: returns the shared NOOP span directly — one attribute
        check, no generator frame, no allocation (the off-is-free
        contract; benchmarks/telemetry_overhead.py)."""
        if not self.enabled:
            return NOOP_SPAN
        return self._live_span(name, attrs, parent)

    @contextlib.contextmanager
    def _live_span(self, name: str, attrs: Optional[Dict[str, Any]],
                   parent: Optional[Span]) -> Iterator[Span]:
        up = parent if parent is not None else _CURRENT.get()
        trace_id = (up.trace_id if up is not None
                    else self._root_trace or _new_id("t"))
        sp = Span(self, trace_id, self._next_span_id(),
                  up.span_id if up is not None else None, name, attrs)
        token = _CURRENT.set(sp)
        self._journal_emit("span.open", trace=sp.trace_id, span=sp.span_id,
                           parent=sp.parent_id, name=sp.name,
                           attrs=sp.attrs)
        try:
            yield sp
        except BaseException as exc:
            sp.status = f"error:{type(exc).__name__}"
            raise
        finally:
            _CURRENT.reset(token)
            sp._close()
            self._journal_emit("span.close", trace=sp.trace_id,
                               span=sp.span_id, name=sp.name,
                               dur_ms=round(sp.dur_ms, 3),
                               status=sp.status, attrs=sp.attrs)

    def emit_span(self, name: str, dur_s: float,
                  parent: Optional[Span] = None,
                  attrs: Optional[Dict[str, Any]] = None,
                  status: str = "ok") -> None:
        """Retroactively journal a completed span — the cross-thread form
        (feeder workers, the serving dispatcher) where the work finished
        on a thread that never held the submitting context."""
        if not self.enabled:
            return
        trace_id = (parent.trace_id if parent is not None
                    else self._root_trace or _new_id("t"))
        span_id = self._next_span_id()
        ts = time.time()
        self._journal_emit("span.open", trace=trace_id, span=span_id,
                           parent=parent.span_id if parent else None,
                           name=name, attrs=dict(attrs or {}), ts=ts - dur_s)
        self._journal_emit("span.close", trace=trace_id, span=span_id,
                           name=name, dur_ms=round(dur_s * 1e3, 3),
                           status=status, attrs=dict(attrs or {}), ts=ts)

    def _next_span_id(self) -> str:
        """Fleet-unique span id: the writer prefix (``p<k>[-<suffix>].``,
        empty single-process) plus the process-local sequence — two
        shards of one run can never collide on a span id in the merged
        view."""
        return f"{self._span_prefix}s{next(self._seq)}"

    # -- journal shorthands --------------------------------------------------
    def _journal_emit(self, ev: str, **fields) -> None:
        # GraftBox: every journaled event also lands in the always-on
        # flight ring (a dead process's last moments survive the journal's
        # file buffer); copied because the labels/ts mutation below would
        # otherwise alias the ring's stored record
        _blackbox.ring_record(ev, dict(fields))
        if self.journal is not None:
            ts = fields.pop("ts", None)
            if ts is not None:
                # retroactive events carry their own timestamp
                fields["at"] = round(ts, 6)
            labels = _LABELS.get()
            if labels:
                # ambient labels (GraftPool tenant attribution) ride every
                # record; an explicit field of the same name wins
                for key, value in labels.items():
                    fields.setdefault(key, value)
            self.journal.emit(ev, **fields)

    def event(self, ev: str, **fields) -> None:
        """Journal a free event stamped with the current span's identity
        (if any) — checkpoint saves, canary readings, stage skips."""
        if not self.enabled:
            # GraftBox: the flight ring records this seam even with
            # tracing off (the kwargs dict is fresh per call — safe to
            # keep without a copy); the journal still sees nothing
            _blackbox.ring_record(ev, fields)
            return
        cur = _CURRENT.get()
        if cur is not None:
            fields.setdefault("trace", cur.trace_id)
            fields.setdefault("span", cur.span_id)
        self._journal_emit(ev, **fields)

    def event_once(self, ev: str, key, **fields) -> None:
        """Journal an event at most once per journal per ``(ev, key)`` —
        for run-identity facts (e.g. ``shard.topology``) that several
        seams may announce; later duplicates are dropped, and a run
        carrying genuinely distinct facts (different keys) journals each."""
        if not self.enabled:
            _blackbox.ring_record(ev, fields)   # ring only; no once-latch
            return
        with self._lock:
            if (ev, key) in self._once:
                return
            self._once.add((ev, key))
        self.event(ev, **fields)

    def counters(self, scope: str, counters) -> None:
        """Journal a named counter snapshot (the CLI renders per-scope
        deltas between successive snapshots)."""
        if not self.enabled:
            return
        self.event("counters", scope=scope, groups=counters.as_dict())

    def gauge(self, name: str, value: float) -> None:
        """Journal a point-in-time gauge reading (queue depths)."""
        if not self.enabled:
            _blackbox.ring_record("gauge", {"name": name, "value": value})
            return
        self.event("gauge", name=name, value=value)


_TRACER = Tracer()


def tracer() -> Tracer:
    """The process tracer (disabled, hence free, until configured)."""
    return _TRACER


def fleet_run_id(conf) -> str:
    """The fleet-shared run identity every journal shard of one run
    carries: ``trace.run.id`` when set, else a fingerprint of the conf's
    workload properties.  Observability knobs (``trace.*``, ``profile.*``,
    ``slo.*`` — including the per-replica ``trace.writer.suffix``) are
    EXCLUDED: two replicas differing only in their writer suffix, or a
    relaunch that turns profiling on, must land in the same run's shard
    set.  Distinct from ``StreamCheckpointer.run_id_from_conf`` (which
    keeps these keys — a checkpoint's identity is stricter than a
    journal's)."""
    explicit = conf.get("trace.run.id")
    if explicit:
        return explicit
    import hashlib

    drop = ("trace.", "profile.", "slo.", "telemetry.")
    stable = sorted(
        (k, v) for k, v in conf.props.items()
        if not any((k[len(conf.prefix) + 1:] if k.startswith(
            conf.prefix + ".") else k).startswith(p) for p in drop))
    return hashlib.blake2s(repr(stable).encode(),
                           digest_size=6).hexdigest()


def configure(conf) -> Tracer:
    """Enable the process tracer from ``trace.*`` config keys; a no-op —
    and one dict lookup — when ``trace.on`` is unset.

    GraftFleet (round 15): EVERY process of a multi-process run gets an
    enabled tracer writing its own journal shard (previously workers'
    spans were silently dropped by a process-0-only gate).  All shards of
    one run share a conf-derived run id (``fleet_run_id``) and root trace
    id, so ``telemetry merge`` + the span-tree CLI render the fleet as
    ONE trace with per-process attribution.  Single-machine replica
    pools and fleet workers that are not jax-distributed opt into the
    same sharding with ``trace.writer.suffix`` (each writer suffix is a
    distinct shard + ``replica`` stamp).  Idempotent: a pipeline and the
    jobs it runs all call this with the same conf; the first enable wins.

    GraftProf (round 14) rides the same entry point: ``profile.on`` is
    checked here too, so every seam that configures tracing — driver,
    jobs, the serving CLI — configures the device-cost profiler from the
    same conf (one dict lookup when off)."""
    from avenir_tpu.telemetry import profile as _profile

    _profile.configure(conf)
    # GraftBox rides the same entry point: blackbox.dir arms the
    # forensics bundle writer and blackbox.watchdog.sec the progress
    # watchdog INDEPENDENTLY of trace.on — crash forensics must not
    # require tracing (a few dict lookups when unset)
    _blackbox.configure(conf)
    t = _TRACER
    if not conf.get_bool("trace.on", False) or t.enabled:
        return t
    nprocs = 1
    try:
        import jax

        nprocs = jax.process_count()
    except Exception:                              # pragma: no cover
        pass
    # GraftPool (round 18): a tenant-dedicated process (tenant.id) shards
    # its journal like a replica — the tenant names the writer suffix when
    # no explicit one is set — and stamps every record with the tenant, so
    # a merged fleet view attributes each shard's events without parsing
    # filenames.  In-process multi-tenant runs use label_scope instead.
    tenant = conf.get("tenant.id", "") or ""
    # GlobalServe (this round): a launcher-spawned serving worker gets its
    # shard suffix via AVENIR_WRITER_SUFFIX (the launch env contract) when
    # the conf file — shared by the whole fleet — can't name one per
    # process; an explicit conf key still wins, then the env, then the
    # tenant id.
    suffix = (conf.get("trace.writer.suffix", "")
              or os.environ.get("AVENIR_WRITER_SUFFIX", "")
              or tenant)
    fleet = nprocs > 1 or bool(suffix) or bool(conf.get("trace.run.id"))
    max_mb = conf.get_float("telemetry.journal.max.mb", 64.0)
    t.enable(conf.get("trace.journal.dir") or ".",
             max_bytes=int(max_mb * (1 << 20)),
             run_id=fleet_run_id(conf) if fleet else None,
             suffix=suffix, tenant=tenant)
    return t


class CompileKeyMonitor:
    """The serving batcher's compile-key diff, generalized (this round) so
    every dispatch loop — batch chunk streams included — publishes a
    measured ``recompiles`` counter instead of assuming shape stability.

    ``prime`` registers expected keys (serving warmup; a stream's first
    chunk) without counting; ``observe`` counts any key outside the known
    set as a recompile, increments ``<group>::recompiles`` and journals a
    ``recompile`` event carrying the fresh keys.  With ``auto_prime`` the
    first observation primes instead of counting — the batch-stream mode,
    where the first chunk's compile is the expected one and only
    *subsequent* fresh shapes (e.g. a ragged tail chunk) are noteworthy.

    GraftProf (round 14): every key that enters the known set — primed or
    observed — is also registered with the
    :class:`~avenir_tpu.telemetry.profile.CompiledProgramRegistry` under
    this monitor's scope, so the seams that already feed the recompile
    diff (batch chunk streams, stream panes, the serving batcher)
    populate the compiled-program table for free: one ``program.compiled``
    event per distinct key, recompile-monitor parity by construction (a
    ragged tail chunk is one recompile AND one extra program)."""

    def __init__(self, counters=None, group: str = "Telemetry",
                 scope: str = "", auto_prime: bool = False):
        self.counters = counters
        self.group = group
        self.scope = scope
        self.auto_prime = auto_prime
        self._known: set = set()
        self._primed = False

    def prime(self, keys: Iterable) -> None:
        keys = set(keys)
        self._known |= keys
        self._primed = True
        self._register_programs(keys)

    def _register_programs(self, keys) -> None:
        """Feed keys entering the known set to the program registry (one
        attribute check when profiling is off)."""
        from avenir_tpu.telemetry import profile as _profile

        prof = _profile.profiler()
        if prof.enabled:
            for key in keys:
                prof.observe(key, site=self.scope or self.group)

    @staticmethod
    def shape_key(*arrays) -> tuple:
        """A dispatch-shape key for array operands: (shape, dtype) per
        operand — a fresh one implies a fresh XLA compile of the jitted
        step consuming them."""
        return tuple((tuple(a.shape), str(a.dtype))
                     for a in arrays if a is not None)

    def observe(self, keys: Iterable) -> int:
        """Fold ``keys`` into the known set; returns (and accounts) how
        many were fresh."""
        fresh = set(keys) - self._known
        if not fresh:
            return 0
        self._known |= fresh
        self._register_programs(fresh)
        if self.auto_prime and not self._primed:
            self._primed = True
            return 0
        if self.counters is not None:
            self.counters.increment(self.group, "recompiles", len(fresh))
        _TRACER.event("recompile", scope=self.scope,
                      keys=sorted(repr(k) for k in fresh))
        return len(fresh)
