"""GraftPool — multi-tenant admission control, fair queueing and
per-tenant SLO isolation over one device pool (round 18).

A production cluster never runs one pipeline: it runs dozens from
different owners on shared chips.  This package arbitrates between them:

- :mod:`~avenir_tpu.tenancy.contract` parses the ``tenant.*`` conf family
  into per-tenant contracts (queue share, in-flight quota, priority,
  queue depth/deadline, per-tenant ``slo.*`` rules);
- :mod:`~avenir_tpu.tenancy.arbiter` is the weighted deficit-round-robin
  device arbiter every dispatch seam draws from — batch SharedScan chunk
  folds and stream pane folds (``pipeline/scan.py::ChunkFolder.fold``)
  and serving batch dispatches (``serving/batcher.py``) all acquire a
  slot, so one noisy tenant is throttled then shed while the others keep
  their contracted share.

Off-is-free: with no ``tenant.<id>.share`` key configured, every seam
pays one attribute check and a shared null context manager — the same
discipline as the tracer/profiler planes.
"""

from avenir_tpu.tenancy.arbiter import (  # noqa: F401
    GraftPool,
    configure,
    pool,
    reset,
    tenant_scope,
)
from avenir_tpu.tenancy.contract import (  # noqa: F401
    TenantContract,
    contracts_from_conf,
    tenant_slo_rules,
)
