"""The GraftPool device arbiter — weighted deficit-round-robin fair
queueing plus tenant-scoped admission control at the dispatch seam.

One device pool, N tenants, one arbiter: every device dispatch the
framework makes — a batch SharedScan chunk fold, a stream pane fold
(both through ``pipeline/scan.py::ChunkFolder.fold``), a serving batch
(``serving/batcher.py``) — acquires a :meth:`GraftPool.slot` before it
runs.  The arbiter decides who goes next when the pool is contended:

- **weighted DRR** (deficit round robin): each tenant's deficit grows by
  its contracted ``share`` per round and one unit of deficit buys one
  dispatch, so BACKLOGGED tenants split device time in share proportion
  — a flooding tenant cannot starve the others.  Like every
  work-conserving fair queue, shares bind only while a tenant has work
  WAITING: two closed-loop tenants each keeping one dispatch outstanding
  alternate 1:1 regardless of share (neither demands more than half, and
  favoring one would idle the device), which is the correct non-idling
  outcome — the noisy-tenant drill floods with many concurrent
  dispatches precisely because that is the shape shares pace;
- **strict priority tiers**: among quota-eligible waiting tenants only
  the highest ``priority`` tier is served; shares arbitrate WITHIN a
  tier (a latency-critical serving tenant outranks batch backfill);
- **in-flight quota**: ``max.inflight`` bounds a tenant's concurrently
  granted slots regardless of deficit;
- **tenant-scoped admission control**: a tenant whose waiting queue is at
  ``queue.depth``, or whose queued dispatch ages past its deadline,
  sheds with a typed
  :class:`~avenir_tpu.serving.errors.TenantShedError` naming the tenant
  and the quota that fired — shedding tenant A never sheds tenant B,
  because every bound is per-tenant by construction.

Every transition journals golden-schema'd events — ``tenant.admitted``
(once per tenant per journal), ``tenant.throttled`` (latched per
excursion, like ``slo.violation``), ``tenant.shed`` — and per-tenant
``Tenant.<id>`` counters (granted/shed/throttled) book the arbitration,
so isolation is a measured artifact (``benchmarks/tenancy_soak.py``).

Off-is-free: the module singleton is a disabled pool until
:func:`configure` finds a ``tenant.<id>.share`` contract; disabled (or
for work outside any tenant scope) ``slot()`` returns a shared null
context — one attribute check on the hot path, the tracer discipline.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from avenir_tpu.tenancy.contract import TenantContract, contracts_from_conf
from avenir_tpu.telemetry import spans as tel
from avenir_tpu.utils.metrics import Counters

# shared inert context manager: the disabled/unmanaged fast path (a
# nullcontext instance is stateless, hence reusable across threads)
_NULL = contextlib.nullcontext()

# bounds on the queue-drain estimate a shed reports (Retry-After must be
# neither 0 — "hammer me again" — nor unbounded); ONE policy shared by
# every shed path (the arbiter here, the serving door in
# serving/batcher.py) so the header means the same thing everywhere
RETRY_AFTER_MIN_S = 0.05
RETRY_AFTER_MAX_S = 600.0
# EWMA weight for the per-tenant slot-hold estimate the drain math uses
_HOLD_ALPHA = 0.2
# how often a queued waiter with an ``on_wait`` hook is woken to tick its
# caller's liveness signal (the serving dispatcher's heartbeat refresh)
_WAIT_TICK_S = 0.25


def tenant_scope(tenant: Optional[str]):
    """Run a workload as ``tenant``: every journal event it emits carries
    the label and every dispatch slot it acquires is arbitrated under the
    tenant's contract.  ``None``/empty = a no-op scope (unmanaged)."""
    return tel.label_scope(tenant=tenant or None)


class _Ticket:
    __slots__ = ("cost", "granted", "enqueued")

    def __init__(self, cost: float, now: float):
        self.cost = cost
        self.granted = False
        self.enqueued = now


class _TenantState:
    __slots__ = ("contract", "queue", "inflight", "deficit", "throttled",
                 "hold_ewma", "grants")

    def __init__(self, contract: TenantContract):
        self.contract = contract
        self.queue: Deque[_Ticket] = deque()
        self.inflight = 0
        self.deficit = 0.0
        self.throttled = False           # the per-excursion event latch
        self.hold_ewma = 0.0             # mean slot hold (drain estimate)
        self.grants = 0


class GraftPool:
    """The tenant arbiter over one device pool (see module docstring).

    ``capacity`` is how many dispatch slots exist pool-wide
    (``tenant.pool.concurrency``, default 1 — one accelerator serializes
    dispatches anyway; raise it for multi-device rigs where concurrent
    dispatches genuinely overlap)."""

    def __init__(self, contracts: Dict[str, TenantContract],
                 capacity: int = 1, counters: Optional[Counters] = None):
        if not contracts:
            raise ValueError("GraftPool needs at least one TenantContract")
        self.enabled = True
        self.capacity = max(int(capacity), 1)
        self.counters = counters if counters is not None else Counters()
        self._states = {t: _TenantState(c) for t, c in
                        sorted(contracts.items())}
        self._rr: List[str] = list(self._states)     # stable round order
        self._rr_pos = 0             # the DRR round pointer (persistent:
        #                              a capacity-1 pool grants one slot
        #                              per engine call, so the round must
        #                              survive across calls or weighting
        #                              degenerates to plain round-robin)
        self._credited: set = set()  # tenants credited in the current round
        self._in_use = 0
        self._cond = threading.Condition()

    @property
    def contracts(self) -> Dict[str, TenantContract]:
        return {t: st.contract for t, st in self._states.items()}

    # -- the dispatch slot (any thread) --------------------------------------
    def slot(self, tenant: Optional[str] = None, cost: float = 1.0,
             timeout_s: Optional[float] = None, on_wait=None):
        """A context manager holding one arbitrated device slot.

        ``tenant`` defaults to the ambient ``tenant`` label
        (:func:`tenant_scope`); work outside any tenant — or under a
        tenant with no contract — passes through unmanaged (the shared
        null context), so un-tenanted deployments never pay arbitration.
        ``timeout_s`` bounds the queued wait (default: the contract's
        ``queue.timeout.ms``; None = wait for the share).  ``on_wait``
        (optional, no-arg) is invoked at least every ``_WAIT_TICK_S``
        while the caller is queued — the liveness hook a caller with its
        own watchdog needs (the serving dispatcher refreshes its
        heartbeat through it, so a tenant replica merely being PACED is
        never mistaken for a wedged one and reaped).  Raises
        :class:`~avenir_tpu.serving.errors.TenantShedError` when the
        tenant's queue share is full or the deadline passes."""
        if tenant is None:
            tenant = tel.current_label("tenant")
        state = self._states.get(tenant) if tenant else None
        if state is None:
            return _NULL
        return self._slot_cm(tenant, state, float(cost), timeout_s, on_wait)

    @contextlib.contextmanager
    def _slot_cm(self, tenant: str, state: _TenantState, cost: float,
                 timeout_s: Optional[float], on_wait):
        t0 = self._acquire(tenant, state, cost, timeout_s, on_wait)
        try:
            yield tenant
        finally:
            self._release(tenant, state, t0)

    def _acquire(self, tenant: str, state: _TenantState, cost: float,
                 timeout_s: Optional[float], on_wait=None) -> float:
        c = state.contract
        tel.tracer().event_once(
            "tenant.admitted", key=tenant, tenant=tenant, share=c.share,
            priority=c.priority, max_inflight=c.max_inflight,
            queue_depth=c.queue_depth)
        if timeout_s is None:
            timeout_s = c.queue_timeout_s
        now = time.monotonic()
        deadline = now + timeout_s if timeout_s is not None else None
        # journal writes happen OUTSIDE the arbiter lock: a shed storm's
        # file I/O must never serialize other tenants' grants behind it
        # (fires = deferred tenant.throttled events; shed = the deferred
        # tenant.shed + typed error)
        fires: List[tuple] = []
        shed = None
        with self._cond:
            if len(state.queue) >= c.queue_depth:
                shed = self._shed_locked(tenant, state, "queue.depth")
            else:
                ticket = _Ticket(cost, now)
                state.queue.append(ticket)
                try:
                    if len(state.queue) > max(c.max_inflight, 1):
                        # backlog beyond what the tenant's quota can ever
                        # run concurrently: it is being paced — the
                        # deterministic throttle signal a capacity-1 pool
                        # can emit (the grant engine's quota/priority/
                        # share marks need spare capacity to observe a
                        # pass-over)
                        self._throttle_locked(tenant, state, "backlog",
                                              fires)
                    self._grant_locked(fires)
                    while not ticket.granted:
                        remaining = None
                        if deadline is not None:
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                state.queue.remove(ticket)
                                shed = self._shed_locked(tenant, state,
                                                         "deadline")
                                break
                        if on_wait is not None:
                            self._cond.wait(
                                _WAIT_TICK_S if remaining is None
                                else min(remaining, _WAIT_TICK_S))
                            on_wait()
                        else:
                            self._cond.wait(remaining)
                except BaseException:
                    # the ticket must never outlive its owner: an
                    # exception escaping here (KeyboardInterrupt in the
                    # wait, an error out of on_wait) would otherwise
                    # leave a queued ticket the engine later grants with
                    # nobody to release it — a one-way slot leak that
                    # wedges a capacity-1 pool
                    if ticket.granted:
                        state.inflight -= 1
                        self._in_use -= 1
                        self._grant_locked(fires)
                        self._cond.notify_all()
                    elif ticket in state.queue:
                        state.queue.remove(ticket)
                    raise
        self._emit_fires(fires)
        if shed is not None:
            err, fields = shed
            tel.tracer().event("tenant.shed", **fields)
            raise err
        return time.monotonic()

    def _release(self, tenant: str, state: _TenantState, t0: float) -> None:
        hold = time.monotonic() - t0
        fires: List[tuple] = []
        with self._cond:
            state.inflight -= 1
            self._in_use -= 1
            state.hold_ewma = (hold if state.hold_ewma == 0.0 else
                               (1.0 - _HOLD_ALPHA) * state.hold_ewma
                               + _HOLD_ALPHA * hold)
            self._grant_locked(fires)
            self._cond.notify_all()
        self._emit_fires(fires)

    @staticmethod
    def _emit_fires(fires: List[tuple]) -> None:
        tracer = tel.tracer()
        for ev, fields in fires:
            tracer.event(ev, **fields)

    # -- the grant engine (lock held) ----------------------------------------
    def _grant_locked(self, fires: List[tuple]) -> None:
        """Hand free slots to waiting tenants: strict priority tiers over
        the quota-eligible set, weighted DRR within the winning tier.
        Tenants passed over on POLICY (quota, priority, exhausted
        deficit) while work was waiting are marked throttled (latched —
        one ``tenant.throttled`` per excursion)."""
        # classic DRR over a persistent round: the pointer stays on a
        # tenant while its deficit buys dispatches, each tenant is
        # credited (+= share) once per round, and a full fruitless pass
        # starts a new round — so deficits always grow toward the next
        # grant (liveness) and grants converge to share proportion over
        # any contended interval, at ANY capacity (a capacity-1 pool
        # grants one slot per engine call; the round state carries the
        # weighting across calls)
        n = len(self._rr)
        while self._in_use < self.capacity:
            eligible = set()
            any_waiting = False
            for t in self._rr:
                st = self._states[t]
                if not st.queue:
                    continue
                any_waiting = True
                quota = st.contract.max_inflight
                if quota and st.inflight >= quota:
                    self._throttle_locked(t, st, "quota", fires)
                else:
                    eligible.add(t)
            if not any_waiting or not eligible:
                break
            top = max(self._states[t].contract.priority for t in eligible)
            tier = set()
            for t in eligible:
                if self._states[t].contract.priority == top:
                    tier.add(t)
                else:
                    self._throttle_locked(t, self._states[t], "priority",
                                          fires)
            granted = False
            scanned = 0
            while scanned < n and self._in_use < self.capacity:
                t = self._rr[self._rr_pos]
                st = self._states[t]
                quota = st.contract.max_inflight
                if t in tier and st.queue and \
                        not (quota and st.inflight >= quota):
                    if t not in self._credited:
                        self._credited.add(t)
                        st.deficit += st.contract.share
                    if st.deficit >= st.queue[0].cost:
                        ticket = st.queue.popleft()
                        st.deficit -= ticket.cost
                        ticket.granted = True
                        st.inflight += 1
                        st.grants += 1
                        self._in_use += 1
                        granted = True
                        if st.throttled:
                            st.throttled = False   # excursion over: re-arm
                        if not st.queue:
                            st.deficit = 0.0       # DRR: idle forfeits
                        else:
                            continue   # deficit may buy another dispatch
                    else:
                        # share exhausted this round with work waiting:
                        # the tenant is being paced
                        self._throttle_locked(t, st, "share", fires)
                self._rr_pos = (self._rr_pos + 1) % n
                scanned += 1
            if scanned >= n and not granted:
                # a full fruitless pass: new round — every tenant earns
                # fresh credit, so some deficit crosses its cost next pass
                self._credited.clear()
        self._cond.notify_all()

    def _throttle_locked(self, tenant: str, state: _TenantState,
                         reason: str, fires: List[tuple]) -> None:
        """Latch the tenant's throttle excursion; the journal event is
        DEFERRED into ``fires`` (emitted after the lock drops — file I/O
        inside the arbiter's critical section would let one tenant's
        throttle storm stall every other tenant's grants)."""
        if state.throttled:
            return
        state.throttled = True
        self.counters.increment(f"Tenant.{tenant}", "throttled")
        fires.append(("tenant.throttled",
                      dict(tenant=tenant, reason=reason,
                           waiting=len(state.queue),
                           inflight=state.inflight)))

    def _shed_locked(self, tenant: str, state: _TenantState,
                     quota: str) -> tuple:
        """Book the shed and BUILD the typed error + journal payload —
        the caller emits and raises after releasing the lock, so a shed
        storm's journal writes never serialize other tenants' slots."""
        from avenir_tpu.serving.errors import TenantShedError

        retry_after = self.drain_estimate_s(tenant, locked=True)
        self.counters.increment(f"Tenant.{tenant}", "shed")
        fields = dict(tenant=tenant, quota=quota,
                      waiting=len(state.queue), inflight=state.inflight,
                      retry_after_ms=round(retry_after * 1e3, 1))
        err = TenantShedError(
            f"tenant {tenant!r} shed at the pool door: {quota} "
            f"(waiting={len(state.queue)}, inflight={state.inflight}, "
            f"retry after ~{retry_after:.2f}s) — other tenants keep "
            f"their share",
            tenant=tenant, quota=quota, retry_after_s=retry_after)
        return err, fields

    # -- observability --------------------------------------------------------
    def drain_estimate_s(self, tenant: str, locked: bool = False) -> float:
        """How long this tenant's backlog needs to drain at its
        contracted share of the pool — the ``Retry-After`` a shed
        carries.  Backlog × mean slot hold ÷ the tenant's slice of
        capacity, bounded to a sane window (no samples yet reads as one
        nominal 100 ms hold)."""
        ctx = contextlib.nullcontext() if locked else self._cond
        with ctx:
            state = self._states[tenant]
            backlog = len(state.queue) + state.inflight
            hold = state.hold_ewma or 0.1
            total_share = sum(st.contract.share
                              for st in self._states.values())
            slice_ = self.capacity * state.contract.share / total_share
        est = (backlog + 1) * hold / max(slice_, 1e-6)
        return min(max(est, RETRY_AFTER_MIN_S), RETRY_AFTER_MAX_S)

    def queue_depths(self) -> Dict[str, int]:
        """Per-tenant waiting dispatches — the ``tenant.queue.<id>``
        gauges a soak publishes."""
        with self._cond:
            return {t: len(st.queue) for t, st in self._states.items()}

    def stats(self) -> Dict[str, dict]:
        """Per-tenant arbitration snapshot (grants/inflight/waiting plus
        the booked shed/throttle counters)."""
        groups = self.counters.as_dict()
        with self._cond:
            return {t: {
                "share": st.contract.share,
                "priority": st.contract.priority,
                "grants": st.grants,
                "inflight": st.inflight,
                "waiting": len(st.queue),
                "shed": groups.get(f"Tenant.{t}", {}).get("shed", 0),
                "throttled": groups.get(f"Tenant.{t}", {}).get(
                    "throttled", 0),
            } for t, st in self._states.items()}

class _DisabledPool:
    """The zero-cost default: no contracts configured, every slot is the
    shared null context."""

    enabled = False
    capacity = 0
    contracts: Dict[str, TenantContract] = {}

    def slot(self, tenant: Optional[str] = None, cost: float = 1.0,
             timeout_s: Optional[float] = None, on_wait=None):
        return _NULL

    def queue_depths(self) -> Dict[str, int]:
        return {}

    def stats(self) -> Dict[str, dict]:
        return {}


_DISABLED = _DisabledPool()
_POOL = _DISABLED
_POOL_LOCK = threading.Lock()


def pool():
    """The process arbiter (disabled, hence free, until configured)."""
    return _POOL


def configure(conf):
    """Arm the process arbiter from ``tenant.*`` conf keys; a no-op —
    and one props scan — when no ``tenant.<id>.share`` contract exists.
    Idempotent like the tracer: the first enabling conf wins (a driver,
    its jobs and a serving plane all call this with the same conf)."""
    global _POOL
    if _POOL.enabled:
        return _POOL
    contracts = contracts_from_conf(conf)
    if not contracts:
        return _POOL
    with _POOL_LOCK:
        if not _POOL.enabled:
            _POOL = GraftPool(
                contracts,
                capacity=conf.get_int("tenant.pool.concurrency", 1))
    return _POOL


def reset() -> None:
    """Drop the process arbiter (tests, run teardown)."""
    global _POOL
    with _POOL_LOCK:
        _POOL = _DISABLED
