"""Tenant contracts — the declarative ``tenant.*`` conf family.

Grammar (properties file, the reference's ``-D`` contract), mirroring the
``slo.<name>.*`` rule family (round 15) — the ``share`` key is the
existence marker, everything else defaults::

    tenant.analytics.share=4           # weighted fair-queueing share
    tenant.analytics.max.inflight=2    # quota: concurrent device slots
    tenant.analytics.queue.depth=64    # waiters bound (admission control)
    tenant.analytics.priority=0        # strict tiers; shares arbitrate
                                       #   WITHIN a tier
    tenant.analytics.queue.timeout.ms=5000   # deadline while queued
    tenant.analytics.slo.p99.metric=p99.latency.ms   # per-tenant SLO
    tenant.analytics.slo.p99.target=50               #   rules (the
                                                     #   slo.* grammar)

Pool-wide keys: ``tenant.pool.concurrency`` (device slots the arbiter
hands out at once, default 1 — the accelerator serializes dispatches
anyway), ``tenant.queue.depth`` / ``tenant.queue.timeout.ms`` (per-tenant
defaults), and ``tenant.id`` (the tenant a conf's OWN workload runs as —
read by the driver, the job layer and the serving batcher, stamped onto
every journal event the workload emits).

Per-tenant SLO rules reuse the round-15 declarative grammar verbatim:
:func:`tenant_slo_rules` strips the ``tenant.<id>.`` prefix and hands the
remainder to ``telemetry.slo.rules_from_conf``, so every metric/op/window
feature — and every future one — works per tenant for free.  Post-hoc
verdicts pair them with ``telemetry slo <journal> --label tenant=<id>``.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional

_SHARE_KEY_RE = re.compile(r"^tenant\.([A-Za-z0-9_-]+)\.share$")
# every per-tenant subkey the grammar knows; anything else under
# tenant.<id>. is a typo that must fail loudly (see contracts_from_conf)
_TENANT_KEY_RE = re.compile(
    r"^tenant\.([A-Za-z0-9_-]+)\.(share|max\.inflight|queue\.depth|"
    r"priority|queue\.timeout\.ms|slo\..+)$")
# pool-wide keys that are NOT per-tenant contracts
_POOL_WIDE_RE = re.compile(
    r"^tenant\.(id|pool\..+|queue\.depth|queue\.timeout\.ms)$")

# segment names the pool-wide tenant.* keys claim — a tenant id colliding
# with one would make the grammar ambiguous (tenant.queue.depth is the
# DEFAULT depth, not tenant "queue"'s), so it is refused loudly
RESERVED_IDS = frozenset({"id", "pool", "queue"})


@dataclass(frozen=True)
class TenantContract:
    """One tenant's admission contract on the shared device pool."""

    tenant: str
    share: float                     # DRR weight (queue share)
    max_inflight: int = 0            # 0 = unbounded (pool capacity bounds)
    queue_depth: int = 64            # waiting dispatches before shedding
    priority: int = 0                # strict tiers, higher first
    queue_timeout_s: Optional[float] = None   # deadline while queued


def contracts_from_conf(conf) -> Dict[str, TenantContract]:
    """Every ``tenant.<id>.share`` contract in the conf (bare or
    prefix-namespaced), keyed by tenant id.  A non-positive share, a
    reserved id, or an unparsable quota raises ConfigError — a silent
    mis-parsed contract would hand a tenant the wrong slice of the pool."""
    from avenir_tpu.core.config import ConfigError

    names = set()
    bare_keys = []
    for key in conf.props:
        bare = key[len(conf.prefix) + 1:] if key.startswith(
            conf.prefix + ".") else key
        bare_keys.append(bare)
        m = _SHARE_KEY_RE.match(bare)
        if m:
            names.add(m.group(1))
    # a tenant.* key the grammar does not know is a typo, not a no-op: a
    # silently-dropped contract key hands a tenant the wrong slice of the
    # pool (or no arbitration at all — the exact starvation this family
    # exists to prevent), so refuse it loudly
    for bare in bare_keys:
        if not bare.startswith("tenant."):
            continue
        if _POOL_WIDE_RE.match(bare):
            continue
        m = _TENANT_KEY_RE.match(bare)
        if m is None:
            raise ConfigError(
                f"unrecognized tenant.* key {bare!r} — per-tenant keys "
                f"are tenant.<id>.{{share,max.inflight,queue.depth,"
                f"priority,queue.timeout.ms,slo.*}} with <id> one dotted "
                f"segment, pool-wide keys tenant.{{id,pool.*,queue.*}}")
        if m.group(1) not in names and m.group(1) not in RESERVED_IDS:
            raise ConfigError(
                f"{bare!r} names tenant {m.group(1)!r} which has no "
                f"tenant.{m.group(1)}.share contract — a quota without "
                f"a share arbitrates nothing")
    default_depth = conf.get_int("tenant.queue.depth", 64)
    default_timeout = conf.get_float("tenant.queue.timeout.ms")
    out: Dict[str, TenantContract] = {}
    for name in sorted(names):
        if name in RESERVED_IDS:
            raise ConfigError(
                f"tenant id {name!r} collides with the pool-wide tenant.* "
                f"key family (reserved: {sorted(RESERVED_IDS)})")
        share = conf.get_float(f"tenant.{name}.share")
        if share is None or share <= 0:
            raise ConfigError(
                f"tenant.{name}.share={share!r} must be a positive weight")
        timeout_ms = conf.get_float(f"tenant.{name}.queue.timeout.ms",
                                    default_timeout)
        out[name] = TenantContract(
            tenant=name,
            share=float(share),
            max_inflight=conf.get_int(f"tenant.{name}.max.inflight", 0) or 0,
            queue_depth=max(
                conf.get_int(f"tenant.{name}.queue.depth", default_depth), 1),
            priority=conf.get_int(f"tenant.{name}.priority", 0) or 0,
            queue_timeout_s=(float(timeout_ms) / 1e3
                             if timeout_ms is not None else None),
        )
    return out


def split_contracts(conf, nworkers: int) -> Dict[str, str]:
    """GlobalServe (round 20): one worker's 1/N slice of the conf's
    tenant contracts, as ``-D``-able conf overrides.

    The fleet launcher hands EVERY worker the same properties file; these
    overrides re-scope the absolute quotas so that N workers' local DRR
    arbitration sums back to the declared GLOBAL contract:

    - ``max.inflight`` and ``queue.depth`` are absolute counts →
      ceil-divided across workers (ceil, so N workers' slices always
      cover the global quota — the router's OWN door enforces the exact
      fleet-wide ceiling with the unsplit contracts, so a worker-side
      over-grant of < 1 slot per worker never admits past the global
      limit);
    - ``share`` and ``priority`` are RELATIVE weights/tiers — identical
      on every worker, a 3:1 split arbitrates 3:1 locally and therefore
      3:1 globally — so they are not overridden;
    - ``queue.timeout.ms`` and ``slo.*`` are per-request/per-journal
      semantics, unsplit.

    Raises the same ConfigError a malformed contract raises anywhere
    (the split must not silently launder a typo into a running fleet)."""
    from avenir_tpu.core.config import ConfigError

    if nworkers < 1:
        raise ConfigError(
            f"split_contracts needs nworkers >= 1, got {nworkers}")
    out: Dict[str, str] = {}
    for name, contract in contracts_from_conf(conf).items():
        if contract.max_inflight:
            out[f"tenant.{name}.max.inflight"] = str(
                -(-contract.max_inflight // nworkers))
        if contract.queue_depth:
            out[f"tenant.{name}.queue.depth"] = str(
                max(-(-contract.queue_depth // nworkers), 1))
    return out


def tenant_slo_rules(conf, tenant: str) -> List:
    """The tenant's own SLO rule set: every ``tenant.<id>.slo.<name>.*``
    key re-read through the round-15 grammar (``slo.* `` semantics —
    metric/target/op/window — apply verbatim).  Evaluate them post-hoc
    over a merged journal with ``telemetry slo --conf ... --label
    tenant=<id>`` so the verdict sees only this tenant's events."""
    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.telemetry.slo import rules_from_conf

    prefix = f"tenant.{tenant}."
    sub: Dict[str, str] = {}
    for key, value in conf.props.items():
        bare = key[len(conf.prefix) + 1:] if key.startswith(
            conf.prefix + ".") else key
        if bare.startswith(prefix):
            sub[bare[len(prefix):]] = value
    return rules_from_conf(JobConfig(sub, prefix=conf.prefix))
