"""Text analysis: tokenizer, Porter stemmer, word counting."""

from avenir_tpu.text.analyzer import STOPWORDS, analyze_lines, porter_stem, tokenize
from avenir_tpu.text.wordcount import WordCount

__all__ = ["STOPWORDS", "analyze_lines", "porter_stem", "tokenize", "WordCount"]
