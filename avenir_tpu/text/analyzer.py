"""Text analysis — tokenizer, stopwords, Porter stemmer.

The reference tokenizes text through Lucene's ``StandardAnalyzer`` (lowercase
+ word-break + English stopword removal) for text-mode Naive Bayes and word
counting (bayesian/BayesianDistribution.java:126-131,187-196,
text/WordCounter.java:94,117-128). This module is the in-tree equivalent:
a regex word-breaker, Lucene's default English stopword set, and a classic
Porter stemmer for the stemming mode.
"""

from __future__ import annotations

import re
from typing import List, Sequence

# Lucene StandardAnalyzer's default English stop set
STOPWORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such "
    "that the their then there these they this to was will with".split()
)

_WORD_RE = re.compile(r"[a-z0-9']+")


def tokenize(text: str, stopwords: bool = True, stem: bool = False,
             min_len: int = 1) -> List[str]:
    """Lowercase word-break tokens, minus stopwords, optionally stemmed."""
    toks = _WORD_RE.findall(text.lower())
    toks = [t.strip("'") for t in toks]
    out = []
    for t in toks:
        if len(t) < min_len or not t:
            continue
        if stopwords and t in STOPWORDS:
            continue
        out.append(porter_stem(t) if stem else t)
    return out


# ---------------------------------------------------------------------------
# Porter stemmer (Porter, 1980 — the classic 5-step suffix stripper)
# ---------------------------------------------------------------------------

_VOWELS = "aeiou"


def _is_cons(word: str, i: int) -> bool:
    ch = word[i]
    if ch in _VOWELS:
        return False
    if ch == "y":
        return i == 0 or not _is_cons(word, i - 1)
    return True


def _measure(stem: str) -> int:
    """m in the [C](VC)^m[V] decomposition."""
    m = 0
    prev_vowel = False
    for i in range(len(stem)):
        if _is_cons(stem, i):
            if prev_vowel:
                m += 1
            prev_vowel = False
        else:
            prev_vowel = True
    return m


def _has_vowel(stem: str) -> bool:
    return any(not _is_cons(stem, i) for i in range(len(stem)))


def _ends_double_cons(word: str) -> bool:
    return (len(word) >= 2 and word[-1] == word[-2]
            and _is_cons(word, len(word) - 1))


def _cvc(word: str) -> bool:
    """ends consonant-vowel-consonant, last not w/x/y."""
    if len(word) < 3:
        return False
    return (_is_cons(word, len(word) - 3)
            and not _is_cons(word, len(word) - 2)
            and _is_cons(word, len(word) - 1)
            and word[-1] not in "wxy")


def porter_stem(word: str) -> str:
    if len(word) <= 2:
        return word
    w = word

    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif w.endswith("ss"):
        pass
    elif w.endswith("s"):
        w = w[:-1]

    # step 1b
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    elif (w.endswith("ed") and _has_vowel(w[:-2])) or \
         (w.endswith("ing") and _has_vowel(w[:-3])):
        w = w[:-2] if w.endswith("ed") else w[:-3]
        if w.endswith(("at", "bl", "iz")):
            w += "e"
        elif _ends_double_cons(w) and w[-1] not in "lsz":
            w = w[:-1]
        elif _measure(w) == 1 and _cvc(w):
            w += "e"

    # step 1c
    if w.endswith("y") and _has_vowel(w[:-1]):
        w = w[:-1] + "i"

    # step 2
    for suf, rep in (
        ("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
        ("anci", "ance"), ("izer", "ize"), ("abli", "able"), ("alli", "al"),
        ("entli", "ent"), ("eli", "e"), ("ousli", "ous"), ("ization", "ize"),
        ("ation", "ate"), ("ator", "ate"), ("alism", "al"), ("iveness", "ive"),
        ("fulness", "ful"), ("ousness", "ous"), ("aliti", "al"),
        ("iviti", "ive"), ("biliti", "ble"),
    ):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 0:
                w = w[:-len(suf)] + rep
            break

    # step 3
    for suf, rep in (
        ("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
        ("ical", "ic"), ("ful", ""), ("ness", ""),
    ):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 0:
                w = w[:-len(suf)] + rep
            break

    # step 4
    for suf in ("al", "ance", "ence", "er", "ic", "able", "ible", "ant",
                "ement", "ment", "ent", "ion", "ou", "ism", "ate", "iti",
                "ous", "ive", "ize"):
        if w.endswith(suf):
            stem = w[:-len(suf)]
            if _measure(stem) > 1:
                if suf == "ion" and (not stem or stem[-1] not in "st"):
                    break
                w = stem
            break

    # step 5a
    if w.endswith("e"):
        m = _measure(w[:-1])
        if m > 1 or (m == 1 and not _cvc(w[:-1])):
            w = w[:-1]
    # step 5b
    if _measure(w) > 1 and _ends_double_cons(w) and w.endswith("l"):
        w = w[:-1]
    return w


def analyze_lines(lines: Sequence[str], stopwords: bool = True,
                  stem: bool = False) -> List[List[str]]:
    return [tokenize(ln, stopwords=stopwords, stem=stem) for ln in lines]
