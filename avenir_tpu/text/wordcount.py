"""Word counting — host tokenization + device aggregation.

The counterpart of text/WordCounter.java: mapper tokenizes (:117-128) and
emits word→1, reducer sums. Here tokenization builds a vocabulary on the host
(the open-vocab pass the reference gets from the shuffle's string keys), and
the counting is a device ``bincount`` over code streams — the same
shuffle-as-histogram collapse used everywhere else in the framework.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from avenir_tpu.text.analyzer import tokenize


class WordCount:
    """Streaming word counter with a growing vocabulary."""

    def __init__(self, stopwords: bool = True, stem: bool = False):
        self.stopwords = stopwords
        self.stem = stem
        self.vocab: Dict[str, int] = {}
        self.counts = np.zeros(0, np.int64)

    def _encode(self, tokens: List[str]) -> np.ndarray:
        codes = np.empty(len(tokens), np.int32)
        vocab = self.vocab
        for i, t in enumerate(tokens):
            code = vocab.get(t)
            if code is None:
                code = len(vocab)
                vocab[t] = code
            codes[i] = code
        return codes

    def add_lines(self, lines: Iterable[str]) -> None:
        tokens: List[str] = []
        for ln in lines:
            tokens.extend(tokenize(ln, stopwords=self.stopwords, stem=self.stem))
        if not tokens:
            return
        codes = self._encode(tokens)
        v = len(self.vocab)
        batch = np.asarray(jnp.bincount(jnp.asarray(codes), length=v))
        if self.counts.shape[0] < v:
            self.counts = np.concatenate(
                [self.counts, np.zeros(v - self.counts.shape[0], np.int64)])
        self.counts += batch.astype(np.int64)

    def items(self) -> List[Tuple[str, int]]:
        inv = {i: w for w, i in self.vocab.items()}
        return [(inv[i], int(self.counts[i])) for i in range(len(self.vocab))]

    def top(self, k: int = 20) -> List[Tuple[str, int]]:
        return sorted(self.items(), key=lambda t: (-t[1], t[0]))[:k]

    def to_lines(self, delim: str = ",", sort: bool = True) -> List[str]:
        items = (sorted(self.items(), key=lambda t: (-t[1], t[0]))
                 if sort else sorted(self.items()))
        return [f"{w}{delim}{c}" for w, c in items]
