from avenir_tpu.utils.metrics import ConfusionMatrix, CostBasedArbitrator, Counters

__all__ = ["ConfusionMatrix", "CostBasedArbitrator", "Counters"]
