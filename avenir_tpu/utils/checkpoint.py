"""Checkpoint/resume — explicit, durable snapshots of model and learner state.

The reference's checkpointing is implicit: durable HDFS files double as
resume points (LR coefficient history, LogisticRegressionJob.java:95-119;
tree directory layout, DataPartitioner.java:114-129; bandit running-aggregate
rows). The online-learner state, by contrast, is lost on bolt restart
(ReinforcementLearnerBolt in-memory state, SURVEY §3.5). Here checkpointing is
explicit and uniform: a :class:`CheckpointManager` writes step-stamped
snapshots of any JSON+array state tree to a directory, keeps the last K,
and restores the latest on resume — covering model sufficient statistics,
RL learner state, and pipeline progress alike.

State trees are nested dicts whose leaves are numpy/JAX arrays, scalars,
strings, lists, or None. Arrays go into one ``.npz`` per snapshot; the
structure (with array placeholders) goes into ``state.json`` — no pickle.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
from typing import Any, Dict, List, Optional

import numpy as np

_ARRAY_TAG = "__array__"
_STEP_RE = re.compile(r"^step_(\d+)$")


def _flatten(tree: Any, prefix: str, arrays: Dict[str, np.ndarray]) -> Any:
    """Replace array leaves with tagged references; collect arrays."""
    if isinstance(tree, dict):
        return {k: _flatten(v, f"{prefix}/{k}", arrays) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        out = [_flatten(v, f"{prefix}/{i}", arrays) for i, v in enumerate(tree)]
        return out if isinstance(tree, list) else {"__tuple__": out}
    if hasattr(tree, "shape") and hasattr(tree, "dtype"):
        key = prefix.lstrip("/")
        arrays[key] = np.asarray(tree)
        return {_ARRAY_TAG: key}
    if isinstance(tree, (str, int, float, bool)) or tree is None:
        return tree
    if isinstance(tree, (np.integer,)):
        return int(tree)
    if isinstance(tree, (np.floating,)):
        return float(tree)
    raise TypeError(f"unsupported checkpoint leaf type {type(tree)!r} at {prefix}")


def _unflatten(node: Any, arrays: Dict[str, np.ndarray]) -> Any:
    if isinstance(node, dict):
        if _ARRAY_TAG in node and len(node) == 1:
            return arrays[node[_ARRAY_TAG]]
        if "__tuple__" in node and len(node) == 1:
            return tuple(_unflatten(v, arrays) for v in node["__tuple__"])
        return {k: _unflatten(v, arrays) for k, v in node.items()}
    if isinstance(node, list):
        return [_unflatten(v, arrays) for v in node]
    return node


def save_state(path: str, state: Any) -> None:
    """Write one snapshot atomically (temp dir + rename)."""
    parent = os.path.dirname(path.rstrip(os.sep)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=".ckpt_", dir=parent)
    try:
        arrays: Dict[str, np.ndarray] = {}
        structure = _flatten(state, "", arrays)
        with open(os.path.join(tmp, "state.json"), "w") as fh:
            json.dump(structure, fh)
        np.savez(os.path.join(tmp, "arrays.npz"), **arrays)
        if os.path.exists(path):
            shutil.rmtree(path)
        os.replace(tmp, path)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_state(path: str) -> Any:
    with open(os.path.join(path, "state.json")) as fh:
        structure = json.load(fh)
    npz_path = os.path.join(path, "arrays.npz")
    arrays = dict(np.load(npz_path, allow_pickle=False)) if os.path.exists(npz_path) else {}
    return _unflatten(structure, arrays)


class CheckpointManager:
    """Step-stamped snapshot directory with retention.

    ::

        mgr = CheckpointManager(dir, keep=3)
        mgr.save(step, {"weights": w, "round": r})
        state = mgr.restore()          # latest, or None if empty
    """

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def _steps(self) -> List[int]:
        out = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m and os.path.isdir(os.path.join(self.directory, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def save(self, step: int, state: Any) -> str:
        path = os.path.join(self.directory, f"step_{step}")
        save_state(path, state)
        for old in self._steps()[:-self.keep] if self.keep else []:
            shutil.rmtree(os.path.join(self.directory, f"step_{old}"),
                          ignore_errors=True)
        return path

    def latest_step(self) -> Optional[int]:
        steps = self._steps()
        return steps[-1] if steps else None

    def restore(self, step: Optional[int] = None) -> Optional[Any]:
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
        return load_state(os.path.join(self.directory, f"step_{step}"))
