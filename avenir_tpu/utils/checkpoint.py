"""Checkpoint/resume — explicit, durable snapshots of model and learner state.

The reference's checkpointing is implicit: durable HDFS files double as
resume points (LR coefficient history, LogisticRegressionJob.java:95-119;
tree directory layout, DataPartitioner.java:114-129; bandit running-aggregate
rows). The online-learner state, by contrast, is lost on bolt restart
(ReinforcementLearnerBolt in-memory state, SURVEY §3.5). Here checkpointing is
explicit and uniform: a :class:`CheckpointManager` writes step-stamped
snapshots of any JSON+array state tree to a directory, keeps the last K,
and restores the latest on resume — covering model sufficient statistics,
RL learner state, and pipeline progress alike.

State trees are nested dicts whose leaves are numpy/JAX arrays, scalars,
strings, lists, or None. Arrays go into one ``.npz`` per snapshot; the
structure (with array placeholders) goes into ``state.json`` — no pickle.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
from typing import Any, Dict, List, Optional

import numpy as np

_ARRAY_TAG = "__array__"
_TUPLE_TAG = "__tuple__"
_DICT_TAG = "__dict__"
_STEP_RE = re.compile(r"^step_(\d+)$")


class CheckpointError(RuntimeError):
    """A snapshot that cannot be restored WHOLE: torn structure, missing
    array payload, or a directory that vanished mid-read.  Restore paths
    must surface this loudly — a partial tree restoring silently is the
    corruption class the atomic save discipline exists to prevent."""


def _escape(key: str) -> str:
    """Array-namespace path escaping: user dict keys may contain '/' (ids are
    user-controlled), which must not collide with the path separator."""
    return key.replace("%", "%25").replace("/", "%2F")


def _flatten(tree: Any, prefix: str, arrays: Dict[str, np.ndarray]) -> Any:
    """Replace array leaves with tagged references; collect arrays."""
    if isinstance(tree, dict):
        out = {k: _flatten(v, f"{prefix}/{_escape(str(k))}", arrays)
               for k, v in tree.items()}
        # a user dict whose single key equals a marker tag would be
        # misread on load — wrap it so decoding stays unambiguous
        if len(out) == 1 and next(iter(out)) in (_ARRAY_TAG, _TUPLE_TAG, _DICT_TAG):
            return {_DICT_TAG: out}
        return out
    if isinstance(tree, (list, tuple)):
        out = [_flatten(v, f"{prefix}/{i}", arrays) for i, v in enumerate(tree)]
        return out if isinstance(tree, list) else {_TUPLE_TAG: out}
    # numpy scalars also expose .shape/.dtype — convert them first so they
    # round-trip as Python scalars, not 0-d arrays
    if isinstance(tree, np.bool_):
        return bool(tree)
    if isinstance(tree, np.integer):
        return int(tree)
    if isinstance(tree, np.floating):
        return float(tree)
    if hasattr(tree, "shape") and hasattr(tree, "dtype"):
        # "k:" guard: np.savez(file, **kwds) would reject a bare key named
        # "file" (collides with its positional parameter)
        key = "k:" + prefix.lstrip("/")
        arrays[key] = np.asarray(tree)
        return {_ARRAY_TAG: key}
    if isinstance(tree, (str, int, float, bool)) or tree is None:
        return tree
    raise TypeError(f"unsupported checkpoint leaf type {type(tree)!r} at {prefix}")


def _unflatten(node: Any, arrays: Dict[str, np.ndarray]) -> Any:
    if isinstance(node, dict):
        if _ARRAY_TAG in node and len(node) == 1:
            ref = node[_ARRAY_TAG]
            if ref not in arrays:
                # the structure references an array the payload lacks: a
                # torn snapshot (external interference — the atomic save
                # never produces this) must refuse, not restore partially
                raise CheckpointError(
                    f"snapshot structure references array {ref!r} missing "
                    f"from arrays.npz — torn snapshot; refusing to "
                    f"restore a partial tree")
            return arrays[ref]
        if _TUPLE_TAG in node and len(node) == 1:
            return tuple(_unflatten(v, arrays) for v in node[_TUPLE_TAG])
        if _DICT_TAG in node and len(node) == 1:
            return {k: _unflatten(v, arrays) for k, v in node[_DICT_TAG].items()}
        return {k: _unflatten(v, arrays) for k, v in node.items()}
    if isinstance(node, list):
        return [_unflatten(v, arrays) for v in node]
    return node


def save_state(path: str, state: Any) -> None:
    """Write one snapshot atomically (temp dir + rename)."""
    parent = os.path.dirname(path.rstrip(os.sep)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=".ckpt_", dir=parent)
    try:
        arrays: Dict[str, np.ndarray] = {}
        structure = _flatten(state, "", arrays)
        with open(os.path.join(tmp, "state.json"), "w") as fh:
            json.dump(structure, fh)
        np.savez(os.path.join(tmp, "arrays.npz"), **arrays)
        if os.path.exists(path):
            # move the old snapshot to a visible <path>.bak before swapping
            # the new one in: a crash in the window leaves the .bak, which
            # load_state and CheckpointManager both know how to recover
            bak = path.rstrip(os.sep) + ".bak"
            shutil.rmtree(bak, ignore_errors=True)      # stale prior crash
            os.replace(path, bak)
            try:
                os.replace(tmp, path)
            except BaseException:
                os.replace(bak, path)                   # roll back
                raise
            shutil.rmtree(bak, ignore_errors=True)
        else:
            os.replace(tmp, path)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_state(path: str) -> Any:
    if not os.path.exists(os.path.join(path, "state.json")) and \
            os.path.exists(path.rstrip(os.sep) + ".bak"):
        # crash during an overwrite swap: the complete old snapshot is at .bak
        path = path.rstrip(os.sep) + ".bak"
    with open(os.path.join(path, "state.json")) as fh:
        try:
            structure = json.load(fh)
        except ValueError as e:
            raise CheckpointError(
                f"snapshot structure {path!r}/state.json is not valid "
                f"JSON ({e}) — torn snapshot; refusing to restore a "
                f"partial tree") from e
    npz_path = os.path.join(path, "arrays.npz")
    arrays = dict(np.load(npz_path, allow_pickle=False)) if os.path.exists(npz_path) else {}
    return _unflatten(structure, arrays)


class CheckpointManager:
    """Step-stamped snapshot directory with retention.

    ::

        mgr = CheckpointManager(dir, keep=3)
        mgr.save(step, {"weights": w, "round": r})
        state = mgr.restore()          # latest, or None if empty
    """

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)
        self._recover()

    def _recover(self) -> None:
        """Finish any overwrite swap interrupted by a crash: promote orphaned
        ``step_N.bak`` snapshots, drop redundant ones, and sweep leftover
        ``.ckpt_*`` temp dirs (each holds a full-size snapshot copy).
        Single-writer assumption: no concurrent save may be in flight."""
        for name in os.listdir(self.directory):
            if name.startswith(".ckpt_"):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)
                continue
            if not name.endswith(".bak") or not _STEP_RE.match(name[:-4]):
                continue
            bak = os.path.join(self.directory, name)
            live = bak[:-4]
            if os.path.exists(live):
                shutil.rmtree(bak, ignore_errors=True)
            else:
                os.replace(bak, live)

    def _steps(self) -> List[int]:
        out = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m and os.path.isdir(os.path.join(self.directory, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def save(self, step: int, state: Any) -> str:
        path = os.path.join(self.directory, f"step_{step}")
        save_state(path, state)
        for old in self._steps()[:-self.keep] if self.keep else []:
            shutil.rmtree(os.path.join(self.directory, f"step_{old}"),
                          ignore_errors=True)
        return path

    def latest_step(self) -> Optional[int]:
        steps = self._steps()
        return steps[-1] if steps else None

    def restore(self, step: Optional[int] = None, *,
                reshard_to=None) -> Optional[Any]:
        """Restore a snapshot — whole, or not at all.

        Latest-step restore (``step=None``) tolerates a snapshot that
        VANISHES between the directory listing and the read (a concurrent
        retention sweep racing ``_steps()``): it falls back to the next-
        newest intact snapshot.  A TORN snapshot raises
        :class:`CheckpointError` instead — torn state means external
        interference the caller must surface, never silently skip.

        ``reshard_to`` (ElasticGraft, round 16): a target topology — a
        ``parallel/shard.ShardSpec``, a ``:mesh:<axis><n>`` suffix
        string, or ``""`` for unsharded — to redistribute every
        mesh-qualified accumulator entry of the restored tree onto
        (``checkpoint/reshard.py``; raises ``ReshardError`` on genuinely
        non-portable state).  The default None means DO NOT reshard:
        the tree comes back exactly as written, mesh qualifiers
        included — pass the empty string, not None, to strip them."""
        steps = [step] if step is not None else \
            list(reversed(self._steps()))
        state = missing = object()
        for s in steps:
            try:
                state = load_state(os.path.join(self.directory, f"step_{s}"))
                break
            except FileNotFoundError:
                if step is not None:
                    raise
        if state is missing:
            return None
        if reshard_to is not None:
            from avenir_tpu.checkpoint import reshard

            state, _ = reshard.reshard_state_tree(state, reshard_to)
        return state

    def clear(self) -> None:
        """Remove every manager-owned entry (``step_N`` snapshots, their
        ``.bak`` twins, ``.ckpt_*`` temps), then the directory itself —
        but ONLY if nothing else lives there.  Users may point the
        checkpoint dir at a shared area holding unrelated files; a
        successful run must never delete those."""
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return
        for name in names:
            owned = (name.startswith(".ckpt_") or _STEP_RE.match(name)
                     or (name.endswith(".bak") and _STEP_RE.match(name[:-4])))
            if owned:
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)
        try:
            os.rmdir(self.directory)        # only succeeds when empty
        except OSError:
            pass
