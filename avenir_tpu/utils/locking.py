"""Race protection for cross-process mutable files — locks + atomic writes.

The reference has exactly one cross-task mutable-state hazard: the LR
coefficient-history rewrite (regress/LogisticRegressionJob.java:238-255,
delete + rewrite), safe there only because ``num.reducer=1`` pins a single
writer (SURVEY.md §5 "race detection"). Everything else inherits MR's
share-nothing model. This framework runs in ordinary processes where
nothing pins a single writer, so the equivalent files (LR history, the
compiled native library) get explicit protection:

- :class:`FileLock` — advisory ``flock`` on a sidecar ``<path>.lock``;
  contention within ``timeout_s`` raises :class:`LockHeldError`, which
  *detects* a concurrent writer instead of silently interleaving (the
  race-detection capability the reference lacks).
- :func:`atomic_write` — write to a same-directory temp file then
  ``os.replace``, so readers never observe a torn file and a crash
  mid-write leaves the previous version intact (complements
  utils/checkpoint.py's temp-dir + rename discipline).
"""

from __future__ import annotations

import contextlib
import errno
import os
import stat
import tempfile
import time
from typing import IO, Iterator, Optional

try:
    import fcntl
except ImportError:                      # non-POSIX: degrade to lockless
    fcntl = None  # type: ignore[assignment]


class LockHeldError(RuntimeError):
    """Another process holds the lock — a concurrent writer was detected."""

    def __init__(self, path: str, timeout_s: float):
        super().__init__(
            f"lock {path!r} held by another process (waited {timeout_s}s); "
            "refusing to interleave writes")
        self.path = path


class FileLock:
    """Advisory exclusive lock on ``<target>.lock``.

    ``timeout_s=0`` means try-once (pure detection); positive values poll
    until acquired or :class:`LockHeldError`. Reentrant use in one process
    is not supported — the point is cross-process exclusion.
    """

    def __init__(self, target: str, timeout_s: float = 0.0,
                 poll_s: float = 0.05):
        self.lock_path = target + ".lock"
        self.timeout_s = timeout_s
        self.poll_s = poll_s
        self._fh: Optional[IO] = None

    def acquire(self) -> "FileLock":
        if fcntl is None:
            return self
        deadline = time.monotonic() + self.timeout_s
        fh = open(self.lock_path, "a+")
        while True:
            try:
                fcntl.flock(fh.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
                self._fh = fh
                return self
            except OSError as e:
                # only genuine contention polls/raises LockHeldError; a
                # filesystem that cannot flock (ENOLCK/EOPNOTSUPP on some
                # NFS/FUSE mounts) must surface its real error, not a
                # phantom concurrent writer
                if e.errno not in (errno.EWOULDBLOCK, errno.EAGAIN,
                                   errno.EACCES):
                    fh.close()
                    raise
                if time.monotonic() >= deadline:
                    fh.close()
                    raise LockHeldError(self.lock_path, self.timeout_s) from None
                time.sleep(self.poll_s)

    def release(self) -> None:
        if self._fh is not None:
            fcntl.flock(self._fh.fileno(), fcntl.LOCK_UN)
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "FileLock":
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


@contextlib.contextmanager
def atomic_write(path: str, mode: str = "w") -> Iterator[IO]:
    """Write via a same-directory temp file + ``os.replace`` — readers see
    either the old or the new complete file, never a torn one."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".tmp.")
    try:
        # mkstemp creates 0600; carry over the target's existing mode (or
        # umask-default for new files) so the rewrite doesn't silently
        # tighten permissions on a file other readers already use
        try:
            os.chmod(tmp, stat.S_IMODE(os.stat(path).st_mode))
        except FileNotFoundError:
            umask = os.umask(0)
            os.umask(umask)
            os.chmod(tmp, 0o666 & ~umask)
        with os.fdopen(fd, mode) as fh:
            yield fh
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise
