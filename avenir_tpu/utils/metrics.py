"""Validation metrics, arbitration, counters, and latency tracking.

Replaces the reference's validation-mode machinery: the binary confusion
matrix with ×100 integer accuracy/recall/precision published as Hadoop
counters (util/ConfusionMatrix.java:34-77, consumed at
bayesian/BayesianPredictor.java:170-180 and knn/NearestNeighbor.java:300-312),
the misclassification-cost arbitrator (util/CostBasedArbitrator.java:35-45),
and the Hadoop counter channel itself (here a plain named-counter object
returned alongside results).

:class:`LatencyTracker` + :func:`serving_stats` are the shared observability
schema of BOTH online paths — the scoring plane (``serving/batcher.py``) and
the RL serving loop (``pipeline/streaming.py``) — so their health endpoints
and benchmark artifacts report identically.
"""

from __future__ import annotations

import threading

from typing import Dict, List, Optional, Sequence

import numpy as np


def percentile_of(values, q: float) -> float:
    """THE percentile definition every surface uses — numpy's linear
    interpolation over the given samples (round 14): ``LatencyTracker``,
    ``StepTimer`` and the bench probes all route through here, so bench,
    profile and serving percentiles agree by construction instead of by
    three copies of the same formula drifting apart."""
    arr = np.asarray(values, np.float64)
    if arr.size == 0:
        return 0.0
    return float(np.percentile(arr, q))


def percentile_summary(samples_ms,
                       percentiles=(50.0, 95.0, 99.0)) -> Dict[str, float]:
    """The shared wall-time summary shape: ``count``, ``mean_ms``,
    ``p50_ms``/``p95_ms``/``p99_ms`` (configurable), ``max_ms`` — the one
    helper behind ``StepTimer.summary`` and any probe that reports
    percentile rows."""
    arr = np.asarray(list(samples_ms), np.float64)
    out: Dict[str, float] = {"count": int(arr.size)}
    if not arr.size:
        out["mean_ms"] = out["max_ms"] = 0.0
        for q in percentiles:
            out[f"p{q:g}_ms"] = 0.0
        return out
    out["mean_ms"] = float(arr.mean())
    for q in percentiles:
        out[f"p{q:g}_ms"] = percentile_of(arr, q)
    out["max_ms"] = float(arr.max())
    return out


class Counters:
    """Named counters — the in-process stand-in for Hadoop job counters.

    Increment is a read-modify-write, and one Counters may be shared across
    serving threads (frontend handlers, fleet workers aggregating into one
    report), so mutations take a lock — the Hadoop counter channel was
    task-concurrent too.
    """

    def __init__(self):
        self._groups: Dict[str, Dict[str, int]] = {}
        self._lock = threading.Lock()

    def increment(self, group: str, name: str, amount: int = 1) -> None:
        with self._lock:
            g = self._groups.setdefault(group, {})
            g[name] = g.get(name, 0) + amount

    def set(self, group: str, name: str, value: int) -> None:
        with self._lock:
            self._groups.setdefault(group, {})[name] = int(value)

    def get(self, group: str, name: str) -> int:
        with self._lock:
            return self._groups.get(group, {}).get(name, 0)

    def as_dict(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {g: dict(d) for g, d in self._groups.items()}

    def merge(self, other: "Counters") -> "Counters":
        """Adopt every counter from ``other`` (overwriting same-named ones)
        — the "latest snapshot wins" semantics for republishing one source's
        counters (e.g. a job adopting its batcher's final totals).  For
        aggregating MANY sources into one report use :meth:`merge_add`:
        overwrite-merge on same-named counters silently keeps only the last
        contributor's count."""
        for group, vals in other.as_dict().items():
            for name, value in vals.items():
                self.set(group, name, value)
        return self

    def merge_add(self, other: "Counters") -> "Counters":
        """SUM every counter from ``other`` into this one — the
        fleet/run-level aggregation semantics (Hadoop's counter merge):
        per-stage or per-worker Counters folded into one rollup keep every
        contributor's counts instead of last-writer-wins."""
        for group, vals in other.as_dict().items():
            for name, value in vals.items():
                self.increment(group, name, value)
        return self

    def __repr__(self) -> str:
        lines = []
        for g in sorted(self._groups):
            for n in sorted(self._groups[g]):
                lines.append(f"{g}::{n} = {self._groups[g][n]}")
        return "\n".join(lines)


class LatencyTracker:
    """Per-request latency percentiles over a bounded ring of recent samples.

    A ring (default 8192 samples) rather than an unbounded list: a serving
    loop alive for days must not grow host memory per request, and recent
    samples are what a health endpoint should describe.  Thread-safe
    (requests complete on dispatch/worker threads while a frontend thread
    reads the percentiles).
    """

    def __init__(self, capacity: int = 8192):
        self._buf = np.zeros(max(int(capacity), 1), np.float64)
        self._next = 0
        self._filled = 0
        self.count = 0                      # total samples ever recorded
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        with self._lock:
            self._buf[self._next] = seconds
            self._next = (self._next + 1) % len(self._buf)
            self._filled = min(self._filled + 1, len(self._buf))
            self.count += 1

    def percentile(self, q: float) -> float:
        """q-th percentile in seconds over the retained window (0.0 when
        no sample was recorded yet)."""
        with self._lock:
            if not self._filled:
                return 0.0
            return percentile_of(self._buf[:self._filled], q)

    @property
    def p50_ms(self) -> float:
        return self.percentile(50.0) * 1e3

    @property
    def p99_ms(self) -> float:
        return self.percentile(99.0) * 1e3

    def snapshot(self) -> Dict[str, float]:
        return {"p50_ms": round(self.p50_ms, 4),
                "p99_ms": round(self.p99_ms, 4),
                "latency_samples": self.count}


def serving_stats(counters: "Counters",
                  latency: Dict[str, LatencyTracker],
                  identity: Optional[Dict[str, str]] = None
                  ) -> Dict[str, dict]:
    """The one stats schema both online paths publish: per served model,
    the ``Serving.<name>`` counter group merged with its latency
    percentiles.  Counter names inside the group: ``requests``, ``batches``,
    ``shed``, ``timeouts``, ``errors``, ``recompiles`` and the batched-size
    histogram ``bucket.<n>`` (the RL loop, which dispatches one event at a
    time, reports everything under ``bucket.1``).

    Covers the UNION of the latency trackers and the ``Serving.<name>``
    counter groups: a model that has counters but no tracker yet (e.g.
    registered and shedding before its first scored request, or a fleet
    rollup that only carried counters) reports with zeroed latency instead
    of silently vanishing from the stats.

    ``identity`` (GraftFleet round 15 —
    ``telemetry.export.fleet_identity``: process index + replica suffix)
    merges into every row, so stats federated from N workers of one
    deployment never collide on identical model names."""
    groups = counters.as_dict()
    prefix = "Serving."
    names = set(latency) | {g[len(prefix):] for g in groups
                            if g.startswith(prefix)}
    out: Dict[str, dict] = {}
    for name in sorted(names):
        stats = dict(groups.get(f"Serving.{name}", {}))
        tracker = latency.get(name)
        stats.update(tracker.snapshot() if tracker is not None else
                     {"p50_ms": 0.0, "p99_ms": 0.0, "latency_samples": 0})
        if identity:
            stats.update(identity)
        out[name] = stats
    return out


class ConfusionMatrix:
    """Multi-class confusion counts with the reference's binary metrics.

    The reference's version is strictly binary (pos/neg class values); this
    one keeps full multi-class counts and exposes the binary metrics when a
    positive class is designated.
    """

    def __init__(self, class_values: Sequence[str], pos_class: Optional[str] = None):
        self.class_values = list(class_values)
        self.pos_class = pos_class if pos_class is not None else (self.class_values[0] if self.class_values else None)
        k = len(self.class_values)
        self.matrix = np.zeros((k, k), dtype=np.int64)   # [actual, predicted]

    def add(self, actual: int, predicted: int, count: int = 1) -> None:
        self.matrix[actual, predicted] += count

    def add_batch(self, actual: np.ndarray, predicted: np.ndarray) -> None:
        k = len(self.class_values)
        idx = actual.astype(np.int64) * k + predicted.astype(np.int64)
        self.matrix += np.bincount(idx, minlength=k * k).reshape(k, k)

    # -- binary metrics (×100 ints to mirror the reference's counter values) --
    def _binary(self):
        p = self.class_values.index(self.pos_class)
        tp = int(self.matrix[p, p])
        fn = int(self.matrix[p, :].sum() - tp)
        fp = int(self.matrix[:, p].sum() - tp)
        tn = int(self.matrix.sum() - tp - fn - fp)
        return tp, fp, tn, fn

    @property
    def accuracy(self) -> int:
        total = int(self.matrix.sum())
        correct = int(np.trace(self.matrix))
        return (100 * correct) // total if total else 0

    @property
    def recall(self) -> int:
        tp, _, _, fn = self._binary()
        return (100 * tp) // (tp + fn) if tp + fn else 0

    @property
    def precision(self) -> int:
        tp, fp, _, _ = self._binary()
        return (100 * tp) // (tp + fp) if tp + fp else 0

    def publish(self, counters: Counters, group: str = "Validation") -> None:
        counters.set(group, "accuracy", self.accuracy)
        counters.set(group, "recall", self.recall)
        counters.set(group, "precision", self.precision)
        correct = int(np.trace(self.matrix))
        counters.set(group, "correct", correct)
        counters.set(group, "incorrect", int(self.matrix.sum()) - correct)


class CostBasedArbitrator:
    """Expected-misclassification-cost argmin over class posteriors.

    Generalizes the reference's binary version (cost of a false-negative vs
    false-positive, util/CostBasedArbitrator.java:35-45) to a full cost
    matrix: pick argmin_k Σ_c P(c|x) · cost[c, k].
    """

    def __init__(self, class_values: Sequence[str], cost: np.ndarray):
        cost = np.asarray(cost, dtype=np.float64)
        k = len(class_values)
        if cost.shape == (k,):
            # reference-style per-class misclassification cost: cost[c] applies
            # when the true class c is predicted as anything else
            full = np.tile(cost[:, None], (1, k))
            np.fill_diagonal(full, 0.0)
            cost = full
        if cost.shape != (k, k):
            raise ValueError(f"cost must be [{k}] or [{k},{k}], got {cost.shape}")
        self.class_values = list(class_values)
        self.cost = cost

    def arbitrate(self, probs: np.ndarray) -> np.ndarray:
        """probs [N, C] → predicted class index [N] minimizing expected cost."""
        expected = probs @ self.cost                     # [N, K]
        return np.argmin(expected, axis=-1).astype(np.int32)
