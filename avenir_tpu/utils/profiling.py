"""Tracing/profiling hooks — the observability layer the reference lacks.

The reference's only observability is the Hadoop job UI plus custom counters
(SURVEY §5). Here: a :func:`trace` context manager around ``jax.profiler``
(viewable in TensorBoard/XProf), a :class:`StepTimer` for per-step
wall-times with percentile summaries (blocking on device results so times
are real), and ``debug.on``-gated logging matching the reference's per-job
debug flag.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Dict, List, Optional

import jax
import numpy as np


def device_sync(value):
    """Reliable device barrier: fetch one scalar PER SHARD of ``value``.

    ``jax.block_until_ready`` is a NO-OP on some PJRT transports (measured
    on the dev tunnel — BASELINE.md "Timing methodology"), so timing code
    must force a host read of the result instead. One scalar is read from
    every addressable shard — fetching only element 0 would wait for the
    device holding shard 0 while the rest of a sharded result is still
    computing (and a global multi-host array is not eagerly indexable at
    all). Works on any pytree of arrays; returns ``value`` unchanged."""
    leaves = [x for x in jax.tree_util.tree_leaves(value)
              if hasattr(x, "dtype") and getattr(x, "size", 0)]
    for x in leaves:
        shards = getattr(x, "addressable_shards", None)
        if shards:
            for sh in shards:
                d = sh.data
                if getattr(d, "size", 0):
                    # this helper IS the blessed sync point the GL005 rule
                    # steers hot loops toward — one scalar per shard, by
                    # design            # graftlint: disable=GL005
                    np.asarray(jax.device_get(d.ravel()[0] if d.ndim else d))
        else:
            # graftlint: disable=GL005 — same: the sync helper itself
            np.asarray(jax.device_get(x.ravel()[0] if x.ndim else x))
    return value


@contextlib.contextmanager
def trace(log_dir: Optional[str]):
    """Capture an XLA/device trace under ``log_dir`` (no-op when None)."""
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock step timing with device synchronization.

    Register the step's device output via :meth:`block_on` — JAX dispatch is
    async, so without the block the recorded time would measure only dispatch
    latency, not the step::

        timer = StepTimer()
        with timer.step("fit") as t:
            out = t.block_on(step_fn(batch))   # synced at step exit
        timer.summary()["fit"]["p50_ms"]
    """

    def __init__(self):
        self.samples: Dict[str, List[float]] = {}
        self._pending = None

    @contextlib.contextmanager
    def step(self, name: str):
        start = time.perf_counter()
        self._pending = None
        yield self
        if self._pending is not None:
            device_sync(self._pending)     # a host fetch, not
            # block_until_ready: the latter is a no-op on some transports
            self._pending = None
        self.samples.setdefault(name, []).append(
            (time.perf_counter() - start) * 1e3)

    def block_on(self, value):
        """Register the step's device output; synced at step exit."""
        self._pending = value
        return value

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-step percentile rows through the ONE shared helper
        (``utils.metrics.percentile_summary``, round 14) — StepTimer,
        bench probes and serving latency now agree on the percentile
        definition by construction, and StepTimer gains p99."""
        from avenir_tpu.utils.metrics import percentile_summary

        return {name: percentile_summary(ms)
                for name, ms in self.samples.items()}


def get_logger(name: str = "avenir_tpu", debug_on: bool = False) -> logging.Logger:
    """Per-job logger honoring the reference's ``debug.on`` flag
    (e.g. CramerCorrelation.java:106-109)."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s %(message)s"))
        logger.addHandler(handler)
    logger.setLevel(logging.DEBUG if debug_on else logging.INFO)
    return logger
