"""Tracing/profiling hooks — the observability layer the reference lacks.

The reference's only observability is the Hadoop job UI plus custom counters
(SURVEY §5). Here: a :func:`trace` context manager around ``jax.profiler``
(viewable in TensorBoard/XProf), a :class:`StepTimer` for per-step
wall-times with percentile summaries (blocking on device results so times
are real), and ``debug.on``-gated logging matching the reference's per-job
debug flag.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Dict, List, Optional

import jax
import numpy as np


@contextlib.contextmanager
def trace(log_dir: Optional[str]):
    """Capture an XLA/device trace under ``log_dir`` (no-op when None)."""
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock step timing with device synchronization.

    Register the step's device output via :meth:`block_on` — JAX dispatch is
    async, so without the block the recorded time would measure only dispatch
    latency, not the step::

        timer = StepTimer()
        with timer.step("fit") as t:
            out = t.block_on(step_fn(batch))   # synced at step exit
        timer.summary()["fit"]["p50_ms"]
    """

    def __init__(self):
        self.samples: Dict[str, List[float]] = {}
        self._pending = None

    @contextlib.contextmanager
    def step(self, name: str):
        start = time.perf_counter()
        self._pending = None
        yield self
        if self._pending is not None:
            jax.block_until_ready(self._pending)
            self._pending = None
        self.samples.setdefault(name, []).append(
            (time.perf_counter() - start) * 1e3)

    def block_on(self, value):
        """Register the step's device output; synced at step exit."""
        self._pending = value
        return value

    def summary(self) -> Dict[str, Dict[str, float]]:
        out = {}
        for name, ms in self.samples.items():
            arr = np.asarray(ms)
            out[name] = {
                "count": int(arr.size),
                "mean_ms": float(arr.mean()),
                "p50_ms": float(np.percentile(arr, 50)),
                "p95_ms": float(np.percentile(arr, 95)),
                "max_ms": float(arr.max()),
            }
        return out


def get_logger(name: str = "avenir_tpu", debug_on: bool = False) -> logging.Logger:
    """Per-job logger honoring the reference's ``debug.on`` flag
    (e.g. CramerCorrelation.java:106-109)."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s %(message)s"))
        logger.addHandler(handler)
    logger.setLevel(logging.DEBUG if debug_on else logging.INFO)
    return logger
