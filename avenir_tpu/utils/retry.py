"""Failure detection and elastic retry — the task-retry layer.

The reference delegates failure handling wholly to its cluster runtimes:
Hadoop re-runs a failed map/reduce task on its input split up to
``mapred.map.max.attempts`` times (resource/knn.properties:5-6 sets 2), and
Storm optionally replays failed messages (``replay.failed.message`` —
resource/boost_lead_generation_tutorial.txt:27; the spout's failed-message
hook is stubbed at RedisSpout.java:103-106). There is no fault injection
anywhere in the reference (SURVEY.md §5).

Here the equivalent unit of work is a *chunk step* — one encoded chunk
through a jitted aggregation kernel — so task retry becomes chunk retry:
chunks are materialized values and every chunk step is a pure function of
its chunk, so re-running a failed step is idempotent by construction (the
framework's accumulate-per-chunk-then-merge discipline; contrast the
reference's only unsafe spot, the single-reducer LR coefficient-file
rewrite, SURVEY.md §5 "race detection").

:class:`FaultInjector` is the fault-injection capability the reference
lacks: deterministic fault schedules wrap any callable so tests can assert
fault-free results survive injected crashes (tests/test_hardening.py).
"""

from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass, field
from typing import (Callable, Dict, Iterable, List, Optional, Sequence,
                    Tuple, TypeVar)

from avenir_tpu.utils.metrics import Counters

log = logging.getLogger(__name__)

T = TypeVar("T")
R = TypeVar("R")

# counter names (the observability channel, as Hadoop publishes task retries)
ATTEMPTS = ("Task", "attempts")
FAILURES = ("Task", "failed.attempts")
EXHAUSTED = ("Task", "exhausted")


@dataclass(frozen=True)
class RetryPolicy:
    """Chunk/task retry policy.

    ``max_attempts`` defaults to 2, the reference deployment's
    ``mapred.map.max.attempts`` value. ``backoff_s`` is the sleep before
    each re-attempt (0 for in-process compute retries; nonzero for I/O).
    ``retryable`` filters which exception types are retried — anything else
    propagates immediately (a schema error will not pass on attempt 2).

    ``jitter`` (round 16, default on — ``retry.jitter``): decorrelated
    jitter on the backoff, so N replicas that all failed on one shared
    resource (a checkpoint store, a queue endpoint) re-arrive spread out
    instead of thundering-herding it in lockstep.  Each sleep draws
    uniformly from ``[backoff_s, 3·previous_sleep]``, capped at
    ``backoff_cap_s`` (default 16× base) — the bounds
    :meth:`next_backoff` pins in tests.  Off, the fixed-``backoff_s``
    schedule is exactly the pre-round-16 behavior.
    """

    max_attempts: int = 2
    backoff_s: float = 0.0
    retryable: Tuple[type, ...] = (Exception,)
    non_retryable: Tuple[type, ...] = ()
    jitter: bool = True
    backoff_cap_s: float = 0.0           # 0 = 16 × backoff_s
    # injectable uniform(a, b) draw — tests pin the distribution bounds
    # through it; random.uniform in production
    uniform: Callable[[float, float], float] = random.uniform

    @property
    def cap_s(self) -> float:
        # never below base: an inverted cap (cap < base) would silently
        # break the documented [base, cap] floor
        if self.backoff_cap_s > 0:
            return max(self.backoff_cap_s, self.backoff_s)
        return 16.0 * self.backoff_s

    def next_backoff(self, prev_sleep_s: float) -> float:
        """The sleep before the next attempt given the previous sleep
        (pass 0 before the first retry).  With jitter on:
        ``min(cap, uniform(base, 3·max(prev, base)))`` — the AWS
        "decorrelated jitter" recipe, bounded to ``[base, cap]``."""
        if self.backoff_s <= 0:
            return 0.0
        if not self.jitter:
            return self.backoff_s
        upper = 3.0 * max(prev_sleep_s, self.backoff_s)
        return min(self.cap_s, self.uniform(self.backoff_s, upper))

    @classmethod
    def from_conf(cls, conf) -> "RetryPolicy":
        """Read the reference's property name (``mapred.map.max.attempts``)
        with the framework name ``task.max.attempts`` as an alias.

        Deterministic configuration errors (:class:`ConfigError` — e.g. a
        schema too incomplete for streaming encode) are non-retryable: the
        same attempt would fail the same way, and wrapping the clear error
        in a TaskExhaustedError would bury it."""
        from avenir_tpu.core.config import ConfigError

        attempts = int(conf.get("task.max.attempts",
                                conf.get("mapred.map.max.attempts", 2)))
        backoff = float(conf.get("task.retry.backoff.sec", 0.0))
        return cls(max_attempts=max(attempts, 1), backoff_s=backoff,
                   non_retryable=(ConfigError,),
                   jitter=conf.get_bool("retry.jitter", True),
                   backoff_cap_s=conf.get_float(
                       "task.retry.backoff.cap.sec", 0.0))


class TaskExhaustedError(RuntimeError):
    """A task failed on every attempt; carries the last underlying error."""

    def __init__(self, task: str, attempts: int, last: BaseException):
        super().__init__(
            f"task {task!r} failed after {attempts} attempts: {last!r}")
        self.task = task
        self.attempts = attempts
        self.last = last


def run_with_retry(fn: Callable[[], R], *, policy: RetryPolicy,
                   counters: Optional[Counters] = None,
                   task: str = "task") -> R:
    """Run ``fn`` under the retry policy; raises TaskExhaustedError after the
    final failed attempt. ``fn`` must be safe to re-run (pure, or idempotent
    against external state)."""
    last: Optional[BaseException] = None
    sleep_s = 0.0
    for attempt in range(1, policy.max_attempts + 1):
        if counters is not None:
            counters.increment(*ATTEMPTS)
        try:
            return fn()
        except policy.retryable as e:          # noqa: PERF203 — retry loop
            if isinstance(e, policy.non_retryable):
                raise                          # deterministic: fail fast
            last = e
            if counters is not None:
                counters.increment(*FAILURES)
            log.warning("task %s attempt %d/%d failed: %r",
                        task, attempt, policy.max_attempts, e)
            if attempt < policy.max_attempts and policy.backoff_s > 0:
                sleep_s = policy.next_backoff(sleep_s)
                time.sleep(sleep_s)
    if counters is not None:
        counters.increment(*EXHAUSTED)
    assert last is not None
    raise TaskExhaustedError(task, policy.max_attempts, last)


def process_chunks(chunks: Iterable[T], step: Callable[[T], R], *,
                   policy: Optional[RetryPolicy] = None,
                   counters: Optional[Counters] = None,
                   task: str = "chunk") -> List[R]:
    """Run ``step`` over each chunk with per-chunk retry — the MR task-retry
    analog (a failed map task re-runs on its split; a failed chunk step
    re-runs on its chunk). Returns the per-chunk results in order."""
    policy = policy or RetryPolicy()
    out: List[R] = []
    for i, chunk in enumerate(chunks):
        out.append(run_with_retry(
            lambda c=chunk: step(c), policy=policy, counters=counters,
            task=f"{task}[{i}]"))
    return out


class InjectedFault(RuntimeError):
    """Raised by FaultInjector on scheduled invocations."""


class FaultInjector:
    """Deterministic fault injection for tests and chaos drills.

    Wraps a callable; raises :class:`InjectedFault` on the 1-based
    invocation numbers in ``fail_on`` — the deterministic analog of a flaky
    worker. A single scheduled number models a transient fault (the retry
    then succeeds); consecutive numbers model a persistent fault that
    defeats an N-attempt policy.
    """

    def __init__(self, fn: Callable[..., R], fail_on: Sequence[int],
                 exc: Callable[[], BaseException] = lambda: InjectedFault("injected")):
        self._fn = fn
        self._fail_on = frozenset(fail_on)
        self._exc = exc
        self.calls = 0
        self.faults_fired = 0

    def __call__(self, *args, **kwargs):
        self.calls += 1
        if self.calls in self._fail_on:
            self.faults_fired += 1
            raise self._exc()
        return self._fn(*args, **kwargs)


class FaultPlan:
    """Conf-driven deterministic fault schedule — the ``fault.*`` family
    (round 16): :class:`FaultInjector` generalized from wrap-one-callable
    to named SITES any seam can consult, so a preemption drill arms
    crashes from configuration alone (no test-only wiring).

    - ``fault.fold.crash.after`` — raise on the N-th fold boundary
      (``stream/windows.py::WindowedScan.close_pane``, before the pane's
      state reaches the ring: a mid-fold kill, the preemption shape);
    - ``fault.checkpoint.save.crash.after`` — raise on the N-th snapshot
      save, BEFORE anything is written (the save must stay atomic);
    - ``fault.checkpoint.restore.crash.after`` — raise on the N-th
      restore attempt (a worker preempted while coming back up);
    - ``fault.serve.dispatch.crash.after`` — raise on the N-th serving
      batch dispatch, BEFORE any request of the batch scores (FleetServe
      round 17: the batcher treats it as replica-fatal — the whole
      replica dies mid-batch and its in-flight requests fail over);
    - ``fault.serve.heartbeat.crash.after`` — wedge the serving
      dispatcher on its N-th loop wake: the thread exits WITHOUT
      finishing pending work, so the replica's heartbeat goes stale and
      the pool's deadline detection is what has to catch it;
    - ``fault.tenant.flood.after`` — the GraftPool noisy-tenant drill
      (round 18): fire on a tenant workload's N-th pacing boundary.  The
      workload driver (``benchmarks/tenancy_soak.py``) treats the raise
      as "go noisy": it stops pacing and floods the arbiter, which must
      throttle then shed THAT tenant while the others' SLOs stay green —
      misbehavior armed from configuration alone, like every other site.

    Each firing journals a golden-schema'd ``fault.injected`` event
    (site, 1-based hit number) so the run's trace explains the drill.
    Counts are per-plan-instance; build one plan per run seam — a
    replica POOL shares one plan across its replicas, so "kill the N-th
    dispatch" means the N-th dispatch pool-wide (``from_conf`` returns
    None when no ``fault.*`` key is armed — the zero-cost default)."""

    SITES = ("fold", "checkpoint.save", "checkpoint.restore",
             "serve.dispatch", "serve.heartbeat", "tenant.flood")

    def __init__(self, schedule: Dict[str, int]):
        unknown = set(schedule) - set(self.SITES)
        if unknown:
            raise ValueError(f"unknown fault sites {sorted(unknown)}; "
                             f"known: {self.SITES}")
        self.schedule = {site: int(n) for site, n in schedule.items()
                         if int(n) > 0}
        self.hits = {site: 0 for site in self.SITES}
        self.faults_fired = 0

    @classmethod
    def from_conf(cls, conf) -> Optional["FaultPlan"]:
        # literal key reads, one per site: the GL004 registry scans
        # conf.get* literals, so the fault.* family stays documented
        sched = {
            "fold": conf.get_int("fault.fold.crash.after", 0) or 0,
            "checkpoint.save":
                conf.get_int("fault.checkpoint.save.crash.after", 0) or 0,
            "checkpoint.restore":
                conf.get_int("fault.checkpoint.restore.crash.after", 0) or 0,
            "serve.dispatch":
                conf.get_int("fault.serve.dispatch.crash.after", 0) or 0,
            "serve.heartbeat":
                conf.get_int("fault.serve.heartbeat.crash.after", 0) or 0,
            "tenant.flood":
                conf.get_int("fault.tenant.flood.after", 0) or 0,
        }
        plan = cls(sched)
        return plan if plan.schedule else None

    def hit(self, site: str) -> None:
        """Count one pass through ``site``; raise :class:`InjectedFault`
        (journaled first) when the schedule says this is the one."""
        if site not in self.hits:
            raise ValueError(f"unknown fault site {site!r}; "
                             f"known: {self.SITES}")
        self.hits[site] += 1
        if self.hits[site] == self.schedule.get(site, 0):
            self.faults_fired += 1
            from avenir_tpu.telemetry import spans as tel

            tel.tracer().event("fault.injected", site=site,
                               hit=self.hits[site])
            raise InjectedFault(
                f"fault.{site}.crash.after={self.hits[site]}: injected "
                f"crash at {site} boundary {self.hits[site]}")


@dataclass
class HeartbeatMonitor:
    """Failure *detection* for long-running host loops: callers beat on
    progress; :meth:`stalled` reports whether the loop has gone silent for
    longer than ``timeout_s`` (the JobTracker's task-timeout analog,
    decoupled from any cluster runtime). Pure bookkeeping — the policy
    (restart, alert) belongs to the supervisor that polls it."""

    timeout_s: float = 600.0
    clock: Callable[[], float] = time.monotonic
    last_beat: float = field(default=0.0)
    beats: int = 0

    def __post_init__(self):
        self.last_beat = self.clock()

    def beat(self) -> None:
        self.beats += 1
        self.last_beat = self.clock()

    def stalled(self) -> bool:
        return (self.clock() - self.last_beat) > self.timeout_s
