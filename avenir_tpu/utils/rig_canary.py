"""Rig-state canaries — tiny bare-XLA probes that separate "the rig is slow
right now" from "a kernel regressed".

Motivation (round 5): the driver's BENCH_r04 captured a kNN median 45%
below the published band with the kernel code unchanged since round 3 —
the fourth consecutive round where a published kNN number and an
arm's-length capture disagreed.  Absolute rates on the dev rig swing ±20%
on ~30-minute scales (BASELINE.md "Timing methodology") and the tunnel
transport adds its own modes, so every benchmark artifact now carries two
bare-XLA reference timings measured in the same process, moments before
the headline measurement:

- ``matmul_4096_bf16_ms`` — a chained 4096x4096x4096 bf16 matmul
  (137 GFLOP/call).  Pure MXU + HBM; no custom kernels, no framework
  code — if this is slow, the rig is slow.  The healthy band is
  established empirically by the artifacts that carry the field (round-2
  notes measured ~6.5 ms through the tunnel).
- ``knn_dot_ms`` (kNN artifacts only) — the bare distance dot at the kNN
  serving shape ([batch, 128] x [1M, 128]^T bf16), the measured lower
  bound the fused search kernel is judged against
  (docs/architecture.md "ceilings").  If headline QPS drops while this
  stays put, the kernel (or its memory layout) regressed; if both drop by
  the same factor, the rig did.

Timing uses the chained-dispatch discipline: ``jax.block_until_ready`` is
a no-op on the tunnel transport, so each call feeds a reduced scalar of
the previous result into its operand and one host fetch at the end
barriers the whole chain.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp


def _chained_ms(step, operand, reps: int) -> float:
    """Per-call ms of ``step(operand + bias)`` over a dependency chain.

    ``step`` must return an array; a scalar of call i's result biases call
    i+1's operand so the final host fetch waits for every call."""
    bias = jnp.zeros((), operand.dtype)
    out = step(operand + bias)                  # compile + warm
    np.asarray(jax.device_get(out.ravel()[0]))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = step(operand + bias)
        bias = (out.ravel()[0] * 0).astype(operand.dtype)
    np.asarray(jax.device_get(out.ravel()[0]))
    return (time.perf_counter() - t0) * 1e3 / reps


def matmul_canary_ms(dim: int = 4096, reps: int = 4) -> float:
    """Chained ``dim³`` bf16 matmul, per-call ms (2·dim³ FLOPs/call)."""
    a = jnp.asarray(np.random.default_rng(0).normal(
        size=(dim, dim)).astype(np.float32)).astype(jnp.bfloat16)
    step = jax.jit(lambda x: jnp.dot(x, a, preferred_element_type=jnp.float32)
                   .astype(jnp.bfloat16))
    return _chained_ms(step, a, reps)


def knn_dot_canary_ms(batch: int = 16384, n_refs: int = 1_000_000,
                      width: int = 128, reps: int = 3,
                      refs=None) -> float:
    """Chained bare distance dot at the kNN serving shape, per-call ms.

    ``refs`` may pass an existing device-resident [n_refs, width] bf16
    operand (e.g. the actual packed reference matrix) so the canary times
    the dot against the very buffer the kernel reads; by default it
    uploads a fresh one.
    """
    rng = np.random.default_rng(0)
    if refs is None:
        refs = jnp.asarray(rng.normal(size=(n_refs, width))
                           .astype(np.float32)).astype(jnp.bfloat16)
    q = jnp.asarray(rng.normal(size=(batch, width))
                    .astype(np.float32)).astype(jnp.bfloat16)
    # scan over reference tiles with a running max: the monolithic
    # [batch, n_refs] f32 dot output would be ~65 GB at the serving shape
    # (XLA:TPU does not fuse a reduce into a matmul) — one [batch, TILE]
    # tile lives at a time (~1 GB), matching how the real kernel streams
    tile = 16384
    n = refs.shape[0] - refs.shape[0] % tile
    r_tiles = refs[:n].reshape(-1, tile, refs.shape[1])

    def step_fn(x):
        def body(best, r):
            d = jnp.dot(x, r.T, preferred_element_type=jnp.float32)
            return jnp.maximum(best, d.max(axis=1)), None
        init = jnp.full((x.shape[0],), -jnp.inf, jnp.float32)
        best, _ = jax.lax.scan(body, init, r_tiles)
        return best

    step = jax.jit(step_fn)
    return _chained_ms(step, q, reps)
