"""Rig-state canaries — tiny bare-XLA probes that separate "the rig is slow
right now" from "a kernel regressed".

Motivation (round 5): the driver's BENCH_r04 captured a kNN median 45%
below the published band with the kernel code unchanged since round 3 —
the fourth consecutive round where a published kNN number and an
arm's-length capture disagreed.  Absolute rates on the dev rig swing ±20%
on ~30-minute scales (BASELINE.md "Timing methodology") and the tunnel
transport adds its own modes, so every benchmark artifact now carries two
bare-XLA reference timings measured in the same process, moments before
the headline measurement:

- ``matmul_4096_bf16_ms`` — a chained 4096x4096x4096 bf16 matmul
  (137 GFLOP/call).  Pure MXU + HBM; no custom kernels, no framework
  code — if this is slow, the rig is slow.
- ``knn_dot_ms`` (kNN artifacts only) — the bare distance dot at the kNN
  serving shape ([batch, 128] x [1M, 128]^T bf16 with a running row max),
  the measured lower bound the fused search kernel is judged against
  (docs/architecture.md "ceilings").  If headline QPS drops while this
  stays put, the kernel (or its memory layout) regressed; if both drop by
  the same factor, the rig did.

Timing methodology (this rig forces all three):

1. ``jax.block_until_ready`` is a no-op on the tunnel transport — only a
   host fetch is a barrier.
2. A synced fetch costs ~100 ms RTT, so the probe chains N dispatches and
   fetches once.
3. Each probe step is ONE jitted call returning a 0-d carry (the scalar
   chains into the next call's operand), because per-op eager dispatch
   overhead through the tunnel is large and variable — the first version
   of this module chained eager ``ravel()[0]`` extractions and measured
   167 ms for the 4096³ matmul while the fused kNN kernel simultaneously
   ran at full speed (round-5 probe log).
4. The constant overhead (final fetch + warmup jitter) is removed by a
   two-point slope: time chains of ``reps_lo`` and ``reps_hi`` calls and
   report ``(t_hi - t_lo) / (reps_hi - reps_lo)``.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp


def _slope_ms(step_scalar, operand, reps_lo: int = 2, reps_hi: int = 10) -> float:
    """Per-call ms of ``step_scalar(operand, carry) -> 0-d carry`` via the
    two-point chained-dispatch slope (see module doc)."""
    def run(n: int) -> float:
        carry = jnp.zeros((), jnp.float32)
        t0 = time.perf_counter()
        for _ in range(n):
            carry = step_scalar(operand, carry)
        np.asarray(jax.device_get(carry))
        return time.perf_counter() - t0

    run(2)                                   # compile
    run(reps_hi)                             # full-length warm: the first
    # post-startup chain runs with lazy transport/allocator init still in
    # flight (a process's first canary measured a 0.0 slope once)
    # min-of-2 per point: a single transient stall in either chain can
    # collapse (or explode) the slope — the embedded round-5 bench run
    # recorded a 1.14 ms knn-dot "bound" (physically impossible for the
    # ~30 ms of MXU work) from exactly that; minima resist one-off stalls
    t_lo = min(run(reps_lo) for _ in range(2))
    t_hi = min(run(reps_hi) for _ in range(2))
    return max((t_hi - t_lo) * 1e3 / (reps_hi - reps_lo), 0.0)


def matmul_canary_ms(dim: int = 4096, reps: int = 32) -> float:
    """Chained ``dim³`` bf16 matmul, per-call ms (2·dim³ FLOPs/call).

    ``reps`` sized so the chain differential (~reps · 5 ms) clearly
    exceeds the tunnel's per-fetch RTT variance — at 8 reps the ~40 ms
    signal drowned in RTT noise inside long-lived processes (embedded
    artifacts read 0.0/0.22 ms for a ~5 ms matmul).

    INTERPRETATION: healthy readings are themselves noisy — fresh
    processes measure ~4–6 ms, long-lived ones as low as ~0.1–1.5 ms
    (the tunnel pipelines deeply enough to hide parts of a short chain
    behind the fetch) — so treat any reading ≲ 7 ms as "healthy".  The
    signal this canary exists for is the CONTENDED regime, which reads
    10–100× higher (measured 167–192 ms under host-CPU load) and is
    unmistakable.  The kNN dot canary (~250 ms of work per chain) sits
    well above the noise and is the steadier of the two."""
    a = jnp.asarray(np.random.default_rng(0).normal(
        size=(dim, dim)).astype(np.float32)).astype(jnp.bfloat16)

    @jax.jit
    def step(x, carry):
        out = jnp.dot(x + carry.astype(jnp.bfloat16), a,
                      preferred_element_type=jnp.float32)
        # data-dependent 0-d carry, scaled so the chained perturbation is
        # far below bf16 resolution (never constant-foldable, never drifts)
        return out[0, 0] * jnp.float32(1e-30)

    return _slope_ms(step, a, reps_lo=2, reps_hi=2 + reps)


def knn_dot_canary_ms(batch: int = 16384, n_refs: int = 1_000_000,
                      width: int = 128, reps: int = 8,
                      refs=None) -> float:
    """Chained bare distance dot at the kNN serving shape, per-call ms.

    ``refs`` may pass an existing device-resident [n_refs, width] bf16
    operand (e.g. the actual packed reference matrix) so the canary times
    the dot against the very buffer the kernel reads; by default it
    uploads a fresh one.  The dot streams reference tiles under a
    ``lax.scan`` with a running row max — the monolithic [batch, n_refs]
    f32 output would be ~65 GB at the serving shape (XLA:TPU does not
    fuse a reduce into a matmul).
    """
    rng = np.random.default_rng(0)
    if refs is None:
        refs = jnp.asarray(rng.normal(size=(n_refs, width))
                           .astype(np.float32)).astype(jnp.bfloat16)
    q = jnp.asarray(rng.normal(size=(batch, width))
                    .astype(np.float32)).astype(jnp.bfloat16)
    tile = 16384
    n = refs.shape[0] - refs.shape[0] % tile
    r_tiles = refs[:n].reshape(-1, tile, refs.shape[1])

    @jax.jit
    def step(x, carry):
        xq = x + carry.astype(x.dtype)

        def body(best, r):
            d = jnp.dot(xq, r.T, preferred_element_type=jnp.float32)
            return jnp.maximum(best, d.max(axis=1)), None

        init = jnp.full((x.shape[0],), -jnp.inf, jnp.float32)
        best, _ = jax.lax.scan(body, init, r_tiles)
        return best[0] * jnp.float32(1e-30)

    return _slope_ms(step, q, reps_lo=1, reps_hi=1 + reps)
