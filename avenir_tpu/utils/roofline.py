"""Roofline accounting for benchmarks: detected-chip peaks + achieved rates.

Every benchmark JSON line carries achieved FLOP/s (compute-bound kernels)
and/or bytes/s (bandwidth-bound kernels) against the detected chip's peak, so
a throughput number can be judged against the hardware ceiling instead of in
a vacuum (the reference publishes no perf numbers at all — BASELINE.md).

Peaks are the published per-chip specs keyed by ``device_kind``; unknown
chips fall back to an empirical probe (a large chained bf16 matmul / HBM
reduction measured on the spot) so MFU is never silently wrong on new
hardware.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

# Published per-chip peaks: bf16 FLOP/s, int8 OP/s, and HBM bytes/s.
# v5e: 197 TFLOP/s bf16 / 394 TOPS int8, 819 GB/s HBM.
_PEAKS: Dict[str, Dict[str, float]] = {
    "TPU v5 lite": {"bf16_flops": 197e12, "int8_ops": 394e12,
                    "hbm_bytes": 819e9},
    "TPU v5e": {"bf16_flops": 197e12, "int8_ops": 394e12,
                "hbm_bytes": 819e9},
    "TPU v5p": {"bf16_flops": 459e12, "int8_ops": 918e12,
                "hbm_bytes": 2765e9},
    "TPU v5": {"bf16_flops": 459e12, "int8_ops": 918e12,
               "hbm_bytes": 2765e9},                             # v5p
    "TPU v4": {"bf16_flops": 275e12, "int8_ops": 275e12,
               "hbm_bytes": 1228e9},
    "TPU v6 lite": {"bf16_flops": 918e12, "int8_ops": 1836e12,
                    "hbm_bytes": 1640e9},                        # v6e
    "TPU v6e": {"bf16_flops": 918e12, "int8_ops": 1836e12,
                "hbm_bytes": 1640e9},
}


def _lookup_peaks(kind: str) -> Optional[Dict[str, float]]:
    """Exact, then normalized-substring match: device_kind strings drift
    across PJRT transports ("TPU v5 lite" vs "TPU v5e" vs "tpu v5 lite"),
    and a silent miss used to drop hbm_pct from bandwidth-bound benchmark
    lines (round-2 advisory)."""
    if kind in _PEAKS:
        return _PEAKS[kind]
    norm = kind.strip().lower()
    # longest key first so "TPU v5 lite" wins over "TPU v5"; one-directional
    # on purpose — matching a short/absent device_kind ("tpu") against table
    # keys would silently assign some other chip's peaks where the
    # empirical-probe fallback (with its warning) is the correct behavior
    for key in sorted(_PEAKS, key=len, reverse=True):
        if key.lower() in norm:
            return _PEAKS[key]
    return None


def chip_peaks(probe_fallback: bool = True) -> Dict[str, float]:
    """{"device_kind", "bf16_flops", "int8_ops", "hbm_bytes"} for the
    attached chip.

    CPU backends (tests) report measured-nothing peaks of 0 → callers skip
    MFU fields rather than print garbage."""
    import jax

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform)
    peaks = _lookup_peaks(kind)
    is_tpu = dev.platform == "tpu" or "tpu" in str(kind).lower()
    if peaks is None and is_tpu and probe_fallback:
        import logging
        logging.getLogger("avenir_tpu").warning(
            "unknown TPU device_kind %r: falling back to the empirical "
            "matmul probe (hbm_bytes unknown -> bandwidth roofline fields "
            "will be absent)", kind)
        peaks = {"bf16_flops": probe_matmul_flops(), "int8_ops": 0.0,
                 "hbm_bytes": 0.0}
    if peaks is None:
        peaks = {"bf16_flops": 0.0, "int8_ops": 0.0, "hbm_bytes": 0.0}
    return {"device_kind": kind, "int8_ops": 0.0, **peaks}


def probe_matmul_flops(dim: int = 4096, iters: int = 30) -> float:
    """Empirical bf16 matmul FLOP/s: chained square matmuls inside one
    dependency chain, one final host fetch (per-dispatch and sync round-trip
    costs amortize across the chain — on tunnel rigs a single synchronized
    call is ~100 ms of pure round trip)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    a = jnp.asarray(np.random.default_rng(0).random((dim, dim)),
                    jnp.bfloat16)
    f = jax.jit(lambda x: jax.lax.dot_general(
        x, x, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(jnp.bfloat16))
    x = f(a)
    float(x[0, 0].astype(jnp.float32))          # warm + sync
    best = float("inf")
    for _ in range(2):
        x = a
        t0 = time.perf_counter()
        for _ in range(iters):
            x = f(x)
        float(x[0, 0].astype(jnp.float32))
        best = min(best, (time.perf_counter() - t0) / iters)
    return 2.0 * dim * dim * dim / best


def mfu_fields(flops: Optional[float] = None, dt: Optional[float] = None,
               bytes_moved: Optional[float] = None,
               peaks: Optional[Dict[str, float]] = None,
               int8_ops: Optional[float] = None) -> Dict[str, float]:
    """Fields to merge into a benchmark JSON line: achieved FLOP/s + MFU,
    achieved int8 OP/s + fraction of int8-MXU peak, and/or achieved
    bytes/s + fraction of HBM peak, for work done in ``dt`` seconds."""
    out: Dict[str, float] = {}
    p = peaks or chip_peaks()
    out["device_kind"] = p["device_kind"]
    if flops and dt:
        out["achieved_tflops"] = round(flops / dt / 1e12, 2)
        if p["bf16_flops"]:
            out["mfu_pct"] = round(100.0 * flops / dt / p["bf16_flops"], 2)
    if int8_ops and dt:
        out["achieved_int8_tops"] = round(int8_ops / dt / 1e12, 2)
        if p.get("int8_ops"):
            out["int8_mxu_pct"] = round(
                100.0 * int8_ops / dt / p["int8_ops"], 2)
    if bytes_moved and dt:
        out["achieved_gbps"] = round(bytes_moved / dt / 1e9, 2)
        if p["hbm_bytes"]:
            out["hbm_pct"] = round(
                100.0 * bytes_moved / dt / p["hbm_bytes"], 2)
    return out
