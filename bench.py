#!/usr/bin/env python
"""Benchmark: Naive-Bayes + mutual-information pipeline throughput on TPU.

The driver-defined primary metric (BASELINE.json): rows/sec/chip on the
NaiveBayes+MI aggregation pipeline — the rebuild of the reference's
hospital-readmission north-star workload (BayesianDistribution +
MutualInformation MR jobs). Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "rows/sec/chip", "vs_baseline": N}

``vs_baseline`` is the speedup over a single-core numpy implementation of the
same counts (the stand-in for the reference's per-record JVM mapper loop,
measured on a subsample and scaled), since the reference publishes no numbers
(BASELINE.md).
"""

import json
import time

import numpy as np

import jax
import jax.numpy as jnp

from avenir_tpu.ops import agg


def make_data(n_rows: int, n_feat: int, n_bins: int, n_classes: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    codes = rng.integers(0, n_bins, size=(n_rows, n_feat), dtype=np.int32)
    labels = rng.integers(0, n_classes, size=n_rows, dtype=np.int32)
    return codes, labels


def numpy_reference_rows_per_sec(codes, labels, n_classes, n_bins):
    """Single-core numpy equivalent of the NB+MI count pass (per-record cost model
    of the reference's mapper+reducer). Computes the SAME work as the TPU
    pipeline (all feature pairs) so vs_baseline compares like for like."""
    n, f = codes.shape
    pairs = [(i, j) for i in range(f) for j in range(i + 1, f)]
    t0 = time.perf_counter()
    # NB: class-conditional counts
    for fi in range(f):
        np.add.at(np.zeros((n_bins, n_classes)), (codes[:, fi], labels), 1)
    # MI: pairwise joint counts
    for i, j in pairs:
        np.add.at(np.zeros((n_bins, n_bins)), (codes[:, i], codes[:, j]), 1)
    dt = time.perf_counter() - t0
    return n / dt


def main():
    n_classes, n_bins, n_feat = 2, 12, 11      # hosp_readmit-shaped workload
    # 16M-row chunks measured ~120M rows/s vs ~60-110M at 4M (honest-sync
    # methodology; fixed per-dispatch cost amortizes). 16M stays under both
    # the 2^24 exact-f32-count bound and the kernel chunk cap.
    chunk = 16_000_000
    n_chunks = 2
    codes, labels = make_data(chunk, n_feat, n_bins, n_classes)
    pair_idx = np.array([(i, j) for i in range(n_feat) for j in range(i + 1, n_feat)], np.int32)
    ci, cj = pair_idx[:, 0], pair_idx[:, 1]

    dcodes = jnp.asarray(codes)
    dlabels = jnp.asarray(labels)

    def pipeline_step(c, l):
        return agg.nb_mi_pipeline_step(c, l, ci, cj, n_classes, n_bins)

    # warmup/compile (device_sync = per-shard host fetch: block_until_ready
    # is a no-op on the tunnel platform); warm the chained form the timed
    # loop uses
    from avenir_tpu.utils.profiling import device_sync
    device_sync(pipeline_step(dcodes, dlabels + jnp.int32(0)))

    # ALL passes are recorded (value = best): the tunnel's dispatch timing
    # jitters run-to-run by tens of percent (BASELINE.md), so a single
    # sample under-reports the kernel's real rate — and the per-pass list in
    # the driver artifact documents the spread instead of hiding it.
    # Sync discipline: jax.block_until_ready is a NO-OP on the tunnel
    # platform (measured round 2); a host fetch of a reduced scalar is the
    # only reliable barrier, so each pass chains the result into the next
    # dispatch and fetches once.
    passes = []
    for _ in range(5):
        bias = jnp.int32(0)
        t0 = time.perf_counter()
        for _ in range(n_chunks):
            # true dependency chain: each dispatch consumes a scalar of the
            # previous result (via the small labels operand, not the big
            # codes tensor), so the final fetch is a barrier for ALL chunks
            # even if the backend could reorder independent dispatches
            out = pipeline_step(dcodes, dlabels + bias)
            bias = (out[0][0, 0, 0] * 0).astype(jnp.int32)
        device_sync(out)
        passes.append(n_chunks * chunk / (time.perf_counter() - t0))
    rows_per_sec = max(passes)

    # numpy single-core baseline on a subsample
    sub = 200_000
    np_rps = numpy_reference_rows_per_sec(codes[:sub], labels[:sub], n_classes, n_bins)

    # roofline: the count pipeline is bandwidth-bound — per pass it reads
    # codes [N, F] int32 + labels [N] int32 from HBM (the count tables it
    # scatters into are KBs); report achieved bytes/s vs the chip's HBM peak
    from avenir_tpu.utils.roofline import chip_peaks, mfu_fields
    bytes_per_row = 4 * (n_feat + 1)
    line = {
        "metric": "nb_mi_pipeline_throughput",
        "value": round(rows_per_sec, 1),
        "unit": "rows/sec/chip",
        "vs_baseline": round(rows_per_sec / np_rps, 2),
        "passes_rows_per_sec": [round(p, 1) for p in passes],
    }
    line.update(mfu_fields(bytes_moved=n_chunks * chunk * bytes_per_row,
                           dt=n_chunks * chunk / rows_per_sec,
                           peaks=chip_peaks()))
    print(json.dumps(line))


if __name__ == "__main__":
    main()
