#!/usr/bin/env python
"""Benchmark: Naive-Bayes + mutual-information pipeline throughput on TPU.

The driver-defined primary metric (BASELINE.json): rows/sec/chip on the
NaiveBayes+MI aggregation pipeline — the rebuild of the reference's
hospital-readmission north-star workload (BayesianDistribution +
MutualInformation MR jobs). Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "rows/sec/chip", "vs_baseline": N}

``vs_baseline`` is the speedup over a single-core numpy implementation of the
same counts (the stand-in for the reference's per-record JVM mapper loop,
measured on a subsample and scaled), since the reference publishes no numbers
(BASELINE.md).

Round 4: the per-chunk device step is the FUSED COLUMNAR MXU co-occurrence
kernel (ops/pallas_hist.py — G = XᵀX over the joint (feature, bin, class)
one-hot, int8 MXU pass, joint+expand fused in-kernel, no transpose/prologue)
when the attached device supports it; the [F,B,C] and [P,B,B,C] tensors are
read out of G once per job on host (microseconds — reported as
``finalize_ms``), exactly how MutualInformation.fit consumes it.  The
einsum/scatter form it replaced measured ~80-113 M rows/s on the same rig
and remains the fallback (and the multi-device path).  The remaining wall
is the W=384 int8 gram's ~30%-of-peak MXU ceiling, cross-validated against
bare XLA (see ops/pallas_hist.py docstring + benchmarks/*_probe.py).

Round 8: this script (and every benchmarks/ probe) is gated by graftlint
in tier-1 — ``python -m avenir_tpu.analysis`` / tests/test_analysis.py —
so a timing loop that regresses into a host-sync-per-iteration pattern
(GL005: .item()/device_get inside the measured loop — the r05 RTT-wall
class the honest-sync discipline here exists to avoid) fails CI before it
can publish an RTT measurement as a kernel number (docs/analysis.md).
"""

import json
import time

import numpy as np

import jax
import jax.numpy as jnp

from avenir_tpu.ops import agg, pallas_hist


def make_data(n_rows: int, n_feat: int, n_bins: int, n_classes: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    codes = rng.integers(0, n_bins, size=(n_rows, n_feat), dtype=np.int32)
    labels = rng.integers(0, n_classes, size=n_rows, dtype=np.int32)
    return codes, labels


def numpy_reference_rows_per_sec(codes, labels, n_classes, n_bins):
    """Single-core numpy equivalent of the NB+MI count pass (per-record cost model
    of the reference's mapper+reducer). Computes the SAME work as the TPU
    pipeline (all feature pairs) so vs_baseline compares like for like.
    Median of 3 reps: the 1-core host is contended by the tunnel relay, so
    a single rep swings vs_baseline by 2× run-to-run."""
    n, f = codes.shape
    pairs = [(i, j) for i in range(f) for j in range(i + 1, f)]
    # Buffers hoisted out of the timed loop (round-5 fix): allocating them
    # per feature/pair inside the timing mildly understated the baseline and
    # thus inflated vs_baseline. The persistent-accumulator shape also
    # matches the reference mapper, which reuses its count maps.
    nb_buf = np.zeros((n_bins, n_classes))
    pair_buf = np.zeros((n_bins, n_bins))
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        # NB: class-conditional counts
        for fi in range(f):
            np.add.at(nb_buf, (codes[:, fi], labels), 1)
        # MI: pairwise joint counts
        for i, j in pairs:
            np.add.at(pair_buf, (codes[:, i], codes[:, j]), 1)
        rates.append(n / (time.perf_counter() - t0))
    return float(np.median(rates))


def main():
    # GraftTrace (round 10): AVENIR_TRACE_DIR opts the bench into the run
    # journal — each pass becomes a span and each canary a journal event,
    # so a regressed artifact ships its own timeline (``trace_artifact``
    # below names it; `python -m avenir_tpu.telemetry <path>` renders it).
    # Unset (the default), the tracer stays disabled: no journal file is
    # created and the timed loop pays one attribute check per span site
    # (benchmarks/telemetry_overhead.py publishes the measured on-state
    # cost).
    import os

    from avenir_tpu.telemetry import profile as prof_mod
    from avenir_tpu.telemetry import spans as tel
    tracer = tel.tracer()
    prof = prof_mod.profiler()
    trace_dir = os.environ.get("AVENIR_TRACE_DIR")
    if trace_dir:
        # GraftProf rides the same opt-in: the journal then carries
        # program.compiled (AOT cost of the chunk program) +
        # program.profile events, so `python -m avenir_tpu.telemetry
        # profile <trace_artifact>` renders this run's roofline table
        tracer.enable(trace_dir)
        prof.enable()

    # Rig-state canary FIRST (round 5): a bare-XLA 4096³ bf16 matmul,
    # measured before any framework kernel touches the chip, so every
    # artifact separates "rig slow" from "kernel regressed"
    # (utils/rig_canary.py).
    from avenir_tpu.utils.rig_canary import matmul_canary_ms
    canary_ms = matmul_canary_ms()
    tracer.event("canary", ms=round(canary_ms, 2), when="pre_run")

    n_classes, n_bins, n_feat = 2, 12, 11      # hosp_readmit-shaped workload
    # 16M-row chunks amortize fixed per-dispatch cost (honest-sync
    # methodology; BASELINE.md) and stay under the 2^24 exact-count chunk
    # cap shared with the einsum path.
    chunk = 16_000_000
    n_chunks = 4
    codes, labels = make_data(chunk, n_feat, n_bins, n_classes)
    pair_idx = np.array([(i, j) for i in range(n_feat) for j in range(i + 1, n_feat)], np.int32)
    ci, cj = pair_idx[:, 0], pair_idx[:, 1]

    # single source of the kernel-vs-einsum routing (and each path's
    # chain-scalar extractor): ops/pallas_hist.chunk_pipeline — the same
    # predicate MutualInformation.fit and e2e_pipeline use.  The kernel
    # path takes COLUMNAR [F, N] codes (round 4: the fused kernel streams
    # codes with no device transpose — the r3 per-chunk transpose+joint
    # prologue measured ~11 ms of the ~50 ms chunk); the one-time host
    # transpose below is setup, not steady-state work, exactly like the
    # one-time host→device upload.
    pipeline_step, chain_scalar, kernel_path = pallas_hist.chunk_pipeline(
        n_feat, n_bins, n_classes, ci, cj, columnar=True)
    if kernel_path:
        dcodes = jnp.asarray(np.ascontiguousarray(codes.T))
    else:
        dcodes = jnp.asarray(codes)
    dlabels = jnp.asarray(labels)

    # register THE program this bench dispatches (AOT cost analysis where
    # the backend supports it; shapes-only otherwise — never raises)
    bench_pkey = None
    if prof.enabled:
        bench_pkey = tel.CompileKeyMonitor.shape_key(dcodes, dlabels) + (
            "nb_mi", kernel_path)
        prof.observe(bench_pkey, site="bench.nb_mi",
                     lowerable=pipeline_step, args=(dcodes, dlabels))

    # Sync discipline: jax.block_until_ready is a NO-OP on the tunnel
    # platform (measured round 2); a host fetch of a reduced scalar is the
    # only reliable barrier, so each pass chains the result into the next
    # dispatch and fetches once (BASELINE.md "Timing methodology").
    from avenir_tpu.utils.profiling import device_sync

    def timed_pass():
        bias = jnp.int32(0)
        t0 = time.perf_counter()
        for _ in range(n_chunks):
            out = pipeline_step(dcodes, dlabels + bias)
            bias = chain_scalar(out)
        device_sync(out)
        return n_chunks * chunk / (time.perf_counter() - t0), out

    # Warm until steady state: one compile call plus one full untimed
    # chained pass, so no cold/compile pass leaks into the recorded spread
    # (round-2 verdict: the artifact carried a 5.6×-low first pass).
    device_sync(pipeline_step(dcodes, dlabels + jnp.int32(0)))
    timed_pass()

    # ALL recorded passes are reported and the headline is the MEDIAN: the
    # tunnel's dispatch timing jitters run-to-run by tens of percent
    # (BASELINE.md), so the per-pass list documents the spread and the
    # median resists both tails.  A fresh canary runs before EACH pass
    # (round-6): the r05 artifact's 158–377M rows/s within-run spread was
    # unattributable with only one pre-run canary — the per-pass list
    # separates rig contention (canary inflates with the slow passes)
    # from kernel regression (canary flat while passes sag).
    passes = []
    canary_per_pass = []
    with tracer.span("bench.nb_mi", attrs={"chunk": chunk,
                                           "n_chunks": n_chunks}):
        for i in range(5):
            canary_per_pass.append(matmul_canary_ms())
            tracer.event("canary", ms=round(canary_per_pass[-1], 2),
                         when=f"pass{i}")
            with tracer.span("bench.pass", attrs={"pass": i}) as sp:
                rate, out = timed_pass()
                sp.set("rows_per_sec", round(rate, 1))
            if bench_pkey is not None:
                # one timed pass = n_chunks chained dispatches of the one
                # program — record each so the profile table's per-dispatch
                # math (achieved = flops x dispatches / wall) is exact
                for _ in range(n_chunks):
                    prof.sample(bench_pkey, "bench.nb_mi",
                                chunk / rate)
            passes.append(rate)
    rows_per_sec = float(np.median(passes))

    # Canary-conditioned headline (round 7, closing the r05 verdict item):
    # the published band is anchored to rate-vs-canary PAIRS, not to a raw
    # band widened after every outlier.  A pass whose fresh canary exceeds
    # the healthy threshold (BASELINE.md interpretation contract: matmul
    # ≲ 7 ms; the contended regime reads 167–428 ms) indicts the RIG, so
    # it documents the spread but is excluded from the conditioned median
    # that regression comparisons use.  ONE constant shared with the
    # sentinel that consumes these fields (round-14): the producer and the
    # gate must agree on what a contended rig is.
    from avenir_tpu.telemetry.sentinel import CANARY_HEALTHY_MS
    canary_healthy_ms = CANARY_HEALTHY_MS
    clean = [r for c_ms, r in zip(canary_per_pass, passes)
             if c_ms <= canary_healthy_ms]
    # an all-contended run publishes NULL, never the contaminated raw
    # median — the conditioned field must only ever carry rig-clean rates
    rows_per_sec_clean = float(np.median(clean)) if clean else None

    # per-job finalization: host read-out of the reference-shaped tensors
    # from G (the jobs path does this once per job via counts_from_cooc)
    finalize_ms = 0.0
    if kernel_path:
        g_host = np.asarray(out, np.int64)
        t0 = time.perf_counter()
        fbc, pair = pallas_hist.counts_from_cooc(
            g_host, n_feat, n_bins, n_classes, ci, cj)
        finalize_ms = (time.perf_counter() - t0) * 1e3
        assert fbc.shape == (n_feat, n_bins, n_classes)
        assert pair.shape == (len(ci), n_bins, n_bins, n_classes)

    # numpy single-core baseline on a subsample
    sub = 200_000
    np_rps = numpy_reference_rows_per_sec(codes[:sub], labels[:sub], n_classes, n_bins)

    # roofline: the kernel is int8-MXU-bound (2·Wp² int8 MACs/row for the
    # XᵀX pass), NOT bandwidth-bound — the 48 B/row input stream is a few
    # GB/s at these rates, so both resources are reported
    from avenir_tpu.utils.roofline import chip_peaks, mfu_fields
    bytes_per_row = 4 * (n_feat + 1)
    mode, _, wp = pallas_hist.plan(n_feat, n_bins, n_classes)
    # the per-class modes perform C sequential wp×wp grams per block →
    # 2·C·wp² MACs per row; the joint modes do one wp×wp gram (2·wp²).
    per_row = (2 * n_classes * wp * wp if mode in ("cls", "clsb")
               else 2 * wp * wp)
    int8_ops_per_row = per_row if kernel_path else 0
    line = {
        "metric": "nb_mi_pipeline_throughput",
        "value": round(rows_per_sec, 1),
        "unit": "rows/sec/chip",
        "vs_baseline": round(rows_per_sec / np_rps, 2),
        "passes_rows_per_sec": [round(p, 1) for p in passes],
        "count_path": "pallas_cooc_int8_mxu" if kernel_path else "einsum",
        "finalize_ms": round(finalize_ms, 3),
        "canary_matmul_4096_bf16_ms": round(canary_ms, 2),
        "canary_per_pass_ms": [round(c, 2) for c in canary_per_pass],
        # the band's regression anchor: (canary ms, rows/s) per pass plus
        # the median over canary-clean passes only (see BASELINE.md)
        "rate_vs_canary": [[round(c, 2), round(p, 1)]
                           for c, p in zip(canary_per_pass, passes)],
        "value_canary_clean": (round(rows_per_sec_clean, 1)
                               if rows_per_sec_clean is not None else None),
        "canary_clean_passes": len(clean),
        "canary_healthy_threshold_ms": canary_healthy_ms,
        # the run's own timeline when AVENIR_TRACE_DIR opted in (else null
        # — and no journal file exists at all, the off-is-free contract)
        "trace_artifact": tracer.journal_path,
    }
    line.update(mfu_fields(
        bytes_moved=n_chunks * chunk * bytes_per_row,
        int8_ops=n_chunks * chunk * int8_ops_per_row or None,
        dt=n_chunks * chunk / rows_per_sec,
        peaks=chip_peaks()))

    # secondary driver metric (BASELINE.json): kNN QPS at 1M refs, embedded
    # as a NESTED object so the one-JSON-line driver contract holds. Runs
    # with the on-chip oracle verification; measured after the primary so
    # the primary never inherits kNN warmup state. Free memory first: the
    # NB+MI operands (codes+labels, ~3 GB over two copies) plus the kNN
    # reference set must not coexist on a 16 GB chip.
    if kernel_path:
        del dcodes, dlabels
        from benchmarks.knn_qps import measure as knn_measure
        knn = knn_measure(verify=True, quick=True)
        line["knn"] = {kf: knn[kf] for kf in
                       ("value", "unit", "k", "batch", "n_refs",
                        "pipelined_passes_qps", "single_shot_qps",
                        "verified_vs_oracle", "mfu_pct",
                        "canary_matmul_4096_bf16_ms", "canary_knn_dot_ms")
                       if kf in knn}

        # per-family driver numbers (round-4 item 5): tree (exhaustive),
        # tree_binary (sklearn-comparable binary-threshold mode, round 6),
        # viterbi/lr/cramer at reduced shapes with measured single-core
        # baselines, so BENCH_r*.json — not BASELINE.md prose — carries
        # every family's value AND its vs_baseline ratio (same
        # chained-sync discipline); tree rows tag their selection path
        from benchmarks.family_bench import families_summary
        line["families"] = families_summary(passes=2)

    # GraftProf sentinel (round 14): gate this capture against the
    # previous artifact in-process, so every BENCH_r*.json carries its
    # own verdict (canary-flagged metrics are skipped with a verdict, not
    # compared — the value_canary_clean convention).  AVENIR_BENCH_BASELINE
    # points at the baseline artifact; a bands-less/missing baseline
    # yields a no_baseline verdict, never a failed capture.
    from avenir_tpu.telemetry import sentinel
    baseline_path = os.environ.get(
        "AVENIR_BENCH_BASELINE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BASELINE.json"))
    line["regression"] = sentinel.bench_verdict(line, baseline_path)
    # GraftFleet SLO gate (round 15): evaluate slo.<name>.* rules from
    # the AVENIR_SLO_CONF properties file over this capture's own
    # journal and embed the verdict next to the sentinel's — no rules
    # configured → "no_rules", rules without a journal (AVENIR_TRACE_DIR
    # unset) → "no_journal"; the capture publishes either way.
    from avenir_tpu.telemetry import slo as slo_mod
    line["slo"] = slo_mod.bench_verdict(tracer.journal_path,
                                        os.environ.get("AVENIR_SLO_CONF"))
    prof.flush()             # cumulative program.profile into the journal
    print(json.dumps(line))


if __name__ == "__main__":
    main()
