#!/usr/bin/env python
"""Bisection harness for the co-occurrence kernel's VPU expand wall.

Round 3 established (ops/pallas_hist.py notes): the int8-MXU XᵀX pass is
~12.6 ms of the ~34 ms 16M-row chunk — i.e. the one-hot expand/compare at
W·N cells governs, not the matmul.  This sweep times EXPAND VARIANTS of the
same G = XᵀX kernel, one configuration per process run (fresh-process
discipline — in-process A/B drifts 30-50%, BASELINE.md), chained-dispatch
host-fetch sync (block_until_ready is a no-op on the tunnel).

Variants:
- ``base``     round-3 shipped kernel: tile-concatenate [W, BN] int32 +
               compare against iota//F, incl. compares on the Wp-W padding
               rows (j-major G layout).
- ``dotonly``  xt = zeros: the dot + grid overhead floor (no expand at all;
               counts are garbage — timing only).
- ``nocmp``    expand copy without compare: jrept.astype(int8) (garbage
               counts — isolates the concatenate+pack cost).
- ``fmaj32``   f-major broadcast expand: (joint[:,None,:] == iota_jc32)
               .astype(int8) — 3-D compare with jc padded to 32 so the int8
               (32,128) tiling is clean, reshape [F·jc32, BN] is a no-op
               tile collapse, zero-pad to Wp is tile-aligned.  No int32
               [W, BN] materialization at all → VMEM drops ~5×, so BN can
               grow past the base variant's budget.
- ``fmaj8``    same broadcast but compare→int32 3-D (jc padded to 8),
               reshape, int32 zero-pad, then one 2-D astype(int8) pack —
               for the case where the 3-D int8 select doesn't lower.

Usage:  python benchmarks/cooc_expand_sweep.py --variant fmaj32 --bn 98304
Each run prints one JSON line; run variants sequentially (ONE TPU process
at a time — the tunnel serializes clients).
"""

from __future__ import annotations

import argparse
import functools
import json
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; module-local alias,
# same as ops/pallas_hist.py
COMPILER_PARAMS = (pltpu.CompilerParams if hasattr(pltpu, "CompilerParams")
                   else pltpu.TPUCompilerParams)


_INVALID = -(1 << 20)
_PAD_SEL = -(1 << 20) - 1


def _ru(x: int, m: int) -> int:
    return -(-x // m) * m


# --------------------------------------------------------------------------
# expand variants: joint [F, BN] int32 -> Xᵀ [Wp, BN] int8
# --------------------------------------------------------------------------

def _expand_base(joint, *, f, jc, wp):
    w = f * jc
    bn = joint.shape[1]
    jrept = jnp.concatenate([joint] * jc, axis=0)
    if wp > w:
        jrept = jnp.concatenate(
            [jrept, jnp.full((wp - w, bn), _INVALID, jnp.int32)], axis=0)
    jw = jax.lax.broadcasted_iota(jnp.int32, (wp, 1), 0)
    jsel = jnp.where(jw < w, jw // f, _PAD_SEL)
    return (jrept == jsel).astype(jnp.int8)


def _expand_nocmp(joint, *, f, jc, wp):
    w = f * jc
    bn = joint.shape[1]
    jrept = jnp.concatenate([joint] * jc, axis=0)
    if wp > w:
        jrept = jnp.concatenate(
            [jrept, jnp.full((wp - w, bn), _INVALID, jnp.int32)], axis=0)
    return jrept.astype(jnp.int8)          # garbage values; timing only


def _expand_fmaj32(joint, *, f, jc, wp):
    bn = joint.shape[1]
    jcp = _ru(jc, 32)
    jv = jax.lax.broadcasted_iota(jnp.int32, (1, jcp, 1), 1)
    xt = (joint[:, None, :] == jv).astype(jnp.int8)       # [F, jc32, BN]
    xt = xt.reshape(f * jcp, bn)
    if wp > f * jcp:
        xt = jnp.concatenate(
            [xt, jnp.zeros((wp - f * jcp, bn), jnp.int8)], axis=0)
    return xt


def _expand_fmaj8(joint, *, f, jc, wp):
    bn = joint.shape[1]
    jcp = _ru(jc, 8)
    jv = jax.lax.broadcasted_iota(jnp.int32, (1, jcp, 1), 1)
    x32 = (joint[:, None, :] == jv).astype(jnp.int32)     # [F, jc8, BN]
    x32 = x32.reshape(f * jcp, bn)
    if wp > f * jcp:
        x32 = jnp.concatenate(
            [x32, jnp.zeros((wp - f * jcp, bn), jnp.int32)], axis=0)
    return x32.astype(jnp.int8)


_EXPANDS = {
    "base": (_expand_base, "jmaj"),
    "nocmp": (_expand_nocmp, "none"),
    "fmaj32": (_expand_fmaj32, "fmaj32"),
    "fmaj8": (_expand_fmaj8, "fmaj8"),
}

# variants fed codes ALREADY in [F, N] layout (no XLA transpose in the
# prologue — the dotonly-vs-base result showed the expand itself is nearly
# free, making the 704 MB/chunk HBM transpose the prime suspect)
_T_VARIANTS = {"base_t": "base", "dotonly_t": "dotonly", "fmaj32_t": "fmaj32"}
# "fused32": joint computed inside the kernel from streamed codes_t+labels
# blocks (saves the separate [F, N] joint materialization round trip too)


def _wp_for(variant: str, f: int, jc: int) -> int:
    if variant == "fmaj32":
        return _ru(f * _ru(jc, 32), 128)
    if variant == "fmaj8":
        return _ru(f * _ru(jc, 8), 128)
    return _ru(f * jc, 128)


def _kernel(joint_ref, out_ref, *, f, jc, wp, n, variant):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    joint = joint_ref[:]
    bn = joint.shape[1]
    if n % bn or n == 0:
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)
        joint = jnp.where(lane < n - i * bn, joint, _INVALID)
    if variant == "dotonly":
        xt = jnp.zeros((wp, bn), jnp.int8)
    else:
        xt = _EXPANDS[variant][0](joint, f=f, jc=jc, wp=wp)
    acc = jax.lax.dot_general(xt, xt, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.int32)
    out_ref[:] += acc


def _fused_kernel(codes_ref, labels_ref, out_ref, *, f, jc, wp, n, nclass,
                  expand):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    ct = codes_ref[:]                                  # [F, BN] int32
    y = labels_ref[:]                                  # [1, BN] int32
    bn = ct.shape[1]
    valid = (y >= 0) & (y < nclass)
    if n % bn or n == 0:
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, bn), 1)
        valid &= lane < n - i * bn
    joint = jnp.where(valid, ct * nclass + y, _INVALID)
    xt = _EXPANDS[expand][0](joint, f=f, jc=jc, wp=wp)
    acc = jax.lax.dot_general(xt, xt, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.int32)
    out_ref[:] += acc


@functools.partial(jax.jit, static_argnames=(
    "num_bins", "num_classes", "bn", "variant", "interpret"))
def cooc_variant(codes, labels, num_bins, num_classes, bn, variant,
                 interpret=False):
    jc = num_bins * num_classes
    npad_of = lambda n: _ru(max(n, bn), bn)
    if variant == "fused32":
        f, n = codes.shape[0], codes.shape[1]          # codes given [F, N]
        wp = _wp_for("fmaj32", f, jc)
        return pl.pallas_call(
            functools.partial(_fused_kernel, f=f, jc=jc, wp=wp, n=n,
                              nclass=num_classes, expand="fmaj32"),
            grid=(npad_of(n) // bn,),
            in_specs=[pl.BlockSpec((f, bn), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((1, bn), lambda i: (0, i),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((wp, wp), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((wp, wp), jnp.int32),
            compiler_params=COMPILER_PARAMS(
                dimension_semantics=("arbitrary",),
                vmem_limit_bytes=110 * 1024 * 1024),
            interpret=interpret,
        )(codes, labels[None, :] if labels.ndim == 1 else labels)
    if variant in _T_VARIANTS:                         # codes given [F, N]
        variant = _T_VARIANTS[variant]
        f, n = codes.shape[0], codes.shape[1]
        codes_t = codes.astype(jnp.int32)
    else:
        n, f = codes.shape
        codes_t = codes.T.astype(jnp.int32)
    wp = _wp_for(variant, f, jc)
    y = labels[None, :]
    valid = (y >= 0) & (y < num_classes)
    joint = jnp.where(valid, codes_t * num_classes + y, _INVALID)
    return pl.pallas_call(
        functools.partial(_kernel, f=f, jc=jc, wp=wp, n=n, variant=variant),
        grid=(npad_of(n) // bn,),
        in_specs=[pl.BlockSpec((f, bn), lambda i: (0, i),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((wp, wp), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((wp, wp), jnp.int32),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=110 * 1024 * 1024),
        interpret=interpret,
    )(joint)


# --------------------------------------------------------------------------
# correctness: interpret-mode check vs a numpy one-hot gram, per layout
# --------------------------------------------------------------------------

def _numpy_g(codes, labels, b, c, variant, f):
    jc = b * c
    n = codes.shape[0]
    joint = codes.astype(np.int64) * c + labels[:, None]
    joint[(labels < 0) | (labels >= c)] = -1
    if variant == "fmaj32":
        jcp, fmaj = _ru(jc, 32), True
    elif variant == "fmaj8":
        jcp, fmaj = _ru(jc, 8), True
    else:
        jcp, fmaj = jc, False
    wp = _wp_for(variant, f, jc)
    x = np.zeros((n, wp), np.int64)
    for fi in range(f):
        for row in range(n):
            j = joint[row, fi]
            if 0 <= j < jc:
                w = fi * jcp + j if fmaj else j * f + fi
                x[row, w] = 1
    return x.T @ x


def self_check(variant: str) -> None:
    if "dotonly" in variant or variant == "nocmp":
        return
    rng = np.random.default_rng(7)
    f, b, c, n = 5, 4, 3, 1000
    codes = rng.integers(0, b, size=(n, f)).astype(np.int32)
    labels = rng.integers(-1, c, size=n).astype(np.int32)   # incl. invalid
    dcodes = jnp.asarray(np.ascontiguousarray(codes.T)) \
        if (variant in _T_VARIANTS or variant == "fused32") \
        else jnp.asarray(codes)
    g = np.asarray(cooc_variant(dcodes, jnp.asarray(labels),
                                b, c, 256, variant, interpret=True))
    base_name = _T_VARIANTS.get(variant,
                                "fmaj32" if variant == "fused32" else variant)
    ref = _numpy_g(codes, labels, b, c, base_name, f)
    np.testing.assert_array_equal(g, ref)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--variant", default="base",
                    choices=["base", "dotonly", "nocmp", "fmaj32", "fmaj8",
                             "base_t", "dotonly_t", "fmaj32_t", "fused32"])
    ap.add_argument("--bn", type=int, default=49152)
    ap.add_argument("--passes", type=int, default=4)
    ap.add_argument("--chunks", type=int, default=4)
    ap.add_argument("--no-check", action="store_true")
    args = ap.parse_args()

    if not args.no_check:
        self_check(args.variant)

    n_classes, n_bins, n_feat = 2, 12, 11     # hosp_readmit shape
    chunk = 16_000_000
    rng = np.random.default_rng(0)
    codes = rng.integers(0, n_bins, size=(chunk, n_feat), dtype=np.int32)
    labels = rng.integers(0, n_classes, size=chunk, dtype=np.int32)
    if args.variant in _T_VARIANTS or args.variant == "fused32":
        dcodes = jnp.asarray(np.ascontiguousarray(codes.T))
    else:
        dcodes = jnp.asarray(codes)
    dlabels = jnp.asarray(labels)

    def timed_pass():
        bias = jnp.int32(0)
        t0 = time.perf_counter()
        for _ in range(args.chunks):
            out = cooc_variant(dcodes, dlabels + bias, n_bins, n_classes,
                               args.bn, args.variant)
            bias = (out[0, 0] * 0).astype(jnp.int32)
        float(out[0, 0])                       # host fetch = the only barrier
        return args.chunks * chunk / (time.perf_counter() - t0)

    timed_pass()                               # compile + warm
    timed_pass()
    passes = [timed_pass() for _ in range(args.passes)]
    med = float(np.median(passes))
    print(json.dumps({
        "variant": args.variant, "bn": args.bn,
        "rows_per_sec": round(med, 1),
        "ms_per_chunk": round(chunk / med * 1e3, 2),
        "passes_rows_per_sec": [round(p, 1) for p in passes],
        "wp": _wp_for(args.variant, n_feat, n_bins * n_classes),
    }))


if __name__ == "__main__":
    main()
