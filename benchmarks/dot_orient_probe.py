#!/usr/bin/env python
"""Isolate the gram-matmul orientation cost in Pallas on TPU.

G = X·Xᵀ with X [W, N] (contract dim 1 of both operands) requires the MXU's
RHS in [N, W]; if Mosaic materializes per-tile int8 transposes for that, the
gram runs far below the int8 peak.  The alternative orientation streams
A = Xᵀ [N, W] and contracts dim 0 of both (AᵀA), which is the systolic
array's native reduce-over-rows mode.  This probe times both on identical
random int8 data (no expand, no compare — dot + streaming only).

One variant per process:  python benchmarks/dot_orient_probe.py --orient a
"""

import argparse
import functools
import json
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; module-local alias,
# same as ops/pallas_hist.py
COMPILER_PARAMS = (pltpu.CompilerParams if hasattr(pltpu, "CompilerParams")
                   else pltpu.TPUCompilerParams)



def _kernel_a(x_ref, out_ref):          # x block [W, BN]; G += x·xᵀ
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = x_ref[:]
    out_ref[:] += jax.lax.dot_general(x, x, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.int32)


def _kernel_b(x_ref, out_ref):          # x block [BN, W]; G += xᵀ·x
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = x_ref[:]
    out_ref[:] += jax.lax.dot_general(x, x, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.int32)


@functools.partial(jax.jit, static_argnames=("bn", "orient"))
def gram(x, bn, orient):
    if orient == "a":
        w, n = x.shape
        return pl.pallas_call(
            _kernel_a, grid=(n // bn,),
            in_specs=[pl.BlockSpec((w, bn), lambda i: (0, i),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((w, w), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((w, w), jnp.int32),
            compiler_params=COMPILER_PARAMS(
                dimension_semantics=("arbitrary",),
                vmem_limit_bytes=110 * 1024 * 1024),
        )(x)
    n, w = x.shape
    return pl.pallas_call(
        _kernel_b, grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, w), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((w, w), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((w, w), jnp.int32),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=110 * 1024 * 1024),
    )(x)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--orient", choices=["a", "b"], default="a")
    ap.add_argument("--bn", type=int, default=98304)
    ap.add_argument("--w", type=int, default=384)
    ap.add_argument("--n", type=int, default=4_194_304)
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    shape = (args.w, args.n) if args.orient == "a" else (args.n, args.w)
    x = jnp.asarray(rng.integers(0, 2, size=shape, dtype=np.int8))

    def timed():
        t0 = time.perf_counter()
        g = gram(x, args.bn, args.orient)
        for _ in range(3):                 # chain: result feeds nothing; use
            g = gram(x + (g[0, 0] * 0).astype(jnp.int8), args.bn, args.orient)
        float(g[0, 0])
        return 4 * args.n / (time.perf_counter() - t0)

    timed()
    timed()
    passes = [timed() for _ in range(4)]
    med = float(np.median(passes))
    tops = 2.0 * args.w * args.w * med / 1e12
    print(json.dumps({
        "orient": args.orient, "bn": args.bn, "w": args.w,
        "rows_per_sec": round(med, 1),
        "eff_int8_tops": round(tops, 1),
        "passes": [round(p, 1) for p in passes],
    }))


if __name__ == "__main__":
    main()
