#!/usr/bin/env python
"""End-to-end pipeline benchmark: CSV bytes → native encode → device NB+MI.

The north-star workload (BASELINE.md) is the hospital-readmission MI +
Naive-Bayes pipeline over CSV with the reference's driver contract. bench.py
measures the device aggregation alone; this measures the whole ingest path:
chunked CSV parsing through the C++ data plane (runtime/native) overlapped
with the jitted count kernels on chip.

Usage: python -m benchmarks.e2e_pipeline [n_rows]   (default 20M)
Prints one JSON line with end-to-end rows/sec, the ingest-only rate, and —
round 7 — the fused-vs-unfused wall for a 3-job (NB + MI + Cramér) pipeline
over the same dataset: unfused pays one full scan per job, the SharedScan
(``pipeline/scan.py``) pays one scan total, with byte-identical models
asserted inline.
"""

import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from avenir_tpu.core.encoding import DatasetEncoder
from avenir_tpu.core.schema import FeatureSchema
from avenir_tpu.datagen.hosp_readmit import HOSP_SCHEMA_JSON, generate_hosp_readmit
from avenir_tpu.ops import agg
from avenir_tpu.runtime import native


def make_csv_block(n_rows: int, seed: int) -> bytes:
    rows = generate_hosp_readmit(n_rows, seed=seed)
    return ("\n".join(",".join(r) for r in rows) + "\n").encode()


def main():
    n_target = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000_000
    block_rows = min(500_000, max(n_target, 1))   # honor small requests
    block = make_csv_block(block_rows, seed=1)      # one synthesized block,
    n_blocks = max(n_target // block_rows, 1)       # streamed n_blocks times

    enc = DatasetEncoder(FeatureSchema.from_json(HOSP_SCHEMA_JSON))
    sample = generate_hosp_readmit(2000, seed=0)
    ds0 = enc.fit_transform(sample)
    ncols = len(sample[0])
    assert native.is_available(), native.build_error()

    f = ds0.codes.shape[1]
    nb = int(ds0.n_bins.max())
    n_classes = len(ds0.class_values)
    pair_idx = np.array([(i, j) for i in range(f) for j in range(i + 1, f)],
                        np.int32)
    ci, cj = pair_idx[:, 0], pair_idx[:, 1]

    # same device work as bench.py's primary metric, routed by the same
    # shared predicate (the per-job G read-out is host-side and amortized)
    from avenir_tpu.ops import pallas_hist
    device_step, chain_scalar, kernel_path = pallas_hist.chunk_pipeline(
        f, nb, n_classes, ci, cj)

    # warm up compile + native path (sync = host fetch; block_until_ready
    # is a no-op on the tunnel platform — BASELINE.md timing methodology)
    from avenir_tpu.utils.profiling import device_sync
    d = native.encode_bytes(block, enc, ncols=ncols)
    device_sync(device_step(jnp.asarray(d.codes), jnp.asarray(d.labels)))

    # ingest-only rate (best of 3, matching knn_qps.py)
    ingest_dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        native.encode_bytes(block, enc, ncols=ncols)
        ingest_dt = min(ingest_dt, time.perf_counter() - t0)

    # end-to-end, serial reference: encode each block on host, dispatch
    # async to device; device work of block i overlaps host encode of
    # block i+1 only through dispatch asynchrony. Best of 3 passes,
    # matching the other benchmarks (tunnel dispatch jitter is tens of
    # percent run-to-run).
    dt_serial = float("inf")
    for _ in range(3):
        bias = jnp.int32(0)
        t0 = time.perf_counter()
        for _ in range(n_blocks):
            d = native.encode_bytes(block, enc, ncols=ncols)
            # dependency chain via the labels operand (BASELINE.md timing
            # methodology): the final fetch then syncs every block
            out = device_step(jnp.asarray(d.codes),
                              jnp.asarray(d.labels) + bias)
            bias = chain_scalar(out)
        device_sync(out)
        dt_serial = min(dt_serial, time.perf_counter() - t0)

    # end-to-end through the DeviceFeeder — the path the streaming jobs use
    # (jobs/base.py encoded_data_source): a worker thread encodes and stages
    # block N+1 while the main thread consumes block N.
    from avenir_tpu.runtime.feeder import DeviceFeeder

    def blocks():
        for _ in range(n_blocks):
            yield native.encode_bytes(block, enc, ncols=ncols)

    def stage(d):
        return jax.device_put(d.codes), jax.device_put(d.labels)

    dt = float("inf")
    for _ in range(3):
        bias = jnp.int32(0)
        t0 = time.perf_counter()
        for codes, labels in DeviceFeeder(blocks(), depth=2, stage=stage):
            out = device_step(codes, labels + bias)
            bias = chain_scalar(out)
        device_sync(out)
        dt = min(dt, time.perf_counter() - t0)
    total = n_blocks * block_rows

    # fused-vs-unfused 3-job pipeline (round 7): NB + MI + Cramér over the
    # SAME dataset.  Unfused = the reference's one-Tool-per-statistic shape
    # (each fit re-parses, re-encodes, re-uploads and re-aggregates the
    # stream); fused = pipeline/scan.SharedScan — one encode + one gram
    # pass serving all three consumers.  ``scan_seconds`` is the wall spent
    # scanning (parse+encode+device aggregation), the quantity the fusion
    # divides by K.
    from avenir_tpu.models.correlation import CramerCorrelation
    from avenir_tpu.models.mutual_info import MutualInformation
    from avenir_tpu.models.naive_bayes import NaiveBayes
    from avenir_tpu.pipeline import scan as shared_scan

    fuse_blocks = max(min(n_blocks, 4_000_000 // block_rows), 1)

    def chunk_stream():
        for _ in range(fuse_blocks):
            yield native.encode_bytes(block, enc, ncols=ncols)

    per_job = {}
    t0 = time.perf_counter()
    nb_model = NaiveBayes().fit(chunk_stream())
    per_job["nb"] = time.perf_counter() - t0
    t0 = time.perf_counter()
    mi_result = MutualInformation().fit(chunk_stream())
    per_job["mi"] = time.perf_counter() - t0
    t0 = time.perf_counter()
    cr_result = CramerCorrelation().fit(chunk_stream(), against_class=True)
    per_job["cramer"] = time.perf_counter() - t0
    unfused_s = sum(per_job.values())

    def build_engine(pack_on):
        engine = shared_scan.SharedScan(pack_on=pack_on)
        engine.register(shared_scan.NaiveBayesConsumer(name="nb"))
        engine.register(shared_scan.MutualInfoConsumer(name="mi"))
        engine.register(shared_scan.CorrelationConsumer(name="cramer",
                                                        against_class=True))
        return engine

    def check(results):
        # the fused scan must reproduce the standalone jobs bit-for-bit —
        # asserted BEFORE any rate is reported, for BOTH engines
        assert np.array_equal(results["nb"].bin_counts, nb_model.bin_counts)
        assert np.array_equal(results["mi"].pair_class_counts,
                              mi_result.pair_class_counts)
        assert np.array_equal(results["cramer"].contingency,
                              cr_result.contingency)

    engine = build_engine(pack_on=False)     # the unpacked fused scan
    t0 = time.perf_counter()
    check(engine.run(chunk_stream()))
    fused_s = time.perf_counter() - t0

    # PackGraft (round 16): the default engine routes the same three
    # consumers onto ONE wide block-diagonal gram dispatch per chunk
    packed_engine = build_engine(pack_on=True)
    t0 = time.perf_counter()
    check(packed_engine.run(chunk_stream()))
    packed_s = time.perf_counter() - t0

    # PlanGraft (round 19): planned-vs-staged DRIVER runs.  A realistic
    # pipeline interleaves non-count stages (report/transform steps)
    # between the count jobs, so the staged driver's consecutive-stage
    # fusion pays THREE scans (NB alone, MI alone, Cramér alone); the
    # planner hoists past the interleaved stages and serves all three
    # count stages from ONE scan.  Byte-identity of every artifact is
    # asserted inline BEFORE any rate is published.
    import os
    import shutil
    import tempfile

    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.pipeline import plan as plan_mod
    from avenir_tpu.pipeline.driver import Pipeline, Stage
    from avenir_tpu.utils.metrics import Counters

    plan_root = tempfile.mkdtemp(prefix="e2e_plan_")
    train_csv = os.path.join(plan_root, "train.csv")
    with open(train_csv, "wb") as fh:
        for _ in range(fuse_blocks):
            fh.write(block)
    schema_path = os.path.join(plan_root, "hosp.json")
    with open(schema_path, "w") as fh:
        fh.write(json.dumps(HOSP_SCHEMA_JSON))
    class_ord = FeatureSchema.from_json(HOSP_SCHEMA_JSON).class_field.ordinal

    def report_stage(conf, in_path, out_path):
        os.makedirs(out_path, exist_ok=True)
        with open(os.path.join(out_path, "part-00000"), "w") as out:
            out.write("report\n")
        return Counters()

    def build_pipeline(ws, plan_on):
        conf = JobConfig({"feature.schema.file.path": schema_path,
                          "plan.on": "true" if plan_on else "false"})
        p = Pipeline(os.path.join(plan_root, ws), conf)
        p.bind("data", train_csv)
        p.add(Stage("nb", "BayesianDistribution", "data", "nb_model"))
        p.add(Stage("report", report_stage, "data", "report_out"))
        p.add(Stage("mi", "MutualInformation", "data", "mi_out"))
        p.add(Stage("report2", report_stage, "data", "report2_out"))
        p.add(Stage("cramer", "CramerCorrelation", "data", "cramer_out",
                    props={"dest.attributes": str(class_ord)}))
        return p

    def timed_run(ws, plan_on, passes=2):
        best = float("inf")
        for _ in range(passes):
            shutil.rmtree(os.path.join(plan_root, ws), ignore_errors=True)
            p = build_pipeline(ws, plan_on)
            t0 = time.perf_counter()
            p.run()
            best = min(best, time.perf_counter() - t0)
        return p, best

    staged_p, staged_s = timed_run("ws_staged", plan_on=False)
    planned_p, planned_s = timed_run("ws_planned", plan_on=True)
    for art in ("nb_model", "report_out", "mi_out", "report2_out",
                "cramer_out"):
        a = open(os.path.join(plan_root, "ws_staged", art,
                              "part-00000"), "rb").read()
        b = open(os.path.join(plan_root, "ws_planned", art,
                              "part-00000"), "rb").read()
        assert a == b, f"planned {art} diverged from the staged oracle"
    plan_summary = plan_mod.plan_pipeline(build_pipeline("ws_x",
                                                         True)).summary()
    shutil.rmtree(plan_root, ignore_errors=True)

    print(json.dumps({
        "metric": "e2e_csv_nb_mi_pipeline",
        "value": round(total / dt, 1),
        "unit": "rows/sec/chip",
        "rows": total,
        "serial_rows_per_sec": round(total / dt_serial, 1),
        "ingest_only_rows_per_sec": round(block_rows / ingest_dt, 1),
        "count_path": "pallas_cooc_int8_mxu" if kernel_path else "einsum",
        "fused_pipeline": {
            "jobs": ["nb", "mi", "cramer"],
            "rows": fuse_blocks * block_rows,
            "unfused_scan_seconds": round(unfused_s, 3),
            "unfused_per_job_seconds": {k: round(v, 3)
                                        for k, v in per_job.items()},
            "fused_scan_seconds": round(fused_s, 3),
            "scan_seconds_ratio": round(unfused_s / fused_s, 2),
            "packed_scan_seconds": round(packed_s, 3),
            "packed_speedup_vs_fused": round(fused_s / packed_s, 2),
            "packed_path": packed_engine.count_path,
            "byte_identical": True,
        },
        # plan_speedup is a shared-rig ratio (both runs interleave on the
        # same device seconds apart), so canary fields divide out — the
        # pack_speedup precedent; the absolute walls ride along as
        # optional rows (BASELINE.json sentinel.optional: planned.*)
        "planned": {
            "plan_speedup": {
                "value": round(staged_s / planned_s, 2), "unit": "x"},
            "staged_scan_seconds": {
                "value": round(staged_s, 3), "unit": "seconds"},
            "planned_scan_seconds": {
                "value": round(planned_s, 3), "unit": "seconds"},
            "byte_identical": True,
            "rewrites": plan_summary["rewrites"],
            "plan_source": plan_summary["source"],
        },
    }))


if __name__ == "__main__":
    main()
