#!/usr/bin/env python
"""Per-family device benchmarks — one measured number for every workload
family the framework ships (VERDICT r3 item 6: "no workload family ships
without a measured number").

Families and shapes (reference-derived):
- ``tree``     decision-tree induction on the retarget shape
               (abandoned-cart retargeting, ``resource/retarget.py`` /
               ``tree/DataPartitioner.java`` two-jobs-per-level ↔ the
               in-memory frontier here); rows/s = rows / full-fit wall.
- ``viterbi``  batch Viterbi decode, email-marketing-tutorial shape
               (``resource/tutorial_opt_email_marketing.txt:15-18``):
               80k sequences × 210 observations; seqs/s.
- ``lr``       logistic-regression gradient iterations/s
               (``regress/LogisticRegressionJob.java:279-289`` ran ONE
               MR job per iteration; here one chained device step).
- ``cramer``   Cramér-index contingency aggregation rows/s
               (``explore/CramerCorrelation.java``).
- ``wordcount``host tokenize+count tokens/s (``text/WordCounter.java``;
               HOST-bound — on the 1-core dev rig this is a rig artifact,
               see BASELINE.md e2e notes).

Sync discipline: device-bound families chain dispatches and fetch once
(block_until_ready is a no-op on the tunnel — BASELINE.md "Timing
methodology"); tree/wordcount are host-driven loops whose wall-clock is
already host-observed.  Run ONE family per process:

  python -m benchmarks.family_bench --family viterbi
"""

import argparse
import json
import time

import numpy as np


def bench_tree(passes: int):
    import jax

    from avenir_tpu.core.encoding import DatasetEncoder
    from avenir_tpu.core.schema import FeatureSchema
    from avenir_tpu.datagen.retarget import (RETARGET_SCHEMA_JSON,
                                             generate_retarget)
    from avenir_tpu.models import tree as dtree

    n = 2_000_000
    schema = FeatureSchema.from_json(RETARGET_SCHEMA_JSON)
    rows = generate_retarget(n, seed=9)
    enc = DatasetEncoder(schema)
    ds = enc.fit_transform(rows)
    is_cat = [f.is_categorical for f in schema.binned_feature_fields]
    builder = dtree.DecisionTree(algorithm="entropy", max_depth=4,
                                 max_split=3)
    vals = []
    model = builder.fit(ds, is_categorical=is_cat)       # compile + warm
    for _ in range(passes):
        t0 = time.perf_counter()
        model = builder.fit(ds, is_categorical=is_cat)
        vals.append(n / (time.perf_counter() - t0))
    return {"metric": "tree_induction_rows_per_sec", "unit": "rows/sec/chip",
            "n_rows": n, "max_depth": 4, "nodes": len(model.nodes),
            "shape": "retarget"}, vals


def bench_viterbi(passes: int):
    import jax
    import jax.numpy as jnp

    from avenir_tpu.models import markov as mk

    r, t, s, o = 80_000, 210, 6, 12                      # email-mktg shape
    rng = np.random.default_rng(0)
    log_a = jnp.asarray(np.log(rng.dirichlet(np.ones(s), size=s)), jnp.float32)
    log_b = jnp.asarray(np.log(rng.dirichlet(np.ones(o), size=s)), jnp.float32)
    log_pi = jnp.asarray(np.log(rng.dirichlet(np.ones(s))), jnp.float32)
    obs = jnp.asarray(rng.integers(0, o, size=(r, t), dtype=np.int32))
    decode = jax.jit(mk._viterbi_batch)
    out = decode(log_a, log_b, log_pi, obs)
    np.asarray(out[0, 0])                                # compile + warm
    vals = []
    for _ in range(passes):
        bias = jnp.int32(0)
        t0 = time.perf_counter()
        for _ in range(3):                               # chained dispatches
            out = decode(log_a, log_b, log_pi, obs + bias * 0)
            bias = out[0, 0] * 0
        np.asarray(out[0, 0])
        vals.append(3 * r / (time.perf_counter() - t0))
    return {"metric": "viterbi_decode_seqs_per_sec", "unit": "seqs/sec/chip",
            "n_seqs": r, "seq_len": t, "n_states": s,
            "shape": "email_marketing_80kx210"}, vals


def bench_lr(passes: int):
    import jax
    import jax.numpy as jnp

    from avenir_tpu.models import logistic as lg

    n, d = 4_000_000, 24
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((n, d), np.float32))
    y = jnp.asarray((rng.random(n) < 0.5).astype(np.float32))
    w = jnp.zeros(d, jnp.float32)
    step = jax.jit(lg._grad_step)
    nn = jnp.float32(n)
    w1 = step(w, x, y, nn, jnp.float32(0.5), jnp.float32(0.01))
    np.asarray(w1[0])                                    # compile + warm
    iters = 20
    vals = []
    for _ in range(passes):
        wi = w
        t0 = time.perf_counter()
        for _ in range(iters):                           # natural chain via w
            wi = step(wi, x, y, nn, jnp.float32(0.5), jnp.float32(0.01))
        np.asarray(wi[0])
        vals.append(iters / (time.perf_counter() - t0))
    return {"metric": "lr_iterations_per_sec", "unit": "iters/sec/chip",
            "n_rows": n, "n_features": d,
            "note": "one iteration == one full-batch gradient step == one "
                    "MR job of the reference"}, vals


def bench_cramer(passes: int):
    import jax.numpy as jnp

    from avenir_tpu.ops import pallas_hist

    n, f, b = 16_000_000, 10, 20
    rng = np.random.default_rng(0)
    codes_t = jnp.asarray(rng.integers(0, b, size=(f, n), dtype=np.int32))
    zeros = jnp.zeros(n, jnp.int32)
    kernel = pallas_hist.use_kernel(f, b, 1)

    def step(bias):
        # all [B, B] contingency tables at once: the one-class gram —
        # exactly CategoricalCorrelation.fit's single-TPU fast path
        return pallas_hist.cooc_counts_cols(codes_t, zeros + bias, b, 1)

    out = step(jnp.int32(0))
    np.asarray(out[0, 0])
    vals = []
    for _ in range(passes):
        bias = jnp.int32(0)
        t0 = time.perf_counter()
        for _ in range(3):
            out = step(bias)
            bias = (out[0, 0] * 0).astype(jnp.int32)
        np.asarray(out[0, 0])
        vals.append(3 * n / (time.perf_counter() - t0))
    return {"metric": "cramer_rows_per_sec", "unit": "rows/sec/chip",
            "n_rows": n, "n_features": f, "cardinality": b,
            "n_pairs": f * (f - 1) // 2, "kernel_path": bool(kernel),
            "plan": list(pallas_hist.plan(f, b, 1))}, vals


def bench_wordcount(passes: int):
    from avenir_tpu.text.analyzer import tokenize

    rng = np.random.default_rng(0)
    vocab = [f"word{i}" for i in range(5000)]
    lines = [" ".join(rng.choice(vocab, size=12)) for _ in range(20_000)]
    n_tokens = sum(len(tokenize(s)) for s in lines)
    vals = []
    for _ in range(passes):
        t0 = time.perf_counter()
        counts: dict = {}
        for s in lines:
            for tok in tokenize(s):
                counts[tok] = counts.get(tok, 0) + 1
        vals.append(n_tokens / (time.perf_counter() - t0))
    return {"metric": "wordcount_tokens_per_sec", "unit": "tokens/sec",
            "n_tokens": n_tokens,
            "note": "HOST-bound (tokenizer); 1-core dev rig number is a "
                    "lower bound, scales with host cores"}, vals


FAMILIES = {"tree": bench_tree, "viterbi": bench_viterbi, "lr": bench_lr,
            "cramer": bench_cramer, "wordcount": bench_wordcount}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", choices=sorted(FAMILIES), required=True)
    ap.add_argument("--passes", type=int, default=4)
    args = ap.parse_args()
    line, vals = FAMILIES[args.family](args.passes)
    line["value"] = round(float(np.median(vals)), 1)
    line["passes"] = [round(v, 1) for v in vals]
    print(json.dumps(line))


if __name__ == "__main__":
    main()
