#!/usr/bin/env python
"""Per-family device benchmarks — one measured number AND one measured
single-core baseline for every workload family the framework ships
(round-3 item 6 "no family without a number"; round-4 item 4 "no family
number without a baseline anchor").

Families and shapes (reference-derived):
- ``tree``     decision-tree induction on the retarget shape
               (abandoned-cart retargeting, ``resource/retarget.py`` /
               ``tree/DataPartitioner.java`` two-jobs-per-level ↔ the
               in-memory frontier here); rows/s = rows / full-fit wall.
               Baseline: sklearn ``DecisionTreeClassifier.fit`` (same
               depth cap) on a subsample, single core.  Exhaustive
               multi-way search (the reference's semantics).
- ``tree_binary`` the same fit in ``split.search=binary`` mode —
               sorted-threshold binary splits over ordinal codes, the
               SAME candidate family sklearn scans, so its vs_baseline
               is the apples-to-apples ratio (device-resident split
               selection on both tree rows).  Runs
               ``tree.hist.mode=subtract`` by default (round 13
               TreeGraft: cumulative-histogram scoring +
               sibling-subtraction level tables, byte-identical trees);
               every tree row carries a ``hist_mode`` tag, a fresh
               matmul canary per pass, and a per-level phase breakdown
               so captures stay attributable.
- ``viterbi``  batch Viterbi decode, email-marketing-tutorial shape
               (``resource/tutorial_opt_email_marketing.txt:15-18``):
               80k sequences × 210 observations; seqs/s.  Baseline: the
               classic per-sequence numpy loop (init/iterate/backtrack,
               ``markov/ViterbiDecoder.java:66-143``).
- ``lr``       logistic-regression gradient iterations/s
               (``regress/LogisticRegressionJob.java:279-289`` ran ONE
               MR job per iteration; here one chained device step).
               Baseline: the identical full-batch numpy gradient step at
               the SAME shape, single core.
- ``cramer``   Cramér-index contingency aggregation rows/s
               (``explore/CramerCorrelation.java``).  Baseline:
               ``np.add.at`` scatter into all pair tables on a subsample.
- ``wordcount``host tokenize+count tokens/s (``text/WordCounter.java``).
               Baseline: the same tokenizer feeding ``collections.Counter``
               — BOTH run on host, so the honest ratio is ~1: this family
               has no device compute and says so instead of implying a
               TPU win (1-core-rig caveat in BASELINE.md).

Baselines are median-of-3 like bench.py's numpy NB+MI baseline, with
buffers hoisted out of the timed region.  Sync discipline for the device
side: chain dispatches, fetch once (BASELINE.md "Timing methodology").
Run ONE family per process:

  python -m benchmarks.family_bench --family viterbi
"""

import argparse
import json
import time

import numpy as np


def _median3(fn) -> float:
    vals = []
    for _ in range(3):
        t0 = time.perf_counter()
        n = fn()
        vals.append(n / (time.perf_counter() - t0))
    return float(np.median(vals))


# ---------------------------------------------------------------------------
# tree
# ---------------------------------------------------------------------------

def _tree_data(n: int):
    from avenir_tpu.core.encoding import DatasetEncoder
    from avenir_tpu.core.schema import FeatureSchema
    from avenir_tpu.datagen.retarget import (RETARGET_SCHEMA_JSON,
                                             generate_retarget)

    schema = FeatureSchema.from_json(RETARGET_SCHEMA_JSON)
    rows = generate_retarget(n, seed=9)
    enc = DatasetEncoder(schema)
    ds = enc.fit_transform(rows)
    is_cat = [f.is_categorical for f in schema.binned_feature_fields]
    return ds, is_cat


def bench_tree(passes: int, n: int = 2_000_000, baseline_sub: int = 100_000,
               search: str = "exhaustive", hist_mode: str = "direct"):
    from avenir_tpu.models import tree as dtree
    from avenir_tpu.utils.rig_canary import matmul_canary_ms

    ds, is_cat = _tree_data(n)
    builder = dtree.DecisionTree(algorithm="entropy", max_depth=4,
                                 max_split=3, split_search=search,
                                 hist_mode=hist_mode)
    vals = []
    canary_per_pass = []
    model = builder.fit(ds, is_categorical=is_cat)       # compile + warm
    for _ in range(passes):
        # rig-state canary BEFORE each tree pass (per the bench.py
        # convention): a slow pass with an inflated canary is rig
        # contention, a slow pass with a flat canary is a tree regression
        # — the attribution the hist-mode comparison needs
        canary_per_pass.append(matmul_canary_ms())
        t0 = time.perf_counter()
        model = builder.fit(ds, is_categorical=is_cat)
        vals.append(n / (time.perf_counter() - t0))
    # one extra instrumented fit for the per-level phase breakdown
    # (table-build / score+select / partition wall ms) — separate from the
    # timed passes because honest phase walls need a sync per phase
    probe = dtree.DecisionTree(algorithm="entropy", max_depth=4,
                               max_split=3, split_search=search,
                               hist_mode=hist_mode, collect_phase_stats=True)
    probe.fit(ds, is_categorical=is_cat)
    if search == "binary":
        note = ("apples-to-apples: sorted-threshold binary splits on "
                "ordinal codes — the SAME candidate family sklearn's "
                "DecisionTreeClassifier scans; selection runs on device")
        metric = "tree_binary_induction_rows_per_sec"
    else:
        note = ("this family evaluates the reference's EXHAUSTIVE "
                "multi-way/categorical candidate-split search "
                "(ClassPartitionGenerator.java:280-432) which sklearn "
                "does not perform; tree_binary is the apples-to-apples "
                "row — see BASELINE.md family table")
        metric = "tree_induction_rows_per_sec"
    return {"metric": metric, "unit": "rows/sec/chip",
            "n_rows": n, "max_depth": 4, "nodes": len(model.nodes),
            "shape": "retarget", "split_search": search,
            "selection_path": builder.selection,
            "hist_mode": hist_mode,
            "canary_per_pass_ms": [round(c, 2) for c in canary_per_pass],
            "level_phases": probe.level_stats,
            "baseline_rows_per_sec": round(baseline_tree(ds, baseline_sub), 1),
            "baseline": f"sklearn DecisionTreeClassifier.fit depth<=4 on "
                        f"{baseline_sub} rows, single core",
            "note": note}, vals


def bench_tree_binary(passes: int, n: int = 2_000_000,
                      baseline_sub: int = 100_000,
                      hist_mode: str = "subtract"):
    """`split.search=binary` benchmarked against the same sklearn anchor —
    both sides search sorted-threshold binary splits over ordinal codes.
    Defaults to `tree.hist.mode=subtract` (cumulative-histogram scoring +
    sibling-subtraction level tables — byte-identical trees, the
    TreeGraft fast path this row exists to measure; the `hist_mode` tag
    keeps every capture attributable)."""
    return bench_tree(passes, n, baseline_sub, search="binary",
                      hist_mode=hist_mode)


def baseline_tree(ds, sub: int) -> float:
    """Single-core sklearn fit rate on the same encoded rows (int codes as
    ordinal features — the standard one-machine counterpart; the reference
    itself had no single-core path, only MR jobs per level).  Returns 0.0
    when sklearn is absent (optional anchor — the expensive device
    measurement must never be lost to a missing baseline dep)."""
    try:
        from sklearn.tree import DecisionTreeClassifier
    except ImportError:                  # pragma: no cover
        return 0.0

    x = np.asarray(ds.codes[:sub], np.float32)
    y = np.asarray(ds.labels[:sub])
    return _median3(lambda: (DecisionTreeClassifier(
        max_depth=4, criterion="entropy").fit(x, y), sub)[1])


# ---------------------------------------------------------------------------
# viterbi
# ---------------------------------------------------------------------------

def _viterbi_model(s: int = 6, o: int = 12):
    rng = np.random.default_rng(0)
    log_a = np.log(rng.dirichlet(np.ones(s), size=s)).astype(np.float32)
    log_b = np.log(rng.dirichlet(np.ones(o), size=s)).astype(np.float32)
    log_pi = np.log(rng.dirichlet(np.ones(s))).astype(np.float32)
    return log_a, log_b, log_pi


def bench_viterbi(passes: int, r: int = 80_000, t: int = 210,
                  baseline_sub: int = 200):
    import jax
    import jax.numpy as jnp

    from avenir_tpu.models import markov as mk

    s, o = 6, 12                                         # email-mktg shape
    rng = np.random.default_rng(0)
    la, lb, lpi = _viterbi_model(s, o)
    log_a, log_b, log_pi = (jnp.asarray(a) for a in (la, lb, lpi))
    obs_np = rng.integers(0, o, size=(r, t), dtype=np.int32)
    obs = jnp.asarray(obs_np)
    decode = jax.jit(mk._viterbi_batch)
    out = decode(log_a, log_b, log_pi, obs)
    np.asarray(out[0, 0])                                # compile + warm
    vals = []
    for _ in range(passes):
        bias = jnp.int32(0)
        t0 = time.perf_counter()
        for _ in range(3):                               # chained dispatches
            out = decode(log_a, log_b, log_pi, obs + bias * 0)
            bias = out[0, 0] * 0
        np.asarray(out[0, 0])
        vals.append(3 * r / (time.perf_counter() - t0))
    base = baseline_viterbi(la, lb, lpi, obs_np[:baseline_sub])
    return {"metric": "viterbi_decode_seqs_per_sec", "unit": "seqs/sec/chip",
            "n_seqs": r, "seq_len": t, "n_states": s,
            "shape": "email_marketing_80kx210",
            "baseline_seqs_per_sec": round(base, 1),
            "baseline": f"per-sequence numpy Viterbi loop on {baseline_sub} "
                        f"seqs, single core"}, vals


def baseline_viterbi(log_a, log_b, log_pi, obs) -> float:
    """Classic per-sequence decode: numpy vectorized over states only —
    the per-record loop shape of ViterbiDecoder.java:66-143."""
    def run():
        for o in obs:
            delta = log_pi + log_b[:, o[0]]
            ptrs = np.empty((len(o) - 1, len(log_pi)), np.int64)
            for i in range(1, len(o)):
                cand = delta[:, None] + log_a
                ptrs[i - 1] = np.argmax(cand, axis=0)
                delta = cand[ptrs[i - 1], np.arange(len(log_pi))] \
                    + log_b[:, o[i]]
            state = int(np.argmax(delta))
            for i in range(len(o) - 2, -1, -1):          # backtrack
                state = int(ptrs[i][state])
        return len(obs)

    return _median3(run)


# ---------------------------------------------------------------------------
# lr
# ---------------------------------------------------------------------------

def bench_lr(passes: int, n: int = 4_000_000, d: int = 24, iters: int = 20,
             baseline_iters: int = 3):
    import jax
    import jax.numpy as jnp

    from avenir_tpu.models import logistic as lg

    rng = np.random.default_rng(0)
    x_np = rng.random((n, d), np.float32)
    y_np = (rng.random(n) < 0.5).astype(np.float32)
    x = jnp.asarray(x_np)
    y = jnp.asarray(y_np)
    w = jnp.zeros(d, jnp.float32)
    step = jax.jit(lg._grad_step)
    nn = jnp.float32(n)
    w1 = step(w, x, y, nn, jnp.float32(0.5), jnp.float32(0.01))
    np.asarray(w1[0])                                    # compile + warm
    vals = []
    for _ in range(passes):
        wi = w
        t0 = time.perf_counter()
        for _ in range(iters):                           # natural chain via w
            wi = step(wi, x, y, nn, jnp.float32(0.5), jnp.float32(0.01))
        np.asarray(wi[0])
        vals.append(iters / (time.perf_counter() - t0))
    base = baseline_lr(x_np, y_np, baseline_iters)
    return {"metric": "lr_iterations_per_sec", "unit": "iters/sec/chip",
            "n_rows": n, "n_features": d,
            "baseline_iters_per_sec": round(base, 3),
            "baseline": f"identical full-batch numpy gradient step at the "
                        f"same [{n}, {d}] shape, single core",
            "note": "one iteration == one full-batch gradient step == one "
                    "MR job of the reference"}, vals


def baseline_lr(x: np.ndarray, y: np.ndarray, iters: int) -> float:
    """The SAME full-batch gradient step in single-core numpy at the same
    shape — like-for-like per-iteration cost (the reference additionally
    paid a whole MR job submission per iteration, which this baseline
    charitably omits)."""
    w = np.zeros(x.shape[1], np.float32)

    def run():
        nonlocal w
        for _ in range(iters):
            p = 1.0 / (1.0 + np.exp(-(x @ w)))
            w = w + np.float32(0.5) * ((x.T @ (y - p)) / len(x)
                                       - np.float32(0.01) * w)
        return iters

    return _median3(run)


# ---------------------------------------------------------------------------
# cramer
# ---------------------------------------------------------------------------

def bench_cramer(passes: int, n: int = 16_000_000, f: int = 10, b: int = 20,
                 baseline_sub: int = 200_000):
    import jax.numpy as jnp

    from avenir_tpu.ops import pallas_hist

    rng = np.random.default_rng(0)
    codes_np = rng.integers(0, b, size=(f, n), dtype=np.int32)
    codes_t = jnp.asarray(codes_np)
    zeros = jnp.zeros(n, jnp.int32)
    kernel = pallas_hist.use_kernel(f, b, 1)

    def step(bias):
        # all [B, B] contingency tables at once: the one-class gram —
        # exactly CategoricalCorrelation.fit's single-TPU fast path
        return pallas_hist.cooc_counts_cols(codes_t, zeros + bias, b, 1)

    out = step(jnp.int32(0))
    np.asarray(out[0, 0])
    vals = []
    for _ in range(passes):
        bias = jnp.int32(0)
        t0 = time.perf_counter()
        for _ in range(3):
            out = step(bias)
            bias = (out[0, 0] * 0).astype(jnp.int32)
        np.asarray(out[0, 0])
        vals.append(3 * n / (time.perf_counter() - t0))
    base = baseline_cramer(codes_np[:, :baseline_sub], b)
    return {"metric": "cramer_rows_per_sec", "unit": "rows/sec/chip",
            "n_rows": n, "n_features": f, "cardinality": b,
            "n_pairs": f * (f - 1) // 2, "kernel_path": bool(kernel),
            "plan": list(pallas_hist.plan(f, b, 1)),
            "baseline_rows_per_sec": round(base, 1),
            "baseline": f"np.add.at contingency scatter over all "
                        f"{f * (f - 1) // 2} pairs on {baseline_sub} rows, "
                        f"single core",
            "note": "rides the int8-only fmaj gram since round 7: plan() "
                    "routes the one-class shape to the broadcast-expand "
                    "layout that carries NB+MI (wp 384 vs jmaj's 256 — the "
                    "jmaj int32 expand, not the dot, was the r05 wall)"}, vals


def baseline_cramer(codes: np.ndarray, b: int) -> float:
    """Single-core np.add.at scatter into every pair's [B, B] table —
    the per-record hashmap-increment cost model of
    CramerCorrelation.java:161-182 (buffer hoisted)."""
    f, n = codes.shape
    pairs = [(i, j) for i in range(f) for j in range(i + 1, f)]
    buf = np.zeros((b, b))

    def run():
        for i, j in pairs:
            np.add.at(buf, (codes[i], codes[j]), 1)
        return n

    return _median3(run)


# ---------------------------------------------------------------------------
# wordcount
# ---------------------------------------------------------------------------

def bench_wordcount(passes: int):
    from avenir_tpu.text.analyzer import tokenize

    rng = np.random.default_rng(0)
    vocab = [f"word{i}" for i in range(5000)]
    lines = [" ".join(rng.choice(vocab, size=12)) for _ in range(20_000)]
    n_tokens = sum(len(tokenize(s)) for s in lines)
    vals = []
    for _ in range(passes):
        t0 = time.perf_counter()
        counts: dict = {}
        for s in lines:
            for tok in tokenize(s):
                counts[tok] = counts.get(tok, 0) + 1
        vals.append(n_tokens / (time.perf_counter() - t0))
    # baseline: the same tokenizer into collections.Counter — both sides
    # are host code, so the ratio is ~1 BY DESIGN: this family has no
    # device compute and the number says so honestly
    from collections import Counter

    def run():
        c: Counter = Counter()
        for s in lines:
            c.update(tokenize(s))
        return n_tokens

    base = _median3(run)
    return {"metric": "wordcount_tokens_per_sec", "unit": "tokens/sec",
            "n_tokens": n_tokens,
            "baseline_tokens_per_sec": round(base, 1),
            "baseline": "same tokenizer into collections.Counter, single "
                        "core (host-vs-host: ratio ~1 by design)",
            "note": "HOST-bound (tokenizer); 1-core dev rig number is a "
                    "lower bound, scales with host cores"}, vals


FAMILIES = {"tree": bench_tree, "tree_binary": bench_tree_binary,
            "viterbi": bench_viterbi, "lr": bench_lr,
            "cramer": bench_cramer, "wordcount": bench_wordcount}

# reduced shapes for the driver artifact (bench.py embeds these; ~10 s
# budget per family including its baseline, same chained-sync discipline)
REDUCED = {
    # tree keeps 1M rows: per-level dispatch overhead amortizes over N,
    # and at 300k rows it dominated (447k rows/s where the 2M shape
    # measures 1.36M — same dispatch-floor distortion as LR's); with
    # device-resident selection the per-level cost is one dispatch + a
    # KB fetch instead of the full-table fetch + host fold
    "tree": dict(n=1_000_000, baseline_sub=50_000),
    "tree_binary": dict(n=1_000_000, baseline_sub=50_000),
    "viterbi": dict(r=16_000, t=210, baseline_sub=100),
    # LR keeps the full 4M-row shape: at 1M rows the ~11 ms device
    # dispatch floor dominates and the ratio collapses to ~1.2× while the
    # representative full-batch shape measures ~3-5× (upload cost is
    # one-time setup, not per-pass)
    "lr": dict(n=4_000_000, d=24, iters=10, baseline_iters=2),
    "cramer": dict(n=4_000_000, baseline_sub=100_000),
}


def family_line(name: str, passes: int = 4, reduced: bool = False) -> dict:
    """One family's JSON-ready dict: median value, pass list, measured
    single-core baseline and the vs_baseline ratio."""
    kwargs = REDUCED.get(name, {}) if reduced else {}
    line, vals = FAMILIES[name](passes, **kwargs)
    line["value"] = round(float(np.median(vals)), 1)
    line["passes"] = [round(v, 1) for v in vals]
    base_key = next((k for k in line if k.startswith("baseline_")
                     and k.endswith("_per_sec")), None)
    if base_key and line[base_key]:
        line["vs_baseline"] = round(line["value"] / line[base_key], 2)
    return line


def families_summary(passes: int = 2) -> dict:
    """Compact per-family object for bench.py's driver artifact: reduced
    shapes, value + vs_baseline + baseline rate per family (wordcount is
    excluded — host-bound, ratio ~1 by design, see bench_wordcount).
    ``tree`` is the exhaustive multi-way search, ``tree_binary`` the
    sklearn-comparable binary-threshold mode; both tag the selection
    path so artifacts attribute gains to device-resident selection."""
    out = {}
    for name in ("tree", "tree_binary", "viterbi", "lr", "cramer"):
        line = family_line(name, passes=passes, reduced=True)
        # level_phases rides into the driver artifact: the tree rows pay
        # one instrumented fit for it, so dropping it here would waste
        # that fit — and the per-level table/select/partition ms is the
        # attribution the hist-mode comparison needs
        out[name] = {k: line[k] for k in
                     ("metric", "value", "unit", "vs_baseline", "note",
                      "selection_path", "split_search", "hist_mode",
                      "canary_per_pass_ms", "level_phases")
                     if k in line}
        bk = next((k for k in line if k.startswith("baseline_")
                   and k.endswith("_per_sec")), None)
        if bk:
            out[name][bk] = line[bk]
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", choices=sorted(FAMILIES), required=True)
    ap.add_argument("--passes", type=int, default=4)
    ap.add_argument("--reduced", action="store_true",
                    help="driver-artifact shapes (bench.py's families object)")
    args = ap.parse_args()
    print(json.dumps(family_line(args.family, args.passes, args.reduced)))


if __name__ == "__main__":
    main()
