#!/usr/bin/env python
"""Decompose the kNN tournament kernel's per-call cost on real shapes.

Variants of `ops/pallas_knn._knn_tourney_kernel` at the production shapes
(4096 queries × 1M refs, bf16 packed width): ``dotonly`` (MXU pass +
trivial output), ``dotkey`` (adds bitcast key formation, no tournament),
``full`` (the shipped kernel).  One variant per process run, chained
dispatches, host-fetch sync — quantifies how much of the ~22 ms call the
tournament extraction actually costs TODAY (the docs/architecture.md
ceiling note cites this probe).

  python -m benchmarks.knn_decomp_probe --variant full

Round-4 result: INCONCLUSIVE on the dev rig — pass spread 29–110 ms on
identical calls (dotonly even measured slower than dotkey, which is
physically impossible), i.e. the rig's ±20%+ drift exceeds any
extraction-pass delta this probe could resolve.  The probe is kept as
the measurement method for a quieter rig; the shipped kernel's floor
analysis stands on the round-3 bisection (docs/architecture.md
"ceilings").
"""

import argparse
import functools
import json
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; module-local alias,
# same as ops/pallas_hist.py
COMPILER_PARAMS = (pltpu.CompilerParams if hasattr(pltpu, "CompilerParams")
                   else pltpu.TPUCompilerParams)


from avenir_tpu.ops import pallas_knn as pk


def _kernel(a_ref, b_ref, k1_out, k2_out, k3_out, *, nbp, variant):
    j = pl.program_id(1)
    d2v = jax.lax.dot_general(
        a_ref[:], b_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if variant == "dotonly":
        r = jnp.min(d2v, axis=1, keepdims=True).astype(jnp.int32)
        k1_out[:] = jnp.broadcast_to(r, k1_out.shape)
        k2_out[:] = k1_out[:]
        k3_out[:] = k1_out[:]
        return
    lane = jax.lax.broadcasted_iota(jnp.int32, (pk.TM, pk.TB), 1)
    col = lane & jnp.int32(pk.SEG - 1)
    di = jax.lax.bitcast_convert_type(jnp.maximum(d2v, 0.0), jnp.int32)
    key = (di & jnp.int32(~(pk.SEG - 1))) | col
    if variant == "dotkey":
        r = jnp.min(key, axis=1, keepdims=True)
        k1_out[:] = jnp.broadcast_to(r, k1_out.shape)
        k2_out[:] = k1_out[:]
        k3_out[:] = k1_out[:]
        return
    # full: replicate the shipped tournament
    nseg = pk.TB // pk.SEG
    outlane = jax.lax.broadcasted_iota(jnp.int32, (pk.TM, nbp), 1)
    for s in range(nseg):
        seg = key[:, s * pk.SEG:(s + 1) * pk.SEG]
        w = pk.SEG // 2
        a, b = seg[:, :w], seg[:, w:]
        m1 = jnp.minimum(a, b)
        m2 = jnp.maximum(a, b)
        w //= 2
        a1, b1 = m1[:, :w], m1[:, w:]
        a2, b2 = m2[:, :w], m2[:, w:]
        hi1 = jnp.maximum(a1, b1)
        lo2 = jnp.minimum(a2, b2)
        m1 = jnp.minimum(a1, b1)
        m2 = jnp.minimum(hi1, lo2)
        m3 = jnp.maximum(lo2, hi1)
        while w > 128:
            w //= 2
            a1, b1 = m1[:, :w], m1[:, w:]
            a2, b2 = m2[:, :w], m2[:, w:]
            a3, b3 = m3[:, :w], m3[:, w:]
            hi1 = jnp.maximum(a1, b1)
            lo2 = jnp.minimum(a2, b2)
            hi2 = jnp.maximum(a2, b2)
            m1 = jnp.minimum(a1, b1)
            m2 = jnp.minimum(hi1, lo2)
            m3 = jnp.minimum(jnp.minimum(jnp.maximum(hi1, lo2), hi2),
                             jnp.minimum(a3, b3))
        t1 = jnp.min(m1, axis=1)
        em = jnp.where(m1 == t1[:, None], m2, m1)
        t2 = jnp.min(em, axis=1)
        em2 = jnp.where(em == t2[:, None],
                        jnp.where(m1 == t1[:, None], m3, m2), em)
        t3 = jnp.min(em2, axis=1)
        sel = outlane == (j * nseg + s)
        k1_out[:] = jnp.where(sel, t1[:, None], k1_out[:])
        k2_out[:] = jnp.where(sel, t2[:, None], k2_out[:])
        k3_out[:] = jnp.where(sel, t3[:, None], k3_out[:])


@functools.partial(jax.jit, static_argnames=("variant",))
def run(a_mat, b_mat, variant):
    m, n = a_mat.shape[0], b_mat.shape[0]
    nb = n // pk.TB
    nseg = n // pk.SEG
    nbp = pk._round_up(nseg, 128)
    spec = pl.BlockSpec((pk.TM, nbp), lambda i, j: (i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_kernel, nbp=nbp, variant=variant),
        grid=(m // pk.TM, nb),
        in_specs=[
            pl.BlockSpec((pk.TM, a_mat.shape[1]), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((pk.TB, b_mat.shape[1]), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[spec] * 3,
        out_shape=[jax.ShapeDtypeStruct((m, nbp), jnp.int32)] * 3,
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
    )(a_mat, b_mat)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--variant", choices=["dotonly", "dotkey", "full"],
                    required=True)
    ap.add_argument("--m", type=int, default=4096)
    ap.add_argument("--n", type=int, default=1_048_576)
    ap.add_argument("--width", type=int, default=128)
    args = ap.parse_args()
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.random((args.m, args.width), np.float32)
                    .astype(np.float16)).astype(jnp.bfloat16)
    b = jnp.asarray(rng.random((args.n, args.width), np.float32)
                    .astype(np.float16)).astype(jnp.bfloat16)
    o = run(a, b, args.variant)
    np.asarray(o[0][0, 0])
    vals = []
    for _ in range(4):
        t0 = time.perf_counter()
        bias = jnp.bfloat16(0)
        for _ in range(4):
            o = run(a + bias, b, args.variant)
            bias = (o[0][0, 0] * 0).astype(jnp.bfloat16)
        np.asarray(o[0][0, 0])
        vals.append((time.perf_counter() - t0) / 4 * 1e3)
    print(json.dumps({"variant": args.variant,
                      "ms_per_call_median": round(float(np.median(vals)), 2),
                      "passes_ms": [round(v, 2) for v in vals]}))


if __name__ == "__main__":
    main()
