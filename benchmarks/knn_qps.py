#!/usr/bin/env python
"""Secondary benchmark: kNN QPS at 1M reference vectors (BASELINE.json's
second driver metric). Prints one JSON line. The primary benchmark remains
bench.py (NB+MI pipeline rows/sec/chip).

Workload shape: 6 binned/categorical + 8 continuous attributes (elearn-like
mixed records), k=10, exact top-k (verified against a numpy oracle in
tests/test_knn.py). The engine is models/knn.nearest_neighbors: one compiled
lax.scan over resident device tiles fusing distance matmuls with a running
top-k merge, so the M×N distance matrix never materializes and the reference
set uploads once.
"""

import json
import time

import numpy as np

from avenir_tpu.core.encoding import EncodedDataset
from avenir_tpu.models import knn as mknn


def make_ds(rng, n, f=6, fc=8, nb=10):
    return EncodedDataset(
        codes=rng.integers(0, nb, size=(n, f)).astype(np.int32),
        cont=rng.normal(size=(n, fc)).astype(np.float32),
        labels=rng.integers(0, 2, size=n).astype(np.int32),
        ids=None, n_bins=np.full(f, nb, np.int32), class_values=["a", "b"],
        binned_ordinals=list(range(f)), cont_ordinals=list(range(f, f + fc)))


def main():
    rng = np.random.default_rng(0)
    n_refs, n_queries, k = 1_000_000, 4096, 10
    model = mknn.fit_knn(make_ds(rng, n_refs))
    test = make_ds(rng, n_queries)

    d_ex, i_ex = mknn.nearest_neighbors(model, test, k=k)   # compile + upload
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        mknn.nearest_neighbors(model, test, k=k)
        dt = time.perf_counter() - t0
        best = min(best or dt, dt)

    # flag-gated approximate mode (knn.search.mode=approx): report its QPS
    # and measured recall alongside the exact headline number
    _, i_ap = mknn.nearest_neighbors(model, test, k=k, mode="approx")
    best_ap = None
    for _ in range(3):
        t0 = time.perf_counter()
        mknn.nearest_neighbors(model, test, k=k, mode="approx")
        dt = time.perf_counter() - t0
        best_ap = min(best_ap or dt, dt)
    recall = float(np.mean([len(set(i_ex[q]) & set(i_ap[q])) / k
                            for q in range(n_queries)]))

    print(json.dumps({
        "metric": "knn_qps_1m_refs",
        "value": round(n_queries / best, 1),
        "unit": "queries/sec/chip",
        "k": k,
        "n_refs": n_refs,
        "approx_qps": round(n_queries / best_ap, 1),
        "approx_recall": round(recall, 4),
    }))


if __name__ == "__main__":
    main()
