#!/usr/bin/env python
"""Secondary benchmark: kNN QPS at 1M reference vectors (BASELINE.json's
second driver metric). Prints one JSON line. The primary benchmark remains
bench.py (NB+MI pipeline rows/sec/chip).

Workload shape: 6 binned/categorical + 8 continuous attributes (elearn-like
mixed records), k=10, exact top-k (verified against a numpy oracle in
tests/test_knn.py; ``--verify`` runs the oracle check on-chip right here).

Two rates are reported:
- ``value`` (headline): PIPELINED throughput — batches of 4096 queries
  stream through the fused single-dispatch search
  (ops/pallas_knn.search_fused) with one final sync. This is the serving
  shape: the tunnel/dispatch round-trip (~100 ms on the dev rig, measured)
  amortizes across in-flight batches.
- ``single_shot_qps``: one synchronized call including every round trip —
  the latency floor a cold caller sees.

Roofline fields (utils/roofline.py): the candidate kernel's matmul work is
2·M·N·K FLOPs; ``mfu_pct`` is reported against the detected chip's bf16
peak. Round 3's segment key-tournament kernel reaches ~17-24% MFU with the
distance dot itself at the bare-XLA matmul bound; the remaining gap is the
exact top-2+bound extraction's materialized VMEM passes (BASELINE.md kNN
notes). Default batch is 16384 queries (throughput serving shape; override
with AVENIR_KNN_BATCH).
"""

import json
import sys
import time

import numpy as np

from avenir_tpu.core.encoding import EncodedDataset
from avenir_tpu.models import knn as mknn
from avenir_tpu.utils.roofline import chip_peaks, mfu_fields


def make_ds(rng, n, f=6, fc=8, nb=10):
    return EncodedDataset(
        codes=rng.integers(0, nb, size=(n, f)).astype(np.int32),
        cont=rng.normal(size=(n, fc)).astype(np.float32),
        labels=rng.integers(0, 2, size=n).astype(np.int32),
        ids=None, n_bins=np.full(f, nb, np.int32), class_values=["a", "b"],
        binned_ordinals=list(range(f)), cont_ordinals=list(range(f, f + fc)))


def verify_on_chip(model, test, k, d, n_check=256, row_chunk=16):
    """Exact-vs-oracle certificate on the compiled kernel (hardware path):
    ``d`` (the [M, k] distances an earlier nearest_neighbors call already
    produced) must match a float64 numpy oracle on the first ``n_check``
    rows. The oracle runs in ``row_chunk``-row slices — a whole-batch
    broadcast against 1M references would allocate a ~16 GB float64 temp."""
    cq_all = mknn._normalize01(test.cont[:n_check], model.cont_lo,
                               model.cont_hi)
    cr = model.cont01().astype(np.float64)
    total = test.codes.shape[1] + test.cont.shape[1]
    for r0 in range(0, n_check, row_chunk):
        cq = cq_all[r0:r0 + row_chunk].astype(np.float64)
        codes_q = test.codes[r0:r0 + row_chunk]
        mism = (codes_q[:, None, :] != model.codes[None, :, :]).sum(-1)
        d2 = mism + ((cq[:, None, :] - cr[None, :, :]) ** 2).sum(-1)
        od = np.sqrt(np.sort(d2, axis=1)[:, :k] / total)
        got = d[r0:r0 + row_chunk]
        if not np.allclose(got, od, atol=1e-5):
            bad = np.max(np.abs(got - od))
            raise AssertionError(
                f"on-chip kNN mismatch vs oracle: max |Δd|={bad}")
    return True


def measure(verify: bool = False, n_queries: int | None = None,
            quick: bool = False) -> dict:
    """Run the kNN measurement and return the JSON-line dict.

    Shared by this benchmark's CLI and bench.py (which embeds the result
    as a nested object so the driver's one-line contract holds).
    ``quick`` skips the approx-engine comparison (bench.py embeds only the
    primary QPS + verification)."""
    import os
    from avenir_tpu.utils.rig_canary import matmul_canary_ms, knn_dot_canary_ms
    canary_ms = matmul_canary_ms()           # rig state BEFORE any kNN work
    rng = np.random.default_rng(0)
    n_refs, k = 1_000_000, 10
    if n_queries is None:
        n_queries = int(os.environ.get("AVENIR_KNN_BATCH", "16384"))
    model = mknn.fit_knn(make_ds(rng, n_refs))
    test = make_ds(rng, n_queries)

    d_warm, _ = mknn.nearest_neighbors(model, test, k=k)   # compile + upload
    verified = verify_on_chip(model, test, k, d_warm) if verify else None

    # single-shot latency (cold-caller view: every round trip included)
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        mknn.nearest_neighbors(model, test, k=k)
        dt = time.perf_counter() - t0
        best = min(best or dt, dt)

    # pipelined throughput: stream batches through the fused search, sync
    # only at the end — per-pass values are all recorded so the driver
    # artifact documents the spread.  Query batches are STAGED ON DEVICE
    # before timing (round 5): with numpy operands each call re-uploads
    # ~1.3 MB through the tunnel, and that upload path degrades with
    # process age (the round-2 "long-lived process" artifact) — embedded
    # bench.py runs measured 100k QPS with HEALTHY device canaries while
    # standalone runs measured 200k the same hour, and staging isolates
    # the kernel from that rig artifact.  On real TPU hosts queries arrive
    # through DMA-capable infeed; ``single_shot_qps`` still includes the
    # full upload + round trip.
    import jax.numpy as jnp

    from avenir_tpu.ops import pallas_knn
    nb = int(model.n_bins.max())
    r_mat, n = model.device_packed(nb)
    cr_dev, cx_dev = model.device_rerank_arrays()
    # bare distance-dot canary against the ACTUAL packed reference buffer:
    # the measured lower bound the fused kernel is judged against — if QPS
    # moves while this stays put, the kernel regressed; if both move
    # together, the rig did (docs/architecture.md "ceilings")
    dot_ms = knn_dot_canary_ms(batch=n_queries, refs=r_mat,
                               width=r_mat.shape[1])
    batches = []
    for i in range(6):
        t = make_ds(rng, n_queries)
        batches.append((jnp.asarray(t.codes),
                        jnp.asarray(mknn._normalize01(
                            t.cont, model.cont_lo, model.cont_hi))))
    total_attrs = 6 + 8
    outs = [pallas_knn.search_fused(c, x + np.float32(0.0), r_mat, cr_dev,
                                    cx_dev, n, nb, k, total_attrs)
            for c, x in batches[:1]]
    np.asarray(outs[-1][0])                          # warm + sync (chained
    # form: the timed loop adds a bias scalar to the cont operand)
    passes = []
    for _ in range(4):
        bias = np.float32(0.0)
        t0 = time.perf_counter()
        for c, x in batches:
            # dependency chain through the tiny cont operand: the final
            # fetch is then a barrier for every batch, not just the last
            o = pallas_knn.search_fused(c, x + bias, r_mat, cr_dev, cx_dev,
                                        n, nb, k, total_attrs)
            bias = o[0][0, 0] * 0
        np.asarray(o[0])
        passes.append(len(batches) * n_queries / (time.perf_counter() - t0))
    passes = passes[1:]                  # first timed pass still warms
    pipelined = float(np.median(passes))

    line = {
        "metric": "knn_qps_1m_refs",
        "value": round(pipelined, 1),
        "unit": "queries/sec/chip",
        "k": k,
        "batch": n_queries,
        "n_refs": n_refs,
        "pipelined_passes_qps": [round(p, 1) for p in passes],
        "single_shot_qps": round(n_queries / best, 1),
        "canary_matmul_4096_bf16_ms": round(canary_ms, 2),
        "canary_knn_dot_ms": round(dot_ms, 2),
    }
    if verified is not None:
        line["verified_vs_oracle"] = verified

    if not quick:
        # approx ENGINE comparison: nearest_neighbors(mode="approx") routes
        # to the fused exact path whenever it applies (faster AND exact), so
        # measure the approx_min_k engine directly — its numbers matter for
        # the configurations the kernel cannot serve
        d_ex, i_ex = mknn.nearest_neighbors(model, test, k=k)
        _, i_ap = mknn._nearest_neighbors_xla(model, test, k, approx=True)
        best_ap = None
        for _ in range(3):
            t0 = time.perf_counter()
            mknn._nearest_neighbors_xla(model, test, k, approx=True)
            dt = time.perf_counter() - t0
            best_ap = min(best_ap or dt, dt)
        recall = float(np.mean([len(set(i_ex[q]) & set(i_ap[q])) / k
                                for q in range(n_queries)]))
        line["approx_qps"] = round(n_queries / best_ap, 1)
        line["approx_recall"] = round(recall, 4)

    # roofline: candidate-kernel matmul work per batch
    width = r_mat.shape[1]
    m_pad = pallas_knn._round_up(max(n_queries, pallas_knn.TM), pallas_knn.TM)
    flops_per_batch = 2.0 * r_mat.shape[0] * m_pad * width
    batch_dt = n_queries / pipelined
    line.update(mfu_fields(flops=flops_per_batch, dt=batch_dt,
                           peaks=chip_peaks()))
    return line


def main():
    verify = "--verify" in sys.argv
    print(json.dumps(measure(verify=verify)))


if __name__ == "__main__":
    main()
