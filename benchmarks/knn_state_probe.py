#!/usr/bin/env python
"""kNN slow-mode bisection (round 5): does running the NB+MI pipeline
before the kNN measurement change kNN QPS?

Round 4's driver artifact captured a 103k QPS kNN median where round 3's
driver captured 163-187k on identical kernel code.  In bench.py the kNN
measurement is EMBEDDED: it runs after the ~1.4 GB NB+MI operands are
allocated, used and freed, so the reference set is uploaded into a
post-churn HBM state — a live fragmentation/tiling hypothesis.  This probe
isolates that variable in a fresh process per condition:

- ``--mode fresh``      : canaries + kNN measurement only (standalone).
- ``--mode after_nbmi`` : replicate bench.py's sequence first — upload the
  16M-row codes/labels, run two chained NB+MI kernel passes, free the
  operands — then the identical kNN measurement.

Each run prints one JSON line with the matmul canary (rig state), the bare
distance-dot canary against the actual packed reference buffer (kernel
lower bound), and the pipelined pass list.  Run interleaved
(fresh, after_nbmi, fresh, after_nbmi, ...) so the ±20% rig drift
(BASELINE.md "Timing methodology") averages out of the comparison:

    for m in fresh after_nbmi fresh after_nbmi; do
        python benchmarks/knn_state_probe.py --mode $m; done

Interpretation: if after_nbmi's QPS tracks fresh's (given matching
canaries), the round-4 collapse was rig-side; if after_nbmi is
consistently slower with matching matmul canaries, the memory-state
hypothesis is confirmed and the dot canary says whether the dot or the
extraction passes absorb it.
"""

import argparse
import json
import time

import numpy as np


def run_nbmi_phase():
    """bench.py's NB+MI sequence at full operand scale: upload, two
    chained kernel passes, free. Returns the phase's rows/sec for context."""
    import jax.numpy as jnp
    from avenir_tpu.ops import pallas_hist
    from avenir_tpu.utils.profiling import device_sync

    n_classes, n_bins, n_feat = 2, 12, 11
    chunk = 16_000_000
    rng = np.random.default_rng(0)
    codes = rng.integers(0, n_bins, size=(chunk, n_feat), dtype=np.int32)
    labels = rng.integers(0, n_classes, size=chunk, dtype=np.int32)
    pair_idx = np.array([(i, j) for i in range(n_feat)
                         for j in range(i + 1, n_feat)], np.int32)
    step, chain_scalar, kernel_path = pallas_hist.chunk_pipeline(
        n_feat, n_bins, n_classes, pair_idx[:, 0], pair_idx[:, 1],
        columnar=True)
    dcodes = jnp.asarray(np.ascontiguousarray(codes.T)) if kernel_path \
        else jnp.asarray(codes)
    dlabels = jnp.asarray(labels)
    device_sync(step(dcodes, dlabels + jnp.int32(0)))
    t0 = time.perf_counter()
    bias = jnp.int32(0)
    for _ in range(2):
        out = step(dcodes, dlabels + bias)
        bias = chain_scalar(out)
    device_sync(out)
    rate = 2 * chunk / (time.perf_counter() - t0)
    del dcodes, dlabels, out
    return float(rate), bool(kernel_path)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["fresh", "after_nbmi"], required=True)
    args = ap.parse_args()

    line = {"probe": "knn_state", "mode": args.mode}
    if args.mode == "after_nbmi":
        nbmi_rate, kp = run_nbmi_phase()
        line["nbmi_rows_per_sec"] = round(nbmi_rate, 1)
        line["nbmi_kernel_path"] = kp

    from benchmarks.knn_qps import measure
    knn = measure(verify=False, quick=True)
    for kf in ("value", "pipelined_passes_qps", "single_shot_qps",
               "canary_matmul_4096_bf16_ms", "canary_knn_dot_ms"):
        line[kf] = knn[kf]
    print(json.dumps(line))


if __name__ == "__main__":
    main()
