#!/usr/bin/env python
"""ShardGraft multichip benchmark: the mesh-sharded SharedScan fold
measured per device count — per-chip + aggregate rows/sec and scaling
efficiency — with byte-identity to the single-chip fold ASSERTED before
any rate is recorded (the acceptance oracle rides the artifact).

Runs the nb_mi-shaped fold (NaiveBayes + MutualInfo consumers — the
BASELINE.md band's workload) over a fixed synthetic chunk stream:

- ``single_chip``: today's unsharded path, the byte-identity oracle and
  the band anchor;
- one section per device count in {1, 2, 4, …, all attached}: the fused
  ``shard_map`` dispatch (per-device Pallas gram + class counts + moments,
  psum'd in-kernel), chunks ballast-padded to their pow-2 shard target and
  placed round-robin over the data axis by the same staging the jobs use;
- ``scaling_efficiency`` = aggregate(d) / (aggregate(1 shard) · d) — the
  near-linear-scaling figure ROADMAP item 1 asks for on 8 real chips;
- a quantized row (``shard.allreduce.quantized``) for the largest device
  count, exactness MEASURED and reported (bit-exact when per-device
  partial cells fit int8 — true for the host-mesh chunk slices, not for
  the TPU-size chunks; max bin-count deviation is published either way).

On a host with fewer devices than 8 and no TPU, the harness re-execs
itself once with ``--xla_force_host_platform_device_count=8`` so the
scaling SHAPE is exercisable anywhere; host-mesh folds run the Pallas
interpreter, so those rates measure the harness, not the kernel —
``interpret_mode: true`` in the artifact flags them.  A fresh matmul
canary rides each section per the PR-2 convention (a loaded rig indicts
itself, not the scan).  One JSON object on stdout.

CrossGraft (``--nprocs N``): the REAL multi-process capture — the
harness drives itself through the fleet launcher
(``avenir_tpu.launch.launch_local``): N OS processes ×
``--devices-per-proc`` devices each join one jax-distributed fleet, the
global (proc × data) SharedScan fold runs the hierarchical psum
dispatch, byte-identity to each worker's local unsharded fold is
asserted BEFORE any rate is recorded, and the artifact publishes
aggregate + per-process rates, ``scaling_efficiency`` against the
1-process local-mesh fold at the same per-process width, and the
quantized cross-host hop's measured deviation — the first non-stub row
of BASELINE.md's MULTICHIP table.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

N_FEAT = 8
N_BINS = 8
N_CLASSES = 2
N_CONT = 2
_FORCED = "AVENIR_MULTICHIP_FORCED"


def _maybe_force_host_mesh():
    """Single-device CPU container → re-exec once with an 8-device host
    mesh (the tier-1 trick) so the scaling harness has shards to measure;
    a TPU or pre-forced environment passes straight through."""
    if os.environ.get(_FORCED):
        return
    import jax

    if len(jax.devices()) > 1 or jax.devices()[0].platform != "cpu":
        return
    env = dict(os.environ)
    env[_FORCED] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    # the child resolves avenir_tpu the way the parent did: repo root
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)],
              env)


def gen_data(n_rows, seed=29):
    rng = np.random.default_rng(seed)
    codes = rng.integers(0, N_BINS, size=(n_rows, N_FEAT)).astype(np.int32)
    # 1/16-grid continuous values: shard-partial f32 sums are exact, so
    # the sharded moments match the single-chip fold byte-for-byte
    cont = (rng.integers(0, 16, size=(n_rows, N_CONT)) / 16.0).astype(
        np.float32)
    labels = rng.integers(0, N_CLASSES, size=n_rows).astype(np.int32)
    return codes, cont, labels


def _multiproc_worker(args):
    """One fleet worker of the ``--nprocs`` capture: join via the env the
    launcher wrote, fold the SAME chunk stream through the global mesh,
    assert byte-identity to the local unsharded oracle, measure, and let
    process 0 write the artifact JSON to ``--out``."""
    from avenir_tpu.launch import join_from_env

    idx = join_from_env()
    import jax

    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.core.encoding import EncodedDataset
    from avenir_tpu.parallel.mesh import make_mesh
    from avenir_tpu.parallel.shard import ShardSpec
    from avenir_tpu.pipeline import scan
    from avenir_tpu.utils.metrics import Counters
    from avenir_tpu.utils.rig_canary import matmul_canary_ms

    nprocs = jax.process_count()
    d_local = len(jax.local_devices())
    on_tpu = jax.local_devices()[0].platform == "tpu"
    chunk = 262_144 if on_tpu else 2_048
    n_chunks = 8 if on_tpu else 3
    passes = 3 if on_tpu else 2
    codes, cont, labels = gen_data(chunk * n_chunks)
    ds = EncodedDataset(
        codes=codes, cont=cont, labels=labels,
        n_bins=np.full(N_FEAT, N_BINS, np.int32), class_values=["a", "b"],
        binned_ordinals=list(range(N_FEAT)),
        cont_ordinals=list(range(N_FEAT, N_FEAT + N_CONT)))
    n_rows = ds.num_rows

    def chunks():
        return iter([ds.slice(i, i + chunk) for i in range(0, n_rows, chunk)])

    def engine(shard=None, counters=None):
        eng = scan.SharedScan(shard=shard, counters=counters)
        eng.register(scan.NaiveBayesConsumer(name="nb"))
        eng.register(scan.MutualInfoConsumer(name="mi"))
        return eng

    def timed(shard=None):
        counters = Counters()
        eng = engine(shard, counters)
        eng.run(chunks())                        # warm (compile + upload)
        canary = matmul_canary_ms()
        rates = []
        for _ in range(passes):
            t0 = time.perf_counter()
            eng.run(chunks())
            rates.append(n_rows / (time.perf_counter() - t0))
        return float(np.median(rates)), canary, counters

    base_results = engine().run(chunks())        # local 1-chip oracle

    def identical(got):
        np.testing.assert_array_equal(got["nb"].bin_counts,
                                      base_results["nb"].bin_counts)
        np.testing.assert_array_equal(got["mi"].pair_class_counts,
                                      base_results["mi"].pair_class_counts)
        if got["mi"].to_lines() != base_results["mi"].to_lines():
            raise RuntimeError("global fold diverged from 1-chip oracle")

    # 1-process local-mesh baseline at the same per-process width: the
    # scaling-efficiency denominator (explicit spec — from_conf resolves
    # globally in a multi-process runtime)
    local_spec = ShardSpec(
        mesh=make_mesh(("data",), shape=(d_local,),
                       devices=jax.local_devices()))
    identical(engine(local_spec).run(chunks()))
    local_rate, local_canary, _ = timed(local_spec)

    spec = ShardSpec.from_conf(JobConfig({"shard.devices": "all"}))
    assert spec.is_global and spec.num_procs == nprocs
    identical(engine(spec).run(chunks()))        # oracle gate before rates
    rate, canary, counters = timed(spec)

    qspec = ShardSpec.from_conf(JobConfig({
        "shard.devices": "all", "shard.allreduce.quantized": "true"}))
    q_res = engine(qspec).run(chunks())
    try:
        identical(q_res)
        q_exact, q_dev = True, 0
    except (AssertionError, RuntimeError):
        q_exact = False
        q_dev = int(np.abs(
            np.asarray(q_res["nb"].bin_counts, np.int64)
            - np.asarray(base_results["nb"].bin_counts, np.int64)).max())
    q_rate, q_canary, _ = timed(qspec)

    if idx == 0:
        artifact = {
            "benchmark": "multichip_scan",
            "metric": "nb_mi_global_mesh_scan_throughput",
            "mode": "multiprocess",
            "topology": spec.announce(),
            "interpret_mode": not on_tpu,
            "rows_total": n_rows,
            "chunk_rows": chunk,
            "passes": passes,
            "local_mesh_1proc": {
                "devices": d_local,
                "rows_per_sec_aggregate": round(local_rate, 1),
                "canary_ms": round(local_canary, 2),
            },
            "global_mesh": {
                "procs": nprocs,
                "devices_total": spec.total_devices,
                "rows_per_sec_aggregate": round(rate, 1),
                "rows_per_sec_per_process": round(rate / nprocs, 1),
                "scaling_efficiency": round(rate / (local_rate * nprocs), 3),
                "collective_bytes_per_chunk": int(
                    (counters.get("Shard", "collective.bytes") or 0)
                    // max(1, counters.get("Shard", "chunks") or 1)),
                "canary_ms": round(canary, 2),
            },
            "quantized_crosshost_hop": {
                "rows_per_sec_aggregate": round(q_rate, 1),
                "byte_identical_at_this_chunk_size": q_exact,
                "max_bin_count_deviation": q_dev,
                "canary_ms": round(q_canary, 2),
            },
            "canary_healthy_threshold_ms": 7.0,
        }
        # --out unset (launched by hand through the launcher CLI rather
        # than the self-launching parent): keep the one-object-on-stdout
        # contract — the launcher echoes rank 0's line
        if args.out:
            with open(args.out, "w") as fh:
                json.dump(artifact, fh)
        else:
            print(json.dumps(artifact), flush=True)
    print(f"proc {idx} multichip multiproc ok", flush=True)


def _launch_multiproc(args):
    """Parent side of ``--nprocs``: respawn this script as a fleet via
    the launcher, then print process 0's artifact JSON on stdout (the
    same one-object-on-stdout contract as the single-process mode)."""
    import tempfile

    from avenir_tpu.launch import LaunchError, launch_local

    out = args.out or os.path.join(tempfile.mkdtemp(prefix="multichip_"),
                                   "multichip_mp.json")
    child = [os.path.abspath(__file__), "--nprocs", str(args.nprocs),
             "--out", out]
    result = launch_local(
        child, args.nprocs, devices_per_proc=args.devices_per_proc,
        join_timeout_s=120, timeout_s=3600, echo=False)
    for w in result.workers:
        sys.stderr.write(f"[p{w.rank}] exit={w.returncode}\n")
    if result.exit_code:
        failed = next(w for w in result.workers if w.returncode)
        sys.stderr.write(failed.output[-3000:] + "\n")
        raise LaunchError(
            f"multichip worker p{failed.rank} exited "
            f"{failed.returncode}")
    with open(out) as fh:
        print(fh.read())


def main():
    # resolve avenir_tpu from the repo root no matter how the script was
    # invoked (the re-exec path passes PYTHONPATH; direct --nprocs runs
    # need it here, and the launcher's workers inherit it)
    _root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if _root not in sys.path:
        sys.path.insert(0, _root)
    os.environ["PYTHONPATH"] = (
        _root + os.pathsep + os.environ.get("PYTHONPATH", "")).rstrip(
        os.pathsep)
    ap = argparse.ArgumentParser()
    ap.add_argument("--nprocs", type=int, default=0,
                    help="CrossGraft capture: N launcher-driven worker "
                         "processes (0 = single-process sections)")
    ap.add_argument("--devices-per-proc", type=int, default=4)
    ap.add_argument("--out", default=None,
                    help="worker artifact path (parent default: tempfile)")
    args = ap.parse_args()
    if args.nprocs and os.environ.get("AVENIR_PROCESS_ID") is None:
        _launch_multiproc(args)
        return
    if os.environ.get("AVENIR_PROCESS_ID") is not None:
        _multiproc_worker(args)
        return
    _single_process_main()


def _single_process_main():
    _maybe_force_host_mesh()
    import jax

    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.core.encoding import EncodedDataset
    from avenir_tpu.parallel.shard import ShardSpec
    from avenir_tpu.pipeline import scan
    from avenir_tpu.utils.metrics import Counters
    from avenir_tpu.utils.rig_canary import matmul_canary_ms

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    # interpret-mode folds are ~10⁴× the kernel; size the stream so a CPU
    # host-mesh run finishes in minutes while a TPU run amortizes dispatch
    chunk = 262_144 if on_tpu else 2_048
    n_chunks = 8 if on_tpu else 3
    passes = 3 if on_tpu else 2
    codes, cont, labels = gen_data(chunk * n_chunks)
    ds = EncodedDataset(
        codes=codes, cont=cont, labels=labels,
        n_bins=np.full(N_FEAT, N_BINS, np.int32), class_values=["a", "b"],
        binned_ordinals=list(range(N_FEAT)),
        cont_ordinals=list(range(N_FEAT, N_FEAT + N_CONT)))
    n_rows = ds.num_rows

    def chunks():
        return iter([ds.slice(i, i + chunk) for i in range(0, n_rows, chunk)])

    def engine(shard=None, counters=None):
        eng = scan.SharedScan(shard=shard, counters=counters)
        eng.register(scan.NaiveBayesConsumer(name="nb"))
        eng.register(scan.MutualInfoConsumer(name="mi"))
        return eng

    def identical(got, want):
        np.testing.assert_array_equal(got["nb"].bin_counts,
                                      want["nb"].bin_counts)
        np.testing.assert_array_equal(got["nb"].class_counts,
                                      want["nb"].class_counts)
        np.testing.assert_array_equal(got["mi"].pair_class_counts,
                                      want["mi"].pair_class_counts)
        if got["mi"].to_lines() != want["mi"].to_lines():
            raise RuntimeError("sharded MI lines diverged from single-chip")

    def timed(shard=None):
        """(median aggregate rows/sec, canary ms, Shard counters) — one
        untimed warm pass (compile + upload), then ``passes`` timed folds;
        Accumulator.add fetches to host, so each fold is host-synced."""
        counters = Counters()
        eng = engine(shard, counters)
        eng.run(chunks())
        canary = matmul_canary_ms()
        rates = []
        for _ in range(passes):
            t0 = time.perf_counter()
            eng.run(chunks())
            rates.append(n_rows / (time.perf_counter() - t0))
        return float(np.median(rates)), canary, counters

    base_results = engine().run(chunks())
    base_rate, base_canary, _ = timed()

    counts, d = [], 1
    while d < len(devices):
        counts.append(d)
        d *= 2
    counts.append(len(devices))

    sections = []
    agg1 = None
    for d in counts:
        spec = ShardSpec.from_conf(JobConfig({"shard.devices": str(d)}))
        identical(engine(spec).run(chunks()), base_results)
        rate, canary, counters = timed(spec)
        if d == 1:
            agg1 = rate
        sections.append({
            "devices": d,
            "rows_per_sec_aggregate": round(rate, 1),
            "rows_per_sec_per_chip": round(rate / d, 1),
            "scaling_efficiency": (round(rate / (agg1 * d), 3)
                                   if agg1 else None),
            "collective_bytes_per_chunk": int(
                (counters.get("Shard", "collective.bytes") or 0)
                // max(1, counters.get("Shard", "chunks") or 1)),
            "canary_ms": round(canary, 2),
        })

    # EQuARX-style quantized all-reduce on the widest mesh: exact ONLY
    # while per-device gram partial cells fit int8 (small per-chip chunk
    # slices — the host-mesh shape); at the TPU chunk size the cells
    # overflow that bound, so identity is MEASURED and reported, never
    # asserted — the exact psum path above stays the byte-identity oracle
    qspec = ShardSpec.from_conf(JobConfig({
        "shard.devices": str(len(devices)),
        "shard.allreduce.quantized": "true"}))
    q_res = engine(qspec).run(chunks())
    try:
        identical(q_res, base_results)
        q_exact, q_dev = True, 0
    except (AssertionError, RuntimeError):
        q_exact = False
        q_dev = int(np.abs(
            np.asarray(q_res["nb"].bin_counts, np.int64)
            - np.asarray(base_results["nb"].bin_counts, np.int64)).max())
    q_rate, q_canary, _ = timed(qspec)

    print(json.dumps({
        "benchmark": "multichip_scan",
        "metric": "nb_mi_sharded_scan_throughput",
        "topology": qspec.announce(),
        "interpret_mode": not on_tpu,
        "rows_total": n_rows,
        "chunk_rows": chunk,
        "passes": passes,
        "single_chip": {
            "rows_per_sec": round(base_rate, 1),
            "canary_ms": round(base_canary, 2),
        },
        "sharded": sections,
        "quantized_allreduce": {
            "devices": len(devices),
            "rows_per_sec_aggregate": round(q_rate, 1),
            "byte_identical_at_this_chunk_size": q_exact,
            "max_bin_count_deviation": q_dev,
            "canary_ms": round(q_canary, 2),
        },
        "canary_healthy_threshold_ms": 7.0,
    }))


if __name__ == "__main__":
    main()
