"""Preemption drill — kill on 8 devices mid-fold, resume on 4,
byte-identical (ElasticGraft, round 16).

The robustness claim of ROADMAP item 3 as a runnable artifact: a sharded
windowed stream with pane-ring checkpoints is killed MID-FOLD by the
conf-driven fault family (``fault.fold.crash.after`` —
``utils/retry.py::FaultPlan``), resumed on a 4-device mesh with
``shard.reshard.on.restore=true``, and the resumed job output is
asserted byte-identical to an unkilled UNSHARDED run's tail — then the
journal is checked for the ``fault.injected`` / ``checkpoint.restore`` /
``checkpoint.reshard`` events that explain the drill (the durability
timeline ``python -m avenir_tpu.telemetry tree`` renders).

Run on any host — the drill forces an 8-device host mesh itself::

    python benchmarks/preemption_drill.py [--rows 4000] [--json out.json]

Exits 0 with a JSON artifact on byte-identity; raises on any mismatch.
The same sequence is gated in tier-1 by
``tests/test_reshard.py::test_preemption_drill_subprocess``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _force_host_mesh() -> None:
    """Force the 8-device CPU host mesh BEFORE jax initializes; if jax
    already initialized this process with fewer devices, exit with an
    instruction to relaunch fresh (an in-place re-shape is impossible)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "jax" in sys.modules:                      # pragma: no cover
        import jax

        if jax.device_count() < 8:
            raise SystemExit(
                "jax already initialized with <8 devices; run this "
                "script fresh with XLA_FLAGS="
                "--xla_force_host_platform_device_count=8")


def build_workload(tmp: str, rows: int):
    """A synthetic labeled CSV + schema file (1/16-grid continuous
    values — the byte-identity scope docs/streaming.md documents)."""
    import numpy as np

    from avenir_tpu.core.encoding import DatasetEncoder
    from avenir_tpu.core.schema import FeatureSchema

    f, b, c, fc = 4, 5, 2, 2
    rng = np.random.default_rng(16)
    codes = rng.integers(0, b, size=(rows, f)).astype(np.int32)
    cont = (rng.integers(0, 16, size=(rows, fc)) / 16.0).astype(np.float32)
    labels = rng.integers(0, c, size=rows).astype(np.int32)
    fields = [{"name": "id", "ordinal": 0, "id": True, "dataType": "string"}]
    for j in range(f):
        fields.append({"name": f"f{j}", "ordinal": 1 + j, "feature": True,
                       "dataType": "categorical",
                       "cardinality": [str(v) for v in range(b)]})
    for j in range(fc):
        fields.append({"name": f"x{j}", "ordinal": 1 + f + j,
                       "feature": True, "dataType": "double"})
    fields.append({"name": "cls", "ordinal": 1 + f + fc,
                   "dataType": "categorical", "cardinality": ["a", "b"]})
    schema = FeatureSchema.from_json({"fields": fields})
    DatasetEncoder(schema)                        # validates completeness
    lines = [",".join([f"r{i}"] + [str(int(v)) for v in codes[i]]
                      + [repr(float(x)) for x in cont[i]]
                      + [["a", "b"][int(labels[i])]])
             for i in range(rows)]
    data = os.path.join(tmp, "data.csv")
    with open(data, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    schema_path = os.path.join(tmp, "schema.json")
    with open(schema_path, "w") as fh:
        json.dump(schema.to_json(), fh)
    return data, schema_path


def run_drill(tmp: str, rows: int) -> dict:
    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.jobs import get_job
    from avenir_tpu.telemetry import spans as tel
    from avenir_tpu.telemetry.journal import read_events
    from avenir_tpu.utils.retry import InjectedFault

    data, schema_path = build_workload(tmp, rows)
    tel_dir = os.path.join(tmp, "tel")
    props = {"feature.schema.file.path": schema_path,
             "stream.pane.rows": "128", "stream.window.panes": "2",
             "stream.slide.panes": "1",
             "stream.consumers": "classDistribution,naiveBayes",
             "stream.checkpoint.dir": os.path.join(tmp, "ring"),
             "stream.checkpoint.interval.panes": "2",
             "trace.on": "true", "trace.journal.dir": tel_dir}

    # the oracle: the unkilled 1-chip (unsharded) run, no drill knobs
    golden_props = {k: v for k, v in props.items()
                    if not k.startswith("stream.checkpoint")}
    get_job("StreamAnalytics").run(JobConfig(dict(golden_props)), data,
                                   os.path.join(tmp, "out_golden"))
    with open(os.path.join(tmp, "out_golden", "part-00000")) as fh:
        golden = fh.read()

    # kill on 8, mid-fold
    killed_at = 6
    try:
        get_job("StreamAnalytics").run(
            JobConfig({**props, "shard.devices": "8",
                       "fault.fold.crash.after": str(killed_at)}),
            data, os.path.join(tmp, "out_killed"))
        raise AssertionError("injected fold fault never fired")
    except InjectedFault:
        pass

    # resume on 4, redistribution gated ON
    counters = get_job("StreamAnalytics").run(
        JobConfig({**props, "shard.devices": "4", "stream.resume": "true",
                   "shard.reshard.on.restore": "true"}),
        data, os.path.join(tmp, "out_resumed"))
    tel.tracer().disable()
    with open(os.path.join(tmp, "out_resumed", "part-00000")) as fh:
        resumed = fh.read()
    identical = bool(resumed) and golden.endswith(resumed)
    if not identical:
        raise AssertionError(
            "resumed output is NOT the unkilled unsharded run's tail — "
            "the byte-identity claim failed")

    events: list = []
    for name in sorted(os.listdir(tel_dir)):
        if name.endswith(".jsonl"):
            events.extend(read_events(os.path.join(tel_dir, name)))
    tally: dict = {}
    for e in events:
        ev = e.get("ev")
        if ev in ("fault.injected", "checkpoint.save",
                  "checkpoint.restore", "checkpoint.reshard"):
            tally[ev] = tally.get(ev, 0) + 1
    reshards = [e for e in events if e.get("ev") == "checkpoint.reshard"]
    assert tally.get("fault.injected") == 1, tally
    assert tally.get("checkpoint.reshard") == 1, tally
    return {
        "drill": "preemption",
        "rows": rows,
        "killed_on_devices": 8,
        "killed_at_fold": killed_at,
        "resumed_on_devices": 4,
        "resumed_windows": int(counters.get("Stream", "windows") or 0),
        "byte_identical_to_unsharded": identical,
        "reshard": {"src": reshards[0].get("src"),
                    "dst": reshards[0].get("dst"),
                    "keys": reshards[0].get("keys")},
        "journal_events": tally,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rows", type=int, default=4000)
    ap.add_argument("--json", default=None,
                    help="also write the artifact to this path")
    args = ap.parse_args(argv)
    _force_host_mesh()
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        artifact = run_drill(tmp, args.rows)
    text = json.dumps(artifact, indent=2)
    print(text)
    if args.json:
        with open(args.json, "w") as fh:
            fh.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
