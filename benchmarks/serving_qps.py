#!/usr/bin/env python
"""Serving-path benchmark: events/sec + action latency through the
ShardedServingFleet (the Storm-topology capacity analog,
ReinforcementLearnerTopology.java:42-85), plus the ServeGraft scoring
plane: QPS + p50/p99 per model family per bucket size through the bucketed
microbatcher, with the zero-steady-state-recompiles invariant ASSERTED
(the compile-cache discipline is the whole point of bucketing — a recompile
on the hot path voids the measurement).  Prints one JSON line; the
scoring-plane section is canary-conditioned per the PR-2 convention (a
fresh matmul canary rides in the artifact so a slow rig indicts itself,
not the kernel).

Workload: G engagement groups, each its own intervalEstimator learner over
5 actions (the reference runs one topology per group); events round-robin
the groups; every event drains that group's reward queue and emits an
action. Reported per worker count (the ``num.bolt.threads`` knob):

- events/sec over the whole stream (dispatch + backpressure + learner
  update + action write);
- p50/p99 per-event latency measured at the single-server level (one
  group, submit → action visible), the serving loop's intrinsic cost.

On the 1-core dev rig thread workers add no parallel speedup (GIL + one
core); the knob exists for capacity parity and is measured honestly —
multi-core hosts scale groups across workers.
"""

import json
import time

import numpy as np

from avenir_tpu.models import online_rl as orl
from avenir_tpu.pipeline import streaming as st
from avenir_tpu.utils.metrics import percentile_of

ACTIONS = [f"a{i}" for i in range(5)]
CONF = {"min.reward.distr.sample": 10}


def make_server(_group: str) -> st.ReinforcementLearnerServer:
    learner = orl.create_learner("intervalEstimator", ACTIONS, CONF, seed=3)
    return st.ReinforcementLearnerServer(
        learner, st.QueueEventSource(st.InProcQueue()),
        st.QueueRewardReader(st.InProcQueue()),
        st.QueueActionWriter(st.InProcQueue()))


def fleet_events_per_sec(num_workers: int, n_groups: int = 32,
                         n_events: int = 40_000) -> float:
    fleet = st.ShardedServingFleet(make_server, num_workers=num_workers,
                                   max_pending=256)
    t0 = time.perf_counter()
    for i in range(n_events):
        fleet.dispatch(f"g{i % n_groups}", f"ev{i}", i)
    fleet.close()
    dt = time.perf_counter() - t0
    assert fleet.processed == n_events
    return n_events / dt


def process_fleet_events_per_sec(num_workers: int, n_groups: int = 32,
                                 n_events: int = 40_000) -> float:
    # The num.workers (multi-process) pool: on a multi-core host this is
    # the knob that scales CPU-bound learners past the GIL; on the 1-core
    # dev rig it measures the IPC overhead honestly.
    fleet = st.ProcessServingFleet(make_server, num_workers=num_workers,
                                   max_pending=256)
    t0 = time.perf_counter()
    for i in range(n_events):
        fleet.dispatch(f"g{i % n_groups}", f"ev{i}", i)
    fleet.close()
    dt = time.perf_counter() - t0
    assert len(fleet.actions()) == n_events
    return n_events / dt


def single_event_latencies(n: int = 20_000):
    srv = make_server("g")
    events = srv.events.queue
    actions = srv.actions.queue
    rewards = srv.rewards.queue
    rng = np.random.default_rng(0)
    lats = []
    for i in range(n):
        t0 = time.perf_counter()
        events.push(f"ev{i},{i}")
        srv.process_one()
        msg = actions.pop()
        lats.append(time.perf_counter() - t0)
        action = msg.split(",")[1]
        rewards.push(f"{action},{max(rng.normal(50, 10), 0.0)}")
    return np.asarray(lats)


class _HeavyWrap:
    """Learner wrapper whose action selection first burns pure-Python CPU
    WHILE HOLDING THE GIL — the worst case for thread workers and the
    justifying case for process workers (round-4 verdict item 7)."""

    def __init__(self, inner, burn_loops: int):
        self._inner = inner
        self._burn = burn_loops

    def next_actions(self, round_num):
        acc = 0
        for i in range(self._burn):          # pure-Python GIL-holding burn
            acc += i & 7
        self._sink = acc
        return self._inner.next_actions(round_num)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def gil_contention_probe(n_events: int = 3000, burn_loops: int = 60_000):
    """Thread vs process fleet at ONE worker each under a GIL-holding
    CPU-bound learner, against the no-fleet per-event cost.

    What this CAN demonstrate on the 1-core dev rig: the measured
    per-event cost of each dispatch path under load — the thread fleet is
    bounded by GIL serialization (≈ the pure cost: dispatcher and worker
    interleave on one lock), the process fleet adds measurable IPC on
    top of OS scheduling.  What it CANNOT demonstrate here: the
    multi-core win — with W cores and W process workers the same
    GIL-holding update scales ~W× while thread workers stay at the pure
    rate; that claim is an EXTRAPOLATION from this measurement, labeled
    as such in BASELINE.md."""
    def heavy_server(_group: str) -> st.ReinforcementLearnerServer:
        learner = _HeavyWrap(
            orl.create_learner("intervalEstimator", ACTIONS, CONF, seed=3),
            burn_loops)
        return st.ReinforcementLearnerServer(
            learner, st.QueueEventSource(st.InProcQueue()),
            st.QueueRewardReader(st.InProcQueue()),
            st.QueueActionWriter(st.InProcQueue()))

    # no-fleet reference: the bare serve loop, one event at a time
    srv = heavy_server("g")
    t0 = time.perf_counter()
    for i in range(n_events):
        srv.events.queue.push(f"ev{i},{i}")
        srv.process_one()
        srv.actions.queue.pop()
    pure = n_events / (time.perf_counter() - t0)

    out = {"pure_events_per_sec": round(pure, 1)}
    for label, cls in (("thread", st.ShardedServingFleet),
                       ("process", st.ProcessServingFleet)):
        fleet = cls(heavy_server, num_workers=1, max_pending=256)
        t0 = time.perf_counter()
        for i in range(n_events):
            fleet.dispatch(f"g{i % 8}", f"ev{i}", i)
        fleet.close()
        rate = n_events / (time.perf_counter() - t0)
        out[f"{label}_events_per_sec"] = round(rate, 1)
        out[f"{label}_per_event_overhead_us"] = round(
            (1.0 / rate - 1.0 / pure) * 1e6, 1)
    return out


# ---------------------------------------------------------------------------
# the scoring plane (ServeGraft) — QPS + latency per family per bucket
# ---------------------------------------------------------------------------

SCORE_BUCKETS = (1, 8, 32)


def _build_serving_workspace(root: str):
    """Train every family's artifact with the real jobs (tiny datasets) and
    return {family: (serve conf, request lines)} — the benchmark measures
    the same artifact-handoff path production serving uses."""
    import os

    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.core.csv_io import write_csv
    from avenir_tpu.datagen.churn import CHURN_SCHEMA_JSON, generate_churn
    from avenir_tpu.datagen.retarget import (
        RETARGET_SCHEMA_JSON,
        generate_retarget,
    )
    from avenir_tpu.jobs import get_job
    from avenir_tpu.jobs.base import read_lines

    j = lambda *p: os.path.join(root, *p)
    rows = generate_churn(1200, seed=7)
    write_csv(j("train.csv"), rows[:800])
    write_csv(j("test.csv"), rows[800:])
    with open(j("churn.json"), "w") as fh:
        fh.write(json.dumps(CHURN_SCHEMA_JSON))
    churn = {"feature.schema.file.path": j("churn.json")}
    get_job("BayesianDistribution").run(JobConfig(dict(churn)),
                                        j("train.csv"), j("nb_model"))
    get_job("LogisticRegressionJob").run(
        JobConfig({**churn, "coeff.file.path": j("coeff.txt"),
                   "iteration.limit": "15"}),
        j("train.csv"), j("lr_out"))
    rrows = generate_retarget(1500, seed=3)
    write_csv(j("rdata.csv"), rrows)
    with open(j("retarget.json"), "w") as fh:
        fh.write(json.dumps(RETARGET_SCHEMA_JSON))
    retarget = {"feature.schema.file.path": j("retarget.json")}
    get_job("DecisionTreeBuilder").run(JobConfig(dict(retarget)),
                                       j("rdata.csv"), j("tree_model"))
    os.mkdir(j("tagged"))
    with open(j("tagged", "part-00000"), "w") as fh:
        fh.write("c1,x:A,y:B,x:A\nc2,y:B,y:B,x:A\nc3,x:A,y:B,x:A,x:A\n")
    get_job("HiddenMarkovModelBuilder").run(JobConfig({}), j("tagged"),
                                            j("hmm_model"))

    churn_lines = read_lines(j("test.csv"))
    seq_lines = [f"u{i},{i % 9},{'x,y,x,y'[: 1 + 2 * (i % 4)]}"
                 for i in range(400)]
    return {
        "naiveBayes": (JobConfig({**churn,
                                  "bayesian.model.file.path": j("nb_model"),
                                  "serve.models": "naiveBayes"}),
                       churn_lines),
        "logistic": (JobConfig({**churn, "coeff.file.path": j("coeff.txt"),
                                "serve.models": "logistic"}), churn_lines),
        "tree": (JobConfig({**retarget,
                            "tree.model.file.path": j("tree_model"),
                            "serve.models": "tree"}),
                 read_lines(j("rdata.csv"))),
        "knn": (JobConfig({**churn, "training.data.path": j("train.csv"),
                           "top.match.count": "7",
                           "kernel.function": "gaussian",
                           "serve.models": "knn"}), churn_lines),
        "viterbi": (JobConfig({"hmm.model.file.path": j("hmm_model"),
                               "skip.field.count": "2",
                               "serve.models": "viterbi",
                               "serve.sequence.pad.len": "16"}), seq_lines),
    }


def scoring_plane_section(bursts_per_bucket: int = 40):
    """{family: {bucket: {qps, p50_ms, p99_ms}}, steady_state_recompiles}.

    Per (family, bucket): submit ``bursts_per_bucket`` bucket-sized bursts
    through the warmed microbatcher (submit_nowait the burst, wait all —
    the dispatcher folds each burst into exactly one padded bucket), report
    rows/sec and per-burst p50/p99.  After ALL steady-state traffic the
    recompiles counter must read zero for every family — asserted, and
    published so the artifact carries the proof."""
    import tempfile

    from avenir_tpu.serving.batcher import BucketedMicrobatcher
    from avenir_tpu.serving.registry import ModelRegistry

    out = {}
    total_recompiles = 0
    with tempfile.TemporaryDirectory(prefix="servegraft_bench_") as root:
        families = _build_serving_workspace(root)
        for family, (conf, lines) in families.items():
            conf.set("serve.bucket.sizes",
                     ",".join(str(b) for b in SCORE_BUCKETS))
            conf.set("serve.flush.deadline.ms", "2")
            registry = ModelRegistry.from_conf(conf)
            batcher = BucketedMicrobatcher.from_conf(registry, conf)
            fam_stats = {}
            try:
                for bucket in SCORE_BUCKETS:
                    burst_lat = []
                    rows_done = 0
                    t0 = time.perf_counter()
                    for burst in range(bursts_per_bucket):
                        take = [lines[(burst * bucket + i) % len(lines)]
                                for i in range(bucket)]
                        tb = time.perf_counter()
                        pend = [batcher.submit_nowait(family, ln)
                                for ln in take]
                        for p in pend:
                            p.wait(60.0)
                        burst_lat.append(time.perf_counter() - tb)
                        rows_done += bucket
                    dt = time.perf_counter() - t0
                    lat = np.asarray(burst_lat)
                    fam_stats[str(bucket)] = {
                        "qps": round(rows_done / dt, 1),
                        "p50_ms": round(percentile_of(lat, 50) * 1e3, 3),
                        "p99_ms": round(percentile_of(lat, 99) * 1e3, 3),
                    }
                recompiles = batcher.counters.get(f"Serving.{family}",
                                                  "recompiles")
                if recompiles != 0:
                    # a hot-path compile voids the timings — hard failure
                    # even under python -O (so no `assert`)
                    raise RuntimeError(
                        f"{family}: {recompiles} steady-state recompile(s) "
                        f"— a shape escaped the warmed bucket set")
                fam_stats["steady_state_recompiles"] = recompiles
            finally:
                batcher.close()
            out[family] = fam_stats
            total_recompiles += recompiles
    out["steady_state_recompiles_total"] = total_recompiles
    return out


def main():
    rates = {w: round(fleet_events_per_sec(w), 1) for w in (1, 2, 4)}
    proc_rates = {w: round(process_fleet_events_per_sec(w), 1)
                  for w in (1, 2, 4)}
    lats = single_event_latencies()
    # fresh canary right before the scoring-plane section (PR-2 convention):
    # inflated canary ⇒ the rig was loaded, not the serving plane slow
    from avenir_tpu.utils.rig_canary import matmul_canary_ms
    canary_ms = matmul_canary_ms()
    print(json.dumps({
        "metric": "serving_events_per_sec",
        "value": max(rates.values()),
        "unit": "events/sec",
        "events_per_sec_by_workers": rates,
        "process_events_per_sec_by_workers": proc_rates,
        "p50_latency_us": round(percentile_of(lats, 50) * 1e6, 1),
        "p99_latency_us": round(percentile_of(lats, 99) * 1e6, 1),
        "groups": 32,
        "learner": "intervalEstimator",
        "gil_contention_1worker": gil_contention_probe(),
        "canary_matmul_4096_bf16_ms": round(canary_ms, 2),
        "scoring_plane": scoring_plane_section(),
    }))


if __name__ == "__main__":
    main()
