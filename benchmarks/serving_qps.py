#!/usr/bin/env python
"""Serving-path benchmark: events/sec + action latency through the
ShardedServingFleet (the Storm-topology capacity analog,
ReinforcementLearnerTopology.java:42-85). Prints one JSON line.

Workload: G engagement groups, each its own intervalEstimator learner over
5 actions (the reference runs one topology per group); events round-robin
the groups; every event drains that group's reward queue and emits an
action. Reported per worker count (the ``num.bolt.threads`` knob):

- events/sec over the whole stream (dispatch + backpressure + learner
  update + action write);
- p50/p99 per-event latency measured at the single-server level (one
  group, submit → action visible), the serving loop's intrinsic cost.

On the 1-core dev rig thread workers add no parallel speedup (GIL + one
core); the knob exists for capacity parity and is measured honestly —
multi-core hosts scale groups across workers.
"""

import json
import time

import numpy as np

from avenir_tpu.models import online_rl as orl
from avenir_tpu.pipeline import streaming as st

ACTIONS = [f"a{i}" for i in range(5)]
CONF = {"min.reward.distr.sample": 10}


def make_server(_group: str) -> st.ReinforcementLearnerServer:
    learner = orl.create_learner("intervalEstimator", ACTIONS, CONF, seed=3)
    return st.ReinforcementLearnerServer(
        learner, st.QueueEventSource(st.InProcQueue()),
        st.QueueRewardReader(st.InProcQueue()),
        st.QueueActionWriter(st.InProcQueue()))


def fleet_events_per_sec(num_workers: int, n_groups: int = 32,
                         n_events: int = 40_000) -> float:
    fleet = st.ShardedServingFleet(make_server, num_workers=num_workers,
                                   max_pending=256)
    t0 = time.perf_counter()
    for i in range(n_events):
        fleet.dispatch(f"g{i % n_groups}", f"ev{i}", i)
    fleet.close()
    dt = time.perf_counter() - t0
    assert fleet.processed == n_events
    return n_events / dt


def process_fleet_events_per_sec(num_workers: int, n_groups: int = 32,
                                 n_events: int = 40_000) -> float:
    # The num.workers (multi-process) pool: on a multi-core host this is
    # the knob that scales CPU-bound learners past the GIL; on the 1-core
    # dev rig it measures the IPC overhead honestly.
    fleet = st.ProcessServingFleet(make_server, num_workers=num_workers,
                                   max_pending=256)
    t0 = time.perf_counter()
    for i in range(n_events):
        fleet.dispatch(f"g{i % n_groups}", f"ev{i}", i)
    fleet.close()
    dt = time.perf_counter() - t0
    assert len(fleet.actions()) == n_events
    return n_events / dt


def single_event_latencies(n: int = 20_000):
    srv = make_server("g")
    events = srv.events.queue
    actions = srv.actions.queue
    rewards = srv.rewards.queue
    rng = np.random.default_rng(0)
    lats = []
    for i in range(n):
        t0 = time.perf_counter()
        events.push(f"ev{i},{i}")
        srv.process_one()
        msg = actions.pop()
        lats.append(time.perf_counter() - t0)
        action = msg.split(",")[1]
        rewards.push(f"{action},{max(rng.normal(50, 10), 0.0)}")
    return np.asarray(lats)


class _HeavyWrap:
    """Learner wrapper whose action selection first burns pure-Python CPU
    WHILE HOLDING THE GIL — the worst case for thread workers and the
    justifying case for process workers (round-4 verdict item 7)."""

    def __init__(self, inner, burn_loops: int):
        self._inner = inner
        self._burn = burn_loops

    def next_actions(self, round_num):
        acc = 0
        for i in range(self._burn):          # pure-Python GIL-holding burn
            acc += i & 7
        self._sink = acc
        return self._inner.next_actions(round_num)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def gil_contention_probe(n_events: int = 3000, burn_loops: int = 60_000):
    """Thread vs process fleet at ONE worker each under a GIL-holding
    CPU-bound learner, against the no-fleet per-event cost.

    What this CAN demonstrate on the 1-core dev rig: the measured
    per-event cost of each dispatch path under load — the thread fleet is
    bounded by GIL serialization (≈ the pure cost: dispatcher and worker
    interleave on one lock), the process fleet adds measurable IPC on
    top of OS scheduling.  What it CANNOT demonstrate here: the
    multi-core win — with W cores and W process workers the same
    GIL-holding update scales ~W× while thread workers stay at the pure
    rate; that claim is an EXTRAPOLATION from this measurement, labeled
    as such in BASELINE.md."""
    def heavy_server(_group: str) -> st.ReinforcementLearnerServer:
        learner = _HeavyWrap(
            orl.create_learner("intervalEstimator", ACTIONS, CONF, seed=3),
            burn_loops)
        return st.ReinforcementLearnerServer(
            learner, st.QueueEventSource(st.InProcQueue()),
            st.QueueRewardReader(st.InProcQueue()),
            st.QueueActionWriter(st.InProcQueue()))

    # no-fleet reference: the bare serve loop, one event at a time
    srv = heavy_server("g")
    t0 = time.perf_counter()
    for i in range(n_events):
        srv.events.queue.push(f"ev{i},{i}")
        srv.process_one()
        srv.actions.queue.pop()
    pure = n_events / (time.perf_counter() - t0)

    out = {"pure_events_per_sec": round(pure, 1)}
    for label, cls in (("thread", st.ShardedServingFleet),
                       ("process", st.ProcessServingFleet)):
        fleet = cls(heavy_server, num_workers=1, max_pending=256)
        t0 = time.perf_counter()
        for i in range(n_events):
            fleet.dispatch(f"g{i % 8}", f"ev{i}", i)
        fleet.close()
        rate = n_events / (time.perf_counter() - t0)
        out[f"{label}_events_per_sec"] = round(rate, 1)
        out[f"{label}_per_event_overhead_us"] = round(
            (1.0 / rate - 1.0 / pure) * 1e6, 1)
    return out


def main():
    rates = {w: round(fleet_events_per_sec(w), 1) for w in (1, 2, 4)}
    proc_rates = {w: round(process_fleet_events_per_sec(w), 1)
                  for w in (1, 2, 4)}
    lats = single_event_latencies()
    print(json.dumps({
        "metric": "serving_events_per_sec",
        "value": max(rates.values()),
        "unit": "events/sec",
        "events_per_sec_by_workers": rates,
        "process_events_per_sec_by_workers": proc_rates,
        "p50_latency_us": round(float(np.percentile(lats, 50)) * 1e6, 1),
        "p99_latency_us": round(float(np.percentile(lats, 99)) * 1e6, 1),
        "groups": 32,
        "learner": "intervalEstimator",
        "gil_contention_1worker": gil_contention_probe(),
    }))


if __name__ == "__main__":
    main()
