#!/usr/bin/env python
"""Serving-path benchmark: events/sec + action latency through the
ShardedServingFleet (the Storm-topology capacity analog,
ReinforcementLearnerTopology.java:42-85). Prints one JSON line.

Workload: G engagement groups, each its own intervalEstimator learner over
5 actions (the reference runs one topology per group); events round-robin
the groups; every event drains that group's reward queue and emits an
action. Reported per worker count (the ``num.bolt.threads`` knob):

- events/sec over the whole stream (dispatch + backpressure + learner
  update + action write);
- p50/p99 per-event latency measured at the single-server level (one
  group, submit → action visible), the serving loop's intrinsic cost.

On the 1-core dev rig thread workers add no parallel speedup (GIL + one
core); the knob exists for capacity parity and is measured honestly —
multi-core hosts scale groups across workers.
"""

import json
import time

import numpy as np

from avenir_tpu.models import online_rl as orl
from avenir_tpu.pipeline import streaming as st

ACTIONS = [f"a{i}" for i in range(5)]
CONF = {"min.reward.distr.sample": 10}


def make_server(_group: str) -> st.ReinforcementLearnerServer:
    learner = orl.create_learner("intervalEstimator", ACTIONS, CONF, seed=3)
    return st.ReinforcementLearnerServer(
        learner, st.QueueEventSource(st.InProcQueue()),
        st.QueueRewardReader(st.InProcQueue()),
        st.QueueActionWriter(st.InProcQueue()))


def fleet_events_per_sec(num_workers: int, n_groups: int = 32,
                         n_events: int = 40_000) -> float:
    fleet = st.ShardedServingFleet(make_server, num_workers=num_workers,
                                   max_pending=256)
    t0 = time.perf_counter()
    for i in range(n_events):
        fleet.dispatch(f"g{i % n_groups}", f"ev{i}", i)
    fleet.close()
    dt = time.perf_counter() - t0
    assert fleet.processed == n_events
    return n_events / dt


def process_fleet_events_per_sec(num_workers: int, n_groups: int = 32,
                                 n_events: int = 40_000) -> float:
    # The num.workers (multi-process) pool: on a multi-core host this is
    # the knob that scales CPU-bound learners past the GIL; on the 1-core
    # dev rig it measures the IPC overhead honestly.
    fleet = st.ProcessServingFleet(make_server, num_workers=num_workers,
                                   max_pending=256)
    t0 = time.perf_counter()
    for i in range(n_events):
        fleet.dispatch(f"g{i % n_groups}", f"ev{i}", i)
    fleet.close()
    dt = time.perf_counter() - t0
    assert len(fleet.actions()) == n_events
    return n_events / dt


def single_event_latencies(n: int = 20_000):
    srv = make_server("g")
    events = srv.events.queue
    actions = srv.actions.queue
    rewards = srv.rewards.queue
    rng = np.random.default_rng(0)
    lats = []
    for i in range(n):
        t0 = time.perf_counter()
        events.push(f"ev{i},{i}")
        srv.process_one()
        msg = actions.pop()
        lats.append(time.perf_counter() - t0)
        action = msg.split(",")[1]
        rewards.push(f"{action},{max(rng.normal(50, 10), 0.0)}")
    return np.asarray(lats)


def main():
    rates = {w: round(fleet_events_per_sec(w), 1) for w in (1, 2, 4)}
    proc_rates = {w: round(process_fleet_events_per_sec(w), 1)
                  for w in (1, 2, 4)}
    lats = single_event_latencies()
    print(json.dumps({
        "metric": "serving_events_per_sec",
        "value": max(rates.values()),
        "unit": "events/sec",
        "events_per_sec_by_workers": rates,
        "process_events_per_sec_by_workers": proc_rates,
        "p50_latency_us": round(float(np.percentile(lats, 50)) * 1e6, 1),
        "p99_latency_us": round(float(np.percentile(lats, 99)) * 1e6, 1),
        "groups": 32,
        "learner": "intervalEstimator",
    }))


if __name__ == "__main__":
    main()
