#!/usr/bin/env python
"""FleetServe chaos soak: millions-of-users-shaped traffic against a
:class:`~avenir_tpu.serving.pool.ReplicaPool`, with failure as the tested
path — a fault-injected replica KILL and a rolling hot-swap both land
mid-soak, and acceptance is a ``telemetry slo`` exit 0 plus journal-proved
request accounting.

GlobalServe (round 20) adds ``--nprocs N``: the same drill at PROCESS
granularity — bursty two-tenant traffic (alpha:beta 3:1 by contract)
against a :class:`~avenir_tpu.serving.global_pool.GlobalRouter` fronting
N REAL OS worker processes, one of which is **SIGKILLed** mid-soak.  The
process autoscaler replaces it (``fleet.pool.autoscale.min``), a rolling
fleet-wide hot-swap then rolls the retrained artifact across every worker
without ready capacity dropping below the floor, and acceptance is read
from the MERGED fleet journal (every worker shard + the router's own):
zero-lost/zero-double request accounting over attempt-qualified rids
(``g<n>.a<k>``), the ``fleet.pool.*`` lifecycle events present, and every
surviving tenant's ``telemetry slo --label tenant=<id>`` gate exit 0.

The traffic shape models the north-star claim in miniature: bursty
arrivals (a repeating burst-size pattern, not a constant rate), mixed
model families sharing one pool (naiveBayes + logistic over the churn
schema), and closed-loop clients (each burst waits before the next — how
real user fan-in backs off).  Mid-soak:

- a **rolling hot-swap** republishes a retrained naiveBayes artifact
  through the round-11 warmup barrier one replica at a time (capacity
  never zero, zero steady-state recompiles across the rollout);
- a **replica kill** fires through the conf-armed
  ``fault.serve.dispatch.crash.after`` site (utils/retry.FaultPlan — no
  monkeypatching): the replica dies mid-batch, its in-flight requests
  fail over to survivors, and the burn-rate autoscaler replaces the lost
  capacity (``pool.autoscale.min``).

Acceptance, all machine-checked:

- ``python -m avenir_tpu.telemetry slo`` exit 0 over the merged fleet
  journal: p99-under-burst, shed-rate, and ``recompiles.total == 0``
  (the ``steady_state_recompiles_total`` invariant) rules;
- ``pool.replica.down`` / ``pool.scale`` / ``fault.injected`` events
  present in the merged journal;
- ZERO lost and ZERO double-scored requests, asserted from the journal's
  per-request ``serve.request`` spans (each carries its pool ``rid``):
  every client-visible success maps to exactly one scored span, and
  every submitted request has exactly one outcome (a scored line or one
  typed error).

One JSON artifact line on stdout; a fresh matmul canary rides in it per
the PR-2 convention (a loaded rig indicts itself, not the pool).
"""

import glob
import json
import os
import tempfile
import time

# the burst-size pattern: heavy/light alternation so queue depth (and the
# p99 the SLO gates) is measured under BURSTS, not a polite constant rate
BURST_PATTERN = (32, 8, 48, 16, 40, 4)


def _train_workspace(root):
    """Train the two serving artifacts (naiveBayes v1+v2, logistic) with
    the real jobs over the churn generator — the same artifact-handoff
    path production serving uses."""
    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.core.csv_io import write_csv
    from avenir_tpu.datagen.churn import CHURN_SCHEMA_JSON, generate_churn
    from avenir_tpu.jobs import get_job
    from avenir_tpu.jobs.base import read_lines

    j = lambda *p: os.path.join(root, *p)
    rows = generate_churn(1400, seed=7)
    write_csv(j("train.csv"), rows[:900])
    write_csv(j("test.csv"), rows[900:])
    write_csv(j("train2.csv"), generate_churn(900, seed=23))  # the retrain
    with open(j("churn.json"), "w") as fh:
        fh.write(json.dumps(CHURN_SCHEMA_JSON))
    churn = {"feature.schema.file.path": j("churn.json")}
    get_job("BayesianDistribution").run(JobConfig(dict(churn)),
                                        j("train.csv"), j("nb_model"))
    get_job("BayesianDistribution").run(JobConfig(dict(churn)),
                                        j("train2.csv"), j("nb_model_v2"))
    get_job("LogisticRegressionJob").run(
        JobConfig({**churn, "coeff.file.path": j("coeff.txt"),
                   "iteration.limit": "10"}),
        j("train.csv"), j("lr_out"))
    return churn, read_lines(j("test.csv"))


def run_soak(bursts=48, replicas=2, p99_target_ms=2000.0,
             shed_target=0.02, scale=1.0, canary=True):
    """The soak body; ``scale`` shrinks the burst pattern and
    ``canary=False`` skips the rig canary (the tier-1 smoke runs a
    miniature soak through the identical failure path — it pins
    correctness, not rig speed, and a chained 4096³ matmul on a CI CPU
    is most of a minute).  Returns the artifact dict; raises
    RuntimeError on any gate failure."""
    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.serving.errors import ServingError
    from avenir_tpu.serving.pool import ReplicaPool
    from avenir_tpu.serving.registry import NaiveBayesServable
    from avenir_tpu.telemetry import spans as tel
    from avenir_tpu.telemetry.__main__ import main as telemetry_cli
    from avenir_tpu.utils.rig_canary import matmul_canary_ms

    root = tempfile.mkdtemp(prefix="serving_soak_")
    churn, lines = _train_workspace(root)
    pattern = [max(int(b * scale), 2) for b in BURST_PATTERN]
    total_requests = sum(pattern[b % len(pattern)] for b in range(bursts))
    # the kill lands mid-soak: total dispatches >= requests/max_bucket,
    # so this count is guaranteed to be reached before traffic ends
    kill_after = max(2, total_requests // 16)
    j = lambda *p: os.path.join(root, *p)
    props = {
        **churn,
        "bayesian.model.file.path": j("nb_model"),
        "coeff.file.path": j("coeff.txt"),
        "serve.models": "naiveBayes,logistic",
        "serve.bucket.sizes": "1,2,4,8",
        "serve.flush.deadline.ms": "4",
        "serve.queue.depth": "256",
        "serve.request.timeout.ms": "20000",
        # the pool: N replicas, fast supervision, failover armed, and the
        # autoscaler replacing lost capacity from the burn/queue gauges
        "pool.replicas": str(replicas),
        "pool.heartbeat.ms": "500",
        "pool.monitor.interval.ms": "40",
        "pool.failover.retries": "1",
        "pool.autoscale.on": "true",
        "pool.autoscale.min": str(replicas),
        "pool.autoscale.max": str(replicas + 1),
        "pool.autoscale.interval.sec": "0.2",
        # the chaos: kill a replica mid-batch through conf alone
        "fault.serve.dispatch.crash.after": str(kill_after),
        # the observability plane the acceptance reads
        "trace.on": "true",
        "trace.journal.dir": root,
        "trace.run.id": "fleetsoak",
        # the SLO gate `telemetry slo` closes on
        "slo.p99.metric": "p99.latency.ms",
        "slo.p99.target": str(p99_target_ms),
        "slo.shed.metric": "shed.rate",
        "slo.shed.target": str(shed_target),
        "slo.recompiles.metric": "recompiles.total",
        "slo.recompiles.target": "0",
    }
    conf_path = j("soak.properties")
    with open(conf_path, "w") as fh:
        fh.write("\n".join(f"{k}={v}" for k, v in props.items()) + "\n")
    conf = JobConfig.from_file(conf_path)
    tel.configure(conf)
    canary_ms = matmul_canary_ms() if canary else None
    pool = ReplicaPool.from_conf(conf)

    models = ("naiveBayes", "logistic")
    outcomes = {}
    door_shed = 0
    swap_at = bursts // 2
    swapped_versions = None
    burst_lat = []
    t0 = time.perf_counter()
    for b in range(bursts):
        size = pattern[b % len(pattern)]
        batch = []
        tb = time.perf_counter()
        for i in range(size):
            model = models[(b + i) % len(models)]
            line = lines[(b * size + i) % len(lines)]
            try:
                batch.append(pool.submit_nowait(model, line))
            except ServingError:
                door_shed += 1            # typed refusal at the door
        for req in batch:
            try:
                outcomes[req.rid] = ("ok", req.wait(60.0))
            except ServingError as err:
                outcomes[req.rid] = (err.code, None)
        burst_lat.append(time.perf_counter() - tb)
        if b == swap_at:
            # mid-soak rolling hot-swap: retrained NB, one replica at a
            # time through the warmup barrier — capacity never zero
            entry = NaiveBayesServable.from_conf(JobConfig(
                {**churn, "bayesian.model.file.path": j("nb_model_v2")}))
            swapped_versions = pool.swap("naiveBayes", entry)
    soak_s = time.perf_counter() - t0
    # let the supervisor finish reaping AND replacing before the books
    # close: a short soak can outrun the autoscale tick, and the
    # replacement's pool.scale/pool.replica.up events are acceptance
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline and \
            pool.stats()["pool"]["ready"] < replicas:
        time.sleep(0.05)
    recompiles = sum(
        vals.get("recompiles", 0)
        for group, vals in pool.counters.as_dict().items()
        if group.startswith("Serving."))
    pool_stats = pool.stats()["pool"]
    health = pool.health()
    # final counter snapshot into the journal: the post-hoc SLO gate's
    # shed.rate / recompiles.total metrics read it
    tel.tracer().counters("serving", pool.counters)
    pool.close()
    tel.tracer().disable()

    # -- the merged fleet journal is the acceptance artifact ------------------
    rc_merge = telemetry_cli(["merge", root])
    fleet = sorted(glob.glob(j("fleet-*.jsonl")))
    if rc_merge != 0 or not fleet:
        raise RuntimeError(f"journal merge failed (rc={rc_merge})")
    from avenir_tpu.telemetry.journal import read_events

    events = read_events(fleet[-1])
    by_ev = {}
    for e in events:
        by_ev.setdefault(e["ev"], []).append(e)
    for required in ("fault.injected", "pool.replica.down", "pool.scale"):
        if required not in by_ev:
            raise RuntimeError(
                f"chaos soak journal carries no {required!r} event — the "
                f"drill did not exercise the failure path")
    # zero lost, zero double-scored — from the journal's own spans
    scored = {}
    for e in by_ev.get("span.close", []):
        if e.get("name") != "serve.request":
            continue
        rid = (e.get("attrs") or {}).get("rid")
        if rid:
            scored[rid] = scored.get(rid, 0) + 1
    doubles = {rid: n for rid, n in scored.items() if n > 1}
    ok_rids = {rid for rid, (code, _) in outcomes.items() if code == "ok"}
    if doubles:
        raise RuntimeError(f"double-scored requests: {doubles}")
    if set(scored) != ok_rids:
        raise RuntimeError(
            f"journal/client disagree: {len(scored)} scored spans vs "
            f"{len(ok_rids)} client successes")
    lost = [rid for rid in outcomes if outcomes[rid][0] not in
            ("ok", "SHED", "TIMEOUT", "REPLICA_DOWN", "BAD_REQUEST")]
    if lost:
        raise RuntimeError(f"requests with untyped outcomes: {lost[:5]}")

    # -- the `telemetry slo` gate: exit 0 is the acceptance -------------------
    rc_slo = telemetry_cli(["slo", fleet[-1], "--conf", conf_path])
    shed = sum(1 for code, _ in outcomes.values() if code == "SHED")
    shed += door_shed
    artifact = {
        "benchmark": "serving_soak",
        "canary_ms": round(canary_ms, 3) if canary_ms is not None else None,
        "requests": total_requests,
        "bursts": bursts,
        "ok": len(ok_rids),
        "shed": shed,
        "door_shed": door_shed,
        "failovers": pool_stats.get("failovers", 0),
        "replicas_lost": pool_stats.get("replicas.lost", 0),
        "replicas_final": pool_stats.get("replicas", 0),
        "events_per_sec": round(total_requests / soak_s, 1),
        "burst_p99_ms": round(
            sorted(burst_lat)[int(0.99 * (len(burst_lat) - 1))] * 1e3, 2),
        "swap_versions": swapped_versions,
        "pool_events": {ev: len(by_ev.get(ev, []))
                        for ev in ("pool.replica.down", "pool.replica.up",
                                   "pool.scale", "pool.failover",
                                   "fault.injected")},
        "steady_state_recompiles_total": int(recompiles),
        "slo_exit": rc_slo,
        "healthz_ready": bool(health["ready"]),
    }
    if recompiles != 0:
        raise RuntimeError(
            f"steady_state_recompiles_total={recompiles}: a shape escaped "
            f"the warmed bucket set (or the swap barrier was skipped)")
    if swapped_versions is None or \
            any(v < 2 for v in swapped_versions.values()):
        raise RuntimeError(
            f"rolling hot-swap never advanced every live replica: "
            f"{swapped_versions}")
    if rc_slo != 0:
        raise RuntimeError(
            f"telemetry slo exited {rc_slo} — the soak violated an SLO "
            f"rule (see verdict above)")
    return artifact


def run_soak_fleet(nprocs=2, bursts=24, p99_target_ms=20000.0,
                   shed_target=0.25, scale=0.5, canary=True):
    """The GlobalServe drill: ``nprocs`` real serving processes behind
    one :class:`GlobalRouter`, two tenants under contract, one worker
    SIGKILLed mid-soak, a rolling fleet swap after the replacement lands.
    Returns the artifact dict; raises RuntimeError on any gate failure."""
    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.serving.errors import ServingError
    from avenir_tpu.serving.global_pool import GlobalRouter, WorkerSpawner
    from avenir_tpu.telemetry import spans as tel
    from avenir_tpu.telemetry.__main__ import main as telemetry_cli
    from avenir_tpu.telemetry.journal import read_events
    from avenir_tpu.tenancy.contract import split_contracts
    from avenir_tpu.utils.rig_canary import matmul_canary_ms

    if nprocs < 2:
        raise RuntimeError("the fleet drill needs --nprocs >= 2 (one "
                           "worker dies; survivors must carry the soak)")
    root = tempfile.mkdtemp(prefix="globalserve_soak_")
    churn, lines = _train_workspace(root)
    pattern = [max(int(b * scale), 2) for b in BURST_PATTERN]
    total_requests = sum(pattern[b % len(pattern)] for b in range(bursts))
    j = lambda *p: os.path.join(root, *p)
    run_id = "globalsoak"
    props = {
        **churn,
        "bayesian.model.file.path": j("nb_model"),
        "coeff.file.path": j("coeff.txt"),
        "serve.models": "naiveBayes,logistic",
        "serve.bucket.sizes": "1,2,4,8",
        "serve.flush.deadline.ms": "4",
        "serve.queue.depth": "256",
        "serve.request.timeout.ms": "20000",
        # each worker PROCESS runs a full (single-replica) ReplicaPool —
        # the round-17 plane — while the process-granularity supervision
        # lives in the router's fleet.pool.* family below
        "pool.replicas": "1",
        "pool.heartbeat.ms": "500",
        "pool.monitor.interval.ms": "50",
        "pool.failover.retries": "1",
        # the global tenancy contracts (alpha:beta 3:1); the launcher
        # hands each worker a 1/N split, the router enforces the full
        # fleet-wide quota at its door
        "tenant.alpha.share": "3",
        "tenant.alpha.max.inflight": "64",
        "tenant.beta.share": "1",
        "tenant.beta.max.inflight": "32",
        # the process-level supervision: fast heartbeats, two failover
        # hops per request, the autoscaler replacing lost workers, and
        # the rolling-swap ready floor
        "fleet.pool.breaker.failures": "3",
        "fleet.pool.heartbeat.ms": "500",
        "fleet.pool.breaker.halfopen.ms": "1000",
        "fleet.pool.failover.retries": "2",
        "fleet.pool.monitor.interval.ms": "100",
        "fleet.pool.client.threads": "8",
        "fleet.pool.autoscale.on": "true",
        "fleet.pool.autoscale.min": str(nprocs),
        "fleet.pool.autoscale.max": str(nprocs + 1),
        "fleet.pool.autoscale.interval.sec": "0.5",
        "fleet.pool.swap.floor": "1",
        # the observability plane the acceptance reads: every process
        # shards the SAME run (workers via -D trace.run.id, suffix via
        # AVENIR_WRITER_SUFFIX; the router under suffix "router")
        "trace.on": "true",
        "trace.journal.dir": root,
        "trace.run.id": run_id,
        # GraftBox (round 21): every process keeps a live forensics
        # bundle — the SIGKILLed victim's is the drill's post-mortem
        "blackbox.dir": j("bb"),
        # the per-tenant SLO gate closes on these over `--label tenant=`
        "slo.p99.metric": "p99.latency.ms",
        "slo.p99.target": str(p99_target_ms),
    }
    conf_path = j("fleet.properties")
    with open(conf_path, "w") as fh:
        fh.write("\n".join(f"{k}={v}" for k, v in props.items()) + "\n")
    conf = JobConfig.from_file(conf_path)
    # the router journals to its OWN shard of the shared run
    router_conf = JobConfig(dict(conf.props), prefix=conf.prefix)
    router_conf.set("trace.writer.suffix", "router")
    tel.configure(router_conf)
    canary_ms = matmul_canary_ms() if canary else None

    spawner = WorkerSpawner(conf_path, run_id,
                            overrides=split_contracts(conf, nprocs),
                            echo=False)
    workers = [spawner.spawn() for _ in range(nprocs)]
    router = GlobalRouter.from_conf(conf, workers=workers,
                                    spawner=spawner.spawn)

    tenants = ("alpha", "alpha", "alpha", "beta")   # the 3:1 mix
    outcomes = {}
    door_shed = 0
    kill_at = bursts // 3
    swap_at = (2 * bursts) // 3
    killed = workers[0].name
    swap_result = None
    burst_lat = []
    t0 = time.perf_counter()
    for b in range(bursts):
        size = pattern[b % len(pattern)]
        batch = []
        tb = time.perf_counter()
        for i in range(size):
            model = ("naiveBayes", "logistic")[(b + i) % 2]
            tenant = tenants[i % len(tenants)]
            line = lines[(b * size + i) % len(lines)]
            try:
                with tel.label_scope(tenant=tenant):
                    batch.append((tenant, router.submit_nowait(model, line)))
            except ServingError:
                door_shed += 1            # typed refusal at the fleet door
        for tenant, req in batch:
            try:
                req.wait(60.0)
                outcomes[req.rid] = ("ok", req.worker, tenant)
            except ServingError as err:
                outcomes[req.rid] = (err.code, req.worker, tenant)
        burst_lat.append(time.perf_counter() - tb)
        if b == kill_at:
            # the chaos: a REAL OS SIGKILL on a worker process mid-soak —
            # no drain, no handler; its in-flight requests must fail over
            workers[0].proc.kill()
        if b == swap_at:
            # wait out the replacement first (the autoscaler's
            # replace-below-min path), then roll the retrained artifact
            # across the fleet without dropping below the ready floor
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline and \
                    router.stats()["fleet"]["ready"] < nprocs:
                time.sleep(0.1)
            swap_result = router.swap_fleet(
                "naiveBayes",
                {**churn, "bayesian.model.file.path": j("nb_model_v2")})
    soak_s = time.perf_counter() - t0
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline and \
            router.stats()["fleet"]["ready"] < nprocs:
        time.sleep(0.1)
    fleet_stats = router.stats()["fleet"]
    health = router.health()
    tel.tracer().counters("fleet", router.counters)
    router.close()                 # SIGTERMs survivors (drain + snapshot)
    tel.tracer().disable()

    # -- the GraftBox post-mortem: the victim MUST have left a bundle ---------
    # (SIGKILL runs no hook — the flush thread's live bundle is the
    # record); the sweep journals it before the merge so the fleet view
    # accounts for the dead worker, then disarms this process's box
    from avenir_tpu.telemetry import blackbox

    bundle_recs = blackbox.sweep(j("bb"), journal_dir=root, run_id=run_id)
    blackbox.reset()
    victim_bundles = [r for r in bundle_recs
                      if (r.get("writer") or "").endswith("-" + killed)]
    if not victim_bundles:
        raise RuntimeError(
            f"SIGKILLed worker {killed!r} left no forensics bundle under "
            f"{j('bb')!r} — swept: {bundle_recs}")
    if not all(r["journaled"] for r in bundle_recs):
        raise RuntimeError(f"unjournaled bundles after sweep: {bundle_recs}")

    # -- the merged fleet journal is the acceptance artifact ------------------
    rc_merge = telemetry_cli(["merge", root, "--run", run_id])
    fleet_path = j(f"fleet-{run_id}.jsonl")
    if rc_merge != 0 or not os.path.exists(fleet_path):
        raise RuntimeError(f"journal merge failed (rc={rc_merge})")
    events = read_events(fleet_path)
    by_ev = {}
    for e in events:
        by_ev.setdefault(e["ev"], []).append(e)
    for required in ("fleet.pool.worker.down", "fleet.pool.worker.up",
                     "fleet.pool.scale", "fleet.pool.swap"):
        if required not in by_ev:
            raise RuntimeError(
                f"fleet journal carries no {required!r} event — the drill "
                f"did not exercise the process failure path")
    if not any(e.get("reason") == "died"
               for e in by_ev["fleet.pool.worker.down"]):
        raise RuntimeError("no fleet.pool.worker.down reason=died event — "
                           "the SIGKILL was never detected")

    # -- zero lost, zero double: attempt-qualified rids across shards ---------
    # every scored span carries its router rid g<n>.a<k> (attempt k) and
    # its shard's worker stamp; the killed worker may hold ORPHANS — a
    # request it scored+journaled but whose response died with it — and
    # each such orphan's base rid must have been re-scored on a survivor
    scored = {}                       # attempt rid -> [worker stamps]
    for e in by_ev.get("span.close", []):
        if e.get("name") != "serve.request":
            continue
        rid = (e.get("attrs") or {}).get("rid")
        if rid and rid.startswith("g"):
            scored.setdefault(rid, []).append(e.get("replica", "?"))
    doubles = {rid: st for rid, st in scored.items() if len(st) > 1}
    if doubles:
        raise RuntimeError(f"attempt scored twice: {doubles}")
    by_base = {}
    for rid, stamps in scored.items():
        base = rid.rsplit(".a", 1)[0]
        by_base.setdefault(base, []).extend(stamps)
    orphans = 0
    for base, stamps in by_base.items():
        if len(stamps) > 1:
            survivors = [s for s in stamps if s != killed]
            if len(survivors) > 1:
                raise RuntimeError(
                    f"request {base} scored on two SURVIVING workers "
                    f"{stamps} — a true double score")
            orphans += len(stamps) - 1
    ok_rids = {rid for rid, (code, _, _) in outcomes.items()
               if code == "ok"}
    torn_tail_ok = 0
    for rid in ok_rids:
        if rid not in by_base:
            # the one legal gap: the KILLED worker delivered the response
            # but its journal tail was torn by the SIGKILL
            if outcomes[rid][1] != killed:
                raise RuntimeError(
                    f"client success {rid} (worker {outcomes[rid][1]}) "
                    f"has no scored span in the merged journal — a lost "
                    f"request")
            torn_tail_ok += 1
    untyped = [rid for rid, (code, _, _) in outcomes.items()
               if code not in ("ok", "SHED", "TENANT_SHED", "TIMEOUT",
                               "WORKER_DOWN", "REPLICA_DOWN")]
    if untyped:
        raise RuntimeError(f"requests with untyped outcomes: {untyped[:5]}")

    # -- every surviving tenant's SLO gate must exit 0 ------------------------
    slo_exits = {}
    for tenant in ("alpha", "beta"):
        slo_exits[tenant] = telemetry_cli(
            ["slo", fleet_path, "--conf", conf_path,
             "--label", f"tenant={tenant}"])
    if swap_result is None or swap_result["min_ready"] < \
            swap_result["floor"]:
        raise RuntimeError(
            f"rolling fleet swap broke the ready floor: {swap_result}")
    if any(v is None or v < 2 for v in swap_result["versions"].values()):
        raise RuntimeError(
            f"fleet swap never advanced every worker: {swap_result}")
    shed = sum(1 for code, _, _ in outcomes.values()
               if code in ("SHED", "TENANT_SHED"))
    artifact = {
        "benchmark": "serving_soak_fleet",
        "canary_ms": round(canary_ms, 3) if canary_ms is not None else None,
        "nprocs": nprocs,
        "requests": total_requests,
        "bursts": bursts,
        "ok": len(ok_rids),
        "shed": shed + door_shed,
        "door_shed": door_shed,
        "killed_worker": killed,
        "victim_bundle": victim_bundles[0]["dir"],
        "bundles_swept": len(bundle_recs),
        "orphan_scored_spans": orphans,
        "torn_tail_ok": torn_tail_ok,
        "failovers": fleet_stats.get("failovers", 0),
        "workers_lost": fleet_stats.get("workers.lost", 0),
        "workers_spawned": fleet_stats.get("workers.spawned", 0),
        "workers_final": fleet_stats.get("workers", 0),
        "events_per_sec": round(total_requests / soak_s, 1),
        "burst_p99_ms": round(
            sorted(burst_lat)[int(0.99 * (len(burst_lat) - 1))] * 1e3, 2),
        "swap_min_ready": swap_result["min_ready"],
        "swap_floor": swap_result["floor"],
        "swap_versions": swap_result["versions"],
        "fleet_events": {ev: len(by_ev.get(ev, []))
                         for ev in ("fleet.pool.worker.down",
                                    "fleet.pool.worker.up",
                                    "fleet.pool.scale",
                                    "fleet.pool.failover",
                                    "fleet.pool.swap")},
        "slo_exits": slo_exits,
        "healthz_ready": bool(health["ready"]),
    }
    if any(rc != 0 for rc in slo_exits.values()):
        raise RuntimeError(
            f"a surviving tenant's SLO gate failed: {slo_exits}")
    return artifact


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="FleetServe / GlobalServe chaos soak")
    ap.add_argument("--nprocs", type=int, default=0,
                    help="serving worker PROCESSES — 0 (default) runs the "
                         "single-process ReplicaPool soak; >= 2 runs the "
                         "GlobalServe drill with one worker SIGKILLed")
    ap.add_argument("--bursts", type=int, default=None)
    ap.add_argument("--no-canary", action="store_true")
    args = ap.parse_args(argv)
    if args.nprocs:
        kwargs = {"nprocs": args.nprocs, "canary": not args.no_canary}
        if args.bursts:
            kwargs["bursts"] = args.bursts
        print(json.dumps(run_soak_fleet(**kwargs)))
    else:
        kwargs = {"canary": not args.no_canary}
        if args.bursts:
            kwargs["bursts"] = args.bursts
        print(json.dumps(run_soak(**kwargs)))


if __name__ == "__main__":
    main()
