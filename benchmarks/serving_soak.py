#!/usr/bin/env python
"""FleetServe chaos soak: millions-of-users-shaped traffic against a
:class:`~avenir_tpu.serving.pool.ReplicaPool`, with failure as the tested
path — a fault-injected replica KILL and a rolling hot-swap both land
mid-soak, and acceptance is a ``telemetry slo`` exit 0 plus journal-proved
request accounting.

The traffic shape models the north-star claim in miniature: bursty
arrivals (a repeating burst-size pattern, not a constant rate), mixed
model families sharing one pool (naiveBayes + logistic over the churn
schema), and closed-loop clients (each burst waits before the next — how
real user fan-in backs off).  Mid-soak:

- a **rolling hot-swap** republishes a retrained naiveBayes artifact
  through the round-11 warmup barrier one replica at a time (capacity
  never zero, zero steady-state recompiles across the rollout);
- a **replica kill** fires through the conf-armed
  ``fault.serve.dispatch.crash.after`` site (utils/retry.FaultPlan — no
  monkeypatching): the replica dies mid-batch, its in-flight requests
  fail over to survivors, and the burn-rate autoscaler replaces the lost
  capacity (``pool.autoscale.min``).

Acceptance, all machine-checked:

- ``python -m avenir_tpu.telemetry slo`` exit 0 over the merged fleet
  journal: p99-under-burst, shed-rate, and ``recompiles.total == 0``
  (the ``steady_state_recompiles_total`` invariant) rules;
- ``pool.replica.down`` / ``pool.scale`` / ``fault.injected`` events
  present in the merged journal;
- ZERO lost and ZERO double-scored requests, asserted from the journal's
  per-request ``serve.request`` spans (each carries its pool ``rid``):
  every client-visible success maps to exactly one scored span, and
  every submitted request has exactly one outcome (a scored line or one
  typed error).

One JSON artifact line on stdout; a fresh matmul canary rides in it per
the PR-2 convention (a loaded rig indicts itself, not the pool).
"""

import glob
import json
import os
import tempfile
import time

# the burst-size pattern: heavy/light alternation so queue depth (and the
# p99 the SLO gates) is measured under BURSTS, not a polite constant rate
BURST_PATTERN = (32, 8, 48, 16, 40, 4)


def _train_workspace(root):
    """Train the two serving artifacts (naiveBayes v1+v2, logistic) with
    the real jobs over the churn generator — the same artifact-handoff
    path production serving uses."""
    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.core.csv_io import write_csv
    from avenir_tpu.datagen.churn import CHURN_SCHEMA_JSON, generate_churn
    from avenir_tpu.jobs import get_job
    from avenir_tpu.jobs.base import read_lines

    j = lambda *p: os.path.join(root, *p)
    rows = generate_churn(1400, seed=7)
    write_csv(j("train.csv"), rows[:900])
    write_csv(j("test.csv"), rows[900:])
    write_csv(j("train2.csv"), generate_churn(900, seed=23))  # the retrain
    with open(j("churn.json"), "w") as fh:
        fh.write(json.dumps(CHURN_SCHEMA_JSON))
    churn = {"feature.schema.file.path": j("churn.json")}
    get_job("BayesianDistribution").run(JobConfig(dict(churn)),
                                        j("train.csv"), j("nb_model"))
    get_job("BayesianDistribution").run(JobConfig(dict(churn)),
                                        j("train2.csv"), j("nb_model_v2"))
    get_job("LogisticRegressionJob").run(
        JobConfig({**churn, "coeff.file.path": j("coeff.txt"),
                   "iteration.limit": "10"}),
        j("train.csv"), j("lr_out"))
    return churn, read_lines(j("test.csv"))


def run_soak(bursts=48, replicas=2, p99_target_ms=2000.0,
             shed_target=0.02, scale=1.0, canary=True):
    """The soak body; ``scale`` shrinks the burst pattern and
    ``canary=False`` skips the rig canary (the tier-1 smoke runs a
    miniature soak through the identical failure path — it pins
    correctness, not rig speed, and a chained 4096³ matmul on a CI CPU
    is most of a minute).  Returns the artifact dict; raises
    RuntimeError on any gate failure."""
    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.serving.errors import ServingError
    from avenir_tpu.serving.pool import ReplicaPool
    from avenir_tpu.serving.registry import NaiveBayesServable
    from avenir_tpu.telemetry import spans as tel
    from avenir_tpu.telemetry.__main__ import main as telemetry_cli
    from avenir_tpu.utils.rig_canary import matmul_canary_ms

    root = tempfile.mkdtemp(prefix="serving_soak_")
    churn, lines = _train_workspace(root)
    pattern = [max(int(b * scale), 2) for b in BURST_PATTERN]
    total_requests = sum(pattern[b % len(pattern)] for b in range(bursts))
    # the kill lands mid-soak: total dispatches >= requests/max_bucket,
    # so this count is guaranteed to be reached before traffic ends
    kill_after = max(2, total_requests // 16)
    j = lambda *p: os.path.join(root, *p)
    props = {
        **churn,
        "bayesian.model.file.path": j("nb_model"),
        "coeff.file.path": j("coeff.txt"),
        "serve.models": "naiveBayes,logistic",
        "serve.bucket.sizes": "1,2,4,8",
        "serve.flush.deadline.ms": "4",
        "serve.queue.depth": "256",
        "serve.request.timeout.ms": "20000",
        # the pool: N replicas, fast supervision, failover armed, and the
        # autoscaler replacing lost capacity from the burn/queue gauges
        "pool.replicas": str(replicas),
        "pool.heartbeat.ms": "500",
        "pool.monitor.interval.ms": "40",
        "pool.failover.retries": "1",
        "pool.autoscale.on": "true",
        "pool.autoscale.min": str(replicas),
        "pool.autoscale.max": str(replicas + 1),
        "pool.autoscale.interval.sec": "0.2",
        # the chaos: kill a replica mid-batch through conf alone
        "fault.serve.dispatch.crash.after": str(kill_after),
        # the observability plane the acceptance reads
        "trace.on": "true",
        "trace.journal.dir": root,
        "trace.run.id": "fleetsoak",
        # the SLO gate `telemetry slo` closes on
        "slo.p99.metric": "p99.latency.ms",
        "slo.p99.target": str(p99_target_ms),
        "slo.shed.metric": "shed.rate",
        "slo.shed.target": str(shed_target),
        "slo.recompiles.metric": "recompiles.total",
        "slo.recompiles.target": "0",
    }
    conf_path = j("soak.properties")
    with open(conf_path, "w") as fh:
        fh.write("\n".join(f"{k}={v}" for k, v in props.items()) + "\n")
    conf = JobConfig.from_file(conf_path)
    tel.configure(conf)
    canary_ms = matmul_canary_ms() if canary else None
    pool = ReplicaPool.from_conf(conf)

    models = ("naiveBayes", "logistic")
    outcomes = {}
    door_shed = 0
    swap_at = bursts // 2
    swapped_versions = None
    burst_lat = []
    t0 = time.perf_counter()
    for b in range(bursts):
        size = pattern[b % len(pattern)]
        batch = []
        tb = time.perf_counter()
        for i in range(size):
            model = models[(b + i) % len(models)]
            line = lines[(b * size + i) % len(lines)]
            try:
                batch.append(pool.submit_nowait(model, line))
            except ServingError:
                door_shed += 1            # typed refusal at the door
        for req in batch:
            try:
                outcomes[req.rid] = ("ok", req.wait(60.0))
            except ServingError as err:
                outcomes[req.rid] = (err.code, None)
        burst_lat.append(time.perf_counter() - tb)
        if b == swap_at:
            # mid-soak rolling hot-swap: retrained NB, one replica at a
            # time through the warmup barrier — capacity never zero
            entry = NaiveBayesServable.from_conf(JobConfig(
                {**churn, "bayesian.model.file.path": j("nb_model_v2")}))
            swapped_versions = pool.swap("naiveBayes", entry)
    soak_s = time.perf_counter() - t0
    # let the supervisor finish reaping AND replacing before the books
    # close: a short soak can outrun the autoscale tick, and the
    # replacement's pool.scale/pool.replica.up events are acceptance
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline and \
            pool.stats()["pool"]["ready"] < replicas:
        time.sleep(0.05)
    recompiles = sum(
        vals.get("recompiles", 0)
        for group, vals in pool.counters.as_dict().items()
        if group.startswith("Serving."))
    pool_stats = pool.stats()["pool"]
    health = pool.health()
    # final counter snapshot into the journal: the post-hoc SLO gate's
    # shed.rate / recompiles.total metrics read it
    tel.tracer().counters("serving", pool.counters)
    pool.close()
    tel.tracer().disable()

    # -- the merged fleet journal is the acceptance artifact ------------------
    rc_merge = telemetry_cli(["merge", root])
    fleet = sorted(glob.glob(j("fleet-*.jsonl")))
    if rc_merge != 0 or not fleet:
        raise RuntimeError(f"journal merge failed (rc={rc_merge})")
    from avenir_tpu.telemetry.journal import read_events

    events = read_events(fleet[-1])
    by_ev = {}
    for e in events:
        by_ev.setdefault(e["ev"], []).append(e)
    for required in ("fault.injected", "pool.replica.down", "pool.scale"):
        if required not in by_ev:
            raise RuntimeError(
                f"chaos soak journal carries no {required!r} event — the "
                f"drill did not exercise the failure path")
    # zero lost, zero double-scored — from the journal's own spans
    scored = {}
    for e in by_ev.get("span.close", []):
        if e.get("name") != "serve.request":
            continue
        rid = (e.get("attrs") or {}).get("rid")
        if rid:
            scored[rid] = scored.get(rid, 0) + 1
    doubles = {rid: n for rid, n in scored.items() if n > 1}
    ok_rids = {rid for rid, (code, _) in outcomes.items() if code == "ok"}
    if doubles:
        raise RuntimeError(f"double-scored requests: {doubles}")
    if set(scored) != ok_rids:
        raise RuntimeError(
            f"journal/client disagree: {len(scored)} scored spans vs "
            f"{len(ok_rids)} client successes")
    lost = [rid for rid in outcomes if outcomes[rid][0] not in
            ("ok", "SHED", "TIMEOUT", "REPLICA_DOWN", "BAD_REQUEST")]
    if lost:
        raise RuntimeError(f"requests with untyped outcomes: {lost[:5]}")

    # -- the `telemetry slo` gate: exit 0 is the acceptance -------------------
    rc_slo = telemetry_cli(["slo", fleet[-1], "--conf", conf_path])
    shed = sum(1 for code, _ in outcomes.values() if code == "SHED")
    shed += door_shed
    artifact = {
        "benchmark": "serving_soak",
        "canary_ms": round(canary_ms, 3) if canary_ms is not None else None,
        "requests": total_requests,
        "bursts": bursts,
        "ok": len(ok_rids),
        "shed": shed,
        "door_shed": door_shed,
        "failovers": pool_stats.get("failovers", 0),
        "replicas_lost": pool_stats.get("replicas.lost", 0),
        "replicas_final": pool_stats.get("replicas", 0),
        "events_per_sec": round(total_requests / soak_s, 1),
        "burst_p99_ms": round(
            sorted(burst_lat)[int(0.99 * (len(burst_lat) - 1))] * 1e3, 2),
        "swap_versions": swapped_versions,
        "pool_events": {ev: len(by_ev.get(ev, []))
                        for ev in ("pool.replica.down", "pool.replica.up",
                                   "pool.scale", "pool.failover",
                                   "fault.injected")},
        "steady_state_recompiles_total": int(recompiles),
        "slo_exit": rc_slo,
        "healthz_ready": bool(health["ready"]),
    }
    if recompiles != 0:
        raise RuntimeError(
            f"steady_state_recompiles_total={recompiles}: a shape escaped "
            f"the warmed bucket set (or the swap barrier was skipped)")
    if swapped_versions is None or \
            any(v < 2 for v in swapped_versions.values()):
        raise RuntimeError(
            f"rolling hot-swap never advanced every live replica: "
            f"{swapped_versions}")
    if rc_slo != 0:
        raise RuntimeError(
            f"telemetry slo exited {rc_slo} — the soak violated an SLO "
            f"rule (see verdict above)")
    return artifact


def main():
    print(json.dumps(run_soak()))


if __name__ == "__main__":
    main()
