#!/usr/bin/env python
"""StreamGraft soak benchmark: sustained windowed-analytics throughput and
the drift→retrain→hot-swap latency, with the zero-steady-state-recompiles
invariant ASSERTED.

Measures, on one synthetic stream (categorical + continuous features,
class-conditional structure):

- ``events_per_sec``: rows/sec through the full windowed path (queue pop →
  parse → encode → pow-2 pad → fused gram+moments fold → ring merge →
  consumer finalize) at steady state;
- ``pane_fold_ms`` p50/p99: latency of one pane close (the per-micro-batch
  cost a live stream pays);
- ``drift_to_swap_ms``: wall time from the FIRST drifted row entering the
  scan to the retrained model published in the serving registry (detection
  lag across the hysteresis windows + batch refit + swap barrier);
- ``steady_state_recompiles_total``: the CompileKeyMonitor count after
  warmup — ragged tail panes MUST land on pre-warmed pow-2 bucket shapes;
  nonzero raises RuntimeError (survives ``python -O``; the invariant IS
  the measurement).

One JSON line on stdout; a fresh matmul canary rides in the artifact per
the PR-2 convention (a loaded rig indicts itself, not the stream).
"""

import json
import os
import tempfile
import time

import numpy as np

SCHEMA = {
    "fields": [
        {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
        {"name": "color", "ordinal": 1, "dataType": "categorical",
         "cardinality": ["r", "g", "b"], "feature": True},
        {"name": "size", "ordinal": 2, "dataType": "categorical",
         "cardinality": ["s", "m", "l"], "feature": True},
        {"name": "score", "ordinal": 3, "dataType": "double",
         "feature": True},
        {"name": "status", "ordinal": 4, "dataType": "categorical",
         "cardinality": ["pos", "neg"]},
    ]
}

PANE_ROWS = 256
WINDOW_PANES = 4
STEADY_PANES = 24
DRIFTED_PANES = 12


def gen_lines(n, seed, flip=False):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        color = ["r", "g", "b"][int(rng.integers(0, 3))]
        size = ["s", "m", "l"][int(rng.integers(0, 3))]
        score = (8 + int(rng.integers(0, 17))) / 16.0 + \
            (1.0 if color == "r" else 0.0)
        p_pos = 0.9 if color == "r" else 0.15
        if flip:
            p_pos = 1.0 - p_pos
        status = "pos" if rng.random() < p_pos else "neg"
        out.append(f"id{i},{color},{size},{score!r},{status}")
    return out


def main():
    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.core.encoding import DatasetEncoder
    from avenir_tpu.core.schema import FeatureSchema
    from avenir_tpu.jobs import get_job
    from avenir_tpu.pipeline import scan
    from avenir_tpu.pipeline.streaming import InProcQueue
    from avenir_tpu.serving import BucketedMicrobatcher, ModelRegistry
    from avenir_tpu.stream import (
        ClassDistributionConsumer,
        DriftDetector,
        DriftRetrainController,
        WindowedScan,
    )
    from avenir_tpu.utils.metrics import LatencyTracker
    from avenir_tpu.utils.rig_canary import matmul_canary_ms

    root = tempfile.mkdtemp(prefix="streaming_soak_")
    schema_path = os.path.join(root, "schema.json")
    with open(schema_path, "w") as fh:
        fh.write(json.dumps(SCHEMA))
    train_path = os.path.join(root, "train.csv")
    with open(train_path, "w") as fh:
        fh.write("\n".join(gen_lines(4096, seed=7)) + "\n")
    conf = JobConfig({
        "feature.schema.file.path": schema_path,
        "bayesian.model.file.path": os.path.join(root, "nb_model"),
        "serve.models": "naiveBayes",
        "serve.bucket.sizes": "1,2,4,8",
        "stream.retrain.dir": os.path.join(root, "retrain"),
    })
    get_job("BayesianDistribution").run(conf, train_path,
                                        os.path.join(root, "nb_model"))
    registry = ModelRegistry.from_conf(conf)
    batcher = BucketedMicrobatcher.from_conf(registry, conf)
    enc = DatasetEncoder(FeatureSchema.from_file(schema_path))
    detector = DriftDetector(threshold=0.01, min_windows=2, source="class")
    controller = DriftRetrainController(conf, batcher, detector)
    ws = WindowedScan(
        enc,
        [ClassDistributionConsumer(name="cd"),
         scan.NaiveBayesConsumer(name="nb"),
         scan.MutualInfoConsumer(name="mi")],
        pane_rows=PANE_ROWS, window_panes=WINDOW_PANES, slide_panes=1,
        retain_rows=True)
    ws.warm()

    canary_ms = matmul_canary_ms()

    # -- steady-state soak: rows/sec + per-pane fold latency ------------------
    steady = gen_lines(STEADY_PANES * PANE_ROWS, seed=11)
    queue = InProcQueue(depth=4 * PANE_ROWS)
    pane_lat = LatencyTracker()
    windows = []
    t0 = time.perf_counter()
    for start in range(0, len(steady), PANE_ROWS):
        for line in steady[start:start + PANE_ROWS]:
            queue.push(line)
        t_pane = time.perf_counter()
        windows.extend(ws.pump(queue))
        pane_lat.record(time.perf_counter() - t_pane)
    steady_s = time.perf_counter() - t0
    for window in windows:
        controller.on_window(window)
    if controller.swaps:
        raise RuntimeError("steady-state traffic must not trip a retrain")

    # -- drift injection: first drifted row → swapped model -------------------
    drifted = gen_lines(DRIFTED_PANES * PANE_ROWS, seed=13, flip=True)
    # an off-pane-size tail exercises the ragged pow-2 bucket path
    drifted = drifted[:-(PANE_ROWS // 3)]
    t_drift = time.perf_counter()
    drift_to_swap_ms = None
    for start in range(0, len(drifted), PANE_ROWS):
        for window in ws.feed(drifted[start:start + PANE_ROWS]):
            if controller.on_window(window) is not None and \
                    drift_to_swap_ms is None:
                drift_to_swap_ms = (time.perf_counter() - t_drift) * 1e3
    for window in ws.flush():
        controller.on_window(window)
    if drift_to_swap_ms is None:
        raise RuntimeError("injected distribution shift never tripped the "
                           "drift→retrain→swap loop")
    if registry.version("naiveBayes") < 2:
        raise RuntimeError("retrain completed but the registry version "
                           "never advanced")
    batcher.close()

    recompiles = int(ws.counters.get("Stream", "recompiles") or 0)
    if recompiles != 0:
        raise RuntimeError(
            f"steady_state_recompiles_total={recompiles}: a pane shape "
            f"missed the pre-warmed pow-2 buckets")
    stats = pane_lat.snapshot()
    print(json.dumps({
        "benchmark": "streaming_soak",
        "canary_ms": round(canary_ms, 3),
        "pane_rows": PANE_ROWS,
        "window_panes": WINDOW_PANES,
        "rows_steady": len(steady),
        "windows_emitted": ws.windows_emitted,
        "events_per_sec": round(len(steady) / steady_s, 1),
        "pane_fold_ms_p50": round(stats["p50_ms"], 3),
        "pane_fold_ms_p99": round(stats["p99_ms"], 3),
        "drift_to_swap_ms": round(drift_to_swap_ms, 1),
        "retrain_fit_swap_ms": round(controller.last_swap_s * 1e3, 1),
        "model_version": registry.version("naiveBayes"),
        "steady_state_recompiles_total": recompiles,
    }))


if __name__ == "__main__":
    main()
