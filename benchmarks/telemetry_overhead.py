#!/usr/bin/env python
"""Measured cost of GraftTrace — the off-is-free contract, quantified.

Two numbers per state (prints one JSON line):

- ``span_ns`` — wall cost of one ``tracer().span(...)`` enter/exit,
  median over batches of 10k spans.  Off: one attribute check returning
  the shared NOOP span (no generator frame, no allocation, no I/O).  On
  (journal to a tmpfile): two JSON lines written + flushed per span, the
  price a traced run pays per unit of work.
- ``bench_site_overhead_pct`` — the off-state span cost projected onto
  the nb_mi bench's span sites per pass (a handful of spans around
  multi-second device passes), documenting why the published
  canary-clean band needs no widening with telemetry merged.

Round 14 adds ``profile_site_ns_off`` — the cost of a GraftProf sample
site (``profiler().sample``/``observe`` guard) while ``profile.on`` is
unset: one attribute check and an early return, the same off-is-free
contract the span sites hold.

Round 15 (GraftFleet) re-measures the off-state bound with the fleet
plane merged — the shard/stamp/skew/SLO machinery adds NOTHING to the
off path (``span_ns_off`` is the same one-attribute-check site; the
skew probe and SLO evaluator are gated behind the same
``profiler().enabled`` check ``profile_site_ns_off`` measures, and no
journal shard is ever created off) — and adds
``span_ns_on_federated``: the on-state cost when the journal is a
fleet SHARD (writer stamp on every event + prefixed span ids), so the
per-event price of per-process attribution is a published number.

Round 21 (GraftBox) adds the flight-ring numbers: ``ring_record_ns`` —
one bounded-deque append, the cost every emit seam now pays on BOTH
sides of ``trace.on`` — plus ``event_site_ns_off`` (a disabled
``tracer().event(...)`` call: the ring append + one enabled check, the
always-on recorder's whole off-state price) and ``event_site_ns_on``
(ring append + journal line).  ``span_ns_off`` is measured by the SAME
code as before the recorder merged — the span sites do not touch the
ring, so the published off-is-free span bound is unchanged by round 21.
"""

from __future__ import annotations

import json
import os
import tempfile
import time

import numpy as np

from avenir_tpu.telemetry import blackbox
from avenir_tpu.telemetry.profile import Profiler
from avenir_tpu.telemetry.spans import Tracer

SPANS_PER_BATCH = 10_000
BATCHES = 7


def measure_span_ns(tracer: Tracer) -> float:
    rates = []
    for _ in range(BATCHES):
        t0 = time.perf_counter()
        for _ in range(SPANS_PER_BATCH):
            with tracer.span("probe"):
                pass
        rates.append((time.perf_counter() - t0) / SPANS_PER_BATCH * 1e9)
    return float(np.median(rates))


def measure_ring_record_ns() -> float:
    """One direct flight-ring append — the GraftBox always-on floor."""
    rates = []
    for _ in range(BATCHES):
        t0 = time.perf_counter()
        for _ in range(SPANS_PER_BATCH):
            blackbox.ring_record("probe", None)
        rates.append((time.perf_counter() - t0) / SPANS_PER_BATCH * 1e9)
    return float(np.median(rates))


def measure_event_ns(t: Tracer) -> float:
    """One ``.event()`` emit seam: off-state this is the ring append plus
    the enabled check (the recorder's whole always-on price); on-state it
    adds the journal line."""
    rates = []
    for _ in range(BATCHES):
        t0 = time.perf_counter()
        for _ in range(SPANS_PER_BATCH):
            t.event("probe")
        rates.append((time.perf_counter() - t0) / SPANS_PER_BATCH * 1e9)
    return float(np.median(rates))


def measure_profile_site_ns(prof: Profiler) -> float:
    key = (("probe",),)
    rates = []
    for _ in range(BATCHES):
        t0 = time.perf_counter()
        for _ in range(SPANS_PER_BATCH):
            prof.sample(key, "probe", 0.0)
        rates.append((time.perf_counter() - t0) / SPANS_PER_BATCH * 1e9)
    return float(np.median(rates))


def measure() -> dict:
    off = Tracer()                       # never enabled: the default state
    off_ns = measure_span_ns(off)
    prof_off_ns = measure_profile_site_ns(Profiler())
    ring_ns = measure_ring_record_ns()
    event_off_ns = measure_event_ns(off)

    on = Tracer()
    with tempfile.TemporaryDirectory() as tmp:
        on.enable(tmp)
        on_ns = measure_span_ns(on)
        journal_bytes = os.path.getsize(on.journal_path)
        event_on_ns = measure_event_ns(on)    # after the size read: the
        on.disable()                          # bytes/span metric is spans-only
    blackbox.ring_clear()                # drop the probe flood

    # federated shard (GraftFleet): writer stamp on every event +
    # prefixed span ids — the per-process-attribution price, on-state
    fed = Tracer()
    with tempfile.TemporaryDirectory() as tmp:
        fed.enable(tmp, run_id="bench", suffix="w0")
        fed_ns = measure_span_ns(fed)
        fed_bytes = os.path.getsize(fed.journal_path)
        fed.disable()

    # the nb_mi bench adds ~7 span sites per run (one bench span, five
    # pass spans, plus per-pass canary events); a pass is seconds of
    # device time, so project the off cost onto one 1-second pass
    bench_spans_per_pass = 2
    overhead_pct = off_ns * bench_spans_per_pass / 1e9 / 1.0 * 100.0
    return {
        "metric": "telemetry_overhead",
        "span_ns_off": round(off_ns, 1),
        "profile_site_ns_off": round(prof_off_ns, 1),
        "ring_record_ns": round(ring_ns, 1),
        "event_site_ns_off": round(event_off_ns, 1),
        "event_site_ns_on": round(event_on_ns, 1),
        "span_ns_on_journaled": round(on_ns, 1),
        "span_ns_on_federated": round(fed_ns, 1),
        "journal_bytes_per_span": round(journal_bytes
                                        / (SPANS_PER_BATCH * BATCHES), 1),
        "federated_bytes_per_span": round(fed_bytes
                                          / (SPANS_PER_BATCH * BATCHES), 1),
        "bench_site_overhead_pct": round(overhead_pct, 6),
        "spans_per_batch": SPANS_PER_BATCH,
        "batches": BATCHES,
    }


def main() -> None:
    print(json.dumps(measure()))


if __name__ == "__main__":
    main()
