#!/usr/bin/env python
"""GraftPool multi-tenant soak: N concurrent workloads from different
owners on ONE device pool, isolation as a measured, journal-proved
artifact.

Four tenants run concurrently under ``tenant.*`` contracts against one
capacity-1 device arbiter (``avenir_tpu/tenancy``):

- **batch** — repeated NB+MI pipelines through the driver (fused
  SharedScan; every chunk fold draws an arbitrated dispatch slot);
- **stream** — windowed analytics with drift detection and the
  drift→retrain→hot-swap loop (panes fold through the SAME seam);
- **serve** — a tenant-owned :class:`BucketedMicrobatcher` under closed-
  loop request bursts (each batch dispatch draws a slot; priority 1 —
  latency outranks backfill);
- **noisy** — a ``fault.tenant.flood.after``-armed tenant that starts
  polite and goes rogue mid-soak, flooding the arbiter far past its
  1-slot quota and 2-deep queue share.

Acceptance, all machine-checked over the merged fleet journal (every
event tenant-labeled by ``label_scope``/the batcher dispatcher/the
driver):

- the noisy tenant is THROTTLED then SHED — journal-proved
  ``tenant.throttled`` + ``tenant.shed`` events with ``tenant=noisy``
  stamps, and its own SLO gate (``counter:Tenant.noisy:shed <= 0``)
  exits 1 — the gate catches the offender;
- every survivor's ``telemetry slo --conf <rules> --label tenant=<id>``
  verdict exits 0 (per-tenant rules via the ``tenant.<id>.slo.*``
  grammar): serve p99 + shed.rate, batch/stream zero tenant sheds,
  stream zero pane recompiles;
- ``steady_state_recompiles_total == 0`` across the warmed planes
  (serve batcher, stream panes, the stream tenant's swap target) —
  compiled-program sharing survives multi-tenancy;
- the drift→retrain→swap loop completed under contention (model v2).

One JSON artifact line on stdout; a fresh matmul canary rides in it per
the PR-2 convention (a loaded rig indicts itself, not the arbiter).
"""

import glob
import json
import os
import tempfile
import threading
import time

import numpy as np

SCHEMA = {
    "fields": [
        {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
        {"name": "color", "ordinal": 1, "dataType": "categorical",
         "cardinality": ["r", "g", "b"], "feature": True},
        {"name": "size", "ordinal": 2, "dataType": "categorical",
         "cardinality": ["s", "m", "l"], "feature": True},
        {"name": "score", "ordinal": 3, "dataType": "double",
         "feature": True},
        {"name": "status", "ordinal": 4, "dataType": "categorical",
         "cardinality": ["pos", "neg"]},
    ]
}


def gen_lines(n, seed, flip=False):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        color = ["r", "g", "b"][int(rng.integers(0, 3))]
        size = ["s", "m", "l"][int(rng.integers(0, 3))]
        score = (8 + int(rng.integers(0, 17))) / 16.0 + \
            (1.0 if color == "r" else 0.0)
        p_pos = 0.9 if color == "r" else 0.15
        if flip:
            p_pos = 1.0 - p_pos
        status = "pos" if rng.random() < p_pos else "neg"
        out.append(f"id{i},{color},{size},{score!r},{status}")
    return out


def run_soak(batch_rounds=3, steady_panes=10, drifted_panes=8,
             serve_bursts=24, burst_size=8, pane_rows=128,
             noisy_polite_iters=6, noisy_flood_workers=5,
             noisy_flood_iters=8, p99_target_ms=60000.0, canary=True):
    """The soak body; the tier-1 smoke runs it miniaturized through the
    IDENTICAL code path (``canary=False`` skips the rig canary — the
    smoke pins correctness, not rig speed).  Returns the artifact dict;
    raises RuntimeError on any gate failure."""
    from avenir_tpu import tenancy
    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.core.encoding import DatasetEncoder
    from avenir_tpu.core.schema import FeatureSchema
    from avenir_tpu.jobs import get_job
    from avenir_tpu.pipeline import scan
    from avenir_tpu.pipeline.driver import Pipeline, Stage
    from avenir_tpu.serving import BucketedMicrobatcher, ModelRegistry
    from avenir_tpu.serving.errors import ServingError, TenantShedError
    from avenir_tpu.stream import (
        ClassDistributionConsumer,
        DriftDetector,
        DriftRetrainController,
        WindowedScan,
    )
    from avenir_tpu.telemetry import spans as tel
    from avenir_tpu.telemetry.__main__ import main as telemetry_cli
    from avenir_tpu.telemetry.journal import read_events
    from avenir_tpu.utils.metrics import Counters
    from avenir_tpu.utils.retry import FaultPlan, InjectedFault

    tenancy.reset()
    root = tempfile.mkdtemp(prefix="tenancy_soak_")
    j = lambda *p: os.path.join(root, *p)
    with open(j("schema.json"), "w") as fh:
        fh.write(json.dumps(SCHEMA))
    with open(j("train.csv"), "w") as fh:
        fh.write("\n".join(gen_lines(2048, seed=7)) + "\n")
    test_lines = gen_lines(256, seed=11)

    base = {
        "feature.schema.file.path": j("schema.json"),
        # the observability plane the acceptance reads: ONE run id, every
        # event tenant-labeled by the scopes below
        "trace.on": "true",
        "trace.journal.dir": root,
        "trace.run.id": "tenancysoak",
        # the contracts: serve outranks backfill; noisy is boxed to one
        # concurrent slot, a 2-deep queue share and a short deadline
        "tenant.pool.concurrency": "1",
        "tenant.batch.share": "2",
        "tenant.stream.share": "2",
        "tenant.serve.share": "4",
        "tenant.serve.priority": "1",
        "tenant.noisy.share": "1",
        "tenant.noisy.max.inflight": "1",
        "tenant.noisy.queue.depth": "2",
        "tenant.noisy.queue.timeout.ms": "200",
        # per-tenant SLO rules (the tenant.<id>.slo.* grammar)
        "tenant.serve.slo.p99.metric": "p99.latency.ms",
        "tenant.serve.slo.p99.target": str(p99_target_ms),
        "tenant.serve.slo.shed.metric": "shed.rate",
        "tenant.serve.slo.shed.target": "0",
        "tenant.batch.slo.shed.metric": "counter:Tenant.batch:shed",
        "tenant.batch.slo.shed.target": "0",
        "tenant.stream.slo.shed.metric": "counter:Tenant.stream:shed",
        "tenant.stream.slo.shed.target": "0",
        "tenant.stream.slo.recompiles.metric": "counter:Stream:recompiles",
        "tenant.stream.slo.recompiles.target": "0",
        "tenant.noisy.slo.shed.metric": "counter:Tenant.noisy:shed",
        "tenant.noisy.slo.shed.target": "0",
        # the chaos: the noisy tenant goes rogue on its N-th pacing
        # boundary, armed from configuration alone
        "fault.tenant.flood.after": str(noisy_polite_iters),
    }
    base_conf = JobConfig(dict(base))
    tel.configure(base_conf)
    gp = tenancy.configure(base_conf)
    canary_ms = None
    if canary:
        from avenir_tpu.utils.rig_canary import matmul_canary_ms

        canary_ms = matmul_canary_ms()

    # serve + stream model artifacts (setup, outside the soak clock)
    fit_conf = {"feature.schema.file.path": j("schema.json")}
    get_job("BayesianDistribution").run(JobConfig(dict(fit_conf)),
                                        j("train.csv"), j("nb_serve"))
    get_job("BayesianDistribution").run(JobConfig(dict(fit_conf)),
                                        j("train.csv"), j("nb_stream"))
    serve_props = {"serve.models": "naiveBayes",
                   "serve.bucket.sizes": "1,2,4,8",
                   "serve.flush.deadline.ms": "4",
                   "serve.request.timeout.ms": "30000"}
    conf_serve = JobConfig({**base, **serve_props, "tenant.id": "serve",
                            "bayesian.model.file.path": j("nb_serve")})
    conf_stream = JobConfig({**base, **serve_props, "tenant.id": "stream",
                             "bayesian.model.file.path": j("nb_stream"),
                             "stream.retrain.dir": j("retrain")})
    serve_b = BucketedMicrobatcher.from_conf(
        ModelRegistry.from_conf(conf_serve), conf_serve)
    stream_b = BucketedMicrobatcher.from_conf(
        ModelRegistry.from_conf(conf_stream), conf_stream)

    errors = []
    results = {}

    def batch_worker():
        # the driver runs each pipeline AS tenant "batch" (tenant.id) —
        # fused NB+MI SharedScan, every chunk fold arbitrated
        total = Counters()
        for r in range(batch_rounds):
            conf_b = JobConfig({**base, "tenant.id": "batch"})
            p = Pipeline(j(f"batch-{r}"), conf_b)
            p.bind("data", j("train.csv"))
            p.add(Stage("nb", "BayesianDistribution", "data", "nb_out"))
            p.add(Stage("mi", "MutualInformation", "data", "mi_out"))
            p.run()
            total.merge_add(p.rollup())
        results["batch_counters"] = total

    def stream_worker():
        with tenancy.tenant_scope("stream"):
            enc = DatasetEncoder(FeatureSchema.from_file(j("schema.json")))
            detector = DriftDetector(threshold=0.01, min_windows=2,
                                     source="class")
            controller = DriftRetrainController(conf_stream, stream_b,
                                                detector)
            ws = WindowedScan(
                enc, [ClassDistributionConsumer(name="cd"),
                      scan.NaiveBayesConsumer(name="nb")],
                pane_rows=pane_rows, window_panes=2, slide_panes=1,
                retain_rows=True)
            ws.warm()
            steady = gen_lines(steady_panes * pane_rows, seed=13)
            for start in range(0, len(steady), pane_rows):
                for window in ws.feed(steady[start:start + pane_rows]):
                    controller.on_window(window)
            drifted = gen_lines(drifted_panes * pane_rows, seed=17,
                                flip=True)
            for start in range(0, len(drifted), pane_rows):
                for window in ws.feed(drifted[start:start + pane_rows]):
                    controller.on_window(window)
            for window in ws.flush():
                controller.on_window(window)
            results["stream_ws"] = ws
            results["stream_swaps"] = controller.swaps

    def serve_worker():
        with tenancy.tenant_scope("serve"):
            ok = shed = 0
            for b in range(serve_bursts):
                pending = []
                for i in range(burst_size):
                    line = test_lines[(b * burst_size + i) % len(test_lines)]
                    try:
                        pending.append(serve_b.submit_nowait("naiveBayes",
                                                             line))
                    except ServingError:
                        shed += 1
                for req in pending:
                    try:
                        req.wait(60.0)
                        ok += 1
                    except ServingError:
                        shed += 1
                time.sleep(0.005)
            results["serve_ok"] = ok
            results["serve_shed"] = shed

    def noisy_worker():
        from avenir_tpu.core.csv_io import read_csv_string

        fault = FaultPlan.from_conf(base_conf)
        enc = DatasetEncoder(FeatureSchema.from_file(j("schema.json")))
        small = enc.transform(
            read_csv_string("\n".join(gen_lines(64, seed=23))),
            with_labels=True)

        def one_fold():
            eng = scan.SharedScan()
            eng.register(scan.NaiveBayesConsumer(name="nb"))
            eng.run(small)

        with tenancy.tenant_scope("noisy"):
            flood = False
            sheds = [0]
            for _ in range(noisy_polite_iters + 1):
                try:
                    fault.hit("tenant.flood")
                except InjectedFault:
                    flood = True        # the drill: go rogue mid-soak
                    break
                try:
                    one_fold()
                except TenantShedError:
                    # even polite work can hit the tenant's own 200 ms
                    # deadline under startup contention — its contract,
                    # its shed; never a neighbor's problem
                    sheds[0] += 1
                time.sleep(0.02)
            if flood:
                lock = threading.Lock()

                rogue_errors: list = []

                def flood_loop():
                    try:
                        with tenancy.tenant_scope("noisy"):
                            for _ in range(noisy_flood_iters):
                                try:
                                    one_fold()
                                except TenantShedError:
                                    with lock:
                                        sheds[0] += 1
                    except Exception as e:  # noqa: BLE001
                        # a crashed rogue must show in the report, not
                        # silently undercount the flood pressure
                        with lock:
                            rogue_errors.append(repr(e))
                rogues = [threading.Thread(target=flood_loop)
                          for _ in range(noisy_flood_workers)]
                for t in rogues:
                    t.start()
                for t in rogues:
                    t.join(120.0)
            results["noisy_flooded"] = flood
            results["noisy_client_sheds"] = sheds[0]
            if flood and rogue_errors:
                results["noisy_rogue_errors"] = rogue_errors

    workers = [threading.Thread(target=fn, name=name) for name, fn in (
        ("soak-batch", batch_worker), ("soak-stream", stream_worker),
        ("soak-serve", serve_worker), ("soak-noisy", noisy_worker))]

    def guarded(thread):
        run = thread.run

        def wrapper():
            try:
                run()
            except BaseException as exc:          # noqa: BLE001 — surfaced
                errors.append(f"{thread.name}: {type(exc).__name__}: {exc}")
        thread.run = wrapper
        return thread

    t0 = time.perf_counter()
    for t in workers:
        guarded(t).start()
    for t in workers:
        t.join(600.0)
    soak_s = time.perf_counter() - t0
    if errors:
        raise RuntimeError(f"tenant workload(s) died: {errors}")

    # -- the books: one tenant-labeled merged snapshot per tenant -------------
    tracer = tel.tracer()
    arb_groups = gp.counters.as_dict()

    def tenant_snapshot(tenant, *sources):
        merged = Counters()
        for src in sources:
            merged.merge_add(src)
        for name, value in arb_groups.get(f"Tenant.{tenant}", {}).items():
            merged.increment(f"Tenant.{tenant}", name, value)
        with tenancy.tenant_scope(tenant):
            tracer.counters(f"tenant.{tenant}", merged)
        return merged

    ws = results["stream_ws"]
    tenant_snapshot("batch", results["batch_counters"])
    tenant_snapshot("stream", ws.counters, stream_b.counters)
    tenant_snapshot("serve", serve_b.counters)
    noisy_books = tenant_snapshot("noisy")
    recompiles = int(ws.counters.get("Stream", "recompiles") or 0)
    for counters in (serve_b.counters, stream_b.counters):
        recompiles += sum(vals.get("recompiles", 0) for group, vals in
                          counters.as_dict().items()
                          if group.startswith("Serving."))
    serve_b.close()
    stream_b.close()
    tracer.disable()
    tenancy.reset()

    # -- the merged fleet journal is the acceptance artifact ------------------
    rc_merge = telemetry_cli(["merge", root])
    fleet = sorted(glob.glob(j("fleet-*.jsonl")))
    if rc_merge != 0 or not fleet:
        raise RuntimeError(f"journal merge failed (rc={rc_merge})")
    events = read_events(fleet[-1])
    by_ev = {}
    for e in events:
        by_ev.setdefault(e["ev"], []).append(e)
    noisy_sheds = [e for e in by_ev.get("tenant.shed", [])
                   if e.get("tenant") == "noisy"]
    noisy_throttles = [e for e in by_ev.get("tenant.throttled", [])
                       if e.get("tenant") == "noisy"]
    if not results.get("noisy_flooded"):
        raise RuntimeError("fault.tenant.flood.after never fired — the "
                           "noisy-tenant drill did not run")
    if not noisy_throttles or not noisy_sheds:
        raise RuntimeError(
            f"noisy tenant was not throttled-then-shed "
            f"(throttled={len(noisy_throttles)}, shed={len(noisy_sheds)})")
    foreign_sheds = [e for e in by_ev.get("tenant.shed", [])
                     if e.get("tenant") != "noisy"]
    if foreign_sheds:
        raise RuntimeError(
            f"shedding leaked across tenant boundaries: {foreign_sheds}")
    admitted = {e.get("tenant") for e in by_ev.get("tenant.admitted", [])}
    if "noisy" not in admitted:
        raise RuntimeError(f"tenant.admitted missing: {admitted}")
    unattributed = [e for e in by_ev.get("span.close", [])
                    if e.get("name") == "serve.request"
                    and e.get("tenant") not in ("serve", "stream")]
    if unattributed:
        raise RuntimeError(
            f"serve.request spans without tenant stamps: "
            f"{unattributed[:3]}")
    if results.get("stream_swaps", 0) < 1:
        raise RuntimeError("drift→retrain→swap never completed under "
                           "multi-tenant contention")

    # -- per-tenant SLO verdicts over the ONE merged journal ------------------
    slo_exits = {}
    for tenant in ("batch", "stream", "serve", "noisy"):
        prefix = f"tenant.{tenant}.slo."
        rules = [f"slo.{k[len(prefix):]}={v}" for k, v in base.items()
                 if k.startswith(prefix)]
        rules_path = j(f"slo-{tenant}.properties")
        with open(rules_path, "w") as fh:
            fh.write("\n".join(rules) + "\n")
        slo_exits[tenant] = telemetry_cli(
            ["slo", fleet[-1], "--conf", rules_path,
             "--label", f"tenant={tenant}"])
    survivors_green = all(slo_exits[t] == 0
                          for t in ("batch", "stream", "serve"))

    artifact = {
        "benchmark": "tenancy_soak",
        "canary_ms": round(canary_ms, 3) if canary_ms is not None else None,
        "tenants": 4,
        "soak_s": round(soak_s, 2),
        "batch_rounds": batch_rounds,
        "batch_rows": int(results["batch_counters"].get(
            "Records", "Processed") or 0),
        "stream_windows": ws.windows_emitted,
        "stream_swaps": results["stream_swaps"],
        "serve_ok": results["serve_ok"],
        "serve_shed": results["serve_shed"],
        "noisy_sheds_booked": int(noisy_books.get(
            "Tenant.noisy", "shed") or 0),
        "noisy_throttled_events": len(noisy_throttles),
        "noisy_shed_events": len(noisy_sheds),
        "tenant_grants": {t: row["grants"]
                          for t, row in gp.stats().items()} if gp.enabled
        else {},
        "steady_state_recompiles_total": recompiles,
        "slo_exits": slo_exits,
        "survivors_green": survivors_green,
    }
    if recompiles != 0:
        raise RuntimeError(
            f"steady_state_recompiles_total={recompiles}: a warmed plane "
            f"recompiled under multi-tenant contention")
    if not survivors_green:
        raise RuntimeError(
            f"a surviving tenant's SLO gate failed: {slo_exits} — "
            f"isolation broke")
    if slo_exits["noisy"] != 1:
        raise RuntimeError(
            f"the noisy tenant's own gate exited {slo_exits['noisy']}, "
            f"expected 1 — the per-tenant verdict must catch the offender")
    if results["serve_shed"]:
        raise RuntimeError(
            f"the serving tenant shed {results['serve_shed']} request(s) "
            f"while the noisy tenant flooded — isolation broke")
    return artifact


def main():
    print(json.dumps(run_soak()))


if __name__ == "__main__":
    main()
