#!/usr/bin/env python
"""Per-level split-selection transport probe — makes the tree family's RTT
claim a reproducible artifact instead of prose.

The round-5 verdict root-caused tree induction's sub-baseline throughput
(`BENCH_r05.json` `families.tree.vs_baseline: 0.21`) to per-level host
round-trips: the host fetched the whole [F, B, K, C] level table
(`selection="host"`) and folded candidate splits there, paying the
~100 ms tunnel RTT once per level.  Device-resident selection
(`selection="device"`, round 6) keeps histograms, scoring and the
per-node top-k on device and fetches only KB-sized chosen-split
descriptors.  This probe measures BOTH at the driver shape
(family_bench's reduced 1M-row retarget fit) and, separately, the two
per-level transports in isolation:

- ``table_fetch_ms``  — wall time of ``np.asarray`` on the root level
  table (the host path's per-level fetch; scales with F·B·K·C and RTT);
- ``select_fetch_ms`` — wall time of the device-selection dispatch + its
  descriptor fetch for the same table (what replaces it).

Sync discipline as everywhere on this rig: a host fetch is the only
reliable barrier, so each timed region ends in one (BASELINE.md
"Timing methodology").  Run:

  python -m benchmarks.tree_rtt_probe [--rows 1000000] [--passes 3]

Prints ONE JSON line.
"""

import argparse
import json
import time

import numpy as np


def measure(rows: int = 1_000_000, passes: int = 3,
            max_depth: int = 4) -> dict:
    import jax
    import jax.numpy as jnp

    from avenir_tpu.models import tree as dtree
    from benchmarks.family_bench import _tree_data

    ds, is_cat = _tree_data(rows)

    def fit_rate(selection: str):
        builder = dtree.DecisionTree(algorithm="entropy", max_depth=max_depth,
                                     max_split=3, selection=selection)
        builder.fit(ds, is_categorical=is_cat)          # compile + warm
        vals = []
        for _ in range(passes):
            t0 = time.perf_counter()
            model = builder.fit(ds, is_categorical=is_cat)
            vals.append(rows / (time.perf_counter() - t0))
        return float(np.median(vals)), model

    host_rate, model = fit_rate("host")
    dev_rate, model_dev = fit_rate("device")
    if model.to_string() != model_dev.to_string():      # paranoia, not timing
        raise AssertionError("device/host selection trees diverged")

    # isolate the two per-level transports on the root level table
    all_splits = dtree.generate_candidate_splits(ds, 3, is_cat, 128)
    flat = dtree.flatten_splits(all_splits, ds.max_bins, 128)
    c = ds.num_classes
    table_dev = dtree.node_bin_class_counts(
        jnp.asarray(ds.codes), jnp.zeros(ds.num_rows, jnp.int32),
        jnp.asarray(ds.labels), 1, c, ds.max_bins)
    allow = jnp.asarray(flat.allow_vector(range(ds.num_binned)))
    np.asarray(table_dev)                               # warm the fetch path
    jax.device_get(dtree._device_select_splits(
        table_dev, flat.seg_tab_dev, flat.attr_dev, flat.nseg_dev, allow,
        algorithm="entropy", gmax=flat.gmax, top_k=1, chunk=flat.chunk))

    def med_ms(fn):
        vals = []
        for _ in range(max(passes, 3)):
            t0 = time.perf_counter()
            fn()
            vals.append((time.perf_counter() - t0) * 1e3)
        return round(float(np.median(vals)), 3)

    table_fetch_ms = med_ms(lambda: np.asarray(table_dev))
    select_fetch_ms = med_ms(lambda: jax.device_get(
        dtree._device_select_splits(
            table_dev, flat.seg_tab_dev, flat.attr_dev, flat.nseg_dev,
            allow, algorithm="entropy", gmax=flat.gmax, top_k=1,
            chunk=flat.chunk)))

    f, b = ds.num_binned, ds.max_bins
    return {
        "metric": "tree_split_selection_rtt_probe",
        "n_rows": rows, "max_depth": max_depth,
        "table_shape_fbkc": [f, b, 1, c],
        "table_bytes": int(f * b * 1 * c * 4),
        "descriptor_bytes": int(4 + 4 + flat.gmax * c * 4),   # per node·pick
        "host_selection_rows_per_sec": round(host_rate, 1),
        "device_selection_rows_per_sec": round(dev_rate, 1),
        "device_vs_host": round(dev_rate / host_rate, 2),
        "table_fetch_ms": table_fetch_ms,
        "select_dispatch_plus_fetch_ms": select_fetch_ms,
        "note": "table_fetch_ms is what selection=host pays PER LEVEL on "
                "top of scoring; select_dispatch_plus_fetch_ms replaces "
                "it (device histograms+scores+top-k, KB descriptor fetch)",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--max-depth", type=int, default=4)
    args = ap.parse_args()
    print(json.dumps(measure(args.rows, args.passes, args.max_depth)))


if __name__ == "__main__":
    main()
