#!/usr/bin/env python
"""Per-level split-selection transport + hist-mode probe — makes the tree
family's RTT and TreeGraft claims reproducible artifacts instead of prose.

The round-5 verdict root-caused tree induction's sub-baseline throughput
(`BENCH_r05.json` `families.tree.vs_baseline: 0.21`) to per-level host
round-trips: the host fetched the whole [F, B, K, C] level table
(`selection="host"`) and folded candidate splits there, paying the
~100 ms tunnel RTT once per level.  Device-resident selection
(`selection="device"`, round 6) keeps histograms, scoring and the
per-node top-k on device and fetches only KB-sized chosen-split
descriptors.  Round 13 attacks the remaining on-device cost with
`tree.hist.mode`: `cumsum` scores every binary threshold from ONE
bin-axis prefix sum of the level table (a B× cut versus the per-split
segment einsum) and `subtract` additionally contracts only the smaller
children per level, deriving each largest sibling by exact parent-slice
subtraction (~half the gram work).  This probe measures:

- the full fit rate under `selection=host` and under `selection=device`
  for EVERY hist mode (direct / cumsum / subtract), on the binary-search
  candidate family (the sklearn-comparable frontier) — with the grown
  trees checked byte-identical across all paths (RuntimeError on
  violation, so `python -O` runs keep the guard);
- a per-level phase breakdown (table-build / score+select / partition
  wall ms) per hist mode, the attribution behind any rate delta;
- the two per-level transports in isolation: ``table_fetch_ms`` (the
  host path's per-level fetch) vs ``select_dispatch_plus_fetch_ms``
  (the device-selection dispatch + KB descriptor fetch that replaces it);
- a fresh matmul canary before each timed section (rig-state
  attribution, per the bench.py convention).

Sync discipline as everywhere on this rig: a host fetch is the only
reliable barrier, so each timed region ends in one (BASELINE.md
"Timing methodology").  Run:

  python -m benchmarks.tree_rtt_probe [--rows 1000000] [--passes 3]
      [--search binary|exhaustive]

Prints ONE JSON line.
"""

import argparse
import json
import time

import numpy as np


def measure(rows: int = 1_000_000, passes: int = 3,
            max_depth: int = 4, search: str = "binary") -> dict:
    import jax
    import jax.numpy as jnp

    from avenir_tpu.models import tree as dtree
    from avenir_tpu.utils.rig_canary import matmul_canary_ms
    from benchmarks.family_bench import _tree_data

    ds, is_cat = _tree_data(rows)
    canaries = {}

    def fit_rate(selection: str, hist_mode: str = "direct"):
        builder = dtree.DecisionTree(algorithm="entropy", max_depth=max_depth,
                                     max_split=3, selection=selection,
                                     split_search=search, hist_mode=hist_mode)
        builder.fit(ds, is_categorical=is_cat)          # compile + warm
        canaries[f"{selection}.{hist_mode}"] = round(matmul_canary_ms(), 2)
        vals = []
        for _ in range(passes):
            t0 = time.perf_counter()
            model = builder.fit(ds, is_categorical=is_cat)
            vals.append(rows / (time.perf_counter() - t0))
        return float(np.median(vals)), model

    def phase_breakdown(hist_mode: str):
        probe = dtree.DecisionTree(algorithm="entropy", max_depth=max_depth,
                                   max_split=3, split_search=search,
                                   hist_mode=hist_mode,
                                   collect_phase_stats=True)
        probe.fit(ds, is_categorical=is_cat)
        return probe.level_stats

    host_rate, model_host = fit_rate("host")
    oracle = model_host.to_string()
    # cumsum only engages on an all-binary candidate family — under
    # exhaustive search it would be a re-measurement of direct published
    # under the wrong label, so only the modes that actually differ run
    # (dtree.HIST_MODES is the canonical mode list: a mode added there
    # is automatically covered here)
    modes = (dtree.HIST_MODES if search == "binary"
             else tuple(m for m in dtree.HIST_MODES if m != "cumsum"))
    mode_rates = {}
    mode_phases = {}
    for mode in modes:
        rate, model_dev = fit_rate("device", mode)
        if model_dev.to_string() != oracle:
            # RuntimeError, not assert: the byte-identity oracle must
            # survive `python -O` — a silently divergent fast path would
            # publish a rate for a DIFFERENT tree
            raise RuntimeError(
                f"hist_mode={mode!r} tree diverged from the "
                f"selection='host' oracle (search={search!r})")
        mode_rates[mode] = round(rate, 1)
        mode_phases[mode] = phase_breakdown(mode)

    # isolate the two per-level transports on the root level table
    all_splits = dtree.candidate_splits_for(ds, search, 3, is_cat, 128)
    flat = dtree.flatten_splits(all_splits, ds.max_bins, 128)
    c = ds.num_classes
    table_dev = dtree.node_bin_class_counts(
        jnp.asarray(ds.codes), jnp.zeros(ds.num_rows, jnp.int32),
        jnp.asarray(ds.labels), 1, c, ds.max_bins)
    allow = jnp.asarray(flat.allow_vector(range(ds.num_binned)))

    def select(binary: bool):
        return jax.device_get(dtree._device_select_splits(
            table_dev, flat.seg_tab_dev, flat.attr_dev, flat.nseg_dev,
            allow, flat.thr_dev if binary else None, algorithm="entropy",
            gmax=flat.gmax, top_k=1, chunk=flat.chunk, binary=binary))

    np.asarray(table_dev)                               # warm the fetch path
    select(False)
    cum_ok = flat.all_binary
    if cum_ok:
        select(True)

    def med_ms(fn):
        vals = []
        for _ in range(max(passes, 3)):
            t0 = time.perf_counter()
            fn()
            vals.append((time.perf_counter() - t0) * 1e3)
        return round(float(np.median(vals)), 3)

    table_fetch_ms = med_ms(lambda: np.asarray(table_dev))
    select_fetch_ms = med_ms(lambda: select(False))
    select_cum_ms = med_ms(lambda: select(True)) if cum_ok else None

    f, b = ds.num_binned, ds.max_bins
    return {
        "metric": "tree_split_selection_rtt_probe",
        "n_rows": rows, "max_depth": max_depth, "split_search": search,
        "table_shape_fbkc": [f, b, 1, c],
        "table_bytes": int(f * b * 1 * c * 4),
        "descriptor_bytes": int(4 + 4 + flat.gmax * c * 4),   # per node·pick
        "host_selection_rows_per_sec": round(host_rate, 1),
        "device_selection_rows_per_sec": dict(mode_rates),
        "device_vs_host": {m: round(mode_rates[m] / host_rate, 2)
                           for m in mode_rates},
        "level_phases_ms": mode_phases,
        "byte_identical_to_host_oracle": True,   # RuntimeError otherwise
        "canary_matmul_4096_bf16_ms": canaries,
        "table_fetch_ms": table_fetch_ms,
        "select_dispatch_plus_fetch_ms": select_fetch_ms,
        "select_cumsum_dispatch_plus_fetch_ms": select_cum_ms,
        "note": "table_fetch_ms is what selection=host pays PER LEVEL on "
                "top of scoring; select_dispatch_plus_fetch_ms replaces "
                "it (device histograms+scores+top-k, KB descriptor "
                "fetch); the cumsum variant scores every binary "
                "threshold from one bin-axis prefix sum of the table",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--max-depth", type=int, default=4)
    ap.add_argument("--search", choices=["binary", "exhaustive"],
                    default="binary")
    args = ap.parse_args()
    print(json.dumps(measure(args.rows, args.passes, args.max_depth,
                             args.search)))


if __name__ == "__main__":
    main()
