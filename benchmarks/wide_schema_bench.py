#!/usr/bin/env python
"""Wide-schema NB+MI count throughput: cls-mode kernel vs the scatter einsum.

The reference handles any cardinality via lazily-sparse reducer maps
(``explore/MutualInformation.java:421-432``); round 3 covered F·B·C ≤ 768
on the MXU and silently fell back to the ~80-113M rows/s scatter einsum
above it.  Round 4's per-class gram mode ("cls" in ops/pallas_hist.plan)
keeps wide shapes on the MXU; this bench measures both paths on the same
data, fresh-process, chained-dispatch host-fetch sync.

  python benchmarks/wide_schema_bench.py --shape 20x20x2 --path kernel
  python benchmarks/wide_schema_bench.py --shape 24x32x2 --path einsum

One (shape, path) per process run (fresh-process discipline).
"""

import argparse
import json
import time

import numpy as np
import jax.numpy as jnp

from avenir_tpu.ops import agg, pallas_hist


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shape", default="20x20x2",
                    help="FxBxC, e.g. 20x20x2 (W=800) or 24x32x2 (W=1536)")
    ap.add_argument("--path", choices=["kernel", "einsum"], default="kernel")
    ap.add_argument("--rows", type=int, default=4_000_000)
    ap.add_argument("--chunks", type=int, default=4)
    ap.add_argument("--passes", type=int, default=4)
    args = ap.parse_args()
    f, b, c = (int(x) for x in args.shape.split("x"))

    rng = np.random.default_rng(0)
    codes = rng.integers(0, b, size=(args.rows, f), dtype=np.int32)
    labels = rng.integers(0, c, size=args.rows, dtype=np.int32)
    pi = np.array([(i, j) for i in range(f) for j in range(i + 1, f)],
                  np.int32).reshape(-1, 2)
    ci, cj = jnp.asarray(pi[:, 0]), jnp.asarray(pi[:, 1])

    if args.path == "kernel":
        mode, jcp, wp = pallas_hist.plan(f, b, c)
        assert mode in ("cls", "clsb"), f"shape routes to {mode}"
        dcodes = jnp.asarray(np.ascontiguousarray(codes.T))
        dlabels = jnp.asarray(labels)

        def step(bias):
            return pallas_hist.cooc_counts_cols(dcodes, dlabels + bias, b, c)

        def chain(out):
            return (out[0, 0, 0] * 0).astype(jnp.int32)
    else:
        # the einsum path sweeps pairs in 256-pair slices — EXACTLY how
        # MutualInformation.fit's fallback runs (its pair_chunk default);
        # the unchunked nb_mi_pipeline_step call a previous version timed
        # OOMs HBM at wide F (its [N, P] broadcast intermediates scale
        # with ALL pairs at once) and would under-report the einsum
        dcodes = jnp.asarray(codes)
        dlabels = jnp.asarray(labels)
        pair_chunk = 256
        slices = [(jnp.asarray(pi[s:s + pair_chunk, 0]),
                   jnp.asarray(pi[s:s + pair_chunk, 1]))
                  for s in range(0, len(pi), pair_chunk)]

        def step(bias):
            y = dlabels + bias
            fc = agg.feature_class_counts(dcodes, y, c, b)
            outs = [agg.pair_class_counts(dcodes[:, si], dcodes[:, sj],
                                          y, c, b)
                    for si, sj in slices]
            return fc, outs[-1]

        def chain(out):
            # chain through BOTH the fc tensor and the last pair slice so
            # the final fetch barriers every dispatch of the pass
            return ((out[0][0, 0, 0] + out[1][0, 0, 0, 0]) * 0).astype(
                jnp.int32)

    def timed_pass():
        bias = jnp.int32(0)
        t0 = time.perf_counter()
        for _ in range(args.chunks):
            out = step(bias)
            bias = chain(out)
        np.asarray(bias)
        return args.chunks * args.rows / (time.perf_counter() - t0)

    timed_pass()
    timed_pass()
    passes = [timed_pass() for _ in range(args.passes)]
    med = float(np.median(passes))
    line = {
        "metric": "nb_mi_wide_schema_throughput",
        "shape": args.shape, "w": f * b * c, "path": args.path,
        "value": round(med, 1), "unit": "rows/sec/chip",
        "passes_rows_per_sec": [round(p, 1) for p in passes],
    }
    if args.path == "kernel":
        line["plan"] = list(pallas_hist.plan(f, b, c))
    print(json.dumps(line))


if __name__ == "__main__":
    main()
