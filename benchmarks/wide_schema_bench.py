#!/usr/bin/env python
"""Wide-schema NB+MI count throughput: cls-mode kernel vs the scatter einsum.

The reference handles any cardinality via lazily-sparse reducer maps
(``explore/MutualInformation.java:421-432``); round 3 covered F·B·C ≤ 768
on the MXU and silently fell back to the ~80-113M rows/s scatter einsum
above it.  Round 4's per-class gram mode ("cls" in ops/pallas_hist.plan)
keeps wide shapes on the MXU; this bench measures both paths on the same
data, fresh-process, chained-dispatch host-fetch sync.

  python benchmarks/wide_schema_bench.py --shape 20x20x2 --path kernel
  python benchmarks/wide_schema_bench.py --shape 24x32x2 --path einsum
  python benchmarks/wide_schema_bench.py --shape 11x12x2 --path pack

One (shape, path) per process run (fresh-process discipline).

``--path pack`` (PackGraft, round 16) times BOTH sides of the packing
decision on the same data — the unpacked per-table einsum fold
(fc + 256-pair slices, ChunkFolder's einsum step) vs the ONE packed
block-diagonal gram (``pallas_hist.gram_counts`` on CPU /
``cooc_counts`` where the joint shape rides the kernel) — publishing
packed-vs-unpacked efficiency points along the width curve.  Byte
identity is asserted BEFORE any rate (``counts_from_cooc`` vs the
einsum tensors), every pass carries a rig canary reading, and the
conditioned ``value_canary_clean`` convention applies; ``pack_speedup``
carries no canary fields — both sides share the rig, so contention
divides out of the ratio.
"""

import argparse
import json
import time

import numpy as np
import jax.numpy as jnp

from avenir_tpu.ops import agg, pallas_hist


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shape", default="20x20x2",
                    help="FxBxC, e.g. 20x20x2 (W=800) or 24x32x2 (W=1536)")
    ap.add_argument("--path", choices=["kernel", "einsum", "pack"],
                    default="kernel")
    ap.add_argument("--rows", type=int, default=4_000_000)
    ap.add_argument("--chunks", type=int, default=4)
    ap.add_argument("--passes", type=int, default=4)
    args = ap.parse_args()
    f, b, c = (int(x) for x in args.shape.split("x"))
    if args.path == "pack":
        return pack_main(args, f, b, c)

    rng = np.random.default_rng(0)
    codes = rng.integers(0, b, size=(args.rows, f), dtype=np.int32)
    labels = rng.integers(0, c, size=args.rows, dtype=np.int32)
    pi = np.array([(i, j) for i in range(f) for j in range(i + 1, f)],
                  np.int32).reshape(-1, 2)
    ci, cj = jnp.asarray(pi[:, 0]), jnp.asarray(pi[:, 1])

    if args.path == "kernel":
        mode, jcp, wp = pallas_hist.plan(f, b, c)
        assert mode in ("cls", "clsb"), f"shape routes to {mode}"
        dcodes = jnp.asarray(np.ascontiguousarray(codes.T))
        dlabels = jnp.asarray(labels)

        def step(bias):
            return pallas_hist.cooc_counts_cols(dcodes, dlabels + bias, b, c)

        def chain(out):
            return (out[0, 0, 0] * 0).astype(jnp.int32)
    else:
        # the einsum path sweeps pairs in 256-pair slices — EXACTLY how
        # MutualInformation.fit's fallback runs (its pair_chunk default);
        # the unchunked nb_mi_pipeline_step call a previous version timed
        # OOMs HBM at wide F (its [N, P] broadcast intermediates scale
        # with ALL pairs at once) and would under-report the einsum
        dcodes = jnp.asarray(codes)
        dlabels = jnp.asarray(labels)
        pair_chunk = 256
        slices = [(jnp.asarray(pi[s:s + pair_chunk, 0]),
                   jnp.asarray(pi[s:s + pair_chunk, 1]))
                  for s in range(0, len(pi), pair_chunk)]

        def step(bias):
            y = dlabels + bias
            fc = agg.feature_class_counts(dcodes, y, c, b)
            outs = [agg.pair_class_counts(dcodes[:, si], dcodes[:, sj],
                                          y, c, b)
                    for si, sj in slices]
            return fc, outs[-1]

        def chain(out):
            # chain through BOTH the fc tensor and the last pair slice so
            # the final fetch barriers every dispatch of the pass
            return ((out[0][0, 0, 0] + out[1][0, 0, 0, 0]) * 0).astype(
                jnp.int32)

    def timed_pass():
        bias = jnp.int32(0)
        t0 = time.perf_counter()
        for _ in range(args.chunks):
            out = step(bias)
            bias = chain(out)
        np.asarray(bias)
        return args.chunks * args.rows / (time.perf_counter() - t0)

    timed_pass()
    timed_pass()
    passes = [timed_pass() for _ in range(args.passes)]
    med = float(np.median(passes))
    line = {
        "metric": "nb_mi_wide_schema_throughput",
        "shape": args.shape, "w": f * b * c, "path": args.path,
        "value": round(med, 1), "unit": "rows/sec/chip",
        "passes_rows_per_sec": [round(p, 1) for p in passes],
    }
    if args.path == "kernel":
        line["plan"] = list(pallas_hist.plan(f, b, c))
    print(json.dumps(line))


def pack_main(args, f, b, c):
    """The --path pack sweep: unpacked per-table einsum fold vs the ONE
    packed gram, same data, byte-identity asserted before any timing."""
    from avenir_tpu.utils.rig_canary import matmul_canary_ms

    rng = np.random.default_rng(0)
    codes = rng.integers(0, b, size=(args.rows, f), dtype=np.int32)
    labels = rng.integers(0, c, size=args.rows, dtype=np.int32)
    pi = np.array([(i, j) for i in range(f) for j in range(i + 1, f)],
                  np.int32).reshape(-1, 2)
    pplan = pallas_hist.pack_tables(f, b, c, len(pi))
    if pplan is None:
        raise SystemExit(f"shape {args.shape} fails the pack gate "
                         f"(wp > WIDTH_SLACK * unpacked cells) — nothing "
                         f"to measure; pick a pair-rich shape")
    kernel = (pallas_hist.packed_applicable(pplan)
              and pallas_hist.on_tpu_single_device())
    dcodes = jnp.asarray(codes)
    dlabels = jnp.asarray(labels)
    pair_chunk = 256
    slices = [(jnp.asarray(pi[s:s + pair_chunk, 0]),
               jnp.asarray(pi[s:s + pair_chunk, 1]))
              for s in range(0, len(pi), pair_chunk)]

    def unpacked_step(bias):
        y = dlabels + bias
        fc = agg.feature_class_counts(dcodes, y, c, b)
        outs = [agg.pair_class_counts(dcodes[:, si], dcodes[:, sj], y, c, b)
                for si, sj in slices]
        return fc, outs

    def packed_step(bias):
        if kernel:
            return pallas_hist.cooc_counts(dcodes, dlabels + bias, b, c)
        return pallas_hist.gram_counts(dcodes, dlabels + bias, b, c)

    # byte-identity BEFORE any rate: the packed G's counts_from_cooc
    # read-out must equal the per-table einsum fold cell-for-cell
    fc0, pair_parts = unpacked_step(jnp.int32(0))
    fbc_u = np.asarray(fc0, np.int64)
    pcc_u = np.concatenate([np.asarray(p, np.int64) for p in pair_parts])
    fbc_p, pcc_p = pallas_hist.counts_from_cooc(
        np.asarray(packed_step(jnp.int32(0))), f, b, c, pi[:, 0], pi[:, 1])
    assert np.array_equal(fbc_u, fbc_p), "packed fbc diverges from einsum"
    assert np.array_equal(pcc_u, pcc_p), "packed pair tensor diverges"

    def chain_unpacked(out):
        return ((out[0][0, 0, 0] + out[1][-1][0, 0, 0, 0]) * 0).astype(
            jnp.int32)

    def chain_packed(out):
        flat = out.reshape(-1)
        return (flat[0] * 0).astype(jnp.int32)

    def timed_pass(step, chain):
        bias = jnp.int32(0)
        t0 = time.perf_counter()
        for _ in range(args.chunks):
            bias = chain(step(bias))
        np.asarray(bias)
        return args.chunks * args.rows / (time.perf_counter() - t0)

    results = {}
    canary_per_pass = []
    for name, step, chain in (("unpacked", unpacked_step, chain_unpacked),
                              ("packed", packed_step, chain_packed)):
        timed_pass(step, chain)
        timed_pass(step, chain)
        passes = []
        for _ in range(args.passes):
            canary_per_pass.append(matmul_canary_ms())
            passes.append(timed_pass(step, chain))
        results[name] = passes

    from avenir_tpu.telemetry.sentinel import CANARY_HEALTHY_MS
    med_u = float(np.median(results["unpacked"]))
    med_p = float(np.median(results["packed"]))
    clean = min(canary_per_pass) <= CANARY_HEALTHY_MS
    mode, _, wp = pallas_hist.plan(f, b, c)
    cells = f * b + len(pi) * b * (1 + c)
    print(json.dumps({
        "metric": "nb_mi_wide_schema_throughput",
        "shape": args.shape, "w": f * b * c, "path": "pack",
        "value": round(med_p, 1), "unit": "rows/sec/chip",
        "value_canary_clean": round(med_p, 1) if clean else None,
        "canary_per_pass_ms": [round(x, 2) for x in canary_per_pass],
        "passes_rows_per_sec": [round(p, 1) for p in results["packed"]],
        "plan": [mode, wp], "pack_signature": pplan.signature,
        "packed_device_path": ("pallas_cooc_int8_mxu" if kernel
                               else "gram_einsum"),
        "packed": {
            "packed_rows_per_sec": {
                "value": round(med_p, 1), "unit": "rows/sec/chip",
                "value_canary_clean": round(med_p, 1) if clean else None},
            "unpacked_rows_per_sec": {
                "value": round(med_u, 1), "unit": "rows/sec/chip",
                "value_canary_clean": round(med_u, 1) if clean else None},
            # both sides share the rig: contention divides out, so the
            # ratio is comparable even on canary-flagged rigs — no
            # canary fields on purpose (the sentinel compares it raw)
            "pack_speedup": {"value": round(med_p / med_u, 2),
                             "unit": "x"},
        },
        "pack_cost_model": {
            "wp": wp, "unpacked_cells": cells,
            "width_slack": pallas_hist.WIDTH_SLACK,
            "packs": wp <= pallas_hist.WIDTH_SLACK * cells},
        "byte_identical": True,
    }))


if __name__ == "__main__":
    main()
