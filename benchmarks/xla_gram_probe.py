#!/usr/bin/env python
"""What does bare XLA achieve on the co-occurrence gram shapes?

Times (a) the raw int8 matmul [W, N]·[N, W] at several W, (b) the full
XLA-only NB+MI count step: joint codes → one-hot X [N, W] int8 in HBM →
G = XᵀX, no Pallas anywhere.  If XLA's int8 gram runs near peak, the
HBM-one-hot form (round 2 dismissed it when the SCATTER was the wall) may
now beat the in-VMEM expand kernel whose dot orientation runs at <10% of
the MXU int8 peak (benchmarks/dot_orient_probe.py).

Sync: sequential launches on the single TPU compute stream execute FIFO;
one host fetch of the last result is the barrier (block_until_ready is a
no-op on the tunnel).  Sanity: per-call time must dwarf the ~1 ms chained
dispatch cost.
"""

import argparse
import functools
import json
import time

import numpy as np
import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("nc", "nb", "w"))
def onehot_gram(codes_t, labels, nc, nb, w):
    """codes_t [F, N] int32, labels [N] → G [W, W] int32 via HBM one-hot."""
    f = codes_t.shape[0]
    y = labels[None, :]
    valid = (y >= 0) & (y < nc)
    joint = jnp.where(valid, codes_t * nc + y, -1)       # [F, N]
    wcode = joint * f + jnp.arange(f, dtype=jnp.int32)[:, None]  # j-major
    wcode = jnp.where(joint >= 0, wcode, -1)
    x = jax.nn.one_hot(wcode.T, w, dtype=jnp.int8, axis=-1)      # [N, F, W]
    x = x.sum(axis=1, dtype=jnp.int8)                             # [N, W]
    return jax.lax.dot_general(x, x, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.int32)


@functools.partial(jax.jit, static_argnames=("acc",))
def gram_only(x, acc=jnp.int32):
    return jax.lax.dot_general(x, x, (((0,), (0,)), ((), ())),
                               preferred_element_type=acc)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["dot", "full"], default="dot")
    ap.add_argument("--w", type=int, default=384)
    ap.add_argument("--n", type=int, default=8_388_608)
    ap.add_argument("--reps", type=int, default=6)
    ap.add_argument("--dtype", choices=["int8", "int4", "bf16"],
                    default="int8")
    args = ap.parse_args()
    rng = np.random.default_rng(0)

    if args.mode == "dot":
        x = jnp.asarray(rng.integers(0, 2, size=(args.n, args.w),
                                     dtype=np.int8))
        acc = jnp.int32
        if args.dtype == "int4":
            x = x.astype(jnp.int4)
        elif args.dtype == "bf16":
            x = x.astype(jnp.bfloat16)
            acc = jnp.float32
        g = gram_only(x, acc)
        float(g[0, 0])                                   # compile + warm
        t0 = time.perf_counter()
        for _ in range(args.reps):
            g = gram_only(x, acc)
        float(g[0, 0])
        dt = (time.perf_counter() - t0) / args.reps
        print(json.dumps({
            "mode": "dot", "w": args.w, "n": args.n, "dtype": args.dtype,
            "ms_per_dot": round(dt * 1e3, 2),
            "eff_int8_tops": round(2.0 * args.w ** 2 * args.n / dt / 1e12, 1),
            "rows_per_sec": round(args.n / dt, 1),
        }))
        return

    nc, nb, f = 2, 12, 11
    n = args.n
    codes_t = jnp.asarray(
        rng.integers(0, nb, size=(f, n), dtype=np.int32))
    labels = jnp.asarray(rng.integers(0, nc, size=n, dtype=np.int32))
    w = -(-f * nb * nc // 128) * 128
    g = onehot_gram(codes_t, labels, nc, nb, w)
    float(g[0, 0])
    t0 = time.perf_counter()
    for _ in range(args.reps):
        g = onehot_gram(codes_t, labels + (g[0, 0] * 0).astype(jnp.int32),
                        nc, nb, w)
    float(g[0, 0])
    dt = (time.perf_counter() - t0) / args.reps
    print(json.dumps({
        "mode": "full", "w": w, "n": n,
        "ms_per_step": round(dt * 1e3, 2),
        "rows_per_sec": round(n / dt, 1),
    }))


if __name__ == "__main__":
    main()
