"""Subprocess worker for the GraftBox kill drill (round 21).

Launched by tests/test_blackbox.py with ``trace.on`` UNSET — the whole
point of the flight recorder is forensics for runs that never paid for
tracing.  Both modes share ``trace.run.id=bbdrill`` (pinned explicitly:
the crash mode's ``fault.*`` conf keys would otherwise change the
fingerprint-derived run id and split the fleet journal) and distinct
``trace.writer.suffix`` values, so the two dead workers' bundles carry
distinct writer identities under one run.

Modes (argv[1], argv[2] = scratch root):

- ``sigkill`` — arm GraftBox, train a tiny NB model through the real
  job, build a real :class:`BucketedMicrobatcher` whose flush deadline
  never fires, queue rid'd requests under a tenant label, print READY
  and spin.  The parent polls the LIVE bundle (the flush thread spills
  it continuously) until the in-flight table shows the rids, then
  SIGKILLs this process mid-flight — no hook runs; the bundle on disk
  is the only record.
- ``crash`` — arm GraftBox, run a :class:`WindowedScan` with a
  conf-armed injected fold fault that propagates UNCAUGHT: the
  excepthook writes the final bundle (ring + stacks + state) and the
  process dies nonzero.
"""

import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"


def _configure(root, suffix, extra=None):
    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.telemetry import spans as tel

    props = {"blackbox.dir": os.path.join(root, "bb"),
             "blackbox.flush.sec": "0.05",
             "trace.run.id": "bbdrill",
             "trace.writer.suffix": suffix}
    props.update(extra or {})
    conf = JobConfig(props)
    tel.configure(conf)         # arms GraftBox; trace.on stays unset
    return conf


def mode_sigkill(root):
    import json

    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.core.csv_io import write_csv
    from avenir_tpu.datagen.churn import CHURN_SCHEMA_JSON, generate_churn
    from avenir_tpu.jobs import get_job
    from avenir_tpu.jobs.base import read_lines
    from avenir_tpu.serving import BucketedMicrobatcher, ModelRegistry
    from avenir_tpu.telemetry import spans as tel

    _configure(root, "w0")
    j = lambda *p: os.path.join(root, *p)  # noqa: E731
    rows = generate_churn(120, seed=7)
    write_csv(j("train.csv"), rows[:96])
    write_csv(j("test.csv"), rows[96:])
    with open(j("churn.json"), "w") as fh:
        json.dump(CHURN_SCHEMA_JSON, fh)
    props = {"feature.schema.file.path": j("churn.json")}
    get_job("BayesianDistribution").run(JobConfig(dict(props)),
                                        j("train.csv"), j("nb_model"))
    conf = JobConfig({**props,
                      "bayesian.model.file.path": j("nb_model"),
                      "serve.models": "naiveBayes",
                      # one huge bucket + an unreachable deadline: the
                      # queued rids never drain, so they ARE the
                      # in-flight table when the SIGKILL lands
                      "serve.bucket.sizes": "64",
                      "serve.flush.deadline.ms": "60000",
                      "serve.queue.depth": "64"})
    registry = ModelRegistry.from_conf(conf)
    batcher = BucketedMicrobatcher.from_conf(registry, conf)
    lines = read_lines(j("test.csv"))
    with tel.label_scope(tenant="drill-tenant"):
        for i, line in enumerate(lines[:6]):
            batcher.submit_nowait("naiveBayes", line, rid=f"drill-{i}")
    print("READY", flush=True)
    time.sleep(300)             # the parent SIGKILLs us long before this
    raise AssertionError("parent never killed the sigkill worker")


def mode_crash(root):
    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.pipeline import scan
    from avenir_tpu.stream.windows import WindowedScan
    from avenir_tpu.utils.retry import FaultPlan

    # fault.* rides the SAME conf the blackbox arms from — proving the
    # pinned trace.run.id keeps both drill workers in one fleet run
    conf = _configure(root, "w1", extra={"fault.fold.crash.after": "2"})
    from reshard_worker import build_inputs     # same-directory helper

    enc, lines = build_inputs(n=300, f=3, b=4, c=2, fc=1)
    ws = WindowedScan(enc, [scan.NaiveBayesConsumer(name="nb")],
                      pane_rows=128, window_panes=2, slide_panes=1,
                      fault=FaultPlan.from_conf(conf))
    ws.feed(lines)              # InjectedFault propagates UNCAUGHT
    raise AssertionError("injected fold fault never fired")


def main():
    mode, root = sys.argv[1], sys.argv[2]
    if mode == "sigkill":
        mode_sigkill(root)
    elif mode == "crash":
        mode_crash(root)
    else:
        raise SystemExit(f"unknown mode {mode!r}")


if __name__ == "__main__":
    main()
