"""Test env: force an 8-device virtual CPU mesh before JAX initializes.

Multi-device sharding/collective behavior is tested without TPU hardware via
``--xla_force_host_platform_device_count`` (the capability the reference lacks
— its only multi-node test rig was a pseudo-distributed Hadoop install).
"""

import os

# Force, not setdefault: the ambient environment pins JAX_PLATFORMS at the
# real TPU tunnel, and tests must never contend for it.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# A sitecustomize on this image registers the TPU-tunnel PJRT plugin and
# overrides the jax_platforms *config* (which beats the env var), so reset the
# config too — tests run on the virtual 8-device CPU mesh only.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture()
def rng():
    # function-scoped so each test draws a deterministic stream regardless of
    # which other tests run or in what order
    return np.random.default_rng(0)
