"""Worker for the CrossGraft global-mesh SharedScan gate
(tests/test_multiprocess.py::test_crossgraft_*).

Each worker owns 4 virtual CPU devices; the hardened
``init_distributed`` joins them into one 2-process × 4-device fleet
(gloo CPU collectives — the cross-process transport the old
multiprocess-env failures were missing).  The worker then drives the
REAL CrossGraft data plane:

- ``ShardSpec.from_conf`` resolves the ``shard.*`` family to the GLOBAL
  (proc × data) hybrid mesh — the old single-process refusal is gone;
- a batch SharedScan over a ragged multi-chunk stream folds every
  consumer (NB, MI, correlation ×2, Fisher, moments) through the fused
  hierarchical-psum dispatch and must equal the worker's own LOCAL
  unsharded fold byte-for-byte (the 1-chip oracle, asserted in-process;
  process 0 also saves the tables so the parent test re-asserts against
  ITS single-chip fold in a fresh environment);
- the EQuARX-style int8 cross-host hop (``shard.allreduce.quantized``)
  must be exact at these per-device partial sizes;
- a sliding-window ``WindowedScan`` (ragged tail pane included) inherits
  the global fold through ``ChunkFolder`` and must recompile ZERO times
  after ``warm()`` (CompileKeyMonitor-asserted);
- a ``WindowCheckpointer`` snapshot is written mid-stream under the
  process-qualified topology (``:mesh:proc2xdata4``) — the parent
  resumes it on ONE process under ``shard.reshard.on.restore`` and
  asserts byte-identical remaining windows (ElasticGraft composition);
- every process journals its own shard: exactly one ``shard.topology``
  event showing the process axis, and one ``fleet.join``.

No module-level jax/avenir imports: the parent test imports
:func:`gen_data`/:func:`expected_params` without touching the worker
environment setup in :func:`main`.
"""

import os
import sys

import numpy as np

N, F, B, C, FC = 2200, 5, 6, 2, 3
CHUNK = 700                      # ragged tail: 2200 % 700 = 100
PANE_ROWS, WINDOW_PANES, SLIDE = 256, 3, 1
CKPT_FEED = 1500                 # rows fed before the mid-stream snapshot
CKPT_RUN_ID = "crossgraft-drill"


def gen_data():
    rng = np.random.default_rng(12)
    codes = rng.integers(0, B, size=(N, F)).astype(np.int32)
    # 1/16-grid continuous values: per-shard f32 partial sums exact, so
    # the hierarchically-psum'd moments match the 1-chip fold bit-for-bit
    cont = (rng.integers(0, 16, size=(N, FC)) / 16.0).astype(np.float32)
    labels = rng.integers(0, C, size=N).astype(np.int32)
    return codes, cont, labels


def mk_ds(data):
    from avenir_tpu.core.encoding import EncodedDataset

    codes, cont, labels = data
    return EncodedDataset(
        codes=codes, cont=cont, labels=labels,
        n_bins=np.full(F, B, np.int32), class_values=["a", "b"],
        binned_ordinals=list(range(F)),
        cont_ordinals=list(range(F, F + FC)))


def chunks_of(data):
    ds = mk_ds(data)
    return iter([ds.slice(i, min(i + CHUNK, N)) for i in range(0, N, CHUNK)])


def build_engine(shard=None, counters=None):
    from avenir_tpu.pipeline import scan

    eng = scan.SharedScan(shard=shard, counters=counters)
    eng.register(scan.NaiveBayesConsumer(name="nb"))
    eng.register(scan.MutualInfoConsumer(name="mi"))
    eng.register(scan.CorrelationConsumer(name="cramer", against_class=True))
    eng.register(scan.CorrelationConsumer(name="het",
                                          algorithm="uncertaintyCoeff"))
    eng.register(scan.FisherConsumer(name="fisher"))
    eng.register(scan.MomentsConsumer(name="moments"))
    return eng


def encoder_and_lines(data):
    """Schema-complete encoder + the raw CSV lines encoding back to the
    module data — the windowed-stream operand (same shape as
    tests/test_shard.py's)."""
    from avenir_tpu.core.encoding import DatasetEncoder
    from avenir_tpu.core.schema import FeatureSchema

    codes, cont, labels = data
    fields = [{"name": "id", "ordinal": 0, "id": True, "dataType": "string"}]
    for j in range(F):
        fields.append({"name": f"f{j}", "ordinal": 1 + j, "feature": True,
                       "dataType": "categorical",
                       "cardinality": [str(v) for v in range(B)]})
    for j in range(FC):
        fields.append({"name": f"x{j}", "ordinal": 1 + F + j,
                       "feature": True, "dataType": "double"})
    fields.append({"name": "cls", "ordinal": 1 + F + FC,
                   "dataType": "categorical", "cardinality": ["a", "b"]})
    enc = DatasetEncoder(FeatureSchema.from_json({"fields": fields}))
    lines = [",".join([f"r{i}"] + [str(int(v)) for v in codes[i]]
                      + [repr(float(x)) for x in cont[i]]
                      + [["a", "b"][int(labels[i])]])
             for i in range(len(labels))]
    return enc, lines


def stream_consumers():
    from avenir_tpu.pipeline import scan

    return [scan.NaiveBayesConsumer(name="nb"),
            scan.MutualInfoConsumer(name="mi")]


def results_npz(res):
    """The byte-comparable arrays of one engine run, flat for np.savez."""
    return {
        "nb_bin": np.asarray(res["nb"].bin_counts),
        "nb_class": np.asarray(res["nb"].class_counts),
        "nb_sumsq": np.asarray(res["nb"].cont_sumsq),
        "mi_pcc": np.asarray(res["mi"].pair_class_counts),
        "mi_lines": np.array("\n".join(res["mi"].to_lines())),
        "cramer_stat": np.asarray(res["cramer"].stat),
        "het_stat": np.asarray(res["het"].stat),
        "fisher_mean": np.asarray(res["fisher"].mean),
        "fisher_var": np.asarray(res["fisher"].var),
        "mom_cnt": np.asarray(res["moments"][0]),
        "mom_s2": np.asarray(res["moments"][2]),
    }


def main():
    port, pid, nprocs, outdir = sys.argv[1:5]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "").replace(
            "--xla_force_host_platform_device_count=8", "").strip() +
        " --xla_force_host_platform_device_count=4").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from avenir_tpu.parallel.mesh import init_distributed

    idx = init_distributed(coordinator_address=f"localhost:{port}",
                           num_processes=int(nprocs), process_id=int(pid),
                           timeout_s=120, attempts=3)
    assert idx == int(pid) and jax.process_count() == int(nprocs)
    assert len(jax.local_devices()) == 4

    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.parallel.shard import ShardSpec
    from avenir_tpu.stream.windows import WindowCheckpointer, WindowedScan
    from avenir_tpu.telemetry import spans as tel
    from avenir_tpu.utils.metrics import Counters

    # every process journals its own shard of one run (GraftFleet)
    tel.configure(JobConfig({"trace.on": "true",
                             "trace.journal.dir": os.path.join(outdir, "tel"),
                             "trace.run.id": "xg"}))

    spec = ShardSpec.from_conf(JobConfig({"shard.devices": "4"}))
    assert spec.is_global and spec.num_procs == int(nprocs)
    assert spec.g_suffix == f":mesh:proc{nprocs}xdata4"
    spec.announce()

    data = gen_data()
    # 1-chip oracle: the worker's own LOCAL unsharded fold
    base = build_engine().run(chunks_of(data))
    counters = Counters()
    out = build_engine(spec, counters).run(chunks_of(data))
    for key, want in results_npz(base).items():
        got = results_npz(out)[key]
        np.testing.assert_array_equal(got, want, err_msg=key)
    assert counters.get("Shard", "chunks") == 4
    assert counters.get("Shard", "collective.bytes") > 0

    # EQuARX int8 cross-host hop: exact at these per-device partials
    qspec = ShardSpec.from_conf(JobConfig({
        "shard.devices": "4", "shard.allreduce.quantized": "true"}))
    qout = build_engine(qspec).run(chunks_of(data))
    np.testing.assert_array_equal(np.asarray(qout["nb"].bin_counts),
                                  np.asarray(base["nb"].bin_counts))
    np.testing.assert_array_equal(np.asarray(qout["mi"].pair_class_counts),
                                  np.asarray(base["mi"].pair_class_counts))

    # sliding-window stream: inherits the global fold through ChunkFolder;
    # ragged tail pane; zero steady-state recompiles after warm()
    enc, lines = encoder_and_lines(data)
    ws = WindowedScan(enc, stream_consumers(), PANE_ROWS,
                      window_panes=WINDOW_PANES, slide_panes=SLIDE,
                      shard=spec)
    ws.warm()
    windows = ws.feed(lines)
    windows.extend(ws.flush())
    assert windows, "stream emitted no windows"
    assert (ws.counters.get("Stream", "recompiles") or 0) == 0, \
        "steady-state stream recompiled under the global plan"

    # mid-stream snapshot under the process-qualified topology — the
    # parent resumes it on ONE process under shard.reshard.on.restore
    ck_dir = os.path.join(outdir, f"ckpt-proc{idx}")
    ckpt = WindowCheckpointer(ck_dir, run_id=CKPT_RUN_ID, interval_panes=2)
    ws2 = WindowedScan(enc, stream_consumers(), PANE_ROWS,
                       window_panes=WINDOW_PANES, slide_panes=SLIDE,
                       shard=spec, checkpointer=ckpt)
    # no warm(): ws already compiled every pane bucket (memoized step)
    ws2.feed(lines[:CKPT_FEED])
    ckpt.save(ws2)                       # durable ring at the current pane
    # deliberately NO finish(): the snapshot must survive (kill shape)

    if idx == 0:
        saved = results_npz(out)
        saved.update({"win_nb_bin": np.stack(
            [np.asarray(w.results["nb"].bin_counts) for w in windows]),
            "win_mi_lines": np.array(
                ["\n".join(w.results["mi"].to_lines())
                 for w in windows]),
            "win_rows": np.array([w.rows for w in windows])})
        np.savez(os.path.join(outdir, "crossgraft.npz"), **saved)
    tel.tracer().disable()
    print(f"proc {idx} crossgraft ok windows={len(windows)}", flush=True)


if __name__ == "__main__":
    main()
