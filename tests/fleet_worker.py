"""Subprocess worker for the GraftFleet journal-federation gate
(tests/test_fleet.py, round 15).

Each invocation is ONE fleet writer: it configures tracing with a shared
``trace.run.id`` and its own ``trace.writer.suffix`` (so every worker
journals to its own shard of the same run), runs a REAL tiny
BayesianDistribution job — real job/chunk spans and a real counter
snapshot in the shard, not synthetic events — and then either exits
cleanly (``ok``) or dies hard via ``os._exit`` INSIDE an open span
(``crash``): the killed worker's shard must end with a ``span.open``
whose close never lands, which the merged fleet view renders as
``OPEN``.

Args: ``<journal_dir> <run_id> <suffix> <ok|crash> <workdir>``.
Prints ``fleet worker ok`` and exits 0 in ``ok`` mode.
"""

import os
import sys

# never contend for the real TPU tunnel — same discipline as
# tests/shard_worker.py (forced here, not inherited from pytest's env)
os.environ["JAX_PLATFORMS"] = "cpu"


def main() -> None:
    journal_dir, run_id, suffix, mode, workdir = sys.argv[1:6]
    import jax

    jax.config.update("jax_platforms", "cpu")

    import json

    from avenir_tpu.core.csv_io import write_csv
    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.datagen.churn import CHURN_SCHEMA_JSON, generate_churn
    from avenir_tpu.jobs import get_job
    from avenir_tpu.telemetry import spans as tel

    os.makedirs(workdir, exist_ok=True)
    train = os.path.join(workdir, "train.csv")
    schema = os.path.join(workdir, "churn.json")
    write_csv(train, generate_churn(120, seed=3))
    with open(schema, "w") as fh:
        fh.write(json.dumps(CHURN_SCHEMA_JSON)
                 if isinstance(CHURN_SCHEMA_JSON, dict)
                 else CHURN_SCHEMA_JSON)

    conf = JobConfig({
        "trace.on": "true",
        "trace.journal.dir": journal_dir,
        "trace.run.id": run_id,
        "trace.writer.suffix": suffix,
        "feature.schema.file.path": schema,
        "stream.chunk.rows": "60",
    })
    tracer = tel.configure(conf)
    assert tracer.enabled, "configure must enable this fleet writer"
    assert f".proc-0-{suffix}.jsonl" in (tracer.journal_path or ""), \
        tracer.journal_path

    # the job runs as the OUTERMOST traced unit, so its per-process
    # counter snapshot lands in this shard (Job.run skips it when a
    # pipeline stage span encloses it — the driver owns that snapshot)
    get_job("BayesianDistribution").run(
        conf, train, os.path.join(workdir, "nb_model"))
    if mode == "crash":
        with tracer.span("fleet.work", attrs={"writer": suffix}):
            # die INSIDE the span: span.open is journaled, span.close
            # never is — the preempted/killed-worker shape the merge
            # must tolerate and the tree must flag as OPEN
            os._exit(3)
    tracer.disable()
    print("fleet worker ok")


if __name__ == "__main__":
    main()
