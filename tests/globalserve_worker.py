"""Subprocess worker for the GlobalServe failover gate
(tests/test_globalserve.py, round 20).

Each invocation is ONE serving worker PROCESS of a GlobalRouter fleet:
it forces the CPU platform (never contend for a real TPU tunnel — the
same discipline as tests/fleet_worker.py) and then runs the REAL serving
CLI (``python -m avenir_tpu.serving``) with the argv passed through —
conf file, ``--http-port``, and the launcher-style ``-D`` overrides
(``trace.run.id``, per-worker tenant splits).  The journal-shard suffix
arrives via ``AVENIR_WRITER_SUFFIX``, exactly as the
:class:`~avenir_tpu.serving.global_pool.WorkerSpawner` sets it, so the
gate exercises the worker's production bring-up path end to end: env
suffix adoption, ``-D`` overrides, model load + warmup, the HTTP plane,
and — when the conf arms ``fault.serve.dispatch.crash.after`` — the
mid-batch death whose in-flight requests the router must re-score on a
survivor byte-identical to the single-plane oracle.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from avenir_tpu.serving.__main__ import main as serve_main

    raise SystemExit(serve_main(sys.argv[1:]))


if __name__ == "__main__":
    main()
