"""Worker for the multi-process JOB/CLI contract test (test_multiprocess.py).

Each process owns 4 virtual CPU devices and joins a jax.distributed run,
then executes the SAME `get_job(name).run(conf, in, out)` call a user would
— the multi-host analog of `hadoop jar avenir.jar BayesianDistribution ...`
fanning out over a cluster (BayesianDistribution.java:82).  Chunks are
round-robin assigned by the job layer, per-process partial counts are
merged at end of stream, and only process 0 writes the part file.
"""

import os
import sys


def main():
    port, pid, nprocs, workdir = sys.argv[1:5]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "").replace(
            "--xla_force_host_platform_device_count=8", "").strip() +
        " --xla_force_host_platform_device_count=4").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.jobs import get_job
    from avenir_tpu.parallel.mesh import init_distributed

    idx = init_distributed(coordinator_address=f"localhost:{port}",
                           num_processes=int(nprocs), process_id=int(pid))
    assert jax.process_count() == int(nprocs)

    # third case: one 3000-row chunk over 2 processes — process 1 owns ZERO
    # chunks and must still complete (vacuous merge contribution, no write)
    for job_name, outdir, chunk_rows in [
            ("BayesianDistribution", "out_nb_mp", "250"),
            ("MutualInformation", "out_mi_mp", "250"),
            ("BayesianDistribution", "out_nb_1chunk", "3000")]:
        conf = JobConfig()
        conf.set("feature.schema.file.path", os.path.join(workdir, "schema.json"))
        conf.set("stream.chunk.rows", chunk_rows)
        c = get_job(job_name).run(conf, os.path.join(workdir, "train.csv"),
                                  os.path.join(workdir, outdir))
        # merged counters must report the WHOLE input on every process
        assert c.get("Records", "Processed") == 3000, c.get(
            "Records", "Processed")
        if idx == 0:
            part = os.path.join(workdir, outdir, "part-00000")
            assert os.path.exists(part), "writer process produced no output"
    print(f"proc {idx} ok", flush=True)


if __name__ == "__main__":
    main()
