"""Worker for the multi-process JOB/CLI contract test (test_multiprocess.py).

Each process owns 4 virtual CPU devices and joins a jax.distributed run,
then executes the SAME `get_job(name).run(conf, in, out)` calls a user would
— the multi-host analog of `hadoop jar avenir.jar <Tool> ...` fanning out
over a cluster (the reference ran EVERY Tool across N machines:
BayesianDistribution.java:82, CramerCorrelation.java:83,
MarkovStateTransitionModel.java:60, LogisticRegressionJob.java:279-289).
Chunks are round-robin assigned by the job layer, per-process partials are
merged at end of stream (or per iteration for LR), and only process 0
writes the part file.

The job list is read from ``<workdir>/jobs.json``:
``[{"job": name, "input": path, "outdir": name, "conf": {...},
    "expect_rows": N}, ...]`` — written by the test, which also runs the
same specs single-process and compares output bytes.
"""

import json
import os
import sys


def main():
    port, pid, nprocs, workdir = sys.argv[1:5]
    jobs_file = sys.argv[5] if len(sys.argv) > 5 else "jobs.json"
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "").replace(
            "--xla_force_host_platform_device_count=8", "").strip() +
        " --xla_force_host_platform_device_count=4").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.jobs import get_job
    from avenir_tpu.parallel.mesh import init_distributed

    idx = init_distributed(coordinator_address=f"localhost:{port}",
                           num_processes=int(nprocs), process_id=int(pid))
    assert jax.process_count() == int(nprocs)

    specs = json.load(open(os.path.join(workdir, jobs_file)))
    for spec in specs:
        conf = JobConfig()
        for k, v in spec["conf"].items():
            conf.set(k, str(v))
        if spec.get("expect_crash"):
            # fault-injection leg of the kill+resume proof: the injected
            # crash must fire on every process (each at its own consumed-
            # chunk count), leaving per-process snapshots behind
            try:
                get_job(spec["job"]).run(
                    conf, os.path.join(workdir, spec["input"]),
                    os.path.join(workdir, spec["outdir"]))
            except RuntimeError as e:
                assert "injected crash" in str(e), e
                print(f"proc {idx} crashed as injected", flush=True)
                continue
            raise AssertionError("expected injected crash did not fire")
        c = get_job(spec["job"]).run(
            conf, os.path.join(workdir, spec["input"]),
            os.path.join(workdir, spec["outdir"]))
        # merged counters must report the WHOLE input on every process
        if "expect_rows" in spec:
            got = c.get("Records", "Processed")
            assert got == spec["expect_rows"], (spec["job"], got)
        if idx == 0:
            part = os.path.join(workdir, spec["outdir"], "part-00000")
            assert os.path.exists(part), \
                f"writer produced no output for {spec['job']}"
    print(f"proc {idx} ok", flush=True)


if __name__ == "__main__":
    main()
