"""Worker for the REAL multi-process multi-host test (test_multiprocess.py).

Each process owns 4 virtual CPU devices; jax.distributed.initialize joins
them into one 8-device run (2 processes = 2 "hosts" over the local
coordinator — the CPU stand-in for DCN). Exercises the actual multi-host
code paths: init_distributed, make_hybrid_mesh off its single-slice
fallback, process_local_batch via make_array_from_process_local_data, and
the sharded NB/LR SPMD steps whose psums now cross process boundaries.
"""

import os
import sys


def main():
    port, pid, nprocs, outdir = sys.argv[1:5]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "").replace(
            "--xla_force_host_platform_device_count=8", "").strip() +
        " --xla_force_host_platform_device_count=4").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from avenir_tpu.parallel import collectives as coll
    from avenir_tpu.parallel.mesh import (init_distributed, make_hybrid_mesh,
                                          process_local_batch)

    idx = init_distributed(coordinator_address=f"localhost:{port}",
                           num_processes=int(nprocs), process_id=int(pid))
    assert idx == int(pid), (idx, pid)
    assert jax.process_count() == int(nprocs), jax.process_count()
    assert len(jax.local_devices()) == 4
    assert jax.device_count() == 4 * int(nprocs)

    mesh = make_hybrid_mesh(("data",))
    assert mesh.shape["data"] == jax.device_count()

    # deterministic GLOBAL dataset; each process feeds only its row range
    rng = np.random.default_rng(0)
    n, f, b, c, fc = 4096, 6, 5, 2, 3
    codes = rng.integers(0, b, size=(n, f), dtype=np.int32)
    labels = rng.integers(0, c, size=n, dtype=np.int32)
    cont = rng.random((n, fc)).astype(np.float32)
    half = n // int(nprocs)
    lo, hi = idx * half, (idx + 1) * half

    step = coll.sharded_nb_fit_step(mesh, c, b, fc)
    g_codes = process_local_batch(mesh, codes[lo:hi])
    g_labels = process_local_batch(mesh, labels[lo:hi])
    g_cont = process_local_batch(mesh, cont[lo:hi])
    fbc, cc, _, s1, s2 = step(g_codes, g_labels, g_cont)

    d = 4
    x = rng.random((n, d)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    w = np.zeros(d, np.float32)
    lr_step = coll.sharded_lr_step(mesh)
    g_x = process_local_batch(mesh, x[lo:hi])
    g_y = process_local_batch(mesh, y[lo:hi])
    w1 = lr_step(w, g_x, g_y, float(n), 0.5, 0.01)
    w2 = lr_step(np.asarray(w1), g_x, g_y, float(n), 0.5, 0.01)

    if idx == 0:
        np.savez(os.path.join(outdir, "result.npz"),
                 fbc=np.asarray(fbc), cc=np.asarray(cc),
                 s1=np.asarray(s1), s2=np.asarray(s2),
                 w2=np.asarray(w2))
    # every process must agree on the replicated outputs
    print(f"proc {idx} ok cc={np.asarray(cc).tolist()}", flush=True)


if __name__ == "__main__":
    main()
