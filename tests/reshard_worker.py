"""Subprocess worker for the ElasticGraft preemption drill (round 16).

Launched by tests/test_reshard.py with ``JAX_PLATFORMS=cpu`` and
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` set EXPLICITLY in
the child environment (the tests/shard_worker.py discipline): the
8-device host mesh is forced here, not inherited, so the gate holds in
any environment with zero TPUs attached.

The drill — ROADMAP open item 3 as a machine-checked artifact:

1. run a sharded WindowedScan on an 8-device mesh with pane-ring
   checkpoints and a conf-driven injected kill mid-fold
   (``fault.fold.crash.after`` — utils/retry.FaultPlan);
2. resume the SAME stream on a 4-device mesh with
   ``shard.reshard.on.restore=true``: the snapshot's mesh-qualified
   accumulator state is redistributed (``checkpoint/reshard.py``), and
   every window emitted after the resume must be byte-identical to the
   unkilled SINGLE-CHIP run's — for every SharedScan consumer (NB, MI,
   correlation, Fisher, moments);
3. the same kill → reshard → resume at the JOB level (StreamAnalytics):
   the resumed part file must equal the unkilled unsharded run's tail
   byte-for-byte, and the journal must carry the golden-schema'd
   ``fault.injected`` and ``checkpoint.reshard`` events that explain the
   drill.

Prints ``reshard worker ok`` and exits 0 on success.
"""

import os
import sys

# the mesh must exist before jax initializes — the whole point of running
# in a fresh subprocess (the parent cannot re-shape an initialized jax)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402


def build_inputs(n, f, b, c, fc):
    """A schema-complete encoder + the raw CSV rows of a synthetic
    labeled stream (1/16-grid continuous values: pane/shard f32 partial
    sums are exact, so moment tables are byte-identical under ANY
    summation order — the docs/streaming.md scope)."""
    from avenir_tpu.core.encoding import DatasetEncoder
    from avenir_tpu.core.schema import FeatureSchema

    rng = np.random.default_rng(16)
    codes = rng.integers(0, b, size=(n, f)).astype(np.int32)
    cont = (rng.integers(0, 16, size=(n, fc)) / 16.0).astype(np.float32)
    labels = rng.integers(0, c, size=n).astype(np.int32)
    fields = [{"name": "id", "ordinal": 0, "id": True, "dataType": "string"}]
    for j in range(f):
        fields.append({"name": f"f{j}", "ordinal": 1 + j, "feature": True,
                       "dataType": "categorical",
                       "cardinality": [str(v) for v in range(b)]})
    for j in range(fc):
        fields.append({"name": f"x{j}", "ordinal": 1 + f + j,
                       "feature": True, "dataType": "double"})
    fields.append({"name": "cls", "ordinal": 1 + f + fc,
                   "dataType": "categorical", "cardinality": ["a", "b"]})
    enc = DatasetEncoder(FeatureSchema.from_json({"fields": fields}))
    lines = [",".join([f"r{i}"] + [str(int(v)) for v in codes[i]]
                      + [repr(float(x)) for x in cont[i]]
                      + [["a", "b"][int(labels[i])]])
             for i in range(n)]
    return enc, lines


def drill_windowed_scan(enc, lines, tmp):
    """Kill on 8 mid-fold, resume on 4, byte-identical to the unkilled
    1-chip fold — at WindowedScan level, every consumer."""
    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.parallel.shard import ShardSpec
    from avenir_tpu.pipeline import scan
    from avenir_tpu.stream.windows import WindowCheckpointer, WindowedScan
    from avenir_tpu.utils.retry import FaultPlan, InjectedFault

    def spec(d):
        return ShardSpec.from_conf(JobConfig({"shard.devices": str(d)}))

    def consumers():
        return [scan.NaiveBayesConsumer(name="nb"),
                scan.MutualInfoConsumer(name="mi"),
                scan.CorrelationConsumer(name="cramer", against_class=True),
                scan.FisherConsumer(name="fisher"),
                scan.MomentsConsumer(name="moments")]

    def windowed(shard=None, checkpointer=None, fault=None):
        return WindowedScan(enc, consumers(), pane_rows=256, window_panes=2,
                            slide_panes=1, shard=shard,
                            checkpointer=checkpointer, fault=fault)

    # the oracle: the UNKILLED 1-chip (unsharded) fold
    oracle_ws = windowed()
    oracle = oracle_ws.feed(lines)
    oracle.extend(oracle_ws.flush())
    assert oracle, "oracle emitted no windows"

    # kill on 8: injected fault at the 3rd pane-fold boundary (one
    # snapshot already durable at pane 2)
    ckdir = os.path.join(tmp, "ring")
    crashed = windowed(
        shard=spec(8),
        checkpointer=WindowCheckpointer(ckdir, run_id="drill",
                                        interval_panes=2),
        fault=FaultPlan({"fold": 3}))
    try:
        crashed.feed(lines)
        raise AssertionError("injected fold fault never fired")
    except InjectedFault:
        pass
    assert os.listdir(ckdir), "no snapshot survived the kill"

    # resume on 4: redistribution gated ON
    ck4 = WindowCheckpointer(ckdir, run_id="drill", interval_panes=2,
                             resume=True, reshard=True)
    resumed_ws = windowed(shard=spec(4), checkpointer=ck4)
    skip = ck4.restore_into(resumed_ws)
    assert 0 < skip < len(lines), skip
    resumed = resumed_ws.feed(lines[skip:])
    resumed.extend(resumed_ws.flush())
    assert resumed_ws.windows_emitted == len(oracle)

    eq = np.testing.assert_array_equal
    by_index = {w.index: w for w in resumed}
    compared = 0
    for want in oracle:
        got = by_index.get(want.index)
        if got is None:
            continue                    # emitted before the kill
        eq(got.results["nb"].bin_counts, want.results["nb"].bin_counts)
        eq(got.results["nb"].class_counts, want.results["nb"].class_counts)
        eq(got.results["nb"].cont_sum, want.results["nb"].cont_sum)
        eq(got.results["nb"].cont_sumsq, want.results["nb"].cont_sumsq)
        eq(got.results["mi"].feature_class_counts,
           want.results["mi"].feature_class_counts)
        eq(got.results["mi"].pair_class_counts,
           want.results["mi"].pair_class_counts)
        assert got.results["mi"].to_lines() == want.results["mi"].to_lines()
        eq(got.results["cramer"].contingency,
           want.results["cramer"].contingency)
        assert (got.results["cramer"].to_lines()
                == want.results["cramer"].to_lines())
        eq(got.results["fisher"].mean, want.results["fisher"].mean)
        eq(got.results["fisher"].var, want.results["fisher"].var)
        for g, w in zip(got.results["moments"], want.results["moments"]):
            eq(g, w)
        compared += 1
    assert compared, "resume emitted no window the oracle also emitted"
    return compared


def drill_job_level(enc, lines, tmp):
    """The same kill → reshard → resume through StreamAnalytics: resumed
    part file == the unkilled unsharded run's tail, and the journal
    carries fault.injected + checkpoint.reshard."""
    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.jobs import get_job
    from avenir_tpu.telemetry import spans as tel
    from avenir_tpu.telemetry.journal import read_events
    from avenir_tpu.utils.retry import InjectedFault

    import json

    data = os.path.join(tmp, "data.csv")
    with open(data, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    schema_path = os.path.join(tmp, "schema.json")
    with open(schema_path, "w") as fh:
        json.dump(enc.schema.to_json(), fh)
    tel_dir = os.path.join(tmp, "tel")
    props = {"feature.schema.file.path": schema_path,
             "stream.pane.rows": "256", "stream.window.panes": "2",
             "stream.slide.panes": "1",
             "stream.consumers": "classDistribution,naiveBayes",
             "stream.checkpoint.dir": os.path.join(tmp, "jring"),
             "stream.checkpoint.interval.panes": "2",
             "trace.on": "true", "trace.journal.dir": tel_dir}

    # the unkilled UNSHARDED oracle (no checkpoint dir: it must not share
    # the drill's ring, and a clean finish would sweep it anyway)
    golden_props = {k: v for k, v in props.items()
                    if not k.startswith("stream.checkpoint")}
    get_job("StreamAnalytics").run(JobConfig(dict(golden_props)), data,
                                   os.path.join(tmp, "out_golden"))
    golden = open(os.path.join(tmp, "out_golden", "part-00000")).read()

    # kill on 8 mid-fold
    try:
        get_job("StreamAnalytics").run(
            JobConfig({**props, "shard.devices": "8",
                       "fault.fold.crash.after": "3"}),
            data, os.path.join(tmp, "out_killed"))
        raise AssertionError("injected fold fault never fired")
    except InjectedFault:
        pass
    assert not os.path.exists(os.path.join(tmp, "out_killed"))

    # resume on 4, redistribution ON
    counters = get_job("StreamAnalytics").run(
        JobConfig({**props, "shard.devices": "4", "stream.resume": "true",
                   "shard.reshard.on.restore": "true"}),
        data, os.path.join(tmp, "out_resumed"))
    tel.tracer().disable()
    resumed = open(os.path.join(tmp, "out_resumed", "part-00000")).read()
    windows = counters.get("Stream", "windows")
    assert windows and windows > 0
    # the resumed run re-emits exactly the tail of the golden output
    assert resumed and golden.endswith(resumed), (
        "resumed job output is not the unkilled unsharded run's tail:\n"
        f"golden tail:\n{golden[-400:]}\nresumed:\n{resumed[-400:]}")

    events = []
    for name in sorted(os.listdir(tel_dir)):
        if name.endswith(".jsonl"):
            events.extend(read_events(os.path.join(tel_dir, name)))
    by_ev = {}
    for e in events:
        by_ev.setdefault(e.get("ev"), []).append(e)
    faults = by_ev.get("fault.injected", [])
    assert [e["site"] for e in faults] == ["fold"], faults
    reshards = by_ev.get("checkpoint.reshard", [])
    assert len(reshards) == 1, reshards
    assert reshards[0]["src"] == ":mesh:data8"
    assert reshards[0]["dst"] == ":mesh:data4"
    assert reshards[0]["keys"] > 0
    assert by_ev.get("checkpoint.restore"), "no checkpoint.restore event"
    return windows


def main() -> None:
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    assert jax.device_count() == 8, jax.devices()

    # 800 rows / 256-row panes: 3 full panes + a ragged 32-row tail pane
    # at flush — a snapshot lands at pane 2 before the 3rd-fold kill,
    # few enough dispatches to keep the tier-1 gate fast
    enc, lines = build_inputs(n=800, f=4, b=5, c=2, fc=2)
    with tempfile.TemporaryDirectory() as tmp:
        compared = drill_windowed_scan(enc, lines, tmp)
        windows = drill_job_level(enc, lines, tmp)
    print(f"windows compared: {compared} (scan) / {windows} (job)")
    print("reshard worker ok")


if __name__ == "__main__":
    main()
