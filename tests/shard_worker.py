"""Subprocess worker for the ShardGraft byte-identity gate (round 12).

Launched by tests/test_shard.py with ``JAX_PLATFORMS=cpu`` and
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` set EXPLICITLY in
the child environment — the 8-device host mesh is forced here, not
inherited from however pytest was invoked, so the sharded == single-chip
assertion holds in any environment with zero TPUs attached.

Asserts, per consumer (NB / MI / correlation / Fisher / moments):
sharded SharedScan fold == single-chip fold, byte-for-byte, over a
multi-chunk stream with a ragged tail — and the same for the streaming
window path (WindowedScan with a ShardSpec vs the unsharded scan),
including a ragged tail pane.  Prints ``shard worker ok`` and exits 0 on
success; any mismatch raises and the parent surfaces the output.
"""

import os
import sys

# the mesh must exist before jax initializes — this is the whole point of
# running in a subprocess (the parent cannot re-shape an initialized jax)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    assert jax.device_count() == 8, jax.devices()

    from avenir_tpu.core.config import JobConfig
    from avenir_tpu.core.encoding import EncodedDataset
    from avenir_tpu.parallel.shard import ShardSpec
    from avenir_tpu.pipeline import scan
    from avenir_tpu.stream.windows import WindowedScan

    n, f, b, c, fc = 1500, 4, 5, 2, 2
    rng = np.random.default_rng(3)
    codes = rng.integers(0, b, size=(n, f)).astype(np.int32)
    # 1/16-grid values: pane/shard-partial f32 sums are exact, so the
    # moment tables are byte-identical under ANY summation order
    cont = (rng.integers(0, 16, size=(n, fc)) / 16.0).astype(np.float32)
    labels = rng.integers(0, c, size=n).astype(np.int32)
    ds = EncodedDataset(
        codes=codes, cont=cont, labels=labels,
        n_bins=np.full(f, b, np.int32), class_values=["a", "b"],
        binned_ordinals=list(range(f)),
        cont_ordinals=list(range(f, f + fc)))

    def chunks():
        # 700/700/100: the tail exercises the ragged pow-2 staging path
        return iter([ds.slice(i, min(i + 700, n)) for i in range(0, n, 700)])

    def engine(shard=None):
        eng = scan.SharedScan(shard=shard)
        eng.register(scan.NaiveBayesConsumer(name="nb"))
        eng.register(scan.MutualInfoConsumer(name="mi"))
        eng.register(scan.CorrelationConsumer(name="cramer",
                                              against_class=True))
        eng.register(scan.FisherConsumer(name="fisher"))
        eng.register(scan.MomentsConsumer(name="moments"))
        return eng

    spec = ShardSpec.from_conf(JobConfig({"shard.devices": "8"}))
    assert spec.num_devices == 8
    base = engine().run(chunks())
    out = engine(spec).run(chunks())

    eq = np.testing.assert_array_equal
    eq(out["nb"].bin_counts, base["nb"].bin_counts)
    eq(out["nb"].class_counts, base["nb"].class_counts)
    eq(out["nb"].cont_count, base["nb"].cont_count)
    eq(out["nb"].cont_sum, base["nb"].cont_sum)
    eq(out["nb"].cont_sumsq, base["nb"].cont_sumsq)
    eq(out["mi"].feature_class_counts, base["mi"].feature_class_counts)
    eq(out["mi"].pair_class_counts, base["mi"].pair_class_counts)
    assert out["mi"].to_lines() == base["mi"].to_lines()
    eq(out["cramer"].contingency, base["cramer"].contingency)
    assert out["cramer"].to_lines() == base["cramer"].to_lines()
    eq(out["fisher"].mean, base["fisher"].mean)
    eq(out["fisher"].var, base["fisher"].var)
    for got, want in zip(out["moments"], base["moments"]):
        eq(got, want)

    # streaming window path: sharded panes == unsharded panes, ragged tail
    # pane included (1500 % 256 != 0)
    lines = [",".join([f"r{i}"] + [str(int(v)) for v in codes[i]]
                      + [repr(float(x)) for x in cont[i]]
                      + [["a", "b"][int(labels[i])]])
             for i in range(n)]

    from avenir_tpu.core.schema import FeatureSchema
    from avenir_tpu.core.encoding import DatasetEncoder

    fields = [{"name": "id", "ordinal": 0, "id": True, "dataType": "string"}]
    for j in range(f):
        fields.append({"name": f"f{j}", "ordinal": 1 + j, "feature": True,
                       "dataType": "categorical",
                       "cardinality": [str(v) for v in range(b)]})
    for j in range(fc):
        fields.append({"name": f"x{j}", "ordinal": 1 + f + j,
                       "feature": True, "dataType": "double"})
    fields.append({"name": "cls", "ordinal": 1 + f + fc,
                   "dataType": "categorical", "cardinality": ["a", "b"]})
    enc = DatasetEncoder(FeatureSchema.from_json({"fields": fields}))

    def windows(shard=None):
        ws = WindowedScan(
            enc, [scan.NaiveBayesConsumer(name="nb"),
                  scan.MutualInfoConsumer(name="mi")],
            pane_rows=256, window_panes=2, slide_panes=1, shard=shard)
        ws.warm()
        got = ws.feed(lines)
        got.extend(ws.flush())
        return got

    plain, sharded = windows(), windows(spec)
    assert len(plain) == len(sharded) and plain, len(plain)
    for wp, wsh in zip(plain, sharded):
        eq(wsh.results["nb"].bin_counts, wp.results["nb"].bin_counts)
        eq(wsh.results["nb"].cont_sumsq, wp.results["nb"].cont_sumsq)
        eq(wsh.results["mi"].pair_class_counts,
           wp.results["mi"].pair_class_counts)
        assert wsh.results["mi"].to_lines() == wp.results["mi"].to_lines()

    print("shard worker ok")


if __name__ == "__main__":
    main()
