"""Aggregation kernels vs numpy oracles; sharded execution; info stats."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from avenir_tpu.ops import agg, info
from avenir_tpu.parallel import mesh as pmesh


def _np_feature_class_counts(codes, labels, C, B):
    F = codes.shape[1]
    out = np.zeros((F, B, C), np.int64)
    for n in range(codes.shape[0]):
        for f in range(F):
            if codes[n, f] >= 0 and labels[n] >= 0:
                out[f, codes[n, f], labels[n]] += 1
    return out


def test_feature_class_counts_oracle(rng):
    codes = rng.integers(0, 6, size=(500, 4)).astype(np.int32)
    labels = rng.integers(0, 3, size=500).astype(np.int32)
    got = np.asarray(agg.feature_class_counts(jnp.asarray(codes), jnp.asarray(labels), 3, 6))
    np.testing.assert_array_equal(got, _np_feature_class_counts(codes, labels, 3, 6))
    # class + feature marginals agree
    np.testing.assert_array_equal(
        np.asarray(agg.class_counts(jnp.asarray(labels), 3)), got.sum(axis=(0, 1)) // 4)
    np.testing.assert_array_equal(
        np.asarray(agg.feature_counts(jnp.asarray(codes), 6)), got.sum(axis=2))


def test_negative_index_is_count_neutral(rng):
    """-1 padding must not contribute to any count (one_hot drops it)."""
    codes = rng.integers(0, 5, size=(100, 3)).astype(np.int32)
    labels = rng.integers(0, 2, size=100).astype(np.int32)
    base = np.asarray(agg.feature_class_counts(jnp.asarray(codes), jnp.asarray(labels), 2, 5))
    padded_codes, padded_labels = pmesh.pad_batch(128, codes, labels)
    assert padded_codes.shape == (128, 3) and (padded_codes[100:] == -1).all()
    padded = np.asarray(agg.feature_class_counts(jnp.asarray(padded_codes), jnp.asarray(padded_labels), 2, 5))
    np.testing.assert_array_equal(base, padded)


def test_pair_counts_oracle(rng):
    a = rng.integers(0, 4, size=(300, 2)).astype(np.int32)
    b = rng.integers(0, 4, size=(300, 2)).astype(np.int32)
    got = np.asarray(agg.pair_counts(jnp.asarray(a), jnp.asarray(b), 4))
    for p in range(2):
        expect = np.zeros((4, 4), np.int64)
        for n in range(300):
            expect[a[n, p], b[n, p]] += 1
        np.testing.assert_array_equal(got[p], expect)


def test_class_moments_oracle(rng):
    vals = rng.normal(size=(400, 3)).astype(np.float32)
    labels = rng.integers(0, 2, size=400).astype(np.int32)
    cnt, s1, s2 = agg.class_moments(jnp.asarray(vals), jnp.asarray(labels), 2)
    for c in range(2):
        m = labels == c
        np.testing.assert_allclose(np.asarray(cnt)[c], m.sum(), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(s1)[c], vals[m].sum(0), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(s2)[c], (vals[m] ** 2).sum(0), rtol=1e-4)


def test_transition_counts(rng):
    a = rng.integers(0, 3, size=200).astype(np.int32)
    b = rng.integers(0, 5, size=200).astype(np.int32)
    got = np.asarray(agg.transition_counts(jnp.asarray(a), jnp.asarray(b), 3, 5))
    expect = np.zeros((3, 5), np.int64)
    for x, y in zip(a, b):
        expect[x, y] += 1
    np.testing.assert_array_equal(got, expect)


def test_sharded_counts_match_single_device(rng):
    """Counts under a sharded jit over the 8-device CPU mesh == local counts.

    This is the MR-shuffle replacement: per-device partial einsum (the
    'combiner') + XLA-inserted all-reduce (the 'shuffle').
    """
    assert jax.device_count() >= 8, "conftest must provide 8 virtual devices"
    m = pmesh.make_mesh(("data",))
    codes = rng.integers(0, 7, size=(1000, 5)).astype(np.int32)
    labels = rng.integers(0, 3, size=1000).astype(np.int32)
    local = np.asarray(agg.feature_class_counts(jnp.asarray(codes), jnp.asarray(labels), 3, 7))
    sc, sl = pmesh.device_put_sharded_batch(m, codes, labels)
    sharded = np.asarray(agg.feature_class_counts(sc, sl, 3, 7))
    np.testing.assert_array_equal(local, sharded)


def test_entropy_gini():
    p = jnp.array([0.5, 0.5])
    np.testing.assert_allclose(float(info.entropy(p)), np.log(2), rtol=1e-6)
    np.testing.assert_allclose(float(info.gini(p)), 0.5, rtol=1e-6)
    counts = jnp.array([2.0, 2.0, 0.0])
    np.testing.assert_allclose(float(info.entropy_from_counts(counts)), np.log(2), rtol=1e-6)


def test_mutual_information_independent_and_dependent():
    # independent: uniform 2x2 grid -> MI 0
    indep = jnp.array([[25.0, 25.0], [25.0, 25.0]])
    np.testing.assert_allclose(float(info.mutual_information(indep)), 0.0, atol=1e-6)
    # perfectly dependent -> MI = log 2
    dep = jnp.array([[50.0, 0.0], [0.0, 50.0]])
    np.testing.assert_allclose(float(info.mutual_information(dep)), np.log(2), rtol=1e-5)
    # joint entropy of uniform 2x2 = log 4
    np.testing.assert_allclose(float(info.joint_entropy(indep)), np.log(4), rtol=1e-6)


def test_mutual_information_vs_sklearn(rng):
    sklearn_metrics = pytest.importorskip("sklearn.metrics")
    x = rng.integers(0, 4, size=2000)
    y = (x + rng.integers(0, 2, size=2000)) % 4
    joint = np.zeros((4, 4))
    for a, b in zip(x, y):
        joint[a, b] += 1
    got = float(info.mutual_information(jnp.asarray(joint)))
    expect = sklearn_metrics.mutual_info_score(x, y)
    np.testing.assert_allclose(got, expect, rtol=1e-4)


def test_cramer_index_vs_oracle(rng):
    scipy_stats = pytest.importorskip("scipy.stats")
    joint = rng.integers(1, 50, size=(3, 4)).astype(np.float64)
    got = float(info.cramer_index(jnp.asarray(joint)))
    chi2 = scipy_stats.chi2_contingency(joint, correction=False)[0]
    expect = chi2 / (joint.sum() * min(3 - 1, 4 - 1))   # Cramér's V²
    np.testing.assert_allclose(got, expect, rtol=1e-4)


def test_uncertainty_and_concentration_bounds(rng):
    joint = rng.integers(1, 30, size=(4, 3)).astype(np.float64)
    u = float(info.uncertainty_coefficient(jnp.asarray(joint)))
    t = float(info.concentration_coefficient(jnp.asarray(joint)))
    assert 0.0 <= u <= 1.0
    assert 0.0 <= t <= 1.0
    # perfect association -> both 1
    perfect = jnp.eye(3) * 10
    np.testing.assert_allclose(float(info.uncertainty_coefficient(perfect)), 1.0, rtol=1e-5)
    np.testing.assert_allclose(float(info.concentration_coefficient(perfect)), 1.0, rtol=1e-5)


def test_conditional_mutual_information():
    # X,Y independent given Z but dependent marginally
    # counts[x, y, z]: within each z slice, independent uniform
    c = np.zeros((2, 2, 2))
    c[:, :, 0] = [[20, 5], [5, 20]]
    c[:, :, 1] = [[5, 20], [20, 5]]
    cmi = float(info.conditional_mutual_information(jnp.asarray(c)))
    # per-slice MI is equal; CMI should equal slice MI
    mi0 = float(info.mutual_information(jnp.asarray(c[:, :, 0])))
    np.testing.assert_allclose(cmi, mi0, rtol=1e-5)


def test_pair_class_counts_out_of_range_labels_dropped():
    # the joint (bin_j, class) one-hot must preserve one_hot's drop-invalid
    # contract: a -1 (mesh pad) or >=C label contributes nothing, never
    # aliases into a neighboring (bin, class) cell
    import jax.numpy as jnp

    codes_i = jnp.asarray([[1], [2], [2]], jnp.int32)
    codes_j = jnp.asarray([[3], [0], [1]], jnp.int32)
    labels = jnp.asarray([0, -1, 2], jnp.int32)           # only row 0 valid
    out = np.asarray(agg.pair_class_counts(codes_i, codes_j, labels, 2, 5))
    assert out.sum() == 1
    assert out[0, 1, 3, 0] == 1
