"""graftlint (avenir_tpu/analysis) — fixture snippets per rule (positive
must fail without the rule, negative must stay clean), the suppression /
baseline / registry mechanics, the CLI contract, and the live whole-tree
gate: the entire ``avenir_tpu/`` + ``benchmarks/`` + ``bench.py`` tree must
carry zero non-baselined findings — graftlint is tier-1 CI from day one.

Pure stdlib + the analysis package: no jax import anywhere here, so the
lint gate also attests that ``avenir_tpu.analysis`` stays importable
without a device runtime.
"""

import json
import pathlib
import subprocess
import sys

import pytest

from avenir_tpu.analysis import engine
from avenir_tpu.analysis import program
from avenir_tpu.analysis import registry_gen

REPO = pathlib.Path(__file__).resolve().parent.parent

# every fixture is (rule, should_fire, source) — config_keys passed where
# GL004 needs a registry
GL001_POS = """\
import os
from avenir_tpu.parallel.mesh import all_process_sum_state

def merge_resume(path):
    text = open(path).read()          # unguarded divergent read
    return all_process_sum_state({"h": text})
"""

GL001_NEG_GUARDED = """\
import jax
from avenir_tpu.parallel.mesh import all_process_sum_state

def merge_resume(path):
    state = {}
    if jax.process_index() == 0:
        state["h"] = open(path).read()     # writer-guarded: broadcast via
    return all_process_sum_state(state)    # the collective itself
"""

GL001_NEG_NO_SINK = """\
def local_read(path):
    return open(path).read()          # no collective in sight
"""

GL002_POS_SNAPSHOT = """\
def snapshot(mgr, acc, cur):
    mgr.save(1, {"acc": acc, "cursor": cur, "rows": 7})
"""

GL002_NEG_SNAPSHOT = """\
def snapshot(mgr, acc, cur, rid):
    mgr.save(1, {"acc": acc, "cursor": cur, "rows": 7, "run": rid})
"""

GL002_POS_KEY = """\
def accumulate(acc, chunks):
    for s, tensor in chunks:
        acc.add(f"c{s}", tensor)
"""

GL002_NEG_KEY = """\
def accumulate(acc, chunks, fingerprint):
    for s, tensor in chunks:
        acc.add(f"{fingerprint}:{s}", tensor)
"""

GL003_POS = """\
def key_for(idx):
    return f"g{idx:08d}"
"""

GL003_NEG = """\
def key_for(idx):
    if idx >= 10 ** 8:
        raise ValueError("index exceeds the 8-digit key width")
    return f"g{idx:08d}"
"""

GL004_SRC = """\
def run(conf):
    return conf.get_int("some.key", 1)
"""

GL004_NEG_DICT = """\
def run(merged):
    return merged.get("rows", 0)      # plain dict, not a JobConfig
"""

GL005_POS_FLOAT = """\
import jax.numpy as jnp

def fold(chunks):
    tot = 0.0
    for c in chunks:
        s = jnp.sum(c)
        tot += float(s)               # per-chunk host sync
    return tot
"""

GL005_POS_ITEM = """\
def fold(chunks):
    tot = 0.0
    for c in chunks:
        tot += c.sum().item()
    return tot
"""

GL005_POS_DEVICE_GET = """\
import jax

def fold(levels, step):
    out = []
    while levels:
        out.append(jax.device_get(step(levels.pop())))
    return out
"""

GL005_NEG_OUTSIDE = """\
import jax.numpy as jnp

def fold(chunks):
    s = jnp.sum(jnp.stack(list(chunks)))
    return float(s)                   # one sync after the loop-free reduce
"""

GL005_NEG_ON_HOST = """\
import jax.numpy as jnp
from avenir_tpu.ops.info import on_host

def fold(chunks):
    out = []
    with on_host():
        for c in chunks:
            s = jnp.sum(c)
            out.append(float(s))      # explicit host-compute escape hatch
    return out
"""


GL006_POS_DIRECT = """\
import threading

_lock = threading.Lock()

def flush(path, rows):
    with _lock:
        with open(path, "a") as fh:       # file I/O under a held lock
            fh.write(str(rows))
"""

GL006_NEG_DEFERRED = """\
import threading

_lock = threading.Lock()

def flush(path, rows):
    fires = []
    with _lock:
        fires.append(("tenant.throttled", {"rows": rows}))
    with open(path, "a") as fh:           # I/O after the release
        fh.write(str(rows))
"""

GL006_NEG_FILELOCK = """\
from avenir_tpu.utils.locking import FileLock

def flush(path):
    lock = FileLock(path + ".lock")
    with lock:                            # cross-process file lock, not a
        with open(path, "a") as fh:       # threading lock — I/O is its job
            fh.write("x")
"""

GL009_POS = """\
import threading

def work(results):
    results.append(1 / 0)

def spawn(results):
    t = threading.Thread(target=work, args=(results,), daemon=True)
    t.start()
    return t
"""

GL009_NEG_ROUTED = """\
import threading

def work(results, errors):
    try:
        results.append(1 / 0)
    except Exception as e:
        errors.append(e)                  # routed: the spawner drains it

def spawn(results, errors):
    t = threading.Thread(target=work, args=(results, errors), daemon=True)
    t.start()
    return t
"""

GL010_POS_GUARDED = """\
def run(conf):
    path = conf.get("some.key")
    if not path:
        raise ValueError("missing input location")
"""

GL010_NEG_TYPED = """\
from avenir_tpu.core.config import ConfigError

def run(conf):
    path = conf.get("some.key")
    if not path:
        raise ConfigError("missing input location")
"""

GL010_NEG_INTERNAL = """\
def check(x):
    if x < 0:
        raise ValueError("negative input")   # not a conf-contract path
"""

GL011_POS = """\
def announce(tracer, devices):
    tracer.event("shard.topology", devices=devices)
"""

GL011_NEG = """\
def announce(tracer, devices):
    tracer.event_once("shard.topology", devices=devices)
"""

GL012_POS = """\
def cleanup(sock):
    try:
        sock.close()
    except Exception:
        pass
"""

GL012_NEG_RERAISE = """\
def cleanup(sock):
    try:
        sock.close()
    except Exception:
        raise
"""

GL012_NEG_IMPORT_PROBE = """\
def maybe_accel():
    try:
        import jax
    except Exception:
        pass                              # optional-dependency probe
    else:
        return jax
    return None
"""


def lint_src(tmp_path, src, config_keys=None, name="snippet.py",
             baseline_path=None):
    f = tmp_path / name
    f.write_text(src)
    return engine.run_paths([str(f)], root=str(tmp_path),
                            baseline_path=baseline_path,
                            config_keys=config_keys)


FIXTURES = [
    ("GL001", True, GL001_POS),
    ("GL001", False, GL001_NEG_GUARDED),
    ("GL001", False, GL001_NEG_NO_SINK),
    ("GL002", True, GL002_POS_SNAPSHOT),
    ("GL002", False, GL002_NEG_SNAPSHOT),
    ("GL002", True, GL002_POS_KEY),
    ("GL002", False, GL002_NEG_KEY),
    ("GL003", True, GL003_POS),
    ("GL003", False, GL003_NEG),
    ("GL005", True, GL005_POS_FLOAT),
    ("GL005", True, GL005_POS_ITEM),
    ("GL005", True, GL005_POS_DEVICE_GET),
    ("GL005", False, GL005_NEG_OUTSIDE),
    ("GL005", False, GL005_NEG_ON_HOST),
    ("GL006", True, GL006_POS_DIRECT),
    ("GL006", False, GL006_NEG_DEFERRED),
    ("GL006", False, GL006_NEG_FILELOCK),
    ("GL009", True, GL009_POS),
    ("GL009", False, GL009_NEG_ROUTED),
    ("GL010", True, GL010_POS_GUARDED),
    ("GL010", False, GL010_NEG_TYPED),
    ("GL010", False, GL010_NEG_INTERNAL),
    ("GL011", True, GL011_POS),
    ("GL011", False, GL011_NEG),
    ("GL012", True, GL012_POS),
    ("GL012", False, GL012_NEG_RERAISE),
    ("GL012", False, GL012_NEG_IMPORT_PROBE),
]


@pytest.mark.parametrize("rule,fires,src", FIXTURES,
                         ids=[f"{r}-{'pos' if p else 'neg'}-{i}"
                              for i, (r, p, _) in enumerate(FIXTURES)])
def test_rule_fixture(tmp_path, rule, fires, src):
    found = [f for f in lint_src(tmp_path, src, config_keys={})
             if f.rule == rule]
    if fires:
        assert found, f"{rule} should fire on:\n{src}"
    else:
        assert not found, (f"{rule} must stay quiet on:\n{src}\n"
                           + "\n".join(f.format() for f in found))


def test_gl004_unknown_undocumented_and_known(tmp_path):
    unknown = lint_src(tmp_path, GL004_SRC, config_keys={})
    assert [f.rule for f in unknown] == ["GL004"]
    assert "unknown config key 'some.key'" in unknown[0].message

    undoc = lint_src(tmp_path, GL004_SRC, config_keys={"some.key": None})
    assert [f.rule for f in undoc] == ["GL004"]
    assert "undocumented" in undoc[0].message

    ok = lint_src(tmp_path, GL004_SRC,
                  config_keys={"some.key": "docs/jobs.md"})
    assert not ok

    assert not lint_src(tmp_path, GL004_NEG_DICT, config_keys={})


def test_gl004_registry_matches_tree():
    """The checked-in registry is exactly what a regeneration produces —
    i.e. nobody added a conf key without regenerating (the GL004 contract
    that code and registry can never drift apart silently)."""
    from avenir_tpu.analysis.config_registry import CONFIG_KEYS

    code = registry_gen.scan_code_keys(
        [str(REPO / "avenir_tpu"), str(REPO / "benchmarks"),
         str(REPO / "bench.py")])
    assert sorted(code) == sorted(CONFIG_KEYS), (
        "config_registry.py is stale — run "
        "`python -m avenir_tpu.analysis --write-registry`")
    undocumented = sorted(k for k, v in CONFIG_KEYS.items() if v is None)
    assert not undocumented, (
        f"undocumented config keys: {undocumented} — add them to "
        f"docs/jobs.md and regenerate the registry")


def test_registry_generator_roundtrip(tmp_path):
    (tmp_path / "mod.py").write_text(
        "def run(conf):\n"
        "    return conf.get('a.b'), conf.get_bool('c.d')\n")
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "ref.md").write_text(
        "Keys: `a.b` (a thing), and fenced blocks must not desync:\n"
        "```\nconf `not.this` stuff\n```\n`-Dc.d=true` works too.\n")
    out = tmp_path / "registry.py"
    registry = registry_gen.write_registry(
        [str(tmp_path / "mod.py")], [str(docs)], root=str(tmp_path),
        out_path=str(out))
    assert registry == {"a.b": "docs/ref.md", "c.d": "docs/ref.md"}
    ns: dict = {}
    exec(out.read_text(), ns)                 # the generated file is valid
    assert ns["CONFIG_KEYS"] == registry


# -- suppression / baseline mechanics ------------------------------------

def test_suppression_same_line_and_line_above(tmp_path):
    inline = GL005_POS_ITEM.replace(
        "tot += c.sum().item()",
        "tot += c.sum().item()  # graftlint: disable=GL005")
    assert not lint_src(tmp_path, inline, config_keys={})

    above = GL005_POS_ITEM.replace(
        "        tot += c.sum().item()",
        "        # graftlint: disable=GL005\n"
        "        tot += c.sum().item()")
    assert not lint_src(tmp_path, above, config_keys={})

    # suppressing a DIFFERENT rule must not hide the finding
    wrong = GL005_POS_ITEM.replace(
        "tot += c.sum().item()",
        "tot += c.sum().item()  # graftlint: disable=GL003")
    assert [f.rule for f in lint_src(tmp_path, wrong, config_keys={})] \
        == ["GL005"]


def test_suppression_file_wide(tmp_path):
    src = "# graftlint: disable-file=GL003\n" + GL003_POS
    assert not lint_src(tmp_path, src, config_keys={})


def test_baseline_pass_and_new_finding_fails(tmp_path):
    """The three-way contract: suppressed line → pass, baselined legacy
    finding → pass, NEW finding → fail."""
    live = lint_src(tmp_path, GL003_POS, config_keys={},
                    name="legacy.py")
    assert len(live) == 1 and not live[0].baselined

    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"findings": [
        {"rule": live[0].rule, "path": live[0].path,
         "message": live[0].message, "why": "grandfathered for the test"}
    ]}))
    again = lint_src(tmp_path, GL003_POS, config_keys={},
                     name="legacy.py", baseline_path=str(bl))
    assert len(again) == 1 and again[0].baselined

    fresh = lint_src(tmp_path, GL003_POS, config_keys={},
                     name="fresh.py", baseline_path=str(bl))
    assert len(fresh) == 1 and not fresh[0].baselined


def test_write_baseline_preserves_existing_whys(tmp_path):
    """--write-baseline must merge: entries still matching a finding keep
    their curated why; only genuinely new findings get stubs (code-review
    finding — a rewrite used to drop every grandfathered entry)."""
    (tmp_path / "legacy.py").write_text(GL003_POS)
    (tmp_path / "fresh.py").write_text(GL003_POS)
    bl = tmp_path / "baseline.json"
    legacy = lint_src(tmp_path, GL003_POS, config_keys={},
                      name="legacy.py")[0]
    bl.write_text(json.dumps({"findings": [
        {"rule": legacy.rule, "path": legacy.path,
         "message": legacy.message, "why": "curated reason"}]}))
    findings = engine.run_paths(
        [str(tmp_path / "legacy.py"), str(tmp_path / "fresh.py")],
        root=str(tmp_path), baseline_path=str(bl), config_keys={})
    engine.write_baseline(str(bl), findings,
                          existing=engine.load_baseline(str(bl)))
    merged = json.loads(bl.read_text())["findings"]
    whys = {e["path"]: e["why"] for e in merged}
    assert whys["legacy.py"] == "curated reason"
    assert "FILL ME IN" in whys["fresh.py"]


def test_baseline_requires_why(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"findings": [
        {"rule": "GL003", "path": "x.py", "message": "m", "why": ""}]}))
    with pytest.raises(ValueError, match="why"):
        engine.load_baseline(str(bl))


def test_syntax_error_reports_gl000(tmp_path):
    findings = lint_src(tmp_path, "def broken(:\n", config_keys={})
    assert [f.rule for f in findings] == ["GL000"]


# -- the whole-program pass (GL006/GL007/GL008) ---------------------------

def _mini_schema(tmp_path, events=("known.event",), once=()):
    p = tmp_path / "mini_schema.py"
    p.write_text(
        "GOLDEN_EVENT_KEYS = {\n"
        + "".join(f'    "{e}": ("ev", "ts"),\n' for e in events)
        + "}\n"
        + f"EVENT_ONCE = {set(once)!r}\n")
    return program.load_event_schema(str(p), explicit=True)


def test_gl006_cross_file_reachability(tmp_path):
    """The tentpole case GL006 exists for: the I/O sits in ANOTHER module,
    reached transitively from inside the held region."""
    (tmp_path / "iohelp.py").write_text(
        "def persist(path):\n"
        "    with open(path, 'a') as fh:\n"
        "        fh.write('x')\n")
    (tmp_path / "hot.py").write_text(
        "import threading\n"
        "from iohelp import persist\n"
        "\n"
        "_lock = threading.Lock()\n"
        "\n"
        "def flush(path):\n"
        "    with _lock:\n"
        "        persist(path)\n")
    findings = engine.run_paths([str(tmp_path)], root=str(tmp_path),
                                baseline_path=None, config_keys={})
    gl6 = [f for f in findings if f.rule == "GL006"]
    assert [f.path for f in gl6] == ["hot.py"], \
        "\n".join(f.format() for f in findings)
    assert "iohelp.py::persist" in gl6[0].message


def test_gl007_unknown_event_and_liveness(tmp_path):
    schema = _mini_schema(tmp_path, events=("known.event",))
    (tmp_path / "emit.py").write_text(
        'def go(tracer):\n'
        '    tracer.event("zorp.mystery", x=1)\n')
    findings = engine.run_paths([str(tmp_path / "emit.py")],
                                root=str(tmp_path), baseline_path=None,
                                config_keys={}, event_schema=schema)
    gl7 = [f for f in findings if f.rule == "GL007"]
    assert any("'zorp.mystery'" in f.message and f.path == "emit.py"
               for f in gl7), "\n".join(f.format() for f in gl7)
    assert any("'known.event'" in f.message and "no live emit site"
               in f.message for f in gl7)


def test_gl007_literal_emit_and_deferred_tuple_both_count_live(tmp_path):
    """A deferred-fire tuple (the arbiter's fires-list pattern) satisfies
    the liveness direction without ever being treated as a literal emit —
    so config-key tuples can't trip the unknown-name direction."""
    schema = _mini_schema(tmp_path, events=("known.event",))
    (tmp_path / "emit.py").write_text(
        'def go(tracer, fires):\n'
        '    fires.append(("known.event", {"x": 1}))\n')
    findings = engine.run_paths([str(tmp_path / "emit.py")],
                                root=str(tmp_path), baseline_path=None,
                                config_keys={}, event_schema=schema)
    assert not [f for f in findings if f.rule == "GL007"], \
        "\n".join(f.format() for f in findings)


def test_gl007_seeded_schema_drift_fires_on_real_tree(tmp_path):
    """The acceptance drill: mutate a copy of the golden schema (rename
    span.open → span.opened) and prove the cross-file pass catches BOTH
    drift directions over the live tree — the real emit site becomes
    unknown, the renamed schema entry goes dead."""
    real = (REPO / "avenir_tpu" / "telemetry" / "schema.py").read_text()
    assert real.count('"span.open"') == 1
    mutated = tmp_path / "mutated_schema.py"
    mutated.write_text(real.replace('"span.open"', '"span.opened"'))
    schema = program.load_event_schema(str(mutated), explicit=True)
    tree = [str(REPO / "avenir_tpu"), str(REPO / "benchmarks"),
            str(REPO / "bench.py")]
    gl7 = [f for f in engine.run_paths(tree, root=str(REPO),
                                       baseline_path=None,
                                       rules={"GL007": None},
                                       event_schema=schema)
           if f.rule == "GL007"]
    assert any("'span.open'" in f.message
               and f.path == "avenir_tpu/telemetry/spans.py"
               for f in gl7), "\n".join(f.format() for f in gl7)
    assert any("'span.opened'" in f.message and "no live emit site"
               in f.message for f in gl7)
    # control: the unmutated schema, same explicit liveness mode, is clean
    clean = program.load_event_schema(
        str(REPO / "avenir_tpu" / "telemetry" / "schema.py"),
        explicit=True)
    assert not [f for f in engine.run_paths(tree, root=str(REPO),
                                            baseline_path=None,
                                            rules={"GL007": None},
                                            event_schema=clean)
                if f.rule == "GL007"]


def test_gl008_unknown_undocumented_and_wildcard(tmp_path):
    src = (
        "def count(counters, model):\n"
        '    counters.increment("Zorp", "n")\n'
        '    counters.increment(f"Serving.{model}", "n")\n')
    (tmp_path / "mod.py").write_text(src)

    def run(reg):
        return [f for f in engine.run_paths(
            [str(tmp_path / "mod.py")], root=str(tmp_path),
            baseline_path=None, config_keys={}, counter_registry=reg)
            if f.rule == "GL008"]

    both = run({"groups": {}, "spans": {}})
    assert len(both) == 2                   # Zorp + Serving.* both unknown
    undoc = run({"groups": {"Zorp": None, "Serving.*": "docs/a.md"},
                 "spans": {}})
    assert len(undoc) == 1 and "Zorp" in undoc[0].message
    clean = run({"groups": {"Zorp": "docs/a.md", "Serving.*": "docs/a.md"},
                 "spans": {}})
    assert not clean
    # test files are exempt — fixture groups are deliberate
    (tmp_path / "test_mod.py").write_text(src)
    assert not [f for f in engine.run_paths(
        [str(tmp_path / "test_mod.py")], root=str(tmp_path),
        baseline_path=None, config_keys={},
        counter_registry={"groups": {}, "spans": {}})
        if f.rule == "GL008"]


def test_counter_registry_matches_tree():
    """Same staleness contract as the config registry: the checked-in
    counter/span registry is exactly what a regeneration produces, and
    nothing in it is undocumented."""
    from avenir_tpu.analysis.counter_registry import (COUNTER_GROUPS,
                                                      SPAN_SITES)
    groups, spans = registry_gen.scan_counter_span_sites(
        [str(REPO / "avenir_tpu"), str(REPO / "benchmarks"),
         str(REPO / "bench.py")])
    assert sorted(groups) == sorted(COUNTER_GROUPS) and \
        sorted(spans) == sorted(SPAN_SITES), (
        "counter_registry.py is stale — run "
        "`python -m avenir_tpu.analysis --write-registry`")
    undocumented = sorted(k for k, v in {**COUNTER_GROUPS,
                                         **SPAN_SITES}.items() if v is None)
    assert not undocumented, (
        f"undocumented counter groups / spans: {undocumented} — document "
        f"them (docs/observability.md has the group table) and regenerate")


# -- facts cache + incremental (--changed) mechanics ----------------------

def test_cache_warm_hits_and_salt_invalidation(tmp_path):
    (tmp_path / "a.py").write_text(GL003_NEG)
    (tmp_path / "b.py").write_text(GL003_NEG)
    cache = tmp_path / "cache.json"

    def run(config_keys={}):
        stats: dict = {}
        findings = engine.run_paths(
            [str(tmp_path / "a.py"), str(tmp_path / "b.py")],
            root=str(tmp_path), baseline_path=None,
            config_keys=config_keys, cache_path=str(cache), stats=stats)
        return findings, stats

    _, cold = run()
    assert cold["files"] == 2 and cold["cache_hits"] == 0
    _, warm = run()
    assert warm["cache_hits"] == 2
    # a different rule-parameter fingerprint must invalidate the cache
    _, salted = run(config_keys={"some.key": "docs/x.md"})
    assert salted["cache_hits"] == 0


def test_changed_set_trusts_git_over_disk(tmp_path):
    """--changed semantics: a cached file NOT in the changed set is reused
    without re-reading — mutations git doesn't report are invisible until
    the file enters the changed set (or the cache is dropped)."""
    b = tmp_path / "b.py"
    (tmp_path / "a.py").write_text(GL003_NEG)
    b.write_text(GL003_NEG)
    cache = tmp_path / "cache.json"

    def run(changed=None):
        return engine.run_paths(
            [str(tmp_path / "a.py"), str(b)], root=str(tmp_path),
            baseline_path=None, config_keys={}, cache_path=str(cache),
            changed=changed)

    assert not [f for f in run() if f.rule == "GL003"]
    b.write_text(GL003_POS)               # now violating, on disk only
    assert not [f for f in run(changed=set()) if f.rule == "GL003"], \
        "a file outside the changed set must be served from cache unread"
    hot = [f for f in run(changed={"b.py"}) if f.rule == "GL003"]
    assert [f.path for f in hot] == ["b.py"]


# -- CLI contract ---------------------------------------------------------

def _run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "avenir_tpu.analysis", *args],
        cwd=cwd, capture_output=True, text=True,
        env={"PYTHONPATH": str(REPO), "PATH": "/usr/bin:/bin"})


def test_cli_findings_format_and_exit_code(tmp_path):
    (tmp_path / "bad.py").write_text(GL003_POS)
    res = _run_cli(["bad.py", "--no-baseline"], cwd=str(tmp_path))
    assert res.returncode == 1
    assert res.stdout.startswith("bad.py:2: GL003 ")
    assert "graftlint: 1 finding(s)" in res.stderr

    res_json = _run_cli(["bad.py", "--no-baseline", "--json"],
                        cwd=str(tmp_path))
    payload = json.loads(res_json.stdout)
    assert payload[0]["rule"] == "GL003" and payload[0]["path"] == "bad.py"


def test_cli_clean_exits_zero(tmp_path):
    (tmp_path / "ok.py").write_text(GL003_NEG)
    res = _run_cli(["ok.py"], cwd=str(tmp_path))
    assert res.returncode == 0, res.stdout + res.stderr


def test_cli_stats_and_cache(tmp_path):
    (tmp_path / "ok.py").write_text(GL003_NEG)
    cold = _run_cli(["ok.py", "--stats"], cwd=str(tmp_path))
    assert cold.returncode == 0
    assert "graftlint stats: 1 files" in cold.stderr
    assert "0 cache hits" in cold.stderr
    warm = _run_cli(["ok.py", "--stats"], cwd=str(tmp_path))
    assert "1 cache hits" in warm.stderr
    uncached = _run_cli(["ok.py", "--stats", "--no-cache"],
                        cwd=str(tmp_path))
    assert "0 cache hits" in uncached.stderr


def test_cli_changed_outside_git_falls_back_to_full_run(tmp_path):
    # tmp_path is no git worktree: --changed must degrade to a full run,
    # not crash or silently lint nothing
    (tmp_path / "bad.py").write_text(GL003_POS)
    res = _run_cli(["bad.py", "--changed", "--no-baseline"],
                   cwd=str(tmp_path))
    assert res.returncode == 1
    assert "GL003" in res.stdout


def test_cli_check_registry_up_to_date():
    res = _run_cli(["--check-registry"], cwd=str(REPO))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "registries up to date" in res.stdout


# -- the live gate: the whole tree, as CI ---------------------------------

def test_whole_tree_zero_nonbaselined_findings():
    # tests/test_serving.py rides the gate too (round 9): serving tests
    # drive the hot dispatch loop directly, exactly where a per-iteration
    # host sync (GL005) or an undocumented serve.* key (GL004) would hide.
    # tests/test_telemetry.py likewise (round 10) — telemetry tests drive
    # traced pipelines end-to-end, where an undocumented trace.* key or a
    # sync-in-loop would hide (avenir_tpu/telemetry/ itself is inside the
    # avenir_tpu tree the gate already walks)
    # tests/test_stream.py likewise (round 11) — stream tests drive the
    # windowed fold + checkpoint + drift→swap loops, where an undocumented
    # stream.* key (GL004) or unfingerprinted snapshot (GL002) would hide
    # tests/test_shard.py + shard_worker.py likewise (round 12) — the
    # ShardGraft byte-identity gate drives the sharded fold loop, where an
    # undocumented shard.* key (GL004) or a sync-in-loop (GL005) would hide
    # tests/test_tree.py likewise (round 13) — the TreeGraft hist-mode
    # byte-identity gate drives the per-level selection loop, where an
    # undocumented tree.hist.* key (GL004) or a sync-in-loop (GL005)
    # would hide
    # tests/test_profile.py likewise (round 14) — the GraftProf tests
    # drive profiled dispatch loops + the sentinel CLI, where an
    # undocumented profile.* key (GL004) or a sync-in-loop (GL005)
    # would hide (telemetry/profile.py + sentinel.py themselves sit
    # inside the avenir_tpu tree the gate already walks)
    # tests/test_fleet.py + fleet_worker.py likewise (round 15) — the
    # GraftFleet tests drive federated journals, the skew probe and the
    # SLO CLI, where an undocumented trace.*/shard.skew.*/slo.* key
    # (GL004) or a sync-in-loop around the probe (GL005) would hide
    # tests/test_reshard.py + reshard_worker.py likewise (round 16) —
    # the ElasticGraft preemption drill drives checkpoint save/restore/
    # reshard loops, where an undocumented shard.reshard.*/fault.* key
    # (GL004) or an unfingerprinted snapshot (GL002) would hide
    # tests/test_pool.py likewise (round 17) — the FleetServe tests
    # drive pool routing/failover/autoscale loops, where an undocumented
    # pool.*/fault.serve.* key (GL004) or a sync-in-loop around the
    # burst timing (GL005) would hide (serving/pool.py itself sits
    # inside the avenir_tpu tree; benchmarks/serving_soak.py inside the
    # benchmarks tree the gate already walks)
    # tests/test_tenancy.py likewise (round 18) — the GraftPool tests
    # drive the tenant arbiter + the multi-tenant soak smoke, where an
    # undocumented tenant.*/fault.tenant.* key (GL004) or a sync-in-loop
    # around the DRR harness (GL005) would hide (avenir_tpu/tenancy/ and
    # benchmarks/tenancy_soak.py sit inside trees the gate already walks)
    # tests/crossgraft_worker.py + test_multiprocess.py likewise (this
    # round) — the CrossGraft global-mesh gate drives the multi-process
    # fold + launcher + elastic restore, where an undocumented shard.*
    # key (GL004), an unguarded writer near the join collective (GL001),
    # or a sync-in-loop around the fused dispatch (GL005) would hide
    # (avenir_tpu/launch/ itself sits inside the avenir_tpu tree)
    # tests/test_plan.py likewise (round 19) — the PlanGraft byte-identity
    # gate drives the planner's rewrite/fallback drills, where an
    # undocumented plan.*/pipeline.* key (GL004) or a sync-in-loop around
    # the measured-dispatch cost probes (GL005) would hide
    # (pipeline/plan.py itself sits inside the avenir_tpu tree)
    # round 20 (graftlint v2): the same walk now also runs the whole-
    # program rules — I/O under held locks (GL006), golden-schema event
    # drift in both directions (GL007, liveness included because
    # telemetry/schema.py sits inside the walked tree), counter/span
    # registry drift (GL008) — plus the new local rules GL009–GL012;
    # designed exceptions live in baseline.json, each with a why
    # tests/test_globalserve.py + globalserve_worker.py likewise
    # (round 20) — the GlobalServe gate drives the cross-process router
    # (breaker, failover byte-identity, rolling fleet swap), where an
    # undocumented fleet.pool.* key (GL004) or a fleet.pool.* event
    # drifting from telemetry/schema.py (GL007) would hide
    findings = engine.run_paths(
        [str(REPO / "avenir_tpu"), str(REPO / "benchmarks"),
         str(REPO / "bench.py"), str(REPO / "tests" / "test_serving.py"),
         str(REPO / "tests" / "test_telemetry.py"),
         str(REPO / "tests" / "test_stream.py"),
         str(REPO / "tests" / "test_shard.py"),
         str(REPO / "tests" / "shard_worker.py"),
         str(REPO / "tests" / "test_tree.py"),
         str(REPO / "tests" / "test_profile.py"),
         str(REPO / "tests" / "test_fleet.py"),
         str(REPO / "tests" / "fleet_worker.py"),
         str(REPO / "tests" / "test_reshard.py"),
         str(REPO / "tests" / "reshard_worker.py"),
         str(REPO / "tests" / "test_pool.py"),
         str(REPO / "tests" / "test_tenancy.py"),
         str(REPO / "tests" / "crossgraft_worker.py"),
         str(REPO / "tests" / "test_multiprocess.py"),
         str(REPO / "tests" / "test_plan.py"),
         str(REPO / "tests" / "test_globalserve.py"),
         str(REPO / "tests" / "globalserve_worker.py")],
        root=str(REPO))
    live = [f for f in findings if not f.baselined]
    assert not live, (
        "graftlint found new hazards (fix them, suppress with a "
        "why-comment, or — for legacy findings only — baseline them):\n"
        + "\n".join(f.format() for f in live))
    # the baseline must stay honest too: every entry still matches a real
    # finding (a fixed finding must leave the baseline when it's fixed)
    matched = {f.key for f in findings if f.baselined}
    stale = [e for e in engine.load_baseline(engine.BASELINE_PATH)
             if (e["rule"], e["path"], e["message"]) not in matched]
    assert not stale, f"baseline entries no longer match any finding: {stale}"
