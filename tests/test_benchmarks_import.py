"""Import-smoke for every ``benchmarks/*.py`` module.

The probes only run by hand on the dev rig, so they rot silently when a
library symbol they import moves (round-7 CI satellite): importing each
module compiles it and resolves its module-scope imports without running
any measurement (they all gate work behind ``__main__``/``main()``)."""

import importlib
import pathlib

import pytest

_BENCH_DIR = pathlib.Path(__file__).resolve().parent.parent / "benchmarks"
_MODULES = sorted(p.stem for p in _BENCH_DIR.glob("*.py")
                  if not p.stem.startswith("_"))


def test_benchmarks_exist():
    assert _MODULES, f"no benchmark modules found under {_BENCH_DIR}"


@pytest.mark.parametrize("mod", _MODULES)
def test_benchmark_module_imports(mod):
    importlib.import_module(f"benchmarks.{mod}")
