"""Import-smoke + lint gate for every ``benchmarks/*.py`` module.

The probes only run by hand on the dev rig, so they rot silently when a
library symbol they import moves (round-7 CI satellite): importing each
module compiles it and resolves its module-scope imports without running
any measurement (they all gate work behind ``__main__``/``main()``).

Round 8 adds graftlint over the same modules (plus ``bench.py``): probe
scripts are exactly where host-sync-per-iteration timing bugs (GL005 —
the r05 RTT-wall class the honest-sync discipline exists for) sneak back
in, so the hazard rules gate them like library code."""

import importlib
import pathlib

import pytest

_BENCH_DIR = pathlib.Path(__file__).resolve().parent.parent / "benchmarks"
_MODULES = sorted(p.stem for p in _BENCH_DIR.glob("*.py")
                  if not p.stem.startswith("_"))


def test_benchmarks_exist():
    assert _MODULES, f"no benchmark modules found under {_BENCH_DIR}"


@pytest.mark.parametrize("mod", _MODULES)
def test_benchmark_module_imports(mod):
    importlib.import_module(f"benchmarks.{mod}")


def test_bench_sentinel_wiring_importable():
    """bench.py now ends every capture with the in-process regression
    sentinel; this pins the wiring it relies on (import + a verdict on a
    minimal line) without running a measurement — the sentinel must stay
    callable from a bare capture environment (stdlib-only)."""
    from avenir_tpu.telemetry import sentinel

    summary = sentinel.evaluate(
        {"metric": "m", "value": 100.0, "unit": "u"},
        {"metric": "m", "value": 100.0, "unit": "u"})
    assert summary["verdict"] == "pass"
    assert sentinel.exit_code("regression") == sentinel.EXIT_REGRESSION
    assert sentinel.bench_verdict(
        {"metric": "m", "value": 1.0}, "/nonexistent/baseline.json"
    )["verdict"] == "no_baseline"


def test_serving_soak_smoke():
    """A miniature FleetServe chaos soak through the IDENTICAL code path
    the dev-rig benchmark runs (round 17): bursty mixed-model traffic
    against a 2-replica pool, a conf-armed mid-soak replica kill, a
    rolling hot-swap, the autoscaler replacing the lost capacity, and
    the `telemetry slo` exit-0 gate over the merged journal — plus the
    zero-lost / zero-double-scored accounting run_soak itself asserts.
    Generous p99 target: the smoke pins CORRECTNESS of the failure path
    on a shared CI rig, not rig speed (the benchmark pins that)."""
    from benchmarks.serving_soak import run_soak

    artifact = run_soak(bursts=6, scale=0.12, p99_target_ms=60_000.0,
                        shed_target=0.2, canary=False)
    assert artifact["slo_exit"] == 0
    assert artifact["steady_state_recompiles_total"] == 0
    assert artifact["replicas_lost"] == 1
    assert artifact["pool_events"]["pool.replica.down"] >= 1
    assert artifact["pool_events"]["pool.scale"] >= 1
    assert artifact["ok"] + artifact["shed"] == artifact["requests"]


def test_benchmarks_lint_clean():
    from avenir_tpu.analysis import engine

    repo = _BENCH_DIR.parent
    findings = engine.run_paths([str(_BENCH_DIR), str(repo / "bench.py")],
                                root=str(repo))
    live = [f for f in findings if not f.baselined]
    assert not live, (
        "graftlint hazards in the benchmark probes (a timing loop that "
        "syncs per iteration measures the RTT, not the kernel):\n"
        + "\n".join(f.format() for f in live))
