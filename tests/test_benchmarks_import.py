"""Import-smoke + lint gate for every ``benchmarks/*.py`` module.

The probes only run by hand on the dev rig, so they rot silently when a
library symbol they import moves (round-7 CI satellite): importing each
module compiles it and resolves its module-scope imports without running
any measurement (they all gate work behind ``__main__``/``main()``).

Round 8 adds graftlint over the same modules (plus ``bench.py``): probe
scripts are exactly where host-sync-per-iteration timing bugs (GL005 —
the r05 RTT-wall class the honest-sync discipline exists for) sneak back
in, so the hazard rules gate them like library code."""

import importlib
import pathlib

import pytest

_BENCH_DIR = pathlib.Path(__file__).resolve().parent.parent / "benchmarks"
_MODULES = sorted(p.stem for p in _BENCH_DIR.glob("*.py")
                  if not p.stem.startswith("_"))


def test_benchmarks_exist():
    assert _MODULES, f"no benchmark modules found under {_BENCH_DIR}"


@pytest.mark.parametrize("mod", _MODULES)
def test_benchmark_module_imports(mod):
    importlib.import_module(f"benchmarks.{mod}")


def test_bench_sentinel_wiring_importable():
    """bench.py now ends every capture with the in-process regression
    sentinel; this pins the wiring it relies on (import + a verdict on a
    minimal line) without running a measurement — the sentinel must stay
    callable from a bare capture environment (stdlib-only)."""
    from avenir_tpu.telemetry import sentinel

    summary = sentinel.evaluate(
        {"metric": "m", "value": 100.0, "unit": "u"},
        {"metric": "m", "value": 100.0, "unit": "u"})
    assert summary["verdict"] == "pass"
    assert sentinel.exit_code("regression") == sentinel.EXIT_REGRESSION
    assert sentinel.bench_verdict(
        {"metric": "m", "value": 1.0}, "/nonexistent/baseline.json"
    )["verdict"] == "no_baseline"


def test_serving_soak_smoke():
    """A miniature FleetServe chaos soak through the IDENTICAL code path
    the dev-rig benchmark runs (round 17): bursty mixed-model traffic
    against a 2-replica pool, a conf-armed mid-soak replica kill, a
    rolling hot-swap, the autoscaler replacing the lost capacity, and
    the `telemetry slo` exit-0 gate over the merged journal — plus the
    zero-lost / zero-double-scored accounting run_soak itself asserts.
    Generous p99 target: the smoke pins CORRECTNESS of the failure path
    on a shared CI rig, not rig speed (the benchmark pins that)."""
    from benchmarks.serving_soak import run_soak

    artifact = run_soak(bursts=6, scale=0.12, p99_target_ms=60_000.0,
                        shed_target=0.2, canary=False)
    assert artifact["slo_exit"] == 0
    assert artifact["steady_state_recompiles_total"] == 0
    assert artifact["replicas_lost"] == 1
    assert artifact["pool_events"]["pool.replica.down"] >= 1
    assert artifact["pool_events"]["pool.scale"] >= 1
    assert artifact["ok"] + artifact["shed"] == artifact["requests"]


def test_plan_explain_cli_smoke(tmp_path, capsys):
    """`python -m avenir_tpu.pipeline plan explain <conf>` end to end over
    a conf-DECLARED pipeline (round 19): the verb must stay runnable from
    a bare properties file — it is the operator's only pre-flight view of
    what PlanGraft will fuse — and must print the unit tree with costs
    WITHOUT executing any stage (no workspace artifacts appear)."""
    import json

    from avenir_tpu.core.csv_io import write_csv
    from avenir_tpu.datagen.churn import CHURN_SCHEMA_JSON, generate_churn
    from avenir_tpu.pipeline.__main__ import main

    write_csv(str(tmp_path / "train.csv"), generate_churn(300, seed=3))
    (tmp_path / "churn.json").write_text(json.dumps(CHURN_SCHEMA_JSON))
    conf = tmp_path / "pipeline.properties"
    conf.write_text("\n".join([
        f"feature.schema.file.path={tmp_path / 'churn.json'}",
        f"pipeline.workspace={tmp_path / 'ws'}",
        f"pipeline.bind.data={tmp_path / 'train.csv'}",
        "pipeline.stages=bayesianDistr,mutualInfo",
        "pipeline.stage.bayesianDistr.job=BayesianDistribution",
        "pipeline.stage.bayesianDistr.input=data",
        "pipeline.stage.bayesianDistr.output=nb_model",
        "pipeline.stage.mutualInfo.job=MutualInformation",
        "pipeline.stage.mutualInfo.input=data",
        "pipeline.stage.mutualInfo.output=mi_out",
    ]) + "\n")
    assert main(["plan", "explain", str(conf)]) == 0
    out = capsys.readouterr().out
    assert "PlanGraft" in out and "rewrites: fuse" in out
    assert "bayesianDistr" in out and "mutualInfo" in out
    assert "MFLOP" in out                      # per-node cost line rendered
    assert not (tmp_path / "ws" / "nb_model").exists()   # plan != run


def test_planner_lint_clean():
    """The planner + its CLI lint clean on their own (round 19): plan.py
    hosts measured-dispatch timing loops — exactly the GL005 shape the
    benchmark gate below exists for — so gate the two modules explicitly
    even though the whole-tree gate also walks them."""
    import avenir_tpu.pipeline.__main__ as plan_cli
    import avenir_tpu.pipeline.plan as plan_mod
    from avenir_tpu.analysis import engine

    repo = _BENCH_DIR.parent
    findings = engine.run_paths(
        [plan_mod.__file__, plan_cli.__file__], root=str(repo))
    live = [f for f in findings if not f.baselined]
    assert not live, "\n".join(f.format() for f in live)


def test_benchmarks_lint_clean():
    from avenir_tpu.analysis import engine

    repo = _BENCH_DIR.parent
    findings = engine.run_paths([str(_BENCH_DIR), str(repo / "bench.py")],
                                root=str(repo))
    live = [f for f in findings if not f.baselined]
    assert not live, (
        "graftlint hazards in the benchmark probes (a timing loop that "
        "syncs per iteration measures the RTT, not the kernel):\n"
        + "\n".join(f.format() for f in live))
