"""GraftBox (round 21): the always-on flight recorder, forensics
bundles, the progress watchdog, the teardown sweep — and the
ISSUE-specified kill drill: a SIGKILLed serving worker (no hook runs)
and a crashing pipeline worker, both with ``trace.on`` UNSET, each
leaving a bundle the sweep journals exactly once into one merged fleet
view, rendered end-to-end by ``telemetry bundle``.

In-process tests always ``blackbox.reset()`` in teardown — the box
installs process-global hooks (excepthook/SIGTERM) that must not leak
into other tests.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from avenir_tpu.core.config import JobConfig
from avenir_tpu.telemetry import blackbox
from avenir_tpu.telemetry import spans as tel
from avenir_tpu.telemetry import __main__ as cli


@pytest.fixture(autouse=True)
def _clean_box():
    blackbox.ring_clear()
    yield
    blackbox.reset()
    blackbox.ring_clear()


# ---------------------------------------------------------------------------
# the flight ring
# ---------------------------------------------------------------------------

def test_ring_records_oldest_first_and_bounded():
    for i in range(5):
        blackbox.ring_record("probe", {"i": i})
    snap = blackbox.ring_snapshot()
    assert [r["i"] for r in snap] == [0, 1, 2, 3, 4]
    assert all(r["ev"] == "probe" and r["ts"] > 0 for r in snap)
    # resize keeps the newest tail; the floor is 16
    blackbox._ring_resize(16)
    for i in range(40):
        blackbox.ring_record("flood", {"i": i})
    snap = blackbox.ring_snapshot()
    assert len(snap) == 16 and snap[-1]["i"] == 39 and snap[0]["i"] == 24
    blackbox._ring_resize(blackbox.DEFAULT_RING_EVENTS)


def test_emit_seams_record_with_tracing_off():
    """Every tracer emit seam lands in the ring even though the journal
    sees nothing — the recorder half of the GraftBox contract."""
    t = tel.Tracer()                    # never enabled
    t.event("checkpoint.save", scope="s", run="r")
    t.event_once("shard.topology", key="k", devices=8)
    t.gauge("serve.queue.depth", 3.0)
    evs = [r["ev"] for r in blackbox.ring_snapshot()]
    assert "checkpoint.save" in evs
    assert "shard.topology" in evs
    assert "gauge" in evs
    assert t.journal is None            # nothing journaled


def test_off_state_span_site_unchanged():
    """The span sites do NOT touch the ring: disabled ``span()`` still
    returns the shared NOOP object (the published off-is-free bound is
    the same one-attribute-check site as before round 21)."""
    t = tel.Tracer()
    before = len(blackbox.ring_snapshot())
    s = t.span("probe")
    assert s is tel.NOOP_SPAN
    with t.span("probe"):
        pass
    assert len(blackbox.ring_snapshot()) == before


# ---------------------------------------------------------------------------
# the progress watchdog
# ---------------------------------------------------------------------------

def test_watchdog_trips_once_per_excursion():
    wd = blackbox.Watchdog()
    wd.sec = 0.05
    wd.enter("fold")
    wd.enter("serve.dispatch")
    try:
        wd.last_progress = time.monotonic() - 1.0
        # the oldest silent seam is named; exactly one trip per excursion
        wd._guards["fold"][1] -= 5.0
        wd.check_once()
        hangs = [r for r in blackbox.ring_snapshot()
                 if r["ev"] == "hang.detected"]
        assert len(hangs) == 1
        assert hangs[0]["site"] == "fold"
        assert hangs[0]["silent_s"] >= 0.05
        assert hangs[0]["threshold"] == 0.05
        wd.last_progress = time.monotonic() - 1.0
        wd.check_once()                 # still the same excursion
        assert len([r for r in blackbox.ring_snapshot()
                    if r["ev"] == "hang.detected"]) == 1
        wd.beat()                       # progress resumed
        wd.check_once()
        assert wd.snapshot()["tripped"] is False
    finally:
        wd.exit("serve.dispatch")
        wd.exit("fold")


def test_watchdog_guard_off_is_shared_nullcontext():
    assert blackbox.watchdog_guard("fold") is blackbox._NULL_GUARD
    snap = blackbox.Watchdog().snapshot()
    assert snap["active"] == {} and snap["sec"] == 0.0


# ---------------------------------------------------------------------------
# the bundle writer
# ---------------------------------------------------------------------------

def _arm(tmp_path, **extra):
    props = {"blackbox.dir": str(tmp_path / "bb"),
             "blackbox.flush.sec": "0",     # no flusher thread in-process
             "trace.run.id": "boxtest"}
    props.update({k: str(v) for k, v in extra.items()})
    conf = JobConfig(props)
    blackbox.configure(conf)
    return conf


def test_arm_finalize_and_latch(tmp_path):
    _arm(tmp_path)
    box = blackbox.box()
    assert box.armed and os.path.isdir(box.bundle_path)
    assert blackbox.read_meta(box.bundle_path)["status"] == "live"
    blackbox.ring_record("serve.submit", {"rid": "r-1", "model": "nb",
                                          "tenant": "t0", "depth": 1})
    path = blackbox.finalize("crash:TestError", "Traceback: boom")
    assert path == box.bundle_path
    for name in ("ring.jsonl", "stacks.txt", "inflight.json", "state.json",
                 "memory.json", "conf.json", "meta.json"):
        assert os.path.isfile(os.path.join(path, name)), name
    meta = blackbox.read_meta(path)
    assert meta["status"] == "final"
    assert meta["reason"] == "crash:TestError"
    assert meta["journaled"] is False        # tracing off
    assert meta["events"] > 0
    assert "Traceback: boom" in open(os.path.join(path, "stacks.txt")).read()
    # exactly one ring entry for the latch, and the latch holds
    ring = [r for r in blackbox.ring_snapshot()
            if r["ev"] == "bundle.written"]
    assert len(ring) == 1 and ring[0]["dir"] == path
    assert blackbox.finalize("crash:Second") is None


def test_capture_is_non_latching(tmp_path):
    _arm(tmp_path)
    box = blackbox.box()
    first = blackbox.capture("breaker:w0")
    second = blackbox.capture("breaker:w1")
    assert first == box.bundle_path + "-c1"
    assert second == box.bundle_path + "-c2"
    assert blackbox.read_meta(first)["reason"] == "breaker:w0"
    # captures spend no latch: a later crash still finalizes
    assert blackbox.finalize("crash:Later") == box.bundle_path


def test_unarmed_configure_is_inert(tmp_path):
    blackbox.configure(JobConfig({}))        # no blackbox.dir
    assert not blackbox.box().armed
    assert blackbox.finalize("crash:Nope") is None
    assert blackbox.capture("breaker:x") is None


def test_bundle_journaled_when_tracing_on(tmp_path):
    """With trace.on, finalize itself journals bundle.written (golden
    schema) and marks the bundle journaled so the sweep never doubles."""
    conf = JobConfig({"blackbox.dir": str(tmp_path / "bb"),
                      "blackbox.flush.sec": "0",
                      "trace.on": "true",
                      "trace.journal.dir": str(tmp_path / "tel"),
                      "trace.run.id": "boxtest"})
    tel.configure(conf)
    try:
        path = blackbox.finalize("crash:Traced")
        assert blackbox.read_meta(path)["journaled"] is True
    finally:
        journal_path = tel.tracer().journal_path
        tel.tracer().disable()
    from avenir_tpu.telemetry.journal import read_events

    written = [e for e in read_events(journal_path)
               if e.get("ev") == "bundle.written"]
    assert len(written) == 1
    assert written[0]["dir"] == path and written[0]["reason"] == "crash:Traced"


def test_sweep_journals_each_dead_bundle_exactly_once(tmp_path):
    bb = tmp_path / "bb" / "bundle-r1-proc-0-wx"
    bb.mkdir(parents=True)
    dead_pid = 2 ** 22 + 12345               # beyond pid_max: never alive
    bb.joinpath("meta.json").write_text(json.dumps(
        {"status": "live", "reason": "", "pid": dead_pid, "run": "r1",
         "writer": "proc-0-wx", "journaled": False, "events": 7}))
    tel_dir = tmp_path / "tel"
    recs = blackbox.sweep(str(tmp_path / "bb"), journal_dir=str(tel_dir),
                          run_id="r1")
    assert len(recs) == 1
    assert recs[0]["status"] == "swept" and recs[0]["reason"] == "killed"
    assert recs[0]["journaled"] is True
    meta = blackbox.read_meta(str(bb))
    assert meta["status"] == "swept" and meta["journaled"] is True
    # idempotent: a second sweep reports but never re-journals
    recs2 = blackbox.sweep(str(tmp_path / "bb"), journal_dir=str(tel_dir),
                           run_id="r1")
    assert len(recs2) == 1
    from avenir_tpu.telemetry.journal import read_events

    shards = [n for n in os.listdir(tel_dir) if n.endswith("-sweep.jsonl")]
    assert len(shards) == 1
    events = read_events(str(tel_dir / shards[0]))
    assert [e["ev"] for e in events] == ["bundle.written"]
    assert events[0]["events"] == 7


def test_sweep_skips_live_bundles_of_running_processes(tmp_path):
    bb = tmp_path / "bb" / "bundle-r1-proc-0-live"
    bb.mkdir(parents=True)
    bb.joinpath("meta.json").write_text(json.dumps(
        {"status": "live", "pid": os.getpid(), "run": "r1",
         "writer": "proc-0-live", "journaled": False, "events": 1}))
    assert blackbox.sweep(str(tmp_path / "bb")) == []


# ---------------------------------------------------------------------------
# the CLI renderers
# ---------------------------------------------------------------------------

def test_bundle_cli_renders_postmortem(tmp_path, capsys):
    _arm(tmp_path)
    blackbox.ring_record("span.open", {"span": "s1", "name": "fold"})
    blackbox.ring_record("serve.submit", {"rid": "drill-0", "model": "nb",
                                          "tenant": "t0", "depth": 1})
    blackbox.register_provider(
        "batcher-t", lambda: [{"rid": "drill-0", "model": "nb",
                               "tenant": "t0", "state": "queued",
                               "age_ms": 9}], kind="inflight")
    try:
        path = blackbox.finalize("crash:CliTest", "Traceback: cli")
    finally:
        blackbox.unregister_provider("batcher-t")
    assert cli.main(["bundle", path]) == 0
    out = capsys.readouterr().out
    assert "reason=crash:CliTest" in out
    assert "serve.submit" in out and "rid=drill-0" in out
    assert "slowest open span: fold" in out
    assert "[batcher-t] rid=drill-0" in out and "state=queued" in out
    assert "Traceback: cli" in out
    # a non-bundle directory refuses with a usage error
    assert cli.main(["bundle", str(tmp_path)]) == 2


def test_diff_cli_per_program_and_stage_deltas(tmp_path, capsys):
    def journal(name, wall, dur):
        path = tmp_path / name
        events = [
            {"ev": "canary", "ms": 2.0},
            {"ev": "program.compiled", "key": "scan/0", "site": "fold",
             "flops": 1e9},
            {"ev": "program.profile", "key": "scan/0", "site": "fold",
             "dispatches": 10, "wall_ms": wall},
            {"ev": "span.open", "span": "s1", "name": "fold", "ts": 1.0},
            {"ev": "span.close", "span": "s1", "dur_ms": dur},
        ]
        path.write_text("\n".join(json.dumps(e) for e in events) + "\n")
        return str(path)

    a = journal("a.jsonl", wall=50.0, dur=40.0)
    b = journal("b.jsonl", wall=80.0, dur=70.0)
    assert cli.main(["diff", a, b]) == 0
    out = capsys.readouterr().out
    assert "scan/0" in out and "+30.0" in out      # program wall delta
    assert "fold" in out                           # stage row
    assert "MFU" in out and "canary peak" in out
    # stage delta +30 appears in the stage table too
    assert out.count("+30.0") >= 2


def test_stage_walls_maps_span_names():
    events = [{"ev": "span.open", "span": "a", "name": "fold"},
              {"ev": "span.close", "span": "a", "dur_ms": 5.0},
              {"ev": "span.open", "span": "b", "name": "fold"},
              {"ev": "span.close", "span": "b", "dur_ms": 7.0},
              {"ev": "span.open", "span": "c", "name": "open-forever"}]
    walls = cli.stage_walls(events)
    assert walls == {"fold": [2, 12.0]}


# ---------------------------------------------------------------------------
# the ISSUE kill drill: fresh subprocesses, trace.on UNSET
# ---------------------------------------------------------------------------

def _worker_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("AVENIR_PROCESS_ID", None)
    env.pop("AVENIR_WRITER_SUFFIX", None)
    return env


def _wait_for_inflight(bundle, rid, timeout_s=60.0):
    """Poll the LIVE bundle's continuously-spilled in-flight table until
    the queued rid shows — the kill lands mid-flight by construction."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with open(os.path.join(bundle, "inflight.json"),
                      encoding="utf-8") as fh:
                tables = json.load(fh)
        except (OSError, ValueError):
            tables = {}
        for rows in tables.values():
            if isinstance(rows, list) and any(
                    isinstance(r, dict) and r.get("rid") == rid
                    for r in rows):
                return tables
        time.sleep(0.1)
    raise AssertionError(f"{rid} never showed in {bundle}/inflight.json")


def test_kill_drill_subprocess(tmp_path, capsys):
    """The acceptance drill: one worker SIGKILLed mid-flight (no hook
    runs — the flush thread's live bundle is the record), one dying on
    an armed ``fault.*`` crash (the excepthook writes the bundle), both
    with ``trace.on`` unset.  The sweep journals exactly one
    ``bundle.written`` per dead worker into one merged fleet view, and
    ``telemetry bundle`` renders the victim's post-mortem, in-flight
    rids included."""
    worker = os.path.join(os.path.dirname(__file__), "blackbox_worker.py")
    env = _worker_env()
    bb_dir = str(tmp_path / "bb")

    # worker 1: uncaught InjectedFault → excepthook bundle, exit != 0
    crash = subprocess.run([sys.executable, worker, "crash", str(tmp_path)],
                           env=env, capture_output=True, text=True,
                           timeout=300)
    assert crash.returncode != 0
    assert "InjectedFault" in crash.stderr, crash.stderr

    # worker 2: queued rids, then SIGKILL — no hook runs
    proc = subprocess.Popen(
        [sys.executable, worker, "sigkill", str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        seen = []
        for line in proc.stdout:        # training chatter may precede it
            seen.append(line)
            if "READY" in line:
                break
        else:
            raise AssertionError(
                f"worker exited before READY:\n{''.join(seen)}"
                f"{proc.stderr.read()}")
        victim = os.path.join(bb_dir, "bundle-bbdrill-proc-0-w0")
        _wait_for_inflight(victim, "drill-0")
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=60)
    assert proc.returncode == -signal.SIGKILL

    bundles = sorted(os.listdir(bb_dir))
    assert bundles == ["bundle-bbdrill-proc-0-w0",
                       "bundle-bbdrill-proc-0-w1"], bundles
    crash_meta = blackbox.read_meta(os.path.join(
        bb_dir, "bundle-bbdrill-proc-0-w1"))
    assert crash_meta["status"] == "final"
    assert crash_meta["reason"].startswith("crash:InjectedFault")
    assert blackbox.read_meta(victim)["status"] == "live"   # SIGKILL: no hook

    # teardown sweep + fleet merge: exactly one bundle.written per dead
    # worker in the merged view
    tel_dir = str(tmp_path / "tel")
    recs = blackbox.sweep(bb_dir, journal_dir=tel_dir, run_id="bbdrill")
    assert sorted(r["writer"] for r in recs) == ["proc-0-w0", "proc-0-w1"]
    assert all(r["journaled"] for r in recs)
    assert blackbox.read_meta(victim)["reason"] == "killed"
    from avenir_tpu.launch import merge_fleet_journal
    from avenir_tpu.telemetry.journal import read_events

    merged = merge_fleet_journal(tel_dir)
    assert merged
    written = [e for e in read_events(merged)
               if e.get("ev") == "bundle.written"]
    assert sorted(os.path.basename(e["dir"]) for e in written) == bundles
    # the victim's ring made it into its bundle with the in-flight rids
    ring = [json.loads(ln) for ln in
            open(os.path.join(victim, "ring.jsonl"), encoding="utf-8")
            if ln.strip()]
    submits = [r for r in ring if r.get("ev") == "serve.submit"]
    assert {r["rid"] for r in submits} >= {f"drill-{i}" for i in range(6)}
    assert all(r.get("tenant") == "drill-tenant" for r in submits)

    # the post-mortem renders end-to-end
    assert cli.main(["bundle", victim]) == 0
    out = capsys.readouterr().out
    assert "reason=killed" in out
    assert "rid=drill-0" in out and "tenant=drill-tenant" in out
    assert "[batcher-" in out                  # in-flight provider table
