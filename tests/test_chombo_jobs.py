"""Subsumed chombo jobs (RunningAggregator, Projection) + the full
price-optimization runbook loop driven purely through the file-based jobs
layer — the tutorial's bandit → measure → RunningAggregator → next-round
cycle (resource/price_optimize_tutorial.txt:15-90) as an automated test."""

import os
import shutil

import numpy as np
import pytest

from avenir_tpu.core.config import JobConfig
from avenir_tpu.datagen.price_opt import generate_price_opt
from avenir_tpu.jobs import REGISTRY, get_job
from avenir_tpu.jobs.base import read_lines


def test_chombo_registry_names():
    assert "org.chombo.mr.RunningAggregator" in REGISTRY
    assert "org.chombo.mr.Projection" in REGISTRY


def test_projection_groups_orders_and_flattens(tmp_path):
    # transaction rows: custID, xid, date, amount (buy_xaction.rb layout),
    # deliberately out of date order within a customer
    rows = [
        "c1,101,2013-01-05,40",
        "c2,102,2013-01-02,70",
        "c1,103,2013-01-02,55",
        "c1,104,2013-02-11,90",
        "c2,105,2013-01-20,30",
    ]
    (tmp_path / "in").mkdir()
    (tmp_path / "in" / "xactions.txt").write_text("\n".join(rows) + "\n")
    conf = JobConfig({
        "projection.key.field": "0",
        "projection.field.ordinals": "2,3",
        "projection.sort.field": "2",
    })
    get_job("org.chombo.mr.Projection").run(
        conf, str(tmp_path / "in"), str(tmp_path / "out"))
    out = sorted(read_lines(str(tmp_path / "out")))
    # layout consumed by xaction_state.rb: cust, date1, amt1, date2, amt2, ...
    assert out == [
        "c1,2013-01-02,55,2013-01-05,40,2013-02-11,90",
        "c2,2013-01-02,70,2013-01-20,30",
    ]


def test_running_aggregator_merges_incrementals(tmp_path):
    indir = tmp_path / "in"
    indir.mkdir()
    # current aggregate: group,item,count,sum,avg
    (indir / "agg.txt").write_text("p1,10,2,200,100\np1,12,1,40,40\n")
    # incremental measurements: group,item,value (quantity.attr=2)
    (indir / "inc_round3.txt").write_text("p1,10,100\np1,12,80\np1,10,70\n")
    conf = JobConfig({"quantity.attr": "2", "incremental.file.prefix": "inc"})
    c = get_job("RunningAggregator").run(conf, str(indir), str(tmp_path / "out"))
    out = {ln.split(",")[1]: ln.split(",") for ln in read_lines(str(tmp_path / "out"))}
    # item 10: count 2+2=4, sum 200+170=370
    assert out["10"][2:4] == ["4", "370"]
    assert float(out["10"][4]) == pytest.approx(92.5)
    # item 12: count 1+1=2, sum 40+80=120, avg 60
    assert out["12"][2:5] == ["2", "120", "60"]
    assert c.get("Aggregate", "IncrementalRows") == 3


@pytest.mark.parametrize("bandit_job,props,n_rounds,assert_converge", [
    # UCB1's √(2·ln t/n) bonus (the reference's own normalized formula,
    # AuerDeterministic.java:212) dwarfs the ~4% adjacent-price revenue gaps
    # at file-loop-feasible round counts, so only the loop mechanics are
    # asserted here; UCB1 convergence is covered at the model layer with
    # larger gaps (test_rl.test_bandit_price_optimization).
    ("org.avenir.reinforce.AuerDeterministic", {}, 25, False),
    ("org.avenir.reinforce.GreedyRandomBandit",
     {"prob.reduction.algorithm": "linear",
      "random.selection.prob": "0.5",
      "prob.reduction.constant": "8.0"}, 60, True),
])
def test_price_optimize_runbook_loop(tmp_path, bandit_job, props, n_rounds,
                                     assert_converge):
    """The tutorial's round loop, file for file: bandit job selects a price
    per product; the revenue oracle writes an inc file; RunningAggregator
    folds it into the running state; the state becomes the next round's
    input. The bandit must converge to the revenue-optimal price."""
    sim = generate_price_opt(n_products=8, seed=5)
    indir = tmp_path / "input"
    indir.mkdir()
    # bootstrap aggregate: group,item,count,sum,avg — no pulls yet
    lines = [f"{pid},{price},0,0,0"
             for pid, p in sim.products.items() for price in p.prices]
    (indir / "agg.txt").write_text("\n".join(lines) + "\n")

    selections = []
    for rnd in range(1, n_rounds + 1):
        conf = JobConfig({
            "current.round.num": str(rnd),
            "count.ordinal": "2",
            "reward.ordinal": "4",
            "seed": str(100 + rnd),
            **props,
        })
        get_job(bandit_job).run(conf, str(indir), str(tmp_path / "select"))
        selections = [ln.split(",") for ln in read_lines(str(tmp_path / "select"))]
        assert len(selections) == len(sim.products)
        # revenue oracle → incremental measurement file (group,item,profit)
        inc = [f"{pid},{price},{sim.reward(pid, price):.3f}"
               for pid, price in selections]
        (indir / f"inc_{rnd}.txt").write_text("\n".join(inc) + "\n")
        conf_agg = JobConfig({"quantity.attr": "2",
                              "incremental.file.prefix": "inc"})
        get_job("org.chombo.mr.RunningAggregator").run(
            conf_agg, str(indir), str(tmp_path / "agg_out"))
        # next round: aggregate output replaces the input dir contents
        shutil.rmtree(indir)
        indir.mkdir()
        shutil.copy(str(tmp_path / "agg_out" / "part-00000"),
                    str(indir / "agg.txt"))

    # loop mechanics: the running state accumulated exactly one pull per
    # product per round
    final = [ln.split(",") for ln in read_lines(str(indir / "agg.txt"))]
    per_group = {}
    for g, _item, cnt, _s, _a in final:
        per_group[g] = per_group.get(g, 0) + int(cnt)
    assert all(v == n_rounds for v in per_group.values())

    if assert_converge:
        # final-round selections: most products at (or adjacent to) optimum
        n_good = 0
        for pid, price in selections:
            p = sim.products[pid]
            picked = p.prices.index(int(price))
            best = int(np.argmax(p.mean_revenue))
            if abs(picked - best) <= 1:
                n_good += 1
        assert n_good >= int(0.75 * len(sim.products)), \
            f"only {n_good}/{len(sim.products)} products near-optimal"


def test_numerical_attr_stats_conditioned(tmp_path):
    # the Fisher usage: per-(attr, classVal) count/mean/var/std/min/max
    rng = np.random.default_rng(3)
    rows = []
    for _ in range(500):
        cls = rng.choice(["a", "b"])
        x = rng.normal(2.0 if cls == "a" else 5.0, 1.0)
        y = rng.normal(-1.0, 0.5)
        rows.append(f"{x:.5f},{cls},{y:.5f}")
    (tmp_path / "in").mkdir()
    (tmp_path / "in" / "data.txt").write_text("\n".join(rows) + "\n")
    conf = JobConfig({"attr.list": "0,2", "cond.attr.ord": "1"})
    get_job("org.chombo.mr.NumericalAttrStats").run(
        conf, str(tmp_path / "in"), str(tmp_path / "out"))
    out = {}
    for line in read_lines(str(tmp_path / "out")):
        f = line.split(",")
        # attr, cond, count, sum, sumSq, mean, var, std, min, max
        out[(f[0], f[1])] = [float(v) for v in f[2:]]
    assert set(out) == {("0", "a"), ("0", "b"), ("2", "a"), ("2", "b")}
    assert abs(out[("0", "a")][3] - 2.0) < 0.3      # mean
    assert abs(out[("0", "b")][3] - 5.0) < 0.3
    assert abs(out[("2", "a")][5] - 0.5) < 0.15     # std
    n_a = out[("0", "a")][0]
    n_b = out[("0", "b")][0]
    assert n_a + n_b == 500
    assert out[("0", "a")][6] <= 2.0 <= out[("0", "a")][7]   # min ≤ μ ≤ max


def test_numerical_attr_stats_unconditioned(tmp_path):
    (tmp_path / "in").mkdir()
    (tmp_path / "in" / "d.txt").write_text("1,10\n2,20\n3,30\n")
    conf = JobConfig({"attr.list": "0,1"})
    get_job("org.chombo.mr.NumericalAttrStats").run(
        conf, str(tmp_path / "in"), str(tmp_path / "out"))
    out = {l.split(",")[0]: l.split(",") for l in read_lines(str(tmp_path / "out"))}
    # attr, count, sum, sumSq, mean, var, std, min, max
    assert float(out["0"][4]) == pytest.approx(2.0)
    assert float(out["1"][2]) == pytest.approx(60.0)
    assert float(out["1"][8]) == pytest.approx(30.0)


def test_numerical_attr_stats_large_magnitude(tmp_path):
    # |mean| >> std: naive f32 E[x^2]-E[x]^2 cancels catastrophically; the
    # job must shift by the column mean and rebuild raw moments in f64
    # (the reference chombo job accumulates in double)
    rng = np.random.default_rng(11)
    base = 1.0e7
    x = base + rng.normal(0.0, 1.0, size=4000)
    (tmp_path / "in").mkdir()
    (tmp_path / "in" / "d.txt").write_text(
        "\n".join(f"{v:.6f}" for v in x) + "\n")
    conf = JobConfig({"attr.list": "0"})
    get_job("org.chombo.mr.NumericalAttrStats").run(
        conf, str(tmp_path / "in"), str(tmp_path / "out"))
    f = read_lines(str(tmp_path / "out"))[0].split(",")
    # attr, count, sum, sumSq, mean, var, std, min, max
    assert float(f[1]) == 4000
    assert float(f[4]) == pytest.approx(x.mean(), rel=1e-9)
    assert float(f[6]) == pytest.approx(x.std(), rel=0.05)
    assert float(f[2]) == pytest.approx(x.sum(), rel=1e-9)
    assert float(f[3]) == pytest.approx((x * x).sum(), rel=1e-7)


def test_numerical_attr_stats_conditioned_large_magnitude(tmp_path):
    # per-GROUP mean shift: group means far apart (0 vs 1e7) with std 1 —
    # a global shift would still leave each group's values ~5e6 in f32 and
    # cancel the spread; per-group shift must preserve it
    rng = np.random.default_rng(13)
    rows = []
    vals = {"a": [], "b": []}
    for _ in range(3000):
        g = "a" if rng.random() < 0.5 else "b"
        v = float(f"{rng.normal(0.0 if g == 'a' else 1.0e7, 1.0):.6f}")
        vals[g].append(v)
        rows.append(f"{v:.6f},{g}")
    (tmp_path / "in").mkdir()
    (tmp_path / "in" / "d.txt").write_text("\n".join(rows) + "\n")
    conf = JobConfig({"attr.list": "0", "cond.attr.ord": "1"})
    get_job("org.chombo.mr.NumericalAttrStats").run(
        conf, str(tmp_path / "in"), str(tmp_path / "out"))
    out = {}
    for line in read_lines(str(tmp_path / "out")):
        f = line.split(",")
        out[f[1]] = [float(v) for v in f[2:]]
    for g in ("a", "b"):
        ref = np.asarray(vals[g])
        assert out[g][0] == len(ref)
        assert out[g][3] == pytest.approx(ref.mean(), abs=1e-3)
        assert out[g][5] == pytest.approx(ref.std(), rel=0.05)   # std survives
        # group-a sum is ~34 built from f32 partial sums of ±4 values: exact
        # to ~1e-4 abs; group-b sum ~1.5e10 must hold 1e-9 relative
        assert out[g][1] == pytest.approx(ref.sum(), rel=1e-9, abs=1e-3)


def test_numerical_attr_stats_nonfinite_input(tmp_path):
    # nan/inf values in a numeric column must print as nan/inf, not crash
    # the int-vs-float formatter
    (tmp_path / "in").mkdir()
    (tmp_path / "in" / "d.txt").write_text("1.5\nnan\n2.5\n")
    conf = JobConfig({"attr.list": "0"})
    get_job("org.chombo.mr.NumericalAttrStats").run(
        conf, str(tmp_path / "in"), str(tmp_path / "out"))
    f = read_lines(str(tmp_path / "out"))[0].split(",")
    assert f[1] == "3"
    assert f[2] == "nan" or np.isnan(float(f[2]))


def test_numerical_attr_stats_inf_input(tmp_path):
    # an inf value must keep sum/mean at inf (shift computed over finite
    # values only), not collapse to nan via inf-minus-inf
    (tmp_path / "in").mkdir()
    (tmp_path / "in" / "d.txt").write_text("1.5\ninf\n2.5\n")
    conf = JobConfig({"attr.list": "0"})
    get_job("org.chombo.mr.NumericalAttrStats").run(
        conf, str(tmp_path / "in"), str(tmp_path / "out"))
    f = read_lines(str(tmp_path / "out"))[0].split(",")
    # attr, count, sum, sumSq, mean, var, std, min, max
    assert float(f[2]) == float("inf")
    assert float(f[4]) == float("inf")
    assert float(f[8]) == float("inf")         # max


def test_numerical_attr_stats_streaming_matches_whole_and_guard(tmp_path):
    """Round-7 hardening: the streaming path's 12-digit zero-padded chunk
    keys keep the finalize fold ordered (counts/min/max exact vs the
    whole-input run, moments to chunked-fold tolerance — the cross-process
    BYTE identity contract is per process count, not vs whole-input), and
    the O(chunks × groups) state guard trips loudly instead of growing
    without bound."""
    from avenir_tpu.core.config import ConfigError

    rng = np.random.default_rng(9)
    rows = []
    for _ in range(600):
        cls = rng.choice(["a", "b", "c"])
        rows.append(f"{rng.normal(3.0, 1.0):.5f},{cls},"
                    f"{rng.normal(-2.0, 0.7):.5f}")
    (tmp_path / "in").mkdir()
    (tmp_path / "in" / "data.txt").write_text("\n".join(rows) + "\n")

    get_job("org.chombo.mr.NumericalAttrStats").run(
        JobConfig({"attr.list": "0,2", "cond.attr.ord": "1"}),
        str(tmp_path / "in"), str(tmp_path / "out_whole"))
    get_job("org.chombo.mr.NumericalAttrStats").run(
        JobConfig({"attr.list": "0,2", "cond.attr.ord": "1",
                   "stream.chunk.rows": "97"}),
        str(tmp_path / "in"), str(tmp_path / "out_stream"))
    whole = (tmp_path / "out_whole" / "part-00000").read_text().splitlines()
    stream = (tmp_path / "out_stream" / "part-00000").read_text().splitlines()
    # same rows (count/min/max exact; moments agree to fold tolerance)
    assert len(whole) == len(stream)
    for wl, sl in zip(sorted(whole), sorted(stream)):
        wf, sf = wl.split(","), sl.split(",")
        assert wf[:3] == sf[:3]                      # attr, cond, count
        assert wf[-2:] == sf[-2:]                    # min, max exact
        np.testing.assert_allclose([float(v) for v in wf[3:]],
                                   [float(v) for v in sf[3:]], rtol=1e-6)

    with pytest.raises(ConfigError, match="stream.stats.max.state.mb"):
        get_job("org.chombo.mr.NumericalAttrStats").run(
            JobConfig({"attr.list": "0,2", "cond.attr.ord": "1",
                       "stream.chunk.rows": "50",
                       "stream.stats.max.state.mb": "0"}),
            str(tmp_path / "in"), str(tmp_path / "out_guard"))
