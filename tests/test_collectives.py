"""Explicit shard_map+psum steps == local computation; graft dryrun passes."""

import numpy as np
import jax
import jax.numpy as jnp

from avenir_tpu.ops import agg
from avenir_tpu.parallel import collectives, mesh as pmesh


def test_sharded_nb_fit_step_matches_local(rng):
    m = pmesh.make_mesh(("data",))
    n, f, fc, C, B = 64 * m.shape["data"], 3, 2, 2, 5
    codes = rng.integers(0, B, size=(n, f)).astype(np.int32)
    labels = rng.integers(0, C, size=n).astype(np.int32)
    cont = rng.normal(size=(n, fc)).astype(np.float32)
    step = collectives.sharded_nb_fit_step(m, C, B, fc)
    fbc, cc, _, s1, s2 = step(jnp.asarray(codes), jnp.asarray(labels), jnp.asarray(cont))
    local_fbc = np.asarray(agg.feature_class_counts(jnp.asarray(codes), jnp.asarray(labels), C, B))
    np.testing.assert_array_equal(np.asarray(fbc).astype(np.int64), local_fbc)
    lcnt, ls1, ls2 = agg.class_moments(jnp.asarray(cont), jnp.asarray(labels), C)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(ls1), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(s2), np.asarray(ls2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(cc), np.asarray(lcnt), rtol=1e-6)


def test_sharded_nb_fit_step_2d_matches_local(rng):
    m = pmesh.make_mesh(("data", "model"), shape=(4, 2))
    n, f, C, B = 32 * 4, 8, 3, 4          # f divisible by model axis
    codes = rng.integers(0, B, size=(n, f)).astype(np.int32)
    labels = rng.integers(0, C, size=n).astype(np.int32)
    step = collectives.sharded_nb_fit_step_2d(m, C, B)
    fbc, cc = step(jnp.asarray(codes), jnp.asarray(labels))
    local = np.asarray(agg.feature_class_counts(jnp.asarray(codes), jnp.asarray(labels), C, B))
    np.testing.assert_array_equal(np.asarray(fbc).astype(np.int64), local)
    assert int(np.asarray(cc).sum()) == n
    # the count tensor is genuinely model-sharded on its feature axis
    shard_shapes = {s.data.shape for s in fbc.addressable_shards}
    assert shard_shapes == {(f // 2, B, C)}


def test_graft_dryrun():
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (256, 2)
    ge.dryrun_multichip(8)


def test_sharded_knn_matches_local(rng):
    """8-way sharded reference set must return the same exact top-k as the
    single-device scan engine."""
    import jax.numpy as jnp
    from avenir_tpu.core.encoding import EncodedDataset
    from avenir_tpu.models import knn as mknn
    from avenir_tpu.parallel import collectives, mesh as pmesh

    m = pmesh.make_mesh(("data",), shape=(8,))
    n, q, f, fc, k, nb = 1024, 64, 4, 3, 5, 6
    ref_codes = rng.integers(0, nb, size=(n, f)).astype(np.int32)
    ref_cont = rng.normal(size=(n, fc)).astype(np.float32)
    tc = rng.integers(0, nb, size=(q, f)).astype(np.int32)
    tx = rng.normal(size=(q, fc)).astype(np.float32)
    lo = ref_cont.min(0); hi = ref_cont.max(0)

    step = collectives.sharded_knn_topk(m, k=k, num_bins=nb)
    d_sh, i_sh = step(jnp.asarray(tc), jnp.asarray(tx), jnp.asarray(ref_codes),
                      jnp.asarray(ref_cont), jnp.asarray(lo), jnp.asarray(hi),
                      jnp.int32(n))

    ds_ref = EncodedDataset(
        codes=ref_codes, cont=ref_cont, labels=None, ids=None,
        n_bins=np.full(f, nb, np.int32), class_values=[],
        binned_ordinals=list(range(f)), cont_ordinals=list(range(f, f + fc)))
    ds_test = EncodedDataset(
        codes=tc, cont=tx, labels=None, ids=None,
        n_bins=np.full(f, nb, np.int32), class_values=[],
        binned_ordinals=list(range(f)), cont_ordinals=list(range(f, f + fc)))
    model = mknn.fit_knn(ds_ref)
    # align normalization with the sharded call's lo/hi
    model.cont_lo, model.cont_hi = lo.astype(np.float32), hi.astype(np.float32)
    d_loc, i_loc = mknn.nearest_neighbors(model, ds_test, k=k)

    np.testing.assert_allclose(np.asarray(d_sh), d_loc, rtol=1e-5, atol=1e-6)
    # global indices must match exactly, except within genuine distance ties
    # (where any permutation of the tied candidates is valid)
    for r in range(q):
        sh, loc = set(np.asarray(i_sh)[r].tolist()), set(i_loc[r].tolist())
        if sh != loc:
            dr = d_loc[r]
            has_boundary_tie = np.isclose(dr[-1], dr, atol=1e-6).sum() > 1
            assert has_boundary_tie, (r, sh, loc, dr)


def test_sharded_knn_masks_pad_rows(rng):
    """Pad rows (index >= n_real) must never be returned, even when their
    zero-filled features would make them artificially near neighbors."""
    import jax.numpy as jnp
    from avenir_tpu.parallel import collectives, mesh as pmesh

    m = pmesh.make_mesh(("data",), shape=(8,))
    n_real, q, f, fc, k, nb = 1000, 16, 3, 2, 5, 6
    pad_to = 1024
    ref_codes = np.zeros((pad_to, f), np.int32)
    ref_cont = np.zeros((pad_to, fc), np.float32)
    # real rows are far from the all-zero queries; pad rows are exactly zero
    ref_codes[:n_real] = rng.integers(1, nb, size=(n_real, f))
    ref_cont[:n_real] = rng.uniform(5.0, 9.0, size=(n_real, fc))
    tc = np.zeros((q, f), np.int32)
    tx = np.zeros((q, fc), np.float32)
    lo = np.zeros(fc, np.float32); hi = np.full(fc, 9.0, np.float32)
    step = collectives.sharded_knn_topk(m, k=k, num_bins=nb)
    d, i = step(jnp.asarray(tc), jnp.asarray(tx), jnp.asarray(ref_codes),
                jnp.asarray(ref_cont), jnp.asarray(lo), jnp.asarray(hi),
                jnp.int32(n_real))
    assert int(np.asarray(i).max()) < n_real
    assert np.isfinite(np.asarray(d)).all()


def test_sharded_lr_step_matches_dense(rng):
    import jax.numpy as jnp
    from avenir_tpu.parallel import collectives, mesh as pmesh

    m = pmesh.make_mesh(("data",), shape=(8,))
    n, d = 512, 6
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (1 / (1 + np.exp(-(x @ w_true))) > rng.uniform(size=n)).astype(np.float32)
    # nonzero start so the l2 term and the sigmoid both have teeth
    w0 = rng.normal(size=d).astype(np.float32)

    step = collectives.sharded_lr_step(m)
    w_sh = np.asarray(step(jnp.asarray(w0), jnp.asarray(x), jnp.asarray(y),
                           jnp.float32(n), jnp.float32(0.5), jnp.float32(0.01)))
    # dense oracle
    p = 1 / (1 + np.exp(-(x @ w0)))
    grad = x.T @ (y - p) / n - 0.01 * w0
    w_ref = w0 + 0.5 * grad
    np.testing.assert_allclose(w_sh, w_ref, rtol=1e-4, atol=1e-5)


def test_hybrid_mesh_single_slice_fallback():
    # single-slice (test) environment: make_hybrid_mesh must reduce to a
    # plain ICI mesh usable by every estimator
    from avenir_tpu.parallel import mesh as pmesh
    m = pmesh.make_hybrid_mesh(("data", "model"), ici_shape=(4, 2))
    assert m.shape == {"data": 4, "model": 2}
    m1 = pmesh.make_hybrid_mesh(("data",))
    assert m1.shape["data"] == 8


def test_init_distributed_single_host_noop():
    from avenir_tpu.parallel import mesh as pmesh
    assert pmesh.init_distributed() == 0


def test_process_local_batch_single_process():
    import numpy as np
    from avenir_tpu.parallel import mesh as pmesh
    m = pmesh.make_mesh(("data",))
    arr = np.arange(20, dtype=np.int32).reshape(10, 2)
    out = pmesh.process_local_batch(m, arr)
    assert out.shape[0] % m.shape["data"] == 0
    np.testing.assert_array_equal(np.asarray(out)[:10], arr)


def test_sharded_mi_step_matches_local():
    # 2-D mesh: batch over data, pair axis of the [P,B,B,C] tensor over
    # model — each device holds 1/model_par of the largest MI tensor
    import numpy as np
    from avenir_tpu.ops import agg
    from avenir_tpu.parallel import collectives, mesh as pmesh

    rng = np.random.default_rng(21)
    c, b, f = 2, 5, 6
    pairs = np.array([(i, j) for i in range(f) for j in range(i + 1, f)],
                     np.int32)                     # P = 15, not divisible by 2
    # pad the pair list to a multiple of the model axis with a sentinel pair
    # (0,0): its counts land in a discarded tail slot
    m = pmesh.make_mesh(("data", "model"), shape=(4, 2))
    pmodel = m.shape["model"]
    P = len(pairs)
    pad = (-P) % pmodel
    pairs_padded = np.concatenate([pairs, np.zeros((pad, 2), np.int32)])

    n = 64
    codes = rng.integers(0, b, size=(n, f)).astype(np.int32)
    labels = rng.integers(0, c, size=n).astype(np.int32)

    step = collectives.sharded_mi_step(m, c, b)
    pabc, fbc, cc = step(codes, labels, pairs_padded[:, 0], pairs_padded[:, 1])
    pabc = np.asarray(pabc)[:P]

    ref_pabc = np.asarray(agg.pair_class_counts(
        codes[:, pairs[:, 0]], codes[:, pairs[:, 1]], labels, c, b))
    ref_fbc = np.asarray(agg.feature_class_counts(codes, labels, c, b))
    np.testing.assert_array_equal(pabc, ref_pabc)
    np.testing.assert_array_equal(np.asarray(fbc), ref_fbc)
    np.testing.assert_array_equal(np.asarray(cc), np.bincount(labels, minlength=c))


def test_maybe_shard_batch_reshards_unsharded_jax_arrays():
    # a jax.Array staged WITHOUT the mesh (plain device_put) must still be
    # resharded+padded by maybe_shard_batch under a >1-device mesh — only
    # arrays already carrying the mesh's batch sharding pass through
    import jax
    import numpy as np

    from avenir_tpu.parallel.mesh import (data_sharding, make_mesh,
                                          maybe_shard_batch)

    mesh = make_mesh(("data",))
    assert mesh.shape["data"] > 1
    x = jax.device_put(np.arange(12, dtype=np.int32))     # single-device
    [out] = maybe_shard_batch(mesh, x)
    assert out.sharding == data_sharding(mesh, 1)
    assert out.shape[0] % mesh.shape["data"] == 0          # padded

    [out2] = maybe_shard_batch(mesh, out)                  # already placed
    assert out2 is out                                     # pass-through
