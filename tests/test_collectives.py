"""Explicit shard_map+psum steps == local computation; graft dryrun passes."""

import numpy as np
import jax
import jax.numpy as jnp

from avenir_tpu.ops import agg
from avenir_tpu.parallel import collectives, mesh as pmesh


def test_sharded_nb_fit_step_matches_local(rng):
    m = pmesh.make_mesh(("data",))
    n, f, fc, C, B = 64 * m.shape["data"], 3, 2, 2, 5
    codes = rng.integers(0, B, size=(n, f)).astype(np.int32)
    labels = rng.integers(0, C, size=n).astype(np.int32)
    cont = rng.normal(size=(n, fc)).astype(np.float32)
    step = collectives.sharded_nb_fit_step(m, C, B, fc)
    fbc, cc, _, s1, s2 = step(jnp.asarray(codes), jnp.asarray(labels), jnp.asarray(cont))
    local_fbc = np.asarray(agg.feature_class_counts(jnp.asarray(codes), jnp.asarray(labels), C, B))
    np.testing.assert_array_equal(np.asarray(fbc).astype(np.int64), local_fbc)
    lcnt, ls1, ls2 = agg.class_moments(jnp.asarray(cont), jnp.asarray(labels), C)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(ls1), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(s2), np.asarray(ls2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(cc), np.asarray(lcnt), rtol=1e-6)


def test_sharded_nb_fit_step_2d_matches_local(rng):
    m = pmesh.make_mesh(("data", "model"), shape=(4, 2))
    n, f, C, B = 32 * 4, 8, 3, 4          # f divisible by model axis
    codes = rng.integers(0, B, size=(n, f)).astype(np.int32)
    labels = rng.integers(0, C, size=n).astype(np.int32)
    step = collectives.sharded_nb_fit_step_2d(m, C, B)
    fbc, cc = step(jnp.asarray(codes), jnp.asarray(labels))
    local = np.asarray(agg.feature_class_counts(jnp.asarray(codes), jnp.asarray(labels), C, B))
    np.testing.assert_array_equal(np.asarray(fbc).astype(np.int64), local)
    assert int(np.asarray(cc).sum()) == n
    # the count tensor is genuinely model-sharded on its feature axis
    shard_shapes = {s.data.shape for s in fbc.addressable_shards}
    assert shard_shapes == {(f // 2, B, C)}


def test_graft_dryrun():
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (256, 2)
    ge.dryrun_multichip(8)
